"""Comm facade + telemetry + quantized collectives on the CPU mesh.

Mirrors the reference's ``tests/unit/comm`` (collective correctness +
comms-logging) and ``tests/unit/runtime/zero/test_zeropp.py`` (qgZ/qwZ).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from deepspeed_tpu.utils.compat import shard_map

import deepspeed_tpu.comm as dist
from deepspeed_tpu.parallel.quant_collectives import (
    quantized_all_gather,
    quantized_reduce_scatter,
)


@pytest.fixture
def mesh():
    devs = jax.devices()[:4]
    return Mesh(np.array(devs), ("dp",))


def test_all_reduce_and_logging(mesh):
    dist.comms_logger.configure(enabled=True)
    dist.comms_logger.reset()

    x = jnp.arange(16, dtype=jnp.float32).reshape(4, 4)

    def f(x):
        return dist.all_reduce(x, "dp")

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(x)
    expected = np.tile(np.asarray(x).reshape(4, 4).sum(axis=0, keepdims=True), (4, 1))
    np.testing.assert_allclose(np.asarray(out), expected)

    rows = dist.comms_logger.summary()
    assert any(r["op"] == "all_reduce_sum" and r["axis"] == "dp" for r in rows)
    r = next(r for r in rows if r["op"] == "all_reduce_sum")
    assert r["count"] >= 1 and r["total_bytes"] > 0 and r["bus_bytes"] > 0
    dist.log_summary()
    dist.comms_logger.configure(enabled=False)


def test_reduce_scatter_all_gather_roundtrip(mesh):
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8))

    def f(x):
        s = dist.reduce_scatter(x[0], "dp", scatter_axis=0)  # local shard [2]
        return dist.all_gather(s, "dp", concat_axis=0)[None]  # full [1, 8]

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(x)
    # reduce_scatter+all_gather == all_reduce
    expected = np.tile(np.asarray(x).sum(axis=0, keepdims=True), (4, 1)).reshape(4, 8)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)


def test_broadcast(mesh):
    x = jnp.arange(4, dtype=jnp.float32).reshape(4, 1)  # rank r holds value r

    def f(x):
        return dist.broadcast(x, "dp", root=2)

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(x)
    np.testing.assert_allclose(np.asarray(out), np.full((4, 1), 2.0))


def test_quantized_reduce_scatter_approximates_mean(mesh):
    N = 4 * 256
    g = jax.random.normal(jax.random.PRNGKey(1), (4, N))  # per-rank full grads

    def f(g):
        return quantized_reduce_scatter(g[0], "dp", block_size=128)[None]

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(g)
    full = np.asarray(g).mean(axis=0)  # exact mean of the 4 ranks' grads
    got = np.asarray(out).reshape(-1)
    # int8 block quant: error bounded by ~absmax/127 per block
    tol = np.abs(np.asarray(g)).max() / 127 + 1e-5
    np.testing.assert_allclose(got, full, atol=tol)


def test_quantized_all_gather_approximates_exact(mesh):
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 64)).astype(jnp.float32)

    def f(xs):
        return quantized_all_gather(xs[0], "dp", block_size=64)[None]

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(x)
    # every rank returns the same gathered buffer; check rank 0's copy
    got = np.asarray(out).reshape(4, 256)[0]
    exact = np.asarray(x).reshape(-1)
    tol = np.abs(exact).max() / 127 + 1e-5
    np.testing.assert_allclose(got, exact, atol=tol)


def test_quantized_reduce_scatter_nondivisible_shard(mesh):
    # shard (750) not a multiple of block (256): blocks must not straddle ranks
    N = 4 * 750
    g = jax.random.normal(jax.random.PRNGKey(3), (4, N))

    def f(g):
        return quantized_reduce_scatter(g[0], "dp", block_size=256)[None]

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(g)
    full = np.asarray(g).mean(axis=0)
    tol = np.abs(np.asarray(g)).max() / 127 + 1e-5
    np.testing.assert_allclose(np.asarray(out).reshape(-1), full, atol=tol)


def test_quantized_all_gather_nondivisible_shard(mesh):
    # local shard 100 with block 64: per-rank padding must survive the gather
    x = jax.random.normal(jax.random.PRNGKey(4), (4, 100)).astype(jnp.float32)

    def f(xs):
        return quantized_all_gather(xs[0], "dp", block_size=64)[None]

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(x)
    got = np.asarray(out).reshape(4, 400)[0]
    exact = np.asarray(x).reshape(-1)
    tol = np.abs(exact).max() / 127 + 1e-5
    np.testing.assert_allclose(got, exact, atol=tol)


def test_host_api_single_process():
    assert dist.get_world_size() >= 1
    assert dist.get_rank() == 0
    dist.barrier()  # no-op single process
    assert dist.init_distributed() is False  # single-process => not multi


def test_collective_bench_rows(devices):
    """ds_bench analog: sweeps run on the CPU mesh and busbw factors hold."""
    from deepspeed_tpu.comm.benchmark import run_collective_bench

    for op in ("all_reduce", "all_gather", "reduce_scatter", "all_to_all"):
        rows = run_collective_bench(op, sizes_mb=[0.05], axis="dp", iters=2, warmup=1)
        (row,) = rows
        assert row["world"] == 8 and row["latency_ms"] > 0
        want = 2 * 7 / 8 if op == "all_reduce" else 7 / 8
        # both gbps fields are rounded to 3dp, so compare within that grain
        # (a loaded CI box can produce sub-0.01 gbps rows)
        assert abs(row["busbw_gbps"] - row["algbw_gbps"] * want) <= 1.5e-3, (op, row)
