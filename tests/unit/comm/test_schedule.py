"""Schedule compiler (ISSUE 19): synthesized hop programs vs jax.lax.

Acceptance pins:

- a compiled program round-trips facade -> compiler -> hop scope and is
  BIT-identical to the ``jax.lax`` baseline on exact wires (integer-valued
  payloads make every summation order exact), on a 1D ring, a (4,2)
  two-axis mesh, and a (2,2,2) mesh — including non-divisible payloads;
- the search is deterministic across cache invalidation;
- the cost model the compiler consumes IS the selector's refit-calibrated
  object (``selector.cost_model()``), and a recalibration visibly flips
  the pick: alpha-dominant -> compiled wins at world 30 (non-pow2, where
  the [2,3,5] factorization's 14 hops beat ring2d's 18 and bidir's 58),
  beta-dominant -> the SAME query flips to ``bidir``, alpha-huge with no
  forced codec -> the 0-hop ``lax`` floor;
- the decision cache keys on the mesh-axis factorization, not just world
  size;
- hierarchical constants (``set_tier_beta_scale``) surface the ZeRO++
  mixed placement (exact inner level, quantized outer) from search.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.collectives import algorithms, schedule, selector
from deepspeed_tpu.comm import benchmark
from deepspeed_tpu.utils.compat import shard_map


@pytest.fixture(autouse=True)
def _reset_selector():
    selector.configure()
    yield
    selector.configure()


def _mesh(shape, names):
    return Mesh(np.array(jax.devices()[:8]).reshape(shape), names)


def _run(mesh, f, x, out_specs):
    spec = P(mesh.axis_names if len(mesh.axis_names) > 1
             else mesh.axis_names[0])
    return jax.jit(shard_map(f, mesh=mesh, in_specs=spec,
                             out_specs=out_specs, check_vma=False))(x)


def _ints(rng, n):
    return jnp.asarray(rng.integers(-8, 8, size=(n,)).astype(np.float32))


# ------------------------------------------------------------ bit identity
@pytest.mark.parametrize("alg", [
    "compiled", "compiled:dp*2.none/dp*4.none",
    "compiled:dp*2.none/dp*2.none/dp*2.none"])
def test_compiled_all_reduce_1d_bit_identical(alg):
    mesh = _mesh((8,), ("dp",))
    x = _ints(np.random.default_rng(0), 8 * 96)
    got = _run(mesh, lambda v: algorithms.all_reduce(v, "dp", algorithm=alg),
               x, P("dp"))
    want = _run(mesh, lambda v: jax.lax.psum(v, "dp"), x, P("dp"))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.nightly
def test_compiled_all_reduce_nondivisible_payload():
    # L=333 per shard is not divisible by the sub-ring sizes -> pad path
    mesh = _mesh((8,), ("dp",))
    x = _ints(np.random.default_rng(1), 8 * 333)
    for alg in ("compiled", "compiled:dp*4.none/dp*2.none"):
        got = _run(mesh, lambda v, a=alg: algorithms.all_reduce(
            v, "dp", algorithm=a), x, P("dp"))
        want = _run(mesh, lambda v: jax.lax.psum(v, "dp"), x, P("dp"))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_compiled_two_axis_mesh_bit_identical():
    mesh = _mesh((4, 2), ("a", "b"))
    x = _ints(np.random.default_rng(2), 8 * 96)
    got = _run(mesh, lambda v: algorithms.all_reduce(
        v, ("a", "b"), algorithm="compiled"), x, P(("a", "b")))
    want = _run(mesh, lambda v: jax.lax.psum(v, ("a", "b")), x, P(("a", "b")))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # forced cross-axis program: minor axis first, then the 4-ring
    got = _run(mesh, lambda v: algorithms.all_gather(
        v, ("a", "b"), algorithm="compiled:b*2.none/a*4.none"), x, P())
    want = _run(mesh, lambda v: jax.lax.all_gather(
        v, ("a", "b"), tiled=True), x, P())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    got = _run(mesh, lambda v: algorithms.reduce_scatter(
        v, ("a", "b"), algorithm="compiled:b*2.none/a*4.none"),
        x, P(("a", "b")))
    want = _run(mesh, lambda v: jax.lax.psum_scatter(
        v, ("a", "b"), tiled=True), x, P(("a", "b")))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.nightly
def test_compiled_three_axis_mesh_bit_identical():
    mesh = _mesh((2, 2, 2), ("a", "b", "c"))
    axes = ("a", "b", "c")
    x = _ints(np.random.default_rng(3), 8 * 96)
    for op, lax_f, outs in (
            (algorithms.all_reduce,
             lambda v: jax.lax.psum(v, axes), P(axes)),
            (algorithms.reduce_scatter,
             lambda v: jax.lax.psum_scatter(v, axes, tiled=True), P(axes))):
        got = _run(mesh, lambda v, f=op: f(v, axes, algorithm="compiled"),
                   x, outs)
        want = _run(mesh, lax_f, x, outs)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    got = _run(mesh, lambda v: algorithms.all_gather(
        v, axes, algorithm="compiled:c*2.none/b*2.none/a*2.none"), x, P())
    want = _run(mesh, lambda v: jax.lax.all_gather(v, axes, tiled=True),
                x, P())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.nightly
def test_compiled_mixed_codec_placement_bounded():
    # ZeRO++ shape by hand: exact 2-ring on b, int8 4-ring on a
    mesh = _mesh((4, 2), ("a", "b"))
    x = _ints(np.random.default_rng(4), 8 * 96)
    got = _run(mesh, lambda v: algorithms.all_reduce(
        v, ("a", "b"), algorithm="compiled:b*2.none/a*4.int8",
        block_size=32), x, P(("a", "b")))
    want = _run(mesh, lambda v: jax.lax.psum(v, ("a", "b")), x, P(("a", "b")))
    rel = (np.abs(np.asarray(got) - np.asarray(want)).max()
           / (np.abs(np.asarray(want)).max() + 1e-9))
    assert rel < 0.1, rel


# ------------------------------------------------------- search + selector
def test_search_deterministic_across_cache_invalidation():
    s1 = schedule.compile_schedule("all_reduce", (("dp", 8),), 1 << 20, "int8")
    s2 = schedule.compile_schedule("all_reduce", (("dp", 8),), 1 << 20, "int8")
    schedule.invalidate_cache()
    s3 = schedule.compile_schedule("all_reduce", (("dp", 8),), 1 << 20, "int8")
    assert s1.signature == s2.signature == s3.signature
    assert s1.est_us == s3.est_us
    # round-trip through the signature grammar
    levels = schedule.parse_signature(s1.signature)
    assert schedule.format_signature(levels) == s1.signature


def test_cost_model_is_selectors_calibrated_object_and_refit_flips():
    op, nbytes, world = "all_reduce", 1 << 20, 30
    axes_sig = (("dp", world),)
    selector.configure(compiled_search=True, codecs=("int8",))
    cm = selector.cost_model()
    # alpha-dominant: hop count decides -> compiled [2,3,5] wins at the
    # non-pow2 world (rhd out; 14 hops vs ring2d 18 / bidir 58)
    selector.calibrate("ppermute", 10.0, 0.1)
    d = selector.select(op, nbytes, world, codec="int8", axes_sig=axes_sig)
    assert d.algorithm.startswith("compiled:"), d
    # the compiler consumed THE selector model, not a frozen copy
    assert cm is selector.cost_model()
    sched = schedule.compile_schedule(op, axes_sig, nbytes, "int8", cm=cm)
    assert f"compiled:{sched.signature}" == d.algorithm
    # beta-dominant refit of the SAME model: bidir's half per-link wire
    # beats single-direction sub-rings -> the SAME query flips
    selector.calibrate("ppermute", 0.01, 100.0)
    d2 = selector.select(op, nbytes, world, codec="int8", axes_sig=axes_sig)
    assert d2.algorithm == "bidir", d2
    # alpha huge + no forced codec: the 0-hop lax floor wins
    selector.calibrate("ppermute", 1e6, 1e-6)
    d3 = selector.select(op, nbytes, 8, axes_sig=(("dp", 8),))
    assert d3.algorithm == "lax", d3


def test_decision_cache_keys_on_axis_factorization():
    # same (op, bytes, world, codec) but different mesh factorizations
    # must NOT collapse to one cached decision
    selector.configure(compiled_search=True, codecs=("int8",))
    selector.calibrate("ppermute", 10.0, 0.1)
    d_flat = selector.select("all_reduce", 1 << 20, 30, codec="int8",
                             axes_sig=(("dp", 30),))
    d_mesh = selector.select("all_reduce", 1 << 20, 30, codec="int8",
                             axes_sig=(("ep", 5), ("dp", 6)))
    assert d_flat.algorithm.startswith("compiled:")
    assert d_mesh.algorithm.startswith("compiled:")
    assert d_flat.algorithm != d_mesh.algorithm
    assert "ep*" in d_mesh.algorithm and "ep*" not in d_flat.algorithm


def test_tier_beta_scale_surfaces_mixed_placement():
    # free inner tier (NVLink-like): exact wire on the first level, int8
    # outside — the ZeRO++ shape from search, not hard-coding
    selector.configure(compiled_search=True, codecs=("int8",))
    selector.cost_model().set_tier_beta_scale((0.0, 1.0))
    d = selector.select("all_reduce", 1 << 20, 8, axes_sig=(("dp", 8),))
    assert d.algorithm.startswith("compiled:"), d
    levels = schedule.parse_signature(d.algorithm.split(":", 1)[1])
    assert levels[0].codec == "none"
    assert levels[-1].codec == "int8"


def test_candidate_signatures_feed_sweep_rows():
    sigs = schedule.candidate_signatures("all_reduce", "dp", 8,
                                         codecs=("none", "int8"))
    assert 0 < len(sigs) <= 3
    for sig in sigs:
        levels = schedule.parse_signature(sig)
        assert np.prod([lv.size for lv in levels]) == 8
    # the sweep enumerates compiled rows next to the hand algorithms
    pairs = benchmark.candidate_pairs(8, ("none", "int8"),
                                      op="all_reduce", axis="dp")
    compiled = [a for a, _ in pairs if a.startswith("compiled:")]
    assert compiled, pairs
