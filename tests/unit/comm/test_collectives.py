"""collectives/: algorithm x codec equivalence, selector, overlap, EF.

Correctness bar (ISSUE 3 acceptance): on the forced 8-device CPU mesh every
hop-composed algorithm matches the ``jax.lax`` baseline collective —
bit-level for passthrough codecs (integer-valued payloads make every
summation order exact), bounded relative error for the int8/fp8 wire codecs
— including non-divisible payloads (internal chunk padding) and block sizes
that do not divide the chunk (codec padding). The selector answers repeated
(op, bytes, axis-size) queries from its cache, measured mode consumes the
``benchmark --sweep`` decision table, and a ``ring2d``+``int8`` all-reduce
runs inside a jitted train step with its hops visible in the exported trace.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu.comm as dist
from deepspeed_tpu import collectives, telemetry
from deepspeed_tpu.collectives import codecs as codecs_mod
from deepspeed_tpu.collectives import overlap, selector
from deepspeed_tpu.utils.compat import shard_map

ALGS = ("ring", "bidir", "rhd", "ring2d")
CODECS = ("none", "fp32", "bf16", "int8", "fp8")
BLOCK = 32


@pytest.fixture
def mesh8():
    devs = jax.devices()[:8]
    return Mesh(np.array(devs), ("dp",))


@pytest.fixture(autouse=True)
def _reset_selector():
    selector.configure()
    yield
    selector.configure()


def _run(mesh, f, *xs, in_specs=None, out_specs=None):
    in_specs = in_specs if in_specs is not None else tuple(P("dp") for _ in xs)
    out_specs = out_specs if out_specs is not None else P("dp")
    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False))(*xs)


def _int_payload(shape, seed=0):
    """Integer-valued fp32: every summation order is exact, so passthrough
    codecs can be checked bit-level even through reductions."""
    return jnp.asarray(np.random.default_rng(seed).integers(-8, 9, shape), jnp.float32)


# ------------------------------------------------------- algorithm x codec


@pytest.mark.parametrize("alg", ALGS)
@pytest.mark.parametrize("codec", CODECS)
def test_all_reduce_matrix_vs_lax(mesh8, alg, codec):
    x = _int_payload((8, 96 + 7))  # 103: not divisible by 8 -> padding path

    def f(v):
        return collectives.all_reduce(v[0], "dp", algorithm=alg, codec=codec,
                                      block_size=BLOCK)[None]

    out = np.asarray(_run(mesh8, f, x)).reshape(8, -1)
    expected = np.tile(np.asarray(x).sum(0, keepdims=True), (8, 1))
    if codec in ("none", "fp32"):
        np.testing.assert_array_equal(out, expected)
    elif codec == "bf16":
        np.testing.assert_allclose(out, expected, rtol=0.05, atol=1.0)
    else:  # int8 / fp8: blockwise-quantized partial sums
        scale = np.abs(expected).max() + 1e-9
        assert np.abs(out - expected).max() / scale < 0.15, codec


@pytest.mark.parametrize("alg", ("ring", "bidir", "rhd"))
@pytest.mark.parametrize("codec", CODECS)
def test_all_gather_matrix_vs_lax(mesh8, alg, codec):
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 37)).astype(jnp.float32)

    def f(v):
        return collectives.all_gather(v[0], "dp", algorithm=alg, codec=codec,
                                      block_size=BLOCK)[None]

    out = np.asarray(_run(mesh8, f, x))[0].reshape(8, 37)
    expected = np.asarray(
        jax.jit(shard_map(lambda v: jax.lax.all_gather(v[0], "dp")[None],
                          mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"),
                          check_vma=False))(x))[0]
    if codec in ("none", "fp32"):
        np.testing.assert_array_equal(out, expected)  # pure data movement
    elif codec == "bf16":
        np.testing.assert_allclose(out, expected, rtol=0.01, atol=0.01)
    else:  # encode-once forwarding: ONE quantization regardless of hops
        scale = np.abs(expected).max() + 1e-9
        # int8: 1/254 of block max; fp8 E4M3: ~2^-3 relative (3 mantissa bits)
        tol = 0.01 if codec == "int8" else 0.05
        assert np.abs(out - expected).max() / scale < tol, codec


@pytest.mark.parametrize("alg", ("ring", "bidir", "rhd"))
@pytest.mark.parametrize("codec", ("none", "int8"))
def test_reduce_scatter_matrix_vs_lax(mesh8, alg, codec):
    x = _int_payload((8, 96), seed=2)  # 96 = 8 * 12

    def f(v):
        return collectives.reduce_scatter(v[0], "dp", algorithm=alg, codec=codec,
                                          block_size=BLOCK)[None]

    out = np.asarray(_run(mesh8, f, x)).reshape(8, 12)
    expected = np.asarray(x).sum(0).reshape(8, 12)
    if codec == "none":
        np.testing.assert_array_equal(out, expected)
    else:
        scale = np.abs(expected).max() + 1e-9
        assert np.abs(out - expected).max() / scale < 0.15


@pytest.mark.parametrize("alg", ALGS)
def test_lossy_all_reduce_ranks_agree(mesh8, alg):
    """Every rank must end with IDENTICAL bytes after a lossy all-reduce —
    the sender's own block goes through the same encode/decode as its
    peers' copies, or data-parallel replicas silently drift apart."""
    x = jax.random.normal(jax.random.PRNGKey(12), (8, 96)).astype(jnp.float32)
    out = np.asarray(_run(
        mesh8, lambda v: collectives.all_reduce(v[0], "dp", algorithm=alg,
                                                codec="int8", block_size=32)[None],
        x)).reshape(8, -1)
    for r in range(1, 8):
        np.testing.assert_array_equal(out[r], out[0], err_msg=alg)


def test_bf16_all_reduce_accumulates_fp32(mesh8):
    """Partial sums must carry fp32 through the hop chain: a bf16
    accumulator would round every hop, drifting past lax.psum's error as
    the world grows."""
    x = (jax.random.normal(jax.random.PRNGKey(9), (8, 1024)) * 3).astype(jnp.bfloat16)
    ref = np.asarray(x).astype(np.float64).sum(0)
    lax_err = np.abs(np.asarray(_run(
        mesh8, lambda v: jax.lax.psum(v[0], "dp")[None], x))[0].astype(np.float64)
        - ref).max()
    for alg in ALGS:
        got = np.asarray(_run(
            mesh8, lambda v, a=alg: collectives.all_reduce(v[0], "dp", algorithm=a)[None],
            x))[0].astype(np.float64)
        assert np.abs(got - ref).max() <= lax_err + 1e-9, alg


def test_reduce_scatter_rejects_non_divisible(mesh8):
    x = jnp.ones((8, 97), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        _run(mesh8, lambda v: collectives.reduce_scatter(v[0], "dp")[None], x)


def test_codec_block_not_dividing_chunk(mesh8):
    """Chunk length 13 with block 32: the codec pads each row internally and
    strips it — output length must survive exactly."""
    x = _int_payload((8, 8 * 13), seed=3)
    out = np.asarray(_run(
        mesh8,
        lambda v: collectives.all_reduce(v[0], "dp", algorithm="ring",
                                         codec="int8", block_size=32)[None],
        x)).reshape(8, -1)
    expected = np.tile(np.asarray(x).sum(0, keepdims=True), (8, 1))
    assert out.shape == expected.shape
    scale = np.abs(expected).max() + 1e-9
    assert np.abs(out - expected).max() / scale < 0.15


def test_hierarchical_all_reduce_multi_axis():
    """Mesh-axis-factored hierarchy (the hpZ shape): all_reduce over the
    ('fsdp', 'dp') tuple == global sum over both axes."""
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("dp", "fsdp"))
    x = _int_payload((4, 2, 24), seed=4)

    def f(v):
        return collectives.all_reduce(v[0, 0], ("fsdp", "dp"), codec="none")[None, None]

    out = np.asarray(_run(
        mesh, f, x, in_specs=(P("dp", "fsdp"),), out_specs=P("dp", "fsdp")))
    expected = np.asarray(x).sum((0, 1))
    for u in range(4):
        for v in range(2):
            np.testing.assert_array_equal(out[u, v], expected)


def test_codec_roundtrip_all():
    """encode_rows/decode_rows invariants for every registered codec."""
    x = jax.random.normal(jax.random.PRNGKey(5), (3, 45)).astype(jnp.float32)
    for name in CODECS:
        c = codecs_mod.get_codec(name, 16)
        back = np.asarray(c.decode_rows(c.encode_rows(x), 45, jnp.float32))
        assert back.shape == (3, 45)
        tol = 0.0 if name in ("none", "fp32") else 0.2
        assert np.abs(back - np.asarray(x)).max() <= tol + 1e-6, name
    with pytest.raises(ValueError, match="unknown codec"):
        codecs_mod.get_codec("int3")


# ------------------------------------------------------------ facade wiring


def test_facade_default_is_lax_baseline(mesh8):
    """No algorithm/codec arguments -> byte-identical lax lowering (the
    subsystem must be invisible until asked for)."""
    x = jnp.arange(16, dtype=jnp.float32).reshape(8, 2)
    out = _run(mesh8, lambda v: dist.all_reduce(v, "dp"), x)
    np.testing.assert_array_equal(
        np.asarray(out), np.tile(np.asarray(x).sum(0, keepdims=True), (8, 1)))


def test_facade_auto_consults_selector(mesh8):
    selector.configure(codecs=("none",))
    before = selector.cache_info()["misses"]
    x = jnp.ones((8, 64), jnp.float32)
    _run(mesh8, lambda v: dist.all_reduce(v[0], "dp", algorithm="auto")[None], x)
    info = selector.cache_info()
    assert info["misses"] == before + 1 and info["entries"] >= 1


# ---------------------------------------------------------------- selector


def test_forced_codec_bypasses_lax_floor():
    """An explicit codec is a quantization request the native lowering
    cannot serve: the small-payload lax floor must not swallow it."""
    d = selector.select("all_reduce", 1024, 8, codec="int8")
    assert d.algorithm != "lax" and d.codec == "int8"
    # ...while un-forced tiny queries still floor to lax
    assert selector.select("all_reduce", 1024, 8).algorithm == "lax"


def test_config_concrete_algorithm_with_auto_codec():
    """codec 'auto' + a concrete algorithm: the selector still picks the
    wire among the configured candidates (here int8 for a big payload,
    exact under min_quant_bytes)."""
    selector.configure(codecs=("none", "int8"))
    assert selector.pick_codec("all_reduce", 1 << 22, 8, "ring2d") == "int8"
    assert selector.pick_codec("all_reduce", 1 << 10, 8, "ring2d") == "none"


def test_selector_caches_repeated_queries():
    d1 = selector.select("all_reduce", 1 << 20, 8)
    d2 = selector.select("all_reduce", 1 << 20, 8)
    assert d1 is d2  # the cached Decision object itself
    info = selector.cache_info()
    assert info["hits"] >= 1 and info["entries"] == 1
    # a different bytes bucket is a fresh decision
    d3 = selector.select("all_reduce", 1 << 24, 8)
    assert d3 is not d1 and selector.cache_info()["entries"] == 2


def test_selector_model_latency_vs_bandwidth_regimes():
    """Alpha-beta model sanity. Exact-wire candidates can never beat the
    native baseline (same bytes + hop latency => lax). Quantized routing:
    small payloads go latency-optimal (rhd, log2(n) hops); huge payloads
    prefer a bandwidth-optimal ring variant."""
    selector.configure(alpha_us=5.0, beta_us_per_mb=10.0, codecs=("none",))
    assert selector.select("all_reduce", 16, 8).algorithm == "lax"  # floor
    assert selector.select("all_reduce", 1 << 28, 8).algorithm == "lax"  # no wire win
    selector.configure(alpha_us=5.0, beta_us_per_mb=10.0)
    small = selector.select("all_reduce", 1 << 13, 8, codec="int8")
    large = selector.select("all_reduce", 1 << 28, 8, codec="int8")
    assert small.algorithm == "rhd", small
    assert large.algorithm in ("ring", "bidir", "ring2d"), large
    # non-power-of-two world can never pick rhd
    odd = selector.select("all_reduce", 1 << 13, 6, codec="int8")
    assert odd.algorithm != "rhd"


def test_selector_all_lossy_codecs_small_payload():
    """codecs=["int8"] (no exact entry) + a payload under min_quant_bytes
    must fall back to the exact wire, not crash with an empty candidate
    set."""
    selector.configure(codecs=("int8",), min_quant_bytes=1 << 16)
    d = selector.select("all_reduce", 1024, 8)
    assert d.codec == "none"
    big = selector.select("all_reduce", 1 << 22, 8)
    assert big.codec == "int8"


def test_facade_config_default_routing(mesh8):
    """The collectives config block's algorithm/codec become the facade
    default: a plain dist.all_reduce call (no arguments) routes through the
    configured algorithm — and reverts to lax when unset."""
    selector.configure(facade_algorithm="ring", facade_codec="int8")
    tracer = telemetry.configure(enabled=True)
    tracer.reset()
    try:
        x = _int_payload((8, 64), seed=11)
        out = _run(mesh8, lambda v: dist.all_reduce(v[0], "dp")[None], x)
        expected = np.tile(np.asarray(x).sum(0, keepdims=True), (8, 1))
        scale = np.abs(expected).max() + 1e-9
        assert np.abs(np.asarray(out).reshape(8, -1) - expected).max() / scale < 0.15
        facade = next(e for e in tracer.events() if e.get("name") == "comm:all_reduce_sum")
        assert facade["args"]["algorithm"] == "ring"
        assert facade["args"]["codec"] == "int8"
        # unset -> plain lax lowering again, no routing tags
        selector.configure()
        tracer.reset()
        _run(mesh8, lambda v: dist.all_reduce(v[0], "dp")[None], x)
        facade = next(e for e in tracer.events() if e.get("name") == "comm:all_reduce_sum")
        assert "algorithm" not in facade.get("args", {})
    finally:
        telemetry.configure(enabled=False)


def test_facade_default_skips_unsupported_shapes(mesh8):
    """Default-routed calls must stay on the lax lowering for max/min
    reductions and non-float payloads (the algorithmic path cannot serve
    them); explicit requests surface the library's own error instead."""
    selector.configure(facade_algorithm="auto", facade_codec="int8",
                       codecs=("none", "int8"))
    x = jnp.arange(16, dtype=jnp.float32).reshape(8, 2)
    out = _run(mesh8, lambda v: dist.all_reduce(v, "dp", op="max"), x)
    np.testing.assert_array_equal(
        np.asarray(out), np.tile(np.asarray(x).max(0, keepdims=True), (8, 1)))
    # int payloads: excluded from default routing (native lowering, exact)
    xi = jnp.arange(16, dtype=jnp.int32).reshape(8, 2)
    gi = _run(mesh8, lambda v: dist.all_gather(v[0], "dp")[None], xi,
              in_specs=(P("dp"),))
    np.testing.assert_array_equal(np.asarray(gi)[0].reshape(8, 2), np.asarray(xi))
    with pytest.raises(ValueError, match="unsupported by algorithmic"):
        _run(mesh8, lambda v: dist.all_reduce(v, "dp", op="max", algorithm="ring"), x)


def test_engine_disabled_resets_facade_defaults(mesh8):
    """A previously-installed facade default must not leak into an engine
    constructed with collectives disabled (the config block's 'disabled =>
    unchanged program' promise)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    selector.configure(facade_algorithm="ring2d", facade_codec="int8")
    tc = TransformerConfig(vocab_size=32, hidden_size=16, intermediate_size=32,
                           num_layers=1, num_heads=2, max_seq_len=16)
    deepspeed_tpu.initialize(
        model=causal_lm_spec(tc, example_seq_len=8),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "steps_per_print": 10_000})
    assert selector.get_config().facade_algorithm is None


def test_error_feedback_requires_ring():
    with pytest.raises(ValueError, match="ring"):
        collectives.reduce_scatter(jnp.ones((8, 8)), "dp", algorithm="rhd",
                                   err=jnp.zeros((8, 8)))


def test_selector_explicit_model_mode_ignores_table(tmp_path):
    table = [{"op": "all_reduce", "world": 8, "size_mb": 1.0,
              "algorithm": "ring2d", "codec": "int8", "latency_ms": 0.5}]
    path = tmp_path / "table.json"
    path.write_text(json.dumps(table))
    selector.configure(mode="model", decision_table=str(path))
    d = selector.select("all_reduce", 1_000_000, 8)
    assert d.source == "model"


def test_selector_measured_mode_uses_decision_table(tmp_path):
    table = [
        {"op": "all_reduce", "world": 8, "size_mb": 1.0, "algorithm": "ring2d",
         "codec": "int8", "latency_ms": 0.5},
        {"op": "all_reduce", "world": 8, "size_mb": 1.0, "algorithm": "ring",
         "codec": "none", "latency_ms": 2.0},
    ]
    path = tmp_path / "table.json"
    path.write_text(json.dumps(table))
    # measured rows only rank codecs the config authorizes
    selector.configure(decision_table=str(path), codecs=("none", "int8"))
    d = selector.select("all_reduce", 1_000_000, 8)
    assert d.source == "measured" and d.algorithm == "ring2d" and d.codec == "int8"
    # ...and never a lossy wire under min_quant_bytes (model-path parity)
    small = selector.select("all_reduce", 1024, 8)
    assert small.codec == "none"
    # ops absent from the table fall back to the model
    d2 = selector.select("all_gather", 1_000_000, 8)
    assert d2.source == "model"


def test_measured_lax_decision_stays_on_lax_lowering(mesh8, tmp_path):
    """A measured 'don't bother' verdict (algorithm='lax' row wins) must
    fall back to the plain lowering through the facade, not crash the
    algorithmic dispatch."""
    table = [{"op": "all_reduce", "world": 8, "size_mb": 0.001,
              "algorithm": "lax", "codec": "none", "latency_ms": 0.1},
             {"op": "all_reduce", "world": 8, "size_mb": 0.001,
              "algorithm": "ring", "codec": "none", "latency_ms": 9.9}]
    path = tmp_path / "lax.json"
    path.write_text(json.dumps(table))
    selector.configure(decision_table=str(path))
    assert selector.select("all_reduce", 1000, 8).algorithm == "lax"
    x = jnp.arange(16, dtype=jnp.float32).reshape(8, 2)
    out = _run(mesh8, lambda v: dist.all_reduce(v, "dp", algorithm="auto"), x)
    np.testing.assert_array_equal(
        np.asarray(out), np.tile(np.asarray(x).sum(0, keepdims=True), (8, 1)))


def test_benchmark_sweep_feeds_selector(tmp_path):
    """--sweep emits rows the selector's measured mode consumes."""
    from deepspeed_tpu.comm.benchmark import run_sweep

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("dp",))
    rows = run_sweep(ops=("all_reduce",), sizes_mb=[0.01], mesh=mesh,
                     algorithms=["lax", "ring"], codecs=["none"],
                     iters=2, warmup=1)
    assert {r["algorithm"] for r in rows} == {"lax", "ring"}
    assert all(r["latency_ms"] > 0 and r["world"] == 4 for r in rows)
    path = tmp_path / "sweep.json"
    path.write_text(json.dumps(rows))
    selector.configure(decision_table=str(path))
    d = selector.select("all_reduce", 10_000, 4)
    assert d.source == "measured"
    assert d.algorithm in ("lax", "ring")


# ------------------------------------------------------------ error feedback


def test_error_feedback_average_converges(mesh8):
    """LoCo property: with the residual carried across calls, the RUNNING
    AVERAGE of int8 reduce-scatter outputs converges toward the exact sum
    (the compensation telescopes); without EF the quantization bias is
    constant and the average never improves."""
    n, L = 8, 64
    x = jax.random.normal(jax.random.PRNGKey(0), (n, n * L)).astype(jnp.float32) * 3.0

    def f_ef(v, err):
        out, new_err = collectives.reduce_scatter(
            v[0], "dp", algorithm="ring", codec="int8", block_size=32, err=err[0])
        return out[None], new_err[None]

    step = jax.jit(shard_map(f_ef, mesh=mesh8, in_specs=(P("dp"), P("dp")),
                             out_specs=(P("dp"), P("dp")), check_vma=False))

    def f_ne(v):
        return collectives.reduce_scatter(
            v[0], "dp", algorithm="ring", codec="int8", block_size=32)[None]

    step_ne = jax.jit(shard_map(f_ne, mesh=mesh8, in_specs=P("dp"),
                                out_specs=P("dp"), check_vma=False))

    exact = np.asarray(x).sum(0).reshape(n, L)
    err = jnp.zeros((n, n, L), jnp.float32)
    T = 16
    run_ef = np.zeros_like(exact)
    first_err = None
    for t in range(1, T + 1):
        out, err = step(x, err)
        run_ef += np.asarray(out).reshape(n, L)
        if t == 1:
            first_err = np.abs(run_ef - exact).max()
    avg_err = np.abs(run_ef / T - exact).max()
    ne_err = np.abs(np.asarray(step_ne(x)).reshape(n, L) - exact).max()
    assert avg_err < first_err / 4, (avg_err, first_err)
    assert avg_err < ne_err / 4, (avg_err, ne_err)


# ---------------------------------------------------------------- overlap


def test_double_buffered_matches_plain():
    xs = [jnp.arange(4, dtype=jnp.float32) + k for k in range(5)]
    got = overlap.double_buffered(xs, comm_fn=lambda v: v * 2, compute_fn=lambda v: v + 1)
    for g, x in zip(got, xs):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(x) * 2 + 1)
    assert overlap.double_buffered([], lambda v: v, lambda v: v) == []


def test_double_buffered_scan_matches_plain():
    chunks = jnp.arange(24, dtype=jnp.float32).reshape(6, 4)
    got = jax.jit(lambda c: overlap.double_buffered_scan(
        c, comm_fn=lambda v: v * 3, compute_fn=lambda v: v - 1))(chunks)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(chunks) * 3 - 1)
    one = overlap.double_buffered_scan(chunks[:1], lambda v: v * 3, lambda v: v - 1)
    np.testing.assert_array_equal(np.asarray(one), np.asarray(chunks[:1]) * 3 - 1)


def test_zeropp_gather_overlap_chunks_equivalent(mesh8):
    """The chunked double-buffered qwZ gather is numerically identical to
    the monolithic one (same codec, same blocks — only the schedule moves)."""
    from deepspeed_tpu.parallel.zeropp import _int8_all_gather_dim

    x = jax.random.normal(jax.random.PRNGKey(7), (8, 16, 6)).astype(jnp.float32)

    def f(chunks):
        def body(v):
            return _int8_all_gather_dim(v[0], 0, "dp", 32, chunks)[None]
        return body

    base = np.asarray(_run(mesh8, f(1), x))
    for chunks in (2, 4):
        got = np.asarray(_run(mesh8, f(chunks), x))
        np.testing.assert_array_equal(got, base)


# ----------------------------------------------- end-to-end + telemetry


def test_ring2d_int8_train_step_with_hop_spans(mesh8, tmp_path):
    """Acceptance: comm.all_reduce(algorithm='ring2d', codec='int8') inside
    a jitted train step, hop spans + the routing decision in the trace."""
    tracer = telemetry.configure(enabled=True, trace_path=str(tmp_path / "trace.json"))
    tracer.reset()
    try:
        w0 = jnp.zeros((64,), jnp.float32)
        x = _int_payload((8, 64), seed=8)

        def local_step(w, batch):
            # grad of a toy quadratic loss; the grad all-reduce is the
            # algorithmic quantized collective under test
            g = jax.grad(lambda wv: jnp.sum((batch[0] - wv) ** 2))(w)
            g = dist.all_reduce(g, "dp", op="mean", algorithm="ring2d",
                                codec="int8", block_size=32)
            return w - 0.1 * g

        step = jax.jit(shard_map(
            local_step, mesh=mesh8, in_specs=(P(), P("dp")), out_specs=P(),
            check_vma=False))
        w1 = step(w0, x)
        assert np.isfinite(np.asarray(w1)).all()
        # one traced program: facade span tagged with the routing, per-hop
        # coll: spans, and the underlying ppermute transfers
        names = [e.get("name") for e in tracer.events()]
        assert any(n == "comm:all_reduce_mean" for n in names)
        facade = next(e for e in tracer.events() if e.get("name") == "comm:all_reduce_mean")
        assert facade["args"]["algorithm"] == "ring2d"
        assert facade["args"]["codec"] == "int8"
        hop_names = {n for n in names if n and n.startswith("coll:all_reduce:ring2d")}
        assert {"coll:all_reduce:ring2d/intra-rs", "coll:all_reduce:ring2d/inter-rs",
                "coll:all_reduce:ring2d/inter-ag", "coll:all_reduce:ring2d/intra-ag"
                } <= hop_names, hop_names
        assert any(n == "comm:ppermute" for n in names)
        # the exported chrome trace holds the same hop spans
        telemetry.export_chrome_trace(str(tmp_path / "trace.json"))
        trace = json.loads((tmp_path / "trace.json").read_text())
        tnames = {ev.get("name") for ev in trace.get("traceEvents", [])}
        assert "coll:all_reduce:ring2d/inter-rs" in tnames
    finally:
        telemetry.configure(enabled=False)


def _count_primitives(jaxpr, counts=None):
    """Recursive primitive census of a (closed) jaxpr — the structural
    evidence for 'one fused program per hop'."""
    counts = counts if counts is not None else {}
    for eqn in jaxpr.eqns:
        counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
        for v in eqn.params.values():
            for j in jax.tree_util.tree_leaves(
                    v, is_leaf=lambda x: isinstance(
                        x, (jax.core.Jaxpr, jax.core.ClosedJaxpr))):
                if isinstance(j, jax.core.ClosedJaxpr):
                    _count_primitives(j.jaxpr, counts)
                elif isinstance(j, jax.core.Jaxpr):
                    _count_primitives(j, counts)
    return counts


# ------------------------------------------------- pallas remote-DMA backend


@pytest.mark.parametrize("alg", ("pallas_ring", "pallas_ring2d"))
def test_pallas_all_reduce_bit_identical_vs_ring(mesh8, alg):
    """Interpret-mode equivalence: exact-wire pallas all-reduce over remote
    DMA hops is BIT-identical to the ppermute ring (and to the true sum —
    integer payloads make every summation order exact). 103 columns is the
    non-divisible chunk-padding path."""
    x = _int_payload((8, 103), seed=21)

    def f(alg):
        return lambda v: collectives.all_reduce(v[0], "dp", algorithm=alg)[None]

    got = np.asarray(_run(mesh8, f(alg), x)).reshape(8, -1)
    ref = np.asarray(_run(mesh8, f("ring"), x)).reshape(8, -1)
    np.testing.assert_array_equal(got, ref)
    np.testing.assert_array_equal(
        got, np.tile(np.asarray(x).sum(0, keepdims=True), (8, 1)))


def test_pallas_all_gather_and_reduce_scatter_match_ring(mesh8):
    """pallas_ring selectable through the comm FACADE for gather/scatter
    too (acceptance), bit-identical to the ppermute ring."""
    x = _int_payload((8, 37), seed=22)
    g = np.asarray(_run(
        mesh8, lambda v: dist.all_gather(v[0], "dp", algorithm="pallas_ring")[None], x))
    gr = np.asarray(_run(
        mesh8, lambda v: collectives.all_gather(v[0], "dp", algorithm="ring")[None], x))
    np.testing.assert_array_equal(g, gr)
    xs = _int_payload((8, 96), seed=23)
    rs = np.asarray(_run(
        mesh8, lambda v: dist.reduce_scatter(v[0], "dp", algorithm="pallas_ring")[None],
        xs)).reshape(8, 12)
    np.testing.assert_array_equal(rs, np.asarray(xs).sum(0).reshape(8, 12))


@pytest.mark.parametrize("alg", ("pallas_ring", "pallas_ring2d"))
@pytest.mark.parametrize("codec", ("int8", "fp8"))
def test_pallas_fused_quant_all_reduce_bounded_error(mesh8, alg, codec):
    """The fused dequant-accumulate-requant hop must track the UNFUSED wire
    codec path (same block math via ops.quant, same fp32 accumulation) and
    stay within the quantization tolerance of the exact sum. 103 columns
    exercises both the chunk padding and the codec block padding."""
    x = (jax.random.normal(jax.random.PRNGKey(24), (8, 103)) * 3).astype(jnp.float32)

    def f(a, c):
        return lambda v: collectives.all_reduce(v[0], "dp", algorithm=a,
                                                codec=c, block_size=32)[None]

    fused = np.asarray(_run(mesh8, f(alg, codec), x)).reshape(8, -1)
    base_alg = "ring" if alg == "pallas_ring" else "ring2d"
    unfused = np.asarray(_run(mesh8, f(base_alg, codec), x)).reshape(8, -1)
    exact = np.tile(np.asarray(x).sum(0, keepdims=True), (8, 1))
    scale = np.abs(exact).max() + 1e-9
    assert np.abs(fused - exact).max() / scale < 0.15, (alg, codec)
    assert np.abs(fused - unfused).max() / scale < 0.05, (alg, codec)
    # every rank ends with identical bytes (replica-drift guard)
    for r in range(1, 8):
        np.testing.assert_array_equal(fused[r], fused[0], err_msg=alg)


def test_pallas_fused_hop_is_single_program(mesh8):
    """Structural acceptance: the fused quantized reduce-scatter runs ONE
    pallas program per hop — no collective-permutes, no separate quant
    programs between hops — where the ppermute+int8 path runs 2 ppermutes
    per hop (wire values + scales) around XLA-side codec math."""
    from deepspeed_tpu.utils.compat import shard_map as smap

    x = jnp.ones((8, 96), jnp.float32)

    def traced(alg):
        def body(v):
            return collectives.reduce_scatter(v[0], "dp", algorithm=alg,
                                              codec="int8", block_size=32)[None]
        return jax.make_jaxpr(smap(body, mesh=mesh8, in_specs=P("dp"),
                                   out_specs=P("dp"), check_vma=False))(x)

    fused = _count_primitives(traced("pallas_ring").jaxpr)
    assert fused.get("pallas_call", 0) == 7  # n-1 hops, one program each
    assert fused.get("ppermute", 0) == 0
    unfused = _count_primitives(traced("ring").jaxpr)
    assert unfused.get("pallas_call", 0) == 0  # CPU dispatch: xla codec math
    assert unfused.get("ppermute", 0) == 2 * 7  # q + scales per hop


def test_pallas_exact_wire_hops_are_remote_dma(mesh8):
    """Exact codecs don't fuse, but their hops still ride remote DMA: one
    pallas program per hop (the wire's q leaf; zero-size scale placeholders
    skip), zero ppermutes."""
    from deepspeed_tpu.utils.compat import shard_map as smap

    x = jnp.ones((8, 96), jnp.float32)

    def body(v):
        return collectives.all_gather(v[0], "dp", algorithm="pallas_ring")[None]

    jaxpr = jax.make_jaxpr(smap(body, mesh=mesh8, in_specs=P("dp"),
                                out_specs=P("dp"), check_vma=False))(x)
    counts = _count_primitives(jaxpr.jaxpr)
    assert counts.get("pallas_call", 0) == 7
    assert counts.get("ppermute", 0) == 0


def test_pallas_error_feedback_still_requires_ring():
    with pytest.raises(ValueError, match="ring"):
        collectives.reduce_scatter(jnp.ones((8, 8)), "dp",
                                   algorithm="pallas_ring",
                                   err=jnp.zeros((8, 8)))


def test_pallas_train_step_smoke_with_hop_spans(mesh8, tmp_path):
    """Acceptance: comm.all_reduce(algorithm='pallas_ring', codec='int8')
    inside a jitted train step — fused hop spans (tagged backend=pallas,
    fused) in the exported trace, comm:remote_dma transfers instead of
    comm:ppermute."""
    tracer = telemetry.configure(enabled=True, trace_path=str(tmp_path / "t.json"))
    tracer.reset()
    try:
        w0 = jnp.zeros((64,), jnp.float32)
        x = _int_payload((8, 64), seed=25)

        def local_step(w, batch):
            g = jax.grad(lambda wv: jnp.sum((batch[0] - wv) ** 2))(w)
            g = dist.all_reduce(g, "dp", op="mean", algorithm="pallas_ring",
                                codec="int8", block_size=32)
            return w - 0.1 * g

        step = jax.jit(shard_map(
            local_step, mesh=mesh8, in_specs=(P(), P("dp")), out_specs=P(),
            check_vma=False))
        assert np.isfinite(np.asarray(step(w0, x))).all()
        events = tracer.events()
        names = [e.get("name") for e in events]
        facade = next(e for e in events if e.get("name") == "comm:all_reduce_mean")
        assert facade["args"]["algorithm"] == "pallas_ring"
        assert facade["args"]["codec"] == "int8"
        # fused RS hops: coll: spans tagged with the backend and the fusion
        rs_hops = [e for e in events
                   if e.get("name") == "coll:reduce_scatter:pallas_ring"]
        assert len(rs_hops) == 7 and all(
            e["args"]["backend"] == "pallas" and e["args"]["fused"] for e in rs_hops)
        # AG relay hops keep their schedule label, backend-tagged
        ag_hops = [e for e in events if e.get("name") == "coll:all_gather:ring"]
        assert len(ag_hops) == 7 and all(
            e["args"]["backend"] == "pallas" for e in ag_hops)
        assert any(n == "comm:remote_dma" for n in names)
        assert not any(n == "comm:ppermute" for n in names)
        telemetry.export_chrome_trace(str(tmp_path / "t.json"))
        trace = json.loads((tmp_path / "t.json").read_text())
        tnames = {ev.get("name") for ev in trace.get("traceEvents", [])}
        assert "coll:reduce_scatter:pallas_ring" in tnames
        assert "comm:remote_dma" in tnames
    finally:
        telemetry.configure(enabled=False)


def test_pallas_multi_axis_tuple_rides_hierarchy():
    """pallas_ring over an axis tuple runs the mesh-axis-factored hierarchy.
    The 0.4.x Pallas INTERPRETER cannot discharge remote DMA on multi-axis
    shardings, so on this CPU mesh the hops fall back to ppermute with a
    logged note (compiled TPU runs keep the kernels) — the schedule and
    numerics are what this test pins."""
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("dp", "fsdp"))
    x = _int_payload((4, 2, 24), seed=26)

    def f(v):
        return collectives.all_reduce(v[0, 0], ("fsdp", "dp"),
                                      algorithm="pallas_ring")[None, None]

    out = np.asarray(_run(mesh, f, x, in_specs=(P("dp", "fsdp"),),
                          out_specs=P("dp", "fsdp")))
    expected = np.asarray(x).sum((0, 1))
    for u in range(4):
        for v in range(2):
            np.testing.assert_array_equal(out[u, v], expected)


def test_selector_never_picks_pallas_off_tpu():
    """Model mode must not route remote-DMA algorithms where the backend
    cannot run them compiled (interpret mode is a test vehicle, not a
    transport); monkeypatched availability admits them — and the cache key
    carries the backend so the two regimes never share decisions."""
    from deepspeed_tpu.collectives import pallas_backend

    selector.configure(codecs=("none", "int8"))
    d = selector.select("all_reduce", 1 << 24, 8, codec="int8")
    assert not d.algorithm.startswith("pallas_")


def test_selector_pallas_available_changes_model_and_cache(monkeypatch):
    from deepspeed_tpu.collectives import pallas_backend

    selector.configure(codecs=("none", "int8"), alpha_us=50.0,
                       beta_us_per_mb=10.0)
    before = selector.select("all_reduce", 1 << 24, 8, codec="int8")
    monkeypatch.setattr(pallas_backend, "available", lambda: True)
    after = selector.select("all_reduce", 1 << 24, 8, codec="int8")
    # same query, different backend token => a FRESH cache entry, and with
    # the alpha discount the pallas carrier wins at this hop-heavy regime
    assert selector.cache_info()["entries"] == 2
    assert after.algorithm.startswith("pallas_"), after
    assert not before.algorithm.startswith("pallas_")


def test_measured_table_backend_stamps(monkeypatch, tmp_path):
    """A ppermute-era table (no backend stamp) must never route a pallas
    algorithm even when the backend is available; correctly stamped pallas
    rows route only when it is."""
    from deepspeed_tpu.collectives import pallas_backend

    table = [
        {"op": "all_reduce", "world": 8, "size_mb": 1.0, "algorithm": "pallas_ring",
         "codec": "none", "latency_ms": 0.1},  # mis-stamped: no backend field
        {"op": "all_reduce", "world": 8, "size_mb": 1.0, "algorithm": "ring",
         "codec": "none", "latency_ms": 2.0, "backend": "ppermute"},
    ]
    path = tmp_path / "t.json"
    path.write_text(json.dumps(table))
    monkeypatch.setattr(pallas_backend, "available", lambda: True)
    selector.configure(decision_table=str(path))
    d = selector.select("all_reduce", 1_000_000, 8)
    assert d.source == "measured" and d.algorithm == "ring"
    # properly stamped pallas rows win when available...
    table[0]["backend"] = "pallas"
    path.write_text(json.dumps(table))
    selector.configure(decision_table=str(path))
    assert selector.select("all_reduce", 1_000_000, 8).algorithm == "pallas_ring"
    # ...and are invisible when the backend is not usable in this process
    monkeypatch.setattr(pallas_backend, "available", lambda: False)
    selector.configure(decision_table=str(path))
    assert selector.select("all_reduce", 1_000_000, 8).algorithm == "ring"


def test_sweep_skips_pallas_off_tpu(caplog):
    """--sweep with pallas algorithms on a CPU box: logged skip, no crash,
    no interpret-mode rows in the table; surviving rows carry backend
    stamps."""
    import logging

    from deepspeed_tpu.comm.benchmark import run_sweep

    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    lg = logging.getLogger("deepspeed_tpu")
    prev = lg.propagate
    lg.propagate = True  # the repo logger defaults propagate=False; caplog
    try:
        with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
            rows = run_sweep(ops=("all_reduce",), sizes_mb=[0.01], mesh=mesh,
                             algorithms=["lax", "ring", "pallas_ring"],
                             codecs=["none"], iters=1, warmup=1)
    finally:
        lg.propagate = prev
    assert any("skipping" in r.message and "pallas_ring" in r.message
               for r in caplog.records)
    algs = {r["algorithm"] for r in rows}
    assert algs == {"lax", "ring"}
    assert {r["backend"] for r in rows} == {"xla", "ppermute"}


def test_selector_decision_emits_telemetry_instant():
    tracer = telemetry.configure(enabled=True)
    tracer.reset()
    try:
        selector.configure(codecs=("none",))
        selector.select("all_gather", 123456, 8)
        evs = [e for e in tracer.events() if e.get("name") == "coll:select"]
        assert evs and evs[0]["args"]["op"] == "all_gather"
        assert evs[0]["args"]["algorithm"] in ALGS + ("lax",)
    finally:
        telemetry.configure(enabled=False)


# --------------------------------------------------------------- all-to-all
#
# ISSUE 15: the algorithmic library's all_to_all (ring / bidir / ring2d
# schedules, encode-once wire codecs, pallas remote-DMA hops) against the
# ``jax.lax.all_to_all(tiled=True)`` baseline.

A2A_ALGS = ("ring", "bidir", "ring2d")


def _lax_a2a(mesh, x, split=0, concat=0):
    return np.asarray(_run(
        mesh, lambda v: jax.lax.all_to_all(
            v[0], "dp", split_axis=split, concat_axis=concat, tiled=True)[None],
        x))


@pytest.mark.parametrize("alg", A2A_ALGS)
@pytest.mark.parametrize("codec", CODECS)
def test_all_to_all_matrix_vs_lax(mesh8, alg, codec):
    """Pure data movement: passthrough codecs are BIT-identical to the lax
    baseline; lossy wires quantize each destination row exactly once
    (encode-once at the source, the ring2d middle hop relays WIRE bytes),
    so the error bound is one codec roundtrip. 37 columns: the per-row
    length is not a multiple of the codec block (padding path)."""
    x = _int_payload((8, 64, 37), seed=31)

    def f(v):
        return collectives.all_to_all(v[0], "dp", split_axis=0, concat_axis=0,
                                      algorithm=alg, codec=codec,
                                      block_size=BLOCK)[None]

    out = np.asarray(_run(mesh8, f, x))
    expected = _lax_a2a(mesh8, x)
    if codec in ("none", "fp32"):
        np.testing.assert_array_equal(out, expected, err_msg=f"{alg}/{codec}")
    elif codec == "bf16":
        np.testing.assert_allclose(out, expected, rtol=0.01, atol=0.05)
    else:  # int8 / fp8: ONE quantization regardless of relay hops
        scale = np.abs(expected).max() + 1e-9
        tol = 0.01 if codec == "int8" else 0.05
        assert np.abs(out - expected).max() / scale < tol, (alg, codec)
        # own block never crosses a link: stays bit-exact on every rank
        own = np.asarray(x).reshape(8, 8, 8, 37)
        got = out.reshape(8, 8, 8, 37)
        for r in range(8):
            np.testing.assert_array_equal(got[r, r], own[r, r])


@pytest.mark.parametrize("alg", A2A_ALGS)
def test_all_to_all_split_concat_axes(mesh8, alg):
    """lax tiled semantics on distinct split/concat axes (the MoE dispatch
    shape: split experts, concat capacity — and back)."""
    x = _int_payload((8, 16, 8), seed=32)

    def f(split, concat):
        def body(v):
            return collectives.all_to_all(v[0], "dp", split_axis=split,
                                          concat_axis=concat, algorithm=alg)[None]
        return body

    out = np.asarray(_run(mesh8, f(0, 1), x))
    np.testing.assert_array_equal(out, _lax_a2a(mesh8, x, split=0, concat=1))
    out = np.asarray(_run(mesh8, f(1, 0), x))
    np.testing.assert_array_equal(out, _lax_a2a(mesh8, x, split=1, concat=0))


def test_all_to_all_non_divisible_split_raises(mesh8):
    x = jnp.ones((8, 12), jnp.float32)  # 12 % 8 != 0
    with pytest.raises(ValueError, match="not divisible"):
        _run(mesh8, lambda v: collectives.all_to_all(
            v[0], "dp", split_axis=0, concat_axis=0, algorithm="ring")[None], x)


def test_all_to_all_rejects_rhd_and_multi_axis(mesh8):
    with pytest.raises(ValueError, match="recursive-halving"):
        collectives.all_to_all(jnp.ones((8, 8)), "dp", split_axis=0,
                               concat_axis=0, algorithm="rhd")
    with pytest.raises(ValueError, match="one axis"):
        collectives.all_to_all(jnp.ones((8, 8)), ("dp", "tp"), split_axis=0,
                               concat_axis=0, algorithm="ring")
    with pytest.raises(ValueError, match="tiled"):
        dist.all_to_all(jnp.ones((8, 8)), "dp", split_axis=0, concat_axis=0,
                        tiled=False, algorithm="ring")


def test_all_to_all_ring2d_factorization(mesh8):
    """The Big-Send-off sub-ring factored schedule: 8 = 4x2, so the traced
    program carries (a-1)+(b-1) = 4 hop phases instead of ring's 7 — the
    structural evidence the 2D variant actually factors the exchange."""
    from deepspeed_tpu.utils.compat import shard_map as smap

    x = jnp.ones((8, 64), jnp.float32)

    def traced(alg):
        def body(v):
            return collectives.all_to_all(v[0], "dp", split_axis=0,
                                          concat_axis=0, algorithm=alg)[None]
        return jax.make_jaxpr(smap(body, mesh=mesh8, in_specs=P("dp"),
                                   out_specs=P("dp"), check_vma=False))(x)

    ring = _count_primitives(traced("ring").jaxpr)
    two_d = _count_primitives(traced("ring2d").jaxpr)
    assert ring.get("ppermute", 0) == 7
    assert two_d.get("ppermute", 0) == 4  # (4-1) + (2-1)
    # bidir pairs mirror distances: ceil(7/2) = 4 phases, two sends in all
    # but the middle phase -> still 7 row moves
    bidir = _count_primitives(traced("bidir").jaxpr)
    assert bidir.get("ppermute", 0) == 7


def test_all_to_all_pallas_census(mesh8):
    """Acceptance (ISSUE 15): the fused pallas dispatch wire runs ONE
    pallas program per hop — n-1 pallas_calls, ZERO ppermutes — where the
    unfused int8 ring permutes wire values + scales around XLA codec math."""
    from deepspeed_tpu.utils.compat import shard_map as smap

    x = jnp.ones((8, 96), jnp.float32)

    def traced(alg, codec):
        def body(v):
            return collectives.all_to_all(v[0], "dp", split_axis=0,
                                          concat_axis=0, algorithm=alg,
                                          codec=codec, block_size=32)[None]
        return jax.make_jaxpr(smap(body, mesh=mesh8, in_specs=P("dp"),
                                   out_specs=P("dp"), check_vma=False))(x)

    fused = _count_primitives(traced("pallas_ring", "int8").jaxpr)
    assert fused.get("pallas_call", 0) == 7  # n-1 hops, one program each
    assert fused.get("ppermute", 0) == 0
    unfused = _count_primitives(traced("ring", "int8").jaxpr)
    assert unfused.get("pallas_call", 0) == 0
    assert unfused.get("ppermute", 0) == 2 * 7  # q + scales per hop
    exact = _count_primitives(traced("pallas_ring", "none").jaxpr)
    assert exact.get("pallas_call", 0) == 7  # exact wire still remote-DMA
    assert exact.get("ppermute", 0) == 0


@pytest.mark.parametrize("alg,codec", [("pallas_ring", "int8"),
                                       ("pallas_ring", "fp8"),
                                       ("pallas_ring2d", "int8")])
def test_all_to_all_pallas_matches_unfused(mesh8, alg, codec):
    """Interpret-mode equivalence: the fused requantize->DMA->dequant hop
    must track the unfused encode-once wire (same ops.quant block math) —
    and the exact pallas wire must be BIT-identical to lax."""
    x = (jax.random.normal(jax.random.PRNGKey(33), (8, 96)) * 3).astype(jnp.float32)

    def f(a, c):
        return lambda v: collectives.all_to_all(
            v[0], "dp", split_axis=0, concat_axis=0, algorithm=a, codec=c,
            block_size=32)[None]

    fused = np.asarray(_run(mesh8, f(alg, codec), x))
    base = "ring" if alg == "pallas_ring" else "ring2d"
    unfused = np.asarray(_run(mesh8, f(base, codec), x))
    exact = _lax_a2a(mesh8, x)
    scale = np.abs(exact).max() + 1e-9
    tol = 0.02 if codec == "int8" else 0.06  # fp8 E4M3: 3 mantissa bits
    assert np.abs(fused - exact).max() / scale < tol, (alg, codec)
    assert np.abs(fused - unfused).max() / scale < tol / 2, (alg, codec)
    got = np.asarray(_run(mesh8, f("pallas_ring", "none"), x))
    np.testing.assert_array_equal(got, exact)


def test_all_to_all_facade_routing_with_hop_spans(mesh8, tmp_path):
    """Acceptance (ISSUE 15): comm.all_to_all(algorithm='ring',
    codec='int8') routes through the collectives layer with the facade span
    tagged, per-hop coll: spans, and an observatory route signature."""
    from deepspeed_tpu.collectives import observatory as coll_obs

    tracer = telemetry.configure(enabled=True)
    tracer.reset()
    obs = coll_obs.configure(enabled=True, persist=False, refit_every=0,
                             async_compile=False)
    try:
        x = _int_payload((8, 8, 64), seed=34)
        out = _run(mesh8, lambda v: dist.all_to_all(
            v[0], "dp", split_axis=0, concat_axis=0, algorithm="ring",
            codec="int8", block_size=32)[None], x)
        expected = _lax_a2a(mesh8, x)
        scale = np.abs(expected).max() + 1e-9
        assert np.abs(np.asarray(out) - expected).max() / scale < 0.02
        names = [e.get("name") for e in tracer.events()]
        facade = next(e for e in tracer.events()
                      if e.get("name") == "comm:all_to_all")
        assert facade["args"]["algorithm"] == "ring"
        assert facade["args"]["codec"] == "int8"
        assert any(n == "coll:all_to_all:ring" for n in names), names
        routes = obs.routes()
        sig = next(r for r in routes if r.op == "all_to_all")
        assert (sig.algorithm, sig.codec, sig.backend) == ("ring", "int8",
                                                           "ppermute")
        assert sig.hops == 7 and sig.wire_bytes > 0  # n-1 hop census
    finally:
        coll_obs.configure(enabled=False)
        telemetry.configure(enabled=False)


def test_all_to_all_selector_and_measured_routing(tmp_path):
    """Selector coverage for the new op: the model never proposes rhd (no
    recursive-halving form), repeated queries hit the decision cache, and a
    measured decision-table row routes an auto call onto its algorithm."""
    selector.configure(codecs=("none", "int8"))
    d1 = selector.select("all_to_all", 1 << 20, 8)
    assert d1.algorithm != "rhd"
    d2 = selector.select("all_to_all", 1 << 20, 8)
    assert d1 is d2 and selector.cache_info()["hits"] >= 1
    # measured mode: a table row for all_to_all wins over the model
    table = [{"op": "all_to_all", "world": 8, "size_mb": 1.0,
              "algorithm": "ring2d", "codec": "int8", "latency_ms": 0.4},
             {"op": "all_to_all", "world": 8, "size_mb": 1.0,
              "algorithm": "ring", "codec": "none", "latency_ms": 2.0}]
    path = tmp_path / "a2a.json"
    path.write_text(json.dumps(table))
    selector.configure(decision_table=str(path), codecs=("none", "int8"))
    d = selector.select("all_to_all", 1_000_000, 8)
    assert d.source == "measured" and d.algorithm == "ring2d" and d.codec == "int8"


def test_all_to_all_candidate_pairs_exclude_rhd():
    """The sweep/probe enumeration (ONE function, shared) never proposes
    rhd for all_to_all, on any world size."""
    from deepspeed_tpu.comm.benchmark import candidate_pairs

    pairs = candidate_pairs(8, ("none", "int8"), op="all_to_all")
    assert pairs and all(alg != "rhd" for alg, _ in pairs)
    assert ("ring", "int8") in pairs and ("lax", "none") in pairs
    # other ops keep rhd on pow2 worlds (no behavior change)
    assert any(alg == "rhd" for alg, _ in candidate_pairs(8, ("none",)))


def test_all_to_all_sweep_feeds_selector(tmp_path):
    """--sweep covers all_to_all end-to-end: backend-stamped rows the
    measured mode consumes."""
    from deepspeed_tpu.comm.benchmark import run_sweep

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("dp",))
    rows = run_sweep(ops=("all_to_all",), sizes_mb=[0.01], mesh=mesh,
                     algorithms=["lax", "ring"], codecs=["none"],
                     iters=2, warmup=1)
    assert {r["algorithm"] for r in rows} == {"lax", "ring"}
    assert all(r["op"] == "all_to_all" and r["latency_ms"] > 0 for r in rows)
    assert {r["backend"] for r in rows} == {"xla", "ppermute"}
    path = tmp_path / "sweep.json"
    path.write_text(json.dumps(rows))
    selector.configure(decision_table=str(path))
    d = selector.select("all_to_all", 10_000, 4)
    assert d.source == "measured"


def test_qgz_exchange_wire_stays_on_lax(mesh8):
    """The zeropp qgZ destination-shard exchange moves an ALREADY-ENCODED
    wire — a facade default must never route it back through the
    algorithmic/codec path (double quantization)."""
    from deepspeed_tpu.parallel.quant_collectives import exchange_wire

    selector.configure(facade_algorithm="ring", facade_codec="int8")
    tracer = telemetry.configure(enabled=True)
    tracer.reset()
    try:
        x = _int_payload((8, 64), seed=35)
        out = _run(mesh8, lambda v: exchange_wire(v[0], "dp")[None], x)
        np.testing.assert_array_equal(np.asarray(out), _lax_a2a(mesh8, x))
        facade = next(e for e in tracer.events()
                      if e.get("name") == "comm:all_to_all")
        assert "algorithm" not in facade.get("args", {})
    finally:
        telemetry.configure(enabled=False)


def test_all_to_all_facade_default_rhd_falls_back_to_lax(mesh8):
    """A configured facade default the op has NO form of (rhd) must keep
    default-routed all_to_all on the lax lowering — only an explicit rhd
    request surfaces the library's error."""
    selector.configure(facade_algorithm="rhd", facade_codec="int8")
    x = _int_payload((8, 64), seed=36)
    out = np.asarray(_run(
        mesh8, lambda v: dist.all_to_all(v[0], "dp", split_axis=0,
                                         concat_axis=0)[None], x))
    np.testing.assert_array_equal(out, _lax_a2a(mesh8, x))
