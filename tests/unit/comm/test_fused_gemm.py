"""Fused matmul<->collective Pallas kernels (ISSUE 19, T3-style).

Acceptance pins, all under the Pallas interpreter on the forced CPU mesh
(the same no-hardware equivalence story as the PR-8 hop kernels):

- ``all_gather_matmul`` (accumulate and ``out_block`` modes) and
  ``matmul_reduce_scatter`` are BIT-identical to the plain
  gather-then-dot / dot-then-scatter composition on exact wires
  (integer-valued payloads), and bounded on int8 wires;
- the jaxpr census shows the fusion is real: n-1 ``pallas_call`` hops and
  ZERO standalone collective primitives between the matmuls;
- config-off is jaxpr-clean (zero ``pallas_call``) and numerically
  identical — the knob cannot change results, only the schedule;
- ``zeropp.sharded_matmul``'s custom_vjp produces fused gradients that
  match the unfused composition bit-exactly, and a multi-step ZeRO-3
  SGD loop keeps its loss trajectory within tolerance of unfused.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.collectives import fused_gemm
from deepspeed_tpu.parallel import zeropp
from deepspeed_tpu.utils.compat import shard_map

N_DEV = 4
M, KS, N = 6, 8, 16
K = N_DEV * KS


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:N_DEV]), ("tp",))


@pytest.fixture(autouse=True)
def _fused_off():
    fused_gemm.configure(enabled=False)
    yield
    fused_gemm.configure(enabled=False)


def _run(mesh, f, *args, in_specs, out_specs):
    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False))(*args)


def _ints(rng, shape):
    return jnp.asarray(rng.integers(-4, 4, size=shape).astype(np.float32))


def test_all_gather_matmul_exact_bit_identity(mesh):
    rng = np.random.default_rng(0)
    x, w = _ints(rng, (M, K)), _ints(rng, (K, N))
    got = _run(mesh, lambda xv, wv: fused_gemm.all_gather_matmul(
        xv, wv, "tp", fused=True), x, w,
        in_specs=(P(), P("tp")), out_specs=P())
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(x) @ np.asarray(w))


def test_all_gather_matmul_out_block_bit_identity(mesh):
    # the backward-dx shape: g [M,N] @ W^T -> [M,K]
    rng = np.random.default_rng(1)
    g, w = _ints(rng, (M, N)), _ints(rng, (K, N))
    got = _run(mesh, lambda gv, wv: fused_gemm.all_gather_matmul(
        gv, wv, "tp", out_block=True, fused=True), g, w,
        in_specs=(P(), P("tp")), out_specs=P())
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(g) @ np.asarray(w).T)


def test_matmul_reduce_scatter_exact_bit_identity(mesh):
    rng = np.random.default_rng(2)
    a, w = _ints(rng, (8, K)), _ints(rng, (K, N))
    got = _run(mesh, lambda av, wv: fused_gemm.matmul_reduce_scatter(
        av, wv, "tp", fused=True), a, w,
        in_specs=(P(None, "tp"), P("tp")), out_specs=P("tp"))
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(a) @ np.asarray(w))


@pytest.mark.nightly
def test_int8_wire_bounded(mesh):
    rng = np.random.default_rng(3)
    xf = jnp.asarray(rng.normal(size=(M, K)).astype(np.float32))
    wf = jnp.asarray(rng.normal(size=(K, N)).astype(np.float32))
    af = jnp.asarray(rng.normal(size=(8, K)).astype(np.float32))
    got = _run(mesh, lambda xv, wv: fused_gemm.all_gather_matmul(
        xv, wv, "tp", codec="int8", block_size=64, fused=True), xf, wf,
        in_specs=(P(), P("tp")), out_specs=P())
    want = np.asarray(xf) @ np.asarray(wf)
    rel = np.abs(np.asarray(got) - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 2e-2, rel
    got = _run(mesh, lambda av, wv: fused_gemm.matmul_reduce_scatter(
        av, wv, "tp", codec="int8", block_size=64, fused=True), af, wf,
        in_specs=(P(None, "tp"), P("tp")), out_specs=P("tp"))
    want = np.asarray(af) @ np.asarray(wf)
    rel = np.abs(np.asarray(got) - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 2e-2, rel


def test_jaxpr_census_fused_and_config_off(mesh):
    rng = np.random.default_rng(4)
    x, w = _ints(rng, (M, K)), _ints(rng, (K, N))
    fn = shard_map(lambda xv, wv: fused_gemm.all_gather_matmul(
        xv, wv, "tp", fused=True), mesh=mesh,
        in_specs=(P(), P("tp")), out_specs=P(), check_vma=False)
    jx = str(jax.make_jaxpr(fn)(x, w))
    # the fusion is real: one pallas hop per ring step, no standalone
    # collective primitive anywhere between the matmuls
    assert jx.count("pallas_call") == N_DEV - 1
    for prim in ("all_gather", "psum", "ppermute", "all_reduce"):
        assert f" {prim}" not in jx and f"{prim}[" not in jx, prim
    # config-off: plain lax composition, zero pallas, identical numbers
    fn_off = shard_map(lambda xv, wv: fused_gemm.all_gather_matmul(
        xv, wv, "tp", fused=False), mesh=mesh,
        in_specs=(P(), P("tp")), out_specs=P(), check_vma=False)
    assert "pallas_call" not in str(jax.make_jaxpr(fn_off)(x, w))
    np.testing.assert_array_equal(np.asarray(jax.jit(fn_off)(x, w)),
                                  np.asarray(x) @ np.asarray(w))
    np.testing.assert_array_equal(np.asarray(jax.jit(fn)(x, w)),
                                  np.asarray(jax.jit(fn_off)(x, w)))


def test_knob_routes_default_path(mesh):
    # fused=None consults configure(); enabled -> pallas hops appear.
    # NOTE: build the shard_map wrapper AFTER flipping the knob — jax
    # caches the traced body by callable identity + avals.
    rng = np.random.default_rng(5)
    x, w = _ints(rng, (M, K)), _ints(rng, (K, N))

    def make():
        return shard_map(lambda xv, wv: fused_gemm.all_gather_matmul(
            xv, wv, "tp"), mesh=mesh,
            in_specs=(P(), P("tp")), out_specs=P(), check_vma=False)

    assert "pallas_call" not in str(jax.make_jaxpr(make())(x, w))
    fused_gemm.configure(enabled=True)
    try:
        assert "pallas_call" in str(jax.make_jaxpr(make())(x, w))
    finally:
        fused_gemm.configure(enabled=False)


@pytest.mark.nightly
def test_sharded_matmul_grads_fused_matches_unfused(mesh):
    rng = np.random.default_rng(6)
    x, w = _ints(rng, (M, K)), _ints(rng, (K, N))
    t = _ints(rng, (M, N))

    def loss(xv, wv):
        y = zeropp.sharded_matmul(xv, wv, "tp", False, 64)
        return jnp.sum((y - t) * (y - t))

    grads = {}
    for fused in (False, True):
        fused_gemm.configure(enabled=fused)
        f = shard_map(jax.grad(loss, argnums=(0, 1)), mesh=mesh,
                      in_specs=(P(), P("tp")), out_specs=(P(), P("tp")),
                      check_vma=False)
        grads[fused] = jax.jit(f)(x, w)
    np.testing.assert_array_equal(np.asarray(grads[True][0]),
                                  np.asarray(grads[False][0]))
    np.testing.assert_array_equal(np.asarray(grads[True][1]),
                                  np.asarray(grads[False][1]))


@pytest.mark.nightly
def test_zero3_sgd_trajectory_fused_tracks_unfused(mesh):
    # batch-sharded x, parameter-sharded w: the fused forward gathers w on
    # the fly, the fused backward reduce-scatters dw to each rank's shard
    steps, lr, rtol = 6, 1e-3, 1e-4
    mb = 4
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(N_DEV * mb, K)).astype(np.float32))
    w0 = jnp.asarray(rng.normal(size=(K, N)).astype(np.float32) * 0.1)
    t = jnp.asarray(rng.normal(size=(N_DEV * mb, N)).astype(np.float32))

    def sgd_step(xv, wv, tv):
        def loss(a, b):
            y = zeropp.sharded_matmul(a, b, "tp", False, 64)
            return jnp.sum((y - tv) * (y - tv))

        lval, dw = jax.value_and_grad(loss, argnums=1)(xv, wv)
        return wv - lr * dw, jnp.reshape(lval, (1,))

    def trajectory(fused):
        fused_gemm.configure(enabled=fused)
        f = jax.jit(shard_map(
            sgd_step, mesh=mesh, in_specs=(P("tp"), P("tp"), P("tp")),
            out_specs=(P("tp"), P("tp")), check_vma=False))
        w, losses = w0, []
        for _ in range(steps):
            w, lv = f(x, w, t)
            losses.append(float(np.asarray(lv).sum()))
        return np.asarray(losses), np.asarray(w)

    l_off, w_off = trajectory(False)
    l_on, w_on = trajectory(True)
    assert l_off[-1] < l_off[0]  # it actually trains
    rel = np.abs(l_on - l_off) / (np.abs(l_off) + 1e-12)
    assert rel.max() < rtol, rel
    w_rel = np.abs(w_on - w_off).max() / (np.abs(w_off).max() + 1e-12)
    assert w_rel < rtol, w_rel
