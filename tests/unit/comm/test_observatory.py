"""Collective performance observatory (collectives/observatory.py).

ISSUE 11 acceptance, pinned here:
  - timing-mode probe sampling (1-in-N cadence) feeds the labelled
    ``coll/hop_ms`` / ``coll/achieved_gbps`` metrics with the full label set
  - online-table round trip: a timed run persists a versioned table that a
    FRESH selector's measured mode consumes, and a decision FLIPS vs the
    model pick
  - alpha/beta refit converges on synthetic samples and lands in the
    selector (``calibrate``), changing model-mode estimates
  - drift detection fires on an injected slow hop: LOUD warning,
    ``coll:drift`` trace instant, profiler-capture arm
  - timing-mode-off (and -on!) hop programs are jaxpr-identical to today's:
    probes are separate dispatches, never ops in the traced program
  - table schema versioning: envelope + legacy list load, mismatch rejected
    with a warning, ``--merge`` fold semantics
"""

import json
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu.comm as dist
from deepspeed_tpu import telemetry
from deepspeed_tpu.collectives import observatory, selector
from deepspeed_tpu.collectives import table as table_mod
from deepspeed_tpu.utils.compat import shard_map

BLOCK = 64


@pytest.fixture
def mesh8():
    devs = jax.devices()[:8]
    return Mesh(np.array(devs), ("dp",))


@pytest.fixture(autouse=True)
def _reset():
    selector.configure()
    observatory.configure(enabled=False)
    yield
    selector.configure()
    observatory.configure(enabled=False)
    telemetry.configure(enabled=False)


@pytest.fixture
def dslog():
    """Route the repo logger into caplog (it defaults propagate=False)."""
    lg = logging.getLogger("deepspeed_tpu")
    prev = lg.propagate
    lg.propagate = True
    yield lg
    lg.propagate = prev


def _route_ring_int8(mesh):
    """Trace one ROUTED facade collective (registers a signature + census)."""

    def f(v):
        return dist.all_reduce(v, "dp", algorithm="ring", codec="int8",
                               block_size=BLOCK)

    x = jnp.ones((8, 4096), jnp.float32)
    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                            check_vma=False))(x)
    out.block_until_ready()
    return out


# ------------------------------------------------------------ probe sampling


def test_probe_sampling_cadence_and_labels(mesh8):
    telemetry.configure(enabled=True)
    tracer = telemetry.get_tracer()
    tracer.reset()
    obs = observatory.configure(enabled=True, sample_every=2, persist=False,
                                probe_alternatives=False, refit_every=0,
                                async_compile=False)
    obs.install(mesh=mesh8)
    _route_ring_int8(mesh8)
    routes = obs.routes()
    assert len(routes) == 1
    r = routes[0]
    assert (r.op, r.algorithm, r.codec, r.backend) == (
        "all_reduce", "ring", "int8", "ppermute")
    # trace-time hop census: ring all-reduce on 8 ranks = 7 RS + 7 AG hops
    assert r.hops == 14
    assert r.wire_bytes > 0

    ran = [obs.on_step(s) for s in (1, 2, 3, 4)]
    # 1-in-2 cadence: steps 2 and 4 sample, 1 and 3 leave steady state alone
    assert ran == [0, 1, 0, 1]

    reg = tracer.registry
    from deepspeed_tpu.collectives.selector import _bytes_bucket

    labels = dict(op="all_reduce", algorithm="ring", codec="int8",
                  backend="ppermute", bucket=_bytes_bucket(r.nbytes), world=8)
    h = reg.peek_histogram("coll/hop_ms", **labels)
    assert h is not None and h.count == 2
    snap = reg.snapshot()
    gkeys = [k for k in snap if k.startswith("coll/achieved_gbps{")]
    assert gkeys and all(
        f'algorithm="ring"' in k and f'world="8"' in k for k in gkeys)
    assert snap["coll/probes"] == 2


def test_async_compile_warms_off_the_step_then_times(mesh8):
    """Production mode: a sampled step never pays a probe compile — the
    cold program is warmed on the background worker and a LATER sampled
    step times it."""
    import time

    obs = observatory.configure(enabled=True, sample_every=1, persist=False,
                                probe_alternatives=False, refit_every=0,
                                async_compile=True)
    obs.install(mesh=mesh8)
    _route_ring_int8(mesh8)
    assert obs.on_step(1) == 0  # cold: scheduled for background warm, not timed
    deadline = time.time() + 60
    while time.time() < deadline:
        entries = list(obs._probe_cache.values())
        if entries and entries[0][1] == "warm":
            break
        time.sleep(0.05)
    else:
        pytest.fail("background warm never completed")
    ran = 0
    for s in range(2, 6):  # the queue re-arms; the warm program gets timed
        ran += obs.on_step(s)
        if ran:
            break
    assert ran == 1
    assert obs.summary()["merged_samples"] == 1


def test_disabled_observatory_is_inert(mesh8):
    _route_ring_int8(mesh8)
    obs = observatory.get_observatory()
    assert obs.routes() == []
    assert obs.on_step(1) == 0


# -------------------------------------------------------- table round trip


def test_online_table_roundtrip_flips_decision(tmp_path):
    """A persisted online table changes a selector decision: the model pick
    for an exact-wire 1 MB all-reduce is the native lax baseline; observed
    rows showing ring beating it flip the fresh process's measured pick."""
    nbytes, world = 1 << 20, 8
    d0 = selector.select("all_reduce", nbytes, world)
    assert (d0.source, d0.algorithm) == ("model", "lax")

    obs = observatory.configure(enabled=True, persist=True,
                                table_path=str(tmp_path / "coll_table.json"),
                                refit_every=0)
    size_mb = nbytes / 1e6
    obs.record_sample(op="all_reduce", algorithm="ring", codec="none",
                      backend="ppermute", world=world, size_mb=size_mb,
                      latency_ms=0.2, itemsize=4)
    obs.record_sample(op="all_reduce", algorithm="lax", codec="none",
                      backend="xla", world=world, size_mb=size_mb,
                      latency_ms=5.0, itemsize=4)
    path = obs.persist()
    assert path and json.loads(open(path).read())["schema"] == table_mod.SCHEMA_VERSION

    # a FRESH selector (new process analog) warm-starts measured mode from
    # the persisted table — and the decision flips lax -> ring
    selector.configure(decision_table=path)
    d1 = selector.select("all_reduce", nbytes, world)
    assert (d1.source, d1.algorithm) == ("measured", "ring")


def test_real_probe_run_persists_consumable_table(mesh8, tmp_path):
    """End-to-end: real timed probes -> persisted envelope -> fresh
    measured-mode selector answers from it."""
    obs = observatory.configure(enabled=True, sample_every=1, persist=True,
                                table_path=str(tmp_path / "t.json"),
                                probe_alternatives=False, refit_every=0,
                                async_compile=False)
    obs.install(mesh=mesh8)
    _route_ring_int8(mesh8)
    assert obs.on_step(1) == 1
    rows = table_mod.load_table(str(tmp_path / "t.json"))
    assert rows and rows[0]["algorithm"] == "ring" and rows[0]["codec"] == "int8"
    assert rows[0]["backend"] == "ppermute" and rows[0]["latency_ms"] > 0
    selector.configure(decision_table=str(tmp_path / "t.json"), mode="measured",
                       codecs=("int8",), min_quant_bytes=0)
    d = selector.select("all_reduce", int(rows[0]["size_mb"] * 1e6), 8)
    assert d.source == "measured"


def test_ema_merge_damps_single_noisy_probe(tmp_path):
    obs = observatory.configure(enabled=True, persist=False, ema=0.25,
                                refit_every=0)
    kw = dict(op="all_reduce", algorithm="ring", codec="none",
              backend="ppermute", world=8, size_mb=1.0, itemsize=4)
    obs.record_sample(latency_ms=1.0, **kw)
    obs.record_sample(latency_ms=9.0, **kw)  # noisy outlier
    rows = obs.table_rows()
    assert len(rows) == 1
    # (1-0.25)*1.0 + 0.25*9.0 = 3.0 — one outlier cannot 9x the row
    assert rows[0]["latency_ms"] == pytest.approx(3.0, rel=1e-6)
    assert rows[0]["samples"] == 2


# ----------------------------------------------------------- schema version


def test_table_schema_envelope_and_legacy(tmp_path, caplog, dslog):
    rows = [{"op": "all_reduce", "world": 8, "size_mb": 1.0,
             "algorithm": "ring", "codec": "none", "backend": "ppermute",
             "latency_ms": 0.5}]
    p = tmp_path / "t.json"
    table_mod.write_table(str(p), rows, source="sweep")
    assert table_mod.load_table(str(p)) == [dict(rows[0])]
    # legacy bare-list files (PR-3 sweeps) still load
    legacy = tmp_path / "legacy.json"
    legacy.write_text(json.dumps(rows))
    assert table_mod.load_table(str(legacy)) == rows
    # ... and so does the schema-LESS dict shape the selector used to accept
    legacy2 = tmp_path / "legacy2.json"
    legacy2.write_text(json.dumps({"rows": rows}))
    assert table_mod.load_table(str(legacy2)) == rows
    # a FUTURE schema is rejected with a warning, not mis-parsed
    future = tmp_path / "future.json"
    future.write_text(json.dumps({"schema": 99, "rows": rows}))
    with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
        assert table_mod.load_table(str(future)) == []
    assert any("schema" in r.message for r in caplog.records)
    # and the selector treats that rejection as "no table" (model fallback)
    selector.configure(decision_table=str(future), mode="measured")
    assert selector.select("all_reduce", 1 << 20, 8).source == "model"


def test_merge_rows_semantics():
    base = [{"op": "all_reduce", "world": 8, "size_mb": 1.0,
             "algorithm": "ring", "codec": "none", "backend": "ppermute",
             "latency_ms": 4.0, "samples": 3},
            {"op": "all_gather", "world": 8, "size_mb": 2.0,
             "algorithm": "rhd", "codec": "none", "backend": "ppermute",
             "latency_ms": 1.0, "samples": 1}]
    fresh = [{"op": "all_reduce", "world": 8, "size_mb": 1.0,
              "algorithm": "ring", "codec": "none", "backend": "ppermute",
              "latency_ms": 2.0, "samples": 1}]
    # --merge (ema=None): the fresh sweep REPLACES the matching row's
    # numbers, uncovered rows survive
    out = {table_mod.row_key(r): r for r in table_mod.merge_rows(base, fresh)}
    assert out[table_mod.row_key(fresh[0])]["latency_ms"] == 2.0
    assert out[table_mod.row_key(fresh[0])]["samples"] == 4
    assert table_mod.row_key(base[1]) in out


def test_merge_replaces_legacy_unstamped_rows():
    """A legacy (pre-backend-stamp) row's merge identity defaults its
    backend from the algorithm name, so a fresh stamped measurement
    REPLACES it instead of leaving a stale duplicate that min-latency
    measured picks could route from forever."""
    legacy = [{"op": "all_reduce", "world": 8, "size_mb": 1.0,
               "algorithm": "ring", "codec": "int8", "latency_ms": 0.1}]
    fresh = [{"op": "all_reduce", "world": 8, "size_mb": 1.0,
              "algorithm": "ring", "codec": "int8", "backend": "ppermute",
              "latency_ms": 2.0, "samples": 1}]
    out = table_mod.merge_rows(legacy, fresh)
    assert len(out) == 1
    assert out[0]["latency_ms"] == 2.0
    # but DIFFERENT element widths at the same byte size are different
    # programs (a lossy wire costs per element) — they must not merge
    fp32 = [dict(fresh[0], itemsize=4)]
    assert len(table_mod.merge_rows(fresh, fp32)) == 2


def test_configure_drops_previous_engine_install(mesh8):
    """Reconfiguring (the next engine's hygiene) must drop the previous
    engine's mesh and profiler-arm callable — a drift event must never arm
    a torn-down engine's diagnostics."""
    obs = observatory.configure(enabled=True, persist=False)
    obs.install(mesh=mesh8, profiler_arm=lambda reason=None: None)
    assert obs._mesh is not None and obs.profiler_arm is not None
    obs = observatory.configure(enabled=False)
    assert obs._mesh is None and obs.profiler_arm is None


def test_sweep_cli_writes_envelope_and_merges(mesh8, tmp_path):
    from deepspeed_tpu.comm import benchmark

    out = tmp_path / "sweep.json"
    rc = benchmark.main(["--sweep", "--op", "all_reduce", "--sizes-mb", "0.01",
                         "--iters", "1", "--algorithms", "lax,ring",
                         "--output", str(out)])
    assert rc == 0
    payload = json.loads(out.read_text())
    assert payload["schema"] == table_mod.SCHEMA_VERSION
    assert payload["source"] == "sweep"
    assert {r["algorithm"] for r in payload["rows"]} == {"lax", "ring"}
    assert all("itemsize" in r and "backend" in r for r in payload["rows"])
    # --merge folds a second sweep into the table, keeping uncovered rows
    extra = {"op": "all_gather", "world": 8, "size_mb": 9.0,
             "algorithm": "rhd", "codec": "none", "backend": "ppermute",
             "latency_ms": 1.0}
    table_mod.write_table(str(out), payload["rows"] + [extra], source="online")
    rc = benchmark.main(["--sweep", "--op", "all_reduce", "--sizes-mb", "0.01",
                         "--iters", "1", "--algorithms", "lax",
                         "--merge", str(out)])
    assert rc == 0
    merged = json.loads(out.read_text())
    assert merged["source"] == "merged"
    algs = {(r["op"], r["algorithm"]) for r in merged["rows"]}
    assert ("all_gather", "rhd") in algs and ("all_reduce", "ring") in algs


def test_measured_pick_prefers_matching_itemsize(tmp_path):
    """A mixed-itemsize table answers each query from rows measured at the
    querying payload's element width: the bf16 rows (where int8 is only 2x
    wire compression) must not decide an fp32 payload's routing (4x)."""
    rows = [
        {"op": "all_reduce", "world": 8, "size_mb": 1.0, "algorithm": "ring",
         "codec": "int8", "backend": "ppermute", "latency_ms": 9.0,
         "itemsize": 2},
        {"op": "all_reduce", "world": 8, "size_mb": 1.0, "algorithm": "rhd",
         "codec": "int8", "backend": "ppermute", "latency_ms": 8.0,
         "itemsize": 2},
        {"op": "all_reduce", "world": 8, "size_mb": 1.0, "algorithm": "ring",
         "codec": "int8", "backend": "ppermute", "latency_ms": 1.0,
         "itemsize": 4},
    ]
    p = tmp_path / "mixed.json"
    table_mod.write_table(str(p), rows)
    selector.configure(decision_table=str(p), mode="measured",
                       codecs=("int8",), min_quant_bytes=0)
    d4 = selector.select("all_reduce", 1_000_000, 8, itemsize=4)
    assert (d4.algorithm, d4.est_us) == ("ring", 1000.0)
    d2 = selector.select("all_reduce", 1_000_000, 8, itemsize=2)
    assert d2.algorithm == "rhd"  # the bf16 rows' own winner


def test_merge_cli_never_clobbers_unreadable_base(mesh8, tmp_path, dslog,
                                                  caplog):
    """--sweep --merge onto a version-mismatched base leaves the base file
    untouched and lands the fresh sweep next to it."""
    from deepspeed_tpu.comm import benchmark

    base = tmp_path / "future.json"
    base.write_text(json.dumps({"schema": 99, "rows": [{"op": "all_reduce"}]}))
    before = base.read_text()
    with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
        rc = benchmark.main(["--sweep", "--op", "all_reduce", "--sizes-mb",
                             "0.01", "--iters", "1", "--algorithms", "lax",
                             "--merge", str(base)])
    assert rc == 0
    assert base.read_text() == before  # the mismatched table survives
    side = tmp_path / "future.json.sweep.json"
    assert side.exists()
    assert json.loads(side.read_text())["rows"]


# ------------------------------------------------------------ alpha/beta fit


def test_alpha_beta_refit_converges_on_synthetic_samples():
    """Samples generated FROM the model at known constants refit back to
    them, and the calibration lands in the selector's estimates."""
    alpha, beta = 5.0, 20.0  # us/hop, us/MB
    obs = observatory.configure(enabled=True, persist=False, refit_every=0)
    for op, alg, size_mb in [("all_reduce", "ring", 0.5),
                             ("all_reduce", "rhd", 2.0),
                             ("all_gather", "ring", 1.0),
                             ("reduce_scatter", "bidir", 4.0),
                             ("all_reduce", "ring2d", 8.0)]:
        hops, wire_mb = observatory.model_terms(
            op, alg, "none", int(size_mb * 1e6), 8, 4)
        obs.record_sample(op=op, algorithm=alg, codec="none",
                          backend="ppermute", world=8, size_mb=size_mb,
                          latency_ms=(hops * alpha + wire_mb * beta) / 1e3,
                          itemsize=4)
    fitted = obs.refit()
    a, b = fitted["ppermute"]
    assert a == pytest.approx(alpha, rel=0.05)
    assert b == pytest.approx(beta, rel=0.05)
    # the selector now costs from the calibrated constants
    assert selector.get_config().backend_ab["ppermute"] == (a, b)
    est = selector.estimate_us("all_reduce", "ring", "none", 1 << 20, 8)
    hops, wire_mb = observatory.model_terms("all_reduce", "ring", "none",
                                            1 << 20, 8, 4)
    assert est == pytest.approx(hops * a + wire_mb * b, rel=1e-6)


def test_refit_decay_tracks_regime_change():
    """With forgetting on, a slowdown shows in the calibrated constants
    after a handful of refits instead of being averaged into history."""
    obs = observatory.configure(enabled=True, persist=False, refit_every=0,
                                fit_decay=0.5)

    def feed(alpha, n):
        for _ in range(n):
            hops, wire_mb = observatory.model_terms(
                "all_reduce", "ring", "none", 1 << 20, 8, 4)
            obs.record_sample(op="all_reduce", algorithm="ring", codec="none",
                              backend="ppermute", world=8, size_mb=1.0,
                              latency_ms=hops * alpha / 1e3, itemsize=4)

    feed(5.0, 8)
    obs.refit()
    assert obs.calibration["ppermute"][0] == pytest.approx(5.0, rel=0.05)
    for _ in range(6):  # regime change: 10x slower hops
        feed(50.0, 4)
        obs.refit()
    assert obs.calibration["ppermute"][0] == pytest.approx(50.0, rel=0.15)


def test_refit_fires_on_cadence(mesh8):
    obs = observatory.configure(enabled=True, sample_every=1, persist=False,
                                refit_every=2, probe_alternatives=False,
                                async_compile=False)
    obs.install(mesh=mesh8)
    _route_ring_int8(mesh8)
    for s in range(1, 5):
        obs.on_step(s)
    assert "ppermute" in obs.calibration
    assert selector.get_config().backend_ab.get("ppermute") is not None


# ------------------------------------------------------------------- drift


def test_drift_warns_arms_profiler_and_traces(mesh8, tmp_path, caplog, dslog):
    telemetry.configure(enabled=True)
    telemetry.get_tracer().reset()
    obs = observatory.configure(enabled=True, sample_every=1, persist=False,
                                refit_every=2, drift_ratio=3.0,
                                probe_alternatives=False, async_compile=False)
    obs.install(mesh=mesh8)
    _route_ring_int8(mesh8)
    for s in range(1, 4):  # calibrate first (drift needs a trusted model)
        obs.on_step(s)
    assert "ppermute" in obs.calibration

    armed = []
    obs.profiler_arm = lambda reason=None: armed.append(reason)
    obs._timer = lambda f, x, iters, warmup: 5.0  # injected slow hop: 5 s
    with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
        for s in range(4, 10):
            obs.on_step(s)
            if obs.drift_events:
                break
    assert obs.drift_events >= 1
    assert any("COLLECTIVE DRIFT" in r.message for r in caplog.records)
    assert armed and armed[0].startswith("coll_drift:")
    instants = [e for e in telemetry.get_tracer().events()
                if e.get("name") == "coll:drift"]
    assert instants and instants[0]["args"]["ratio"] > 3.0
    reg = telemetry.get_tracer().registry
    ratios = [k for k in reg.gauges() if k.startswith("coll/model_ratio{")]
    assert ratios


def test_no_drift_alarm_against_uncalibrated_model(mesh8, caplog, dslog):
    """The hand-set alpha/beta constants are NOT a drift baseline: before
    any calibration/measured rows exist, probes observe without alarming
    (a never-tuned mesh would otherwise cry wolf on its first sample)."""
    obs = observatory.configure(enabled=True, sample_every=1, persist=False,
                                refit_every=0, probe_alternatives=False,
                                async_compile=False)
    obs.install(mesh=mesh8)
    _route_ring_int8(mesh8)
    with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
        obs.on_step(1)
    assert obs.drift_events == 0
    assert not any("COLLECTIVE DRIFT" in r.message for r in caplog.records)


# ------------------------------------------------------- program identity


def test_observatory_never_touches_the_traced_program(mesh8):
    """THE structural acceptance: hop programs are jaxpr-identical with the
    observatory off, on, and absent — its timings come from standalone
    probe dispatches, never from ops added to the step."""

    def make():
        # a FRESH closure per trace: shard_map caches the traced body per
        # function identity, and a cache hit would skip the second trace
        def f(v):
            return dist.all_reduce(v, "dp", algorithm="ring", codec="int8",
                                   block_size=BLOCK)

        return shard_map(f, mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"),
                         check_vma=False)

    x = jnp.ones((8, 4096), jnp.float32)
    observatory.configure(enabled=False)
    j_off = str(jax.make_jaxpr(make())(x))
    obs = observatory.configure(enabled=True, sample_every=1, persist=False)
    obs.install(mesh=mesh8)
    j_on = str(jax.make_jaxpr(make())(x))
    assert j_on == j_off
    # and the census DID observe the enabled trace
    assert obs.routes() and obs.routes()[0].hops == 14


def test_hlo_wire_reconciliation_in_program_registry(mesh8):
    """A captured routed program reconciles the observatory's traced wire
    bytes against its HLO-extracted collective bytes (the ppermute hops ARE
    the collectives in this program, so the ratio sits near 1)."""
    from deepspeed_tpu.telemetry.programs import get_program_registry

    telemetry.configure(enabled=True)
    reg = get_program_registry()
    reg.reset()
    obs = observatory.configure(enabled=True, persist=False)
    obs.install(mesh=mesh8)

    def f(v):
        return dist.all_reduce(v, "dp", algorithm="ring", codec="int8",
                               block_size=BLOCK)

    fn = jax.jit(shard_map(f, mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"),
                           check_vma=False))
    wrapped = reg.wrap(fn, "coll_probe_program")
    wrapped(jnp.ones((8, 4096), jnp.float32)).block_until_ready()
    rec = reg.latest("coll_probe_program")
    assert rec is not None
    assert rec.routed_wire_bytes > 0
    assert rec.routed_wire_bytes == obs.routes()[0].wire_bytes
    assert rec.wire_ratio is not None and 0.5 < rec.wire_ratio < 2.0
    key = 'coll/wire_bytes_ratio{program="coll_probe_program"}'
    assert key in telemetry.get_tracer().registry.gauges()


# ------------------------------------------------------------ engine wiring


def test_engine_installs_observatory_and_steps():
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    tc = TransformerConfig(vocab_size=32, hidden_size=16, intermediate_size=32,
                           num_layers=1, num_heads=2, max_seq_len=16)
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(tc, example_seq_len=8),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "steps_per_print": 10_000,
                "collectives": {"enabled": True,
                                "observe": {"enabled": True,
                                            "sample_every": 1,
                                            "persist": False}}})
    assert engine._coll_observatory is not None
    assert observatory.get_observatory().enabled
    batch = {"input_ids": np.zeros((engine.train_batch_size, 8), np.int32)}
    engine.train_batch(batch)  # on_step runs (no routed signatures: no-op)
    # an engine WITHOUT the observatory resets the process-global instance
    deepspeed_tpu.initialize(
        model=causal_lm_spec(tc, example_seq_len=8),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "steps_per_print": 10_000})
    assert not observatory.get_observatory().enabled
