"""Tier-1 lint: no bare ``jax.shard_map`` / ``from jax import shard_map``.

jax 0.4.x has no ``jax.shard_map`` (experimental-only, with a different
signature) — the seed-wide breakage that took out dozens of tests at import
time. Every call site must go through ``deepspeed_tpu.utils.compat.shard_map``
(which also translates ``axis_names``/``check_vma`` to the experimental API);
this grep makes the regression impossible to land quietly. Same story for
``jax.lax.axis_size`` (absent pre-0.5): use ``compat.axis_size``.
"""

import os
import re

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the shim itself (resolves the native symbol) and this lint are exempt
EXEMPT = {
    os.path.join("deepspeed_tpu", "utils", "compat.py"),
    os.path.join("tests", "unit", "test_no_bare_shard_map.py"),
}

BARE_PATTERNS = [
    (re.compile(r"\bjax\.shard_map\b"), "jax.shard_map"),
    (re.compile(r"^\s*from\s+jax\s+import\s+.*\bshard_map\b", re.M),
     "from jax import shard_map"),
    (re.compile(r"^\s*from\s+jax\.experimental\.shard_map\s+import", re.M),
     "from jax.experimental.shard_map import"),
    (re.compile(r"\bjax\.lax\.axis_size\b|\blax\.axis_size\b"), "lax.axis_size"),
]

SCAN_DIRS = ("deepspeed_tpu", "tests", "tools")
SCAN_FILES = ("bench.py",)


def _python_files():
    for d in SCAN_DIRS:
        for root, _dirs, files in os.walk(os.path.join(REPO_ROOT, d)):
            if ".jax_cache" in root:
                continue
            for f in files:
                if f.endswith(".py"):
                    yield os.path.join(root, f)
    for f in SCAN_FILES:
        p = os.path.join(REPO_ROOT, f)
        if os.path.exists(p):
            yield p


def test_no_bare_shard_map_or_axis_size():
    offenders = []
    for path in _python_files():
        rel = os.path.relpath(path, REPO_ROOT)
        if rel in EXEMPT:
            continue
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        for pat, label in BARE_PATTERNS:
            for m in pat.finditer(src):
                line = src.count("\n", 0, m.start()) + 1
                offenders.append(f"{rel}:{line}: {label}")
    assert not offenders, (
        "bare shard_map/axis_size usage (breaks on jax 0.4.x; import from "
        "deepspeed_tpu.utils.compat instead):\n  " + "\n  ".join(offenders))


def test_lint_scans_collectives_package():
    """The collectives/ package (hop algorithms over ppermute — the most
    likely place for a bare axis_size/shard_map to sneak back in) must be
    inside the lint's walk; guards against a future src-layout move
    silently dropping it from SCAN_DIRS."""
    scanned = {os.path.relpath(p, REPO_ROOT) for p in _python_files()}
    expected = {
        os.path.join("deepspeed_tpu", "collectives", f)
        for f in ("__init__.py", "algorithms.py", "codecs.py", "selector.py", "overlap.py")
    }
    missing = expected - scanned
    assert not missing, f"collectives files escaped the lint walk: {sorted(missing)}"


def test_compat_shard_map_resolves():
    """The shim must resolve on the installed jax (both kw spellings)."""
    from deepspeed_tpu.utils.compat import shard_map

    assert callable(shard_map)
    with pytest.raises(TypeError):
        shard_map(lambda x: x, mesh=None, in_specs=None, out_specs=None,
                  check_vma=False, check_rep=False)
