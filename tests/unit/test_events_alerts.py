"""Incident plane (telemetry/events.py + telemetry/alerts.py) — ISSUE 20.

Pinned here:
  - the structured event stream: bounded ring + monotonic seq, dedup-window
    folding onto the first occurrence, severity validation, subscriber
    failures counted but never raised, JSONL export/load round trip
  - the shared warn-once helper: logs exactly once per key AND emits one
    typed event (the dedup of the former per-module ``_warn_once`` copies)
  - the alert state machine on a FAKE clock: inactive -> pending (for_s) ->
    firing -> resolved (resolve_s flap damper), refire suppression,
    absence rules (missing AND stalled), event-rate rules, rule-error
    isolation, ``alerts/firing{rule=}`` gauges
  - sink discipline: a raising sink and a dead-receiver webhook are counted,
    never propagated into the evaluation path
  - cross-process incident correlation over real collector ingestion:
    two processes' events fold into ONE incident with a stable id; a
    re-pushed tail is idempotent (per-proc seq high-watermark); the
    ``incident_key`` label bridges events across the time window
  - program identity: the engine update jaxpr is identical with the event
    plane absent, enabled, and disabled — emission is host-side only
"""

import json
import logging

import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.telemetry import alerts as alerts_mod
from deepspeed_tpu.telemetry import events as events_mod
from deepspeed_tpu.telemetry import fleet, get_tracer
from deepspeed_tpu.telemetry.alerts import (
    AlertEngine,
    AlertRule,
    JsonlSink,
    WebhookSink,
)
from deepspeed_tpu.telemetry.collector import FleetCollector, correlate_events
from deepspeed_tpu.telemetry.events import Event, EventStream, WarnOnceSet
from deepspeed_tpu.telemetry.registry import MetricsRegistry
from tests.unit.simple_model import simple_model_spec


@pytest.fixture(autouse=True)
def _reset():
    fleet.reset_identity()
    fleet.configure_identity(run_id="testrun", process_index=0,
                             host="testhost", role="train")
    events_mod.reset_warn_once()
    events_mod.configure_events(capacity=2048, dedup_window_s=300.0,
                                jsonl_path="", enabled=True)
    events_mod.get_event_stream().clear()
    tr = get_tracer()
    tr.configure(enabled=False)
    tr.reset()
    yield
    events_mod.reset_warn_once()
    events_mod.get_event_stream().clear()
    fleet.reset_identity()
    get_tracer().configure(enabled=False)
    get_tracer().reset()


@pytest.fixture
def dslog():
    lg = logging.getLogger("deepspeed_tpu")
    prev = lg.propagate
    lg.propagate = True
    yield lg
    lg.propagate = prev


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t


def _stream(clock=None, capacity=64, **kw):
    return EventStream(capacity=capacity, registry=MetricsRegistry(),
                       clock=clock or FakeClock(), **kw)


# ------------------------------------------------------------ event stream
def test_ring_is_bounded_and_seq_monotonic():
    s = _stream(capacity=4)
    for i in range(6):
        s.emit("numerics", "tick", f"m{i}", severity="info")
    evs = s.events()
    assert len(evs) == 4 and s.total_emitted == 6 and s.dropped == 2
    assert [e.seq for e in evs] == [3, 4, 5, 6]
    assert [e.message for e in evs] == ["m2", "m3", "m4", "m5"]
    assert float(s.registry.gauge("events/buffered").value) == 4.0


def test_dedup_folds_onto_first_occurrence():
    clk = FakeClock(0.0)
    s = _stream(clock=clk, dedup_window_s=300.0)
    first = s.emit("coll", "drift", "drifting", dedup_key="coll:drift:x")
    clk.t = 10.0
    assert s.emit("coll", "drift", "drifting", dedup_key="coll:drift:x") is None
    assert first.count == 2 and s.total_emitted == 1
    assert float(s.registry.counter("events/deduped").value) == 1.0
    # past the window: a fresh event, not a fold
    clk.t = 400.0
    again = s.emit("coll", "drift", "drifting", dedup_key="coll:drift:x")
    assert again is not None and again.seq == 2 and first.count == 2


def test_severity_validated_and_filters_apply():
    clk = FakeClock(0.0)
    s = _stream(clock=clk)
    with pytest.raises(ValueError):
        s.emit("numerics", "x", "m", severity="fatal")
    s.emit("numerics", "a", "m", severity="info")
    clk.t = 5.0
    s.emit("fabric", "b", "m", severity="warn")
    clk.t = 9.0
    s.emit("fabric", "c", "m", severity="critical")
    assert len(s.events(min_severity="warn")) == 2
    assert [e.kind for e in s.events(subsystem="fabric")] == ["b", "c"]
    assert [e.kind for e in s.events(since_ts=5.0)] == ["b", "c"]
    assert [e["kind"] for e in s.drain_since(2)] == ["c"]
    assert s.last_seq == 3


def test_disabled_stream_emits_nothing():
    s = _stream()
    s.enabled = False
    assert s.emit("numerics", "x", "m") is None
    assert s.total_emitted == 0 and not s.events()


def test_subscriber_failure_is_counted_never_raised():
    s = _stream()
    seen = []

    def bad(ev):
        raise RuntimeError("boom")

    s.subscribe(bad)
    s.subscribe(seen.append)
    ev = s.emit("health", "probe", "m")
    assert ev is not None and seen == [ev]
    assert float(s.registry.counter("events/subscriber_failures").value) == 1.0


def test_jsonl_round_trip(tmp_path):
    s = _stream(clock=FakeClock(123.5))
    s.emit("perf", "regression", "slow", severity="warn",
           labels={"suite": "train"}, dedup_key="perf:x", step=7)
    s.emit("perf", "regression", "slow", dedup_key="perf:x")  # folds
    path = s.export_jsonl(str(tmp_path / "event_log.jsonl"))
    lines = [json.loads(ln) for ln in open(path, encoding="utf-8")]
    assert lines[0]["kind"] == "process_meta"
    assert lines[0]["schema"] == "dstpu_events_v1"
    assert lines[0]["identity"]["run_id"] == "testrun"
    back = events_mod.load_events_jsonl(path)
    assert len(back) == 1
    ev = back[0]
    assert (ev.subsystem, ev.kind, ev.count, ev.step) == (
        "perf", "regression", 2, 7)
    assert ev.labels == {"suite": "train"}
    # wire-dict round trip is exact
    assert Event.from_dict(ev.to_dict()).to_dict() == ev.to_dict()


# --------------------------------------------------------------- warn-once
def test_warn_once_set_logs_once_and_emits_typed_event(dslog, caplog):
    w = WarnOnceSet(subsystem="coll", default_kind="observatory_warning")
    with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
        assert w("k1", "the sky is falling") is True
        assert w("k1", "the sky is falling") is False
    assert [r for r in caplog.records
            if "sky is falling" in r.message][0] and len(
        [r for r in caplog.records if "sky is falling" in r.message]) == 1
    evs = events_mod.get_event_stream().events(subsystem="coll")
    assert len(evs) == 1
    assert (evs[0].kind, evs[0].dedup_key) == ("observatory_warning", "k1")
    assert w.seen("k1") and not w.seen("k2")
    w.reset()
    assert w("k1", "again") is True


def test_module_warn_once_defaults_key_to_message(dslog, caplog):
    with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
        assert events_mod.warn_once("legacy warning path") is True
        assert events_mod.warn_once("legacy warning path") is False
    evs = events_mod.get_event_stream().events(subsystem="logging")
    assert len(evs) == 1 and evs[0].kind == "warning_once"


# ------------------------------------------------------ alert state machine
def _engine_with(rules, clk, stream=None):
    reg = MetricsRegistry()
    return AlertEngine(rules=rules, registry=reg,
                       stream=stream or _stream(clock=clk),
                       sinks=[], clock=clk), reg


def test_threshold_pending_for_duration_then_firing_then_resolved():
    clk = FakeClock(0.0)
    rule = AlertRule(name="hot", metric="perf/regression_events",
                     op=">", value=0, for_s=10.0, resolve_s=10.0,
                     summary="regressions: {value}")
    eng, reg = _engine_with([rule], clk)
    g = reg.gauge("perf/regression_events")
    assert eng.evaluate() == [] and eng.firing() == []
    g.set(3)
    assert eng.evaluate() == []          # pending, waiting out for_s
    assert eng.firing() == []
    clk.t = 5.0
    assert eng.evaluate() == []
    clk.t = 10.0
    notes = eng.evaluate()               # for_s elapsed -> firing
    assert [n["state"] for n in notes] == ["firing"]
    assert notes[0]["summary"] == "regressions: 3.0"
    assert float(reg.gauge("alerts/firing", rule="hot").value) == 1.0
    assert [f["rule"] for f in eng.firing()] == ["hot"]
    # a clear shorter than resolve_s never resolves (flap damper)
    g.set(0)
    clk.t = 15.0
    assert eng.evaluate() == [] and eng.firing()
    g.set(2)
    clk.t = 16.0
    assert eng.evaluate() == []          # reactivated: still one firing
    g.set(0)
    clk.t = 20.0
    assert eng.evaluate() == []
    clk.t = 31.0
    notes = eng.evaluate()               # clear held resolve_s -> resolved
    assert [n["state"] for n in notes] == ["resolved"]
    assert eng.firing() == []
    assert float(reg.gauge("alerts/firing", rule="hot").value) == 0.0
    assert float(reg.counter("alerts/fired", rule="hot").value) == 1.0
    assert float(reg.counter("alerts/resolved", rule="hot").value) == 1.0


def test_pending_that_clears_never_notifies():
    clk = FakeClock(0.0)
    rule = AlertRule(name="blip", metric="perf/regression_events",
                     op=">", value=0, for_s=30.0)
    eng, reg = _engine_with([rule], clk)
    g = reg.gauge("perf/regression_events")
    g.set(1)
    eng.evaluate()
    g.set(0)
    clk.t = 5.0
    assert eng.evaluate() == []
    g.set(1)
    clk.t = 10.0
    eng.evaluate()                       # pending restarts from t=10
    clk.t = 35.0
    assert eng.evaluate() == []          # 25s < for_s: still pending
    clk.t = 40.0
    assert [n["state"] for n in eng.evaluate()] == ["firing"]


def test_refire_suppression_counts_but_keeps_state():
    clk = FakeClock(0.0)
    rule = AlertRule(name="flappy", metric="perf/regression_events",
                     op=">", value=0, refire_suppress_s=100.0)
    eng, reg = _engine_with([rule], clk)
    g = reg.gauge("perf/regression_events")
    g.set(1)
    assert [n["state"] for n in eng.evaluate()] == ["firing"]
    g.set(0)
    clk.t = 10.0
    eng.evaluate()                       # resolved (resolve_s=0)
    g.set(1)
    clk.t = 20.0
    assert eng.evaluate() == []          # re-fire inside suppress window
    assert [f["rule"] for f in eng.firing()] == ["flappy"]  # state transitioned
    assert float(reg.counter("alerts/suppressed", rule="flappy").value) == 1.0
    g.set(0)
    clk.t = 30.0
    eng.evaluate()
    g.set(1)
    clk.t = 150.0
    assert [n["state"] for n in eng.evaluate()] == ["firing"]  # window passed


def test_threshold_matches_every_labelled_child():
    clk = FakeClock(0.0)
    rule = AlertRule(name="fail", metric="fabric/rpc_failures",
                     op=">", value=0)
    eng, reg = _engine_with([rule], clk)
    reg.counter("fabric/rpc_failures", endpoint="query").add(1)
    reg.counter("fabric/rpc_failures", endpoint="admit").add(2)
    notes = eng.evaluate()
    assert len(notes) == 2
    assert {n["labels_key"] for n in notes} == {
        '{endpoint="admit"}', '{endpoint="query"}'}
    assert float(reg.gauge("alerts/firing", rule="fail").value) == 2.0


def test_absence_rule_missing_and_stalled():
    clk = FakeClock(0.0)
    rule = AlertRule(name="stalled", kind="absence", metric="fleet/last_step",
                     window_s=60.0)
    eng, reg = _engine_with([rule], clk)
    # missing entirely -> fires immediately (for_s=0)
    assert [n["state"] for n in eng.evaluate()] == ["firing"]
    # metric appears and moves -> resolves
    g = reg.gauge("fleet/last_step")
    g.set(1)
    clk.t = 10.0
    assert [n["state"] for n in eng.evaluate()] == ["resolved"]
    # value keeps changing: quiet
    g.set(2)
    clk.t = 30.0
    assert eng.evaluate() == []
    clk.t = 80.0
    assert eng.evaluate() == []          # change at t=30 restarts staleness
    # stalled past window_s -> fires again
    clk.t = 95.0
    assert [n["state"] for n in eng.evaluate()] == ["firing"]


def test_event_rate_rule_over_trailing_window():
    clk = FakeClock(0.0)
    stream = _stream(clock=clk)
    rule = AlertRule(name="rpc", kind="event_rate", subsystem="fabric",
                     event_kind="rpc_failure", window_s=300.0,
                     op=">", value=2)
    eng, _reg = _engine_with([rule], clk, stream=stream)
    for _ in range(2):
        stream.emit("fabric", "rpc_failure", "down")
    assert eng.evaluate() == []          # 2 is not > 2
    stream.emit("fabric", "rpc_failure", "down")
    notes = eng.evaluate()
    assert [n["state"] for n in notes] == ["firing"]
    assert notes[0]["value"] == 3.0
    # dedup counts fold into the rate
    stream.emit("fabric", "rpc_failure", "down", dedup_key="k")
    stream.emit("fabric", "rpc_failure", "down", dedup_key="k")
    assert eng.evaluate() == []          # already firing
    # the window slides past the burst -> resolves
    clk.t = 301.0
    assert [n["state"] for n in eng.evaluate()] == ["resolved"]


def test_rule_error_is_isolated_to_that_rule():
    clk = FakeClock(0.0)
    good = AlertRule(name="good", metric="perf/regression_events",
                     op=">", value=0)
    bad = AlertRule(name="bad", kind="event_rate", subsystem="fabric",
                    event_kind="rpc_failure")
    class BrokenEvents:
        def events(self, **kw):
            raise RuntimeError("ring poisoned")

        def emit(self, *a, **kw):        # delivery path must stay alive
            return None

    eng, reg = _engine_with([good, bad], clk)
    eng.stream = BrokenEvents()          # event-rate access now raises
    reg.gauge("perf/regression_events").set(1)
    notes = eng.evaluate()               # must not propagate the bad rule
    assert [n["rule"] for n in notes] == ["good"]
    assert float(reg.counter("alerts/rule_errors", rule="bad").value) == 1.0


def test_firing_alert_emits_alert_event_and_jsonl_sink(tmp_path):
    clk = FakeClock(0.0)
    stream = _stream(clock=clk)
    path = str(tmp_path / "notifications.jsonl")
    rule = AlertRule(name="diverged", metric="numerics/divergence_events",
                     op=">", value=0, severity="critical",
                     summary="divergence: {value}")
    reg = MetricsRegistry()
    eng = AlertEngine(rules=[rule], registry=reg, stream=stream,
                      sinks=[JsonlSink(path)], clock=clk)
    reg.counter("numerics/divergence_events").add(1)
    eng.evaluate()
    # alerts are events too: they federate + correlate like any detector
    evs = stream.events(subsystem="alerts")
    assert len(evs) == 1
    assert (evs[0].kind, evs[0].severity) == ("firing", "critical")
    assert evs[0].labels["rule"] == "diverged"
    rows = [json.loads(ln) for ln in open(path, encoding="utf-8")]
    assert rows[0]["rule"] == "diverged" and rows[0]["state"] == "firing"
    assert rows[0]["identity"]["run_id"] == "testrun"


def test_raising_sink_is_counted_never_propagated():
    clk = FakeClock(0.0)

    class BadSink:
        name = "bad"

        def notify(self, n):
            raise RuntimeError("receiver down")

    rule = AlertRule(name="r", metric="perf/regression_events",
                     op=">", value=0)
    reg = MetricsRegistry()
    eng = AlertEngine(rules=[rule], registry=reg,
                      stream=_stream(clock=clk), sinks=[BadSink()], clock=clk)
    reg.gauge("perf/regression_events").set(1)
    notes = eng.evaluate()               # must not raise
    assert [n["state"] for n in notes] == ["firing"]
    assert float(reg.counter("alerts/sink_failures", sink="bad").value) == 1.0


def test_webhook_sink_dead_receiver_never_raises():
    sink = WebhookSink("http://127.0.0.1:9/unroutable", timeout=0.2)
    for i in range(3):
        sink.notify({"rule": "r", "state": "firing", "n": i})
    sink.flush(timeout=10.0)
    sink.stop()
    assert sink.failures >= 1 and sink.delivered == 0


def test_default_rules_quiet_on_empty_state():
    clk = FakeClock(0.0)
    eng, reg = _engine_with(alerts_mod.default_rules(), clk)
    assert eng.evaluate() == [] and eng.firing() == []
    names = {r.name for r in eng.rules}
    assert {"numerics_divergence", "collective_drift", "perf_regression",
            "replica_dead", "replica_unreachable", "rpc_failures",
            "health_abort", "recompile_storm"} <= names
    # and loud once a defect counter moves
    reg.counter("numerics/divergence_events").add(1)
    assert {n["rule"] for n in eng.evaluate()} == {"numerics_divergence"}


# ----------------------------------------------- cross-process correlation
def _ev(ts, subsystem, kind, seq, severity="critical", **labels):
    d = {"ts": ts, "severity": severity, "subsystem": subsystem,
         "kind": kind, "message": f"{subsystem}/{kind}", "seq": seq,
         "count": 1}
    if labels:
        d["labels"] = {k: str(v) for k, v in labels.items()}
    return d


def test_collector_ingest_correlates_two_processes_into_one_incident():
    c = FleetCollector(incident_window_s=30.0)
    base = 1_000_000.0
    c.ingest({"identity": {"run_id": "r1", "process_index": 0},
              "events": [_ev(base, "numerics", "divergence", 1)]})
    c.ingest({"identity": {"run_id": "r1", "process_index": 1},
              "events": [_ev(base + 5.0, "fabric", "replica_unreachable", 1)]})
    incs = c.incidents()
    assert len(incs) == 1
    inc = incs[0]
    assert inc["run_id"] == "r1" and inc["severity"] == "critical"
    assert set(inc["kinds"]) == {"numerics/divergence",
                                 "fabric/replica_unreachable"}
    assert set(inc["procs"]) == {"r1/p0", "r1/p1"}
    # id is stable across repeated reads of the same state
    assert c.incidents()[0]["id"] == inc["id"]
    assert inc["id"].startswith("inc-")


def test_collector_repushed_tail_is_idempotent():
    c = FleetCollector()
    doc = {"identity": {"run_id": "r1", "process_index": 0},
           "events": [_ev(1.0, "health", "abort", 1),
                      _ev(2.0, "health", "abort", 2)]}
    c.ingest(doc)
    c.ingest(doc)                        # ack lost, client re-sends the tail
    assert c.events_ingested == 2 and len(c.events()) == 2
    # a genuinely new event past the watermark still appends
    c.ingest({"identity": {"run_id": "r1", "process_index": 0},
              "events": [_ev(3.0, "health", "abort", 3)]})
    assert len(c.events()) == 3


def test_incident_key_bridges_events_across_the_window():
    base = 1_000_000.0
    far = [_ev(base, "coll", "drift", 1, incident_key="perf_gate:x"),
           _ev(base + 500.0, "perf", "regression", 2,
               incident_key="perf_gate:x"),
           _ev(base + 900.0, "numerics", "divergence", 3)]
    for e in far:
        e["proc"] = "r1/p0"
        e.setdefault("identity", {"run_id": "r1", "process_index": 0})
    incs = correlate_events(far, window_s=30.0)
    assert len(incs) == 2                # key joins 1+2; 3 stands alone
    joined = max(incs, key=lambda i: i["event_count"])
    assert set(joined["kinds"]) == {"coll/drift", "perf/regression"}
    # without the stamp the same spacing is three separate incidents
    for e in far:
        e.pop("labels", None)
    assert len(correlate_events(far, window_s=30.0)) == 3


def test_correlation_separates_runs_and_respects_severity_floor():
    base = 1_000_000.0
    evs = [dict(_ev(base, "health", "abort", 1), proc="r1/p0",
                identity={"run_id": "r1", "process_index": 0}),
           dict(_ev(base + 1.0, "health", "abort", 1), proc="r2/p0",
                identity={"run_id": "r2", "process_index": 0}),
           dict(_ev(base + 2.0, "data", "note", 2, severity="info"),
                proc="r1/p0", identity={"run_id": "r1", "process_index": 0})]
    incs = correlate_events(evs, window_s=30.0)
    assert len(incs) == 2                # per-run, info below the floor
    assert {i["run_id"] for i in incs} == {"r1", "r2"}
    assert all(i["event_count"] == 1 for i in incs)


# ---------------------------------------------------------- program identity
def test_event_plane_is_jaxpr_invisible():
    """THE structural acceptance: the traced update program is one and the
    same jaxpr with the event plane absent, actively emitting, and
    disabled — emission is host-side bookkeeping, never an op in the
    step."""

    def make_engine():
        eng, *_ = deepspeed_tpu.initialize(
            model=simple_model_spec(),
            config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1},
                "steps_per_print": 10_000,
            })
        return eng

    def update_jaxpr(eng):
        state = eng.state
        grads = jax.tree_util.tree_map(jnp.zeros_like, state.params)

        def fn(s, g):
            return eng._update_math(s, g, s.rng, grads_are_unscaled=True)

        return str(jax.make_jaxpr(fn)(state, grads))

    stream = events_mod.get_event_stream()
    j_absent = update_jaxpr(make_engine())
    for i in range(5):
        events_mod.emit_event("bench", "tick", f"t{i}", severity="info")
    clk = FakeClock(0.0)
    AlertEngine(rules=alerts_mod.default_rules(),
                registry=MetricsRegistry(), stream=stream,
                sinks=[], clock=clk).evaluate()
    j_emitting = update_jaxpr(make_engine())
    stream.enabled = False
    j_disabled = update_jaxpr(make_engine())
    stream.enabled = True
    assert j_absent == j_emitting == j_disabled


def test_engine_config_wires_event_plane():
    eng, *_ = deepspeed_tpu.initialize(
        model=simple_model_spec(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 10_000,
            "telemetry": {"enabled": False, "events_capacity": 99,
                          "events_dedup_window_s": 7.5},
        })
    s = events_mod.get_event_stream()
    assert s.capacity == 99 and s.dedup_window_s == 7.5
    assert eng._alert_engine is None     # alerts stay opt-in
