"""Tiny model fixtures (analog of reference ``tests/unit/simple_model.py``)."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.model import ModelSpec


class SimpleMLP(nn.Module):
    """Regression MLP: batch = {'x': [B, D], 'y': [B, 1]} -> (loss, preds)."""

    hidden: int = 32
    depth: int = 2

    @nn.compact
    def __call__(self, batch, train: bool = False):
        h = batch["x"]
        for _ in range(self.depth):
            h = nn.Dense(self.hidden)(h)
            h = nn.relu(h)
        pred = nn.Dense(1)(h)
        loss = jnp.mean((pred - batch["y"]) ** 2)
        return loss, pred


def simple_model_spec(dim: int = 16, hidden: int = 32, depth: int = 2) -> ModelSpec:
    module = SimpleMLP(hidden=hidden, depth=depth)
    example = {"x": jnp.zeros((2, dim)), "y": jnp.zeros((2, 1))}
    return ModelSpec.from_flax(module, example)


def _teacher(dim: int) -> np.ndarray:
    # fixed across batches so there is something to learn
    return np.random.default_rng(1234).normal(size=(dim, 1)).astype(np.float32)


def random_batch(batch_size: int, dim: int = 16, seed: int = 0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(batch_size, dim)).astype(np.float32)
    y = x @ _teacher(dim) + 0.01 * rng.normal(size=(batch_size, 1)).astype(np.float32)
    return {"x": x, "y": y}


def make_dataset(n: int = 256, dim: int = 16, seed: int = 0):
    return random_batch(n, dim, seed)
