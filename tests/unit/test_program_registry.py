"""Compiled-program registry tests (ISSUE 7 tentpole contract).

What every device-side observability claim leans on:
  - the registry captures the engine's train-step and the v2 decode-chain
    programs at their dispatch compile, with real cost/memory analysis
  - collective ops are extracted from compiled HLO text (kind, bytes,
    replica groups)
  - the ``utils/hbm.py`` pre-flight estimate is reconciled against XLA's
    peak (``hbm/estimate_ratio`` in the Prometheus exposition, loud warning
    on under-estimates)
  - recompile warnings carry the old/new HLO fingerprint
  - anomaly/manual/SIGUSR2 triggers produce a ``jax.profiler`` trace
  - disabled: no records, and engine dispatch is the raw jitted callable
"""

import contextlib
import io
import logging
import os
import signal

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.telemetry import get_tracer
from deepspeed_tpu.telemetry.programs import (
    ProgramRegistry,
    extract_collectives,
    get_program_registry,
    hlo_fingerprint,
    unwrap_program_watch,
)
from deepspeed_tpu.utils.compat import shard_map
from tests.unit.inference.test_inference_v2 import make_model


@contextlib.contextmanager
def _ds_log():
    """Capture the deepspeed_tpu logger (its handler binds the import-time
    stdout object, which pytest's capsys/capfd fixtures cannot intercept)."""
    from deepspeed_tpu.utils.logging import logger as ds_logger

    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    ds_logger.addHandler(handler)
    try:
        yield buf
    finally:
        ds_logger.removeHandler(handler)


@pytest.fixture(autouse=True)
def _clean_globals():
    tr = get_tracer()
    reg = get_program_registry()
    for _ in range(1):
        tr.configure(enabled=False)
        tr.trace_path = None
        tr.jsonl_path = None
        tr.reset()
        reg.configure(enabled=None)
        reg.reset()
    yield
    tr.configure(enabled=False)
    tr.trace_path = None
    tr.jsonl_path = None
    tr.reset()
    reg.configure(enabled=None)
    reg.reset()


def _tiny_lm():
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, max_seq_len=64)
    return cfg, causal_lm_spec(cfg, example_seq_len=16)


def _train_engine(telemetry=True, **extra):
    cfg, spec = _tiny_lm()
    eng, *_ = deepspeed_tpu.initialize(
        model=spec,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "steps_per_print": 10_000,
            **({"telemetry": {"enabled": True}} if telemetry else {}),
            **extra,
        })
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, cfg.vocab_size, (eng.train_batch_size, 16), dtype=np.int32)}
    return eng, batch


# --------------------------------------------------------- HLO text analysis
CANNED_HLO = """\
HloModule jit_f, input_output_alias={ {0}: (0, {}, may-alias) }, entry_computation_layout={(f32[8,128]{1,0})->f32[8,128]{1,0}}

ENTRY %main (p0: f32[8,128]) -> f32[8,128] {
  %p0 = f32[8,128]{1,0} parameter(0)
  %all-reduce = f32[8,128]{1,0} all-reduce(f32[8,128]{1,0} %p0), replica_groups={{0,1,2,3}}, to_apply=%add
  %ag = f32[16,128]{1,0} all-gather(f32[8,128]{1,0} %all-reduce), replica_groups=[2,2]<=[4], dimensions={0}
  ROOT %done = f32[8,128]{1,0} slice(f32[16,128]{1,0} %ag), slice={[0:8], [0:128]}
}
"""


def test_extract_collectives_from_hlo_text():
    colls = extract_collectives(CANNED_HLO)
    kinds = [c["kind"] for c in colls]
    assert kinds == ["all-reduce", "all-gather"]
    assert colls[0]["bytes"] == 8 * 128 * 4
    assert colls[0]["replica_groups"] == "{{0,1,2,3}}"
    assert colls[1]["bytes"] == 16 * 128 * 4
    assert colls[1]["replica_groups"] == "[2,2]<=[4]"


def test_extract_custom_kernels_from_hlo_text():
    """Pallas/Mosaic kernels surface as custom-call targets — how a FUSED
    collective hop reads in a program inventory: one tpu_custom_call per
    hop where the unfused path showed quantize calls + collective-permute."""
    from deepspeed_tpu.telemetry.programs import extract_custom_kernels

    hlo = CANNED_HLO + """
  %hop0 = (s8[2048]{0}, f32[1]{0}) custom-call(s8[2048]{0} %w0), custom_call_target="tpu_custom_call"
  %hop1 = (s8[2048]{0}, f32[1]{0}) custom-call(s8[2048]{0} %w1), custom_call_target="tpu_custom_call"
  %host = f32[4]{0} custom-call(f32[4]{0} %x), custom_call_target="annotate_device_placement"
"""
    kernels = extract_custom_kernels(hlo)
    by_target = {k["target"]: (k["count"], k["kernel"]) for k in kernels}
    assert by_target["tpu_custom_call"] == (2, True)
    # GSPMD/placement annotations are listed but NOT kernels — they must
    # not inflate program/custom_kernel_count
    assert by_target["annotate_device_placement"] == (1, False)
    assert extract_custom_kernels(CANNED_HLO) == []
    from deepspeed_tpu.telemetry.programs import ProgramRecord

    rec = ProgramRecord(label="x", index=0, custom_kernels=kernels)
    assert rec.custom_kernel_count == 2


def test_hlo_fingerprint_stable_and_counts():
    fp1, n1 = hlo_fingerprint(CANNED_HLO)
    fp2, n2 = hlo_fingerprint(CANNED_HLO)
    assert fp1 == fp2 and len(fp1) == 12
    assert n1 == n2 == 4  # p0, all-reduce, ag, done
    fp3, _ = hlo_fingerprint(CANNED_HLO + "\n")
    assert fp3 != fp1  # content hash, not structure hash


# ------------------------------------------------------------- train capture
def test_train_step_capture_costs_and_exposition():
    """The engine's train step lands in the registry with nonzero flops and
    peak HBM, calibrated against the pre-flight estimate, and rides the
    Prometheus exposition."""
    eng, batch = _train_engine(telemetry=True)
    eng.train_batch(batch)
    reg = get_program_registry()

    rec = reg.latest("train_step")
    assert rec is not None
    assert rec.flops > 0 and rec.bytes_accessed > 0
    assert rec.peak_hbm_bytes > 0
    assert rec.fingerprint and rec.instruction_count > 0
    assert rec.compile_wall_s is not None and rec.compile_wall_s > 0
    # calibration: the engine registered its utils/hbm.py estimate
    assert reg.hbm_estimate("train") and reg.hbm_estimate("train") > 0
    assert rec.hbm_estimate_ratio is not None and rec.hbm_estimate_ratio > 0

    from deepspeed_tpu.telemetry.exposition import render_prometheus

    prom = render_prometheus(get_tracer().registry)
    assert 'dstpu_program_flops{program="train_step"}' in prom
    assert 'dstpu_program_peak_hbm_bytes{program="train_step"}' in prom
    assert "dstpu_hbm_estimate_ratio" in prom
    assert 'dstpu_compile_count_total{program="train_step"}' in prom

    # a second step of the same shape compiles nothing -> no new capture
    n = len(reg.records())
    eng.train_batch(batch)
    assert len(reg.records()) == n


def test_decode_chain_capture_serving_scope():
    """The v2 decode-chain program is captured with costs and calibrated
    against the serving-scope estimate."""
    get_tracer().configure(enabled=True)
    cfg, _, params = make_model()
    from deepspeed_tpu.inference import InferenceEngineV2

    eng = InferenceEngineV2(cfg, params, {
        "dtype": "fp32", "kv_block_size": 4, "num_kv_blocks": 64,
        "chunk_bucket": 8, "decode_chain": 4, "hbm_check": "off"})
    prompts = [np.arange(5, dtype=np.int64), np.arange(3, dtype=np.int64)]
    eng.generate(prompts, max_new_tokens=8)

    reg = get_program_registry()
    chains = [lbl for lbl in reg.labels() if lbl.startswith("v2:decode_chain")]
    assert chains, f"no decode-chain capture in {reg.labels()}"
    rec = reg.latest(chains[0])
    assert rec.flops > 0 and rec.peak_hbm_bytes > 0
    # hbm_check "off" still registers the serving estimate while capture is on
    assert reg.hbm_estimate("serving") and rec.hbm_estimate_ratio is not None
    # prefill (fused-sampling step) captured too
    assert any(lbl.startswith("v2:prefill") for lbl in reg.labels())


def test_collective_extraction_on_compiled_psum():
    """A program containing a real psum shows an all-reduce with payload
    bytes in its registry record (full-manual shard_map on the 8-CPU mesh)."""
    devs = jax.devices()[:8]
    mesh = Mesh(np.array(devs), ("dp",))
    reg = get_program_registry().configure(enabled=True)

    fn = jax.jit(shard_map(
        lambda v: jax.lax.psum(v, "dp"),
        mesh=mesh, in_specs=P("dp"), out_specs=P(None)))
    x = jnp.arange(8 * 32, dtype=jnp.float32).reshape(8, 32)
    rec = reg.capture(fn, x, label="psum_probe")
    assert rec is not None
    ars = [c for c in rec.collectives if c["kind"] == "all-reduce"]
    assert ars, f"no all-reduce in {rec.collectives}"
    assert all(c["bytes"] > 0 for c in ars)
    assert rec.collective_bytes >= ars[0]["bytes"]


# ------------------------------------------------------------- disabled mode
def test_disabled_allocates_nothing_and_leaves_dispatch_untouched():
    """Telemetry off: no records, no estimates, and the engine's jitted
    callables are the raw jit objects (no watcher layer), with the jit cache
    size unchanged by stepping."""
    eng, batch = _train_engine(telemetry=False)
    eng.train_batch(batch)
    eng.train_batch(batch)

    reg = get_program_registry()
    assert reg.records() == []
    assert not reg.enabled
    # dispatch untouched: the train step is the bare jit (not a watcher)...
    assert unwrap_program_watch(eng._train_step) is eng._train_step
    assert type(eng._train_step).__name__ not in ("_Watch", "_WrappedJit")
    # ...and exactly one compiled program in its cache
    assert eng._train_step._cache_size() == 1

    get_tracer().configure(enabled=False)
    cfg, _, params = make_model()
    from deepspeed_tpu.inference import InferenceEngineV2

    v2 = InferenceEngineV2(cfg, params, {
        "dtype": "fp32", "kv_block_size": 4, "num_kv_blocks": 64,
        "chunk_bucket": 8, "decode_chain": 4, "hbm_check": "off"})
    v2.generate([np.arange(5, dtype=np.int64)], max_new_tokens=4)
    assert reg.records() == []
    for fn in v2._step_cache.values():
        assert unwrap_program_watch(fn) is fn


def test_explicit_capture_failure_is_safe():
    reg = ProgramRegistry().configure(enabled=True)
    rec = reg.capture(lambda: (_ for _ in ()).throw(RuntimeError("boom")),
                      label="broken")
    assert rec is None and reg.capture_failures == 1


def test_explicit_capture_dedupes_unchanged_program():
    """Repeated capture() of the same program returns the existing record —
    a per-step compiled_cost loop must not grow the inventory unboundedly."""
    reg = ProgramRegistry().configure(enabled=True)
    f = jax.jit(lambda x: (x * 3.0).sum())
    x = jnp.ones((8, 8))
    r1 = reg.capture(f, x, label="loop")
    r2 = reg.capture(f, x, label="loop")
    assert r1 is r2 and len(reg.records()) == 1
    # a different program under the same label is a new record
    r3 = reg.capture(f, jnp.ones((8, 16)), label="loop")
    assert r3 is not r1 and len(reg.records()) == 2


def test_capture_survives_recompile_detection_disabled():
    """diagnostics on + recompile checking off must not silently lose
    program capture — the manager falls back to the registry watcher."""
    eng, batch = _train_engine(
        telemetry=True,
        diagnostics={"enabled": True, "recompile": {"enabled": False},
                     "health": {"enabled": False}})
    eng.train_batch(batch)
    rec = get_program_registry().latest("train_step")
    assert rec is not None and rec.flops > 0


# --------------------------------------------------- recompile fingerprints
def test_recompile_warning_carries_hlo_fingerprint():
    """A recompile report names the old and new HLO identity (hash +
    instruction count) — what GREW, not just which argument drifted."""
    get_tracer().configure(enabled=True)
    from deepspeed_tpu.diagnostics import RecompileDetector

    det = RecompileDetector("test")
    f = det.wrap(jax.jit(lambda x: (x * 2.0).sum()), "toy")
    f(jnp.ones((4, 8)))
    with _ds_log() as buf:
        f(jnp.ones((4, 16)))  # forced recompile
    evs = [e for e in det.events if e["kind"] == "recompile"]
    assert evs, "no recompile event"
    assert evs[0]["hlo"]["fingerprint"] and evs[0]["hlo"]["instructions"] > 0
    # the initial compile carried its own fingerprint too (the "old" side)
    initial = [e for e in det.events if e["kind"] == "initial"][0]
    assert initial["hlo"]["fingerprint"]
    text = buf.getvalue()
    assert "RECOMPILE" in text and "HLO" in text and "instr" in text


# ------------------------------------------------------------ profiler capture
def test_profiler_capture_window(tmp_path):
    """arm() -> the next N step brackets run under jax.profiler and the
    trace directory is recorded (and referenced from the flight recorder)."""
    from deepspeed_tpu.diagnostics import FlightRecorder
    from deepspeed_tpu.profiling.capture import ProfilerCapture

    rec = FlightRecorder(capacity=8, dump_dir=str(tmp_path / "fr"))
    cap = ProfilerCapture(steps=2, out_dir=str(tmp_path / "prof"),
                          cooldown_steps=100, recorder=rec)
    assert not cap.active
    cap.arm(reason="test")
    step_fn = jax.jit(lambda x: (x * x).sum())
    for step in (1, 2, 3):
        cap.on_step_start(step)
        np.asarray(step_fn(jnp.ones((32, 32))))
        cap.on_step_end(step)
    assert len(cap.captures) == 1
    window = cap.captures[0]
    assert window["reason"] == "test"
    assert window["first_step"] == 1 and window["last_step"] == 2
    files = [os.path.join(r, f) for r, _, fs in os.walk(window["trace_dir"])
             for f in fs]
    assert files, f"no trace files under {window['trace_dir']}"
    # the crash-dump header names the freshest device trace
    assert rec._context["profiler_trace"] == window["trace_dir"]
    # cooldown: a second arm right after is dropped at the next boundary
    cap.arm(reason="too-soon")
    cap.on_step_start(4)
    assert not cap.active


def test_anomaly_flags_arm_capture(tmp_path):
    """A straggler flag from the step-time detector arms the capture; the
    window starts at the next step boundary."""
    from deepspeed_tpu.config.config import DiagnosticsConfig
    from deepspeed_tpu.diagnostics.manager import DiagnosticsManager

    cfg = DiagnosticsConfig(**{
        "enabled": True,
        "health": {"enabled": False},
        "flight_recorder": {"enabled": False},
        "step_time": {"enabled": True, "window": 8, "min_samples": 4,
                      "straggler_factor": 2.0},
        "profiler_capture": {"enabled": True, "steps": 1,
                             "dir": str(tmp_path / "prof"),
                             "cooldown_steps": 0, "signal": False},
    })
    mgr = DiagnosticsManager(cfg)
    assert mgr.profiler_capture is not None
    for step in range(1, 7):
        mgr.before_step(step)
        mgr.after_step(step, {}, step_time_s=0.01)
    # straggler: 10x the rolling median
    mgr.before_step(7)
    mgr.after_step(7, {}, step_time_s=0.1)
    assert mgr.profiler_capture._armed_reason is not None
    assert "straggler" in mgr.profiler_capture._armed_reason
    step_fn = jax.jit(lambda x: x + 1)
    mgr.before_step(8)  # window opens at the next boundary
    np.asarray(step_fn(jnp.ones((8,))))
    mgr.after_step(8, {}, step_time_s=0.01)
    assert len(mgr.profiler_capture.captures) == 1
    assert "straggler" in mgr.profiler_capture.captures[0]["reason"]


def test_sigusr2_arms_capture(tmp_path):
    from deepspeed_tpu.profiling import capture as cap_mod

    cap = cap_mod.ProfilerCapture(steps=1, out_dir=str(tmp_path))
    cap_mod.install_sigusr2()
    try:
        os.kill(os.getpid(), signal.SIGUSR2)
        assert cap._armed_reason == "signal:SIGUSR2"
    finally:
        cap._armed_reason = None


# ------------------------------------------------------------- hbm calibration
def test_record_calibration_warns_on_underestimate():
    from deepspeed_tpu.utils.hbm import record_calibration

    tr = get_tracer().configure(enabled=True)
    with _ds_log() as buf:
        ratio = record_calibration(100, 90, what="close")  # within 1.2x: quiet
    assert ratio == pytest.approx(0.9)
    assert "HBM calibration" not in buf.getvalue()
    with _ds_log() as buf:
        ratio = record_calibration(100, 150, what="blown")
    assert ratio == pytest.approx(1.5)
    assert "HBM calibration" in buf.getvalue()
    assert tr.registry.gauge("hbm/estimate_ratio").value == pytest.approx(1.5)
    # unusable inputs -> None, never a crash
    assert record_calibration(0, 100, what="x") is None
    assert record_calibration(100, None, what="x") is None


# ---------------------------------------------------------- moe gauge plumbing
def test_moe_dispatch_stats_ride_step_metrics():
    """MoE engines with telemetry on emit device-computed moe/* scalars in
    the step metrics and refresh registry gauges at the print cadence."""
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, max_seq_len=64, num_experts=4, moe_top_k=2,
        moe_capacity_factor=1.25)
    eng, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=16),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "steps_per_print": 1,
            "telemetry": {"enabled": True},
        })
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, cfg.vocab_size, (eng.train_batch_size, 16), dtype=np.int32)}
    metrics = eng.train_batch(batch)
    vals = jax.device_get({k: metrics[k] for k in (
        "moe/capacity_factor", "moe/token_drop_rate", "moe/expert_load_balance")})
    assert float(vals["moe/capacity_factor"]) > 0
    assert 0.0 <= float(vals["moe/token_drop_rate"]) <= 1.0
    assert float(vals["moe/expert_load_balance"]) >= 1.0 - 1e-6
    # steps_per_print=1 -> the sync point refreshed the registry gauges
    reg = get_tracer().registry
    assert reg.gauge("moe/capacity_factor").value > 0
    from deepspeed_tpu.telemetry.exposition import render_prometheus

    assert "dstpu_moe_expert_load_balance" in render_prometheus(reg)


def test_moe_stats_off_without_telemetry():
    """Telemetry off: the model spec is untouched and no moe/* keys appear
    (byte-identical step program contract)."""
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, max_seq_len=64, num_experts=4, moe_top_k=2)
    eng, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=16),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "steps_per_print": 10_000,
        })
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, cfg.vocab_size, (eng.train_batch_size, 16), dtype=np.int32)}
    metrics = eng.train_batch(batch)
    assert not [k for k in metrics if k.startswith("moe/")]
    assert eng.model.transformer_config.moe_metrics is False
