"""AutoTP rule inference (coverage model: reference tests/unit/
model_parallelism/test_autotp_training.py + inference AutoTP tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.autotp import infer_tp_spec, tp_model_init
from deepspeed_tpu.topology.mesh import build_mesh, set_mesh


def test_infer_patterns():
    # llama-style
    assert infer_tp_spec("['model']['layers_0']['self_attn']['q_proj']['kernel']", (64, 64)) == P(None, "tp")
    assert infer_tp_spec("['model']['layers_0']['self_attn']['o_proj']['kernel']", (64, 64)) == P("tp", None)
    assert infer_tp_spec("['model']['layers_0']['mlp']['down_proj']['kernel']", (128, 64)) == P("tp", None)
    assert infer_tp_spec("['model']['layers_0']['mlp']['gate_proj']['kernel']", (64, 128)) == P(None, "tp")
    # gpt2-style fused qkv + bias handling
    assert infer_tp_spec("['transformer']['h_0']['attn']['c_attn']['kernel']", (64, 192)) == P(None, "tp")
    assert infer_tp_spec("['transformer']['h_0']['attn']['c_attn']['bias']", (192,)) == P("tp")
    assert infer_tp_spec("['transformer']['h_0']['attn']['c_proj']['bias']", (64,)) is None
    # bert-style attention output dense (row) vs generic dense (replicate)
    assert infer_tp_spec("['encoder']['layer_0']['attention']['output']['dense']['kernel']", (64, 64)) == P("tp", None)
    assert infer_tp_spec("['pooler']['dense']['kernel']", (64, 64)) is None
    # embeddings + head
    assert infer_tp_spec("['transformer']['wte']['embedding']", (1000, 64)) == P("tp", None)
    assert infer_tp_spec("['lm_head']['kernel']", (64, 1000)) == P(None, "tp")
    # norms replicate
    assert infer_tp_spec("['model']['norm']['weight']", (64,)) is None


def test_hf_flax_gpt2_autotp_exactness(devices):
    """Real HF flax model: AutoTP-sharded params over tp=4 must produce
    IDENTICAL logits to the unsharded model (the AutoTP correctness bar)."""
    transformers = pytest.importorskip("transformers")
    from transformers import FlaxGPT2LMHeadModel, GPT2Config

    cfg = GPT2Config(vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4)
    model = FlaxGPT2LMHeadModel(cfg, seed=0)
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 128))

    ref = np.asarray(model(ids).logits)

    mesh = build_mesh(axis_sizes={"tp": 4, "dp": 2})
    set_mesh(mesh)
    sharded = tp_model_init(model.params, mesh=mesh)

    @jax.jit
    def fwd(params, ids):
        return model(ids, params=params).logits

    got = np.asarray(fwd(sharded, jnp.asarray(ids)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
    # and the placements actually shard (not all replicated)
    flat = jax.tree_util.tree_flatten_with_path(sharded)[0]
    sharded_leaves = [k for k, v in flat
                     if any(s is not None for s in v.sharding.spec)]
    assert len(sharded_leaves) >= 8  # qkv/proj/fc kernels across 2 layers


def test_tp_model_init_uneven_vocab_falls_back(devices):
    """Vocab not divisible by tp: embedding replicates instead of erroring."""
    mesh = build_mesh(axis_sizes={"tp": 8, "dp": -1})
    set_mesh(mesh)
    params = {"wte": {"embedding": jnp.ones((127, 32))},
              "h_0": {"attn": {"c_attn": {"kernel": jnp.ones((32, 96))}}}}
    placed = tp_model_init(params, mesh=mesh)
    assert all(s is None for s in placed["wte"]["embedding"].sharding.spec)
    assert placed["h_0"]["attn"]["c_attn"]["kernel"].sharding.spec[1] == "tp"


def test_extra_rules_override(devices):
    mesh = build_mesh(axis_sizes={"tp": 2, "dp": -1})
    set_mesh(mesh)
    params = {"custom_linear": {"kernel": jnp.ones((16, 16))}}

    def my_rules(path, shape):
        if "custom_linear" in path and "kernel" in path:
            return P(None, "tp")
        return None

    placed = tp_model_init(params, mesh=mesh, extra_rules=my_rules)
    assert placed["custom_linear"]["kernel"].sharding.spec[1] == "tp"


def test_whole_name_matching_no_false_positives():
    # 'shared_expert' must NOT match the 'shared' embed pattern
    spec = infer_tp_spec("['shared_expert']['gate_proj']['kernel']", (64, 128))
    assert spec == P(None, "tp")  # column rule, not vocab sharding
    # position/token-type embeddings must replicate (not vocab-shard)
    assert infer_tp_spec("['embeddings']['position_embeddings']['embedding']", (64, 32)) is None
    assert infer_tp_spec("['embeddings']['token_type_embeddings']['embedding']", (2, 32)) is None
    # word embeddings still shard
    assert infer_tp_spec("['embeddings']['word_embeddings']['embedding']", (256, 32)) == P("tp", None)


def test_torch_layout_weights():
    """torch Linear.weight is [out, in]: specs must invert vs flax kernels."""
    assert infer_tp_spec("['self_attn']['q_proj']['weight']", (64, 32)) == P("tp", None)
    assert infer_tp_spec("['self_attn']['o_proj']['weight']", (32, 64)) == P(None, "tp")
    assert infer_tp_spec("['embed_tokens']['weight']", (256, 32)) == P("tp", None)


def test_dense_general_2d_bias_follows_heads():
    # [heads, head_dim] bias of a column layer shards heads, matching the kernel
    assert infer_tp_spec("['attn']['wq']['bias']", (4, 8)) == P("tp", None)
    assert infer_tp_spec("['attn']['wq']['kernel']", (32, 4, 8)) == P(None, "tp", None)
