"""Long-context attention: ring attention + FPDT chunking vs dense baseline
(coverage model: reference tests/unit/sequence_parallelism/test_ulysses.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import causal_attention
from deepspeed_tpu.parallel.ring_attention import ring_attention
from deepspeed_tpu.sequence import FPDTAttention, chunked_attention
from deepspeed_tpu.topology.mesh import build_mesh, set_mesh


def make_qkv(B=2, S=32, H=4, Hkv=2, D=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32)
    return q, k, v


def test_ring_attention_matches_dense(devices):
    mesh = build_mesh(axis_sizes={"sp": 8, "dp": 1})
    set_mesh(mesh)
    q, k, v = make_qkv(S=64)
    ref = causal_attention(q, k, v, impl="xla")
    got = ring_attention(q, k, v, mesh=mesh, axis="sp")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_attention_contiguous_fallback(devices):
    """S divisible by P but not 2P routes through the contiguous (non-zigzag)
    causal path with the fully-masked-hop skip — keep it covered."""
    mesh = build_mesh(axis_sizes={"sp": 4, "dp": 2})
    set_mesh(mesh)
    q, k, v = make_qkv(S=36)
    ref = causal_attention(q, k, v, impl="xla")
    got = ring_attention(q, k, v, mesh=mesh, axis="sp")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)

    # grad through this path too (the masked-hop lax.cond under
    # scan+shard_map+grad is exactly the composition that has aborted the
    # XLA CPU runtime before — keep it pinned)
    g = jax.jit(jax.grad(lambda q: ring_attention(q, k, v, mesh=mesh).sum()))(q)
    ref_g = jax.grad(lambda q: causal_attention(q, k, v, impl="xla").sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref_g), rtol=2e-4, atol=2e-4)


def test_ring_attention_jits_in_train_context(devices):
    """ring_attention must compose under jit + grad (training usage)."""
    mesh = build_mesh(axis_sizes={"sp": 4, "dp": 2})
    set_mesh(mesh)
    q, k, v = make_qkv(S=32)

    def loss(q):
        return ring_attention(q, k, v, mesh=mesh).sum()

    g = jax.jit(jax.grad(loss))(q)
    ref_g = jax.grad(lambda q: causal_attention(q, k, v, impl="xla").sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref_g), rtol=2e-4, atol=2e-4)


def test_chunked_attention_matches_dense():
    q, k, v = make_qkv(S=64)
    ref = causal_attention(q, k, v, impl="xla")
    got = chunked_attention(q, k, v, chunk_size=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_chunked_attention_non_causal_and_offset():
    q, k, v = make_qkv(S=32)
    # non-causal: every query sees all keys
    got = chunked_attention(q, k, v, chunk_size=8, causal=False)
    qg = q.astype(jnp.float32) * (q.shape[-1] ** -0.5)
    kv_rep = jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qg, kv_rep[0].astype(jnp.float32))
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), kv_rep[1])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)
    # offset: a later query chunk against the full cache == slice of dense
    full_q, _, _ = make_qkv(S=32)
    ref_c = causal_attention(full_q, k, v, impl="xla")
    tail = chunked_attention(full_q[:, 16:], k, v, chunk_size=8, q_offset=16)
    np.testing.assert_allclose(np.asarray(tail), np.asarray(ref_c[:, 16:]), rtol=2e-5, atol=2e-5)


def test_fpdt_host_offload_matches_dense():
    q, k, v = make_qkv(S=64)
    ref = np.asarray(causal_attention(q, k, v, impl="xla"))
    fp = FPDTAttention(q_chunk=16, kv_chunk=16)
    got = fp(np.asarray(q), np.asarray(k), np.asarray(v))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_fpdt_longer_than_typical_hbm_tile():
    """A long sequence runs in small tiles (memory never holds S x S)."""
    q, k, v = make_qkv(B=1, S=512, H=2, Hkv=1, D=4, seed=3)
    fp = FPDTAttention(q_chunk=64, kv_chunk=64)
    got = fp(np.asarray(q), np.asarray(k), np.asarray(v))
    ref = np.asarray(causal_attention(q, k, v, impl="xla"))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_causal_lm_with_ring_sp(devices):
    """The flagship model trains with sp_impl='ring' and matches the ulysses
    trajectory (same math, different comm pattern)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import TransformerConfig, causal_lm_spec

    outs = {}
    for sp_impl in ("ulysses", "ring"):
        cfg = TransformerConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                                num_layers=2, num_heads=4, num_kv_heads=2,
                                max_seq_len=64, sp_impl=sp_impl)
        e, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(cfg, example_seq_len=16),
            config={"train_micro_batch_size_per_gpu": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                    "mesh": {"dp": 2, "sp": 4}, "steps_per_print": 1000},
            seed=11,
        )
        ids = np.asarray(jax.random.randint(jax.random.PRNGKey(0), (e.train_batch_size, 16), 0, 64))
        losses = [float(e.train_batch({"input_ids": ids})["loss"]) for _ in range(3)]
        outs[sp_impl] = losses
    np.testing.assert_allclose(outs["ring"], outs["ulysses"], rtol=2e-4)


def test_fpdt_chunk_major_zero_copy_layout(devices):
    """chunk_major=True accepts pre-chunked [n, B, C, Hkv, D] K/V (the
    zero-copy prefetch layout) and matches the strided-input path."""
    import numpy as np
    from deepspeed_tpu.sequence.fpdt import FPDTAttention

    B, S, H, D, Ck = 2, 256, 4, 16, 64
    rng = np.random.default_rng(3)
    q, k, v = (rng.standard_normal((B, S, H, D)).astype(np.float32) for _ in range(3))
    fp = FPDTAttention(q_chunk=64, kv_chunk=Ck, causal=True)
    want = fp(q, k, v)

    def cm(x):
        return np.ascontiguousarray(
            x.reshape(B, S // Ck, Ck, H, D).transpose(1, 0, 2, 3, 4))

    got = fp(q, cm(k), cm(v), chunk_major=True)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("S", [64, 52])  # zigzag (S % 2P == 0) + contiguous fallback (52 % 8 != 0)
def test_ring_attention_alibi_matches_dense(devices, S):
    """ALiBi through the ring hops: each block's bias uses its true global
    key offset (incl. the zigzag pair-select path)."""
    import numpy as np
    from deepspeed_tpu.models.transformer import alibi_slopes
    from deepspeed_tpu.ops.attention import causal_attention
    from deepspeed_tpu.parallel.ring_attention import ring_attention
    from deepspeed_tpu.topology.mesh import build_mesh, mesh_context

    B, H, D = 2, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32) for kk in ks)
    slopes = alibi_slopes(H)
    want = causal_attention(q, k, v, impl="xla", alibi_slopes=slopes)

    mesh = build_mesh(axis_sizes={"sp": 4, "dp": 2})
    with mesh_context(mesh):
        got = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, axis="sp", alibi_slopes=slopes))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_chunked_attention_alibi_matches_dense(devices):
    import numpy as np
    from deepspeed_tpu.models.transformer import alibi_slopes
    from deepspeed_tpu.ops.attention import causal_attention
    from deepspeed_tpu.sequence.fpdt import chunked_attention

    B, S, H, D = 2, 64, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32) for kk in ks)
    slopes = alibi_slopes(H)
    want = causal_attention(q, k, v, impl="xla", alibi_slopes=slopes)
    got = chunked_attention(q, k, v, chunk_size=16, alibi_slopes=slopes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)
