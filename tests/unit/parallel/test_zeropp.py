"""ZeRO++ wiring tests: the qwZ/qgZ knobs change the compiled step.

Reference: ``runtime/comm/coalesced_collectives.py:31`` (qgZ),
``zero/partition_parameters.py:1200`` (qwZ). Here both route through
``parallel/zeropp.sharded_weight_gather`` inside the train step; tests pin
(a) trajectory within quantization tolerance of the exact run, (b) comm
telemetry showing int8 (not fp32/bf16) bytes on the wire, (c) an honest
error for the unimplemented hpZ knob.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.comm import comms_logger
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
from tests.unit.parallel.partial_manual import partial_manual_xfail


def _cfg(stage=2, **zero_extra):
    return {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage, **zero_extra},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
    }


def _model():
    cfg = TransformerConfig(
        vocab_size=64, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=2, max_seq_len=32,
    )
    return causal_lm_spec(cfg, example_seq_len=16)


def _run(engine, n=3, seed=0):
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(n):
        batch = {"input_ids": rng.integers(0, 64, (engine.train_batch_size, 16), dtype=np.int32)}
        losses.append(float(engine.train_batch(batch)["loss"]))
    return losses


@pytest.mark.parametrize("stage,knobs", [
    (2, {"zero_quantized_gradients": True}),
    (3, {"zero_quantized_gradients": True, "zero_quantized_weights": True}),
    (3, {"zero_quantized_weights": True}),
])
def test_zpp_trajectory_close_to_exact(stage, knobs):
    exact, *_ = deepspeed_tpu.initialize(model=_model(), config=_cfg(stage=stage))
    zpp, *_ = deepspeed_tpu.initialize(model=_model(), config=_cfg(stage=stage, **knobs))
    l0 = _run(exact, 3)
    l1 = _run(zpp, 3)
    # int8 block quantization of comm: same trend, small error
    np.testing.assert_allclose(l0, l1, rtol=0.05)
    assert abs(l0[-1] - l1[-1]) < 0.25


def test_zpp_comm_bytes_reduced():
    """Telemetry must show the gradient reduction riding int8, not fp32."""
    comms_logger.configure(enabled=True)
    comms_logger.reset()
    try:
        zpp, *_ = deepspeed_tpu.initialize(
            model=_model(), config=_cfg(stage=2, zero_quantized_gradients=True)
        )
        _run(zpp, 1)
        rows = comms_logger.summary()
    finally:
        comms_logger.configure(enabled=False)
        comms_logger.reset()
    a2a = [r for r in rows if r["op"] == "all_to_all"]
    assert a2a, f"no all_to_all telemetry recorded: {[r['op'] for r in rows]}"
    # int8 payload: bytes == numel (1 byte/elem); fp32 would be 4x. Each
    # sharded leaf contributes numel int8 values + fp32 scales (1/2048th).
    total_a2a = sum(r["total_bytes"] for r in a2a)
    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(zpp.state.params)
    )
    assert total_a2a < 2 * n_params, (total_a2a, n_params)


def test_hpz_with_quantized_collectives_raises():
    """hpZ itself is implemented (tests/unit/runtime/test_hpz.py); the
    unimplemented COMPOSITION with qwZ/qgZ must still fail loudly."""
    with pytest.raises(NotImplementedError, match="hpZ"):
        deepspeed_tpu.initialize(
            model=_model(),
            config=_cfg(stage=3, zero_hpz_partition_size=2, zero_quantized_weights=True),
        )


def test_zpp_parity_path_uses_quantized_comm():
    """forward/backward/step must ride the same quantized collectives."""
    zpp, *_ = deepspeed_tpu.initialize(
        model=_model(), config=_cfg(stage=2, zero_quantized_gradients=True)
    )
    rng = np.random.default_rng(1)
    batch = {"input_ids": rng.integers(0, 64, (zpp.train_batch_size, 16), dtype=np.int32)}
    comms_logger.configure(enabled=True)
    comms_logger.reset()
    try:
        zpp.backward(batch=batch)
        zpp.step()
        rows = comms_logger.summary()
    finally:
        comms_logger.configure(enabled=False)
        comms_logger.reset()
    assert any(r["op"] == "all_to_all" for r in rows), [r["op"] for r in rows]


def test_zpp_rejects_offload_combination():
    with pytest.raises(NotImplementedError):
        deepspeed_tpu.initialize(
            model=_model(),
            config=_cfg(stage=2, zero_quantized_gradients=True,
                        offload_optimizer={"device": "cpu"}),
        )


def test_nvme_requires_path():
    with pytest.raises(ValueError):
        deepspeed_tpu.initialize(
            model=_model(),
            config=_cfg(stage=2, offload_optimizer={"device": "nvme"}),
        )


def test_qg_requires_stage2():
    with pytest.raises(ValueError):
        deepspeed_tpu.initialize(
            model=_model(), config=_cfg(stage=1, zero_quantized_gradients=True)
        )


# ------------------------------------------------------------ LoCo (round 5)

def test_loco_error_feedback_beats_plain_qgz(devices):
    """The EF property (reference all_to_all_loco_quant_reduce): repeatedly
    reducing the SAME gradient, the loco running sum tracks the exact sum with
    bounded error, while plain qgZ accumulates its quantization bias linearly."""
    from deepspeed_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.zeropp import (
        _int8_reduce_scatter_dim,
        _int8_reduce_scatter_dim_loco,
    )
    from deepspeed_tpu.topology.mesh import build_mesh

    mesh = build_mesh(axis_sizes={"dp": 8})
    rng = np.random.default_rng(0)
    g = jnp.asarray(rng.standard_normal((8, 512)), jnp.float32)  # replicated grad
    T = 8

    def plain(gl):
        # lax.scan (not a Python loop): the body compiles ONCE — the unrolled
        # form was the single slowest test in the default tier (69 s cold)
        def body(out, _):
            return out + _int8_reduce_scatter_dim(gl, 0, ("dp",), 64), ()

        out0 = jnp.zeros((gl.shape[0] // 8, gl.shape[1]), jnp.float32)
        return jax.lax.scan(body, out0, None, length=T)[0]

    def loco(gl):
        def body(carry, _):
            out, err = carry
            s, err = _int8_reduce_scatter_dim_loco(gl, err, 0, ("dp",), 1.0, 64)
            return (out + s, err), ()

        out0 = jnp.zeros((gl.shape[0] // 8, gl.shape[1]), jnp.float32)
        return jax.lax.scan(body, (out0, jnp.zeros_like(gl)), None, length=T)[0][0]

    spec = P()  # grad replicated over dp; outputs scattered on dim 0
    run = lambda f: shard_map(  # noqa: E731
        f, mesh=mesh, in_specs=(spec,), out_specs=P("dp"), check_vma=False)(g)
    exact = T * g  # mean over 8 identical replicas == g; rank r gets row r
    err_plain = float(jnp.abs(run(plain) - exact).max())
    err_loco = float(jnp.abs(run(loco) - exact).max())
    assert err_loco < 0.5 * err_plain, (err_loco, err_plain)


def test_loco_trajectory_close_to_exact():
    """Engine-level: qgZ+LoCo trains within quantization tolerance of exact,
    and the residual state actually lives in the step (nonzero after a step)."""
    exact, *_ = deepspeed_tpu.initialize(model=_model(), config=_cfg(stage=2))
    loco, *_ = deepspeed_tpu.initialize(
        model=_model(),
        config=_cfg(stage=2, zero_quantized_gradients=True,
                    loco_param={"err_beta": 0.8, "reset_T": 64}))
    l0 = _run(exact, 3)
    l1 = _run(loco, 3)
    np.testing.assert_allclose(l0, l1, rtol=0.05)
    assert abs(l0[-1] - l1[-1]) < 0.25
    assert loco.state.comm_error is not None
    max_err = max(float(jnp.abs(e).max())
                  for e in jax.tree_util.tree_leaves(loco.state.comm_error))
    assert max_err > 0, "LoCo residuals never updated — EF not wired"


def test_loco_requires_qg():
    with pytest.raises(ValueError, match="loco"):
        deepspeed_tpu.initialize(
            model=_model(),
            config=_cfg(stage=2, loco_param={"err_beta": 0.8}))


@partial_manual_xfail
def test_zpp_composes_with_ulysses_sp(devices):
    """Ulysses sharding constraints inside the ZeRO++ manual micro fn must
    name only non-manual axes (round-5 dryrun D caught the violation)."""
    model = TransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, max_seq_len=32)
    eng, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(model, example_seq_len=32),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2, "zero_quantized_gradients": True},
                "mesh": {"dp": 4, "sp": 2}, "steps_per_print": 1000})
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 64, (eng.train_batch_size, 32), dtype=np.int32)}
    loss = float(eng.train_batch(batch)["loss"])
    assert np.isfinite(loss)
