"""PipelineModule/LayerSpec API at pp>1 (reference runtime/pipe/module.py:86 +
engine.py:61: the user-facing pipeline API must execute multi-stage).

Correctness bar (round-2 verdict item 2): the SAME PipelineModule trained on a
pp=2 and a pp=4 mesh matches the pp=1 trajectory.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.pipeline import LayerSpec, PipelineModule, TiedLayerSpec

from tests.unit.parallel.partial_manual import partial_manual_xfail


V, D, B, S = 64, 16, 4, 8


def _embed_layer():
    def init(rng, batch):
        return {"w": jax.random.normal(rng, (V, D)) * 0.02}

    def apply(p, batch):
        return p["w"][batch["input_ids"]].astype(jnp.float32)

    return init, apply


def _block_layer():
    def init(rng, x):
        d = x.shape[-1]
        return {"w": jax.random.normal(rng, (d, d)) * (0.5 / np.sqrt(d))}

    def apply(p, x):
        return x + jnp.tanh(x @ p["w"])

    return init, apply


def _head_forward(p, x):
    return x @ p["w"].T


def _ce_loss(logits, labels):
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def _module(n_blocks=4):
    return PipelineModule(
        layers=[
            TiedLayerSpec("embed", _embed_layer),
            *[LayerSpec(_block_layer) for _ in range(n_blocks)],
            TiedLayerSpec("embed", _embed_layer, forward_fn=_head_forward),
        ],
        loss_fn=_ce_loss,
        example_input={"input_ids": jnp.zeros((2, S), jnp.int32)},
    )


def _config(pp):
    # Fixed global batch (32) across meshes so pp=1/2/4 trajectories are
    # comparable; the triad resolves micro = 32 / dp_world.
    return {
        "train_batch_size": 32,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "mesh": {"pp": pp, "dp": 8 // pp},
        "steps_per_print": 1000,
    }


def _run(pp, steps=4):
    engine, *_ = deepspeed_tpu.initialize(model=_module(), config=_config(pp))
    rng = np.random.default_rng(0)
    losses = []
    for i in range(steps):
        ids = rng.integers(0, V, (engine.train_batch_size, S), dtype=np.int64)
        labels = rng.integers(0, V, (engine.train_batch_size, S), dtype=np.int64)
        m = engine.train_batch({"input_ids": ids, "labels": labels})
        losses.append(float(m["loss"]))
    return losses


def test_pipeline_module_pp1_baseline(devices):
    engine, *_ = deepspeed_tpu.initialize(model=_module(), config=_config(1))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, V, (engine.train_batch_size, S), dtype=np.int64)
    labels = rng.integers(0, V, (engine.train_batch_size, S), dtype=np.int64)
    batch = {"input_ids": ids, "labels": labels}
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"no learning: {losses}"


@pytest.mark.parametrize("pp", [2, 4])
@partial_manual_xfail
def test_pipeline_module_matches_pp1(devices, pp):
    base = _run(pp=1)
    piped = _run(pp=pp)
    np.testing.assert_allclose(piped, base, rtol=2e-4, atol=2e-5)


def test_pipeline_module_needs_example_input(devices):
    mod = PipelineModule(layers=[LayerSpec(_block_layer)], loss_fn=_ce_loss)
    with pytest.raises(ValueError, match="example_input"):
        deepspeed_tpu.initialize(model=mod, config=_config(2))


def test_pipeline_module_too_few_blocks(devices):
    mod = PipelineModule(
        layers=[TiedLayerSpec("embed", _embed_layer),
                LayerSpec(_block_layer),
                TiedLayerSpec("embed", _embed_layer, forward_fn=_head_forward)],
        loss_fn=_ce_loss,
        example_input={"input_ids": jnp.zeros((2, S), jnp.int32)},
    )
    with pytest.raises(ValueError, match="contiguous run"):
        deepspeed_tpu.initialize(model=mod, config=_config(2))


@partial_manual_xfail
def test_pipeline_module_interleaved_matches_pp1(devices):
    """LayerSpec API with virtual_stages=2 on pp=2 matches the pp=1 trajectory."""
    base = _run(pp=1)

    def module_v():
        m = _module()
        m.virtual_stages = 2
        return m

    engine, *_ = deepspeed_tpu.initialize(model=module_v(), config=_config(2))
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(4):
        ids = rng.integers(0, V, (engine.train_batch_size, S), dtype=np.int64)
        labels = rng.integers(0, V, (engine.train_batch_size, S), dtype=np.int64)
        losses.append(float(engine.train_batch({"input_ids": ids, "labels": labels})["loss"]))
    np.testing.assert_allclose(losses, base, rtol=2e-4)
