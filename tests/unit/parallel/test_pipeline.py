"""Pipeline parallelism tests (reference tests/unit/runtime/pipe/).

Runs on the 8-device virtual CPU mesh. Correctness bar: the pipelined program
must produce the same loss and gradients as the unpipelined layer chain.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.topology.mesh import build_mesh, mesh_context
from deepspeed_tpu.parallel.pipeline_spmd import spmd_pipeline, pipeline_bubble_fraction
from deepspeed_tpu.parallel.pipe_schedule import (
    BackwardPass,
    ForwardPass,
    InferenceSchedule,
    TrainSchedule,
)

from tests.unit.parallel.partial_manual import partial_manual_xfail


@partial_manual_xfail
def test_spmd_pipeline_matches_sequential(devices):
    """Pipelined linear stack == sequential application (pp=4, M=4)."""
    mesh = build_mesh(axis_sizes={"pp": 4, "dp": 2})
    L, D, M, B = 8, 16, 4, 2
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (L, D, D)) * (0.5 / np.sqrt(D))
    stream = jax.random.normal(jax.random.PRNGKey(1), (M, B, D))

    def stage_fn(stage_w, x, rng):
        def body(c, wi):
            return jnp.tanh(c @ wi), None

        c, _ = jax.lax.scan(body, x, stage_w)
        return c

    out = jax.jit(lambda w, s: spmd_pipeline(stage_fn, w, s, mesh=mesh, rng=key))(w, stream)

    def sequential(x):
        for i in range(L):
            x = jnp.tanh(x @ w[i])
        return x

    expected = jax.vmap(sequential)(stream)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5)


@partial_manual_xfail
def test_spmd_pipeline_gradients(devices):
    """Gradients through the pipeline == gradients of the sequential program."""
    mesh = build_mesh(axis_sizes={"pp": 2, "dp": 4})
    L, D, M, B = 4, 8, 2, 2
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (L, D, D)) * (0.5 / np.sqrt(D))
    stream = jax.random.normal(jax.random.PRNGKey(1), (M, B, D))

    def stage_fn(stage_w, x, rng):
        c, _ = jax.lax.scan(lambda c, wi: (jnp.tanh(c @ wi), None), x, stage_w)
        return c

    def piped_loss(w):
        out = spmd_pipeline(stage_fn, w, stream, mesh=mesh, rng=key)
        return (out ** 2).mean()

    def seq_loss(w):
        def one(x):
            for i in range(L):
                x = jnp.tanh(x @ w[i])
            return x

        return (jax.vmap(one)(stream) ** 2).mean()

    g_pipe = jax.jit(jax.grad(piped_loss))(w)
    g_seq = jax.jit(jax.grad(seq_loss))(w)
    np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq), rtol=1e-4, atol=1e-5)


@partial_manual_xfail
def test_pipelined_causal_lm_matches_plain(devices):
    """Pipelined CausalLM loss/grads == plain CausalLM (same params)."""
    from deepspeed_tpu.models.transformer import (
        CausalLM,
        TransformerConfig,
        pipelined_causal_lm_loss,
    )

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=4, max_seq_len=32, dropout=0.0,
    )
    module = CausalLM(cfg)
    batch = {"input_ids": jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (4, 16)), jnp.int32)}
    params = module.init({"params": jax.random.PRNGKey(0)}, batch, train=False)["params"]

    mesh = build_mesh(axis_sizes={"pp": 2, "dp": 4})
    with mesh_context(mesh):
        rng = jax.random.PRNGKey(3)

        def plain(p):
            loss, _ = module.apply({"params": p}, batch, train=True, rngs={"dropout": rng})
            return loss

        def piped(p):
            loss, _ = pipelined_causal_lm_loss(
                p, batch, rng, config=cfg, num_microbatches=2, mesh=mesh)
            return loss

        l_plain, g_plain = jax.jit(jax.value_and_grad(plain))(params)
        l_pipe, g_pipe = jax.jit(jax.value_and_grad(piped))(params)

    np.testing.assert_allclose(float(l_pipe), float(l_plain), rtol=1e-5)
    flat_a = jax.tree_util.tree_leaves(g_plain)
    flat_b = jax.tree_util.tree_leaves(g_pipe)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=2e-4, atol=2e-5)


@partial_manual_xfail
def test_pipelined_engine_end_to_end(devices):
    """Full train step with pp=2 x dp=2 x tp=2 + ZeRO-1: loss decreases."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=4, num_kv_heads=2, max_seq_len=32,
    )
    spec = causal_lm_spec(cfg, pipeline_microbatches=2)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "mesh": {"pp": 2, "dp": 2, "tp": 2},
        "steps_per_print": 1000,
    }
    engine, *_ = deepspeed_tpu.initialize(model=spec, config=config)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 128, (engine.train_batch_size, 16), dtype=np.int32)}
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(8)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"no learning: {losses}"


def test_train_schedule_ordering():
    """Every microbatch forward precedes its backward; all M appear (parity
    check against reference TrainSchedule semantics)."""
    M, S = 4, 2
    for stage in range(S):
        sched = TrainSchedule(micro_batches=M, stages=S, stage_id=stage)
        fwd, bwd = 0, 0
        for cmds in sched:
            for c in cmds:
                if isinstance(c, ForwardPass):
                    fwd += 1
                if isinstance(c, BackwardPass):
                    bwd += 1
                    assert bwd <= fwd
        assert fwd == M and bwd == M


def test_inference_schedule_tick_mapping():
    M, S = 3, 4
    for stage in range(S):
        sched = InferenceSchedule(micro_batches=M, stages=S, stage_id=stage)
        active_ticks = [t for t, cmds in enumerate(sched) if cmds]
        assert active_ticks == [stage + m for m in range(M)]


def test_bubble_fraction():
    assert pipeline_bubble_fraction(1, 1) == 0.0
    assert abs(pipeline_bubble_fraction(7, 2) - 1 / 8) < 1e-9


@pytest.mark.parametrize("S,M", [(2, 4), (4, 6)])
def test_schedule_executor_matches_sequential(S, M):
    """EXECUTING the 1F1B instruction streams (ScheduleExecutor) reproduces
    the unpipelined model's loss and gradients — the schedules are a real,
    runnable contract, not just generators."""
    from deepspeed_tpu.parallel.pipe_executor import ScheduleExecutor

    D, B, Lps = 8, 2, 2  # layers per stage
    key = jax.random.PRNGKey(0)
    ws = [jax.random.normal(jax.random.fold_in(key, s), (Lps, D, D)) * (0.5 / np.sqrt(D))
          for s in range(S)]
    inputs = [jax.random.normal(jax.random.fold_in(key, 100 + m), (B, D)) for m in range(M)]
    targets = [jax.random.normal(jax.random.fold_in(key, 200 + m), (B, D)) for m in range(M)]

    def stage_fn(w, x):
        for i in range(Lps):
            x = jnp.tanh(x @ w[i])
        return x

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    ex = ScheduleExecutor([stage_fn] * S, ws, loss_fn)
    loss, grads = ex.run(TrainSchedule, inputs, targets)

    def ref(ws_flat):
        total = 0.0
        for m in range(M):
            x = inputs[m]
            for s in range(S):
                x = stage_fn(ws_flat[s], x)
            total = total + loss_fn(x, targets[m])
        return total / M

    ref_loss, ref_grads = jax.value_and_grad(ref)(ws)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    for g, rg in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg), rtol=1e-5, atol=1e-6)


def test_schedule_executor_buffer_safety():
    """A schedule that reuses a buffer before its backward must raise."""
    from deepspeed_tpu.parallel.pipe_executor import ScheduleExecutor

    class BadSchedule(TrainSchedule):
        @property
        def num_pipe_buffers(self):
            return 1  # too few for 1F1B steady state at S=2, M=4

    D = 4
    w = jax.random.normal(jax.random.PRNGKey(0), (1, D, D))
    xs = [jnp.ones((2, D))] * 4
    ex = ScheduleExecutor([lambda w, x: jnp.tanh(x @ w[0])] * 2, [w, w],
                          lambda y, t: jnp.mean((y - t) ** 2))
    with pytest.raises(RuntimeError, match="num_pipe_buffers|buffer"):
        ex.run(BadSchedule, xs, xs)


@partial_manual_xfail
def test_interleaved_pipeline_matches_sequential(devices):
    """Virtual-stage pipeline == sequential chain (pp=4, V=2, M=4)."""
    from deepspeed_tpu.parallel.pipeline_spmd import (
        pipeline_bubble_fraction_interleaved,
        spmd_pipeline_interleaved,
    )

    mesh = build_mesh(axis_sizes={"pp": 4, "dp": 2})
    L, D, M, B = 16, 8, 4, 2
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (L, D, D)) * (0.5 / np.sqrt(D))
    stream = jax.random.normal(jax.random.PRNGKey(1), (M, B, D))

    def stage_fn(stage_w, x, rng):
        c, _ = jax.lax.scan(lambda c, wi: (jnp.tanh(c @ wi), None), x, stage_w)
        return c

    out = jax.jit(lambda w, s: spmd_pipeline_interleaved(
        stage_fn, w, s, mesh=mesh, rng=key, virtual=2))(w, stream)

    def sequential(x):
        for i in range(L):
            x = jnp.tanh(x @ w[i])
        return x

    expected = jax.vmap(sequential)(stream)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5)
    # the whole point: bubble shrinks by V
    assert pipeline_bubble_fraction_interleaved(4, 4, 2) < pipeline_bubble_fraction(4, 4)


@partial_manual_xfail
def test_interleaved_pipeline_gradients(devices):
    from deepspeed_tpu.parallel.pipeline_spmd import spmd_pipeline_interleaved

    mesh = build_mesh(axis_sizes={"pp": 2, "dp": 4})
    L, D, M, B = 8, 8, 2, 2
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (L, D, D)) * (0.5 / np.sqrt(D))
    stream = jax.random.normal(jax.random.PRNGKey(1), (M, B, D))

    def stage_fn(stage_w, x, rng):
        c, _ = jax.lax.scan(lambda c, wi: (jnp.tanh(c @ wi), None), x, stage_w)
        return c

    def piped_loss(w):
        out = spmd_pipeline_interleaved(stage_fn, w, stream, mesh=mesh, rng=key, virtual=2)
        return (out ** 2).sum()

    def seq_loss(w):
        def one(x):
            for i in range(L):
                x = jnp.tanh(x @ w[i])
            return x
        return (jax.vmap(one)(stream) ** 2).sum()

    g1 = jax.jit(jax.grad(piped_loss))(w)
    g2 = jax.grad(seq_loss)(w)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=2e-4, atol=1e-5)


@partial_manual_xfail
def test_interleaved_causal_lm_trains(devices):
    """Full engine train step with pp=2 x V=2 virtual stages: loss decreases
    and matches the plain-pipeline loss on step 0 (same params, dropout 0)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
        num_heads=4, num_kv_heads=2, max_seq_len=32,
    )
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "mesh": {"pp": 2, "dp": 2, "tp": 2},
        "steps_per_print": 1000,
    }
    batch = {"input_ids": np.random.default_rng(0).integers(0, 128, (4, 16), dtype=np.int32)}

    losses = {}
    for v in (1, 2):
        spec = causal_lm_spec(cfg, pipeline_microbatches=2, pipeline_virtual_stages=v)
        engine, *_ = deepspeed_tpu.initialize(model=spec, config=config, seed=3)
        assert engine.train_batch_size == 4
        traj = [float(engine.train_batch(batch)["loss"]) for _ in range(4)]
        assert traj[-1] < traj[0], f"V={v}: no learning {traj}"
        losses[v] = traj

    # same params/seed => identical first-step loss across schedules
    np.testing.assert_allclose(losses[1][0], losses[2][0], rtol=1e-5)


@partial_manual_xfail
def test_pipelined_alibi_embed_norm_matches_plain(devices):
    """Pipeline execution x the round-4 model features (ALiBi + embedding
    layernorm): pp=2 trajectory equals the plain forward at equal global
    batch. Nightly tier (registered in conftest)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    common = dict(vocab_size=128, hidden_size=32, intermediate_size=64,
                  num_layers=4, num_heads=4, max_seq_len=32,
                  norm="layernorm", activation="gelu", position="alibi",
                  embed_norm=True)
    ids = np.random.default_rng(0).integers(0, 128, (16, 32), dtype=np.int32)

    def run(pp):
        spec = causal_lm_spec(TransformerConfig(**common), example_seq_len=32,
                              pipeline_microbatches=4 if pp > 1 else 0)
        engine, *_ = deepspeed_tpu.initialize(
            model=spec,
            config={"train_micro_batch_size_per_gpu": 4 if pp > 1 else 2,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 1},
                    "mesh": {"pp": pp, "dp": 8 // pp},
                    "steps_per_print": 10000, "seed": 11})
        return [float(np.asarray(engine.train_batch({"input_ids": ids})["loss"]))
                for _ in range(3)]

    np.testing.assert_allclose(run(2), run(1), rtol=2e-5, atol=2e-6)
