"""FPDT as a TRAINING feature (round-5; reference ``sequence/fpdt_layer.py:510``
``_FPDTGPUOffloadingAttentionImpl_`` backward, ``:971 FPDT_Attention``).

The custom-VJP chunked attention must (a) match dense forward AND gradients,
(b) compose into the model as ``attn_impl='fpdt'`` including under Ulysses
sp>1, (c) keep compiled fwd+bwd memory linear in S at fixed chunk size, and
(d) support the pinned-host K/V offload remat policy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import CausalLM, TransformerConfig, causal_lm_spec
from deepspeed_tpu.ops.attention import causal_attention
from deepspeed_tpu.sequence import fpdt_attention


def _qkv(B=2, S=64, H=4, Hkv=2, D=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (B, S, H, D), jnp.float32),
            jax.random.normal(ks[1], (B, S, Hkv, D), jnp.float32),
            jax.random.normal(ks[2], (B, S, Hkv, D), jnp.float32))


def _fpdt_parity_combos(combos):
    q, k, v = _qkv()
    slopes = jnp.asarray(np.geomspace(0.25, 0.004, q.shape[2]), jnp.float32)
    for causal, use_alibi in combos:
        sl = slopes if use_alibi else None

        def ref(q, k, v):
            if causal:
                return causal_attention(q, k, v, impl="xla", alibi_slopes=sl)
            from deepspeed_tpu.sequence import chunked_attention
            return chunked_attention(q, k, v, chunk_size=q.shape[1],
                                     causal=False, alibi_slopes=sl)

        def new(q, k, v):
            return fpdt_attention(q, k, v, q_chunk=16, kv_chunk=16,
                                  causal=causal, alibi_slopes=sl)

        np.testing.assert_allclose(np.asarray(new(q, k, v)),
                                   np.asarray(ref(q, k, v)),
                                   rtol=2e-5, atol=2e-5)
        sum_ref = lambda *a: ref(*a).astype(jnp.float32).sum() * 0.01  # noqa: E731
        sum_new = lambda *a: new(*a).astype(jnp.float32).sum() * 0.01  # noqa: E731
        g_ref = jax.grad(sum_ref, argnums=(0, 1, 2))(q, k, v)
        g_new = jax.jit(jax.grad(sum_new, argnums=(0, 1, 2)))(q, k, v)
        for a, b, nm in zip(g_new, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5,
                err_msg=f"d{nm} causal={causal} alibi={sl is not None}")


def test_fpdt_attention_fwd_and_grad_parity():
    """Forward + all three input grads vs dense, with GQA, causal and
    causal+ALiBi — the backward is the round-5 feature."""
    _fpdt_parity_combos([(True, False), (True, True)])


def test_fpdt_attention_noncausal_parity():
    """Non-causal chunked parity (nightly: the causal combos above exercise
    the same kernel with the strictly harder tile-skip logic)."""
    _fpdt_parity_combos([(False, False)])


# 1 layer + seq 32 (2x2 chunks of 16): the model-level test proves the
# attn_impl wiring; depth and longer scans add double-scan VJP compile time
# (the slowest test in the tier at 2 layers/4x4 chunks), not coverage —
# per-layer math is already pinned by the attention parity
_MODEL_KW = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
                 num_layers=1, num_heads=4, num_kv_heads=2, max_seq_len=32,
                 fused_ce=False)


def _loss_and_grad(cfg, ids):
    m = CausalLM(cfg)
    params = m.init(jax.random.PRNGKey(0), {"input_ids": ids}, train=False)["params"]

    def f(p):
        return m.apply({"params": p}, {"input_ids": ids}, train=False)[0]

    # jit both: eager dispatch of the chunked double-scan VJP dominates the
    # tier's wall-clock otherwise
    return jax.jit(f)(params), jax.jit(jax.grad(f))(params)


def test_fpdt_model_parity():
    """attn_impl='fpdt' trains identically to the xla path."""
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 32)), jnp.int32)
    l_ref, g_ref = _loss_and_grad(TransformerConfig(**_MODEL_KW, attn_impl="xla"), ids)
    l_new, g_new = _loss_and_grad(
        TransformerConfig(**_MODEL_KW, attn_impl="fpdt",
                          fpdt_q_chunk=16, fpdt_kv_chunk=16), ids)
    np.testing.assert_allclose(l_new, l_ref, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-6),
        g_new, g_ref)


def test_fpdt_model_host_offload_parity():
    """With fpdt_offload the q/k/v/out residuals park in host memory between
    fwd and bwd (reference host-offloaded SequenceChunk) — same math.
    Nightly tier: same model-level compile as test_fpdt_model_parity plus the
    host-transfer program; default keeps the attention-level parity + the
    no-offload model parity."""
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 32)), jnp.int32)
    l_ref, g_ref = _loss_and_grad(TransformerConfig(**_MODEL_KW, attn_impl="xla"), ids)
    # single-device jit: the host-memory residual transfers compile and the
    # math is unchanged (multi-device is blocked upstream — see
    # test_fpdt_offload_multidevice_raises)
    l_off, g_off = _loss_and_grad(
        TransformerConfig(**_MODEL_KW, attn_impl="fpdt",
                          fpdt_offload=True, fpdt_q_chunk=16, fpdt_kv_chunk=16), ids)
    np.testing.assert_allclose(l_off, l_ref, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-6),
        g_off, g_ref)


def test_fpdt_offload_multidevice_raises(devices):
    """XLA's SPMD partitioner rejects host-memory placement annotations in
    this version; the engine must say so loudly instead of dying with a
    RET_CHECK mid-compile."""
    model = TransformerConfig(vocab_size=256, hidden_size=32, intermediate_size=64,
                              num_layers=2, num_heads=4, max_seq_len=64,
                              attn_impl="fpdt", fpdt_offload=True,
                              fpdt_q_chunk=16, fpdt_kv_chunk=16)
    with pytest.raises(NotImplementedError, match="fpdt_offload"):
        deepspeed_tpu.initialize(
            model=causal_lm_spec(model, example_seq_len=64),
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "mesh": {"dp": 8}, "steps_per_print": 1000})


def test_fpdt_offload_requires_fpdt_impl():
    with pytest.raises(ValueError, match="fpdt_offload"):
        TransformerConfig(**_MODEL_KW, attn_impl="xla", fpdt_offload=True)


def test_fpdt_engine_sp2_trajectory(devices):
    """The FPDT training path under Ulysses sp=2 must reproduce the sp=1
    trajectory — long-context training composes with sequence parallelism
    (reference FPDT sits inside Ulysses; fpdt_layer.py:971)."""

    def run(mesh):
        model = TransformerConfig(vocab_size=256, hidden_size=32, intermediate_size=64,
                                  num_layers=2, num_heads=4, num_kv_heads=4,
                                  max_seq_len=64, attn_impl="fpdt",
                                  fpdt_q_chunk=16, fpdt_kv_chunk=16)
        cfg = {"train_micro_batch_size_per_gpu": 2,
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
               "mesh": mesh, "steps_per_print": 1000}
        eng, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(model, example_seq_len=64), config=cfg, seed=11)
        rng = np.random.default_rng(3)
        losses = []
        for _ in range(3):
            batch = {"input_ids": rng.integers(
                0, 256, (eng.train_batch_size, 64), dtype=np.int32)}
            losses.append(float(eng.train_batch(batch)["loss"]))
        return losses

    # same dp (=> same global batch); the second mesh folds the spare factor
    # into sp (pp=2 in the baseline is inert without pipeline microbatches)
    base = run({"dp": 4, "pp": 2})
    sp = run({"dp": 4, "sp": 2})
    np.testing.assert_allclose(sp, base, rtol=2e-4)


@pytest.mark.nightly
def test_fpdt_memory_linear_in_seq():
    """Compiled fwd+bwd peak temp bytes at fixed chunk size must scale ~O(S),
    not O(S²): the per-tile score buffer is Cq x Ck regardless of S. The
    dense xla path is the positive control (its score matrix IS O(S²))."""
    B, H, D = 1, 4, 16

    def temp_bytes(S, fpdt):
        q = jnp.zeros((B, S, H, D), jnp.float32)

        def loss(q):
            if fpdt:
                o = fpdt_attention(q, q[:, :, :H, :], q, q_chunk=128,
                                   kv_chunk=128, causal=True)
            else:
                o = causal_attention(q, q, q, impl="xla")
            return o.astype(jnp.float32).sum()

        comp = jax.jit(jax.grad(loss)).lower(q).compile()
        return comp.memory_analysis().temp_size_in_bytes

    lo, hi = 512, 2048  # 4x sequence
    r_fpdt = temp_bytes(hi, True) / max(temp_bytes(lo, True), 1)
    r_dense = temp_bytes(hi, False) / max(temp_bytes(lo, False), 1)
    # linear would be 4x, quadratic 16x; leave headroom for constant terms
    assert r_fpdt < 7, f"fpdt temp grew {r_fpdt:.1f}x over a 4x seq increase"
    assert r_dense > 9, (
        f"positive control broken: dense temp grew only {r_dense:.1f}x")
