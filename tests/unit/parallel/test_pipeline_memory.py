"""Compiled pipeline memory contract (round-3 verdict item 6).

``pipeline_spmd.py`` claims fill-drain + remat matches 1F1B's steady-state
activation memory (reference ``runtime/pipe/schedule.py:189``
``num_pipe_buffers``). The host-side simulator checks the 1F1B buffer bound;
THIS test pins the production path: compile the full fwd+bwd at M >> S and
assert the per-microbatch temp-memory slope tracks the O(1) boundary carry,
not the O(layers x activations) internal state a scan that saved everything
would keep.
"""

import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.parallel.pipeline_spmd import (
    spmd_pipeline,
    spmd_pipeline_interleaved,
)
from deepspeed_tpu.topology.mesh import build_mesh

from tests.unit.parallel.partial_manual import partial_manual_xfail


H, L, B = 64, 8, 4
PP, DP = 4, 2


def _make_stage(remat):
    def stage(params, x, rng):
        def layer(x, w):
            return jax.nn.gelu(x @ w), None

        f = lambda x: jax.lax.scan(layer, x, params)[0]  # noqa: E731
        return (jax.checkpoint(f) if remat else f)(x)

    return stage


def _temp_bytes(mesh, M, remat, virtual=1):
    params = jax.random.normal(jax.random.PRNGKey(0), (L, H, H)) * 0.1
    stream = jnp.ones((M, B, H))

    def loss(p):
        if virtual > 1:
            out = spmd_pipeline_interleaved(
                _make_stage(remat), p, stream, mesh=mesh,
                rng=jax.random.PRNGKey(1), virtual=virtual)
        else:
            out = spmd_pipeline(_make_stage(remat), p, stream, mesh=mesh,
                                rng=jax.random.PRNGKey(1))
        return (out ** 2).sum()

    comp = jax.jit(jax.grad(loss)).lower(params).compile()
    return comp.memory_analysis().temp_size_in_bytes


@pytest.mark.parametrize("virtual", [1, 2])
@partial_manual_xfail
def test_pipeline_activation_memory_is_o_of_stages_not_microbatches(devices, virtual):
    """Slope of temp bytes per extra microbatch must be a small multiple of
    the boundary carry (stream slice + ppermute buffers), NOT the per-tick
    internal activations — rematerialization is what the 1F1B-parity memory
    claim rests on, and a remat regression would only show up here."""
    mesh = build_mesh(axis_sizes={"pp": PP, "dp": DP})
    m_lo, m_hi = 8, 32
    t_lo = _temp_bytes(mesh, m_lo, remat=True, virtual=virtual)
    t_hi = _temp_bytes(mesh, m_hi, remat=True, virtual=virtual)
    slope = (t_hi - t_lo) / (m_hi - m_lo)

    # Boundary carry: one [B, H] fp32 slab (the stream rides the shard_map
    # replicated — in_specs P() — so it is NOT dp-sharded). The slope budget
    # allows the stream copies the schedule legitimately makes (padded input,
    # output buffer, their cotangents, ppermute staging) but NOT the ~L/S
    # layers' worth of saved intermediates per tick.
    carry = B * H * 4
    assert slope < 8 * carry, (
        f"temp slope {slope:.0f} B/microbatch exceeds {8 * carry} — the scan "
        "is holding per-tick internal activations (remat contract broken)")


@partial_manual_xfail
def test_pipeline_memory_positive_control_without_remat(devices):
    """The measurement itself must be able to see the failure: without
    jax.checkpoint the slope MUST blow past the rematted slope."""
    mesh = build_mesh(axis_sizes={"pp": PP, "dp": DP})
    m_lo, m_hi = 8, 32
    slope_remat = (_temp_bytes(mesh, m_hi, True) - _temp_bytes(mesh, m_lo, True)) / (m_hi - m_lo)
    slope_full = (_temp_bytes(mesh, m_hi, False) - _temp_bytes(mesh, m_lo, False)) / (m_hi - m_lo)
    assert slope_full > 2 * slope_remat, (
        f"positive control failed: no-remat slope {slope_full:.0f} should far "
        f"exceed rematted slope {slope_remat:.0f} — memory_analysis may have "
        "stopped reflecting live buffers")
