"""Ulysses sequence parallelism + MoE/expert parallelism tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import MeshConfig
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
from deepspeed_tpu.topology import build_mesh, mesh_context
from tests.unit.parallel.partial_manual import partial_manual_xfail


def _tokens(bs, seq, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, size=(bs, seq), dtype=np.int32)}


def _cfg(mesh=None, stage=0, micro=1):
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage, "param_persistence_threshold": 1},
        "steps_per_print": 1000,
    }
    if mesh:
        cfg["mesh"] = mesh
    return cfg


SP_MODEL = TransformerConfig(
    vocab_size=256, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=4, max_seq_len=64,
)


class TestUlysses:
    def test_sp_matches_dp_baseline(self, devices):
        """sp=2 sequence sharding must reproduce the non-sp trajectory."""
        e1, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(SP_MODEL), config=_cfg(mesh={"dp": 4, "pp": 2}), seed=8
        )
        e2, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(SP_MODEL), config=_cfg(mesh={"dp": 4, "sp": 2}), seed=8
        )
        l1 = [float(e1.train_batch(_tokens(4, 32, seed=60 + i))["loss"]) for i in range(3)]
        l2 = [float(e2.train_batch(_tokens(4, 32, seed=60 + i))["loss"]) for i in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_sp_with_zero3(self, devices):
        engine, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(SP_MODEL),
            config=_cfg(mesh={"dp": 2, "fsdp": 2, "sp": 2}, stage=3),
        )
        batch = _tokens(engine.train_batch_size, 32)
        losses = [float(engine.train_batch(batch)["loss"]) for _ in range(3)]
        assert losses[-1] < losses[0]

    def test_distributed_attention_class(self, devices):
        """Explicit shard_map DistributedAttention == local attention."""
        from deepspeed_tpu.ops import causal_attention
        from deepspeed_tpu.parallel.ulysses import DistributedAttention

        mesh = build_mesh(MeshConfig(dp=2, sp=4))
        B, S, H, D = 2, 16, 8, 8
        rng = jax.random.PRNGKey(0)
        q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (B, S, H, D)) for i in range(3))
        ref = causal_attention(q, k, v)
        with mesh_context(mesh):
            dist_attn = DistributedAttention(lambda q, k, v: causal_attention(q, k, v))
            out = jax.jit(dist_attn)(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=5e-5, atol=5e-6)

    def test_distributed_attention_uneven_heads_raises(self, devices):
        from deepspeed_tpu.parallel.ulysses import DistributedAttention

        mesh = build_mesh(MeshConfig(sp=8))
        with mesh_context(mesh):
            da = DistributedAttention(lambda q, k, v: q)
            with pytest.raises(ValueError, match="not divisible"):
                da(jnp.zeros((1, 8, 4, 4)), jnp.zeros((1, 8, 4, 4)), jnp.zeros((1, 8, 4, 4)))


MOE_MODEL = TransformerConfig(
    vocab_size=256, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, max_seq_len=32, num_experts=4, moe_top_k=2,
    moe_capacity_factor=2.0,
)


class TestMoE:
    def test_moe_trains(self, devices):
        engine, *_ = deepspeed_tpu.initialize(model=causal_lm_spec(MOE_MODEL), config=_cfg())
        batch = _tokens(engine.train_batch_size, 16)
        losses = [float(engine.train_batch(batch)["loss"]) for _ in range(6)]
        assert losses[-1] < losses[0]

    @partial_manual_xfail
    def test_expert_parallel_matches_dense_ep(self, devices):
        """ep=4 sharded experts must reproduce the ep=1 trajectory."""
        e1, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(MOE_MODEL), config=_cfg(mesh={"dp": 2, "pp": 4}), seed=13
        )
        e2, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(MOE_MODEL), config=_cfg(mesh={"dp": 2, "ep": 4}), seed=13
        )
        l1 = [float(e1.train_batch(_tokens(2, 16, seed=80 + i))["loss"]) for i in range(3)]
        l2 = [float(e2.train_batch(_tokens(2, 16, seed=80 + i))["loss"]) for i in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)
        # expert weights actually sharded over ep
        w = e2.state.params["layers"]["moe"]["experts"]["w_up"]
        assert "ep" in str(w.sharding.spec), w.sharding.spec

    def test_gating_capacity_and_aux(self):
        from deepspeed_tpu.parallel.moe import top_k_gating

        T, E, C = 32, 4, 8
        logits = jax.random.normal(jax.random.PRNGKey(0), (T, E))
        l_aux, combine, dispatch, counts = top_k_gating(logits, 2, C, drop_tokens=True, use_rts=False)
        assert combine.shape == (T, E, C)
        assert dispatch.shape == (T, E, C)
        # capacity respected
        assert int(dispatch.sum(axis=(0,))[..., :].max()) <= C
        per_slot = dispatch.sum(axis=0)  # [E, C] tokens per slot
        assert float(per_slot.max()) <= 1.0 + 1e-6  # one token per slot
        assert float(l_aux) > 0
        # combine weights normalized per token: sum to 1 (kept) or 0 (dropped)
        w = np.asarray(combine.sum(axis=(1, 2)))
        assert np.all(np.isclose(w, 1.0, atol=1e-5) | np.isclose(w, 0.0)), w

    def test_top1_gating(self):
        from deepspeed_tpu.parallel.moe import top_k_gating

        logits = jax.random.normal(jax.random.PRNGKey(1), (16, 4))
        l_aux, combine, dispatch, counts = top_k_gating(logits, 1, 8, use_rts=False)
        # each token goes to at most one expert slot
        assert float(dispatch.sum(axis=(1, 2)).max()) <= 1.0 + 1e-6

    def test_no_drop_tokens_keeps_everything(self):
        from deepspeed_tpu.parallel.moe import top_k_gating

        # all tokens prefer expert 0: without drops, every token must be kept
        logits = jnp.tile(jnp.array([[10.0, 0.0, 0.0, 0.0]]), (16, 1))
        l_aux, combine, dispatch, counts = top_k_gating(
            logits, 1, capacity=2, drop_tokens=False, use_rts=False
        )
        w = np.asarray(combine.sum(axis=(1, 2)))
        assert np.all(np.isclose(w, 1.0, atol=1e-5)), w

    def test_unknown_gate_policy_raises(self, devices):
        from deepspeed_tpu.parallel.moe import MoEConfig, MoELayer

        layer = MoELayer(MoEConfig(num_experts=2, noisy_gate_policy="bogus"), 8, 16, train=True)
        with pytest.raises(ValueError, match="noisy_gate_policy"):
            layer.init(
                {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
                jnp.zeros((1, 4, 8)),
            )


# ----------------------------------------------------------------- PR-MoE

def test_pr_moe_residual_trains(devices):
    """PR-MoE residual expert + coefficient gate (reference moe/layer.py
    use_residual): trains, and the residual params exist."""
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                            num_layers=2, num_heads=2, max_seq_len=16,
                            num_experts=4, moe_top_k=1, moe_use_residual=True)
    e, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=8),
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "mesh": {"dp": 4, "ep": 2}, "steps_per_print": 1000})
    p = e.state.params["layers"]["moe"]
    assert "residual_mlp" in p and "coefficient" in p
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 64, (8, 8), dtype=np.int32)}
    losses = [float(e.train_batch(batch)["loss"]) for _ in range(5)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses


def test_pyramid_moe_per_layer_experts(devices):
    """Pyramid expert counts per layer (dense -> 2 -> 4), scan disabled."""
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
    import pytest as _pytest

    cfg = TransformerConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                            num_layers=3, num_heads=2, max_seq_len=16,
                            moe_layer_experts=(0, 2, 4), scan_layers=False)
    e, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=8),
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "mesh": {"dp": 4, "ep": 2}, "steps_per_print": 1000})
    p = e.state.params
    assert "mlp" in p["layer_0"] and "moe" not in p["layer_0"]
    assert p["layer_1"]["moe"]["experts"]["w_up"].shape[0] == 2
    assert p["layer_2"]["moe"]["experts"]["w_up"].shape[0] == 4
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 64, (8, 8), dtype=np.int32)}
    losses = [float(e.train_batch(batch)["loss"]) for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses

    # pyramid + scan is rejected with a clear error
    bad = TransformerConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                            num_layers=3, num_heads=2, max_seq_len=16,
                            moe_layer_experts=(0, 2, 4), scan_layers=True)
    with _pytest.raises(ValueError, match="scan_layers=False"):
        deepspeed_tpu.initialize(
            model=causal_lm_spec(bad, example_seq_len=8),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                    "steps_per_print": 1000})


@partial_manual_xfail
def test_alibi_model_under_sp_matches_dp(devices):
    """Bloom-style ALiBi + Ulysses sequence parallelism: the sharding-
    constraint form keeps the program global SPMD, so the per-head slope
    bias partitions with the head axis — sp=2 must reproduce the pure-dp
    trajectory."""
    import numpy as np
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                            num_layers=2, num_heads=4, max_seq_len=64,
                            norm="layernorm", activation="gelu",
                            position="alibi", embed_norm=True)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (8, 32), dtype=np.int32)

    def run(mesh_axes, gas):
        engine, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(cfg, example_seq_len=32),
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": gas,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 1}, "mesh": mesh_axes,
                    "steps_per_print": 10000, "seed": 7})
        losses = []
        for _ in range(3):
            m = engine.train_batch({"input_ids": ids[: engine.train_batch_size]})
            losses.append(float(np.asarray(m["loss"])))
        return losses

    # equal GLOBAL batch (8 rows, same data): dp=8 gas=1 vs sp2 x dp4 gas=2
    l_dp = run({"dp": 8}, gas=1)
    l_sp = run({"sp": 2, "dp": 4}, gas=2)
    np.testing.assert_allclose(l_sp, l_dp, rtol=2e-5, atol=2e-6)


class TestMoETPComposition:
    """ISSUE 15: ep x tp meshes route the MoE block through the explicit
    collective token dispatch (parallel/moe.py collective_moe_apply) instead
    of the old loud refusal at runtime/engine.py."""

    def test_collective_dispatch_matches_gspmd_on_ep_mesh(self, devices):
        """Forced collective dispatch reproduces the verified GSPMD ep-only
        trajectory on the SAME mesh — the correctness pin for the shard_map
        + facade all_to_all region itself (no cross-mesh init confounds)."""
        coll = TransformerConfig(**{**MOE_MODEL.__dict__,
                                    "moe_dispatch": "collective"})
        e1, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(MOE_MODEL), config=_cfg(mesh={"dp": 2, "ep": 4}),
            seed=13)
        e2, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(coll), config=_cfg(mesh={"dp": 2, "ep": 4}),
            seed=13)
        l1 = [float(e1.train_batch(_tokens(2, 16, seed=70 + i))["loss"])
              for i in range(3)]
        l2 = [float(e2.train_batch(_tokens(2, 16, seed=70 + i))["loss"])
              for i in range(3)]
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_ep_tp_trains_and_matches_global_math(self, devices):
        """Acceptance: dp2 x ep2 x tp2 trains end-to-end, and the collective
        dispatch on that mesh reproduces the GLOBAL (1-device) math of the
        same loss on the engine's own trained params — the direct
        mis-routing pin (the GSPMD constraint path the engine used to
        refuse deviates ~0.5% here; the collective region must not).
        Cross-mesh trajectory comparison is impossible at identical params
        (sharded init draws per-shard RNG), so the reference is a replay,
        not a second engine."""
        from deepspeed_tpu.topology import mesh as mesh_mod
        from deepspeed_tpu.topology.mesh import set_mesh

        e2, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(MOE_MODEL),
            config=_cfg(mesh={"dp": 2, "ep": 2, "tp": 2}, micro=2), seed=21)
        assert e2.train_batch_size == 4
        l2 = [float(e2.train_batch(_tokens(4, 16, seed=90 + i))["loss"])
              for i in range(6)]
        assert l2[-1] < l2[0]  # end-to-end: the composition actually learns
        w = e2.state.params["layers"]["moe"]["experts"]["w_up"]
        assert "ep" in str(w.sharding.spec), w.sharding.spec
        # replay: same loss fn, same params, same rng — once through the
        # ep x tp collective dispatch, once as plain global math
        host = jax.device_get(e2.state.params)
        batch = _tokens(4, 16, seed=99)
        rng = jax.random.PRNGKey(7)
        set_mesh(e2.mesh)
        mesh_loss = float(jax.jit(e2.model.loss_fn)(host, batch, rng)[0])
        mesh_mod._ACTIVE_MESH = None  # no mesh: the unsharded reference
        global_loss = float(jax.jit(e2.model.loss_fn)(host, batch, rng)[0])
        np.testing.assert_allclose(mesh_loss, global_loss, rtol=1e-5)

    def test_ep_tp_int8_wire_bounded(self, devices):
        """The quantized dispatch wire (moe_wire_codec='int8') on the
        ep x tp mesh stays within a pinned bound of the exact wire — and
        still learns."""
        q = TransformerConfig(**{**MOE_MODEL.__dict__,
                                 "moe_wire_codec": "int8"})
        e1, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(MOE_MODEL),
            config=_cfg(mesh={"dp": 2, "ep": 2, "tp": 2}), seed=33)
        e2, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(q),
            config=_cfg(mesh={"dp": 2, "ep": 2, "tp": 2}), seed=33)
        l1 = [float(e1.train_batch(_tokens(2, 16, seed=40 + i))["loss"])
              for i in range(4)]
        l2 = [float(e2.train_batch(_tokens(2, 16, seed=40 + i))["loss"])
              for i in range(4)]
        np.testing.assert_allclose(l2, l1, rtol=0.05)  # quantization-bounded
        assert np.isfinite(l2).all()

    def test_ep_tp_unservable_shape_fails_loudly(self, devices):
        """The old blanket NotImplementedError is gone; what remains loud is
        a genuinely unservable ep x tp shape (experts not divisible by ep)
        — it must raise at trace time, never silently mis-route."""
        bad = TransformerConfig(**{**MOE_MODEL.__dict__, "num_experts": 3})
        with pytest.raises(ValueError, match="collective token dispatch"):
            engine, *_ = deepspeed_tpu.initialize(
                model=causal_lm_spec(bad),
                config=_cfg(mesh={"dp": 2, "ep": 2, "tp": 2}))
            engine.train_batch(_tokens(engine.train_batch_size, 16))
