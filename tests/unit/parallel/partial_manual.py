"""Shared xfail marker for pipeline tests hitting the upstream
partial-manual shard_map bug.

Partial-manual shard_map (manual subset of >1-sized mesh axes) is broken on
this jax 0.4.37/XLA — the SPMD partitioner rejects the PartitionId
instruction that pipeline_spmd's ppermute lowering emits under ``auto=``
(see CHANGES PR 2). xfail(strict=False) keeps tier-1 green on the known bug
while still surfacing any *new* failure mode in the marked tests. Delete
this module (and the marks) when the jax/XLA stack is upgraded past the bug.
"""

import pytest

partial_manual_xfail = pytest.mark.xfail(
    strict=False,
    reason="upstream jax 0.4.37/XLA: PartitionId unsupported under partial-manual shard_map",
)
