"""Shared knowledge of the upstream partial-manual shard_map bug.

Partial-manual shard_map (manual subset of >1-sized mesh axes) is broken on
this jax 0.4.37/XLA — the SPMD partitioner rejects the PartitionId
instruction that pipeline_spmd's ppermute lowering emits under ``auto=``
(see CHANGES PR 2). Everything that must recognize the bug imports it from
here: the pytest xfail marker for the pipeline tests, and
``__graft_entry__``'s dryrun A, which skips-with-reason instead of failing
the whole multichip sweep on a known-upstream lowering hole. Delete this
module (and both call sites) when the jax/XLA stack is upgraded past the
bug.

The classifier and reason string live ABOVE the pytest import on purpose:
``__graft_entry__`` runs outside the test harness and must not require
pytest to be importable.
"""

PARTIAL_MANUAL_REASON = (
    "upstream jax 0.4.37/XLA: PartitionId unsupported under partial-manual "
    "shard_map"
)


def is_partition_id_error(exc: BaseException) -> bool:
    """True when ``exc`` is the upstream PartitionId lowering failure.

    XLA surfaces it as a generic error type whose message names the
    rejected instruction, so classification is by message — checked against
    ``type: message`` so a hypothetical exception TYPE named PartitionId
    would also match.
    """
    return "PartitionId" in f"{type(exc).__name__}: {exc}"


try:
    import pytest
except ImportError:  # pragma: no cover - non-test consumers (dryrun entry)
    pytest = None

if pytest is not None:
    partial_manual_xfail = pytest.mark.xfail(
        strict=False, reason=PARTIAL_MANUAL_REASON)
