"""Mesh/topology tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.config import MeshConfig
from deepspeed_tpu.topology import (
    MESH_AXES,
    ProcessTopology,
    batch_pspec,
    build_mesh,
    get_data_parallel_world_size,
    get_world_size,
    mesh_context,
    resolve_axis_sizes,
    topology_from_mesh,
)


def test_resolve_axis_sizes_wildcard():
    sizes = resolve_axis_sizes({"dp": -1, "tp": 2}, 8)
    assert sizes["dp"] == 4 and sizes["tp"] == 2
    assert np.prod([sizes[a] for a in MESH_AXES]) == 8


def test_resolve_axis_sizes_exact():
    sizes = resolve_axis_sizes({"dp": 2, "fsdp": 4}, 8)
    assert sizes["dp"] == 2 and sizes["fsdp"] == 4


def test_resolve_axis_sizes_errors():
    with pytest.raises(ValueError):
        resolve_axis_sizes({"dp": -1, "tp": -1}, 8)
    with pytest.raises(ValueError):
        resolve_axis_sizes({"dp": 3}, 8)
    with pytest.raises(ValueError):
        resolve_axis_sizes({"dp": -1, "tp": 3}, 8)


def test_build_mesh_default(devices):
    mesh = build_mesh()
    assert mesh.size == 8
    assert mesh.shape["dp"] == 8
    assert mesh.axis_names == MESH_AXES


def test_build_mesh_from_config(devices):
    mesh = build_mesh(MeshConfig(dp=-1, fsdp=2, tp=2))
    assert mesh.shape["fsdp"] == 2
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp"] == 2


def test_world_size_helpers(devices):
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, sp=2, tp=1))
    with mesh_context(mesh):
        assert get_world_size() == 8
        assert get_data_parallel_world_size() == 4  # dp * fsdp


def test_batch_pspec(devices):
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, sp=2))
    with mesh_context(mesh):
        spec = batch_pspec()
        assert spec == PartitionSpec(("dp", "fsdp"), "sp")
    mesh2 = build_mesh(MeshConfig(dp=-1))
    with mesh_context(mesh2):
        assert batch_pspec() == PartitionSpec(("dp",))


def test_sharded_array_roundtrip(devices):
    """A batch sharded over the mesh reassembles to the original array."""
    mesh = build_mesh(MeshConfig(dp=4, fsdp=2))
    x = jnp.arange(16 * 4, dtype=jnp.float32).reshape(16, 4)
    sharded = jax.device_put(x, NamedSharding(mesh, PartitionSpec(("dp", "fsdp"))))
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(x))
    # psum over data axes equals global sum
    total = jax.jit(lambda a: a.sum())(sharded)
    assert float(total) == float(x.sum())


def test_process_topology_roundtrip():
    topo = ProcessTopology(["pp", "dp", "tp"], [2, 2, 2])
    assert topo.world_size == 8
    for rank in range(8):
        assert topo.get_rank(**topo.get_coord(rank)) == rank
    assert topo.filter_match(pp=0) == [0, 1, 2, 3]
    assert topo.get_dim("dp") == 2


def test_topology_from_mesh(devices):
    mesh = build_mesh(MeshConfig(dp=4, tp=2))
    topo = topology_from_mesh(mesh)
    assert topo.world_size == 8
    assert topo.get_dim("tp") == 2
