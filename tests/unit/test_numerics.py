"""Numerics observatory (telemetry/numerics.py) — ISSUE 17 acceptance.

Pinned here:
  - an injected single-replica bit flip (``FaultInjector.flip_param_bit``)
    fires the divergence sentinel within ONE sampled step, names the
    offending param group, and latches the event in the carried state
  - the ``abort`` policy raises ``TrainingHealthError`` from the host hook
  - disabled mode is jaxpr-identical: the engine update program with the
    numerics block absent, explicitly disabled, and enabled-without-sentinel
    all trace to the same jaxpr (probes are standalone dispatches)
  - the whole-tree xor digest checksum is bit-stable across mesh shapes
    (the fleet heartbeat's cross-process comparator contract)
  - wire-fidelity probes cover every routed lossy codec and sit under the
    pinned per-codec bounds; drift vs those bounds warns + counts + arms
  - the forced-lossy-codec grad-mean warning fires once at trace time
  - serving probes (KV dequant / WOQ matmul / spec-accept trend alarm)
  - the ``numerics`` perf-ledger suite is headline-gated by the PR-16 gate
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.collectives import selector
from deepspeed_tpu.diagnostics.faultinject import FaultInjector
from deepspeed_tpu.diagnostics.manager import TrainingHealthError
from deepspeed_tpu.telemetry import get_tracer
from deepspeed_tpu.telemetry import numerics
from deepspeed_tpu.utils.compat import shard_map
from tests.unit.simple_model import random_batch, simple_model_spec


@pytest.fixture(autouse=True)
def _reset():
    numerics.configure(enabled=False)
    selector.configure()
    tr = get_tracer()
    tr.configure(enabled=False)
    tr.reset()
    yield
    numerics.configure(enabled=False)
    selector.configure()
    get_tracer().configure(enabled=False)
    get_tracer().reset()


@pytest.fixture
def dslog():
    lg = logging.getLogger("deepspeed_tpu")
    prev = lg.propagate
    lg.propagate = True
    yield lg
    lg.propagate = prev


def _engine(num=None, extra=None):
    eng, *_ = deepspeed_tpu.initialize(
        model=simple_model_spec(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 10_000,
            **({"numerics": num} if num else {}),
            **(extra or {}),
        },
    )
    return eng


def _step(eng, seed=0):
    return eng.train_batch(batch=random_batch(eng.train_batch_size, seed=seed))


# ----------------------------------------------------------- sentinel: engine
def test_bit_flip_fires_sentinel_within_one_sampled_step(dslog, caplog):
    eng = _engine({"enabled": True, "sample_every": 1,
                   "sentinel_sample_every": 1})
    assert eng.state.numerics is not None
    for s in range(2):
        _step(eng, seed=s)
    obs = numerics.get_observatory()
    assert obs.divergence_events_seen == 0
    FaultInjector().flip_param_bit(eng)
    with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
        m = _step(eng, seed=9)
    assert obs.divergence_events_seen == 1
    fetched = jax.device_get(
        {k: v for k, v in m.items() if k.startswith("numerics/")})
    assert int(fetched["numerics/diverged"]) == 1
    assert int(fetched["numerics/divergence_events"]) == 1
    # the offending top-level group is named; the untouched ones are clean
    flagged = {k: int(v) for k, v in fetched.items()
               if k.startswith("numerics/diverged/")}
    assert sum(flagged.values()) == 1
    assert any("NUMERICS DIVERGENCE" in r.message for r in caplog.records)


def test_clean_run_raises_zero_alarms():
    eng = _engine({"enabled": True, "sample_every": 1,
                   "sentinel_sample_every": 1})
    for s in range(4):
        m = _step(eng, seed=s)
    obs = numerics.get_observatory()
    assert obs.divergence_events_seen == 0
    assert obs.wire_drift_events == 0
    assert int(jax.device_get(m["numerics/divergence_events"])) == 0
    assert int(jax.device_get(m["numerics/checked"])) == 4


def test_abort_policy_raises_training_health_error():
    eng = _engine({"enabled": True, "sample_every": 1,
                   "sentinel_sample_every": 1,
                   "divergence_policy": "abort"})
    _step(eng, seed=0)
    FaultInjector().flip_param_bit(eng)
    with pytest.raises(TrainingHealthError) as ei:
        _step(eng, seed=1)
    assert "numerics divergence" in str(ei.value)
    assert ei.value.verdicts["numerics/divergence_events"] >= 1


def test_identical_corruption_on_all_replicas_is_invisible():
    """The sentinel detects REPLICA DISAGREEMENT, not bad values: a fault
    every replica applies identically keeps the digests equal — that
    failure class belongs to the health probes. Pinned at sentinel level
    (a mid-training engine-state device_put swap is not cache-hermetic on
    the forced-CPU harness)."""
    mesh = _mesh((4, 2))
    params = jax.device_put({"w": np.ones((8, 8), np.float32) * 1.5},
                            {"w": NamedSharding(mesh, P())})
    _st, m = _digest_params(mesh, {"w": P()}, params)
    assert int(m["numerics/diverged"]) == 0


# ------------------------------------------------------------ program identity
def test_disabled_mode_is_jaxpr_identical():
    """THE structural acceptance: the traced update program with the
    numerics block absent, explicitly disabled, and enabled WITHOUT the
    sentinel is one and the same jaxpr — wire probes are standalone
    dispatches, never ops inside the step."""

    def update_jaxpr(eng):
        state = eng.state
        grads = jax.tree_util.tree_map(jnp.zeros_like, state.params)

        def fn(s, g):
            return eng._update_math(s, g, s.rng, grads_are_unscaled=True)

        return str(jax.make_jaxpr(fn)(state, grads))

    j_absent = update_jaxpr(_engine())
    j_off = update_jaxpr(_engine({"enabled": False}))
    j_probes_only = update_jaxpr(
        _engine({"enabled": True, "sentinel": False, "sample_every": 4}))
    assert j_absent == j_off == j_probes_only
    # and the sentinel DOES change the program when armed (the cond + digest)
    j_sentinel = update_jaxpr(
        _engine({"enabled": True, "sentinel_sample_every": 4}))
    assert j_sentinel != j_absent


def test_disabled_engine_keeps_state_numerics_none():
    eng = _engine()
    assert eng.state.numerics is None
    assert eng._numerics is None
    assert eng._numerics_sentinel is None


# --------------------------------------------------------------- digest math
def _mesh(shape):
    return Mesh(np.array(jax.devices()[:8]).reshape(*shape), ("dp", "fsdp"))


def _digest_params(mesh, specs, params, sample_every=1):
    sent = numerics.DivergenceSentinel(mesh, specs, sample_every=sample_every)
    st = jax.device_put(sent.init_state(), NamedSharding(mesh, P()))

    @jax.jit
    def step(st, p):
        return sent.probe(st, p, jnp.zeros((), jnp.int32))

    new_st, metrics = step(st, params)
    return new_st, jax.device_get(metrics)


def test_digest_checksum_bit_stable_across_mesh_shapes():
    """The fleet comparator contract: the whole-tree xor checksum is the
    SAME number on a 4x2 and a 2x4 mesh over the same params (sum-of-squares
    folds would not be — xor is order-independent and exact)."""
    host = {"blk": {"w": np.arange(64, dtype=np.float32).reshape(8, 8) / 7.0},
            "head": {"b": np.linspace(-1, 1, 16, dtype=np.float32)}}
    specs = {"blk": {"w": P("fsdp", None)}, "head": {"b": P()}}
    cks = []
    for shape in ((4, 2), (2, 4)):
        mesh = _mesh(shape)
        sharding = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))
        params = jax.device_put(host, sharding)
        st, m = _digest_params(mesh, specs, params)
        assert int(m["numerics/diverged"]) == 0
        cks.append(int(np.uint32(jax.device_get(st.checksum))))
    assert cks[0] == cks[1]


def test_sentinel_detects_single_replica_flip_on_sharded_leaf():
    """A leaf sharded over fsdp but replicated over dp: flipping one dp
    replica's copy of one shard must still trip the comparator."""
    mesh = _mesh((4, 2))
    specs = {"w": P("fsdp", None)}
    sharding = {"w": NamedSharding(mesh, P("fsdp", None))}
    params = jax.device_put(
        {"w": np.ones((8, 8), np.float32)}, sharding)
    leaf = params["w"]
    shards = [np.array(np.asarray(s.data), copy=True)
              for s in leaf.addressable_shards]
    shards[0].view(np.uint32).flat[0] ^= np.uint32(1 << 18)
    bufs = [jax.device_put(s, sh.device)
            for s, sh in zip(shards, leaf.addressable_shards)]
    bad = jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, bufs)
    _st, m = _digest_params(mesh, specs, {"w": bad})
    assert int(m["numerics/diverged"]) == 1


def test_sentinel_cond_skips_unsampled_steps():
    mesh = _mesh((4, 2))
    params = jax.device_put({"w": np.ones((8,), np.float32)},
                            {"w": NamedSharding(mesh, P())})
    sent = numerics.DivergenceSentinel(mesh, {"w": P()}, sample_every=4)
    st = jax.device_put(sent.init_state(), NamedSharding(mesh, P()))

    @jax.jit
    def step(st, p, i):
        return sent.probe(st, p, i)

    for i in range(8):
        st, _m = step(st, params, jnp.int32(i))
    assert int(jax.device_get(st.checked)) == 2  # steps 0 and 4


# ---------------------------------------------------------------- wire probes
LOSSY = sorted(numerics.LOSSY_CODECS)


def test_wire_probes_cover_every_routed_lossy_codec():
    obs = numerics.configure(enabled=True, sample_every=1)
    for codec in LOSSY:
        obs.note_route("all_gather", "ring", codec, 4096 * 4, 4, 8, "dp",
                       "float32", block_size=64)
    out = obs.sample_now()
    assert set(out) == {f"all_gather/{c}" for c in LOSSY}
    for codec in LOSSY:
        rel = out[f"all_gather/{codec}"]
        assert 0.0 < rel < numerics.WIRE_REL_ERR_BOUNDS[codec], (codec, rel)
    # the labelled histogram landed in the registry
    snap = get_tracer().registry.snapshot()
    assert any(k.startswith("numerics/wire_rel_err") for k in snap)


def test_exact_codecs_are_not_probed():
    obs = numerics.configure(enabled=True, sample_every=1)
    obs.note_route("all_reduce", "ring", "none", 4096, 4, 8, "dp", "float32")
    obs.note_route("all_reduce", "ring", "fp32", 4096, 4, 8, "dp", "float32")
    assert obs.routes() == []
    assert obs.sample_now() == {}


def test_wire_drift_warns_counts_and_arms(dslog, caplog):
    armed = []
    obs = numerics.configure(enabled=True, sample_every=1,
                             drift_ratio=1e-9)  # any real error drifts
    obs.install(profiler_arm=lambda reason: armed.append(reason))
    obs.note_route("all_gather", "ring", "int8", 4096 * 4, 4, 8, "dp",
                   "float32", block_size=64)
    with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
        obs.sample_now()
        obs.sample_now()  # second round: counts again, warns ONCE
    assert obs.wire_drift_events == 2
    drift_warnings = [r for r in caplog.records
                      if "numerics drift" in r.message]
    assert len(drift_warnings) == 1
    assert armed and armed[0].startswith("numerics_drift:")


def test_route_registration_noop_when_disabled():
    obs = numerics.configure(enabled=False)
    obs.note_route("all_gather", "ring", "int8", 4096, 4, 8, "dp", "float32")
    assert obs.routes() == []


# ----------------------------------------------------- forced-lossy grad mean
def test_facade_grad_mean_lossy_codec_warns_once(dslog, caplog):
    from deepspeed_tpu.runtime.engine import _facade_grad_mean

    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    selector.configure(facade_algorithm="ring", facade_codec="int8",
                       codecs=("int8",))

    def make():
        def f(g):
            return _facade_grad_mean(g, "dp")

        return shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                         check_vma=False)

    x = jnp.ones((8, 256), jnp.float32)
    with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
        jax.make_jaxpr(make())(x)
        jax.make_jaxpr(make())(x)  # retrace: still one warning
    warns = [r for r in caplog.records
             if "forced lossy codec" in r.message]
    assert len(warns) == 1
    # an exact wire stays quiet
    numerics.configure(enabled=False)  # reset warn-once epoch
    caplog.clear()
    selector.configure(facade_algorithm="ring", facade_codec="fp32",
                       codecs=("fp32",))
    with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
        jax.make_jaxpr(make())(x)
    assert not [r for r in caplog.records
                if "forced lossy codec" in r.message]


# -------------------------------------------------------------- serving plane
def test_kv_dequant_probe_within_pinned_bounds():
    obs = numerics.configure(enabled=True)
    rel8 = obs.kv_dequant_probe("int8", head_dim=128)
    relf8 = obs.kv_dequant_probe("fp8", head_dim=128)
    assert 0.0 < rel8 < numerics.WIRE_REL_ERR_BOUNDS["int8"]
    assert 0.0 < relf8 < numerics.WIRE_REL_ERR_BOUNDS["fp8"]
    assert obs.kv_dequant_probe(None) == 0.0


def test_woq_matmul_probe_reports_small_error():
    obs = numerics.configure(enabled=True)
    rel = obs.woq_matmul_probe("int8")
    assert 0.0 < rel < 0.05
    g = get_tracer().registry.gauges()
    assert any(k.startswith("numerics/woq_matmul_rel_err") for k in g)


def test_spec_accept_trend_alarm_fires_on_collapse(dslog, caplog):
    obs = numerics.configure(enabled=True, spec_accept_window=16,
                             spec_accept_mads=6.0, spec_accept_min_n=8)
    rng = np.random.default_rng(0)
    with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
        for _ in range(12):
            assert not obs.note_spec_accept(0.8 + rng.normal() * 0.01)
        assert obs.note_spec_accept(0.1)  # collapse
    assert obs.spec_accept_alarm.alarms == 1
    assert any("acceptance rate" in r.message for r in caplog.records)


def test_trend_alarm_needs_quorum():
    alarm = numerics.TrendAlarm(window=8, mads=6.0, min_n=4)
    assert not alarm.observe(0.9)
    assert not alarm.observe(0.0)  # only 1 prior observation: no verdict
    assert alarm.alarms == 0


# ----------------------------------------------------------------- perf gate
def test_numerics_suite_is_headline_gated():
    from deepspeed_tpu.telemetry.perfgate import GateConfig, gate_row
    from deepspeed_tpu.telemetry.perfledger import make_row

    hist = [make_row("numerics", "wire_rel_err/int8", 0.010, "rel",
                     direction="lower", method="probe", samples=1,
                     backend="cpu", round=r) for r in (1, 2, 3)]
    good = make_row("numerics", "wire_rel_err/int8", 0.0101, "rel",
                    direction="lower", method="probe", samples=1,
                    backend="cpu", round=4)
    bad = make_row("numerics", "wire_rel_err/int8", 0.10, "rel",
                   direction="lower", method="probe", samples=1,
                   backend="cpu", round=4)
    cfg = GateConfig()
    assert gate_row(good, hist, cfg).status == "ok"
    v = gate_row(bad, hist, cfg)
    assert v.status == "regression" and v.mode == "mad"


# ------------------------------------------------------------------ EF gauges
def test_ef_residual_norm_gauges():
    obs = numerics.configure(enabled=True)
    err = {"layer": {"w": jnp.full((4, 4), 0.5, jnp.float32)}}
    out = obs.note_ef_residuals(err)
    assert out and abs(out["layer"] - 2.0) < 1e-5
    g = get_tracer().registry.gauges()
    assert any(k.startswith("numerics/ef_residual_norm") for k in g)
