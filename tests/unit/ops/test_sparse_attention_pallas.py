"""Tile-skipping block-sparse attention kernel (reference
ops/sparse_attention/matmul.py:196 sdd/dsd block-skipping)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    block_sparse_attention,
    block_sparse_attention_dense,
    get_sparsity_config,
)
from deepspeed_tpu.ops.pallas.sparse_attention import layout_to_lists


def _qkv(B=2, S=64, H=2, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (B, S, H, D), jnp.float32)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("name,kw", [
    ("local", {"num_sliding_window_blocks": 2}),
    ("fixed", {"num_local_blocks": 2}),
    ("bigbird", {"num_random_blocks": 1, "num_sliding_window_blocks": 2}),
])
def test_pallas_sparse_matches_dense_masked(name, kw):
    q, k, v = _qkv()
    cfg = get_sparsity_config(name, num_heads=2, block=8, **kw)
    lay = cfg.make_layout(64)
    want = block_sparse_attention_dense(q, k, v, lay, block=8)
    got = block_sparse_attention(q, k, v, lay, block=8, impl="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_pallas_sparse_actually_skips_tiles():
    """The compute win is structural: the grid's active-column axis is the
    layout's max row population, not the full block count."""
    cfg = get_sparsity_config("local", num_heads=2, block=8, num_sliding_window_blocks=2)
    lay = cfg.make_layout(64)  # 8x8 blocks, window 2
    cols, ncols = layout_to_lists(lay)
    n = lay.shape[1]
    assert cols.shape[-1] == 2  # max 2 active columns per row
    assert cols.shape[-1] < n  # vs 8 dense tiles per row
    # executed tile fraction == layout density
    assert ncols.sum() == lay.sum()
    assert lay.sum() / (2 * n * n) < 0.3


def test_pallas_sparse_gradients_match_dense():
    q, k, v = _qkv(S=32)
    cfg = get_sparsity_config("local", num_heads=2, block=8, num_sliding_window_blocks=2)
    lay = cfg.make_layout(32)

    def loss_p(q, k, v):
        return (block_sparse_attention(q, k, v, lay, block=8, impl="pallas") ** 2).sum()

    def loss_d(q, k, v):
        return (block_sparse_attention_dense(q, k, v, lay, block=8) ** 2).sum()

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_layout_cache_eviction_safe_under_grad():
    """Backward after >_LAYOUT_CAP registrations must not KeyError: keys are
    self-describing, so evicted entries rebuild from the key."""
    from deepspeed_tpu.ops.pallas import sparse_attention as sa

    q, k, v = _qkv(S=16, H=1)

    def loss(q, k, v, lay):
        return (block_sparse_attention(q, k, v, lay, block=8, impl="pallas") ** 2).sum()

    # register one layout under grad, then churn the cache past the cap with
    # unique layouts (i encoded in the spare sub-diagonal bit pattern)
    lay0 = np.ones((1, 2, 2), dtype=np.int64)
    f = jax.vjp(lambda q: loss(q, k, v, lay0), q)[1]
    qq, kk, vv = _qkv(S=64, H=1)
    for i in range(sa._LAYOUT_CAP + 4):
        lay = np.eye(8, dtype=np.int64)[None]
        for b in range(6):
            lay[0, b + 2, b] = (i >> b) & 1
        block_sparse_attention(qq, kk, vv, lay, block=8, impl="pallas")
    key0 = (lay0.shape, lay0.dtype.str, lay0.tobytes())
    assert key0 not in sa._LAYOUTS  # really evicted
    (dq,) = f(jnp.ones(()))  # backward still works
    assert np.isfinite(np.asarray(dq)).all()
