"""Tile-skipping block-sparse attention kernel (reference
ops/sparse_attention/matmul.py:196 sdd/dsd block-skipping)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    block_sparse_attention,
    block_sparse_attention_dense,
    get_sparsity_config,
)
from deepspeed_tpu.ops.pallas.sparse_attention import layout_to_lists


def _qkv(B=2, S=64, H=2, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (B, S, H, D), jnp.float32)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("name,kw", [
    ("local", {"num_sliding_window_blocks": 2}),
    ("fixed", {"num_local_blocks": 2}),
    ("bigbird", {"num_random_blocks": 1, "num_sliding_window_blocks": 2}),
    ("variable", {"num_random_blocks": 1, "local_window_blocks": (2, 3),
                  "global_block_indices": (0,)}),
    ("bslongformer", {"num_sliding_window_blocks": 2,
                      "global_block_indices": (0, 4),
                      "global_block_end_indices": (1, 6)}),
])
def test_pallas_sparse_matches_dense_masked(name, kw):
    q, k, v = _qkv()
    cfg = get_sparsity_config(name, num_heads=2, block=8, **kw)
    lay = cfg.make_layout(64)
    want = block_sparse_attention_dense(q, k, v, lay, block=8)
    got = block_sparse_attention(q, k, v, lay, block=8, impl="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_pallas_sparse_actually_skips_tiles():
    """The compute win is structural: the grid's active-column axis is the
    layout's max row population, not the full block count."""
    cfg = get_sparsity_config("local", num_heads=2, block=8, num_sliding_window_blocks=2)
    lay = cfg.make_layout(64)  # 8x8 blocks, window 2
    cols, ncols = layout_to_lists(lay)
    n = lay.shape[1]
    assert cols.shape[-1] == 2  # max 2 active columns per row
    assert cols.shape[-1] < n  # vs 8 dense tiles per row
    # executed tile fraction == layout density
    assert ncols.sum() == lay.sum()
    assert lay.sum() / (2 * n * n) < 0.3


def test_pallas_sparse_gradients_match_dense():
    q, k, v = _qkv(S=32)
    cfg = get_sparsity_config("local", num_heads=2, block=8, num_sliding_window_blocks=2)
    lay = cfg.make_layout(32)

    def loss_p(q, k, v):
        return (block_sparse_attention(q, k, v, lay, block=8, impl="pallas") ** 2).sum()

    def loss_d(q, k, v):
        return (block_sparse_attention_dense(q, k, v, lay, block=8) ** 2).sum()

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_layout_cache_eviction_safe_under_grad():
    """Backward after >_LAYOUT_CAP registrations must not KeyError: keys are
    self-describing, so evicted entries rebuild from the key."""
    from deepspeed_tpu.ops.pallas import sparse_attention as sa

    q, k, v = _qkv(S=16, H=1)

    def loss(q, k, v, lay):
        return (block_sparse_attention(q, k, v, lay, block=8, impl="pallas") ** 2).sum()

    # register one layout under grad, then churn the cache past the cap with
    # unique layouts (i encoded in the spare sub-diagonal bit pattern)
    lay0 = np.ones((1, 2, 2), dtype=np.int64)
    f = jax.vjp(lambda q: loss(q, k, v, lay0), q)[1]
    qq, kk, vv = _qkv(S=64, H=1)
    for i in range(sa._LAYOUT_CAP + 4):
        lay = np.eye(8, dtype=np.int64)[None]
        for b in range(6):
            lay[0, b + 2, b] = (i >> b) & 1
        block_sparse_attention(qq, kk, vv, lay, block=8, impl="pallas")
    key0 = (lay0.shape, lay0.dtype.str, lay0.tobytes())
    assert key0 not in sa._LAYOUTS  # really evicted
    (dq,) = f(jnp.ones(()))  # backward still works
    assert np.isfinite(np.asarray(dq)).all()


def test_pallas_sparse_bwd_skips_tiles():
    """The backward's grids end at the layout population too: dq walks
    cols/ncols, dk/dv walk the transposed rows/nrows — both bounded by the
    max row/column population, not the block count."""
    from deepspeed_tpu.ops.pallas.sparse_attention import layout_to_lists_t

    cfg = get_sparsity_config("local", num_heads=2, block=8, num_sliding_window_blocks=2)
    lay = cfg.make_layout(64)
    rows, nrows = layout_to_lists_t(lay)
    n = lay.shape[1]
    assert rows.shape[-1] < n  # dk/dv active-row axis << dense
    assert nrows.sum() == lay.sum()  # executed tile count == live tiles
    # transposed lists really are the transpose: column ki's rows are the
    # rows qi whose col-list contains ki
    cols, ncols = layout_to_lists(lay)
    for h in range(lay.shape[0]):
        for ki in range(n):
            got = set(rows[h, ki, :nrows[h, ki]].tolist())
            want = {qi for qi in range(n) if lay[h, qi, ki]}
            assert got == want


def _grad_shapes(fn, *args):
    """All f32 buffer shapes in the compiled gradient program."""
    import re

    comp = jax.jit(jax.grad(fn, argnums=(0, 1, 2))).lower(*args).compile()
    return [tuple(map(int, m.group(1).split(",")))
            for m in re.finditer(r"f32\[([\d,]+)\]", comp.as_text())], comp


def test_pallas_sparse_bwd_memory_is_linear_in_seq():
    """No S x S score buffer anywhere in the compiled backward — the round-3
    dense-recompute fallback materialized one; the sparse kernels peak at
    O(S*block) (one [block, block] tile in VMEM at a time)."""
    S, block = 256, 8
    cfg = get_sparsity_config("local", num_heads=2, block=block,
                              num_sliding_window_blocks=2)
    lay = cfg.make_layout(S)
    q = jnp.ones((1, S, 2, 16), jnp.float32)

    def loss_sparse(q, k, v):
        return (block_sparse_attention(q, k, v, lay, block=block, impl="pallas") ** 2).sum()

    def loss_dense(q, k, v):
        return (block_sparse_attention_dense(q, k, v, lay, block=block) ** 2).sum()

    def has_sq(shapes):
        return any(sum(d >= S for d in shp) >= 2 for shp in shapes)

    sparse_shapes, _ = _grad_shapes(loss_sparse, q, q, q)
    dense_shapes, _ = _grad_shapes(loss_dense, q, q, q)
    assert has_sq(dense_shapes), "positive control: dense path should materialize SxS"
    assert not has_sq(sparse_shapes), f"SxS buffer in sparse bwd: {sparse_shapes}"


def test_pallas_sparse_gradients_match_dense_noncausal():
    q, k, v = _qkv(S=32)
    cfg = get_sparsity_config("bigbird", num_heads=2, block=8,
                              num_random_blocks=1, num_sliding_window_blocks=2)
    lay = cfg.make_layout(32)

    def loss_p(q, k, v):
        return (block_sparse_attention(q, k, v, lay, block=8, impl="pallas",
                                       causal=False) ** 2).sum()

    def loss_d(q, k, v):
        return (block_sparse_attention_dense(q, k, v, lay, block=8,
                                             causal=False) ** 2).sum()

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_pallas_sparse_gradients_bf16_finite():
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(S=32))
    cfg = get_sparsity_config("local", num_heads=2, block=8, num_sliding_window_blocks=2)
    lay = cfg.make_layout(32)

    def loss(q, k, v):
        return (block_sparse_attention(q, k, v, lay, block=8, impl="pallas")
                .astype(jnp.float32) ** 2).sum()

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (gq, gk, gv):
        assert g.dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(g, dtype=np.float32)).all()


# ---------------------------------------------------- round-5 breadth tests

def test_variable_layout_semantics():
    """Variable (reference sparsity_config.py:250): window sizes consume
    successive spans (last repeats), global columns causally clamped, and
    no future blocks ever marked."""
    from deepspeed_tpu.ops.sparse_attention import VariableSparsityConfig

    cfg = VariableSparsityConfig(num_heads=1, block=8,
                                 local_window_blocks=(2, 3),
                                 global_block_indices=(0,))
    lay = cfg.make_layout(8 * 8)[0]  # 8 block rows: windows [0,2), [2,5), [5,8)
    assert np.triu(lay, 1).sum() == 0  # causal
    assert lay[1, 0] == 1 and lay[1, 1] == 1       # inside window 0
    assert lay[3, 2] == 1 and lay[3, 3] == 1       # inside window 1
    assert lay[3, 1] == 0                          # window 0 interior not seen
    assert lay[6, 5] == 1 and lay[6, 4] == 0       # window 2 local only
    assert all(lay[i, 0] == 1 for i in range(8))   # global column 0


def test_bslongformer_layout_semantics():
    """BSLongformer (reference sparsity_config.py:555): sliding window plus
    global ranges that attend (horizontal) and are attended (vertical)."""
    from deepspeed_tpu.ops.sparse_attention import BSLongformerSparsityConfig

    cfg = BSLongformerSparsityConfig(num_heads=1, block=8,
                                     num_sliding_window_blocks=2,
                                     global_block_indices=(0, 4),
                                     global_block_end_indices=(1, 6))
    lay = cfg.make_layout(8 * 8)[0]
    assert np.triu(lay, 1).sum() == 0
    assert all(lay[i, 0] == 1 for i in range(8))          # vertical global 0
    assert all(lay[i, 4] == 1 for i in range(4, 8))       # vertical global 4
    assert all(lay[i, 5] == 1 for i in range(5, 8))       # vertical global 5
    assert lay[4].sum() == 5 and all(lay[4, :5] == 1)     # horizontal global
    assert lay[7, 2] == 0                                 # outside window+globals


def test_global_range_validation():
    from deepspeed_tpu.ops.sparse_attention import BSLongformerSparsityConfig

    with pytest.raises(ValueError, match="length"):
        BSLongformerSparsityConfig(num_heads=1, block=8,
                                   global_block_indices=(0, 4),
                                   global_block_end_indices=(1,)).make_layout(64)
    with pytest.raises(ValueError, match="empty"):
        BSLongformerSparsityConfig(num_heads=1, block=8,
                                   global_block_indices=(4,),
                                   global_block_end_indices=(4,)).make_layout(64)


def test_sparse_composes_with_alibi_and_padding():
    """Round-5 lift (reference composes these through its masked softmax):
    with a DENSE layout the sparse path + ALiBi + key padding must match
    exact attention bit-for-bit-ish — pins the bias/mask math."""
    from deepspeed_tpu.ops.attention import causal_attention

    q, k, v = _qkv(S=32)
    lay = get_sparsity_config("dense", num_heads=2, block=8).make_layout(32)
    slopes = jnp.asarray([0.25, 0.0625], jnp.float32)
    pad = jnp.asarray(np.concatenate([np.ones((2, 28)), np.zeros((2, 4))], axis=1),
                      jnp.float32)
    got = block_sparse_attention(q, k, v, lay, block=8,
                                 alibi_slopes=slopes, pad_mask=pad)
    want = causal_attention(q, k, v, impl="xla", mask=pad, alibi_slopes=slopes)
    # padded rows self-attend in `want` but emit zeros in the sparse path —
    # compare the live rows only
    np.testing.assert_allclose(np.asarray(got)[:, :28], np.asarray(want)[:, :28],
                               rtol=2e-5, atol=2e-5)


def test_model_sparse_alibi_training():
    """bloom-style (ALiBi) model trains through attn_impl='sparse' with a
    padding mask — the round-4 NotImplementedErrors are gone. With a dense
    layout the logits must match the xla path exactly."""
    from deepspeed_tpu.models import CausalLM, TransformerConfig

    # 1 layer, seq 16 (2x2 blocks of 8): the xla-vs-sparse comparison
    # compiles two full models; depth/length add compile time, not coverage
    # (the routing is per-layer-identical, the block math per-block)
    kw = dict(vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=1,
              num_heads=2, max_seq_len=16, position="alibi", fused_ce=False)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)), jnp.int32)
    mask = jnp.asarray(np.concatenate([np.ones((2, 14)), np.zeros((2, 2))], 1),
                       jnp.int32)
    batch = {"input_ids": ids, "attention_mask": mask}

    def run(cfg):
        m = CausalLM(cfg)
        params = m.init(jax.random.PRNGKey(0), batch, train=False)["params"]
        # jit: eager op-by-op apply+grad of even this tiny model costs ~30 s
        # of pure dispatch on the single-core lane
        loss, logits = jax.jit(
            lambda p: m.apply({"params": p}, batch, train=False))(params)
        g = jax.jit(jax.grad(
            lambda p: m.apply({"params": p}, batch, train=False)[0]))(params)
        return loss, logits, g

    l_x, logit_x, g_x = run(TransformerConfig(**kw, attn_impl="xla"))
    l_s, logit_s, g_s = run(TransformerConfig(
        **kw, attn_impl="sparse", sparse_attention={"mode": "dense", "block": 8}))
    np.testing.assert_allclose(np.asarray(l_s), np.asarray(l_x), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(logit_s)[:, :14],
                               np.asarray(logit_x)[:, :14], rtol=2e-4, atol=2e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-5),
        g_s, g_x)


def test_forced_pallas_with_extras_raises():
    """An explicit impl='pallas' must not be silently rerouted when the
    kernel can't fuse alibi/padding — loud error, auto still routes."""
    q, k, v = _qkv(S=32)
    lay = get_sparsity_config("dense", num_heads=2, block=8).make_layout(32)
    slopes = jnp.asarray([0.25, 0.0625], jnp.float32)
    with pytest.raises(NotImplementedError, match="alibi"):
        block_sparse_attention(q, k, v, lay, block=8, impl="pallas",
                               alibi_slopes=slopes)
    out = block_sparse_attention(q, k, v, lay, block=8, impl="auto",
                                 alibi_slopes=slopes)
    assert np.isfinite(np.asarray(out)).all()
