"""Tile-skipping block-sparse attention kernel (reference
ops/sparse_attention/matmul.py:196 sdd/dsd block-skipping)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    block_sparse_attention,
    block_sparse_attention_dense,
    get_sparsity_config,
)
from deepspeed_tpu.ops.pallas.sparse_attention import layout_to_lists


def _qkv(B=2, S=64, H=2, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (B, S, H, D), jnp.float32)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("name,kw", [
    ("local", {"num_sliding_window_blocks": 2}),
    ("fixed", {"num_local_blocks": 2}),
    ("bigbird", {"num_random_blocks": 1, "num_sliding_window_blocks": 2}),
])
def test_pallas_sparse_matches_dense_masked(name, kw):
    q, k, v = _qkv()
    cfg = get_sparsity_config(name, num_heads=2, block=8, **kw)
    lay = cfg.make_layout(64)
    want = block_sparse_attention_dense(q, k, v, lay, block=8)
    got = block_sparse_attention(q, k, v, lay, block=8, impl="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_pallas_sparse_actually_skips_tiles():
    """The compute win is structural: the grid's active-column axis is the
    layout's max row population, not the full block count."""
    cfg = get_sparsity_config("local", num_heads=2, block=8, num_sliding_window_blocks=2)
    lay = cfg.make_layout(64)  # 8x8 blocks, window 2
    cols, ncols = layout_to_lists(lay)
    n = lay.shape[1]
    assert cols.shape[-1] == 2  # max 2 active columns per row
    assert cols.shape[-1] < n  # vs 8 dense tiles per row
    # executed tile fraction == layout density
    assert ncols.sum() == lay.sum()
    assert lay.sum() / (2 * n * n) < 0.3


def test_pallas_sparse_gradients_match_dense():
    q, k, v = _qkv(S=32)
    cfg = get_sparsity_config("local", num_heads=2, block=8, num_sliding_window_blocks=2)
    lay = cfg.make_layout(32)

    def loss_p(q, k, v):
        return (block_sparse_attention(q, k, v, lay, block=8, impl="pallas") ** 2).sum()

    def loss_d(q, k, v):
        return (block_sparse_attention_dense(q, k, v, lay, block=8) ** 2).sum()

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_layout_cache_eviction_safe_under_grad():
    """Backward after >_LAYOUT_CAP registrations must not KeyError: keys are
    self-describing, so evicted entries rebuild from the key."""
    from deepspeed_tpu.ops.pallas import sparse_attention as sa

    q, k, v = _qkv(S=16, H=1)

    def loss(q, k, v, lay):
        return (block_sparse_attention(q, k, v, lay, block=8, impl="pallas") ** 2).sum()

    # register one layout under grad, then churn the cache past the cap with
    # unique layouts (i encoded in the spare sub-diagonal bit pattern)
    lay0 = np.ones((1, 2, 2), dtype=np.int64)
    f = jax.vjp(lambda q: loss(q, k, v, lay0), q)[1]
    qq, kk, vv = _qkv(S=64, H=1)
    for i in range(sa._LAYOUT_CAP + 4):
        lay = np.eye(8, dtype=np.int64)[None]
        for b in range(6):
            lay[0, b + 2, b] = (i >> b) & 1
        block_sparse_attention(qq, kk, vv, lay, block=8, impl="pallas")
    key0 = (lay0.shape, lay0.dtype.str, lay0.tobytes())
    assert key0 not in sa._LAYOUTS  # really evicted
    (dq,) = f(jnp.ones(()))  # backward still works
    assert np.isfinite(np.asarray(dq)).all()


def test_pallas_sparse_bwd_skips_tiles():
    """The backward's grids end at the layout population too: dq walks
    cols/ncols, dk/dv walk the transposed rows/nrows — both bounded by the
    max row/column population, not the block count."""
    from deepspeed_tpu.ops.pallas.sparse_attention import layout_to_lists_t

    cfg = get_sparsity_config("local", num_heads=2, block=8, num_sliding_window_blocks=2)
    lay = cfg.make_layout(64)
    rows, nrows = layout_to_lists_t(lay)
    n = lay.shape[1]
    assert rows.shape[-1] < n  # dk/dv active-row axis << dense
    assert nrows.sum() == lay.sum()  # executed tile count == live tiles
    # transposed lists really are the transpose: column ki's rows are the
    # rows qi whose col-list contains ki
    cols, ncols = layout_to_lists(lay)
    for h in range(lay.shape[0]):
        for ki in range(n):
            got = set(rows[h, ki, :nrows[h, ki]].tolist())
            want = {qi for qi in range(n) if lay[h, qi, ki]}
            assert got == want


def _grad_shapes(fn, *args):
    """All f32 buffer shapes in the compiled gradient program."""
    import re

    comp = jax.jit(jax.grad(fn, argnums=(0, 1, 2))).lower(*args).compile()
    return [tuple(map(int, m.group(1).split(",")))
            for m in re.finditer(r"f32\[([\d,]+)\]", comp.as_text())], comp


def test_pallas_sparse_bwd_memory_is_linear_in_seq():
    """No S x S score buffer anywhere in the compiled backward — the round-3
    dense-recompute fallback materialized one; the sparse kernels peak at
    O(S*block) (one [block, block] tile in VMEM at a time)."""
    S, block = 256, 8
    cfg = get_sparsity_config("local", num_heads=2, block=block,
                              num_sliding_window_blocks=2)
    lay = cfg.make_layout(S)
    q = jnp.ones((1, S, 2, 16), jnp.float32)

    def loss_sparse(q, k, v):
        return (block_sparse_attention(q, k, v, lay, block=block, impl="pallas") ** 2).sum()

    def loss_dense(q, k, v):
        return (block_sparse_attention_dense(q, k, v, lay, block=block) ** 2).sum()

    def has_sq(shapes):
        return any(sum(d >= S for d in shp) >= 2 for shp in shapes)

    sparse_shapes, _ = _grad_shapes(loss_sparse, q, q, q)
    dense_shapes, _ = _grad_shapes(loss_dense, q, q, q)
    assert has_sq(dense_shapes), "positive control: dense path should materialize SxS"
    assert not has_sq(sparse_shapes), f"SxS buffer in sparse bwd: {sparse_shapes}"


def test_pallas_sparse_gradients_match_dense_noncausal():
    q, k, v = _qkv(S=32)
    cfg = get_sparsity_config("bigbird", num_heads=2, block=8,
                              num_random_blocks=1, num_sliding_window_blocks=2)
    lay = cfg.make_layout(32)

    def loss_p(q, k, v):
        return (block_sparse_attention(q, k, v, lay, block=8, impl="pallas",
                                       causal=False) ** 2).sum()

    def loss_d(q, k, v):
        return (block_sparse_attention_dense(q, k, v, lay, block=8,
                                             causal=False) ** 2).sum()

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_pallas_sparse_gradients_bf16_finite():
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(S=32))
    cfg = get_sparsity_config("local", num_heads=2, block=8, num_sliding_window_blocks=2)
    lay = cfg.make_layout(32)

    def loss(q, k, v):
        return (block_sparse_attention(q, k, v, lay, block=8, impl="pallas")
                .astype(jnp.float32) ** 2).sum()

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (gq, gk, gv):
        assert g.dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(g, dtype=np.float32)).all()
