"""Native AIO + swap layer tests (coverage model: reference
tests/unit/ops/aio/test_aio.py + runtime/test_ds_initialize offload paths)."""

import os

import numpy as np
import pytest

from deepspeed_tpu.ops.aio import AioHandle, aio_available
from deepspeed_tpu.ops.op_builder import AsyncIOBuilder

pytestmark = pytest.mark.skipif(not aio_available(), reason="no C++ toolchain")


def test_builder_compiles_and_caches():
    b = AsyncIOBuilder()
    so1 = b.build()
    so2 = b.build()
    assert so1 == so2 and os.path.exists(so1)


def test_async_write_read_roundtrip(tmp_path):
    h = AioHandle(num_threads=2)
    data = np.random.randint(0, 255, 1 << 20, np.uint8)
    f = str(tmp_path / "a.bin")
    req = h.async_pwrite(data, f)
    h.wait(req)
    assert os.path.getsize(f) == data.nbytes
    out = np.empty_like(data)
    h.pread(out, f)
    np.testing.assert_array_equal(out, data)
    h.close()


def test_many_overlapping_requests(tmp_path):
    h = AioHandle(num_threads=4)
    bufs = [np.full(4096, i, np.uint8) for i in range(32)]
    for i, b in enumerate(bufs):
        h.async_pwrite(b, str(tmp_path / f"f{i}.bin"))
    h.wait_all()
    outs = [np.empty(4096, np.uint8) for _ in range(32)]
    reqs = [h.async_pread(o, str(tmp_path / f"f{i}.bin")) for i, o in enumerate(outs)]
    for r in reqs:
        h.wait(r)
    for i, o in enumerate(outs):
        assert (o == i).all()
    h.close()


def test_offsets_and_errors(tmp_path):
    h = AioHandle(num_threads=1)
    f = str(tmp_path / "off.bin")
    h.pwrite(np.arange(16, dtype=np.uint8), f)
    h.pwrite(np.arange(100, 104, dtype=np.uint8), f, offset=16)
    out = np.empty(20, np.uint8)
    h.pread(out, f)
    assert out[16] == 100 and out[3] == 3
    # reading a missing file surfaces an OSError
    with pytest.raises(OSError):
        h.pread(np.empty(4, np.uint8), str(tmp_path / "missing.bin"))
    # short read (file smaller than buffer) is an error, not silence
    with pytest.raises(OSError):
        h.pread(np.empty(1 << 20, np.uint8), f)
    h.close()


def test_tensor_swapper_roundtrip(tmp_path, devices):
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.swap_tensor import AsyncTensorSwapper

    sw = AsyncTensorSwapper(str(tmp_path), num_threads=2)
    tree = {"a": jnp.arange(1024, dtype=jnp.float32), "b": {"c": jnp.ones((8, 8), jnp.bfloat16)}}
    sw.swap_out("t0", tree)  # async
    got = sw.swap_in("t0", like=tree)
    np.testing.assert_array_equal(np.asarray(got["a"]), np.asarray(tree["a"]))
    assert got["b"]["c"].dtype == jnp.bfloat16
    sw.release("t0")
    assert not os.path.exists(os.path.join(str(tmp_path), "t0"))
    sw.close()


def test_optimizer_state_swapper_with_engine(tmp_path, devices):
    """NVMe optimizer offload around real engine steps: state swapped to disk
    between steps must reproduce the in-memory trajectory exactly."""
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.runtime.swap_tensor import OptimizerStateSwapper
    from tests.unit.simple_model import random_batch, simple_model_spec

    cfg = {"train_micro_batch_size_per_gpu": 2,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "steps_per_print": 1000}
    # baseline: 4 uninterrupted steps
    e0, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg, seed=5)
    for i in range(4):
        e0.train_batch(random_batch(e0.train_batch_size, seed=i))
    baseline = jax.device_get(e0.state.params)

    # swapped run: state goes to disk and back between every step
    e1, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg, seed=5)
    sw = OptimizerStateSwapper(str(tmp_path / "opt"))
    for i in range(4):
        if i > 0:
            shapes = e1.state.opt_state
            restored = sw.swap_in_opt_state(like=shapes)
            e1.state = e1.state._replace(opt_state=restored)
        e1.train_batch(random_batch(e1.train_batch_size, seed=i))
        sw.swap_out_opt_state(e1.state.opt_state, wait=False)
    swapped = jax.device_get(e1.state.params)
    for a, b in zip(jax.tree_util.tree_leaves(baseline), jax.tree_util.tree_leaves(swapped)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    sw.close()


def test_io_benchmark(tmp_path):
    from deepspeed_tpu.nvme import run_io_benchmark

    r = run_io_benchmark(str(tmp_path), size_mb=8, num_threads=2)
    assert r["write_gbps"] > 0 and r["read_gbps"] > 0
    assert not any(f.startswith("ds_io") for f in os.listdir(tmp_path))
