"""Pallas paged flash-decode kernel vs the dense-gather XLA fallback
(reference inference/v2/kernels/ragged_ops/blocked_flash/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.registry import dispatch
import deepspeed_tpu.ops.pallas.paged_attention  # noqa: F401
import deepspeed_tpu.inference.paged  # noqa: F401  (registers the xla impl)


def _setup(N=3, C=4, H=8, kvH=2, hd=32, P=6, bs=16, seed=0):
    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 5)
    S_flat = 64 * bs + 1
    q = jax.random.normal(ks[0], (N, C, H, hd), jnp.float32)
    pool_k = jax.random.normal(ks[1], (S_flat, kvH, hd), jnp.float32)
    pool_v = jax.random.normal(ks[2], (S_flat, kvH, hd), jnp.float32)
    # distinct random pages per row
    bt = jax.random.permutation(ks[3], 64)[: N * P].reshape(N, P).astype(jnp.int32)
    # rows with different live lengths: row n ends at position end_n
    ends = jnp.asarray([5, 37, 90])[:N]
    positions = jnp.stack([jnp.arange(C) + e - C + 1 for e in ends]).astype(jnp.int32)
    new_lens = jnp.full((N,), C, jnp.int32)
    return q, pool_k, pool_v, bt, positions, new_lens, bs


@pytest.mark.parametrize("ppcb", [1, 2, 8])
def test_paged_pallas_matches_xla(ppcb):
    q, pk, pv, bt, pos, lens, bs = _setup()
    xla = dispatch("paged_attention", "xla")
    pallas = dispatch("paged_attention", "pallas")
    want = xla(q, pk, pv, bt, pos, bs)
    got = pallas(q, pk, pv, bt, pos, bs, new_lens=lens, pages_per_block=ppcb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_paged_pallas_decode_single_token():
    q, pk, pv, bt, pos, lens, bs = _setup(C=1)
    xla = dispatch("paged_attention", "xla")
    pallas = dispatch("paged_attention", "pallas")
    want = xla(q, pk, pv, bt, pos, bs)
    got = pallas(q, pk, pv, bt, pos, bs, new_lens=lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_paged_pallas_gqa_grouping():
    q, pk, pv, bt, pos, lens, bs = _setup(H=8, kvH=4, hd=16)
    want = dispatch("paged_attention", "xla")(q, pk, pv, bt, pos, bs)
    got = dispatch("paged_attention", "pallas")(q, pk, pv, bt, pos, bs, new_lens=lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_ragged_forward_uses_kernel_consistently():
    """v2 ragged_forward parity between forced impls (engine path sanity)."""
    from deepspeed_tpu.inference.paged import init_pool, ragged_forward
    from deepspeed_tpu.models import CausalLM, TransformerConfig

    cfg = TransformerConfig(vocab_size=64, hidden_size=32, intermediate_size=48,
                            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=64,
                            dtype=jnp.float32)
    module = CausalLM(cfg)
    batch = {"input_ids": jnp.zeros((1, 8), jnp.int32)}
    params = module.init({"params": jax.random.PRNGKey(0)}, batch, train=False)["params"]
    pool = init_pool(cfg, num_blocks=8, block_size=16, dtype=jnp.float32)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 8)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(8), (2, 8)).astype(jnp.int32)
    new_lens = jnp.asarray([8, 5], jnp.int32)
    bt = jnp.asarray([[0, 1], [2, 3]], jnp.int32)

    logits, _ = ragged_forward(params, cfg, pool, tokens, positions, new_lens, bt, 16)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.parametrize("kvH,ppcb", [(2, 8), (8, 8), (2, 2)])  # GQA/MHA + multi-chunk
def test_paged_pallas_alibi_matches_xla(kvH, ppcb):
    """ALiBi fused into the decode kernel (slope * key-position on the
    existing position iota) — bloom keeps the Pallas fast path."""
    from deepspeed_tpu.models.transformer import alibi_slopes

    q, pk, pv, bt, pos, lens, bs = _setup(H=8, kvH=kvH, hd=16)
    slopes = alibi_slopes(8)
    xla = dispatch("paged_attention", "xla")
    pallas = dispatch("paged_attention", "pallas")
    want = xla(q, pk, pv, bt, pos, bs, alibi_slopes=slopes)
    got = pallas(q, pk, pv, bt, pos, bs, new_lens=lens, alibi_slopes=slopes,
                 pages_per_block=ppcb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)
