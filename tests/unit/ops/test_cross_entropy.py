"""Fused chunked-vocab LM-head + CE vs the naive materialized path.

Reference analog: the fused softmax/logits kernels the reference ships for
exactly this memory wall (csrc/transformer/inference/csrc/softmax.cu,
sequence/fpdt_layer.py:1137 FPDT_LogitsLoss).
"""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.cross_entropy import lm_head_cross_entropy


def _naive(x, embed, labels, pad_mask=None, ignore_index=-100):
    logits = (x @ embed.T.astype(x.dtype)).astype(jnp.float32)
    valid = labels != ignore_index
    if pad_mask is not None:
        valid = valid & (pad_mask > 0)
    safe = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    per_tok = jnp.where(valid, logz - gold, 0.0)
    return per_tok.sum() / jnp.maximum(valid.sum(), 1)


def test_fused_ce_matches_naive_loss_and_grads():
    B, S, D, V = 2, 16, 32, 1000
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (B, S, D), jnp.float32)
    embed = jax.random.normal(jax.random.PRNGKey(1), (V, D), jnp.float32) * 0.1
    labels = jax.random.randint(jax.random.PRNGKey(2), (B, S), 0, V)
    labels = labels.at[0, -3:].set(-100)  # ignore tail

    f_fused = jax.jit(lambda x, e: lm_head_cross_entropy(x, e, labels, chunk_size=128))
    f_naive = jax.jit(lambda x, e: _naive(x, e, labels))
    np.testing.assert_allclose(float(f_fused(x, embed)), float(f_naive(x, embed)), rtol=1e-5)

    g_fused = jax.jit(jax.grad(lambda x, e: lm_head_cross_entropy(x, e, labels, chunk_size=128), argnums=(0, 1)))(x, embed)
    g_naive = jax.jit(jax.grad(lambda x, e: _naive(x, e, labels), argnums=(0, 1)))(x, embed)
    for a, b in zip(g_fused, g_naive):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_fused_ce_pad_mask_and_uneven_chunks():
    B, S, D, V = 2, 8, 16, 130  # V not divisible by chunk
    x = jax.random.normal(jax.random.PRNGKey(0), (B, S, D))
    embed = jax.random.normal(jax.random.PRNGKey(1), (V, D)) * 0.1
    labels = jax.random.randint(jax.random.PRNGKey(2), (B, S), 0, V)
    mask = jnp.ones((B, S), jnp.int32).at[1, -4:].set(0)
    got = float(lm_head_cross_entropy(x, embed, labels, pad_mask=mask, chunk_size=64))
    want = float(_naive(x, embed, labels, pad_mask=mask))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_causal_lm_fused_ce_matches_unfused():
    """CausalLM train loss identical (within fp tolerance) with/without fusion."""
    from deepspeed_tpu.models import CausalLM, TransformerConfig

    kw = dict(vocab_size=4096, hidden_size=32, intermediate_size=64,
              num_layers=2, num_heads=4, max_seq_len=16, dropout=0.0)
    batch = {"input_ids": jnp.asarray(
        np.random.default_rng(0).integers(0, 4096, (2, 16)), jnp.int32)}

    for tie in (True, False):
        cfg_f = TransformerConfig(tie_embeddings=tie, fused_ce=True, fused_ce_min_vocab=1, **kw)
        cfg_p = TransformerConfig(tie_embeddings=tie, fused_ce=False, **kw)
        m_f, m_p = CausalLM(cfg_f), CausalLM(cfg_p)
        params = m_p.init({"params": jax.random.PRNGKey(0)}, batch, train=False)["params"]

        def loss_f(p):
            return m_f.apply({"params": p}, batch, train=True)[0]

        def loss_p(p):
            return m_p.apply({"params": p}, batch, train=True)[0]

        lf, gf = jax.value_and_grad(loss_f)(params)
        lp, gp = jax.value_and_grad(loss_p)(params)
        np.testing.assert_allclose(float(lf), float(lp), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(gf), jax.tree_util.tree_leaves(gp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)
