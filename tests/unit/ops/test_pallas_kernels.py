"""Pallas kernels vs XLA reference implementations (interpret mode on CPU).

Mirrors the reference's per-kernel unit tests (``tests/unit/ops/transformer``,
``tests/unit/ops/quantizer``): numerical parity of the hand-written kernel
against the plain composed implementation, forward and backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu.ops as ops
from deepspeed_tpu.ops.pallas import register_all

register_all()


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


class TestFlashAttention:
    @pytest.mark.parametrize("S", [16, 100])
    @pytest.mark.parametrize("gqa", [False, True])
    def test_forward_matches_xla(self, S, gqa):
        B, H, D = 2, 4, 8
        Hkv = 2 if gqa else H
        q = _rand(0, (B, S, H, D))
        k = _rand(1, (B, S, Hkv, D))
        v = _rand(2, (B, S, Hkv, D))
        ref = ops.causal_attention(q, k, v, impl="xla")
        out = ops.dispatch("causal_attention", "pallas")(q, k, v, block_q=32, block_k=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_padding_mask(self):
        B, S, H, D = 2, 24, 2, 8
        q, k, v = _rand(0, (B, S, H, D)), _rand(1, (B, S, H, D)), _rand(2, (B, S, H, D))
        mask = jnp.asarray(np.random.default_rng(0).integers(0, 2, (B, S)), jnp.int32).at[:, 0].set(1)
        ref = ops.causal_attention(q, k, v, mask=mask, impl="xla")
        out = ops.dispatch("causal_attention", "pallas")(q, k, v, mask=mask, block_q=8, block_k=8)
        # compare only rows whose own position is kept (masked-out query rows
        # are don't-care: xla fills them from masked softmax, pallas zeros)
        keep = np.asarray(mask, bool)
        np.testing.assert_allclose(np.asarray(out)[keep], np.asarray(ref)[keep], atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("gqa", [False, True])
    def test_grads_match_xla(self, gqa):
        B, S, H, D = 2, 32, 4, 8
        Hkv = 2 if gqa else H
        q = _rand(0, (B, S, H, D))
        k = _rand(1, (B, S, Hkv, D))
        v = _rand(2, (B, S, Hkv, D))

        def loss(fn):
            def f(q, k, v):
                out = fn(q, k, v)
                return jnp.sum(out * jnp.cos(out.astype(jnp.float32)))

            return f

        ref_fn = loss(lambda q, k, v: ops.causal_attention(q, k, v, impl="xla"))
        pl_fn = loss(lambda q, k, v: ops.dispatch("causal_attention", "pallas")(q, k, v, block_q=16, block_k=16))
        ref_grads = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
        pl_grads = jax.grad(pl_fn, argnums=(0, 1, 2))(q, k, v)
        for rg, pg in zip(ref_grads, pl_grads):
            np.testing.assert_allclose(np.asarray(pg), np.asarray(rg), atol=5e-5, rtol=5e-5)

    @pytest.mark.parametrize("bq,bk", [(8, 8), (16, 8)])  # squashed + dense grids
    def test_masked_grads_match_xla(self, bq, bk):
        """Backward with a padding mask (the masked branches of both bwd
        kernels). The loss reads only kept-query outputs so masked rows are
        genuinely don't-care and gradients must match everywhere."""
        B, S, H, D = 2, 24, 2, 8
        q, k, v = _rand(0, (B, S, H, D)), _rand(1, (B, S, H, D)), _rand(2, (B, S, H, D))
        mask = jnp.asarray(np.random.default_rng(1).integers(0, 2, (B, S)), jnp.int32).at[:, 0].set(1)
        keep = mask.astype(jnp.float32)[:, :, None, None]

        def loss(fn):
            def f(q, k, v):
                out = fn(q, k, v)
                return jnp.sum(keep * out * jnp.cos(out.astype(jnp.float32)))
            return f

        ref_fn = loss(lambda q, k, v: ops.causal_attention(q, k, v, mask=mask, impl="xla"))
        pl_fn = loss(lambda q, k, v: ops.dispatch("causal_attention", "pallas")(
            q, k, v, mask=mask, block_q=bq, block_k=bk))
        ref_grads = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
        pl_grads = jax.grad(pl_fn, argnums=(0, 1, 2))(q, k, v)
        for rg, pg in zip(ref_grads, pl_grads):
            np.testing.assert_allclose(np.asarray(pg), np.asarray(rg), atol=5e-5, rtol=5e-5)

    def test_unequal_blocks_dense_grid(self):
        """block_q != block_k routes through the dense (non-squashed) causal
        grid — keep that branch covered: fwd + all three gradients."""
        B, S, H, D = 2, 32, 2, 8
        q, k, v = _rand(0, (B, S, H, D)), _rand(1, (B, S, H, D)), _rand(2, (B, S, H, D))

        def f(fn):
            def g(q, k, v):
                out = fn(q, k, v)
                return jnp.sum(out * jnp.cos(out.astype(jnp.float32)))
            return g

        pallas = ops.dispatch("causal_attention", "pallas")
        ref = ops.causal_attention(q, k, v, impl="xla")
        out = pallas(q, k, v, block_q=16, block_k=8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
        ref_grads = jax.grad(f(lambda q, k, v: ops.causal_attention(q, k, v, impl="xla")),
                             argnums=(0, 1, 2))(q, k, v)
        pl_grads = jax.grad(f(lambda q, k, v: pallas(q, k, v, block_q=16, block_k=8)),
                            argnums=(0, 1, 2))(q, k, v)
        for rg, pg in zip(ref_grads, pl_grads):
            np.testing.assert_allclose(np.asarray(pg), np.asarray(rg), atol=5e-5, rtol=5e-5)

    # (16, 16) squashed triangle grid; (16, 8) dense grid — both split branches
    @pytest.mark.parametrize("k_splits,bq,bk", [(2, 16, 16), (2, 16, 8), (4, 16, 16)])
    def test_k_splits_matches_unsplit(self, k_splits, bq, bk):
        """k_splits sub-chunked online softmax (MXU/VPU overlap restructuring)
        matches the unsplit kernel: fwd + all three gradients, with a padding
        mask so the masked sub-chunk slicing is exercised too."""
        B, S, H, D = 2, 32, 2, 8
        q, k, v = _rand(0, (B, S, H, D)), _rand(1, (B, S, H, D)), _rand(2, (B, S, H, D))
        mask = jnp.ones((B, S), jnp.int32).at[1, 20:].set(0)

        def f(fn):
            def g(q, k, v):
                out = fn(q, k, v)
                return jnp.sum(out * jnp.cos(out.astype(jnp.float32)))
            return g

        pallas = ops.dispatch("causal_attention", "pallas")
        base = pallas(q, k, v, mask=mask, block_q=bq, block_k=bk)
        out = pallas(q, k, v, mask=mask, block_q=bq, block_k=bk, k_splits=k_splits)
        np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=2e-6, rtol=2e-6)
        base_grads = jax.grad(f(lambda q, k, v: pallas(q, k, v, mask=mask, block_q=bq, block_k=bk)),
                              argnums=(0, 1, 2))(q, k, v)
        pl_grads = jax.grad(f(lambda q, k, v: pallas(q, k, v, mask=mask, block_q=bq,
                                                     block_k=bk, k_splits=k_splits)),
                            argnums=(0, 1, 2))(q, k, v)
        for rg, pg in zip(base_grads, pl_grads):
            np.testing.assert_allclose(np.asarray(pg), np.asarray(rg), atol=5e-6, rtol=5e-6)


    def test_kernel_kwargs_forwarded_to_pallas_and_dropped_on_xla(self):
        """The attn_kwargs plumbing (TransformerConfig -> causal_attention ->
        kernel): scheduling knobs must reach the pallas kernel (identical
        math, different blocking) and be silently DROPPED when dispatch
        resolves to the XLA path — an autotuned block config must never make
        the fallback path raise TypeError."""
        B, S, H, D = 2, 32, 2, 8
        q, k, v = _rand(0, (B, S, H, D)), _rand(1, (B, S, H, D)), _rand(2, (B, S, H, D))
        kw = dict(block_q=16, block_k=16, k_splits=2)
        ref = ops.causal_attention(q, k, v, impl="xla")
        # xla impl has no blocking params: kwargs must be dropped, not passed
        out_xla = ops.causal_attention(q, k, v, impl="xla", **kw)
        np.testing.assert_allclose(np.asarray(out_xla), np.asarray(ref), rtol=1e-6)
        # pallas impl must actually honor them (reject an impossible block)
        out_pl = ops.causal_attention(q, k, v, impl="pallas", **kw)
        np.testing.assert_allclose(np.asarray(out_pl), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        # model-level: TransformerConfig freezes the dict hashable for jit
        from deepspeed_tpu.models import TransformerConfig

        cfg = TransformerConfig(vocab_size=32, hidden_size=16,
                                intermediate_size=32, num_layers=1,
                                num_heads=2, max_seq_len=32, attn_kwargs=kw)
        assert cfg.attn_kwargs == tuple(sorted(kw.items()))
        assert hash(cfg.attn_kwargs) is not None


class TestNorms:
    def test_rms_norm(self):
        x = _rand(0, (4, 12, 64))
        scale = 1.0 + 0.1 * _rand(1, (64,))
        ref = ops.rms_norm(x, scale, impl="xla")
        out = ops.dispatch("rms_norm", "pallas")(x, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)

    def test_rms_norm_grad(self):
        x = _rand(0, (8, 32))
        scale = 1.0 + 0.1 * _rand(1, (32,))

        def f(fn):
            return lambda x, s: jnp.sum(jnp.sin(fn(x, s)))

        ref = jax.grad(f(lambda x, s: ops.rms_norm(x, s, impl="xla")), argnums=(0, 1))(x, scale)
        out = jax.grad(f(lambda x, s: ops.dispatch("rms_norm", "pallas")(x, s)), argnums=(0, 1))(x, scale)
        for r, o in zip(ref, out):
            np.testing.assert_allclose(np.asarray(o), np.asarray(r), atol=1e-5, rtol=1e-5)

    def test_layer_norm(self):
        x = _rand(0, (4, 12, 64))
        scale = 1.0 + 0.1 * _rand(1, (64,))
        bias = 0.1 * _rand(2, (64,))
        ref = ops.layer_norm(x, scale, bias, impl="xla")
        out = ops.dispatch("layer_norm", "pallas")(x, scale, bias)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)

    def test_layer_norm_grad(self):
        x = _rand(0, (8, 32))
        scale = 1.0 + 0.1 * _rand(1, (32,))
        bias = 0.1 * _rand(2, (32,))

        def f(fn):
            return lambda x, s, b: jnp.sum(jnp.sin(fn(x, s, b)))

        ref = jax.grad(f(lambda x, s, b: ops.layer_norm(x, s, b, impl="xla")), argnums=(0, 1, 2))(x, scale, bias)
        out = jax.grad(f(lambda x, s, b: ops.dispatch("layer_norm", "pallas")(x, s, b)), argnums=(0, 1, 2))(x, scale, bias)
        for r, o in zip(ref, out):
            np.testing.assert_allclose(np.asarray(o), np.asarray(r), atol=1e-5, rtol=1e-5)


class TestQuantizer:
    @pytest.mark.parametrize("n", [64, 1000, 4096])
    def test_roundtrip_error_bounded(self, n):
        x = _rand(0, (n,))
        vals, scales = ops.quantize_int8(x, block_size=256, impl="pallas")
        assert vals.dtype == jnp.int8
        back = ops.dequantize_int8(vals, scales, (n,), dtype=jnp.float32, block_size=256, impl="pallas")
        err = np.abs(np.asarray(back) - np.asarray(x))
        bound = np.asarray(scales).max() * 0.51 + 1e-6
        assert err.max() <= bound

    def test_pallas_matches_xla(self):
        x = _rand(0, (512,))
        v_p, s_p = ops.quantize_int8(x, block_size=128, impl="pallas")
        v_x, s_x = ops.quantize_int8(x, block_size=128, impl="xla")
        np.testing.assert_array_equal(np.asarray(v_p), np.asarray(v_x))
        np.testing.assert_allclose(np.asarray(s_p), np.asarray(s_x), rtol=1e-6)


def test_attention_pair_bias_and_alibi(devices):
    """Evoformer-style additive pair bias + bloom-style alibi slopes
    (reference csrc/deepspeed4science/evoformer_attn + the alibi softmax
    path). Biased forms ride the differentiable XLA path."""
    import numpy as np
    from deepspeed_tpu.ops import causal_attention

    B, S, H, D = 2, 16, 4, 8
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks[:3])
    bias = jax.random.normal(ks[3], (H, S, S)) * 0.5

    # manual reference with the bias folded into masked scores
    def ref(q, k, v, extra):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.float32(D)) + extra
        s = jnp.where(jnp.tril(jnp.ones((S, S), bool))[None, None], s, -1e9)
        return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)

    got = causal_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref(q, k, v, bias[None])),
                               rtol=2e-5, atol=2e-5)

    # pair bias is differentiable (evoformer trains through it)
    gb = jax.grad(lambda b: (causal_attention(q, k, v, bias=b) ** 2).sum())(bias)
    assert np.abs(np.asarray(gb)).sum() > 0 and np.isfinite(np.asarray(gb)).all()

    # alibi == bias of slopes * key-position
    from deepspeed_tpu.models.transformer import alibi_slopes

    slopes = alibi_slopes(H)
    ali = causal_attention(q, k, v, alibi_slopes=slopes)
    want = ref(q, k, v, (slopes[:, None, None] *
                         jnp.arange(S, dtype=jnp.float32)[None, None, :])[None])
    np.testing.assert_allclose(np.asarray(ali), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_alibi_slopes_match_hf_formula(devices):
    import numpy as np
    from deepspeed_tpu.models.transformer import alibi_slopes

    # power-of-2 head count: geometric sequence from 2^(-8/n)
    s8 = np.asarray(alibi_slopes(8))
    np.testing.assert_allclose(s8, [2 ** (-(i + 1)) for i in range(8)], rtol=1e-6)
    # non-power-of-2 (6 heads): 4 base slopes then 2 odd-power extras,
    # appended (NOT sorted) exactly as HF build_alibi_tensor orders them
    s6 = np.asarray(alibi_slopes(6))
    np.testing.assert_allclose(
        s6, [0.25, 0.0625, 0.015625, 0.00390625, 0.5, 0.125], rtol=1e-6)


def test_evoformer_attention_bidirectional_with_pair_bias(devices):
    """DS4Science evoformer coverage (reference csrc/deepspeed4science/
    evoformer_attn): bidirectional + pair bias + mask, d(pair_bias) flows."""
    B, S, H, D = 2, 12, 4, 8
    ks = jax.random.split(jax.random.PRNGKey(3), 4)
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks[:3])
    bias = jax.random.normal(ks[3], (H, S, S)) * 0.3
    mask = jnp.asarray(np.array([[1] * 12, [1] * 9 + [0] * 3]), jnp.int32)

    def ref(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.float32(D)) + bias[None]
        s = jnp.where(mask[:, None, None, :] > 0, s, -1e9)  # NO causal mask
        return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)

    got = ops.evoformer_attention(q, k, v, pair_bias=bias, mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref(q, k, v)),
                               rtol=2e-5, atol=2e-5)
    # genuinely bidirectional: differs from the causal-masked form
    c = ops.causal_attention(q, k, v, mask=mask, bias=bias)
    assert np.abs(np.asarray(got - c)).max() > 1e-3

    gb = jax.grad(lambda b: (ops.evoformer_attention(q, k, v, pair_bias=b,
                                                     mask=mask) ** 2).sum())(bias)
    assert np.isfinite(np.asarray(gb)).all() and np.abs(np.asarray(gb)).sum() > 0


class TestFlashAlibi:
    """ALiBi fused into the flash kernels (slope * column iota in all three
    kernels) — bloom-style training keeps the flash path instead of the XLA
    fallback."""

    def _qkv(self, B=2, S=32, H=4, D=8, Hkv=None):
        from deepspeed_tpu.models.transformer import alibi_slopes

        Hkv = Hkv or H
        return (_rand(0, (B, S, H, D)), _rand(1, (B, S, Hkv, D)),
                _rand(2, (B, S, Hkv, D)), alibi_slopes(H))

    @pytest.mark.parametrize("bq,bk", [(8, 8), (16, 8)])  # squashed + dense grids
    def test_forward_matches_xla(self, bq, bk):
        q, k, v, slopes = self._qkv()
        ref = ops.causal_attention(q, k, v, impl="xla", alibi_slopes=slopes)
        out = ops.dispatch("causal_attention", "pallas")(
            q, k, v, block_q=bq, block_k=bk, alibi_slopes=slopes)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("gqa,bq,bk", [
        (False, 8, 8),   # squashed grid
        (False, 16, 8),  # dense grid (incl. the _DEC_DENSE_KQ dkv decoder)
        (True, 8, 8),    # GQA: slope indexed by query head h, k/v by h//G
    ])
    def test_grads_match_xla(self, gqa, bq, bk):
        q, k, v, slopes = self._qkv(Hkv=2 if gqa else None)

        def loss(fn):
            def f(q, k, v):
                out = fn(q, k, v)
                return jnp.sum(out * jnp.cos(out.astype(jnp.float32)))
            return f

        ref = jax.grad(loss(lambda q, k, v: ops.causal_attention(
            q, k, v, impl="xla", alibi_slopes=slopes)), argnums=(0, 1, 2))(q, k, v)
        got = jax.grad(loss(lambda q, k, v: ops.dispatch("causal_attention", "pallas")(
            q, k, v, block_q=bq, block_k=bk, alibi_slopes=slopes)), argnums=(0, 1, 2))(q, k, v)
        for r, g in zip(ref, got):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=5e-5, rtol=5e-5)

    def test_gqa_forward_matches_xla(self):
        q, k, v, slopes = self._qkv(Hkv=2)
        ref = ops.causal_attention(q, k, v, impl="xla", alibi_slopes=slopes)
        out = ops.dispatch("causal_attention", "pallas")(
            q, k, v, block_q=8, block_k=8, alibi_slopes=slopes)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_masked_forward_matches_xla(self):
        q, k, v, slopes = self._qkv(S=24)
        mask = jnp.asarray(np.random.default_rng(2).integers(0, 2, (2, 24)), jnp.int32).at[:, 0].set(1)
        ref = ops.causal_attention(q, k, v, mask=mask, impl="xla", alibi_slopes=slopes)
        out = ops.dispatch("causal_attention", "pallas")(
            q, k, v, mask=mask, block_q=8, block_k=8, alibi_slopes=slopes)
        keep = np.asarray(mask, bool)
        np.testing.assert_allclose(np.asarray(out)[keep], np.asarray(ref)[keep],
                                   atol=2e-5, rtol=2e-5)
