"""Pin the per-commit compilation-cache keying (ISSUE 18).

The suite's persistent XLA cache is keyed by HEAD sha
(``tests/conftest.py``): jax hashes the traced program, not the python that
produced it, so without the key a source change could be served a stale
executable compiled at another commit. These tests pin the contract: the
active cache dir is ``tests/.jax_cache/<short-sha>``, and pruning removes
other commits' dirs plus legacy flat entries while leaving the live dir
alone.
"""

import os

import jax

from tests import conftest


def test_cache_dir_is_keyed_by_head_sha(tmp_path):
    sha = conftest._head_sha()
    # the repo under test IS a git checkout; if this ever runs from an
    # export tarball the 'nogit' fallback keeps the cache functional
    key = sha or "nogit"
    assert conftest.jax_cache_dir() == os.path.join(conftest._CACHE_ROOT, key)
    # explicit args win (what the pruner and this test key off)
    assert conftest.jax_cache_dir(root=str(tmp_path), sha="abc123") == str(
        tmp_path / "abc123")


def test_active_jax_config_points_into_keyed_dir():
    configured = jax.config.jax_compilation_cache_dir
    assert configured == conftest._CACHE_DIR
    # the configured dir is a CHILD of the cache root, never the root
    # itself (the root held flat entries before keying landed)
    assert os.path.dirname(os.path.abspath(configured)) == os.path.abspath(
        conftest._CACHE_ROOT)


def test_prune_removes_stale_siblings_and_flat_files(tmp_path):
    root = tmp_path / "cache"
    live = root / "abc123"
    stale = root / "0ldsha"
    live.mkdir(parents=True)
    stale.mkdir()
    (live / "entry-cache").write_bytes(b"keep")
    (stale / "entry-cache").write_bytes(b"drop")
    (root / "jit_fn-deadbeef-cache").write_bytes(b"legacy flat entry")

    removed = conftest._prune_stale_cache(keep=str(live), root=str(root))

    assert sorted(removed) == ["0ldsha", "jit_fn-deadbeef-cache"]
    assert (live / "entry-cache").read_bytes() == b"keep"
    assert not stale.exists()
    assert sorted(os.listdir(root)) == ["abc123"]


def test_prune_handles_missing_root(tmp_path):
    assert conftest._prune_stale_cache(
        keep=str(tmp_path / "x" / "sha"), root=str(tmp_path / "x")) == []
