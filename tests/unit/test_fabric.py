"""Cross-process serving fabric tests (ISSUE 18).

Contract under test:
  - wire serialization is byte-VERBATIM: arrays (bf16, int8 + quant
    scales) and PRNG keys round-trip ``fabric/wire.py`` bit-identically;
  - multi-host snapshot writes: ``partition_atoms`` is deterministic and
    balanced, non-zero ranks publish part dirs, rank 0 merges into ONE
    committed snapshot that loads bit-identically via unchanged loaders;
  - preemption: ``PreemptionGuard`` latches SIGTERM without killing the
    step, ``assert_deterministic_batch_fn`` rejects a nondeterministic
    stream, and the elastic agent relaunches (not drops) a host that
    exits with ``EXIT_PREEMPTED``;
  - liveness: a replica whose engine reports dead mid-serve has its
    admitted requests re-queued and completed on survivors (never
    dropped); ``faultinject.kill_replica_daemon`` hard-kills a process;
  - the multi-process integration smoke (``tools/fabric_smoke.py
    --smoke``): real replica-daemon processes behind an unchanged
    ServingRouter — remote greedy decode token-identical to a local
    engine for bf16 AND int8 KV, cross-process migration preserves
    per-block digests, drain completes without drops, and the merged
    trace links request flows across >= 2 pids through serve:dispatch.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.checkpoint import snapshot as snap
from deepspeed_tpu.diagnostics import FaultInjector
from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
from deepspeed_tpu.elasticity.resilience import (
    EXIT_PREEMPTED,
    PreemptionGuard,
    assert_deterministic_batch_fn,
)
from deepspeed_tpu.fabric import wire

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# ------------------------------------------------------------------- wire
@pytest.mark.parametrize("dtype", ["bfloat16", "int8", "float32"])
def test_wire_array_roundtrip_bit_identical(dtype):
    rng = np.random.default_rng(0)
    if dtype == "int8":
        a = rng.integers(-128, 128, size=(3, 16, 4), dtype=np.int8)
    else:
        a = np.asarray(rng.standard_normal((3, 16, 4)), jnp.dtype(dtype))
    doc = wire.array_to_wire(a)
    json.dumps(doc)  # must be JSON-transportable as-is
    back = wire.array_from_wire(doc)
    assert back.dtype == a.dtype and back.shape == a.shape
    assert a.tobytes() == back.tobytes()  # byte-verbatim, not just close


def test_wire_export_roundtrip_preserves_buffers():
    from deepspeed_tpu.inference.paged import MigrationBuffer

    rng = np.random.default_rng(1)
    buf = MigrationBuffer(
        k=rng.integers(-128, 128, size=(2, 4, 16, 2, 8), dtype=np.int8),
        v=rng.integers(-128, 128, size=(2, 4, 16, 2, 8), dtype=np.int8),
        k_scale=np.asarray(rng.standard_normal((2, 4, 16, 2, 1)), np.float32),
        v_scale=np.asarray(rng.standard_normal((2, 4, 16, 2, 1)), np.float32))
    export = {"buffer": buf, "n_blocks": 4, "pages": [0, 1, 2, 3],
              "seen_tokens": 37, "kv_dtype": "int8", "quant": "int8",
              "block_size": 16}
    doc = json.loads(json.dumps(wire.export_to_wire(export)))
    back = wire.export_from_wire(doc)
    assert back["seen_tokens"] == 37 and back["n_blocks"] == 4
    b2 = back["buffer"]
    for name in ("k", "v", "k_scale", "v_scale"):
        assert getattr(buf, name).tobytes() == np.asarray(
            getattr(b2, name)).tobytes()


def test_wire_key_roundtrip():
    key = jax.random.fold_in(jax.random.PRNGKey(42), 7)
    back = wire.key_from_wire(json.loads(json.dumps(wire.key_to_wire(key))))
    assert np.array_equal(np.asarray(key), np.asarray(back))
    # and it still works as a key
    jax.random.uniform(back)


# --------------------------------------------------- multi-host snapshots
def test_partition_atoms_deterministic_and_balanced():
    atoms = {f"a{i}": np.zeros((i + 1, 64), np.float32) for i in range(7)}
    p2 = snap.partition_atoms(atoms, 2)
    assert snap.partition_atoms(atoms, 2) == p2  # deterministic
    assert sorted(sum(p2, [])) == sorted(atoms)  # exact cover
    weights = [sum(atoms[k].nbytes for k in part) for part in p2]
    # greedy largest-first keeps the bins within one largest-atom of even
    assert abs(weights[0] - weights[1]) <= max(a.nbytes for a in atoms.values())
    assert snap.partition_atoms(atoms, 1) == [sorted(atoms)]
    with pytest.raises(ValueError):
        snap.partition_atoms(atoms, 0)


def test_multiprocess_snapshot_write_merges_parts(tmp_path):
    """Rank 1 publishes its part; rank 0 merges into ONE snapshot whose
    unchanged loader returns the full atom tree bit-identically."""
    rng = np.random.default_rng(3)
    atoms = {f"k{i}": np.asarray(rng.standard_normal((8 + i, 6)), np.float32)
             for i in range(5)}
    meta = {"step": 4, "source_mesh": {"dp": 2}, "zero_stage": 1}
    part = snap.write_snapshot(atoms, meta, str(tmp_path), "step000004",
                               process_index=1, process_count=2, fsync=False)
    assert os.path.basename(part) == "step000004.part1"
    assert snap.list_snapshots(str(tmp_path)) == []  # parts never listed
    final = snap.write_snapshot(atoms, meta, str(tmp_path), "step000004",
                                process_index=0, process_count=2,
                                part_timeout_s=10.0, fsync=False)
    assert snap.latest_tag(str(tmp_path)) == "step000004"
    assert not os.path.exists(part)  # rank 0 reclaimed the merged part
    got, manifest = snap.load_snapshot_atoms(str(tmp_path), "step000004")
    assert manifest["writer_processes"] == 2
    assert set(got) == set(atoms)
    for k in atoms:
        assert atoms[k].tobytes() == got[k].tobytes()
    assert final.endswith("step000004")


def test_multiprocess_snapshot_times_out_on_missing_part(tmp_path):
    atoms = {"a": np.zeros((4,), np.float32)}
    with pytest.raises(snap.SnapshotError, match="timed out"):
        snap.write_snapshot(atoms, {"step": 1}, str(tmp_path), "step000001",
                            process_index=0, process_count=2,
                            part_timeout_s=0.2, fsync=False)


# ------------------------------------------------------------- preemption
def test_preemption_guard_latches_and_uninstalls():
    guard = PreemptionGuard(signals=(signal.SIGUSR1,))
    try:
        assert not guard.requested
        os.kill(os.getpid(), signal.SIGUSR1)
        time.sleep(0.05)
        assert guard.requested  # latched, process NOT killed
    finally:
        guard.uninstall()


def test_assert_deterministic_batch_fn():
    assert_deterministic_batch_fn(
        lambda step: {"x": np.full((2,), step, np.float32)}, 3)
    state = {"n": 0}

    def nondet(step):
        state["n"] += 1
        return {"x": np.full((2,), state["n"], np.float32)}

    with pytest.raises(ValueError, match="DETERMINISTIC"):
        assert_deterministic_batch_fn(nondet, 0)


def test_elastic_agent_relaunches_preempted_host():
    """Exit code 143 (preemption-clean) must RELAUNCH the host, not drop
    it — roster intact, next generation at the same world size."""
    launches = []

    def _proc(code):
        return subprocess.Popen([sys.executable, "-c",
                                 f"import sys; sys.exit({code})"])

    def launch(hosts, gen, cfg):
        launches.append(sorted(hosts))
        # generation 0: host 'b' is preempted; generation 1: all succeed
        return {h: _proc(EXIT_PREEMPTED if (gen == 0 and h == "b") else 0)
                for h in hosts}

    agent = DSElasticAgent(
        {"a": 4, "b": 4},
        {"enabled": True, "max_train_batch_size": 48,
         "micro_batch_sizes": [1, 2, 4], "min_gpus": 1, "max_gpus": 64},
        launch, max_restarts=2, poll_interval_s=0.05)
    result = agent.run()
    assert result.ok and result.generation == 1
    assert launches == [["a", "b"], ["a", "b"]]  # roster NEVER shrank
    gen0 = agent.history[0]
    assert not gen0.ok and gen0.preempted == ["b"]
    assert gen0.returncodes["b"] == EXIT_PREEMPTED


# --------------------------------------------------------------- liveness
def test_kill_replica_daemon_sigkills_process():
    proc = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(120)"])
    fi = FaultInjector()
    pid = fi.kill_replica_daemon(proc)
    assert pid == proc.pid
    assert proc.returncode == -signal.SIGKILL
    assert fi.daemon_kills_fired == 1


def test_router_readmits_requests_of_dead_replica():
    """A replica whose engine reports dead mid-serve (the heartbeat path:
    ``engine.alive`` False) is removed from the roster and its admitted
    requests complete on the survivor — never dropped."""
    from deepspeed_tpu.fabric.replica_daemon import _build_model
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.inference.router import ServingRouter

    mc, params = _build_model()
    cfg = {"dtype": "bf16", "kv_block_size": 16, "num_kv_blocks": 96,
           "max_seqs": 2}
    engines = [InferenceEngineV2(mc, params, dict(cfg)) for _ in range(2)]
    router = ServingRouter(engines, dispatch="threads")
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 512, size=12).astype(np.int32)
               for _ in range(4)]
    box = {}

    def run():
        box["outs"] = router.serve(prompts, max_new_tokens=24)

    t = threading.Thread(target=run)
    t.start()
    deadline = time.time() + 120.0
    flipped = False
    while time.time() < deadline and t.is_alive():
        if router.replicas[1].active:
            engines[1].alive = False  # what a missed-heartbeat limit sets
            flipped = True
            break
        time.sleep(0.005)
    t.join(600.0)
    assert not t.is_alive()
    outs = box["outs"]
    assert len(outs) == len(prompts) and all(o is not None for o in outs)
    if flipped:  # death landed while it still held work
        assert router.dead_replicas == 1
        assert router.stats()["dead"] == [1]


# -------------------------------------------- multi-process fabric smoke
def test_multiprocess_fabric_smoke(tmp_path):
    """The acceptance gate: real replica-daemon processes driven by an
    unchanged ServingRouter. Remote greedy decode token-identical to a
    local engine (bf16 AND int8 KV), cross-process migration preserves
    per-block blake2b digests, drain completes without drops, and the
    merged trace links flows from >= 2 pids through serve:dispatch."""
    from tests.conftest import _CACHE_DIR

    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "fabric_smoke.py"),
         "--smoke", "--out", str(tmp_path)],
        capture_output=True, timeout=1500,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             # daemons reuse the suite's keyed compile cache across runs
             "JAX_COMPILATION_CACHE_DIR": _CACHE_DIR},
        cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout.decode() + out.stderr.decode()[-800:]
    doc = json.loads(out.stdout.decode().strip().splitlines()[-1])
    assert doc["ok"] and not doc["leg_failures"]
    assert doc["tokens_identical_bf16"] and doc["migrations_bf16"] >= 1
    assert doc["tokens_identical_int8"] and doc["migrations_int8"] >= 1
    assert doc["digests_identical"] and doc["digest_blocks"] >= 1
    assert doc["drain_complete"] and doc["drain_ok"]
    assert doc["trace_ok"] and doc["trace_dispatch_pids"] >= 2
