"""Fleet telemetry plane tests (ISSUE 13).

Contract under test:
  - process identity: env/config resolution, stamps on expositions, JSONL
    streams, flight-recorder dumps, observatory table rows
  - metric federation is EXACT: merging K sharded registries equals
    observing the concatenated sample stream (property test — quantiles
    and bucket counts bit-identical; counter sum + gauge last-per-proc
    rules pinned alongside)
  - FleetCollector: push/scrape ingestion, federated render, fleet/*
    rollups, cross-process straggler flags, health ledger, federated
    observatory table round-trip into a fresh selector's measured mode
  - distributed tracing: TraceContext wire round-trip, stable flow ids,
    dispatch_span emission, trace_merge joining per-process JSONL into one
    flow-linked Perfetto trace
  - /healthz liveness endpoint (identity + last-step age + registry size)
  - the 3-process CPU integration smoke (tools/fleet_smoke.py): collector
    + 2 real worker processes, every exit gate green
"""

import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from deepspeed_tpu.telemetry import exposition, fleet
from deepspeed_tpu.telemetry.collector import FleetClient, FleetCollector
from deepspeed_tpu.telemetry.registry import MetricsRegistry, decode_key
from deepspeed_tpu.telemetry.tracer import Tracer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _pinned_identity():
    """Deterministic identity per test; restore the lazy default after."""
    fleet.reset_identity()
    fleet.configure_identity(run_id="testrun", process_index=0,
                             host="testhost", role="train")
    yield
    fleet.reset_identity()


# ------------------------------------------------------------- identity
def test_identity_defaults_and_overrides(monkeypatch):
    fleet.reset_identity()
    monkeypatch.setenv("DSTPU_RUN_ID", "envrun")
    monkeypatch.setenv("DSTPU_PROCESS_INDEX", "3")
    monkeypatch.setenv("DSTPU_ROLE", "replica")
    ident = fleet.get_identity()
    assert (ident.run_id, ident.process_index, ident.role) == (
        "envrun", 3, "replica")
    assert ident.proc == "p3" and ident.key() == "envrun/p3"
    fleet.configure_identity(role="router")
    assert fleet.get_identity().role == "router"
    # wire round-trip
    back = fleet.ProcessIdentity.from_dict(
        json.loads(json.dumps(ident.to_dict())))
    assert back == ident


def test_identity_stamped_on_expositions():
    reg = MetricsRegistry()
    reg.counter("serving/requests").add(1)
    text = exposition.render_prometheus(reg)
    assert 'dstpu_process_info{' in text and 'run_id="testrun"' in text
    doc = json.loads(exposition.render_json_snapshot(reg))
    assert doc["identity"]["run_id"] == "testrun"
    # the collector's federated render suppresses the single-process stamp
    assert "process_info" not in exposition.render_prometheus(
        reg, identity=False)


def test_identity_stamped_on_flight_record(tmp_path):
    from deepspeed_tpu.diagnostics.flight_recorder import FlightRecorder

    rec = FlightRecorder(capacity=4, dump_dir=str(tmp_path))
    rec.record(1, {"loss": 1.0})
    path = rec.dump(reason="test")
    header = json.loads(open(path).readline())
    assert header["identity"]["run_id"] == "testrun"
    assert header["identity"]["process_index"] == 0
    # per-process default filename: proc 0 keeps the historical name,
    # proc 2 gets a distinguishable one
    fleet.configure_identity(process_index=2)
    assert os.path.basename(rec._resolve_path(None)) == "flight_record.p2.jsonl"
    fleet.configure_identity(process_index=0)
    assert os.path.basename(rec._resolve_path(None)) == "flight_record.jsonl"


def test_observatory_rows_and_table_stamped(tmp_path):
    from deepspeed_tpu.collectives import observatory, table as table_mod

    obs = observatory.CollectiveObservatory()
    obs.configure(enabled=True, persist=False)
    row = obs.record_sample(op="all_reduce", algorithm="ring", codec="none",
                            backend="ppermute", world=8, size_mb=0.1,
                            latency_ms=1.0, itemsize=4)
    assert row["proc"] == "testrun/p0"
    path = obs.persist(str(tmp_path / "t.json"))
    payload = json.load(open(path))
    assert payload["identity"]["run_id"] == "testrun"
    # proc stamp does not participate in merge identity
    other = dict(row, proc="testrun/p1", latency_ms=3.0)
    merged = table_mod.merge_rows([row], [other], ema=0.5)
    assert len(merged) == 1 and merged[0]["latency_ms"] == 2.0


def test_observatory_default_table_path_is_per_process():
    from deepspeed_tpu.collectives import observatory

    assert observatory.default_table_path().endswith("coll_table.json")
    fleet.configure_identity(process_index=4)
    assert observatory.default_table_path().endswith("coll_table.p4.json")


# ----------------------------------------------------- federation (exact)
def test_histogram_merge_is_exact_property():
    """Merging K sharded registries == observing the concatenated stream:
    bucket counts and quantiles BIT-identical, counters sum, gauges keep
    last-per-process under {proc=}."""
    rng = np.random.default_rng(7)
    samples = np.concatenate([
        rng.lognormal(2.0, 1.8, 4000),
        [0.0, -3.0, 1e-12, 1e9],  # underflow + extreme buckets
    ])
    order = rng.permutation(len(samples))
    shards = [MetricsRegistry() for _ in range(4)]
    whole = MetricsRegistry()
    for j, i in enumerate(order):
        v = float(samples[i])
        shards[j % 4].histogram("serving/ttft_ms", k=8).observe(v)
        whole.histogram("serving/ttft_ms", k=8).observe(v)
        shards[j % 4].counter("serving/requests").add(1.0)
    for k, sh in enumerate(shards):
        sh.gauge("serving/queue_depth").set(float(10 + k))
    merged = MetricsRegistry()
    for k, sh in enumerate(shards):
        dump = fleet.registry_dump(
            sh, fleet.ProcessIdentity("testrun", k))
        dump = json.loads(json.dumps(dump))  # the real wire round-trip
        fleet.merge_dump_into(merged, dump)
    hm = merged.histogram("serving/ttft_ms", k=8)
    hw = whole.histogram("serving/ttft_ms", k=8)
    assert hm.count == hw.count
    assert dict(hm.buckets()) == dict(hw.buckets())  # bucket-wise identical
    assert (hm.min, hm.max) == (hw.min, hw.max)
    for q in (0.0, 0.5, 0.9, 0.95, 0.99, 1.0):
        assert hm.quantile(q) == hw.quantile(q), q  # bit-identical
    # counters: arithmetic sum (integers — exact)
    assert merged.counter("serving/requests").value == float(len(samples))
    # gauges: one child per process, no cross-process fold
    for k in range(4):
        assert merged.gauge("serving/queue_depth",
                            proc=f"p{k}").value == float(10 + k)


def test_decode_key_round_trip():
    from deepspeed_tpu.telemetry.registry import encode_labels

    for labels in ({}, {"k": "8"}, {"proc": "p1", "op": "all_reduce"}):
        key = "serving/x" + encode_labels(labels)
        name, back = decode_key(key)
        assert name == "serving/x" and back == labels


# ------------------------------------------------------------ collector
def _push_worker(collector, k, step_rate=10.0, requests=3):
    reg = MetricsRegistry()
    for _ in range(requests):
        reg.counter("serving/requests").add(1.0)
    reg.histogram("serving/ttft_ms").observe(5.0 * (k + 1))
    reg.gauge("serving/tokens_per_s").set(100.0)
    ident = fleet.ProcessIdentity("testrun", k, host="h", role="replica")
    client = FleetClient(collector.url, identity=ident, registry=reg,
                         observatory=None)
    assert client.register()["ok"]
    ack = client.push(heartbeat_extra={"step_rate": step_rate},
                      include_table=False)
    assert ack["ok"]
    return reg, client


def test_collector_federates_and_rolls_up():
    col = FleetCollector().start()
    try:
        regs = [_push_worker(col, k)[0] for k in range(3)]
        fed = col.federated_registry()
        # counters: bit-exact sum of the per-process registries
        expected = sum(r.counter("serving/requests").value for r in regs)
        assert fed.counter("serving/requests").value == expected
        # histogram: merged count
        assert fed.histogram("serving/ttft_ms").count == 3
        # gauges: per-proc children + rollup
        assert fed.gauge("serving/tokens_per_s", proc="p1").value == 100.0
        assert fed.gauge("fleet/tokens_per_s").value == 300.0
        assert fed.gauge("fleet/processes").value == 3.0
        assert fed.gauge("fleet/step_rate_min").value == 10.0
        text = col.render_prometheus()
        assert "dstpu_fleet_processes" in text
        assert 'dstpu_serving_tokens_per_s{proc="p2"}' in text
        # federated view carries no single-process info stamp
        assert "dstpu_process_info" not in text
    finally:
        col.stop()


def test_collector_per_role_rollups():
    """Disagg topology rollups (ISSUE 14): role membership and role-summed
    serving rates read as labelled children of the federated view."""
    col = FleetCollector().start()
    try:
        for k, role in enumerate(("prefill", "decode", "decode")):
            reg = MetricsRegistry()
            reg.gauge("serving/tokens_per_s").set(100.0 * (k + 1))
            ident = fleet.ProcessIdentity("testrun", k, host="h", role=role)
            client = FleetClient(col.url, identity=ident, registry=reg,
                                 observatory=None)
            assert client.register()["ok"]
            assert client.push(heartbeat_extra={"step_rate": 10.0 * (k + 1)},
                               include_table=False)["ok"]
        fed = col.federated_registry()
        assert fed.gauge("fleet/role_processes", role="prefill").value == 1.0
        assert fed.gauge("fleet/role_processes", role="decode").value == 2.0
        # role-summed tokens/s: decode pool = procs 1+2 = 200+300
        assert fed.gauge("fleet/tokens_per_s", role="decode").value == 500.0
        assert fed.gauge("fleet/tokens_per_s", role="prefill").value == 100.0
        # unlabelled rollup unchanged (the whole fleet)
        assert fed.gauge("fleet/tokens_per_s").value == 600.0
        assert fed.gauge("fleet/step_rate_min", role="decode").value == 20.0
        assert fed.gauge("fleet/step_rate_min").value == 10.0
        ledger = col.ledger()
        assert {r["identity"]["role"] for r in ledger["processes"]} == \
            {"prefill", "decode"}
    finally:
        col.stop()


def test_collector_http_endpoints_and_ledger():
    col = FleetCollector(stale_after_s=30.0).start()
    try:
        _push_worker(col, 1, step_rate=10.0)
        _push_worker(col, 2, step_rate=9.8)
        _push_worker(col, 3, step_rate=1.0)  # the straggler
        led = json.loads(urllib.request.urlopen(
            col.url + "/fleet", timeout=5).read())
        rows = {r["identity"]["process_index"]: r for r in led["processes"]}
        assert rows[3]["straggler"] and not rows[1]["straggler"]
        assert all(not r["stale"] for r in led["processes"])
        assert all(r["clock_offset_s"] is not None for r in led["processes"])
        text = urllib.request.urlopen(
            col.url + "/metrics", timeout=5).read().decode()
        assert "dstpu_fleet_straggler" in text
        doc = json.loads(urllib.request.urlopen(
            col.url + "/metrics.json", timeout=5).read())
        assert doc["metrics"]["serving/requests"] == 9.0
        hz = json.loads(urllib.request.urlopen(
            col.url + "/healthz", timeout=5).read())
        assert hz["ok"] and hz["processes"] == 3
    finally:
        col.stop()


def test_collector_replaces_not_adds_on_repush():
    """Pushes carry cumulative snapshots: a re-push must REPLACE the
    process's prior contribution (and a worker restart's reset counters
    must not go backwards at the collector)."""
    col = FleetCollector().start()
    try:
        reg, client = _push_worker(col, 0, requests=3)
        reg.counter("serving/requests").add(2.0)  # now 5 cumulative
        client.push(include_table=False)
        fed = col.federated_registry()
        assert fed.counter("serving/requests").value == 5.0  # not 8
    finally:
        col.stop()


def test_collector_scrape_mode():
    """Collector-initiated federation: GET the worker's /metrics.fleet."""
    reg = MetricsRegistry()
    reg.counter("serving/requests").add(4.0)
    srv = exposition.serve_metrics(registry=reg)
    col = FleetCollector().start()
    try:
        ack = col.scrape(f"http://127.0.0.1:{srv.port}")
        assert ack["ok"]
        assert col.federated_registry().counter(
            "serving/requests").value == 4.0
    finally:
        col.stop()
        srv.stop()


def test_federated_observatory_table_round_trip(tmp_path):
    """Rows pushed by two processes EMA-merge at the collector and a fresh
    selector consumes the federated table in measured mode."""
    from deepspeed_tpu.collectives import selector, table as table_mod

    col = FleetCollector().start()
    try:
        row = {"op": "all_reduce", "world": 8, "size_mb": 0.125,
               "algorithm": "ring", "codec": "none", "backend": "ppermute",
               "latency_ms": 2.0, "busbw_gbps": 1.0, "itemsize": 4,
               "samples": 1, "proc": "testrun/p1"}
        col.ingest({"identity": {"run_id": "testrun", "process_index": 1},
                    "coll_rows": [row]})
        col.ingest({"identity": {"run_id": "testrun", "process_index": 2},
                    "coll_rows": [dict(row, latency_ms=4.0,
                                       proc="testrun/p2")]})
        rows = col.table_rows()
        assert len(rows) == 1  # same signature -> ONE federated row
        assert 2.0 < rows[0]["latency_ms"] < 4.0  # EMA fold, not clobber
        # the HTTP surface serves a loadable versioned envelope
        tpath = tmp_path / "fleet_table.json"
        tpath.write_bytes(urllib.request.urlopen(
            col.url + "/coll_table", timeout=5).read())
        loaded = table_mod.load_table(str(tpath))
        assert len(loaded) == 1
    finally:
        col.stop()
    selector.configure(decision_table=str(tpath), mode="measured",
                       min_algorithmic_bytes=0)
    try:
        d = selector.select("all_reduce", int(0.125e6), 8, itemsize=4)
        assert (d.source, d.algorithm) == ("measured", "ring")
    finally:
        selector.configure()


def test_table_repush_replaces_not_inflates():
    """Cadence pushes carry the process's full cumulative table: a re-push
    must REPLACE that process's rows in the federation, never re-fold them
    (sample counts would inflate and the EMA would re-apply on identical
    data every interval)."""
    col = FleetCollector().start()
    try:
        row = {"op": "all_reduce", "world": 8, "size_mb": 0.125,
               "algorithm": "ring", "codec": "none", "backend": "ppermute",
               "latency_ms": 2.0, "busbw_gbps": 1.0, "itemsize": 4,
               "samples": 12, "proc": "testrun/p1"}
        for _ in range(5):  # five identical cadence pushes
            col.ingest({"identity": {"run_id": "testrun",
                                     "process_index": 1},
                        "coll_rows": [row]})
        rows = col.table_rows()
        assert len(rows) == 1
        assert rows[0]["samples"] == 12  # not 60
        assert rows[0]["latency_ms"] == 2.0  # EMA not re-applied
    finally:
        col.stop()


def test_straggler_threshold_consistent_between_gauge_and_ledger():
    """The fleet/straggler gauge and GET /fleet must agree on who is
    straggling: both consult the collector's configured straggler_mads."""
    col = FleetCollector(straggler_mads=3.0).start()
    try:
        # p3 sits ~4 MADs below the median: straggler at 3.0, not at 6.0
        for k, rate in ((0, 10.0), (1, 10.2), (2, 9.9), (3, 9.0)):
            _push_worker(col, k, step_rate=rate)
        led = {r["identity"]["process_index"]: r["straggler"]
               for r in col.ledger()["processes"]}
        gauges = {k: v for k, v in
                  col.federated_registry().gauges().items()
                  if k.startswith("fleet/straggler")}
        assert led[3] and not led[0]
        assert gauges['fleet/straggler{proc="p3"}'] == 1.0
        assert gauges['fleet/straggler{proc="p0"}'] == 0.0
    finally:
        col.stop()


def test_flow_name_matches_across_serve_generations(tmp_path):
    """Chrome binds flow arrows on (cat, name, id): the lifecycle track's
    flow NAME must be the context's (request-id-derived) spelling, not the
    local rid's — a second serve() call's rid 0 maps to a fleet request id
    > 0 and the remote dispatch step must still bind."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import trace_merge

    from deepspeed_tpu.inference.lifecycle import LifecycleTracker
    from deepspeed_tpu.telemetry import export_jsonl

    # router side: local rid 0, fleet request id 7 (second-generation)
    ctx = fleet.TraceContext.mint(7, run_id="testrun")
    tr_a = Tracer(enabled=True)
    tracker = LifecycleTracker(tr_a)
    tracker.arrive(0)
    tracker.admit(0, uid=0)
    tracker.set_trace_context(0, ctx)
    tracker.mark_dispatch([0], "prefill")
    tracker.emitted(0, 1)
    tracker.finish(0)
    pa = str(tmp_path / "a.jsonl")
    export_jsonl(pa, tracer=tr_a)
    # replica side: dispatch span from the wire context
    tr_b = Tracer(enabled=True)
    with fleet.dispatch_span(fleet.TraceContext.from_wire(ctx.to_wire()),
                             tracer=tr_b):
        pass
    fleet.configure_identity(process_index=1)
    pb = str(tmp_path / "b.jsonl")
    export_jsonl(pb, tracer=tr_b)
    merged = trace_merge.merge_streams([pa, pb])
    # linked_flow_pids binds on (cat, name, id) like the viewer: both
    # processes must land under ONE bindable key
    assert trace_merge.linked_flow_pids(merged)[ctx.flow_id] == [0, 1]
    names = {e["name"] for e in merged["traceEvents"]
             if e.get("ph") in ("s", "t", "f")}
    assert names == {ctx.flow_name}


def test_engine_fleet_client_is_process_global_per_url():
    """Two engines with the same fleet_url share ONE push client/thread."""
    from deepspeed_tpu.runtime.engine import _FLEET_CLIENTS, _get_fleet_client

    col = FleetCollector().start()
    try:
        _FLEET_CLIENTS.clear()
        a = _get_fleet_client(col.url, 60.0)
        b = _get_fleet_client(col.url, 60.0)
        assert a is b
    finally:
        _FLEET_CLIENTS.clear()
        col.stop()


def test_colliding_process_indices_get_distinct_labels():
    """Two standalone workers that both defaulted to process_index 0
    (distinct minted run_ids) must not clobber each other: gauges land
    under run_id-qualified {proc=} labels and the straggler math keeps
    both rates; fleet/processes counts ALL registered members, heartbeat
    or not, matching the ledger's row count."""
    col = FleetCollector().start()
    try:
        for run, rate in (("runA", 10.0), ("runB", 10.1), ("runC", 1.0)):
            reg = MetricsRegistry()
            reg.gauge("serving/queue_depth").set(ord(run[-1]) * 1.0)
            ident = fleet.ProcessIdentity(run, 0, host="h", role="worker")
            client = FleetClient(col.url, identity=ident, registry=reg,
                                 observatory=None)
            client.push(heartbeat_extra={"step_rate": rate},
                        include_table=False)
        # a registered-but-never-heartbeating member still counts
        col.ingest({"identity": {"run_id": "runD", "process_index": 0}})
        fed = col.federated_registry()
        gauges = fed.gauges()
        for run in ("runA", "runB", "runC"):
            key = f'serving/queue_depth{{proc="{run}/p0"}}'
            assert gauges[key] == ord(run[-1]) * 1.0, (key, gauges)
        assert gauges["fleet/processes"] == 4.0
        led = col.ledger()
        assert len(led["processes"]) == 4
        flags = {r["proc"]: r["straggler"] for r in led["processes"]}
        assert flags["runC/p0"] and not flags["runA/p0"]
        assert gauges['fleet/straggler{proc="runC/p0"}'] == 1.0
    finally:
        col.stop()


def test_cross_process_straggler_median_mad():
    rates = {"p0": 10.0, "p1": 10.2, "p2": 9.9, "p3": 1.0}
    flags = fleet.straggler_flags(rates)
    assert flags == {"p0": False, "p1": False, "p2": False, "p3": True}
    # identical healthy rates never flag on jitter (MAD floor)
    assert not any(fleet.straggler_flags(
        {f"p{i}": 10.0 for i in range(4)}).values())
    # below quorum: never flags
    assert fleet.straggler_flags({"p0": 10.0, "p1": 0.1}) == {
        "p0": False, "p1": False}


def test_push_async_latest_wins_and_flushes():
    """Hot-path pushes snapshot synchronously but pay HTTP on the worker;
    the single pending slot keeps the LATEST snapshot (cumulative dumps
    supersede), and flush() drains it."""
    col = FleetCollector().start()
    try:
        reg = MetricsRegistry()
        ident = fleet.ProcessIdentity("testrun", 1)
        client = FleetClient(col.url, identity=ident, registry=reg,
                             observatory=None)
        for i in range(5):
            reg.counter("serving/requests").add(1.0)
            client.push_async(include_table=False)
        client.flush()
        fed = col.federated_registry()
        # the LAST snapshot (5 cumulative) landed, whatever was dropped
        assert fed.counter("serving/requests").value == 5.0
        assert client.pushes >= 1
    finally:
        col.stop()


def test_fleet_client_failures_never_raise():
    client = FleetClient("http://127.0.0.1:1", timeout_s=0.2,
                         observatory=None)
    assert client.push(include_table=False) is None
    assert client.push_failures >= 1


# ----------------------------------------------------- distributed traces
def test_trace_context_stable_flow_id():
    a = fleet.TraceContext.mint(5, run_id="runA")
    b = fleet.TraceContext.from_wire(json.loads(json.dumps(a.to_wire())))
    assert b.flow_id == a.flow_id == fleet.flow_id_for("runA", 5)
    assert fleet.flow_id_for("runA", 6) != a.flow_id
    assert fleet.flow_id_for("runB", 5) != a.flow_id


def test_dispatch_span_emits_span_and_flow_step():
    tr = Tracer(enabled=True)
    ctx = fleet.TraceContext.mint(9, run_id="testrun")
    with fleet.dispatch_span(ctx, tracer=tr, replica=1):
        pass
    evs = tr.events()
    flow = next(e for e in evs if e["kind"] == "flow")
    span = next(e for e in evs if e["kind"] == "span")
    assert flow["id"] == ctx.flow_id and flow["ph"] == "t"
    assert span["name"] == "serve:dispatch"
    assert span["args"]["request_id"] == 9
    # the flow step is INSIDE the span (the arrow binds to the slice)
    assert span["ts"] <= flow["ts"] <= span["ts"] + span["dur"]
    # disabled tracer: no-op, no events
    tr2 = Tracer(enabled=False)
    with fleet.dispatch_span(ctx, tracer=tr2):
        pass
    assert tr2.events() == []


def test_trace_merge_joins_streams(tmp_path):
    """Two tracers (distinct identities, offset origins) -> one merged
    trace: distinct pids, aligned timeline, flow linked across pids."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import trace_merge

    from deepspeed_tpu.telemetry import export_jsonl

    ctx = fleet.TraceContext.mint(3, run_id="testrun")
    # router process: admission + flow start
    tr_a = Tracer(enabled=True)
    with tr_a.span("admit", cat="router"):
        tr_a.flow(f"req-{ctx.request_id}", ctx.flow_id, "start")
    fleet.configure_identity(process_index=0, role="router")
    pa = str(tmp_path / "a.jsonl")
    export_jsonl(pa, tracer=tr_a)
    # replica process: dispatch span + flow step (identity switched to p1
    # before ITS export — each stream carries its own meta line)
    tr_b = Tracer(enabled=True)
    tr_b._origin_unix = tr_a.origin_unix() + 0.5  # skewed origin
    with fleet.dispatch_span(ctx, tracer=tr_b):
        pass
    fleet.configure_identity(process_index=1, role="replica")
    pb = str(tmp_path / "b.jsonl")
    export_jsonl(pb, tracer=tr_b)

    merged = trace_merge.merge_streams([pa, pb])
    evs = merged["traceEvents"]
    pids = {e["pid"] for e in evs if e.get("ph") not in ("M",)}
    assert pids == {0, 1}
    links = trace_merge.linked_flow_pids(merged)
    assert links[ctx.flow_id] == [0, 1]  # the cross-process arrow
    # the replica's dispatch span landed 0.5s later on the merged timeline
    disp = next(e for e in evs if e.get("name") == "serve:dispatch")
    admit = next(e for e in evs if e.get("name") == "admit")
    assert disp["ts"] >= admit["ts"] + 0.4e6  # us
    # process metadata names both roles
    names = {e["pid"]: e["args"]["name"] for e in evs
             if e.get("name") == "process_name"}
    assert "router" in names[0] and "replica" in names[1]


def test_trace_merge_applies_ledger_offsets(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import trace_merge

    from deepspeed_tpu.telemetry import export_jsonl

    tr = Tracer(enabled=True)
    tr.instant("x")
    pa = str(tmp_path / "a.jsonl")
    export_jsonl(pa, tracer=tr)
    ledger = {"processes": [{"proc": "testrun/p0", "clock_offset_s": 2.0}]}
    lp = tmp_path / "fleet.json"
    lp.write_text(json.dumps(ledger))
    m0 = trace_merge.merge_streams([pa])
    m1 = trace_merge.merge_streams([pa], ledger=str(lp))
    # single stream: offset shifts the base too, timeline unchanged — but
    # the offset must parse and apply without error
    assert len(m1["traceEvents"]) == len(m0["traceEvents"])


# ------------------------------------------------------------- /healthz
def test_healthz_reports_identity_step_age_and_size():
    reg = MetricsRegistry()
    reg.counter("serving/requests").add(1)
    reg.gauge("serving/queue_depth").set(2)
    fleet.note_step(42)
    srv = exposition.serve_metrics(registry=reg)
    try:
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=5).read())
        assert doc["ok"] and doc["identity"]["run_id"] == "testrun"
        assert doc["step"] == 42 and doc["age_s"] is not None
        assert doc["registry_size"] == 2
        dump = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics.fleet", timeout=5).read())
        assert dump["counters"]["serving/requests"] == 1.0
    finally:
        srv.stop()


def test_last_step_info_before_any_step():
    fleet.reset_identity()
    assert fleet.last_step_info() == {"step": None, "age_s": None}


# ---------------------------------------------------- engine config wiring
def test_engine_fleet_url_config_wires_client_and_heartbeat():
    """`telemetry.fleet_url` builds a FleetClient on the engine, the
    per-step note_step feeds the heartbeat, and the collector's ledger sees
    the training process after a couple of steps."""
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    col = FleetCollector().start()
    try:
        cfg = TransformerConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_layers=2, num_heads=2, max_seq_len=32)
        eng, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(cfg, example_seq_len=16),
            config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1},
                "steps_per_print": 10_000,
                "telemetry": {"enabled": True, "fleet_url": col.url,
                              "fleet_push_interval_s": 60.0,
                              "fleet_role": "train"},
            })
        assert eng._fleet_client is not None
        batch = {"input_ids": np.zeros((eng.train_batch_size, 16), np.int32)}
        for _ in range(2):
            eng.train_batch(batch)
        # the interval is long; push explicitly (what the daemon would do)
        ack = eng._fleet_client.push(include_table=False)
        assert ack["ok"]
        led = col.ledger()
        row = next(r for r in led["processes"]
                   if r["identity"]["role"] == "train")
        assert row["heartbeat"]["step"] == 2
        assert row["heartbeat"]["last_step_age_s"] is not None
        assert row["clock_offset_s"] is not None
        fed = col.federated_registry()
        # the training registry federated: span histograms made it across
        assert fed.histogram("span/train_batch").count >= 2
    finally:
        col.stop()


# -------------------------------------------- 3-process integration smoke
def test_three_process_fleet_smoke(tmp_path):
    """The acceptance gate: collector + 2 real CPU worker processes.
    Federated counters bit-exactly equal the per-process sums; the merged
    trace links router admission flows into both workers' serve:dispatch
    spans; the federated observatory table round-trips into a fresh
    selector's measured mode."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "fleet_smoke.py"),
         "--out", str(tmp_path), "--workers", "2", "--requests", "2"],
        capture_output=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout.decode() + out.stderr.decode()[-800:]
    doc = json.loads(out.stdout.decode().strip().splitlines()[-1])
    assert doc["ok"]
    assert doc["counters_bit_exact"]
    assert doc["federated_requests"] == doc["expected_requests"] == 10.0
    assert doc["trace_linked"] and doc["cross_process_flow_links"] >= 1
    assert doc["dispatch_pids"] == [1, 2]
    assert doc["ledger_ok"] and doc["ledger_replicas"] == 2
    assert doc["coll_table_round_trip"]
    # the merged trace artifact is a loadable Chrome trace with 3 processes
    merged = json.load(open(doc["merged_trace"]))
    pnames = [e["args"]["name"] for e in merged["traceEvents"]
              if e.get("name") == "process_name"]
    assert len(pnames) == 3
