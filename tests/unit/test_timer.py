"""Timer regression tests."""

import time

from deepspeed_tpu.utils.timer import Timer, SynchronizedWallClockTimer, ThroughputTimer


def test_timer_elapsed_reset_while_running_does_not_double_count():
    t = Timer("t", synchronize=False)
    t.start()
    time.sleep(0.03)
    first = t.elapsed(reset=True)
    time.sleep(0.03)
    t.stop()
    second = t.elapsed(reset=True)
    assert first >= 0.025
    # second interval must not include the first
    assert second < first + 0.03


def test_wallclock_group_and_log():
    timers = SynchronizedWallClockTimer(synchronize=False)
    timers("fwd").start()
    timers("fwd").stop()
    msg = timers.log(["fwd", "missing"])
    assert "fwd" in msg


def test_throughput_timer():
    tt = ThroughputTimer(batch_size=4, steps_per_output=1000)
    for _ in range(3):
        tt.start()
        tt.stop()
    assert tt.global_step_count == 3
    assert tt.avg_samples_per_sec() > 0
