"""Elastic snapshot suite: atomic commit under writer crashes, checksum
fallback, mesh-reshape restore, cadence/pruning, ckpt telemetry.

Coverage model: the reference's universal-checkpoint reshape tests plus the
durability semantics its Nebula tier promises (publish only after persist) —
here proven by FAULT INJECTION (``diagnostics/faultinject.py``) rather than
asserted in prose: the writer is killed between shard writes, shards are
truncated on disk, and `latest` must keep loading something good.
"""

import json
import os

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.checkpoint import snapshot as snap
from deepspeed_tpu.diagnostics import FaultInjector
from tests.unit.simple_model import random_batch, simple_model_spec


def _config(stage=1, mesh=None, snapshot=None, micro=2, extra=None):
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
        "steps_per_print": 1000,
        **(extra or {}),
    }
    if mesh:
        cfg["mesh"] = mesh
    if snapshot:
        cfg["snapshot"] = snapshot
    return cfg


def _engine(tmp_path, seed=3, stage=1, mesh=None, every=100, extra=None, **snap_kw):
    e, *_ = deepspeed_tpu.initialize(
        model=simple_model_spec(),
        config=_config(stage=stage, mesh=mesh, extra=extra,
                       snapshot={"enabled": True, "dir": str(tmp_path),
                                 "every_n_steps": every, "fsync": False,
                                 **snap_kw}),
        seed=seed)
    return e


def _train(engine, steps, seed0=0):
    for i in range(steps):
        engine.train_batch(random_batch(engine.train_batch_size, seed=seed0 + i))


def _state_leaves(engine):
    tree = {"params": engine.state.params,
            "opt": engine.canonical_opt_state(engine.state.opt_state)}
    # deep copies, not device_get views — these references outlive later
    # donated train steps (utils.compat.host_copy_unaliased)
    return [np.array(x, copy=True)
            for x in jax.tree_util.tree_leaves(jax.device_get(tree))]


def _assert_state_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


# ------------------------------------------------------------------ roundtrip
def test_snapshot_roundtrip_bit_identical_and_restored_engine_keeps_stepping(
        devices, tmp_path):
    """Save → drift → restore is bit-identical, and the restored fused
    (donating) engine keeps stepping for MANY steps — the regression test
    that replaces the PR-1 'step each restored engine at most once' fence."""
    e = _engine(tmp_path)
    _train(e, 3)
    e.snapshot_manager.snapshot(blocking=True)
    saved = _state_leaves(e)
    tag = snap.latest_tag(str(tmp_path))
    assert tag == "step000003"

    _train(e, 2, seed0=50)  # drift
    assert e.restore_snapshot(str(tmp_path)) == tag
    assert e.global_steps == 3
    _assert_state_equal(saved, _state_leaves(e))

    # the landmine regression: restored state lives in fresh committed
    # buffers, so continued stepping of the donating engine is safe
    _train(e, 5, seed0=100)
    assert e.global_steps == 8


def test_async_snapshot_off_the_step_clock(devices, tmp_path):
    """The cadenced save returns before durability; wait() is the barrier
    and the committed snapshot holds the state of ITS boundary, not a later
    one (the host copy happened at the boundary)."""
    e = _engine(tmp_path, every=2)
    _train(e, 2)
    expected = _state_leaves(e)  # state at the step-2 boundary
    _train(e, 1, seed0=77)  # overlaps the background write
    e.snapshot_manager.wait()
    assert snap.latest_tag(str(tmp_path)) == "step000002"
    e.restore_snapshot(str(tmp_path))
    _assert_state_equal(expected, _state_leaves(e))


# ------------------------------------------------------- crash-mid-save/atomic
@pytest.mark.parametrize("at", ["shard", "manifest", "commit"])
def test_crash_mid_save_keeps_latest_loadable(devices, tmp_path, at):
    """Writer killed between shard writes / before the manifest / before the
    commit rename: `latest` still names the previous durable snapshot and
    restoring it works; the crashed write leaves only a tmp dir."""
    e = _engine(tmp_path)
    _train(e, 2)
    e.snapshot_manager.snapshot(blocking=True)
    good = snap.latest_tag(str(tmp_path))
    good_state = _state_leaves(e)

    fi = FaultInjector()
    fi.kill_writer(e.snapshot_manager, after_shards=1, at=at)
    _train(e, 2, seed0=10)
    e.snapshot_manager.snapshot()  # dies in the writer thread
    with pytest.raises(snap.SnapshotError):
        e.snapshot_manager.wait()
    assert fi.writer_kills_fired == 1
    assert snap.latest_tag(str(tmp_path)) == good
    assert snap.list_snapshots(str(tmp_path)) == [good]

    _train(e, 1, seed0=20)  # drift past the crash
    assert e.restore_snapshot(str(tmp_path)) == good
    _assert_state_equal(good_state, _state_leaves(e))

    # the injected fault was transient (times=1): the next snapshot commits
    e.snapshot_manager.snapshot(blocking=True)
    assert snap.latest_tag(str(tmp_path)) == "step000002"  # same step after rewind


def test_truncated_shard_falls_back_to_previous_tag(devices, tmp_path, caplog):
    """Checksum mismatch on the latest snapshot: load_checkpoint-level
    restore validates BEFORE touching device state and falls back to the
    previous tag with a loud warning instead of crashing."""
    e = _engine(tmp_path)
    _train(e, 2)
    e.snapshot_manager.snapshot(blocking=True)
    older_state = _state_leaves(e)
    older = snap.latest_tag(str(tmp_path))
    _train(e, 2, seed0=30)
    e.snapshot_manager.snapshot(blocking=True)
    newest = snap.latest_tag(str(tmp_path))
    assert newest != older

    FaultInjector.truncate_shard(str(tmp_path), tag=newest, shard_index=1)
    import logging

    lg = logging.getLogger("deepspeed_tpu")
    lg.propagate = True  # the repo logger defaults propagate=False; caplog
    try:
        with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
            # via the engine's load_checkpoint: a snapshot-only dir routes here
            tag, _client = e.load_checkpoint(str(tmp_path))
    finally:
        lg.propagate = False
    assert tag == older
    assert any("checksum mismatch" in r.message for r in caplog.records)
    assert any("falling back" in r.message for r in caplog.records)
    _assert_state_equal(older_state, _state_leaves(e))


def test_corrupt_manifest_and_no_fallback_raises(devices, tmp_path):
    e = _engine(tmp_path)
    _train(e, 1)
    e.snapshot_manager.snapshot(blocking=True)
    only = snap.latest_tag(str(tmp_path))
    FaultInjector.corrupt_manifest(str(tmp_path), tag=only)
    with pytest.raises(snap.SnapshotCorruptionError):
        e.restore_snapshot(str(tmp_path))


# ------------------------------------------------------------- mesh reshape
def test_mesh_reshape_restore_8_to_4_and_1(devices, tmp_path):
    """The reshape matrix: a snapshot from an 8-way dp mesh restores onto
    4-way and 1-way meshes BIT-IDENTICALLY (state compared leaf-for-leaf
    against the saving engine), and the resumed trajectory matches the
    uninterrupted 8-way run."""
    from deepspeed_tpu.topology.mesh import MESH_AXES
    from jax.sharding import Mesh

    e8 = _engine(tmp_path, seed=3)
    _train(e8, 3)
    e8.snapshot_manager.snapshot(blocking=True)
    saved = _state_leaves(e8)
    tag = snap.latest_tag(str(tmp_path))

    _train(e8, 2, seed0=100)  # uninterrupted continuation -> baseline
    baseline = jax.device_get(e8.state.params)  # e8 never steps again

    def submesh(n):
        shape = [1] * len(MESH_AXES)
        shape[MESH_AXES.index("dp")] = n
        return Mesh(np.asarray(jax.devices()[:n]).reshape(shape), MESH_AXES)

    for world in (4, 1):
        eN, *_ = deepspeed_tpu.initialize(
            model=simple_model_spec(),
            config=_config(extra={"train_batch_size": e8.train_batch_size}),
            seed=99,  # different init — must be overwritten by the restore
            mesh=submesh(world))
        assert eN.restore_snapshot(str(tmp_path), tag=tag) == tag
        assert eN.global_steps == 3
        _assert_state_equal(saved, _state_leaves(eN))  # bit-identical restore

        # resume with the SAME global batches the 8-way run consumed
        _train(eN, 2, seed0=100)
        for a, b in zip(jax.tree_util.tree_leaves(baseline),
                        jax.tree_util.tree_leaves(jax.device_get(eN.state.params))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-6, atol=1e-7)


def test_reshape_across_zero_stages(devices, tmp_path):
    """ZeRO re-partitioning on restore: stage-1 dp=8 snapshot restores into a
    stage-3 dp=2 x fsdp=4 engine (sharded params) with identical logical
    state, and the restored engine trains."""
    e1 = _engine(tmp_path, stage=1)
    _train(e1, 2)
    e1.snapshot_manager.snapshot(blocking=True)
    saved = _state_leaves(e1)

    e3, *_ = deepspeed_tpu.initialize(
        model=simple_model_spec(),
        config=_config(stage=3, mesh={"dp": 2, "fsdp": 4},
                       extra={"train_batch_size": e1.train_batch_size}),
        seed=99)
    e3.restore_snapshot(str(tmp_path))
    _assert_state_equal(saved, _state_leaves(e3))
    _train(e3, 2, seed0=7)
    assert e3.global_steps == 4


# ------------------------------------------------------------ format details
def test_manifest_schema_shards_and_pruning(devices, tmp_path):
    """Manifest carries the partition/provenance metadata the restore matrix
    needs; large atoms split into bounded shard files; pruning keeps the
    newest `keep` snapshots and drops stale tmp dirs."""
    e = _engine(tmp_path, keep=2, shard_megabytes=1)
    _train(e, 1)
    mgr = e.snapshot_manager
    mgr.snapshot(blocking=True)
    tag = snap.latest_tag(str(tmp_path))
    man = snap.read_manifest(str(tmp_path), tag)
    assert man["format_version"] == snap.FORMAT_VERSION
    assert man["step"] == 1
    assert man["source_mesh"]["dp"] == 8
    assert man["zero_stage"] == 1
    assert man["payload_bytes"] == sum(s["bytes"] for s in man["shards"])
    for s in man["shards"]:
        assert set(s) >= {"file", "atom", "dtype", "shape", "slice", "sha256"}
        assert len(s["sha256"]) == 64
    atom_keys = {s["atom"] for s in man["shards"]}
    assert any(k.startswith("['params']") for k in atom_keys)
    assert any(k.startswith("['opt_state']") for k in atom_keys)

    # tiny shard cap -> a multi-row atom splits into multiple slices
    atoms = {"['x']": np.arange(64, dtype=np.float32).reshape(8, 8)}
    snap.write_snapshot(atoms, {"step": 0}, str(tmp_path / "direct"),
                        "step000000", shard_bytes=64, fsync=False)
    man2 = snap.read_manifest(str(tmp_path / "direct"), "step000000")
    slices = [s for s in man2["shards"] if s["atom"] == "['x']"]
    assert len(slices) > 1 and slices[0]["slice"] == [0, slices[0]["shape"][0]]
    loaded, _ = snap.load_snapshot_atoms(str(tmp_path / "direct"), "step000000")
    np.testing.assert_array_equal(loaded["['x']"], atoms["['x']"])

    # pruning: 3 snapshots with keep=2 -> oldest removed; STALE tmp dirs from
    # other pids removed, recent ones kept (a live writer sharing the dir
    # must not lose its in-flight write)
    for i in range(2):
        _train(e, 1, seed0=10 * (i + 1))
        mgr.snapshot(blocking=True)
    stale = os.path.join(snap.snapshot_root(str(tmp_path)), "stepX.tmp-1")
    fresh = os.path.join(snap.snapshot_root(str(tmp_path)), "stepY.tmp-2")
    os.makedirs(stale)
    os.makedirs(fresh)
    os.utime(stale, (0, 0))  # crashed long ago
    snap.prune_snapshots(str(tmp_path), keep=2)
    tags = snap.list_snapshots(str(tmp_path))
    assert tags == ["step000002", "step000003"]
    assert not os.path.exists(stale)
    assert os.path.exists(fresh)  # age-gated: could be a live writer's


def test_snapshot_telemetry_gauges_and_spans(devices, tmp_path):
    """ckpt/save_ms|bytes|inflight gauges land in the shared registry and the
    ckpt:snapshot / ckpt:commit spans appear in the trace (scrapeable via the
    PR-5 /metrics exposition)."""
    from deepspeed_tpu import telemetry

    tr = telemetry.get_tracer()
    tr.configure(enabled=True)
    tr.reset()
    try:
        e = _engine(tmp_path, extra={"telemetry": {"enabled": True}})
        _train(e, 1)
        e.snapshot_manager.snapshot(blocking=True)
        gauges = tr.registry.gauges()
        assert gauges.get("ckpt/save_ms", 0) > 0
        assert gauges.get("ckpt/bytes", 0) > 0
        assert gauges.get("ckpt/inflight") == 0
        names = {ev.get("name") for ev in tr.events()}
        assert "ckpt:snapshot" in names and "ckpt:commit" in names
        prom = telemetry.render_prometheus(tr.registry)
        assert "dstpu_ckpt_save_ms" in prom and "dstpu_ckpt_bytes" in prom
    finally:
        tr.configure(enabled=False)
        tr.reset()


def test_nvme_offload_snapshot_carries_and_rewinds_optimizer_moments(tmp_path):
    """An NVMe-offload engine holds ``opt_state=None`` between steps (the
    moments live on disk). The snapshot paths must materialize them — a
    snapshot missing every optimizer atom committed silently, and a rewind
    left ``_opt_on_nvme`` pointing at the aborted timeline's stale moments."""
    snapdir = tmp_path / "snaps"

    def nvme_engine(swap):
        cfg = _config(snapshot={"enabled": True, "dir": str(snapdir),
                                "every_n_steps": 100, "fsync": False})
        cfg["zero_optimization"]["offload_optimizer"] = {
            "device": "nvme", "nvme_path": str(tmp_path / swap)}
        e, *_ = deepspeed_tpu.initialize(
            model=simple_model_spec(), config=cfg, seed=3)
        return e

    e = nvme_engine("swap_a")
    _train(e, 2)
    assert e.state.opt_state is None  # precondition: moments are on NVMe
    e.snapshot_manager.snapshot(blocking=True)
    atoms, _manifest = snap.load_latest_atoms(str(snapdir))
    assert any("opt_state" in k for k in atoms), sorted(atoms)[:5]

    e.materialize_state()
    saved = _state_leaves(e)
    _train(e, 2, seed0=10)  # divergent timeline writes new moments to NVMe
    tag = e.restore_snapshot(str(snapdir))
    assert tag is not None
    e.materialize_state()
    _assert_state_equal(saved, _state_leaves(e))

    # continued stepping must consume the RESTORED moments, not swap the
    # divergent timeline's back in: match an uninterrupted run bit-for-bit
    _train(e, 1, seed0=2)
    base = nvme_engine("swap_b")
    _train(base, 3)
    e.materialize_state()
    base.materialize_state()
    _assert_state_equal(_state_leaves(base), _state_leaves(e))


def test_failed_async_save_does_not_consume_next_boundary(devices, tmp_path):
    """A transient async write failure is reported at the next cadenced
    boundary — but reporting it must not eat that boundary's save (regression:
    snapshot()'s raise-pending-first consumed the enqueue, silently doubling
    the rewind window)."""
    e = _engine(tmp_path, every=1)
    mgr = e.snapshot_manager
    _train(e, 1)  # boundary 1: clean save
    mgr.wait()
    fi = FaultInjector()
    fi.kill_writer(mgr, after_shards=1, times=1)
    _train(e, 1)  # boundary 2: save enqueued, writer crashes mid-write
    th = mgr._inflight
    if th is not None:
        th.join()  # writer dead, error stashed — deliberately not drained
    assert fi.writer_kills_fired == 1
    _train(e, 1)  # boundary 3: must report the stale failure AND still save
    mgr.wait()
    assert mgr.save_failures == 1
    assert snap.latest_tag(str(tmp_path)) == "step000003"


def test_sole_snapshot_overwrite_crash_window_recovers(devices, tmp_path):
    """Same-tag overwrite of the SOLE committed snapshot: a crash between the
    slide-aside and the swap-in leaves 'latest' empty and the only durable
    copy under '<tag>.old.tmp-<pid>'. load_latest_atoms must re-commit it
    instead of reporting 'no snapshots'."""
    e = _engine(tmp_path, every=100)
    _train(e, 1)
    e.snapshot_manager.snapshot(blocking=True)
    root = snap.snapshot_root(str(tmp_path))
    os.replace(os.path.join(root, "step000001"),
               os.path.join(root, "step000001.old.tmp-99999"))
    with open(os.path.join(root, "latest"), "w") as f:
        f.write("")
    assert snap.list_snapshots(str(tmp_path)) == []
    atoms, manifest = snap.load_latest_atoms(str(tmp_path))
    assert manifest["tag"] == "step000001" and atoms
    assert snap.latest_tag(str(tmp_path)) == "step000001"
    assert snap.list_snapshots(str(tmp_path)) == ["step000001"]
