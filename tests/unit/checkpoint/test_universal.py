"""Universal checkpoint + engine suite.

Coverage model: reference ``tests/unit/checkpoint/`` (14 files) — zero
round-trips, universal reshape across parallel layouts
(``TestZeROUniversalCheckpointDP``), latest-tag handling — plus the
checkpoint-engine ABC behavior.
"""

import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint import (
    AsyncCheckpointEngine,
    convert_to_fp32_file,
    get_checkpoint_engine,
    get_fp32_state_dict_from_checkpoint,
)
from deepspeed_tpu.utils.compat import host_copy_unaliased
from tests.unit.simple_model import random_batch, simple_model_spec


def _config(stage=0, mesh=None, micro=2):
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
        "steps_per_print": 1000,
    }
    if mesh:
        cfg["mesh"] = mesh
    return cfg


def _train(engine, steps, seed=0):
    for i in range(steps):
        engine.train_batch(random_batch(engine.train_batch_size, seed=seed + i))


def _params_close(a, b, **kw):
    import jax

    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)


def test_universal_reshape_across_meshes(devices, tmp_path):
    """Save under ZeRO-1 dp=8, resume under ZeRO-3 dp=2×fsdp=4: trajectories
    must agree with an uninterrupted run (the TestZeROUniversalCheckpointDP
    analog, but across *stages and meshes* in one hop)."""
    d = str(tmp_path)
    e1, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(stage=1), seed=3)
    _train(e1, 4)
    e1.save_universal_checkpoint(d)

    # continue the original for 3 more steps -> baseline
    _train(e1, 3, seed=100)
    baseline = e1.state.params

    # fresh engine on a different mesh + stage, universal-restored
    e3, *_ = deepspeed_tpu.initialize(
        model=simple_model_spec(),
        config=_config(stage=3, mesh={"dp": 2, "fsdp": 4}),
        seed=99,  # different init — must be overwritten by the restore
    )
    e3.load_checkpoint(d, load_universal=True)
    assert e3.global_steps == 4
    _train(e3, 3, seed=100)
    _params_close(baseline, e3.state.params, rtol=2e-5, atol=2e-6)


def test_universal_strict_mismatch_raises(devices, tmp_path):
    d = str(tmp_path)
    e, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(), seed=0)
    e.save_universal_checkpoint(d)
    other, *_ = deepspeed_tpu.initialize(
        model=simple_model_spec(depth=3), config=_config(), seed=0
    )
    with pytest.raises(ValueError):
        other.load_checkpoint(d, load_universal=True)


def test_zero_to_fp32_consolidation(devices, tmp_path):
    """fp32 consolidation matches the live master params (zero_to_fp32)."""
    d = str(tmp_path)
    e, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(stage=2), seed=1)
    _train(e, 2)
    e.save_universal_checkpoint(d)
    sd = get_fp32_state_dict_from_checkpoint(d)
    live = {k: np.asarray(v) for k, v in
            ((kp, lv) for kp, lv in _flat_params(e.state.params))}
    assert set(sd) == set(live)
    for k in sd:
        np.testing.assert_allclose(sd[k], live[k], rtol=1e-6)
    out = convert_to_fp32_file(d, str(tmp_path / "consolidated.npz"))
    data = np.load(out)
    assert set(data.files) == set(live)


def _flat_params(params):
    import jax

    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        yield jax.tree_util.keystr(path), leaf


def test_regular_checkpoint_roundtrip_and_latest(devices, tmp_path):
    d = str(tmp_path)
    e, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(stage=1), seed=2)
    _train(e, 3)
    e.save_checkpoint(d, client_state={"epoch": 7})
    import jax
    # deep copy, not a device_get view: later donated train steps can write
    # through the zero-copy view (utils.compat.host_copy_unaliased)
    saved = host_copy_unaliased(e.state.params)
    _train(e, 2)  # drift
    path, client = e.load_checkpoint(d)
    assert path is not None and client["epoch"] == 7
    assert e.global_steps == 3
    _params_close(saved, e.state.params, rtol=0, atol=0)
    assert open(os.path.join(d, "latest")).read().strip() == "global_step3"
    # restored state lives in fresh committed buffers ('fresh' placement):
    # the donating fused engine keeps stepping — the old landmine shape
    _train(e, 3, seed=200)
    assert e.global_steps == 6


def test_async_checkpoint_engine(devices, tmp_path):
    d = str(tmp_path)
    e, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(), seed=4)
    _train(e, 1)
    eng = AsyncCheckpointEngine()
    from deepspeed_tpu.checkpoint.checkpointing import save_checkpoint

    save_checkpoint(e, d, checkpoint_engine=eng)  # returns before durable
    import jax
    # deep copy, not a device_get view: later donated train steps can write
    # through the zero-copy view (utils.compat.host_copy_unaliased)
    saved = host_copy_unaliased(e.state.params)
    _train(e, 1)  # overlaps with the background write
    eng.commit("")  # durability barrier before reading
    e.load_checkpoint(d)
    _params_close(saved, e.state.params, rtol=0, atol=0)
    eng.shutdown()


def test_get_checkpoint_engine_selection():
    from deepspeed_tpu.checkpoint import AsyncCheckpointEngine, OrbaxCheckpointEngine

    assert isinstance(get_checkpoint_engine("orbax"), OrbaxCheckpointEngine)
    eng = get_checkpoint_engine("nebula")
    assert isinstance(eng, AsyncCheckpointEngine)
    eng.shutdown()
    with pytest.raises(ValueError):
        get_checkpoint_engine("bogus")


def test_universal_checkpoint_moe_expert_params(tmp_path, devices):
    """MoE-specific checkpoint handling (reference engine.py:3375 expert
    checkpoint special-casing): ep-sharded expert params round-trip through a
    universal checkpoint into a DIFFERENT ep layout."""
    import deepspeed_tpu
    from deepspeed_tpu.checkpoint.universal import load_universal, save_universal
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                            num_layers=2, num_heads=2, max_seq_len=16,
                            num_experts=4, moe_top_k=1)

    def make(mesh):
        e, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(cfg, example_seq_len=8),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                    "mesh": mesh, "steps_per_print": 1000})
        return e

    e1 = make({"dp": 2, "ep": 4})
    batch = {"input_ids": np.random.default_rng(0).integers(0, 64, (8, 8), dtype=np.int32)}
    e1.train_batch(batch)
    save_universal(e1, str(tmp_path))

    e2 = make({"dp": 4, "ep": 2})  # different expert-parallel degree
    load_universal(e2, str(tmp_path))
    np.testing.assert_allclose(
        np.asarray(e2.state.params["layers"]["moe"]["experts"]["w_up"]),
        np.asarray(e1.state.params["layers"]["moe"]["experts"]["w_up"]), rtol=1e-6)
    l2 = float(e2.train_batch(batch)["loss"])
    l1 = float(e1.train_batch(batch)["loss"])
    np.testing.assert_allclose(l2, l1, rtol=1e-4)
