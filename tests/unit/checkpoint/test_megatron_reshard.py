"""Legacy Megatron checkpoint reshard (reference state_dict_factory.py:21,190).

The capability: a Megatron-LM GPT checkpoint saved at TP degree N loads at any
other degree — merge mp_rank shards to the full state, convert, and placement
(AutoTP) supplies the new degree.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.checkpoint.megatron import (
    config_from_megatron,
    convert_megatron_state,
    load_megatron_model,
    merge_tp_state_dicts,
    split_tp_state_dict,
)
from deepspeed_tpu.models import CausalLM

torch = pytest.importorskip("torch")

H_, HEADS, INTER, LAYERS, VOCAB, SEQ = 32, 4, 64, 2, 128, 64


def _full_megatron_state(seed=0):
    rng = np.random.default_rng(seed)
    r = lambda *s: rng.standard_normal(s).astype(np.float32) * 0.2  # noqa: E731
    state = {
        "embedding.word_embeddings.weight": r(VOCAB, H_),
        "embedding.position_embeddings.weight": r(SEQ, H_),
        "transformer.final_layernorm.weight": r(H_) + 1.0,
        "transformer.final_layernorm.bias": r(H_),
    }
    for i in range(LAYERS):
        p = f"transformer.layers.{i}."
        state.update({
            p + "input_layernorm.weight": r(H_) + 1.0,
            p + "input_layernorm.bias": r(H_),
            p + "post_attention_layernorm.weight": r(H_) + 1.0,
            p + "post_attention_layernorm.bias": r(H_),
            p + "attention.query_key_value.weight": r(3 * H_, H_),
            p + "attention.query_key_value.bias": r(3 * H_),
            p + "attention.dense.weight": r(H_, H_),
            p + "attention.dense.bias": r(H_),
            p + "mlp.dense_h_to_4h.weight": r(INTER, H_),
            p + "mlp.dense_h_to_4h.bias": r(INTER),
            p + "mlp.dense_4h_to_h.weight": r(H_, INTER),
            p + "mlp.dense_4h_to_h.bias": r(H_),
        })
    return state


def test_split_merge_roundtrip_and_reshard():
    full = _full_megatron_state()
    for tp in (2, 4):
        back = merge_tp_state_dicts(split_tp_state_dict(full, tp))
        assert set(back) == set(full) | {"_checkpoint_version"}  # in-band metadata
        for k in full:
            np.testing.assert_array_equal(back[k], full[k], err_msg=k)
    # reshard 2 -> 4: merge the 2-way shards, re-split 4-way, merge again
    via2 = merge_tp_state_dicts(split_tp_state_dict(full, 2))
    via4 = merge_tp_state_dicts(split_tp_state_dict(via2, 4))
    for k in full:
        np.testing.assert_array_equal(via4[k], full[k], err_msg=k)


def test_tp_split_semantics_match_parallel_compute():
    """The split axes must BE Megatron's parallelism: column-parallel output
    concat == full output; row-parallel partial sums == full output; blocked
    q|k|v stays q|k|v per rank."""
    full = _full_megatron_state()
    tp = 2
    shards = split_tp_state_dict(full, tp)
    x = np.random.default_rng(1).standard_normal(H_).astype(np.float32)

    colw = "transformer.layers.0.mlp.dense_h_to_4h.weight"
    np.testing.assert_allclose(
        np.concatenate([s[colw] @ x for s in shards]), full[colw] @ x, rtol=1e-5)

    roww = "transformer.layers.0.mlp.dense_4h_to_h.weight"
    xi = np.random.default_rng(2).standard_normal(INTER).astype(np.float32)
    partial = sum(s[roww] @ xi_part
                  for s, xi_part in zip(shards, np.split(xi, tp)))
    np.testing.assert_allclose(partial, full[roww] @ xi, rtol=1e-4)

    qkvw = "transformer.layers.0.attention.query_key_value.weight"
    q_full = full[qkvw][:H_]
    q_ranks = np.concatenate([s[qkvw][: H_ // tp] for s in shards])
    np.testing.assert_array_equal(q_ranks, q_full)


def test_megatron_load_convert_logits_consistent(tmp_path):
    """End to end: full state -> tp=2 mp_rank dirs (torch .pt, megatron
    nesting) -> load_megatron_model -> logits must equal converting the
    unsharded state directly."""
    full = _full_megatron_state()
    shards = split_tp_state_dict(full, 2)
    for r, sd in enumerate(shards):
        d = tmp_path / f"mp_rank_{r:02d}"
        os.makedirs(d)
        nested = {"model": {"language_model": {
            "embedding": {
                "word_embeddings": {"weight": torch.tensor(sd["embedding.word_embeddings.weight"])},
                "position_embeddings": {"weight": torch.tensor(sd["embedding.position_embeddings.weight"])},
            },
            "transformer": {k.split("transformer.", 1)[1]: torch.tensor(v)
                            for k, v in sd.items() if k.startswith("transformer.")},
        }}}
        torch.save(nested, str(d / "model_optim_rng.pt"))

    cfg, params = load_megatron_model(str(tmp_path), num_heads=HEADS)
    assert cfg.num_layers == LAYERS and cfg.vocab_size == VOCAB

    want_params = convert_megatron_state(full, cfg)
    ids = np.random.default_rng(0).integers(0, VOCAB, (2, 10))
    module = CausalLM(cfg)

    def logits(p):
        return module.apply({"params": jax.tree_util.tree_map(jnp.asarray, p)},
                            {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)[1]

    np.testing.assert_allclose(np.asarray(logits(params)),
                               np.asarray(logits(want_params)), rtol=1e-5, atol=1e-6)
    assert np.isfinite(np.asarray(logits(params))).all()


def _reinterleave_qkv(full, version):
    """Rewrite the canonical v0 blocked q|k|v rows into the given
    checkpoint_version's row layout (reference state_dict_factory.py:220)."""
    hd = H_ // HEADS
    out = dict(full)
    for k, v in full.items():
        if "query_key_value" not in k:
            continue
        rest = v.shape[1:]
        q, kk, vv = (t.reshape(HEADS, hd, *rest) for t in np.split(v, 3, axis=0))
        axis = 2 if version == 1.0 else 1  # v1: [H, hd, 3]; v2: [H, 3, hd]
        out[k] = np.stack([q, kk, vv], axis=axis).reshape(3 * H_, *rest)
    return out


@pytest.mark.parametrize("version", [1.0, 2.0])
def test_megatron_checkpoint_version_layouts(tmp_path, version):
    """v1.0/v2.0 checkpoints store per-head-interleaved qkv rows and merge by
    plain concat; loading one must produce the SAME params as the equivalent
    v0 checkpoint (reference merge_query_key_value branches on ckpt_ver)."""
    from deepspeed_tpu.checkpoint.megatron import load_megatron_model

    full_v0 = _full_megatron_state()
    full_ver = _reinterleave_qkv(full_v0, version)
    shards = split_tp_state_dict(full_ver, 2, version=version)
    for r, sd in enumerate(shards):
        d = tmp_path / f"mp_rank_{r:02d}"
        os.makedirs(d)
        nested = {"checkpoint_version": version, "model": {"language_model": {
            "embedding": {
                "word_embeddings": {"weight": torch.tensor(sd["embedding.word_embeddings.weight"])},
                "position_embeddings": {"weight": torch.tensor(sd["embedding.position_embeddings.weight"])},
            },
            "transformer": {k.split("transformer.", 1)[1]: torch.tensor(v)
                            for k, v in sd.items() if k.startswith("transformer.")},
        }}}
        torch.save(nested, str(d / "model_optim_rng.pt"))

    cfg, params = load_megatron_model(str(tmp_path), num_heads=HEADS)
    want = convert_megatron_state(full_v0, cfg)  # no _checkpoint_version -> v0
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), params, want)

    # resharding a loaded v1/v2 state WITHOUT the version kwarg must honor the
    # in-band version (not scramble rows through the v0 thirds split)
    from deepspeed_tpu.checkpoint.megatron import load_megatron_checkpoint
    state = load_megatron_checkpoint(str(tmp_path))
    back = merge_tp_state_dicts(split_tp_state_dict(state, 4))
    for k in full_ver:
        np.testing.assert_allclose(back[k], full_ver[k], rtol=1e-6, err_msg=k)


def test_megatron_unknown_checkpoint_version_raises(tmp_path):
    """A future/unknown checkpoint_version must fail loudly, not load blocked-
    layout math onto interleaved rows (reference asserts, ours raises)."""
    full = _full_megatron_state()
    sd = split_tp_state_dict(full, 1)[0]
    d = tmp_path / "mp_rank_00"
    os.makedirs(d)
    nested = {"checkpoint_version": 3.0, "model": {"language_model": {
        "embedding": {
            "word_embeddings": {"weight": torch.tensor(sd["embedding.word_embeddings.weight"])},
            "position_embeddings": {"weight": torch.tensor(sd["embedding.position_embeddings.weight"])},
        },
        "transformer": {k.split("transformer.", 1)[1]: torch.tensor(v)
                        for k, v in sd.items() if k.startswith("transformer.")},
    }}}
    torch.save(nested, str(d / "model_optim_rng.pt"))
    from deepspeed_tpu.checkpoint.megatron import load_megatron_checkpoint
    with pytest.raises(ValueError, match="checkpoint_version"):
        load_megatron_checkpoint(str(tmp_path))


def test_config_inference_from_state():
    full = _full_megatron_state()
    cfg = config_from_megatron(full, num_heads=HEADS)
    assert (cfg.hidden_size, cfg.intermediate_size, cfg.num_layers,
            cfg.vocab_size, cfg.max_seq_len) == (H_, INTER, LAYERS, VOCAB, SEQ)
    assert cfg.norm == "layernorm" and cfg.position == "learned" and cfg.tie_embeddings
