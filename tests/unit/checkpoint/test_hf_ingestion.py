"""HF checkpoint ingestion (reference module_inject/load_checkpoint.py +
inference/v2/engine_factory.py): safetensors -> param pytree -> engines.

Ground truth is the transformers implementation itself: a tiny random HF model
is saved with save_pretrained, ingested, and must reproduce the HF logits.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.checkpoint.hf import (
    config_from_hf,
    convert_hf_state,
    detect_family,
    load_hf_checkpoint,
)
from deepspeed_tpu.models import CausalLM

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _save_tiny_llama(tmp_path, tie=False, moe=False):
    if moe:
        cfg = transformers.MixtralConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, rope_theta=10000.0,
            num_local_experts=4, num_experts_per_tok=2,
            tie_word_embeddings=tie,
        )
        model = transformers.MixtralForCausalLM(cfg)
    else:
        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, rope_theta=10000.0,
            tie_word_embeddings=tie,
        )
        model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def test_llama_ingestion_logits_parity(tmp_path):
    hf_model = _save_tiny_llama(tmp_path)
    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert cfg.norm == "rmsnorm" and cfg.num_kv_heads == 2

    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()

    module = CausalLM(cfg)
    _, logits = module.apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-3, atol=2e-4)


def test_qwen2_ingestion_logits_parity(tmp_path):
    """qwen2 = llama graph + qkv biases; family auto-detected from bias keys."""
    cfg_hf = transformers.Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    hf_model = transformers.Qwen2ForCausalLM(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert cfg.qkv_bias is True
    assert "bias" in params["layers"]["attn"]["wq"]

    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()

    module = CausalLM(cfg)
    _, logits = module.apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("act,want_act", [("relu", "relu"), ("gelu", "gelu_exact")])
def test_opt_ingestion_logits_parity(tmp_path, act, want_act):
    """OPT: layernorm + relu/exact-gelu + learned positions (offset-2 rows)."""
    cfg_hf = transformers.OPTConfig(
        vocab_size=128, hidden_size=32, ffn_dim=64, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
        word_embed_proj_dim=32, activation_function=act,
    )
    hf_model = transformers.OPTForCausalLM(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert cfg.activation == want_act and cfg.position == "learned"
    assert params["pos_embed"].shape == (64, 32)  # offset rows stripped

    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()

    module = CausalLM(cfg)
    _, logits = module.apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-3, atol=2e-4)


def test_falcon_ingestion_logits_parity(tmp_path):
    """Falcon-7B style: parallel attn+MLP block, MQA, fused qkv, bias-free."""
    cfg_hf = transformers.FalconConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, new_decoder_architecture=False,
        multi_query=True, parallel_attn=True, bias=False, alibi=False,
        max_position_embeddings=64,
    )
    hf_model = transformers.FalconForCausalLM(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert cfg.parallel_block and cfg.kv_heads == 1 and cfg.dense_bias is False
    assert "mlp_norm" not in params["layers"]

    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()

    module = CausalLM(cfg)
    _, logits = module.apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-3, atol=2e-4)


def test_phi_ingestion_logits_parity(tmp_path):
    """Phi: parallel block + partial rotary + biased head and projections."""
    cfg_hf = transformers.PhiConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, partial_rotary_factor=0.5,
    )
    hf_model = transformers.PhiForCausalLM(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert cfg.parallel_block and cfg.rotary_dim == 4 and cfg.lm_head_bias
    assert "bias" in params["lm_head"]

    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()

    module = CausalLM(cfg)
    _, logits = module.apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-3, atol=2e-4)


def test_gpt2_ingestion_logits_parity(tmp_path):
    cfg_hf = transformers.GPT2Config(
        vocab_size=96, n_embd=32, n_layer=2, n_head=4, n_positions=64)
    hf_model = transformers.GPT2LMHeadModel(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert cfg.norm == "layernorm" and cfg.tie_embeddings

    ids = np.random.default_rng(1).integers(0, 96, (2, 10))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()
    module = CausalLM(cfg)
    _, logits = module.apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("multi_query", [True, False])
def test_gpt_bigcode_ingestion_logits_parity(tmp_path, multi_query):
    """starcoder/santacoder-style (round 5; reference module_inject bigcode
    containers): Linear-oriented c_attn, one shared KV head when multi_query.
    The MHA variant (nightly) pins the [3h, h]-vs-[h, 3h] family detection."""
    cfg_hf = transformers.GPTBigCodeConfig(
        vocab_size=96, n_embd=32, n_layer=2, n_head=4, n_positions=64,
        multi_query=multi_query, activation_function="gelu_pytorch_tanh")
    hf_model = transformers.GPTBigCodeForCausalLM(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert cfg.kv_heads == (1 if multi_query else 4)
    assert cfg.norm == "layernorm" and cfg.tie_embeddings

    ids = np.random.default_rng(3).integers(0, 96, (2, 10))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()
    module = CausalLM(cfg)
    _, logits = module.apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-3, atol=2e-4)


def test_mixtral_ingestion_structure(tmp_path):
    """Mixtral converts to the exact tree the in-repo MoE CausalLM expects
    (logits parity is not pinned: HF routes without capacity dropping)."""
    _save_tiny_llama(tmp_path, moe=True)
    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert cfg.num_experts == 4

    module = CausalLM(cfg)
    batch = {"input_ids": jnp.zeros((2, 8), jnp.int32)}
    want = jax.eval_shape(
        lambda: module.init({"params": jax.random.PRNGKey(0)}, batch, train=False)["params"])
    got = jax.tree_util.tree_map(jnp.asarray, params)
    want_flat = jax.tree_util.tree_flatten_with_path(want)[0]
    got_flat = {jax.tree_util.keystr(k): v for k, v in jax.tree_util.tree_flatten_with_path(got)[0]}
    for k, leaf in want_flat:
        ks = jax.tree_util.keystr(k)
        assert ks in got_flat, f"missing {ks}"
        assert got_flat[ks].shape == leaf.shape, f"{ks}: {got_flat[ks].shape} != {leaf.shape}"
    # and it runs
    loss, _ = module.apply({"params": got}, {"input_ids": jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (2, 8)), jnp.int32)}, train=False)
    assert np.isfinite(float(loss))


def test_init_inference_tp2_from_hf(tmp_path, devices):
    """VERDICT round-2 'done' bar: tiny llama safetensors -> init_inference
    (tp=2) on the CPU mesh -> generate."""
    import deepspeed_tpu

    _save_tiny_llama(tmp_path)
    cfg, params = load_hf_checkpoint(str(tmp_path))
    engine = deepspeed_tpu.init_inference(
        cfg, config={"tensor_parallel": {"tp_size": 2}, "dtype": "float32", "seq_bucket": 8},
        params=params)
    out = engine.generate(np.asarray([[5, 6, 7]]), max_new_tokens=4, do_sample=False)
    assert out.shape == (1, 7)


def test_build_hf_engine_v2_from_checkpoint(tmp_path):
    """One-call HF dir -> v2 continuous-batching engine (reference
    ``inference/v2/engine_factory.py:69 build_hf_engine``); greedy output
    matches the v1 engine on the same checkpoint."""
    import deepspeed_tpu

    _save_tiny_llama(tmp_path)
    eng = deepspeed_tpu.build_hf_engine(
        str(tmp_path), {"dtype": "fp32", "kv_block_size": 4, "num_kv_blocks": 32})
    prompt = np.asarray([5, 6, 7], dtype=np.int32)
    out = eng.generate([prompt], max_new_tokens=4)[0]
    assert out.shape == (4,) and out.dtype == np.int32
    # v2-output-vs-v1 parity itself is pinned by
    # test_continuous_batching_interleaves; this test owns the factory glue:
    # config ingestion produced a generatable engine with clean bookkeeping
    assert len(eng.state._seqs) == 0


def test_initialize_training_from_hf(tmp_path, devices):
    """HF params feed initialize(model_parameters=...) and train."""
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm_spec

    _save_tiny_llama(tmp_path)
    cfg, params = load_hf_checkpoint(str(tmp_path))
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=8),
        model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2}, "steps_per_print": 100},
    )
    batch = {"input_ids": np.random.default_rng(0).integers(0, 128, (8, 8), dtype=np.int32)}
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(3)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_detect_family():
    assert detect_family({"model.layers.0.self_attn.q_proj.weight": 0}) == "llama"
    # c_attn orientation separates gpt2 (Conv1D [in, 3in]) from gpt_bigcode
    # ([out, in] Linear; out = 3in for MHA, in + 2*head_dim for MQA)
    assert detect_family({"h.0.attn.c_attn.weight": np.zeros((8, 24))}) == "gpt2"
    assert detect_family({"h.0.attn.c_attn.weight": np.zeros((24, 8))}) == "gpt_bigcode"
    assert detect_family({"h.0.attn.c_attn.weight": np.zeros((12, 8))}) == "gpt_bigcode"
    assert detect_family({"model.layers.0.block_sparse_moe.gate.weight": 0}) == "mixtral"
    with pytest.raises(ValueError):
        detect_family({"bogus": 0})


def test_config_from_hf_rejects_unknown():
    with pytest.raises(ValueError, match="model_type"):
        config_from_hf({"model_type": "resnet"})


def test_gpt_neox_ingestion_logits_parity(tmp_path):
    """GPT-NeoX: per-head fused QKV (fusedqkv_utils 'glmtype' ordering),
    partial rotary, parallel residual with SEPARATE mlp norm."""
    cfg_hf = transformers.GPTNeoXConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.5, rotary_emb_base=10000,
        use_parallel_residual=True, hidden_act="gelu",
        tie_word_embeddings=False,
    )
    hf_model = transformers.GPTNeoXForCausalLM(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert cfg.parallel_block and cfg.parallel_mlp_norm
    assert cfg.rotary_dim == 4  # 0.5 * head_dim(8)
    assert "mlp_norm" in params["layers"]

    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()

    module = CausalLM(cfg)
    _, logits = module.apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-3, atol=2e-4)


def test_gpt_neox_sequential_residual_parity(tmp_path):
    cfg_hf = transformers.GPTNeoXConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=False, tie_word_embeddings=False,
    )
    hf_model = transformers.GPTNeoXForCausalLM(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)
    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert not cfg.parallel_block

    ids = np.random.default_rng(1).integers(0, 128, (1, 10))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()
    _, logits = CausalLM(cfg).apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-3, atol=2e-4)


def test_bloom_ingestion_logits_parity(tmp_path):
    """Bloom: ALiBi position biases, embedding layernorm, per-head fused QKV
    ('bloomtype' ordering), tied head."""
    cfg_hf = transformers.BloomConfig(
        vocab_size=128, hidden_size=32, n_layer=2, n_head=4,
        layer_norm_epsilon=1e-5, tie_word_embeddings=True,
    )
    hf_model = transformers.BloomForCausalLM(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert cfg.position == "alibi" and cfg.embed_norm and cfg.tie_embeddings
    assert "embed_norm" in params

    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()

    module = CausalLM(cfg)
    _, logits = module.apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-3, atol=2e-4)


def test_bloom_generate_matches_hf(tmp_path):
    """The DECODE path's alibi (slopes * cache-slot position) must agree with
    HF greedy generation, not just teacher-forcing logits."""
    import deepspeed_tpu

    cfg_hf = transformers.BloomConfig(
        vocab_size=128, hidden_size=32, n_layer=2, n_head=4,
        tie_word_embeddings=True,
    )
    hf_model = transformers.BloomForCausalLM(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_hf_checkpoint(str(tmp_path))
    eng = deepspeed_tpu.init_inference(
        cfg, params=params, config={"dtype": "float32", "seq_bucket": 8})

    ids = np.random.default_rng(0).integers(5, 128, (1, 6))
    with torch.no_grad():
        want = hf_model.generate(
            torch.tensor(ids), max_new_tokens=6, do_sample=False,
            pad_token_id=0).numpy()
    got = eng.generate(ids, max_new_tokens=6, do_sample=False)
    np.testing.assert_array_equal(got, want)


def test_gptj_ingestion_logits_parity(tmp_path):
    """GPT-J: INTERLEAVED rotary (rotate_every_two), parallel block with one
    shared ln_1, bias-free attention + biased MLP, biased untied lm_head."""
    cfg_hf = transformers.GPTJConfig(
        vocab_size=128, n_embd=32, n_layer=2, n_head=4, n_positions=64,
        rotary_dim=4, n_inner=None, activation_function="gelu_new",
        tie_word_embeddings=False,
    )
    hf_model = transformers.GPTJForCausalLM(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert cfg.rope_interleaved and cfg.parallel_block and not cfg.parallel_mlp_norm
    assert cfg.rotary_dim == 4 and cfg.mlp_bias and cfg.lm_head_bias
    assert "bias" not in params["layers"]["attn"]["wq"]
    assert "bias" in params["layers"]["mlp"]["w_up"]

    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()

    module = CausalLM(cfg)
    _, logits = module.apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-3, atol=2e-4)


def test_gptj_generate_matches_hf(tmp_path):
    """Decode path with interleaved partial rotary must agree with HF greedy."""
    import deepspeed_tpu

    cfg_hf = transformers.GPTJConfig(
        vocab_size=128, n_embd=32, n_layer=2, n_head=4, n_positions=64,
        rotary_dim=4, tie_word_embeddings=False)
    hf_model = transformers.GPTJForCausalLM(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_hf_checkpoint(str(tmp_path))
    eng = deepspeed_tpu.init_inference(
        cfg, params=params, config={"dtype": "float32", "seq_bucket": 8})
    ids = np.random.default_rng(1).integers(5, 128, (1, 6))
    with torch.no_grad():
        want = hf_model.generate(torch.tensor(ids), max_new_tokens=6,
                                 do_sample=False, pad_token_id=0).numpy()
    got = eng.generate(ids, max_new_tokens=6, do_sample=False)
    np.testing.assert_array_equal(got, want)


def test_codegen_ingestion_logits_parity(tmp_path):
    """CodeGen: gpt-j graph + the mp_num-blocked fused QKV (reference
    fusedqkv_utils 'codegentype' — q|V|K order inside each of 4 groups)."""
    # n_head=8 > mp_num=4: TWO heads per mp group, so the blocked layout is
    # exercised in its non-degenerate form (intra-group head ordering)
    cfg_hf = transformers.CodeGenConfig(
        vocab_size=128, n_embd=32, n_layer=2, n_head=8, n_positions=64,
        rotary_dim=2, activation_function="gelu_new",
        tie_word_embeddings=False,
    )
    hf_model = transformers.CodeGenForCausalLM(cfg_hf)
    hf_model.eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_hf_checkpoint(str(tmp_path))
    assert cfg.rope_interleaved and cfg.parallel_block and cfg.mlp_bias

    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()

    module = CausalLM(cfg)
    _, logits = module.apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        {"input_ids": jnp.asarray(ids, jnp.int32)}, train=False)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-3, atol=2e-4)
