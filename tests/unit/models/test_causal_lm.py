"""CausalLM model family tests on the CPU mesh."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import PRESETS, TransformerConfig, causal_lm_spec
from tests.unit.parallel.partial_manual import partial_manual_xfail


def _tokens(bs, seq, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, size=(bs, seq), dtype=np.int32)}


def _cfg(stage=0, mesh=None, micro=1, extra=None):
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage, "param_persistence_threshold": 1},
        "steps_per_print": 1000,
    }
    if mesh:
        cfg["mesh"] = mesh
    if extra:
        cfg.update(extra)
    return cfg


TINY = TransformerConfig(
    vocab_size=256, hidden_size=32, intermediate_size=64, num_layers=2,
    num_heads=4, num_kv_heads=2, max_seq_len=32,
)


def test_tiny_llama_trains(devices):
    engine, *_ = deepspeed_tpu.initialize(model=causal_lm_spec(TINY), config=_cfg())
    batch = _tokens(engine.train_batch_size, 16)
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0]
    # initial loss near ln(vocab)
    assert abs(losses[0] - np.log(256)) < 1.0


def test_gpt2_style_trains(devices):
    cfg = TransformerConfig(
        vocab_size=256, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, max_seq_len=32, norm="layernorm", activation="gelu",
        position="learned", tie_embeddings=True,
    )
    engine, *_ = deepspeed_tpu.initialize(model=causal_lm_spec(cfg), config=_cfg())
    batch = _tokens(engine.train_batch_size, 16)
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0]


@partial_manual_xfail
def test_tp_matches_pure_dp(devices):
    """tp=2 must reproduce the dp-only loss trajectory (same seed/data).

    The baseline uses an idle pp axis to get the same dp width (4) on 8
    devices, so both engines see identical global batches.
    """
    e1, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TINY), config=_cfg(mesh={"dp": 4, "pp": 2}), seed=4
    )
    e2, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TINY), config=_cfg(mesh={"dp": 4, "tp": 2}), seed=4
    )
    assert e1.train_batch_size == 4 and e2.train_batch_size == 4
    l1 = [float(e1.train_batch(_tokens(4, 16, seed=30 + i))["loss"]) for i in range(3)]
    l2 = [float(e2.train_batch(_tokens(4, 16, seed=30 + i))["loss"]) for i in range(3)]
    np.testing.assert_allclose(l1, l2, rtol=1e-4)
    # params are tp-sharded
    import jax

    sharded = [
        x for x in jax.tree_util.tree_leaves(e2.state.params)
        if any(ax == "tp" for e in x.sharding.spec for ax in (e if isinstance(e, tuple) else (e,)) if e)
    ]
    assert sharded, "expected at least one tp-sharded parameter"


def test_zero3_tp_composition(devices):
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TINY), config=_cfg(stage=3, mesh={"dp": 2, "fsdp": 2, "tp": 2})
    )
    batch = _tokens(engine.train_batch_size, 16)
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(4)]
    assert losses[-1] < losses[0]


def test_remat_and_no_scan_match(devices):
    base = causal_lm_spec(TINY)
    remat_cfg = TransformerConfig(**{**TINY.__dict__, "remat": True})
    e1, *_ = deepspeed_tpu.initialize(model=base, config=_cfg(), seed=11)
    e2, *_ = deepspeed_tpu.initialize(model=causal_lm_spec(remat_cfg), config=_cfg(), seed=11)
    b = _tokens(e1.train_batch_size, 16, seed=5)
    l1 = float(e1.train_batch(b)["loss"])
    l2 = float(e2.train_batch(b)["loss"])
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_presets_exist():
    assert "llama3-8b" in PRESETS and "gpt2-125m" in PRESETS
    assert PRESETS["llama3-8b"].num_params() > 7e9
    assert 1.0e8 < PRESETS["gpt2-125m"].num_params() < 2.0e8


@pytest.mark.parametrize("n_exp,top_k,residual", [(0, 2, False), (4, 2, False), (4, 1, True)])
def test_num_params_matches_init(devices, n_exp, top_k, residual):
    """Analytic num_params == actual initialized leaf count (dense/MoE/PR-MoE)."""
    import jax

    cfg = TransformerConfig(**{
        **TINY.__dict__, "num_experts": n_exp, "moe_top_k": top_k,
        "moe_use_residual": residual,
    })
    engine, *_ = deepspeed_tpu.initialize(model=causal_lm_spec(cfg), config=_cfg())
    actual = sum(x.size for x in jax.tree.leaves(engine.state.params))
    assert actual == cfg.num_params()
    if n_exp:
        assert cfg.num_active_params() < cfg.num_params()
    else:
        assert cfg.num_active_params() == cfg.num_params()


def test_padding_mask(devices):
    engine, *_ = deepspeed_tpu.initialize(model=causal_lm_spec(TINY), config=_cfg())
    batch = _tokens(engine.train_batch_size, 16)
    mask = np.ones((engine.train_batch_size, 16), np.int32)
    mask[:, 8:] = 0
    batch["attention_mask"] = mask
    m = engine.train_batch(batch)
    assert np.isfinite(m["loss"])


def test_attention_kernels_tp_sharded(devices):
    """Regression: q/k/v kernels must carry the tp placement (keystr paths)."""
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TINY), config=_cfg(mesh={"dp": 4, "tp": 2})
    )
    wq = engine.state.params["layers"]["attn"]["wq"]["kernel"]
    assert "tp" in str(wq.sharding.spec), wq.sharding.spec
    wo = engine.state.params["layers"]["attn"]["wo"]["kernel"]
    assert "tp" in str(wo.sharding.spec), wo.sharding.spec


def test_sparse_attention_model_trains(devices):
    """attn_impl='sparse' (reference sparse_attention config section): the
    model runs the tile-skipping kernels fwd+bwd through the engine, and a
    DENSE layout reproduces the standard path exactly."""
    import numpy as np
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    common = dict(vocab_size=128, hidden_size=32, intermediate_size=64,
                  num_layers=2, num_heads=4, max_seq_len=64,
                  norm="layernorm", activation="gelu", position="learned")
    ids = np.random.default_rng(0).integers(0, 128, (8, 64), dtype=np.int32)

    def run(**extra):
        engine, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(TransformerConfig(**common, **extra),
                                 example_seq_len=64),
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
                    "zero_optimization": {"stage": 1},
                    "steps_per_print": 10000, "seed": 3})
        return [float(np.asarray(engine.train_batch({"input_ids": ids})["loss"]))
                for _ in range(3)]

    # dense layout == exact attention (XLA path) trajectory
    l_dense_layout = run(attn_impl="sparse",
                         sparse_attention={"mode": "dense", "block": 16})
    l_exact = run(attn_impl="xla")
    np.testing.assert_allclose(l_dense_layout, l_exact, rtol=2e-5, atol=2e-6)

    # bigbird layout trains (loss decreases through the sparse bwd kernels)
    l_bb = run(attn_impl="sparse",
               sparse_attention={"mode": "bigbird", "block": 16,
                                 "num_random_blocks": 1,
                                 "num_sliding_window_blocks": 2})
    assert l_bb[-1] < l_bb[0]
