"""Launcher + env report (coverage model: reference tests/unit/launcher/:
hostfile parsing, runner command construction, user-args handling)."""

import os
import subprocess
import sys

import pytest

from deepspeed_tpu.env_report import collect_versions, report
from deepspeed_tpu.launcher import build_launch_commands, filter_hosts, parse_hostfile


HOSTFILE = """
# cluster
worker-1 slots=4
worker-2 slots=4
worker-3 slots=8  # trailing comment
"""


def test_parse_hostfile():
    hosts = parse_hostfile(HOSTFILE, from_text=True)
    assert hosts == {"worker-1": 4, "worker-2": 4, "worker-3": 8}
    with pytest.raises(ValueError):
        parse_hostfile("a slots=2\na slots=4", from_text=True)  # duplicate
    with pytest.raises(ValueError):
        parse_hostfile("# nothing\n", from_text=True)


def test_include_exclude_filters():
    hosts = parse_hostfile(HOSTFILE, from_text=True)
    assert list(filter_hosts(hosts, include="worker-2")) == ["worker-2"]
    assert list(filter_hosts(hosts, exclude="worker-2")) == ["worker-1", "worker-3"]
    with pytest.raises(ValueError):
        filter_hosts(hosts, include="worker-1", exclude="worker-2")
    with pytest.raises(ValueError):
        filter_hosts(hosts, include="nope")


def test_build_launch_commands_multihost():
    hosts = parse_hostfile(HOSTFILE, from_text=True)
    cmds = build_launch_commands(hosts, "train.py", ["--lr", "1e-4"])
    assert len(cmds) == 3
    # multi-host goes through ssh with the per-host process id
    host, argv = cmds[1]
    assert host == "worker-2" and argv[0] == "ssh"
    joined = " ".join(argv)
    assert "--process-id 1" in joined and "--num-processes 3" in joined
    assert "--coordinator worker-1:29500" in joined
    assert "train.py --lr 1e-4" in joined


def test_build_launch_commands_single_host_no_ssh():
    cmds = build_launch_commands({"localhost": 1}, "t.py", [])
    (host, argv), = cmds
    assert host == "localhost" and "ssh" not in argv
    assert argv[0] == sys.executable


def test_dry_run_cli(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("a slots=2\nb slots=2\n")
    from deepspeed_tpu.launcher.runner import main

    rc = main(["--hostfile", str(hf), "--dry_run", "train.py", "--x", "1"])
    assert rc == 0


def test_local_launch_runs_script(tmp_path):
    """Single-host end-to-end: the launcher actually executes the script."""
    script = tmp_path / "hello.py"
    out = tmp_path / "out.txt"
    script.write_text(f"import sys; open({str(out)!r}, 'w').write(' '.join(sys.argv[1:]))")
    repo_root = str(__import__("pathlib").Path(__file__).resolve().parents[3])
    env = dict(os.environ, PYTHONPATH=repo_root, JAX_PLATFORMS="cpu")
    rc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
         "--coordinator", "localhost:29999", "--num-processes", "1",
         "--process-id", "0", "--", str(script), "alpha", "beta"],
        env=env, timeout=120,
    ).returncode
    assert rc == 0
    assert out.read_text() == "alpha beta"


def test_ds_report():
    vs = collect_versions()
    assert "jax" in vs and vs["jax"] != "not installed"
    r = report()
    assert "op compatibility" in r and "deepspeed_tpu" in r


def test_single_remote_host_uses_ssh():
    """One REMOTE host must still go through ssh (only local hosts run inline)."""
    cmds = build_launch_commands({"tpu-vm-1": 4}, "train.py", [])
    (host, argv), = cmds
    assert host == "tpu-vm-1" and argv[0] == "ssh"
    assert "cd " in " ".join(argv)  # remote cwd preserved


def test_flat_torch_state_dict_keys_shard():
    from deepspeed_tpu.parallel.autotp import infer_tp_spec
    from jax.sharding import PartitionSpec as P

    assert infer_tp_spec("['self_attn.q_proj.weight']", (64, 32)) == P("tp", None)
    assert infer_tp_spec("['model.embed_tokens.weight']", (256, 32)) == P("tp", None)


def test_ds_ssh_quotes_remote_command():
    """ds_ssh must shlex-quote remote args (spaces/metacharacters survive)."""
    import subprocess
    import unittest.mock as mock

    from deepspeed_tpu.launcher.ssh import run_on_hosts

    with mock.patch("subprocess.run") as r:
        r.return_value = subprocess.CompletedProcess([], 3, "a\nb\n", "")
        code = run_on_hosts(["h1"], ["ls", "my dir", "a;b"])
    assert code == 3
    argv = r.call_args[0][0]
    assert argv[:2] == ["ssh", "-o"] and argv[-1] == "ls 'my dir' 'a;b'"
