"""End-to-end engine tests on the 8-device CPU mesh.

Coverage model: reference ``tests/unit/runtime/zero/test_zero.py`` (stage
correctness vs an unsharded baseline) + ``half_precision`` loss-scale tests.
"""

import numpy as np
import pytest

import deepspeed_tpu
from tests.unit.simple_model import make_dataset, random_batch, simple_model_spec
from tests.unit.parallel.partial_manual import partial_manual_xfail


def _config(stage=0, dtype="fp32", mesh=None, gas=1, micro=2, extra=None):
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
        "steps_per_print": 1000,
    }
    if dtype == "bf16":
        cfg["bf16"] = {"enabled": True}
    elif dtype == "fp16":
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8, "loss_scale_window": 4}
    if mesh:
        cfg["mesh"] = mesh
    if extra:
        cfg.update(extra)
    return cfg


def _train(engine, steps=5, seed=0):
    losses = []
    for i in range(steps):
        batch = random_batch(engine.train_batch_size, seed=seed + i)
        m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    return losses


def test_engine_trains_and_loss_decreases(devices):
    engine, opt, _, _ = deepspeed_tpu.initialize(
        model=simple_model_spec(), config=_config(stage=0)
    )
    assert engine.train_batch_size == 16  # micro=2 * dp=8
    losses = _train(engine, steps=10)
    assert losses[-1] < losses[0] * 0.9
    assert engine.global_steps == 10


@pytest.mark.parametrize(
    "stage", [1, 2, pytest.param(3, marks=partial_manual_xfail)])
def test_zero_stage_matches_stage0(devices, stage):
    """Same data + seed: sharded stages must track the unsharded trajectory."""
    mesh = {"dp": 2, "fsdp": 4} if stage == 3 else None
    e0, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(stage=0), seed=7)
    es, *_ = deepspeed_tpu.initialize(
        model=simple_model_spec(),
        config=_config(stage=stage, mesh=mesh, extra={"zero_optimization": {"stage": stage, "param_persistence_threshold": 1}}),
        seed=7,
    )
    l0 = _train(e0, steps=4, seed=3)
    ls = _train(es, steps=4, seed=3)
    np.testing.assert_allclose(l0, ls, rtol=2e-4, atol=1e-5)
    # final params agree
    p0 = e0.module_state_dict()
    p1 = es.module_state_dict()
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(p0), jax.tree_util.tree_leaves(p1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)


def test_gradient_accumulation_equivalence(devices):
    """gas=4 with micro=1 must equal gas=1 with micro=4 (same global batch)."""
    e1, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(micro=4, gas=1), seed=5)
    e2, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(micro=1, gas=4), seed=5)
    assert e1.train_batch_size == e2.train_batch_size == 32
    l1 = _train(e1, steps=3, seed=11)
    l2 = _train(e2, steps=3, seed=11)
    np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=1e-5)


def test_bf16_training(devices):
    engine, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(dtype="bf16"))
    batch = random_batch(engine.train_batch_size, seed=2)
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_bf16_grad_accum_dtype_knob(devices):
    """bf16.accumulate_grads_in_fp32=false (reference grad-accum-dtype knob,
    previously dead here): the micro-step accumulator is carried in bf16 and
    training stays close to (but measurably distinct from) the
    fp32-accumulated run."""
    bf16_off = {"bf16": {"enabled": True, "accumulate_grads_in_fp32": False}}
    e_bf, *_ = deepspeed_tpu.initialize(
        model=simple_model_spec(),
        config=_config(dtype="bf16", micro=1, gas=4, extra=bf16_off), seed=7)
    e_fp, *_ = deepspeed_tpu.initialize(
        model=simple_model_spec(), config=_config(dtype="bf16", micro=1, gas=4), seed=7)
    assert e_bf._accum_dtype.__name__ == "bfloat16"
    assert e_fp._accum_dtype.__name__ == "float32"
    l_bf = _train(e_bf, steps=3, seed=21)
    l_fp = _train(e_fp, steps=3, seed=21)
    np.testing.assert_allclose(l_bf, l_fp, rtol=5e-2)  # bf16 accum rounding
    assert l_bf[-1] < l_bf[0]


def test_fp16_loss_scale_dynamics(devices):
    engine, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(dtype="fp16"))
    assert engine.cur_scale == 2.0**8
    _train(engine, steps=5)
    # no overflow on a benign problem: scale grew after loss_scale_window=4 steps
    assert engine.cur_scale > 2.0**8
    assert engine.skipped_steps == 0


def test_fp16_overflow_skips_step(devices):
    engine, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(dtype="fp16"))
    before = engine.global_steps
    bad = random_batch(engine.train_batch_size)
    bad["x"] = bad["x"] * np.float32(1e30)  # force non-finite grads
    m = engine.train_batch(bad)
    assert bool(m["overflow"])
    assert engine.global_steps == before  # update skipped
    assert engine.skipped_steps == 1
    # hysteresis=2: first overflow only decrements hysteresis, scale unchanged
    assert engine.cur_scale == 2.0**8
    engine.train_batch(bad)  # second overflow exhausts hysteresis -> backoff
    assert engine.skipped_steps == 2
    assert engine.cur_scale == 2.0**7
    # a good step afterwards still trains
    good = random_batch(engine.train_batch_size)
    m2 = engine.train_batch(good)
    assert not bool(m2["overflow"])
    assert engine.global_steps == before + 1


def test_forward_backward_step_parity(devices):
    """The 3-call API must produce the same update as train_batch."""
    import jax

    cfg = _config(micro=2, gas=2)
    e1, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(micro=2, gas=2), seed=9)
    e2, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg, seed=9)

    batch = random_batch(e1.train_batch_size, seed=21)
    e1.train_batch(batch)

    # same global batch fed as 2 micro-batches through forward/backward/step
    n = e2.train_batch_size // 2
    for i in range(2):
        micro = {k: v[i * n : (i + 1) * n] for k, v in batch.items()}
        e2.backward(batch=micro)
    e2.step()

    # trajectories won't match exactly (different rng fold), but params must be
    # close since the model is deterministic (no dropout)
    for a, b in zip(
        jax.tree_util.tree_leaves(e1.module_state_dict()),
        jax.tree_util.tree_leaves(e2.module_state_dict()),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_dataloader_path(devices):
    data = make_dataset(n=128)
    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=simple_model_spec(), config=_config(), training_data=data
    )
    assert loader is not None
    from deepspeed_tpu.runtime.dataloader import RepeatingLoader

    it = iter(RepeatingLoader(loader))
    m = engine.train_batch(data_iter=it)
    assert np.isfinite(m["loss"])


def test_checkpoint_roundtrip(tmp_path, devices):
    engine, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(), seed=3)
    _train(engine, steps=3)
    engine.save_checkpoint(str(tmp_path), client_state={"epoch": 2})
    step_before = engine.global_steps

    # fresh engine restores
    e2, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=_config(), seed=99)
    path, client = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert client == {"epoch": 2}
    assert e2.global_steps == step_before
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(engine.module_state_dict()),
        jax.tree_util.tree_leaves(e2.module_state_dict()),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_lr_schedule_in_step(devices):
    cfg = _config()
    cfg["scheduler"] = {"type": "WarmupLR", "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01, "warmup_num_steps": 10, "warmup_type": "linear"}}
    engine, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg)
    m1 = engine.train_batch(random_batch(engine.train_batch_size))
    m5 = None
    for i in range(4):
        m5 = engine.train_batch(random_batch(engine.train_batch_size, seed=i))
    assert m5["lr"] > m1["lr"]
