"""Indexed dataset, data analyzer, and curriculum wiring into deepspeed_io
(round-2 verdict items 8 + weak 60)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
    DataAnalyzer,
    DistributedDataAnalyzer,
    load_difficulties,
)
from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
)


def _build_corpus(tmp_path, n=20, seed=0):
    rng = np.random.default_rng(seed)
    prefix = str(tmp_path / "corpus")
    b = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    docs = [rng.integers(0, 100, rng.integers(3, 30)).astype(np.int32) for _ in range(n)]
    b.add_documents(docs)
    b.finalize()
    return prefix, docs


def test_mmap_indexed_roundtrip(tmp_path):
    prefix, docs = _build_corpus(tmp_path)
    ds = MMapIndexedDataset(prefix)
    assert len(ds) == len(docs)
    assert MMapIndexedDataset.exists(prefix)
    for i in (0, 7, len(docs) - 1):
        np.testing.assert_array_equal(ds[i], docs[i])
    np.testing.assert_array_equal(ds.sizes, [len(d) for d in docs])
    np.testing.assert_array_equal(ds.get(3, offset=1, length=2), docs[3][1:3])


def test_mmap_builder_merge(tmp_path):
    p1, d1 = _build_corpus(tmp_path / "a", n=4, seed=1)
    p2, d2 = _build_corpus(tmp_path / "b", n=3, seed=2)
    merged = str(tmp_path / "merged")
    b = MMapIndexedDatasetBuilder(merged, dtype=np.int32)
    b.merge_file(p1)
    b.merge_file(p2)
    b.finalize()
    ds = MMapIndexedDataset(merged)
    assert len(ds) == 7
    np.testing.assert_array_equal(ds[5], d2[1])


def test_data_analyzer_seqlen(tmp_path):
    prefix, docs = _build_corpus(tmp_path)
    ds = MMapIndexedDataset(prefix)
    paths = DataAnalyzer(ds, save_path=str(tmp_path / "maps")).run()
    vals = load_difficulties(str(tmp_path / "maps"))
    np.testing.assert_array_equal(vals, [len(d) for d in docs])
    assert "seqlen" in paths


def test_distributed_data_analyzer_matches_single(tmp_path):
    prefix, docs = _build_corpus(tmp_path, n=11)
    ds = MMapIndexedDataset(prefix)
    for w in range(3):
        DistributedDataAnalyzer(ds, save_path=str(tmp_path / "dmaps"),
                                worker_id=w, num_workers=3).run_map()
    DistributedDataAnalyzer(ds, save_path=str(tmp_path / "dmaps"),
                            worker_id=0, num_workers=3).run_reduce()
    np.testing.assert_array_equal(
        load_difficulties(str(tmp_path / "dmaps")), [len(d) for d in docs])


def test_deepspeed_io_curriculum_filters_batches(devices):
    """engine.deepspeed_io consults data_efficiency: early batches contain
    only low-difficulty samples; the cap rises with steps."""
    TC = TransformerConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                           num_layers=1, num_heads=2, max_seq_len=16)
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=8),
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "steps_per_print": 1000,
            "data_efficiency": {
                "enabled": True,
                "data_sampling": {
                    "enabled": True,
                    "curriculum_learning": {
                        "enabled": True,
                        "curriculum_type": "seqlen",
                        "min_difficulty": 2,
                        "max_difficulty": 8,
                        "schedule_type": "fixed_linear",
                        "schedule_config": {"total_curriculum_step": 4, "difficulty_step": 1},
                    },
                },
            },
        },
    )
    n = 64
    rng = np.random.default_rng(0)
    lens = rng.integers(1, 9, n)  # per-sample difficulty = true length
    ids = np.zeros((n, 8), np.int32)
    mask = np.zeros((n, 8), np.int32)
    for i, l in enumerate(lens):
        ids[i, :l] = rng.integers(1, 64, l)
        mask[i, :l] = 1
    loader = engine.deepspeed_io({"input_ids": ids, "attention_mask": mask})
    assert loader.sampler is not None
    first = next(iter(loader))
    assert "difficulties" not in first
    got_lens = first["attention_mask"].sum(-1)
    assert got_lens.max() <= 2, f"first batch exceeded curriculum cap: {got_lens}"
    # after the curriculum finishes, high-difficulty samples appear
    loader.sampler.global_step = 10
    late = next(iter(loader))
    assert late["attention_mask"].sum(-1).max() > 2
