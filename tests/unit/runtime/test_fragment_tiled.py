"""tensor_fragment safe_get/set API + TiledLinear (round-2 verdict item 10)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
from deepspeed_tpu.utils.tensor_fragment import (
    safe_get_full_fp32_param,
    safe_get_full_grad,
    safe_get_full_optimizer_state,
    safe_set_full_fp32_param,
    safe_set_full_optimizer_state,
)

TC = TransformerConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                       num_layers=1, num_heads=2, max_seq_len=16)


def _engine(stage=3):
    e, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=8),
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": stage, "param_persistence_threshold": 0},
                "mesh": {"fsdp": 8, "dp": 1} if stage == 3 else {"dp": 8},
                "steps_per_print": 1000},
    )
    return e


def test_safe_get_set_fp32_param_across_shards(devices):
    e = _engine(stage=3)
    w = safe_get_full_fp32_param(e, "embed/embedding")
    assert w.shape == (64, 16)
    new = np.full_like(w, 0.25)
    safe_set_full_fp32_param(e, "embed/embedding", new)
    np.testing.assert_allclose(safe_get_full_fp32_param(e, "embed/embedding"), 0.25)
    # still sharded after the write
    leaf = e.state.params["embed"]["embedding"]
    assert not leaf.sharding.is_fully_replicated


def test_safe_optimizer_state_roundtrip(devices):
    e = _engine(stage=1)
    batch = {"input_ids": np.random.default_rng(0).integers(0, 64, (8, 8), dtype=np.int32)}
    e.train_batch(batch)
    mu = safe_get_full_optimizer_state(e, "embed/embedding", "exp_avg")
    assert mu is not None and mu.shape == (64, 16)
    assert np.abs(mu).sum() > 0
    safe_set_full_optimizer_state(e, "embed/embedding", "exp_avg", np.zeros_like(mu))
    np.testing.assert_allclose(
        safe_get_full_optimizer_state(e, "embed/embedding", "exp_avg"), 0.0)
    with pytest.raises(ValueError, match="unknown optimizer state"):
        safe_get_full_optimizer_state(e, "embed/embedding", "bogus")


def test_safe_get_full_grad_parity_path(devices):
    e = _engine(stage=0)
    batch = {"input_ids": np.random.default_rng(0).integers(0, 64, (8, 8), dtype=np.int32)}
    assert safe_get_full_grad(e, "embed/embedding") is None
    e.backward(batch=batch)
    g = safe_get_full_grad(e, "embed/embedding")
    assert g is not None and g.shape == (64, 16) and np.abs(g).sum() > 0
    e.step()


def test_tiled_linear_matches_dense():
    from deepspeed_tpu.linear.tiled_linear import TiledLinear

    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32))
    tiled = TiledLinear(features=24, in_splits=2, out_splits=3)
    params = tiled.init(jax.random.PRNGKey(1), x)["params"]
    y = tiled.apply({"params": params}, x)
    assert y.shape == (4, 24)

    # reassemble the tile grid into one dense kernel and compare
    blocks = [[params[f"tile_{i}_{j}"] for j in range(3)] for i in range(2)]
    W = jnp.concatenate([jnp.concatenate(r, axis=1) for r in blocks], axis=0)
    want = x @ W + params["bias"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=1e-5, atol=1e-6)

    # gradients flow through the remat tiles
    g = jax.grad(lambda p: (tiled.apply({"params": p}, x) ** 2).sum())(params)
    assert all(np.abs(np.asarray(l)).sum() > 0 for l in jax.tree_util.tree_leaves(g))


def test_tiled_linear_rejects_nondividing():
    from deepspeed_tpu.linear.tiled_linear import TiledLinear

    x = jnp.zeros((2, 30))
    with pytest.raises(ValueError, match="divide"):
        TiledLinear(features=24, in_splits=4).init(jax.random.PRNGKey(0), x)
