"""LoRA/OptimizedLinear, block-sparse attention, hybrid engine (coverage
model: reference tests/unit/linear/, ops/sparse_attention/, hybrid_engine/)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest


# ----------------------------------------------------------------- LoRA
class TestLoRA:
    def _make(self, lora=True, quant=False):
        from deepspeed_tpu.linear import LoRAConfig, OptimizedLinear, QuantizationConfig

        mod = OptimizedLinear(
            features=16,
            lora_config=LoRAConfig(lora_r=4, lora_alpha=8.0) if lora else None,
            quantization_config=QuantizationConfig(q_bits=8) if quant else None,
        )
        x = jnp.ones((2, 8))
        params = mod.init(jax.random.PRNGKey(0), x)["params"]
        return mod, params, x

    def test_lora_starts_as_base(self):
        """lora_b zero-init: initial output == base linear output."""
        mod, params, x = self._make(lora=True)
        y = mod.apply({"params": params}, x)
        base = x @ params["lora"]["kernel"]
        np.testing.assert_allclose(np.asarray(y), np.asarray(base), rtol=1e-6)

    def test_trainable_mask_freezes_base(self):
        from deepspeed_tpu.linear import lora_optimizer, lora_trainable_mask

        mod, params, x = self._make(lora=True)
        mask = lora_trainable_mask(params)
        assert mask["lora"]["lora_a"] and mask["lora"]["lora_b"]
        assert not mask["lora"]["kernel"]
        tx = lora_optimizer(optax.sgd(0.1))
        g = jax.grad(lambda p: mod.apply({"params": p}, x).sum())(params)
        updates, _ = tx.update(g, tx.init(params), params)
        new = optax.apply_updates(params, updates)
        np.testing.assert_array_equal(np.asarray(new["lora"]["kernel"]),
                                      np.asarray(params["lora"]["kernel"]))
        # b is zero-init so a's grad is zero on step 1; b must move
        assert not np.allclose(np.asarray(new["lora"]["lora_b"]),
                               np.asarray(params["lora"]["lora_b"]))

    def test_lora_merge_equivalence(self):
        """After training the adapters, merged kernel == adapter forward."""
        from deepspeed_tpu.linear import LoRAConfig, lora_merge

        mod, params, x = self._make(lora=True)
        # give the adapters non-trivial values
        params["lora"]["lora_a"] = jnp.ones_like(params["lora"]["lora_a"]) * 0.1
        params["lora"]["lora_b"] = jnp.ones_like(params["lora"]["lora_b"]) * 0.2
        y_adapters = mod.apply({"params": params}, x)
        scaling = LoRAConfig(lora_r=4, lora_alpha=8.0).scaling
        merged = lora_merge(params, scaling)
        y_merged = x @ merged["lora"]["kernel"]
        np.testing.assert_allclose(np.asarray(y_adapters), np.asarray(y_merged), rtol=1e-5)

    def test_quantized_base(self):
        mod, params, x = self._make(lora=True, quant=True)
        y = mod.apply({"params": params}, x)
        assert np.isfinite(np.asarray(y)).all()


# ----------------------------------------------------------------- sparse attn
class TestSparseAttention:
    def test_layout_shapes_and_density(self):
        from deepspeed_tpu.ops.sparse_attention import get_sparsity_config

        for name in ("dense", "fixed", "bigbird", "local"):
            cfg = get_sparsity_config(name, num_heads=2, block=8)
            lay = cfg.make_layout(64)
            assert lay.shape == (2, 8, 8)
            # diagonal always active (causal self-block)
            assert all(lay[h, i, i] for h in range(2) for i in range(8))
        dense = get_sparsity_config("dense", 2, 8).make_layout(64).sum()
        local = get_sparsity_config("local", 2, 8).make_layout(64).sum()
        assert local < dense

    def test_dense_layout_matches_full_attention(self):
        from deepspeed_tpu.ops.attention import causal_attention
        from deepspeed_tpu.ops.sparse_attention import block_sparse_attention, get_sparsity_config

        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (2, 32, 2, 8))
        k = jax.random.normal(ks[1], (2, 32, 2, 8))
        v = jax.random.normal(ks[2], (2, 32, 2, 8))
        lay = get_sparsity_config("dense", 2, 8).make_layout(32)
        got = block_sparse_attention(q, k, v, lay, block=8)
        ref = causal_attention(q, k, v, impl="xla")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_sliding_window_restricts_context(self):
        from deepspeed_tpu.ops.sparse_attention import block_sparse_attention, get_sparsity_config

        S, blk = 64, 8
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q = jax.random.normal(ks[0], (1, S, 1, 4))
        k = jax.random.normal(ks[1], (1, S, 1, 4))
        v = jax.random.normal(ks[2], (1, S, 1, 4))
        lay = get_sparsity_config("local", 1, blk, num_sliding_window_blocks=2).make_layout(S)
        got = block_sparse_attention(q, k, v, lay, block=blk)
        # last query sees only the last 2 blocks: recompute restricted attention
        lo = S - 2 * blk
        sub = block_sparse_attention(
            q[:, lo:], k[:, lo:], v[:, lo:],
            get_sparsity_config("dense", 1, blk).make_layout(2 * blk), block=blk,
        )
        np.testing.assert_allclose(np.asarray(got[0, -1]), np.asarray(sub[0, -1]), rtol=2e-5, atol=2e-5)


# ----------------------------------------------------------------- hybrid
def test_hybrid_engine_train_generate_flip(devices):
    """RLHF shape: train a CausalLM, generate mid-training, train more —
    generations must track the freshest weights."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import TransformerConfig, causal_lm_spec
    from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedTPUHybridEngine

    cfg = TransformerConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=64)
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=16),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 2}, "steps_per_print": 1000},
        seed=0,
    )
    hybrid = DeepSpeedTPUHybridEngine(engine, cfg, inference_config={"dtype": "fp32", "seq_bucket": 8})

    prompts = np.asarray(jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, 64))
    gen0 = hybrid.generate(prompts, max_new_tokens=4)
    assert gen0.shape == (2, 10)

    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(2), (engine.train_batch_size, 16), 0, 64))
    for _ in range(5):
        hybrid.train_batch({"input_ids": ids})
    gen1 = hybrid.generate(prompts, max_new_tokens=4)
    # weights moved -> the inference view must have refreshed
    assert hybrid._infer_step == engine.global_steps == 5
    # determinism of the refreshed view
    np.testing.assert_array_equal(gen1, hybrid.generate(prompts, max_new_tokens=4))
    assert hybrid.total_generate_calls == 3
