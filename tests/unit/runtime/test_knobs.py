"""The three round-2 'dead knobs', now live (VERDICT item 5):

(a) gradient_compression -> 1-bit sign+error-feedback compressed allreduce
    (reference runtime/comm/nccl.py:51, OnebitAdam family)
(b) activation_checkpointing -> jax.checkpoint policy on the compiled loss
    (reference runtime/activation_checkpointing/checkpointing.py:948)
(c) mics_shard_size -> fsdp sub-group mesh (reference zero/mics.py:64)

Each knob must demonstrably change the compiled program or raise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

TC = TransformerConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                       num_layers=2, num_heads=4, max_seq_len=32)


def _cfg(**over):
    base = {
        "train_batch_size": 16,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "steps_per_print": 1000,
    }
    base.update(over)
    return base


def _batch(engine, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 128, (engine.train_batch_size, 16), dtype=np.int32)}


# ------------------------------------------------------------- (a) onebit

def test_onebit_packing_roundtrip():
    from deepspeed_tpu.parallel.onebit import pack_signs, unpack_signs

    x = jnp.asarray(np.random.default_rng(0).normal(size=(37,)), jnp.float32)
    signs = unpack_signs(pack_signs(x), 37)
    np.testing.assert_array_equal(np.asarray(signs), np.where(np.asarray(x) >= 0, 1.0, -1.0))


def test_onebit_trains_and_ships_uint8(devices):
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=16),
        config=_cfg(optimizer={"type": "OneBitAdam", "params": {"lr": 1e-2}}),
    )
    assert engine._onebit  # compression active
    batch = _batch(engine)
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"no learning under 1-bit compression: {losses}"
    # the wire format is uint8: the compiled step must contain u8 collectives
    placed = engine._shard_global_batch(batch)
    text = engine._train_step.lower(engine.state, placed).as_text()
    assert "all_gather" in text and "ui8" in text, "no uint8 all_gather on the wire"


def test_onebit_error_feedback_state(devices):
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=16),
        config=_cfg(gradient_compression={"enabled": True}),
    )
    assert engine.state.comm_error is not None
    before = [np.asarray(x).copy() for x in jax.tree_util.tree_leaves(engine.state.comm_error)]
    engine.train_batch(_batch(engine))
    after = [np.asarray(x) for x in jax.tree_util.tree_leaves(engine.state.comm_error)]
    # residuals become non-zero after one compressed step
    assert any((a != b).any() for a, b in zip(after, before))


def test_onebit_close_to_uncompressed(devices):
    """Early-step trajectory stays near the exact-allreduce run (error
    feedback bounds the drift; not exact by construction)."""
    batch = None
    runs = {}
    for name, cfg in (
        ("exact", _cfg()),
        ("onebit", _cfg(gradient_compression={"enabled": True})),
    ):
        e, *_ = deepspeed_tpu.initialize(model=causal_lm_spec(TC, example_seq_len=16), config=cfg)
        batch = _batch(e)
        runs[name] = [float(e.train_batch(batch)["loss"]) for _ in range(4)]
    # step 1 is bit-identical (no error accumulated yet); later steps drift
    # with compression noise but stay in the same descent envelope
    np.testing.assert_allclose(runs["onebit"][0], runs["exact"][0], rtol=1e-5)
    np.testing.assert_allclose(runs["onebit"], runs["exact"], rtol=0.25)
    assert all(b < a for a, b in zip(runs["onebit"], runs["onebit"][1:]))


def test_onebit_rejects_stage2(devices):
    with pytest.raises(ValueError, match="stage <= 1"):
        deepspeed_tpu.initialize(
            model=causal_lm_spec(TC, example_seq_len=16),
            config=_cfg(gradient_compression={"enabled": True},
                        zero_optimization={"stage": 2}),
        )


# ------------------------------------- (b) activation checkpointing policy

def test_activation_checkpointing_changes_program_not_math(devices):
    base, remat = [], []
    for store, ac in ((base, {}), (remat, {"enabled": True, "policy": "full"})):
        e, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(TC, example_seq_len=16),
            config=_cfg(activation_checkpointing=ac),
        )
        batch = _batch(e)
        store.extend(float(e.train_batch(batch)["loss"]) for _ in range(3))
        if ac:
            placed = e._shard_global_batch(batch)
            jaxpr = str(e._train_step.trace(e.state, placed).jaxpr)
            assert "remat" in jaxpr or "checkpoint" in jaxpr
    np.testing.assert_allclose(remat, base, rtol=1e-5)


def test_activation_checkpointing_bad_policy_raises(devices):
    e, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=16),
        config=_cfg(activation_checkpointing={"enabled": True, "policy": "bogus"}),
    )
    with pytest.raises(ValueError, match="policy"):
        e.train_batch(_batch(e))


# ------------------------------------------------------- (c) mics_shard_size

def test_mics_submesh_shard_and_replication(devices):
    """fsdp=8 + mics_shard_size=2 => params sharded over groups of 2 and
    replicated 4x across groups (reference zero/mics.py:64 semantics)."""
    e, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=16),
        config=_cfg(mesh={"fsdp": 8, "dp": 1},
                    zero_optimization={"stage": 3, "mics_shard_size": 2,
                                       "param_persistence_threshold": 0}),
    )
    assert e.mesh.shape["fsdp"] == 2 and e.mesh.shape["dp"] == 4
    # big leaves: sharded into 2 distinct shards, each replicated on 4 devices
    leaf = e.state.params["embed"]["embedding"]
    dbl = leaf.sharding.devices_indices_map(leaf.shape)
    distinct = {str(v) for v in dbl.values()}
    assert len(distinct) == 2, f"expected 2 distinct shards, got {len(distinct)}"


def test_mics_trajectory_matches_full_fsdp(devices):
    runs = {}
    for name, zcfg in (
        ("full", {"stage": 3}),
        ("mics", {"stage": 3, "mics_shard_size": 2}),
    ):
        e, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(TC, example_seq_len=16),
            config=_cfg(mesh={"fsdp": 8, "dp": 1}, zero_optimization=zcfg),
        )
        batch = _batch(e)
        runs[name] = [float(e.train_batch(batch)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(runs["mics"], runs["full"], rtol=2e-4)


def test_mics_rejects_stage1(devices):
    with pytest.raises(ValueError, match="stage 3"):
        deepspeed_tpu.initialize(
            model=causal_lm_spec(TC, example_seq_len=16),
            config=_cfg(mesh={"fsdp": 8, "dp": 1},
                        zero_optimization={"stage": 1, "mics_shard_size": 2}),
        )


def test_mics_rejects_nondividing(devices):
    with pytest.raises(ValueError, match="divide"):
        deepspeed_tpu.initialize(
            model=causal_lm_spec(TC, example_seq_len=16),
            config=_cfg(mesh={"fsdp": 8, "dp": 1},
                        zero_optimization={"stage": 3, "mics_shard_size": 3}),
        )


def test_onebit_universal_checkpoint_excludes_residuals(tmp_path, devices):
    """comm_error is per-run scratch: a OneBit run's universal checkpoint
    loads into a plain engine (and vice versa) — mesh-independence holds."""
    from deepspeed_tpu.checkpoint.universal import load_universal, save_universal

    ob, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=16),
        config=_cfg(gradient_compression={"enabled": True}),
    )
    batch = _batch(ob)
    ob.train_batch(batch)
    save_universal(ob, str(tmp_path))

    plain, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=16), config=_cfg())
    load_universal(plain, str(tmp_path))
    assert plain.state.comm_error is None
    l = float(plain.train_batch(batch)["loss"])
    assert np.isfinite(l)

    ob2, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=16),
        config=_cfg(gradient_compression={"enabled": True}),
    )
    load_universal(ob2, str(tmp_path))
    assert ob2.state.comm_error is not None  # fresh residuals, not restored
    assert np.isfinite(float(ob2.train_batch(batch)["loss"]))


def test_round5_knob_wiring(monkeypatch):
    """Previously-dead knobs now act (or loudly refuse): comms_logger config
    section configures the logger, dump_state prints the resolved config,
    prescale_gradients raises (no-op in the fused step), wall_clock_breakdown
    switches the throughput window to per-step."""
    import deepspeed_tpu
    from deepspeed_tpu.comm import comm as comm_mod
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    def spec():
        cfg = TransformerConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                                num_layers=1, num_heads=2, max_seq_len=16)
        return causal_lm_spec(cfg, example_seq_len=16)

    base = {"train_micro_batch_size_per_gpu": 1, "steps_per_print": 1000,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}}

    with pytest.raises(NotImplementedError, match="prescale_gradients"):
        deepspeed_tpu.initialize(model=spec(), config={**base, "prescale_gradients": True})
    with pytest.raises(NotImplementedError, match="predivide"):
        deepspeed_tpu.initialize(model=spec(), config={**base, "gradient_predivide_factor": 2.0})

    was_enabled = comm_mod.comms_logger.enabled
    try:
        eng, *_ = deepspeed_tpu.initialize(
            model=spec(),
            config={**base, "comms_logger": {"enabled": True, "verbose": False},
                    "wall_clock_breakdown": True, "dump_state": True})
        assert comm_mod.comms_logger.enabled
        assert eng.throughput_timer.steps_per_output == 1  # per-step breakdown
    finally:
        comm_mod.comms_logger.configure(enabled=was_enabled)
