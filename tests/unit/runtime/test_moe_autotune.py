"""Capacity-factor autotuning (ISSUE 15): the host-side controller moves the
gate's effective capacity between steps from the moe/* dispatch gauges,
inside the moe_autotune bounds, with the jit cache pinned at ONE program
(capacity arrays are padded to the static ceiling; only the traced cutoff
scalar moves)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
from deepspeed_tpu.telemetry import get_tracer


def _moe_cfg(**overrides):
    base = dict(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, max_seq_len=64, num_experts=4, moe_top_k=2,
        moe_capacity_factor=1.0)
    base.update(overrides)
    return TransformerConfig(**base)


def _engine(model_cfg, autotune, steps_per_print=1, telemetry=True):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": steps_per_print,
        "telemetry": {"enabled": telemetry},
        "moe_autotune": autotune,
    }
    eng, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(model_cfg, example_seq_len=16), config=cfg)
    return eng


def _batch(eng, seed=0, vocab=128):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(
        0, vocab, (eng.train_batch_size, 16), dtype=np.int32)}


def test_drop_rate_above_target_raises_capacity_within_bounds(devices):
    """Starting tight (factor 1.0), random routing drops tokens, so every
    controller tick must RAISE the factor — monotonically, by
    increase_step, never past max_factor — and the compiled step count
    stays at one program across all adjustments."""
    eng = _engine(_moe_cfg(), {
        "enabled": True, "target_drop_rate": 0.01, "min_factor": 0.5,
        "max_factor": 2.0, "increase_step": 0.25})
    assert eng._moe_autotune is not None
    factors = [eng._moe_cap_factor]
    drops = []
    for i in range(6):
        m = eng.train_batch(_batch(eng, seed=i))
        drops.append(float(m["moe/token_drop_rate"]))
        factors.append(eng._moe_cap_factor)
    # every above-target observation raised the knob by exactly the step
    for prev, nxt, d in zip(factors, factors[1:], drops):
        if d > 0.01:
            assert nxt == pytest.approx(min(prev + 0.25, 2.0))
        assert 0.5 <= nxt <= 2.0
    assert factors[-1] > factors[0]  # net effect: capacity grew
    assert eng._train_step._cache_size() == 1  # one program, a moving scalar


def test_balanced_no_drop_load_lowers_capacity(devices):
    """At a generous starting factor the drop rate is ~0 and the dispatch
    is near balanced, so ticks DECAY the factor toward min_factor (by
    decrease_step, never below)."""
    eng = _engine(_moe_cfg(moe_capacity_factor=2.0), {
        "enabled": True, "target_drop_rate": 0.5, "min_factor": 1.0,
        "max_factor": 2.0, "decrease_step": 0.125, "balance_threshold": 4.0})
    factors = [eng._moe_cap_factor]
    for i in range(4):
        m = eng.train_batch(_batch(eng, seed=10 + i))
        assert float(m["moe/token_drop_rate"]) <= 0.5
        factors.append(eng._moe_cap_factor)
    for prev, nxt in zip(factors, factors[1:]):
        assert nxt == pytest.approx(max(prev - 0.125, 1.0))
    assert factors[-1] < factors[0]
    assert eng._train_step._cache_size() == 1


def test_applied_gauge_reflects_realized_factor(devices):
    """moe/capacity_factor_applied is the factor the step's cutoff actually
    ENFORCED — it must track the knob with a one-step lag (the controller
    adjusts AFTER the step ran) and land in the registry/monitor stream."""
    eng = _engine(_moe_cfg(), {
        "enabled": True, "target_drop_rate": 0.0, "min_factor": 0.5,
        "max_factor": 2.0, "increase_step": 0.5})
    knob_before = []
    applied = []
    for i in range(3):
        knob_before.append(eng._moe_cap_factor)
        m = eng.train_batch(_batch(eng, seed=20 + i))
        applied.append(float(m["moe/capacity_factor_applied"]))
    # applied_t == ceil-quantized knob_t (the cutoff is an integer slot
    # count, so the realized factor is the knob rounded UP to the slot grid
    # within bounds); with T=32 tokens x k=2 over E=4 the grid is E/(T*k)
    T, k, E = 32, 2, 4
    for knob, got in zip(knob_before, applied):
        slots = np.ceil(T * k * knob / E)
        assert got == pytest.approx(float(slots) * E / (T * k))
    # the registry carries both the applied gauge and the controller target
    reg = get_tracer().registry
    assert reg.gauge("moe/capacity_factor_applied").value > 0
    assert reg.gauge("moe/capacity_factor_target").value == pytest.approx(
        eng._moe_cap_factor)


def test_autotune_disarmed_without_gauges(devices):
    """moe_autotune without telemetry (no moe/* sensors) must disarm the
    controller — the engine trains exactly as before, no batch key, no
    factor state."""
    eng = _engine(_moe_cfg(), {"enabled": True}, telemetry=False)
    assert eng._moe_autotune is None
    m = eng.train_batch(_batch(eng))
    assert np.isfinite(float(m["loss"]))
    assert "moe/capacity_factor_applied" not in m


def test_autotune_bad_bounds_rejected(devices):
    with pytest.raises(ValueError, match="min_factor"):
        _engine(_moe_cfg(), {"enabled": True, "min_factor": 2.0,
                             "max_factor": 1.0})
    # a config error must surface even when the controller would disarm
    # (telemetry off) — never accepted silently
    with pytest.raises(ValueError, match="min_factor"):
        _engine(_moe_cfg(), {"enabled": True, "min_factor": 2.0,
                             "max_factor": 1.0}, telemetry=False)


def test_gate_dynamic_capacity_unit():
    """top_k_gating with effective_capacity: the traced cutoff is enforced
    (no slot beyond it is used), the arrays keep the padded static bound,
    and the applied-factor stat reports the cutoff."""
    from deepspeed_tpu.parallel.moe import top_k_gating

    T, E, C = 32, 4, 16
    logits = jax.random.normal(jax.random.PRNGKey(0), (T, E))
    eff = jnp.int32(4)
    l_aux, combine, dispatch, counts, stats = top_k_gating(
        logits, 2, C, use_rts=False, drop_tokens=True, collect_stats=True,
        effective_capacity=eff)
    assert dispatch.shape == (T, E, C)  # padded static bound
    used = np.asarray(dispatch).sum(axis=(0, 1))  # per-slot occupancy
    assert used[4:].sum() == 0  # nothing beyond the dynamic cutoff
    assert used[:4].sum() > 0
    assert float(stats["moe/capacity_factor_applied"]) == pytest.approx(
        4 * E / (T * 2))
    # same call, larger cutoff: more slots fill, same shapes (jit-stable)
    _, _, d2, _, s2 = top_k_gating(
        logits, 2, C, use_rts=False, drop_tokens=True, collect_stats=True,
        effective_capacity=jnp.int32(16))
    assert d2.shape == dispatch.shape
    assert np.asarray(d2).sum() >= np.asarray(dispatch).sum()


def test_autotune_never_shrinks_configured_capacity(devices):
    """max_factor below the model's static capacity factor must RAISE the
    ceiling, not clamp the model below what it was tuned with."""
    eng = _engine(_moe_cfg(moe_capacity_factor=3.0), {
        "enabled": True, "min_factor": 1.0, "max_factor": 2.0})
    assert eng._moe_cap_max == 3.0
    assert eng._moe_cap_factor == 3.0  # starts AT the configured factor
    assert eng.model.transformer_config.moe_capacity_factor_max == 3.0
    m = eng.train_batch(_batch(eng))
    assert float(m["moe/capacity_factor_applied"]) >= 1.0
