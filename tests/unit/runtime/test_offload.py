"""ZeRO-Offload / ZeRO-Infinity wiring tests.

The reference integrates optimizer offload into the step
(``runtime/zero/stage3.py:2082`` + ``swap_tensor/partitioned_optimizer_swapper.py:29``);
here the engine reads ``zero_optimization.offload_optimizer`` and splits the
step into a device grad program + a host-committed compiled update. These
tests pin (a) state placement off the mesh, (b) trajectory match vs the fused
non-offload step, (c) the NVMe round-trip keeping state on disk between steps.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec


def _cfg(extra_zero=None, stage=1):
    zero = {"stage": stage, **(extra_zero or {})}
    return {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3, "weight_decay": 0.01}},
        "zero_optimization": zero,
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
    }


def _model():
    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=2, max_seq_len=32,
    )
    return causal_lm_spec(cfg, example_seq_len=16)


def _run_steps(engine, n=3, seed=0):
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(n):
        batch = {"input_ids": rng.integers(0, 64, (engine.train_batch_size, 16), dtype=np.int32)}
        m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    return losses


def test_twin_flow_partial_offload_structure():
    """Twin-Flow (reference ZeRO-Offload++ ``offload_optimizer.ratio``):
    with ratio<1, part of the master state must stay ON the mesh (device
    partition updates in a fused accelerator program) while the host
    partition lives on the CPU backend — and a step runs."""
    eng, *_ = deepspeed_tpu.initialize(
        model=_model(),
        config=_cfg({"offload_optimizer": {"device": "cpu", "ratio": 0.5}}),
    )
    assert eng.offload_mode == "host-jit" and eng._twin_ratio == 0.5
    leaves = jax.tree_util.tree_leaves(eng.state.params)
    kinds = [type(leaf.sharding).__name__ for leaf in leaves]
    assert "SingleDeviceSharding" in kinds and "NamedSharding" in kinds, kinds
    # host partition holds ~ratio of the master bytes (greedy split)
    host_b = sum(l.size for l in leaves if type(l.sharding).__name__ == "SingleDeviceSharding")
    total_b = sum(l.size for l in leaves)
    assert 0.2 < host_b / total_b < 0.8, host_b / total_b
    losses = _run_steps(eng, 2)
    assert all(np.isfinite(losses))
    # the fragment API sees THROUGH the masked partition states: a moment is
    # retrievable for params in both partitions (embed is first in flatten
    # order => host; the final norm lands in the device partition)
    from deepspeed_tpu.utils.tensor_fragment import safe_get_full_optimizer_state

    mu_host = safe_get_full_optimizer_state(eng, "embed/embedding", "exp_avg")
    mu_dev = safe_get_full_optimizer_state(eng, "final_norm/scale", "exp_avg")
    assert mu_host is not None and float(np.abs(mu_host).max()) > 0
    assert mu_dev is not None and float(np.abs(mu_dev).max()) > 0


def test_twin_flow_trajectory_matches_fused():
    """ratio=0.5 partial offload reproduces the fused non-offload trajectory
    (same split semantics: one global grad norm, one loss-scale/step
    bookkeeping; nightly depth for the new feature)."""
    twin, *_ = deepspeed_tpu.initialize(
        model=_model(),
        config=_cfg({"offload_optimizer": {"device": "cpu", "ratio": 0.5}}),
    )
    base, *_ = deepspeed_tpu.initialize(model=_model(), config=_cfg())
    l0 = _run_steps(base, 3)
    l1 = _run_steps(twin, 3)
    np.testing.assert_allclose(l0, l1, rtol=2e-4)
    # and the masters stay consistent: fp32 state_dict matches closely
    sd_t = twin.module_state_dict()
    sd_b = base.module_state_dict()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5),
        sd_t, sd_b)


def test_twin_flow_fp16_dynamic_scale_matches_fused():
    """fp16 dynamic loss scaling under Twin-Flow: the shared bookkeeping
    (one finite flag, one loss-scale state) must reproduce the fused fp16
    trajectory including any scale adjustments (nightly depth)."""
    fp16 = {"fp16": {"enabled": True, "initial_scale_power": 8, "loss_scale_window": 2}}

    twin, *_ = deepspeed_tpu.initialize(
        model=_model(),
        config={**_cfg({"offload_optimizer": {"device": "cpu", "ratio": 0.5}}), **fp16})
    base, *_ = deepspeed_tpu.initialize(model=_model(), config={**_cfg(), **fp16})
    l0 = _run_steps(base, 4)
    l1 = _run_steps(twin, 4)
    np.testing.assert_allclose(l0, l1, rtol=3e-3)
    assert float(jax.device_get(twin.state.loss_scale.loss_scale)) == \
        float(jax.device_get(base.state.loss_scale.loss_scale))
    assert int(jax.device_get(twin.state.step)) == int(jax.device_get(base.state.step))


def test_offload_bf16_grad_transfer_close_to_fp32():
    """bf16 grad accumulation x CPU offload: grads cross to the host in bf16
    (half the D2H bytes — what the offload bench configs use) and the
    trajectory stays close to the fp32-accumulated offload run (nightly)."""
    import jax.numpy as jnp

    def run(accum_fp32):
        cfg = _cfg({"offload_optimizer": {"device": "cpu"}})
        cfg["bf16"] = {"enabled": True, "accumulate_grads_in_fp32": accum_fp32}
        cfg["gradient_accumulation_steps"] = 2
        eng, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg, seed=4)
        return eng, _run_steps(eng, 3)

    e_bf, l_bf = run(False)
    _, l_fp = run(True)
    assert e_bf._accum_dtype is jnp.bfloat16
    np.testing.assert_allclose(l_bf, l_fp, rtol=5e-2)


def test_twin_flow_checkpoint_restores_across_partitionings(tmp_path):
    """Checkpoints canonicalize the Twin-Flow opt_state (the two optax.masked
    partitions merge to ONE param-shaped moment tree on save, re-partition on
    load — ADVICE round 5): a checkpoint saved under ratio=0.5 restores into
    a non-Twin-Flow engine AND into a different-ratio (0.75) engine, with
    identical multi-step trajectories. (Restored engines step freely now:
    load_checkpoint's 'fresh' placement restores into newly allocated
    committed buffers, so the seed-era orbax heap-corruption landmine no
    longer applies.)"""
    twin, *_ = deepspeed_tpu.initialize(
        model=_model(),
        config=_cfg({"offload_optimizer": {"device": "cpu", "ratio": 0.5}}))
    _run_steps(twin, 2)
    twin.save_checkpoint(str(tmp_path / "twin"))

    # twin -> non-twin: canonical atoms restore against the plain structure,
    # values identical leaf-for-leaf
    plain, *_ = deepspeed_tpu.initialize(model=_model(), config=_cfg())
    path, _ = plain.load_checkpoint(str(tmp_path / "twin"))
    assert path is not None
    canon = jax.device_get(twin.canonical_opt_state())
    restored = jax.device_get(plain.state.opt_state)
    canon_leaves = jax.tree_util.tree_leaves(canon)
    restored_leaves = jax.tree_util.tree_leaves(restored)
    assert len(canon_leaves) == len(restored_leaves)
    for a, b in zip(canon_leaves, restored_leaves):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b, np.float32))

    # twin -> twin under a DIFFERENT ratio: re-partitioned against the 0.75
    # hole placement, not the saver's
    twin2, *_ = deepspeed_tpu.initialize(
        model=_model(),
        config=_cfg({"offload_optimizer": {"device": "cpu", "ratio": 0.75}}))
    path2, _ = twin2.load_checkpoint(str(tmp_path / "twin"))
    assert path2 is not None
    assert int(jax.device_get(twin2.state.step)) == int(jax.device_get(twin.state.step))

    # multi-step post-restore trajectories coincide (and exercise the fresh-
    # buffer restore path under continued stepping — the old landmine shape)
    l_twin = _run_steps(twin, 3)
    l_plain = _run_steps(plain, 3)
    l_twin2 = _run_steps(twin2, 3)
    np.testing.assert_allclose(l_twin, l_plain, rtol=1e-5)
    np.testing.assert_allclose(l_twin, l_twin2, rtol=1e-5)


def test_twin_flow_universal_checkpoint_canonical(tmp_path):
    """The universal (mesh-independent) format canonicalizes Twin-Flow
    opt_state the same way: atoms from a ratio=0.5 engine restore into a
    non-twin engine (canonical paths), and a twin self-reload exercises the
    load-side re-partitioning. Restored engines keep stepping (fresh-buffer
    restore placement; the seed-era one-step fence is gone)."""
    twin, *_ = deepspeed_tpu.initialize(
        model=_model(),
        config=_cfg({"offload_optimizer": {"device": "cpu", "ratio": 0.5}}))
    _run_steps(twin, 2)
    twin.save_universal_checkpoint(str(tmp_path))

    from deepspeed_tpu.checkpoint.universal import load_universal

    plain, *_ = deepspeed_tpu.initialize(model=_model(), config=_cfg())
    load_universal(plain, str(tmp_path))
    canon = jax.device_get(twin.canonical_opt_state())
    rest = jax.device_get(plain.state.opt_state)
    for a, b in zip(jax.tree_util.tree_leaves(canon), jax.tree_util.tree_leaves(rest)):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b, np.float32))

    load_universal(twin, str(tmp_path))  # self-reload: departition path
    l_twin = _run_steps(twin, 3)
    l_plain = _run_steps(plain, 3)
    np.testing.assert_allclose(l_twin, l_plain, rtol=1e-5)


def test_twin_flow_warns_on_bf16_grad_accumulation(caplog):
    """bf16.accumulate_grads_in_fp32=false is force-overridden to fp32 on the
    Twin-Flow path (its stats/partition programs need fp32 grads) — that must
    warn, not silently lie (ADVICE round 5; the prescale_gradients stance)."""
    import logging

    cfg = _cfg({"offload_optimizer": {"device": "cpu", "ratio": 0.5}})
    cfg["bf16"] = {"enabled": True, "accumulate_grads_in_fp32": False}
    lg = logging.getLogger("deepspeed_tpu")
    lg.propagate = True  # the repo logger defaults propagate=False; caplog
    try:                 # listens on the root logger
        with caplog.at_level(logging.WARNING, logger="deepspeed_tpu"):
            deepspeed_tpu.initialize(model=_model(), config=cfg)
    finally:
        lg.propagate = False
    assert any("Twin-Flow" in r.getMessage() and "fp32" in r.getMessage()
               for r in caplog.records), caplog.records


def test_twin_flow_ratio_rejected_with_nvme(tmp_path):
    with pytest.raises(ValueError, match="Twin-Flow"):
        deepspeed_tpu.initialize(
            model=_model(),
            config=_cfg({"offload_optimizer": {
                "device": "nvme", "nvme_path": str(tmp_path), "ratio": 0.5}}),
        )


def test_twin_flow_ratio_bounds_and_param_offload_rejected():
    for bad in (0.0, -0.5, 1.5):
        with pytest.raises(ValueError, match="ratio"):
            deepspeed_tpu.initialize(
                model=_model(),
                config=_cfg({"offload_optimizer": {"device": "cpu", "ratio": bad}}))
    with pytest.raises(NotImplementedError, match="offload_param"):
        deepspeed_tpu.initialize(
            model=_model(),
            config=_cfg({"offload_optimizer": {"device": "cpu", "ratio": 0.5},
                         "offload_param": {"device": "cpu"}}, stage=3))


def test_offload_optimizer_cpu_trajectory_matches_fused():
    base, *_ = deepspeed_tpu.initialize(model=_model(), config=_cfg())
    off, *_ = deepspeed_tpu.initialize(
        model=_model(), config=_cfg({"offload_optimizer": {"device": "cpu"}})
    )
    assert off.offload_mode in ("host-jit", "memories")
    l0 = _run_steps(base, 3)
    l1 = _run_steps(off, 3)
    np.testing.assert_allclose(l0, l1, rtol=2e-4)
    p0 = jax.device_get(base.state.params)
    p1 = jax.device_get(off.state.params)
    for a, b in zip(jax.tree_util.tree_leaves(p0), jax.tree_util.tree_leaves(p1)):
        np.testing.assert_allclose(a, b, rtol=3e-4, atol=3e-5)


def test_offload_state_not_on_mesh():
    off, *_ = deepspeed_tpu.initialize(
        model=_model(), config=_cfg({"offload_optimizer": {"device": "cpu"}})
    )
    if off.offload_mode != "host-jit":
        pytest.skip("host-jit offload unavailable on this backend")
    _run_steps(off, 1)
    # master params + moments are committed to ONE host device, not spread
    # over the mesh (the device-memory drop on a real accelerator)
    for leaf in jax.tree_util.tree_leaves(off.state.params):
        assert len(leaf.sharding.device_set) == 1
    for leaf in jax.tree_util.tree_leaves(off.state.opt_state):
        if isinstance(leaf, jax.Array):
            assert len(leaf.sharding.device_set) == 1
    # the device-side view is only the bf16/compute-dtype params
    assert off._compute_dev is not None


def test_offload_nvme_roundtrip(tmp_path):
    off, *_ = deepspeed_tpu.initialize(
        model=_model(),
        config=_cfg({"offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path)}}),
    )
    assert off.offload_mode == "nvme"
    base, *_ = deepspeed_tpu.initialize(model=_model(), config=_cfg())
    l0 = _run_steps(base, 3)
    l1 = _run_steps(off, 3)
    np.testing.assert_allclose(l0, l1, rtol=2e-4)
    # between steps the moments live on disk, not in the state
    assert off._opt_on_nvme and off.state.opt_state is None
    assert any((tmp_path / "opt_state").rglob("*.bin"))
    # checkpoint materializes them back
    off.materialize_state()
    assert off.state.opt_state is not None


def test_offload_zero3_with_param_offload():
    off, *_ = deepspeed_tpu.initialize(
        model=_model(),
        config=_cfg(
            {"offload_optimizer": {"device": "cpu"}, "offload_param": {"device": "cpu"}},
            stage=3,
        ),
    )
    base, *_ = deepspeed_tpu.initialize(model=_model(), config=_cfg(stage=3))
    l0 = _run_steps(base, 2)
    l1 = _run_steps(off, 2)
    np.testing.assert_allclose(l0, l1, rtol=2e-4)
    # param offload: no persistent device-side weights between steps
    assert off._compute_dev is None


def test_param_only_offload_is_not_a_silent_noop():
    """offload_param without offload_optimizer must still offload (the
    reference supports standalone param offload; a parsed-but-dead knob is
    worse than an error)."""
    off, *_ = deepspeed_tpu.initialize(
        model=_model(), config=_cfg({"offload_param": {"device": "cpu"}}, stage=3)
    )
    assert off.offload_mode is not None
    _run_steps(off, 1)
    assert off._compute_dev is None  # nothing persists device-side


def test_offload_checkpoint_roundtrip(tmp_path):
    off, *_ = deepspeed_tpu.initialize(
        model=_model(), config=_cfg({"offload_optimizer": {"device": "cpu"}})
    )
    _run_steps(off, 2)
    step_before = off.global_steps
    off.save_checkpoint(str(tmp_path))
    _run_steps(off, 1)
    path, _ = off.load_checkpoint(str(tmp_path))
    assert path is not None
    assert off.global_steps == step_before
    _run_steps(off, 1)  # still trains after reload
