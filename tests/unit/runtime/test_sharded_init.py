"""Sharded construction (zero.Init analog — reference
partition_parameters.py:825): params materialize directly in their target
sharding under jit, bit-identical to the eager init-then-place path."""

import os

import jax
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

TC = TransformerConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                       num_layers=2, num_heads=4, max_seq_len=32)


def _cfg(stage=3):
    return {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage, "param_persistence_threshold": 0},
        "mesh": {"fsdp": 8, "dp": 1},
        "steps_per_print": 1000,
    }


def test_sharded_init_matches_eager_init(devices):
    spec = causal_lm_spec(TC, example_seq_len=16)
    engine, *_ = deepspeed_tpu.initialize(model=spec, config=_cfg())

    # the engine's own seed path: init_rng is the first split of PRNGKey(seed)
    seed = engine.config.model.seed
    init_rng = jax.random.split(jax.random.PRNGKey(seed))[0]
    want = spec.init_fn(init_rng)

    got = engine.state.params
    for (ka, a), (kb, b) in zip(
        sorted(jax.tree_util.tree_flatten_with_path(want)[0], key=lambda kv: str(kv[0])),
        sorted(jax.tree_util.tree_flatten_with_path(got)[0], key=lambda kv: str(kv[0])),
    ):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=1e-6,
            err_msg=f"{ka} vs {kb}")


def test_sharded_init_places_leaves_sharded(devices):
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=16), config=_cfg())
    leaf = engine.state.params["embed"]["embedding"]
    # fsdp=8: the embedding's shards live on 8 distinct devices
    assert len(leaf.sharding.device_set) == 8
    assert not leaf.sharding.is_fully_replicated


def test_universal_checkpoint_streams_atoms(tmp_path, devices):
    """v2 universal checkpoints are tensorstore dirs (parallel streamed I/O),
    not one consolidated host .npz (round-2 verdict item 6)."""
    from deepspeed_tpu.checkpoint.universal import load_universal, save_universal

    e1, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=16), config=_cfg())
    batch = {"input_ids": np.random.default_rng(0).integers(0, 128, (8, 16), dtype=np.int32)}
    l1 = [float(e1.train_batch(batch)["loss"]) for _ in range(2)]
    path = save_universal(e1, str(tmp_path), sidecar=False)
    assert not os.path.exists(os.path.join(path, "atoms.npz"))
    assert not os.path.exists(os.path.join(path, "atoms_host.npz"))
    assert os.path.isdir(os.path.join(path, "atoms"))

    # reload into a DIFFERENT layout (stage-1, dp-only mesh) and continue
    e2, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=16),
        config={**_cfg(stage=1), "mesh": {"dp": 8}})
    load_universal(e2, str(tmp_path))
    l2 = float(e2.train_batch(batch)["loss"])
    l1b = float(e1.train_batch(batch)["loss"])
    np.testing.assert_allclose(l2, l1b, rtol=1e-4)


def test_zero_namespace_gathered_parameters(devices):
    """deepspeed_tpu.zero.GatheredParameters (reference deepspeed.zero):
    gathered full params are mutable inside the context and the mutation
    lands back in the sharded masters — and the next step consumes it."""
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu import zero
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
    from deepspeed_tpu.utils import safe_get_full_fp32_param

    cfg = TransformerConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                            num_layers=1, num_heads=2, max_seq_len=16)
    with zero.Init():  # API-compat context
        spec = causal_lm_spec(cfg, example_seq_len=16)
    eng, *_ = deepspeed_tpu.initialize(
        model=spec,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3}, "mesh": {"fsdp": 8},
                "steps_per_print": 1000})
    with zero.GatheredParameters(eng) as params:
        assert isinstance(params["embed"]["embedding"], np.ndarray)
        params["embed"]["embedding"][:] = 0.125
    got = safe_get_full_fp32_param(eng, "embed/embedding")
    np.testing.assert_allclose(got, 0.125)
    m = eng.train_batch({"input_ids": np.zeros((eng.train_batch_size, 16), np.int32)})
    assert np.isfinite(float(m["loss"]))


def test_gathered_parameters_rejects_param_list():
    """Reference-signature misuse fails EAGERLY with a clear TypeError: the
    reference's GatheredParameters(params, modifier_rank=...) takes a
    parameter list, the TPU-native form takes the engine — passing anything
    without `.state` must not surface later as an opaque AttributeError
    (ADVICE round 5; divergence documented in migrating-from-deepspeed.md)."""
    import pytest

    from deepspeed_tpu import zero

    for bad in ([np.zeros((2, 2))], {"w": np.zeros(3)}, None):
        with pytest.raises(TypeError, match="ENGINE.*deepspeed_tpu.initialize"):
            zero.GatheredParameters(bad, modifier_rank=0)
