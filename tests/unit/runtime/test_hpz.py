"""ZeRO++ hpZ secondary partition (reference zero/config.py:294-315,
utils/groups.py:650-695): masters sharded over the FULL data world, compute
params over an intra-node sub-group — per-layer gathers ride the small axis.
"""

import re

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
from tests.unit.parallel.partial_manual import partial_manual_xfail

TC = TransformerConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                       num_layers=2, num_heads=4, max_seq_len=32)


def _cfg(zero):
    return {
        "train_batch_size": 16,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "mesh": {"fsdp": 8, "dp": 1},
        "zero_optimization": zero,
        "steps_per_print": 1000,
    }


def _batch(e, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 128, (e.train_batch_size, 16), dtype=np.int32)}


def test_hpz_mesh_and_shardings(devices):
    e, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(TC, example_seq_len=16),
        config=_cfg({"stage": 3, "zero_hpz_partition_size": 2,
                     "param_persistence_threshold": 0}),
    )
    # fsdp re-factored to the intra-node group; leftover folded into dp
    assert e.mesh.shape["fsdp"] == 2 and e.mesh.shape["dp"] == 4
    # masters: FULL data world (dp x fsdp = 8 distinct shards)
    leaf = e.state.params["embed"]["embedding"]
    distinct = {str(v) for v in leaf.sharding.devices_indices_map(leaf.shape).values()}
    assert len(distinct) == 8, f"master should shard 8 ways, got {len(distinct)}"
    # secondary (compute) partition: fsdp only
    sec = jax.tree_util.tree_leaves(e._hpz_compute_sharding)[0]
    flat = [a for entry in sec.spec if entry is not None
            for a in (entry if isinstance(entry, tuple) else (entry,))]
    assert set(flat) <= {"fsdp", "tp"}


def test_hpz_trajectory_matches_stage3(devices):
    runs = {}
    for name, zero in (
        ("plain", {"stage": 3}),
        ("hpz", {"stage": 3, "zero_hpz_partition_size": 2}),
    ):
        e, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(TC, example_seq_len=16), config=_cfg(zero))
        batch = _batch(e)
        runs[name] = [float(e.train_batch(batch)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(runs["hpz"], runs["plain"], rtol=2e-4)


@partial_manual_xfail
def test_hpz_gathers_ride_small_axis(devices):
    """Comm-volume evidence: the compiled hpZ step's all-gathers are
    predominantly over 2-device (intra-node) groups; the plain stage-3 step
    gathers over all 8."""

    def gather_group_sizes(zero):
        e, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(TC, example_seq_len=16),
            config=_cfg({**zero, "param_persistence_threshold": 0}))
        placed = e._shard_global_batch(_batch(e))
        hlo = e._train_step.lower(e.state, placed).compile().as_text()
        sizes = []
        for line in hlo.splitlines():
            if "all-gather" not in line or "replica_groups" not in line:
                continue
            m = re.search(r"replica_groups=\[(\d+),(\d+)\]", line)  # iota form
            if m:
                sizes.append(int(m.group(2)))
                continue
            m = re.search(r"replica_groups=\{\{([0-9, ]*)\}", line)  # list form
            if m:
                sizes.append(len(m.group(1).split(",")))
        return sizes

    plain = gather_group_sizes({"stage": 3})
    hpz = gather_group_sizes({"stage": 3, "zero_hpz_partition_size": 2})
    assert plain and hpz, "no all-gathers found in compiled HLO"
    # plain stage 3: every gather spans the full 8-way fsdp axis
    assert max(plain) == 8
    # hpZ: small-group gathers exist and dominate
    assert any(s == 2 for s in hpz), f"no intra-group gathers: {hpz}"
    frac_small = sum(1 for s in hpz if s <= 2) / len(hpz)
    assert frac_small >= 0.5, f"intra-group gathers not dominant: {hpz}"


def test_hpz_rejects_zpp_combo(devices):
    with pytest.raises(NotImplementedError, match="hpZ"):
        deepspeed_tpu.initialize(
            model=causal_lm_spec(TC, example_seq_len=16),
            config=_cfg({"stage": 3, "zero_hpz_partition_size": 2,
                         "zero_quantized_weights": True}),
        )


def test_hpz_rejects_mics_combo(devices):
    with pytest.raises(ValueError, match="mutually exclusive"):
        deepspeed_tpu.initialize(
            model=causal_lm_spec(TC, example_seq_len=16),
            config=_cfg({"stage": 3, "zero_hpz_partition_size": 2,
                         "mics_shard_size": 2}),
        )
