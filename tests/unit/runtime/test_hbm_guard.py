"""Pre-flight HBM-fit guard + unverified-composition guards (ISSUE 4
satellites; VERDICT r5 items 2 and 6).

The guard must fire BEFORE any device materialization — the round-5 incident
was an over-budget param init that wedged the relay without raising, so a
post-hoc OOM handler is useless. These tests drive the guard with an
explicit device-memory override (CPU backends report no budget)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.autotuning.autotuner import estimate_state_memory
from deepspeed_tpu.utils.hbm import HBMBudgetError, check_hbm_fit, device_memory_bytes

from ..simple_model import simple_model_spec


@pytest.fixture
def devices():
    import jax

    return jax.devices()


BASE_CFG = {
    "train_micro_batch_size_per_gpu": 2,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    "steps_per_print": 10_000,
}


# ------------------------------------------------------------ memory model
def test_estimate_adds_activation_and_logit_terms():
    base = estimate_state_memory(int(1e6), 0, dp_world=1)
    with_acts = estimate_state_memory(
        int(1e6), 0, dp_world=1, micro_batch=4, seq_len=1024,
        hidden_size=512, num_layers=8, remat=True)
    no_remat = estimate_state_memory(
        int(1e6), 0, dp_world=1, micro_batch=4, seq_len=1024,
        hidden_size=512, num_layers=8, remat=False)
    assert base < with_acts < no_remat

    with_logits = estimate_state_memory(
        int(1e6), 0, dp_world=1, micro_batch=4, seq_len=1024,
        vocab_size=50_000)
    fused = estimate_state_memory(
        int(1e6), 0, dp_world=1, micro_batch=4, seq_len=1024,
        vocab_size=50_000, fused_ce=True)
    # fp32 logits + softmax grad + the CE-backward temp pair (the round-9
    # calibration blind spot): 4 logit-class arrays
    assert with_logits - base == 4 * 1024 * 50_000 * 16
    assert base < fused < with_logits

    # bf16 accumulator halves the grads term; positional form is unchanged
    fp32 = estimate_state_memory(int(1e6), 0, dp_world=1)
    bf16 = estimate_state_memory(int(1e6), 0, dp_world=1, accum_dtype_bytes=2)
    assert fp32 - bf16 == int(1e6) * 2
    assert fp32 == int(1e6) * (4 + 4 + 8)


def test_estimate_attention_temp_term():
    """The materialized-attention backward workspace (the temp-buffer blind
    spot): 5 fp32 score-class arrays per layer, gone under flash attention
    (the kernel never materializes scores)."""
    kw = dict(micro_batch=4, seq_len=256, hidden_size=128, num_layers=2,
              remat=False)
    base = estimate_state_memory(int(5e5), 1, dp_world=8, **kw)
    with_attn = estimate_state_memory(int(5e5), 1, dp_world=8, num_heads=4, **kw)
    assert with_attn - base == 4 * 4 * 256 * 256 * 4 * 2 * 5
    flash = estimate_state_memory(int(5e5), 1, dp_world=8, num_heads=4,
                                  flash_attention=True, **kw)
    assert flash == base
    # remat recomputes scores one layer at a time: the workspace term must
    # not scale with depth (a 48L remat'd model is not 252 GiB of temps)
    kw_r = dict(kw, remat=True)
    base_r = estimate_state_memory(int(5e5), 1, dp_world=8, **kw_r)
    attn_r = estimate_state_memory(int(5e5), 1, dp_world=8, num_heads=4, **kw_r)
    assert attn_r - base_r == 4 * 4 * 256 * 256 * 4 * 1 * 5


def test_estimate_tracks_bench_config_peak():
    """Calibration closure for the round-9 finding: on the CPU bench config
    (2L x 128h, micro 4 x seq 256, bf16 + stage 1, materialized attention)
    the estimate must cover XLA's measured peak (67.4 MiB at dp=8) within
    the 1.2x warn threshold — it used to sit at ~5x."""
    est = estimate_state_memory(
        459392, 1, dp_world=8, compute_dtype_bytes=2, accum_dtype_bytes=4,
        micro_batch=4, seq_len=256, hidden_size=128, num_layers=2,
        vocab_size=512, num_heads=4, remat=False)
    measured_peak = 67_421_149  # memory_analysis() on this jax/XLA, dp=8
    assert measured_peak / est < 1.2, (est, measured_peak / est)
    # and it must not have ballooned into uselessness either
    assert est < 3 * measured_peak


def test_check_hbm_fit_modes():
    # no budget discoverable -> no-op regardless of size
    assert check_hbm_fit(1 << 60, what="x", mode="warn")
    assert check_hbm_fit(1 << 60, what="x", mode="refuse")

    budget = 16 << 30
    assert check_hbm_fit(10 << 30, what="x", mode="refuse", device_memory=budget)
    assert not check_hbm_fit(20 << 30, what="x", mode="warn", device_memory=budget)
    with pytest.raises(HBMBudgetError, match="GiB"):
        check_hbm_fit(20 << 30, what="x", mode="refuse", device_memory=budget)
    with pytest.raises(ValueError):
        check_hbm_fit(1, what="x", mode="bogus")


def test_device_memory_env_override(monkeypatch):
    monkeypatch.setenv("DSTPU_DEVICE_MEMORY_GB", "16")
    assert device_memory_bytes() == 16 << 30


# ------------------------------------------------------------ engine guard
def test_engine_refuses_over_budget_before_materialization(devices):
    cfg = dict(BASE_CFG)
    cfg["hbm_guard"] = {"enabled": True, "device_memory_gb": 1e-6}
    with pytest.raises(HBMBudgetError) as ei:
        deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg)
    # the refusal carries the byte estimate and the budget
    assert ("GiB" in str(ei.value) or "MiB" in str(ei.value))
    assert "budget" in str(ei.value)


def test_engine_warns_by_default_and_proceeds(devices, monkeypatch):
    from deepspeed_tpu.utils import hbm as hbm_mod

    msgs = []
    monkeypatch.setattr(hbm_mod.logger, "warning",
                        lambda m, *a, **k: msgs.append(str(m)))
    cfg = dict(BASE_CFG)
    cfg["hbm_guard"] = {"device_memory_gb": 1e-6}  # enabled stays False
    engine, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg)
    assert engine is not None
    assert any("HBM pre-flight" in m for m in msgs)


def test_engine_fits_is_silent(devices):
    cfg = dict(BASE_CFG)
    cfg["hbm_guard"] = {"enabled": True, "device_memory_gb": 64.0}
    engine, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg)
    assert engine is not None


def test_v2_engine_refuses_over_budget(monkeypatch):
    from .. import simple_model  # noqa: F401  (import side effects none)
    from tests.unit.inference.test_inference_v2 import make_model

    cfg, _, params = make_model()
    monkeypatch.setenv("DSTPU_DEVICE_MEMORY_GB", "0.000001")
    from deepspeed_tpu.inference import InferenceEngineV2

    with pytest.raises(HBMBudgetError, match="KV pool"):
        InferenceEngineV2(cfg, params, {"dtype": "fp32", "hbm_check": "refuse"})
    # default mode warns but builds
    eng = InferenceEngineV2(cfg, params, {"dtype": "fp32"})
    assert eng is not None


def test_v1_engine_refuses_over_budget(monkeypatch):
    from tests.unit.inference.test_inference_v2 import make_model

    cfg, _, params = make_model()
    monkeypatch.setenv("DSTPU_DEVICE_MEMORY_GB", "0.000001")
    with pytest.raises(HBMBudgetError, match="param placement"):
        deepspeed_tpu.init_inference(model=cfg, params=params,
                                     config={"dtype": "fp32", "hbm_check": "refuse"})


# ------------------------------------------------------- MoE x TP refusal
def test_moe_tp_mesh_raises(devices):
    """ep×tp composition is unverified (no cross-tp token gather/drop):
    engine build must refuse the mesh loudly (VERDICT r5 item 6)."""
    cfg = dict(BASE_CFG)
    cfg["mesh"] = {"ep": 2, "tp": 2, "dp": -1}
    with pytest.raises(NotImplementedError, match="ep=2 × tp=2"):
        deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg)
