"""Pre-flight HBM-fit guard + unverified-composition guards (ISSUE 4
satellites; VERDICT r5 items 2 and 6).

The guard must fire BEFORE any device materialization — the round-5 incident
was an over-budget param init that wedged the relay without raising, so a
post-hoc OOM handler is useless. These tests drive the guard with an
explicit device-memory override (CPU backends report no budget)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.autotuning.autotuner import estimate_state_memory
from deepspeed_tpu.utils.hbm import HBMBudgetError, check_hbm_fit, device_memory_bytes

from ..simple_model import simple_model_spec


@pytest.fixture
def devices():
    import jax

    return jax.devices()


BASE_CFG = {
    "train_micro_batch_size_per_gpu": 2,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    "steps_per_print": 10_000,
}


# ------------------------------------------------------------ memory model
def test_estimate_adds_activation_and_logit_terms():
    base = estimate_state_memory(int(1e6), 0, dp_world=1)
    with_acts = estimate_state_memory(
        int(1e6), 0, dp_world=1, micro_batch=4, seq_len=1024,
        hidden_size=512, num_layers=8, remat=True)
    no_remat = estimate_state_memory(
        int(1e6), 0, dp_world=1, micro_batch=4, seq_len=1024,
        hidden_size=512, num_layers=8, remat=False)
    assert base < with_acts < no_remat

    with_logits = estimate_state_memory(
        int(1e6), 0, dp_world=1, micro_batch=4, seq_len=1024,
        vocab_size=50_000)
    fused = estimate_state_memory(
        int(1e6), 0, dp_world=1, micro_batch=4, seq_len=1024,
        vocab_size=50_000, fused_ce=True)
    # fp32 logits + softmax grad + the CE-backward temp pair (the round-9
    # calibration blind spot): 4 logit-class arrays
    assert with_logits - base == 4 * 1024 * 50_000 * 16
    assert base < fused < with_logits

    # bf16 accumulator halves the grads term; positional form is unchanged
    fp32 = estimate_state_memory(int(1e6), 0, dp_world=1)
    bf16 = estimate_state_memory(int(1e6), 0, dp_world=1, accum_dtype_bytes=2)
    assert fp32 - bf16 == int(1e6) * 2
    assert fp32 == int(1e6) * (4 + 4 + 8)


def test_estimate_attention_temp_term():
    """The materialized-attention backward workspace (the temp-buffer blind
    spot): 5 fp32 score-class arrays per layer, gone under flash attention
    (the kernel never materializes scores)."""
    kw = dict(micro_batch=4, seq_len=256, hidden_size=128, num_layers=2,
              remat=False)
    base = estimate_state_memory(int(5e5), 1, dp_world=8, **kw)
    with_attn = estimate_state_memory(int(5e5), 1, dp_world=8, num_heads=4, **kw)
    assert with_attn - base == 4 * 4 * 256 * 256 * 4 * 2 * 5
    flash = estimate_state_memory(int(5e5), 1, dp_world=8, num_heads=4,
                                  flash_attention=True, **kw)
    assert flash == base
    # remat recomputes scores one layer at a time: the workspace term must
    # not scale with depth (a 48L remat'd model is not 252 GiB of temps)
    kw_r = dict(kw, remat=True)
    base_r = estimate_state_memory(int(5e5), 1, dp_world=8, **kw_r)
    attn_r = estimate_state_memory(int(5e5), 1, dp_world=8, num_heads=4, **kw_r)
    assert attn_r - base_r == 4 * 4 * 256 * 256 * 4 * 1 * 5


def test_estimate_tracks_bench_config_peak():
    """Calibration closure for the round-9 finding: on the CPU bench config
    (2L x 128h, micro 4 x seq 256, bf16 + stage 1, materialized attention)
    the estimate must cover XLA's measured peak (67.4 MiB at dp=8) within
    the 1.2x warn threshold — it used to sit at ~5x."""
    est = estimate_state_memory(
        459392, 1, dp_world=8, compute_dtype_bytes=2, accum_dtype_bytes=4,
        micro_batch=4, seq_len=256, hidden_size=128, num_layers=2,
        vocab_size=512, num_heads=4, remat=False)
    measured_peak = 67_421_149  # memory_analysis() on this jax/XLA, dp=8
    assert measured_peak / est < 1.2, (est, measured_peak / est)
    # and it must not have ballooned into uselessness either
    assert est < 3 * measured_peak


def test_check_hbm_fit_modes():
    # no budget discoverable -> no-op regardless of size
    assert check_hbm_fit(1 << 60, what="x", mode="warn")
    assert check_hbm_fit(1 << 60, what="x", mode="refuse")

    budget = 16 << 30
    assert check_hbm_fit(10 << 30, what="x", mode="refuse", device_memory=budget)
    assert not check_hbm_fit(20 << 30, what="x", mode="warn", device_memory=budget)
    with pytest.raises(HBMBudgetError, match="GiB"):
        check_hbm_fit(20 << 30, what="x", mode="refuse", device_memory=budget)
    with pytest.raises(ValueError):
        check_hbm_fit(1, what="x", mode="bogus")


def test_device_memory_env_override(monkeypatch):
    monkeypatch.setenv("DSTPU_DEVICE_MEMORY_GB", "16")
    assert device_memory_bytes() == 16 << 30


# ------------------------------------------------------------ engine guard
def test_engine_refuses_over_budget_before_materialization(devices):
    cfg = dict(BASE_CFG)
    cfg["hbm_guard"] = {"enabled": True, "device_memory_gb": 1e-6}
    with pytest.raises(HBMBudgetError) as ei:
        deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg)
    # the refusal carries the byte estimate and the budget
    assert ("GiB" in str(ei.value) or "MiB" in str(ei.value))
    assert "budget" in str(ei.value)


def test_engine_warns_by_default_and_proceeds(devices, monkeypatch):
    from deepspeed_tpu.utils import hbm as hbm_mod

    msgs = []
    monkeypatch.setattr(hbm_mod.logger, "warning",
                        lambda m, *a, **k: msgs.append(str(m)))
    cfg = dict(BASE_CFG)
    cfg["hbm_guard"] = {"device_memory_gb": 1e-6}  # enabled stays False
    engine, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg)
    assert engine is not None
    assert any("HBM pre-flight" in m for m in msgs)


def test_engine_fits_is_silent(devices):
    cfg = dict(BASE_CFG)
    cfg["hbm_guard"] = {"enabled": True, "device_memory_gb": 64.0}
    engine, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg)
    assert engine is not None


def test_v2_engine_refuses_over_budget(monkeypatch):
    from .. import simple_model  # noqa: F401  (import side effects none)
    from tests.unit.inference.test_inference_v2 import make_model

    cfg, _, params = make_model()
    monkeypatch.setenv("DSTPU_DEVICE_MEMORY_GB", "0.000001")
    from deepspeed_tpu.inference import InferenceEngineV2

    with pytest.raises(HBMBudgetError, match="KV pool"):
        InferenceEngineV2(cfg, params, {"dtype": "fp32", "hbm_check": "refuse"})
    # default mode warns but builds
    eng = InferenceEngineV2(cfg, params, {"dtype": "fp32"})
    assert eng is not None


def test_v1_engine_refuses_over_budget(monkeypatch):
    from tests.unit.inference.test_inference_v2 import make_model

    cfg, _, params = make_model()
    monkeypatch.setenv("DSTPU_DEVICE_MEMORY_GB", "0.000001")
    with pytest.raises(HBMBudgetError, match="param placement"):
        deepspeed_tpu.init_inference(model=cfg, params=params,
                                     config={"dtype": "fp32", "hbm_check": "refuse"})


# --------------------------------------------- quantized-serving byte math
def test_kv_byte_formulas():
    """The quantized pool/block formulas the guard, the engine sizing, and
    the capacity bench all share (utils/hbm.py)."""
    from deepspeed_tpu.utils.hbm import kv_blocks_for_bytes, kv_pool_bytes, kv_slot_bytes

    # head_dim=64: bf16 slot-head = 128 B; int8 = 64 + 4 (fp32 scale) = 68 B
    assert kv_slot_bytes(2, 2, 64, 2, None) == 2 * 2 * 2 * 128
    assert kv_slot_bytes(2, 2, 64, 2, "int8") == 2 * 2 * 2 * 68
    assert kv_slot_bytes(2, 2, 64, 2, "fp8") == kv_slot_bytes(2, 2, 64, 2, "int8")
    assert kv_pool_bytes(2, 100, 2, 64, 2, None) == 100 * kv_slot_bytes(2, 2, 64, 2)
    # at identical bytes, int8 yields >=1.8x the blocks (the capacity lever)
    budget = 1 << 22
    b_bf16 = kv_blocks_for_bytes(budget, 2, 16, 2, 64, 2, None)
    b_int8 = kv_blocks_for_bytes(budget, 2, 16, 2, 64, 2, "int8")
    assert b_int8 / b_bf16 >= 1.8


def test_v2_quantized_pool_fits_where_dense_refuses(monkeypatch):
    """The v2 pre-flight learns the quantized pool bytes: a budget the fp32
    pool blows is admitted with kv_cache_dtype='int8' — refuse-before-
    materialize with the REAL (smaller) byte count."""
    from tests.unit.inference.test_inference_v2 import make_model

    from deepspeed_tpu.inference import InferenceEngineV2

    cfg, _, params = make_model()
    # 4096 x 16 slots, head_dim 8: fp32 pool ~16.8 MB, int8 pool ~6.3 MB
    monkeypatch.setenv("DSTPU_DEVICE_MEMORY_GB", "0.012")  # ~12.9 MB budget
    v2_cfg = {"dtype": "fp32", "kv_block_size": 16, "num_kv_blocks": 4096,
              "hbm_check": "refuse"}
    with pytest.raises(HBMBudgetError, match="KV pool"):
        InferenceEngineV2(cfg, params, v2_cfg)
    eng = InferenceEngineV2(cfg, params, dict(v2_cfg, kv_cache_dtype="int8"))
    assert eng.pool.k.dtype.name == "int8" and eng.pool.k_scale is not None


def test_v2_woq_estimate_admits_where_dense_refuses(monkeypatch):
    """WOQ weights enter the pre-flight with the quantized byte formula
    (values + scales through the same eligibility predicate as the real
    pass): a model that only fits quantized is admitted."""
    from tests.unit.inference.test_inference_v2 import make_model

    from deepspeed_tpu.inference import InferenceEngineV2
    from deepspeed_tpu.inference.woq import quantized_bytes_estimate, woq_bytes

    cfg, _, params = make_model(vocab_size=512, hidden_size=256,
                                intermediate_size=512)
    import jax

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    dense_mb = n_params * 4 / (1 << 20)
    est = quantized_bytes_estimate(params, "int8", min_size=0, dense_itemsize=4)
    assert est < 0.6 * n_params * 4  # the estimate reflects the shrink
    budget_gb = (est + 0.35 * (dense_mb * (1 << 20))) / (1 << 30) / 0.92
    monkeypatch.setenv("DSTPU_DEVICE_MEMORY_GB", f"{budget_gb:.6f}")
    v2_cfg = {"dtype": "fp32", "kv_block_size": 4, "num_kv_blocks": 8,
              "hbm_check": "refuse"}
    with pytest.raises(HBMBudgetError):
        InferenceEngineV2(cfg, params, v2_cfg)
    eng = InferenceEngineV2(cfg, params, dict(
        v2_cfg, quant={"enabled": True, "bits": 8, "min_leaf_size": 0}))
    # and the estimate the guard admitted on tracks what actually landed
    actual = woq_bytes(eng.params)
    assert actual <= est * 1.05


def test_v2_quantized_estimate_calibration_within_threshold():
    """The serving estimate with quantized pool bytes still covers the XLA
    peak of the captured decode program inside the 1.2x warn threshold
    (telemetry/programs.py calibration — the guard isn't flying blind on
    quantized configs)."""
    from tests.unit.inference.test_inference_v2 import make_model

    from deepspeed_tpu.inference import InferenceEngineV2
    from deepspeed_tpu.telemetry import get_tracer
    from deepspeed_tpu.telemetry.programs import get_program_registry

    tr = get_tracer()
    was = tr.enabled
    tr.configure(enabled=True)
    reg = get_program_registry()
    reg.reset()
    try:
        cfg, _, params = make_model()
        eng = InferenceEngineV2(cfg, params, {
            "dtype": "fp32", "kv_block_size": 4, "num_kv_blocks": 64,
            "chunk_bucket": 8, "decode_chain": 4, "hbm_check": "off",
            "kv_cache_dtype": "int8"})
        eng.generate([np.arange(6) % cfg.vocab_size], max_new_tokens=6)
        assert reg.hbm_estimate("serving")
        chains = [lbl for lbl in reg.labels() if lbl.startswith("v2:decode_chain")]
        assert chains, f"no decode-chain capture in {reg.labels()}"
        ratio = reg.latest(chains[0]).hbm_estimate_ratio
        assert ratio is not None and ratio < 1.2, ratio
    finally:
        tr.configure(enabled=was)
        reg.reset()
        if not was:
            tr.reset()


# ---------------------------------------------------- MoE x TP composition
def test_moe_tp_mesh_no_longer_refused(devices):
    """ISSUE 15 flips the old VERDICT-r5 refusal: ep×tp meshes build — MoE
    models route their token dispatch through the collective all_to_all
    (parallel/moe.py; trajectory + global-math pins live in
    test_ulysses_moe.py::TestMoETPComposition, unservable shapes still
    raise loudly there). A dense model on the same mesh simply trains."""
    cfg = dict(BASE_CFG)
    cfg["mesh"] = {"ep": 2, "tp": 2, "dp": -1}
    engine, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg)
    assert dict(engine.mesh.shape)["ep"] == 2 and dict(engine.mesh.shape)["tp"] == 2
