"""Sparse embedding-gradient DP sync (reference runtime/sparse_tensor.py:69).

The invariant: the sparse path (rows all-gathered over dp, scatter-added
once) must equal psum of the dense per-replica embedding gradient.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.sparse_grad import (
    embedding_row_grads,
    scatter_rows,
    should_use_sparse_embedding_grad,
    sparse_embedding_grad_allreduce,
    sparse_grad_comm_volume,
)
from deepspeed_tpu.topology.mesh import build_mesh
from tests.unit.parallel.partial_manual import partial_manual_xfail

V, H = 64, 16


def test_sparse_equals_dense_psum(devices):
    mesh = build_mesh(axis_sizes={"dp": 8})
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, V, (16, 4), dtype=np.int32))  # dup-heavy
    g_x = jnp.asarray(rng.standard_normal((16, 4, H)), jnp.float32)

    got = jax.jit(lambda i, g: sparse_embedding_grad_allreduce(i, g, V, mesh))(ids, g_x)

    # dense reference: scatter-add per replica then mean over replicas ==
    # scatter-add of everything / dp (linearity)
    fids, rows = embedding_row_grads(ids, g_x)
    want = np.zeros((V, H), np.float32)
    np.add.at(want, np.asarray(fids), np.asarray(rows))
    np.testing.assert_allclose(np.asarray(got), want / 8, rtol=1e-5, atol=1e-6)


def test_row_grads_match_take_vjp(devices):
    """The segment-sum rows are exactly the VJP of jnp.take."""
    rng = np.random.default_rng(1)
    emb = jnp.asarray(rng.standard_normal((V, H)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, V, (2, 8), dtype=np.int32))
    g_x = jnp.asarray(rng.standard_normal((2, 8, H)), jnp.float32)

    _, vjp = jax.vjp(lambda e: jnp.take(e, ids, axis=0), emb)
    (want,) = vjp(g_x)
    fids, rows = embedding_row_grads(ids, g_x)
    got = scatter_rows(fids, rows, V)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_size_heuristic_and_volume():
    assert should_use_sparse_embedding_grad(50304, 8 * 1024) is True
    assert should_use_sparse_embedding_grad(32000, 64 * 1024) is False
    dense, sparse = sparse_grad_comm_volume(50304, 768, dp=8, local_tokens=1024)
    assert sparse < dense  # the win the reference's sparse path exists for


# ----------------------------------------------------- engine-wired (round 5)

def test_sparse_lookup_grad_equals_take(devices):
    """The custom-VJP lookup's table grad must equal jnp.take's, computed
    under an active dp mesh with batch-sharded ids."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.runtime.sparse_grad import sparse_lookup
    from deepspeed_tpu.topology.mesh import set_mesh

    mesh = build_mesh(axis_sizes={"dp": 8})
    set_mesh(mesh)
    rng = np.random.default_rng(2)
    table = jnp.asarray(rng.standard_normal((V, H)), jnp.float32)
    ids = jax.device_put(
        jnp.asarray(rng.integers(0, V, (8, 4), dtype=np.int32)),
        NamedSharding(mesh, P("dp", None)))
    w = jnp.asarray(rng.standard_normal((8, 4, H)), jnp.float32)

    g_sparse = jax.jit(jax.grad(lambda t: (sparse_lookup(t, ids) * w).sum()))(table)
    g_dense = jax.grad(lambda t: (jnp.take(t, ids, axis=0) * w).sum())(table)
    np.testing.assert_allclose(np.asarray(g_sparse), np.asarray(g_dense),
                               rtol=1e-5, atol=1e-6)


def _hlo_for(sparse: bool, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.models import CausalLM, TransformerConfig

    # 1 layer: the embed-grad reduce pattern under test is depth-independent
    # and this helper compiles two full SPMD grad programs (default tier cost)
    cfg = TransformerConfig(
        vocab_size=512, hidden_size=32, intermediate_size=64, num_layers=1,
        num_heads=2, max_seq_len=16, sparse_embedding_grads=sparse)
    model = CausalLM(cfg)
    ids = jax.device_put(jnp.zeros((8, 16), jnp.int32),
                         NamedSharding(mesh, P("dp", None)))
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids}, train=False)["params"]

    def loss(p, i):
        return model.apply({"params": p}, {"input_ids": i}, train=False)[0]

    return jax.jit(jax.grad(loss)).lower(params, ids).compile().as_text()


def test_compiled_step_comm_pattern(devices):
    """With sparse grads the compiled program must contain NO dense [V, H]
    embedding-grad all-reduce — the wire carries the gathered (ids, rows)
    pairs instead. The dense build is the positive control."""
    from deepspeed_tpu.topology.mesh import set_mesh

    mesh = build_mesh(axis_sizes={"dp": 8})
    set_mesh(mesh)
    dense_hlo = _hlo_for(False, mesh)
    sparse_hlo = _hlo_for(True, mesh)

    # the [512, 32] embedding-grad all-reduce (metadata pins it to the embed
    # scatter-add transpose — the untied LM head's dense [V, H] grad reduce
    # legitimately remains in both builds) exists in the dense build only
    def embed_grad_reduces(hlo):
        return [ln for ln in hlo.splitlines()
                if "all-reduce" in ln and "512,32" in ln and "embed" in ln]

    assert embed_grad_reduces(dense_hlo), "positive control broken"
    assert not embed_grad_reduces(sparse_hlo)
    assert "all-gather" in sparse_hlo  # the compact pairs ride the wire


def test_engine_sparse_gradients_trajectory(devices):
    """`sparse_gradients: true` engages the sparse lookup (heuristic wins at
    vocab=512 vs 128 batch tokens) and the training trajectory matches the
    dense-sync engine exactly."""
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    model_cfg = TransformerConfig(
        vocab_size=512, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=2, max_seq_len=16)

    def run(sparse):
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
               "sparse_gradients": sparse, "steps_per_print": 1000}
        eng, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(model_cfg, example_seq_len=16),
            config=cfg, seed=5)
        if sparse:
            assert eng.model.transformer_config.sparse_embedding_grads
        rng = np.random.default_rng(7)
        losses = []
        for _ in range(3):
            batch = {"input_ids": rng.integers(
                0, 512, (eng.train_batch_size, 16), dtype=np.int32)}
            losses.append(float(eng.train_batch(batch)["loss"]))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5)


@partial_manual_xfail
def test_sparse_gradients_compose_with_zeropp(devices):
    """Sparse embedding grads inside the ZeRO++ manual-shard_map micro fn:
    the backward detects the bound axes and gathers directly (no nested
    shard_map). Trajectory within qgZ quantization tolerance of the
    dense-sync qgZ engine."""
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    model_cfg = TransformerConfig(
        vocab_size=512, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=2, max_seq_len=16)

    def run(sparse):
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 2, "zero_quantized_gradients": True},
               "sparse_gradients": sparse, "steps_per_print": 1000}
        eng, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(model_cfg, example_seq_len=16),
            config=cfg, seed=5)
        if sparse:
            assert eng.model.transformer_config.sparse_embedding_grads
        rng = np.random.default_rng(9)
        losses = []
        for _ in range(3):
            batch = {"input_ids": rng.integers(
                0, 512, (eng.train_batch_size, 16), dtype=np.int32)}
            losses.append(float(eng.train_batch(batch)["loss"]))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=0.05)


@partial_manual_xfail
def test_sparse_lookup_grad_scale_inside_manual_shard_map(devices):
    """Inside a manual shard_map (the ZeRO++/1-bit micro-fn convention:
    per-rank grads that a downstream pmean averages), the sparse backward
    must reproduce jnp.take's convention EXACTLY — review r5 caught a dp_world
    over-count here."""
    from deepspeed_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.runtime.sparse_grad import sparse_lookup
    from deepspeed_tpu.topology.mesh import set_mesh

    mesh = build_mesh(axis_sizes={"dp": 8})
    set_mesh(mesh)
    rng = np.random.default_rng(3)
    table = jnp.asarray(rng.standard_normal((V, H)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, V, (8, 4), dtype=np.int32))
    w = jnp.asarray(rng.standard_normal((8, 4, H)), jnp.float32)

    def per_rank_grad(lookup):
        def local(table, ids_l, w_l):
            g = jax.grad(lambda t: (lookup(t, ids_l) * w_l).sum())(table)
            return jax.lax.pmean(g, "dp")  # the engine's unsharded-leaf mean

        return shard_map(local, mesh=mesh,
                         in_specs=(P(), P("dp"), P("dp")), out_specs=P(),
                         check_vma=False)(table, ids, w)

    g_sparse = per_rank_grad(sparse_lookup)
    g_dense = per_rank_grad(lambda t, i: jnp.take(t, i, axis=0))
    np.testing.assert_allclose(np.asarray(g_sparse), np.asarray(g_dense),
                               rtol=1e-5, atol=1e-6)
