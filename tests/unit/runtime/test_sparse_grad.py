"""Sparse embedding-gradient DP sync (reference runtime/sparse_tensor.py:69).

The invariant: the sparse path (rows all-gathered over dp, scatter-added
once) must equal psum of the dense per-replica embedding gradient.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.sparse_grad import (
    embedding_row_grads,
    scatter_rows,
    should_use_sparse_embedding_grad,
    sparse_embedding_grad_allreduce,
    sparse_grad_comm_volume,
)
from deepspeed_tpu.topology.mesh import build_mesh

V, H = 64, 16


def test_sparse_equals_dense_psum(devices):
    mesh = build_mesh(axis_sizes={"dp": 8})
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, V, (16, 4), dtype=np.int32))  # dup-heavy
    g_x = jnp.asarray(rng.standard_normal((16, 4, H)), jnp.float32)

    got = jax.jit(lambda i, g: sparse_embedding_grad_allreduce(i, g, V, mesh))(ids, g_x)

    # dense reference: scatter-add per replica then mean over replicas ==
    # scatter-add of everything / dp (linearity)
    fids, rows = embedding_row_grads(ids, g_x)
    want = np.zeros((V, H), np.float32)
    np.add.at(want, np.asarray(fids), np.asarray(rows))
    np.testing.assert_allclose(np.asarray(got), want / 8, rtol=1e-5, atol=1e-6)


def test_row_grads_match_take_vjp(devices):
    """The segment-sum rows are exactly the VJP of jnp.take."""
    rng = np.random.default_rng(1)
    emb = jnp.asarray(rng.standard_normal((V, H)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, V, (2, 8), dtype=np.int32))
    g_x = jnp.asarray(rng.standard_normal((2, 8, H)), jnp.float32)

    _, vjp = jax.vjp(lambda e: jnp.take(e, ids, axis=0), emb)
    (want,) = vjp(g_x)
    fids, rows = embedding_row_grads(ids, g_x)
    got = scatter_rows(fids, rows, V)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_size_heuristic_and_volume():
    assert should_use_sparse_embedding_grad(50304, 8 * 1024) is True
    assert should_use_sparse_embedding_grad(32000, 64 * 1024) is False
    dense, sparse = sparse_grad_comm_volume(50304, 768, dp=8, local_tokens=1024)
    assert sparse < dense  # the win the reference's sparse path exists for
