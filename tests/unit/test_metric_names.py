"""Tier-1 lint: registry metric names follow the ``subsystem/name`` convention.

The telemetry registry is get-or-create by string, so a typo'd or
unconventioned name silently creates a new metric family that no dashboard,
exposition scrape, or doc catalogue knows about. Same pattern as
``test_no_bare_shard_map.py``: grep the tree so the regression can't land
quietly.

Rules (docs/telemetry.md "label conventions"):
  - every name passed to ``registry.counter/gauge/histogram``,
    ``tracer.count`` or ``tracer.sample_counter`` is ``subsystem/name``
  - the subsystem prefix is a literal (an f-string may interpolate only
    after ``subsystem/``) and comes from the known set below
  - name characters are ``[a-z0-9_/.:]`` (metric names are registry-side;
    the Prometheus exposition handles identifier mapping)
"""

import os
import re

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# one place to extend when a PR adds a legitimate new subsystem
ALLOWED_SUBSYSTEMS = {
    "alerts",
    "anomaly",
    "ckpt",
    "events",
    "coll",
    "comm",
    "compile",
    "data",
    "fabric",
    "fleet",
    "flops",
    "hbm",
    "health",
    "mem",
    "moe",
    "numerics",
    "perf",
    "program",
    "recompile",
    "router",
    "serving",
    "span",
}

# .counter("x") / .gauge( / .histogram( / .sample_counter( are registry- or
# tracer-specific method names; bare .count( is too generic (str.count), so
# it is matched only on tracer-ish receivers.
CALL_RE = re.compile(
    r"\.(?:counter|gauge|histogram|sample_counter)\(\s*f?\"([^\"]+)\"")
TRACER_COUNT_RE = re.compile(
    r"\b(?:tracer|_tracer|tr)\.count\(\s*f?\"([^\"]+)\"")

NAME_RE = re.compile(r"^[a-z0-9_]+/[a-z0-9_/.:{}]*$")

SCAN_DIRS = ("deepspeed_tpu", "tools")
SCAN_FILES = ("bench.py",)


def _python_files():
    for d in SCAN_DIRS:
        for root, _dirs, files in os.walk(os.path.join(REPO_ROOT, d)):
            if ".jax_cache" in root or "__pycache__" in root:
                continue
            for f in files:
                if f.endswith(".py"):
                    yield os.path.join(root, f)
    for f in SCAN_FILES:
        p = os.path.join(REPO_ROOT, f)
        if os.path.exists(p):
            yield p


def _check_name(name: str):
    """Returns a violation string or None. ``name`` is the string literal as
    written; f-string placeholders may only appear after ``subsystem/``."""
    brace = name.find("{")
    slash = name.find("/")
    if slash < 0 or (0 <= brace < slash):
        return f"no literal 'subsystem/' prefix in {name!r}"
    subsystem = name[:slash]
    if subsystem not in ALLOWED_SUBSYSTEMS:
        return (f"unknown subsystem {subsystem!r} in {name!r} "
                f"(extend ALLOWED_SUBSYSTEMS if intentional)")
    if not NAME_RE.match(name):
        return f"bad characters in metric name {name!r}"
    return None


def test_registry_metric_names_follow_convention():
    offenders = []
    for path in _python_files():
        rel = os.path.relpath(path, REPO_ROOT)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        for pat in (CALL_RE, TRACER_COUNT_RE):
            for m in pat.finditer(src):
                err = _check_name(m.group(1))
                if err:
                    line = src.count("\n", 0, m.start()) + 1
                    offenders.append(f"{rel}:{line}: {err}")
    assert not offenders, (
        "registry metric names violating the subsystem/name convention "
        "(docs/telemetry.md):\n  " + "\n  ".join(offenders))


def test_lint_scans_telemetry_and_serving_sources():
    """The files that mint most metric names must be inside the walk —
    guards against a src-layout move silently dropping them."""
    scanned = {os.path.relpath(p, REPO_ROOT) for p in _python_files()}
    expected = {
        os.path.join("deepspeed_tpu", "telemetry", f)
        for f in ("tracer.py", "registry.py", "exposition.py",
                  # fleet telemetry plane (ISSUE 13): the federation layer
                  # mints the fleet/* rollup series
                  "fleet.py", "collector.py",
                  # perf observatory (ISSUE 16): the gate mints the
                  # perf/trajectory + perf/regression_events series
                  "perfgate.py",
                  # numerics observatory (ISSUE 17): wire/serving fidelity
                  # + divergence series
                  "numerics.py",
                  # incident plane (ISSUE 20): the event stream mints the
                  # events/* series, the alert engine the alerts/* series
                  "events.py", "alerts.py")
    } | {
        # step-time attribution gauges (ISSUE 16)
        os.path.join("deepspeed_tpu", "profiling", "attribution.py"),
    } | {
        os.path.join("deepspeed_tpu", "inference", f)
        for f in ("engine_v2.py", "lifecycle.py", "router.py",
                  # disagg serving (ISSUE 14): migration transport rides the
                  # serving metric families minted in router/lifecycle
                  "migrate.py")
    } | {
        # cross-process serving fabric (ISSUE 18): the remote proxy and the
        # daemon mint the fabric/* RPC + liveness series
        os.path.join("deepspeed_tpu", "fabric", f)
        for f in ("remote.py", "replica_daemon.py")
    } | {
        # schedule compiler (ISSUE 19): compile_schedule mints the
        # coll/schedule_* search census
        os.path.join("deepspeed_tpu", "collectives", "schedule.py"),
    } | {os.path.join("tools", "alerts_smoke.py"),
         os.path.join("tools", "bench_serving.py"),
         os.path.join("tools", "fabric_smoke.py"),
         os.path.join("tools", "incident_report.py"),
         os.path.join("tools", "fleet_smoke.py"),
         os.path.join("tools", "numerics_smoke.py"),
         os.path.join("tools", "schedule_smoke.py"),
         os.path.join("tools", "trace_merge.py")}
    missing = expected - scanned
    assert not missing, f"metric-minting files escaped the lint walk: {sorted(missing)}"


def test_known_names_pass_and_bad_names_fail():
    """The checker itself: real names from the tree pass, malformed fail."""
    for good in ("serving/ttft_ms", "span/serve:dispatch", "comm/bytes",
                 "mem/device_bytes_in_use", "anomaly/step_straggler",
                 # quantized-serving capacity gauges (ISSUE 10)
                 "serving/kv_pool_dtype", "serving/kv_bytes_per_token",
                 "serving/kv_pool_utilization",
                 # serving-tier metrics (ISSUE 12)
                 "router/shed_requests", "router/replica_queue_depth",
                 "serving/prefix_hit_rate", "serving/spec_accept_rate",
                 "serving/readmit_wait_ms",
                 # fleet telemetry plane (ISSUE 13)
                 "fleet/goodput", "fleet/tokens_per_s", "fleet/step_rate_min",
                 "fleet/straggler", "fleet/clock_offset_s",
                 # disaggregated serving (ISSUE 14)
                 "serving/migration_ms", "serving/migrated_blocks",
                 "serving/migration_failures", "router/migrations",
                 "fleet/role_processes",
                 # MoE at scale (ISSUE 15): capacity autotuning gauges next
                 # to the PR-7 dispatch-health family; the all-to-all hop
                 # timings ride the existing coll/* histograms
                 "moe/capacity_factor_applied", "moe/capacity_factor_target",
                 "moe/token_drop_rate", "coll/hop_ms", "coll/achieved_gbps",
                 # perf observatory (ISSUE 16): gate trajectory/regression
                 # series and the step-time attribution gauges
                 "perf/trajectory", "perf/regression_events",
                 "perf/attribution_wall_ms", "perf/attribution_compute_ms",
                 "perf/attribution_stall_ms", "perf/attribution_bound",
                 "perf/roofline_flops_fraction", "perf/roofline_bw_fraction",
                 # numerics observatory (ISSUE 17): wire/serving fidelity,
                 # the divergence sentinel, and the fleet digest comparator
                 "numerics/wire_rel_err", "numerics/wire_drift_events",
                 "numerics/ef_residual_norm", "numerics/divergence_events",
                 "numerics/digest_checksum", "numerics/digest_gap",
                 "numerics/kv_dequant_rel_err", "numerics/woq_matmul_rel_err",
                 "numerics/spec_accept_alarm",
                 # cross-process serving fabric (ISSUE 18): remote-replica
                 # RPC/liveness series and the router's roster-change events
                 "fabric/rpcs", "fabric/rpc_ms", "fabric/heartbeat_misses",
                 "fabric/dead_replicas", "fabric/wire_migration_ms",
                 "fabric/wire_bytes", "fabric/drains", "fabric/preempts",
                 "router/dead_replicas", "router/drains",
                 # schedule compiler (ISSUE 19): per-compile search census
                 # next to the observatory's coll/* calibration family
                 "coll/schedule_compiles", "coll/schedule_candidates",
                 "coll/schedule_pred_us", "coll/schedule_levels",
                 # incident plane (ISSUE 20): event-stream accounting, alert
                 # engine state, and the per-endpoint fabric RPC series
                 "events/emitted", "events/deduped", "events/buffered",
                 "events/subscriber_failures",
                 "alerts/firing", "alerts/fired", "alerts/resolved",
                 "alerts/suppressed", "alerts/evaluations",
                 "alerts/rule_errors", "alerts/sink_failures",
                 "fabric/rpc_failures", "fabric/rpc_server_ms",
                 "fabric/rpc_server_failures"):
        assert _check_name(good) is None, good
    for bad in ("ttft", "Serving/ttft", "serving ttft", "{x}/y", "bogus/name"):
        assert _check_name(bad) is not None, bad
