"""Exposition + labelled-registry tests (ISSUE 5 serving SLO observability).

Contract under test:
  - Prometheus text round-trip: render -> parse (small in-test parser) ->
    counters/gauges/histogram buckets and labels match the registry
  - log-bucketed histogram quantiles carry bounded relative error vs numpy
    percentiles
  - labels create separable children; unlabelled call sites are unchanged
  - the /metrics HTTP server serves the live registry (text + JSON)
  - tracer.prometheus_path export rides maybe_export
"""

import json
import re
import urllib.request

import numpy as np
import pytest

from deepspeed_tpu.telemetry import exposition
from deepspeed_tpu.telemetry.registry import MetricsRegistry, bucket_upper_bound
from deepspeed_tpu.telemetry.tracer import Tracer


# ------------------------------------------------------- in-test parser
def parse_prometheus(text):
    """Tiny exposition-format parser: returns (types, samples) where samples
    maps (name, frozenset(labels.items())) -> float."""
    types = {}
    samples = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ")
            types[name] = kind
            continue
        if line.startswith("#"):
            continue
        m = re.match(r'^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})?\s+(\S+)$', line)
        assert m, f"unparseable exposition line: {line!r}"
        name, labelstr, value = m.groups()
        labels = {}
        if labelstr:
            for part in re.findall(r'(\w+)="((?:[^"\\]|\\.)*)"', labelstr):
                labels[part[0]] = part[1]
        v = float("inf") if value == "+Inf" else float(value)
        samples[(name, frozenset(labels.items()))] = v
    return types, samples


# ---------------------------------------------------------- round-trip
def test_prometheus_round_trip_counters_gauges():
    r = MetricsRegistry()
    r.counter("comm/bytes").add(512)
    r.counter("serving/requests", k=8, model="tiny").add(3)
    r.gauge("serving/queue_depth").set(5)
    types, samples = parse_prometheus(exposition.render_prometheus(r))

    assert types["dstpu_comm_bytes_total"] == "counter"
    assert samples[("dstpu_comm_bytes_total", frozenset())] == 512.0
    assert types["dstpu_serving_requests_total"] == "counter"
    assert samples[("dstpu_serving_requests_total",
                    frozenset({("k", "8"), ("model", "tiny")}.union()))] == 3.0
    assert types["dstpu_serving_queue_depth"] == "gauge"
    assert samples[("dstpu_serving_queue_depth", frozenset())] == 5.0


def test_prometheus_round_trip_histogram_buckets():
    r = MetricsRegistry()
    h = r.histogram("serving/ttft_ms", k=4)
    values = [0.5, 1.0, 5.0, 5.0, 40.0, 900.0]
    for v in values:
        h.observe(v)
    text = exposition.render_prometheus(r)
    types, samples = parse_prometheus(text)
    assert types["dstpu_serving_ttft_ms"] == "histogram"

    base = frozenset({("k", "4")})
    assert samples[("dstpu_serving_ttft_ms_count", base)] == len(values)
    assert samples[("dstpu_serving_ttft_ms_sum", base)] == pytest.approx(sum(values))
    # +Inf bucket equals the count
    assert samples[("dstpu_serving_ttft_ms_bucket",
                    frozenset({("k", "4"), ("le", "+Inf")}))] == len(values)
    # cumulative bucket counts reproduce the registry's sparse log buckets
    cum = 0
    for idx, c in h.buckets():
        cum += c
        le = bucket_upper_bound(idx)
        key = ("dstpu_serving_ttft_ms_bucket",
               frozenset({("k", "4"), ("le", repr(float(le)))}))
        assert samples[key] == cum
        # the bucket bound really is an upper bound for everything below it
        assert sum(1 for v in values if v <= le) >= cum
    # precomputed quantile gauges ride along for raw-exposition readers
    assert ("dstpu_serving_ttft_ms_p50", base) in samples
    assert ("dstpu_serving_ttft_ms_p99", base) in samples


def test_quantile_bounded_relative_error_vs_numpy():
    r = MetricsRegistry()
    h = r.histogram("serving/tpot_ms")
    rng = np.random.default_rng(0)
    data = rng.lognormal(mean=2.0, sigma=1.2, size=8000)
    for v in data:
        h.observe(float(v))
    for q in (0.50, 0.90, 0.95, 0.99):
        est = h.quantile(q)
        ref = float(np.percentile(data, q * 100))
        assert abs(est - ref) / ref < 0.06, (q, est, ref)
    # extremes: p0 within one bucket's relative error of the min (estimates
    # clamp to the exact observed range), p100 exactly the max
    assert h.quantile(0.0) <= float(data.min()) * 1.05
    assert h.quantile(1.0) == pytest.approx(float(data.max()))
    s = h.summary()
    assert {"p50", "p95", "p99"} <= set(s)


def test_observe_n_matches_repeated_observe():
    r = MetricsRegistry()
    a = r.histogram("serving/a")
    b = r.histogram("serving/b")
    for _ in range(7):
        a.observe(3.25)
    b.observe_n(3.25, 7)
    assert a.summary() == b.summary()
    assert a.buckets() == b.buckets()


def test_labels_separate_children_unlabelled_unchanged():
    r = MetricsRegistry()
    assert r.counter("comm/bytes") is r.counter("comm/bytes")
    c8 = r.counter("serving/chains", k=8)
    c1 = r.counter("serving/chains", k=1)
    assert c8 is not c1
    assert c8 is r.counter("serving/chains", k=8)
    c8.add(2)
    c1.add(5)
    snap = r.snapshot()
    assert snap['serving/chains{k="8"}'] == 2
    assert snap['serving/chains{k="1"}'] == 5
    # unlabelled key format untouched
    r.counter("comm/bytes").add(7)
    assert r.snapshot()["comm/bytes"] == 7


def test_json_snapshot_has_quantiles_and_labels(tmp_path):
    r = MetricsRegistry()
    r.histogram("serving/ttft_ms", k=2).observe(12.0)
    path = exposition.export_json_snapshot(str(tmp_path / "m.json"), registry=r)
    doc = json.load(open(path))
    m = doc["metrics"]['serving/ttft_ms{k="2"}']
    assert m["count"] == 1 and "p99" in m and m["p50"] == pytest.approx(12.0)


# ------------------------------------------------------------- /metrics
def test_metrics_http_server_serves_live_registry():
    r = MetricsRegistry()
    r.counter("serving/requests").add(1)
    srv = exposition.serve_metrics(port=0, registry=r)
    try:
        url = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(url + "/metrics").read().decode()
        assert "dstpu_serving_requests_total 1.0" in body
        r.counter("serving/requests").add(2)  # live: next scrape sees it
        body = urllib.request.urlopen(url + "/metrics").read().decode()
        assert "dstpu_serving_requests_total 3.0" in body
        doc = json.loads(urllib.request.urlopen(url + "/metrics.json").read())
        assert doc["metrics"]["serving/requests"] == 3.0
        with pytest.raises(Exception):
            urllib.request.urlopen(url + "/nope")
    finally:
        srv.stop()
    assert srv.port is None


def test_tracer_prometheus_path_export(tmp_path):
    tr = Tracer(enabled=True)
    tr.configure(enabled=True, prometheus_path=str(tmp_path / "m.prom"))
    with tr.span("phase_a"):
        pass
    tr.maybe_export()
    text = open(tmp_path / "m.prom").read()
    assert "dstpu_span_phase_a" in text  # whole registry is scrapeable
