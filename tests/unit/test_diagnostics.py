"""Diagnostics subsystem tests (health policies, recompile detector,
step-time anomaly, flight recorder, disabled no-op contract).

Default tier: like telemetry, the diagnostics contract is what every future
reliability claim leans on, so it stays under the cheap sweep. Engine-level
tests use the SimpleMLP fixture on the 8-device CPU mesh; NaN injection goes
through the batch (a NaN input poisons the whole backward), matching how a
bad shard poisons a real run.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.diagnostics import (
    FlightRecorder,
    RecompileDetector,
    StepTimeAnomalyDetector,
    TrainingHealthError,
)
from deepspeed_tpu.telemetry import get_tracer
from tests.unit.simple_model import random_batch, simple_model_spec


@pytest.fixture(autouse=True)
def _clean_global_tracer():
    tr = get_tracer()
    tr.configure(enabled=False)
    tr.trace_path = None
    tr.jsonl_path = None
    tr.reset()
    yield
    tr.configure(enabled=False)
    tr.trace_path = None
    tr.jsonl_path = None
    tr.reset()


def _engine(diag=None, extra=None):
    eng, *_ = deepspeed_tpu.initialize(
        model=simple_model_spec(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 10_000,
            **({"diagnostics": diag} if diag else {}),
            **(extra or {}),
        },
    )
    return eng


def _poisoned(batch):
    bad = {k: np.array(v, copy=True) for k, v in batch.items()}
    bad["x"][0, 0] = np.nan
    return bad


def _params(eng):
    return jax.device_get(eng.state.params)


def _same(a, b):
    return all(np.array_equal(x, y) for x, y in
               zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)))


# ------------------------------------------------------------ health policies
def test_nan_injection_skip_step_policy():
    """skip_step: the poisoned step applies NO update (params, opt state,
    step counter all frozen — the fp16 overflow-skip select, extended to
    bf16/fp32 runs the loss scaler never watches)."""
    eng = _engine({"enabled": True, "health": {"nonfinite_policy": "skip_step"}})
    batch = random_batch(eng.train_batch_size)
    eng.train_batch(batch)
    assert eng.global_steps == 1
    before = _params(eng)

    m = eng.train_batch(_poisoned(batch))
    assert bool(m["health/skip"])
    assert bool(m["health/nonfinite_any"])
    assert int(m["health/nonfinite_total"]) > 0
    # per-leaf-group attribution names the layer group(s) that went nonfinite
    groups = [k for k in m if k.startswith("health/nonfinite/")]
    assert groups and any(int(m[k]) > 0 for k in groups)
    assert eng.global_steps == 1  # skipped step does not count
    assert _same(before, _params(eng))

    m2 = eng.train_batch(batch)  # clean step applies again
    assert not bool(m2["health/skip"])
    assert eng.global_steps == 2
    assert not _same(before, _params(eng))


def test_nan_injection_log_policy_applies_update():
    """log: the verdict is recorded but the update still applies (and the
    step counter advances) — observation only."""
    eng = _engine({"enabled": True, "health": {"nonfinite_policy": "log"}})
    batch = random_batch(eng.train_batch_size)
    eng.train_batch(batch)
    m = eng.train_batch(_poisoned(batch))
    assert bool(m["health/nonfinite_any"])
    assert not bool(m["health/skip"])
    assert eng.global_steps == 2


def test_nan_injection_abort_policy_raises_and_dumps(tmp_path):
    eng = _engine({
        "enabled": True,
        "health": {"nonfinite_policy": "abort"},
        "flight_recorder": {"dump_dir": str(tmp_path),
                            "install_signal_handlers": False,
                            "dump_on_exception": False},
    })
    batch = random_batch(eng.train_batch_size)
    eng.train_batch(batch)
    with pytest.raises(TrainingHealthError) as ei:
        eng.train_batch(_poisoned(batch))
    assert ei.value.verdicts.get("health/nonfinite_any")
    assert ei.value.dump_path and os.path.exists(ei.value.dump_path)
    # abort also skipped the poisoned update
    assert eng.global_steps == 1


def test_grad_spike_zscore_detection():
    """A 1000x-scaled batch after a stable warmup trips the grad-norm
    z-score; with policy log the verdict lands in metrics."""
    eng = _engine({"enabled": True, "health": {
        "grad_spike_policy": "log", "warmup_steps": 4, "grad_spike_zscore": 4.0,
        "ema_beta": 0.9}})
    batch = random_batch(eng.train_batch_size)
    for i in range(8):  # stable baseline past warmup
        m = eng.train_batch(random_batch(eng.train_batch_size, seed=i))
        assert not bool(m["health/grad_spike"])
    spike = {k: np.array(v, copy=True) for k, v in batch.items()}
    spike["x"] *= 1000.0
    m = eng.train_batch(spike)
    assert bool(m["health/grad_spike"])
    assert float(m["health/grad_zscore"]) > 4.0


def test_health_ema_not_poisoned_by_skipped_step():
    """The EMA baseline must ignore skipped steps: after a NaN step the
    count stays put and later clean steps are not judged against NaN."""
    eng = _engine({"enabled": True, "health": {"nonfinite_policy": "skip_step"}})
    batch = random_batch(eng.train_batch_size)
    eng.train_batch(batch)
    c1 = int(eng.state.health.count)
    eng.train_batch(_poisoned(batch))
    assert int(eng.state.health.count) == c1
    assert np.isfinite(float(eng.state.health.gnorm_ema))
    m = eng.train_batch(batch)
    assert not bool(m["health/skip"])


# ------------------------------------------------------- disabled-path no-op
def test_disabled_diagnostics_is_noop():
    eng = _engine()  # no diagnostics block
    assert eng.diagnostics is None
    assert eng.state.health is None
    m = eng.train_batch(random_batch(eng.train_batch_size))
    assert not any(k.startswith("health/") for k in m)
    # and nothing leaked into the (disabled) tracer
    assert get_tracer().events() == []


def test_disabled_health_block_keeps_state_none():
    eng = _engine({"enabled": True, "health": {"enabled": False},
                   "flight_recorder": {"install_signal_handlers": False,
                                       "dump_on_exception": False}})
    assert eng.diagnostics is not None and eng._health is None
    assert eng.state.health is None
    m = eng.train_batch(random_batch(eng.train_batch_size))
    assert not any(k.startswith("health/") for k in m)


# ----------------------------------------------------------------- recompile
def test_recompile_detector_warns_once_naming_argument():
    det = RecompileDetector("unit", arg_names=("x",))
    f = det.wrap(jax.jit(lambda x: x * 2))
    f(jnp.ones((4, 8)))  # initial compile: expected, no warning
    f(jnp.ones((4, 8)))  # cache hit
    assert det.compiles == 1 and det.recompiles == 0

    f(jnp.ones((4, 16)))  # forced shape change -> exactly one recompile event
    assert det.recompiles == 1
    recs = [e for e in det.events if e["kind"] == "recompile"]
    assert len(recs) == 1
    assert any("x" in d and "(4, 8)" in d and "(4, 16)" in d for d in recs[0]["diff"])

    f(jnp.ones((4, 16)))  # stable again: no new events
    assert det.recompiles == 1


def test_recompile_storm_escalates():
    det = RecompileDetector("storm", storm_threshold=3, storm_window_s=60.0)
    f = det.wrap(jax.jit(lambda x: x + 1))
    for n in range(2, 7):  # every call a new shape
        f(jnp.ones((n,)))
    assert det.recompiles >= 3
    assert any(e["kind"] == "storm" for e in det.events)


def test_engine_forced_recompile_fires_detector():
    """An unpadded sequence length (the classic silent-recompile trigger)
    recompiles the fused step; the engine's detector names the changed leaf
    exactly once."""
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=2, max_seq_len=64,
    )
    eng, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=16),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 10_000,
            "diagnostics": {"enabled": True, "health": {"enabled": False},
                            "flight_recorder": {"install_signal_handlers": False,
                                                "dump_on_exception": False}},
        },
    )

    def tok_batch(seq, seed=0):
        rng = np.random.default_rng(seed)
        return {"input_ids": rng.integers(
            0, 64, (eng.train_batch_size, seq), dtype=np.int32)}

    eng.train_batch(tok_batch(16))
    eng.train_batch(tok_batch(16, seed=1))
    det = eng.diagnostics.detector("train_step")
    assert det is not None and det.recompiles == 0

    eng.train_batch(tok_batch(24, seed=2))
    assert det.recompiles == 1
    recs = [e for e in det.events if e["kind"] == "recompile"]
    assert len(recs) == 1
    assert any("input_ids" in d and "16" in d and "24" in d
               for d in recs[0]["diff"]), recs[0]["diff"]


def test_inference_bucketing_no_recompile_within_bucket():
    """The v1 engine's seq_bucket claim, now checked: prompts inside one
    bucket never recompile; a new bucket is an expected first compile."""
    from deepspeed_tpu.models import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=2, max_seq_len=128,
    )
    import flax.linen as nn  # noqa: F401  (CausalLM import path warmup)
    from deepspeed_tpu.models import CausalLM

    module = CausalLM(cfg)
    params = module.init({"params": jax.random.PRNGKey(0)},
                         {"input_ids": jnp.zeros((1, 8), jnp.int32)},
                         train=False)["params"]
    eng = deepspeed_tpu.init_inference(
        cfg, params=params, config={"dtype": "fp32", "seq_bucket": 32})
    assert eng._gen_detector is not None
    eng.generate(np.ones((1, 10), np.int32), max_new_tokens=4)
    eng.generate(np.ones((1, 20), np.int32), max_new_tokens=4)  # same bucket
    eng.generate(np.ones((1, 17), np.int32), max_new_tokens=4)  # same bucket
    det = eng._gen_detector
    assert det.compiles == 1 and det.recompiles == 0
    eng.generate(np.ones((1, 40), np.int32), max_new_tokens=4)  # new bucket
    assert det.compiles == 2 and det.recompiles == 0


# ------------------------------------------------------------------- anomaly
def test_step_time_straggler_and_regression_flags():
    tr = get_tracer()
    det = StepTimeAnomalyDetector(window=32, straggler_mads=6.0,
                                  regression_factor=1.3, min_samples=8,
                                  name="t", tracer=tr)
    for _ in range(16):
        flags = det.observe(0.100)
        assert not flags["straggler"] and not flags["regression"]
    flags = det.observe(1.0)  # 10x median: straggler, not yet a regression
    assert flags["straggler"]
    assert det.stragglers == 1
    for _ in range(12):  # sustained 1.5x shift
        flags = det.observe(0.150)
    assert flags["regression"]
    gauges = tr.registry.gauges()
    assert gauges["anomaly/t_median_ms"] > 0
    assert gauges["anomaly/t_regression"] == 1.0


# ----------------------------------------------------------- flight recorder
def test_flight_recorder_ring_and_dump_schema(tmp_path):
    """≥8 step records with health verdicts survive in the dump; the ring
    stays bounded; the JSONL round-trips."""
    eng = _engine({
        "enabled": True,
        "health": {"nonfinite_policy": "skip_step"},
        "flight_recorder": {"capacity": 12, "dump_dir": str(tmp_path),
                            "install_signal_handlers": False,
                            "dump_on_exception": False},
    })
    batch = random_batch(eng.train_batch_size)
    for i in range(15):
        eng.train_batch(random_batch(eng.train_batch_size, seed=i))
    eng.train_batch(_poisoned(batch))
    assert len(eng.diagnostics.flight_recorder) == 12  # bounded

    path = eng.diagnostics.dump(reason="unit_test")
    lines = [json.loads(l) for l in open(path) if l.strip()]
    header = lines[0]
    assert header["kind"] == "header"
    assert header["reason"] == "unit_test"
    assert header["n_records"] == 12
    assert header["context"]["zero_stage"] == 1

    recs = [l for l in lines if l["kind"] == "step_record"]
    assert len(recs) >= 8
    for r in recs:
        assert {"step", "t_unix", "metrics", "health"} <= set(r)
        assert "skip" in r["health"] and "nonfinite_any" in r["health"]
        assert "loss" in r["metrics"] and "grad_norm" in r["metrics"]
    # the poisoned step's verdict is in the dump
    assert recs[-1]["health"]["skip"] is True
    assert recs[-1]["health"]["nonfinite_any"] is True
    # steps are contiguous and ordered (the ring kept the LAST capacity steps)
    steps = [r["step"] for r in recs]
    assert steps == sorted(steps) and steps[-1] == 16

    # schema round-trip: re-serialize == re-parse identical
    assert [json.loads(json.dumps(l)) for l in lines] == lines


def test_flight_recorder_dump_all_via_hook_helpers(tmp_path):
    """dump_all (what the excepthook/signal handlers call) reaches every
    live recorder without an engine reference."""
    from deepspeed_tpu.diagnostics import dump_all

    rec = FlightRecorder(capacity=4, dump_dir=str(tmp_path))
    rec.set_context(run="t")
    for i in range(6):
        rec.record(i, {"loss": float(i)})
    paths = dump_all(reason="signal:SIGUSR1")
    assert any(str(tmp_path) in p for p in paths)
    mine = [p for p in paths if str(tmp_path) in p][0]
    lines = [json.loads(l) for l in open(mine) if l.strip()]
    assert lines[0]["reason"] == "signal:SIGUSR1"
    assert lines[0]["n_records"] == 4  # bounded ring kept the last 4
    assert [l["step"] for l in lines[1:5]] == [2, 3, 4, 5]


def test_flops_profiler_mfu_reaches_registry_and_monitor_scalars():
    """The flops profiler publishes achieved-TFLOPS/MFU into the shared
    registry, so MFU rides the same step_scalars stream (monitor CSV/trace)
    as step time and comm bytes."""
    tr = get_tracer()
    tr.configure(enabled=True)
    eng = _engine(extra={"telemetry": {"enabled": True}})
    eng.flops_profiler.start_profile()
    eng.train_batch(random_batch(eng.train_batch_size))
    assert eng.flops_profiler.result is not None
    gauges = tr.registry.gauges()
    assert "flops/mfu" in gauges and "flops/achieved_tflops" in gauges
    assert gauges["flops/flops_per_step"] > 0
    scalars = tr.step_scalars()
    assert "Telemetry/flops/mfu" in scalars
    assert scalars["Telemetry/flops/flops_per_step"] > 0


def test_explicit_dump_includes_recent_spans(tmp_path):
    """With telemetry on, the dump carries the recent span tail so the
    post-mortem has the timeline, not just the scalars."""
    eng = _engine(
        {"enabled": True,
         "flight_recorder": {"dump_dir": str(tmp_path),
                             "install_signal_handlers": False,
                             "dump_on_exception": False}},
        extra={"telemetry": {"enabled": True}})
    eng.train_batch(random_batch(eng.train_batch_size))
    path = eng.diagnostics.dump()
    lines = [json.loads(l) for l in open(path) if l.strip()]
    span_names = {l["name"] for l in lines if l.get("kind") == "span"}
    assert {"train_batch", "step"} <= span_names
    # Perfetto trace written next to the JSONL
    assert os.path.exists(os.path.splitext(path)[0] + "_trace.json")
