"""Flops profiler tests (coverage model: reference tests/unit/profiling/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.profiling import FlopsProfiler, flops_by_op, get_model_profile
from tests.unit.simple_model import random_batch, simple_model_spec


def test_flops_by_op_matmul_exact():
    a = jnp.zeros((8, 32)); b = jnp.zeros((32, 16))
    counts = flops_by_op(lambda x, y: x @ y, a, b)
    assert counts["dot_general"] == 2 * 8 * 32 * 16


def test_flops_by_op_counts_scan_trips():
    w = jnp.zeros((4, 16, 16)); x = jnp.zeros((2, 16))

    def fn(w, x):
        def body(c, wi):
            return c @ wi, None
        out, _ = jax.lax.scan(body, x, w)
        return out

    counts = flops_by_op(fn, w, x)
    assert counts["dot_general"] == 4 * (2 * 2 * 16 * 16)


def test_get_model_profile_end_to_end():
    a = jnp.ones((16, 64)); b = jnp.ones((64, 64))
    r = get_model_profile(lambda x, y: (x @ y).sum(), a, b, params={"w": b})
    assert r.latency_s > 0
    assert r.params == 64 * 64
    # XLA cost analysis flops should be at least the matmul flops
    assert r.flops_per_step >= 2 * 16 * 64 * 64 * 0.5  # tolerate backend accounting
    d = r.as_dict()
    assert set(d) >= {"flops_per_step", "latency_s", "mfu"}


def test_engine_profiler_integration(devices):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "flops_profiler": {"enabled": True, "profile_step": 1, "top_modules": 3},
        "steps_per_print": 1000,
    }
    e, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg, seed=0)
    for i in range(2):
        e.train_batch(random_batch(e.train_batch_size, seed=i))
    prof = e.flops_profiler
    assert prof.result is not None
    assert prof.get_total_flops() > 0
    assert prof.get_total_params() > 0
    report = prof.print_model_profile()
    assert "flops per step" in report and "dot_general" in report


def test_profiler_fires_once_and_rearms(devices):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "flops_profiler": {"enabled": True, "profile_step": 1},
        "steps_per_print": 1000,
    }
    e, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg, seed=0)
    for i in range(3):
        e.train_batch(random_batch(e.train_batch_size, seed=i))
    first = e.flops_profiler.result
    e.train_batch(random_batch(e.train_batch_size, seed=9))
    assert e.flops_profiler.result is first  # config trigger fired exactly once
    e.flops_profiler.start_profile()  # manual re-arm
    e.train_batch(random_batch(e.train_batch_size, seed=10))
    assert e.flops_profiler.result is not first
    assert not e.flops_profiler.armed  # disarmed itself


def test_flops_by_op_counts_remat_bodies():
    """jax.checkpoint (remat2) bodies must be walked: grad of a remat'd
    matmul re-runs the forward plus two backward dots."""
    w = jnp.ones((16, 16)); x = jnp.ones((4, 16))

    def fn(w, x):
        f = jax.checkpoint(lambda w, x: (x @ w).sum())
        return jax.grad(f)(w, x)

    counts = flops_by_op(fn, w, x)
    base = 2 * 4 * 16 * 16
    assert counts["dot_general"] >= 2 * base  # fwd recompute + bwd dots
