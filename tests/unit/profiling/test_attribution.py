"""Step-time attribution: the four buckets always sum exactly to the wall.

The decomposition never invents time — every estimate is clamped to what
remains of the measured wall, and the residual is an honest ``stall``
bucket. These tests pin the clamping order, the roofline bound verdicts,
the measured-source joins (program registry / coll hops / tracer spans)
and the published gauge surface.
"""

import pytest

from deepspeed_tpu.profiling.attribution import (
    PEAK_BYTES_PER_S,
    PEAK_FLOPS,
    attribute,
    attribute_program,
    measured_collective_s,
    span_last_s,
)
from deepspeed_tpu.telemetry.programs import ProgramRecord, get_program_registry
from deepspeed_tpu.telemetry.registry import MetricsRegistry


def _sum_ms(attr):
    return (attr.compute_ms + attr.collective_ms + attr.host_ms
            + attr.stall_ms)


def test_buckets_sum_exactly_to_wall():
    attr = attribute("step", 0.010, flops=2e9, bytes_accessed=5e7,
                     peak_flops=1e12, peak_bytes_per_s=50e9,
                     collective_s=0.001, host_s=0.0005, publish=False)
    # flop term 2ms > bw term 1ms -> compute=2ms; then coll 1ms, host 0.5ms
    assert attr.compute_ms == pytest.approx(2.0)
    assert attr.collective_ms == pytest.approx(1.0)
    assert attr.host_ms == pytest.approx(0.5)
    assert attr.stall_ms == pytest.approx(6.5)
    assert _sum_ms(attr) == pytest.approx(attr.wall_ms, rel=1e-9)
    assert attr.bound == "stall"
    assert attr.flops_fraction == pytest.approx(0.2)


def test_clamping_order_compute_then_coll_then_host():
    # estimates larger than the wall: compute soaks it all, the rest clamp
    # to zero, and the total still equals the wall exactly
    attr = attribute("step", 0.001, flops=1e12, bytes_accessed=0.0,
                     peak_flops=1e12, collective_s=5.0, host_s=5.0,
                     publish=False)
    assert attr.compute_ms == pytest.approx(1.0)
    assert attr.collective_ms == 0.0
    assert attr.host_ms == 0.0
    assert attr.stall_ms == 0.0
    assert attr.bound == "compute"


def test_memory_bound_verdict():
    # bw term (4ms) dominates flop term (1ms): compute-bucket-dominant but
    # the verdict names the roofline regime actually hit
    attr = attribute("step", 0.005, flops=1e9, bytes_accessed=200e6,
                     peak_flops=1e12, peak_bytes_per_s=50e9, publish=False)
    assert attr.bound == "memory"
    assert attr.compute_ms == pytest.approx(4.0)


def test_comm_and_host_bounds():
    comm = attribute("step", 0.010, collective_s=0.008, publish=False)
    assert comm.bound == "comm"
    host = attribute("step", 0.010, host_s=0.008, publish=False)
    assert host.bound == "host"


def test_zero_wall_and_missing_sources_are_safe():
    attr = attribute("step", 0.0, flops=1e9, peak_flops=1e12,
                     collective_s=1.0, publish=False)
    assert _sum_ms(attr) == 0.0
    assert attr.flops_fraction == 0.0
    rendered = attribute("step", 0.010, publish=False).render()
    assert "stall" in rendered


def test_publish_gauge_surface():
    reg = MetricsRegistry()
    attribute("train_step", 0.010, flops=2e9, peak_flops=1e12,
              registry=reg, publish=True)
    g = reg.gauges()
    assert g['perf/attribution_wall_ms{program="train_step"}'] == pytest.approx(10.0)
    assert g['perf/attribution_compute_ms{program="train_step"}'] == pytest.approx(2.0)
    assert g['perf/attribution_bound{bound="stall",program="train_step"}'] == 1.0
    assert g['perf/roofline_flops_fraction{program="train_step"}'] == pytest.approx(0.2)


# --------------------------------------------------------- measured joins
def test_measured_collective_sums_hop_probes():
    reg = MetricsRegistry()
    assert measured_collective_s(reg) == 0.0
    reg.histogram("coll/hop_ms", route="sig0").observe(2.0)
    reg.histogram("coll/hop_ms", route="sig1").observe(3.0)
    reg.histogram("coll/other_ms", route="sig0").observe(99.0)
    assert measured_collective_s(reg) == pytest.approx(0.005)


def test_span_last_s():
    reg = MetricsRegistry()
    assert span_last_s("data", reg) == 0.0  # never ran: honest zero
    reg.histogram("span/data").observe(7.5)
    assert span_last_s("data", reg) == pytest.approx(7.5)


def test_attribute_program_joins_program_registry():
    preg = get_program_registry()
    preg.reset()
    preg._records["fake_step"] = [ProgramRecord(
        label="fake_step", index=0, flops=2e9, bytes_accessed=5e7)]
    reg = MetricsRegistry()
    reg.histogram("coll/hop_ms", route="sig0").observe(1.0)  # ms
    reg.histogram("span/data").observe(0.0005)               # seconds
    try:
        attr = attribute_program("fake_step", 0.010, backend="cpu",
                                 registry=reg, publish=False)
    finally:
        preg.reset()
    # cpu peaks: flop term 2e9/1e12=2ms > bw term 5e7/50e9=1ms
    assert attr.compute_ms == pytest.approx(2.0)
    assert attr.collective_ms == pytest.approx(1.0)
    assert attr.host_ms == pytest.approx(0.5)
    assert _sum_ms(attr) == pytest.approx(attr.wall_ms, rel=1e-9)


def test_attribute_program_without_capture_is_all_stall():
    preg = get_program_registry()
    preg.reset()
    attr = attribute_program("never_captured", 0.010, backend="cpu",
                             registry=MetricsRegistry(), publish=False)
    assert attr.compute_ms == 0.0
    assert attr.stall_ms == pytest.approx(10.0)


def test_peak_envelopes_cover_all_ledger_backends():
    for backend in ("cpu", "tpu-v5e", "interpret"):
        assert PEAK_FLOPS[backend] > 0
        assert PEAK_BYTES_PER_S[backend] > 0
