"""Disaggregated serving: KV-block migration (ISSUE 14).

Contract under test:
  - page export/import round-trips BIT-IDENTICALLY on bf16/int8/fp8 pools
    (values AND scale pages — the PR-10 layout travels as one unit), with
    the blake2b block content identity preserved across the move (prefix
    cache entries survive migration)
  - the block-table rewrite lands correctly into a FRAGMENTED destination
    allocator (arbitrary, non-contiguous destination block ids)
  - a jaxpr census of the export+import programs on a quantized pool shows
    no re-quantization: no floating head-dim tensor anywhere — the bytes
    move verbatim
  - refcounted prefix-cache blocks export without double-free: the source's
    flush after a migration releases only its own reference
  - import refusal (destination capacity) leaves the destination unchanged
    and — at the router level — the request on its source, never dropped
  - the remote-DMA transport (PR-8 hop kernel shape) moves buffer leaves
    rank-to-rank bit-identically on the CPU mesh
  - router-level: disagg serving is greedy token-identical to a single
    engine, migration stamps land, thread-per-replica dispatch actually
    overlaps (the two-replica concurrency pin)
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference import InferenceEngineV2, ServingRouter
from deepspeed_tpu.inference.migrate import (
    remote_copy_pages,
    transposition_perm,
)
from deepspeed_tpu.inference.paged import (
    export_pool_blocks,
    import_pool_blocks,
)
from deepspeed_tpu.telemetry import chrome_trace_events, get_tracer

from .test_inference_v2 import make_model
from .test_quantized_serving import _all_avals


@pytest.fixture(autouse=True)
def _clean_global_tracer():
    tr = get_tracer()
    tr.configure(enabled=False)
    tr.reset()
    yield
    tr.configure(enabled=False)
    tr.reset()


BASE = {"dtype": "fp32", "kv_block_size": 4, "num_kv_blocks": 64,
        "chunk_bucket": 8, "decode_chain": 4, "hbm_check": "off"}


def _engine(cfg, params, **over):
    base = dict(BASE)
    base.update(over)
    return InferenceEngineV2(cfg, params, base)


def _block_bytes(eng, block):
    """Raw host bytes of one block's pool pages (values + scales)."""
    parts = eng._block_fetch_fn()(eng.pool, jnp.int32(block * eng.config.kv_block_size))
    return tuple(None if p is None else np.asarray(p).tobytes() for p in parts)


def _prefill(eng, prompt, uid=0):
    """Write a prompt's KV through the real put path; returns the seq."""
    eng.put([uid], [np.asarray(prompt, np.int32)])
    return eng.state.get(uid)


# ------------------------------------------------------------- round trips
@pytest.mark.parametrize("kvd", [None, "int8", "fp8"])
def test_export_import_round_trip_bit_identical(kvd):
    """Every pool storage mode: the destination's blocks hold the SOURCE's
    bytes exactly — values and scale pages — under a rewritten block
    table, and the blake2b content identity matches per block in
    block-table order."""
    cfg, _, params = make_model()
    over = {} if kvd is None else {"kv_cache_dtype": kvd}
    src = _engine(cfg, params, **over)
    dst = _engine(cfg, params, **over)
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, cfg.vocab_size, (11,))
    seq = _prefill(src, prompt)
    src_blocks = list(seq.blocks)
    src_hashes = [src._block_content_hash(b) for b in src_blocks]
    src_bytes = [_block_bytes(src, b) for b in src_blocks]

    export = src.export_request(0)
    assert export["n_blocks"] == len(src_blocks)
    assert dst.import_request(7, export)
    dseq = dst.state.get(7)
    assert dseq.seen_tokens == seq.seen_tokens
    assert dseq.n_blocks == seq.n_blocks
    for i, b in enumerate(dseq.blocks):
        assert dst._block_content_hash(int(b)) == src_hashes[i]
        assert _block_bytes(dst, int(b)) == src_bytes[i]


def test_import_into_fragmented_allocator():
    """The destination allocation may be arbitrarily fragmented: the scatter
    IS the block-table rewrite, so non-contiguous / out-of-order block ids
    still receive the pages in block-table order."""
    cfg, _, params = make_model()
    src = _engine(cfg, params, kv_cache_dtype="int8")
    dst = _engine(cfg, params, kv_cache_dtype="int8")
    rng = np.random.RandomState(1)
    seq = _prefill(src, rng.randint(0, cfg.vocab_size, (14,)))
    src_hashes = [src._block_content_hash(b) for b in seq.blocks]

    # fragment the destination free stack: allocate a run, free every
    # second block — the import's allocation interleaves with live blocks
    held = dst.state.allocator.allocate(12)
    dst.state.allocator.free(held[::2])

    export = src.export_request(0)
    assert dst.import_request(3, export)
    dseq = dst.state.get(3)
    got = list(dseq.blocks)
    assert sorted(got) != list(range(min(got), min(got) + len(got))) or True
    # the allocation really is fragmented relative to a fresh engine's
    # contiguous stack pops (some of the freed every-second blocks return)
    assert any(b in set(held[::2].tolist()) for b in got)
    for i, b in enumerate(got):
        assert dst._block_content_hash(int(b)) == src_hashes[i]
    # cleanup path stays consistent
    dst.flush(3)
    dst.state.allocator.free(held[1::2])
    assert dst.state.free_blocks == dst.num_kv_blocks


def test_migration_never_requantizes_jaxpr_census():
    """The PR-8/PR-10 census pattern: the export+import programs of an int8
    pool contain NO floating tensor carrying the head dimension — the
    quantized bytes (and their fp32 [.., 1] scale pages) move verbatim;
    there is no dequant, no requant, no convert anywhere."""
    cfg, _, params = make_model()
    eng = _engine(cfg, params, kv_cache_dtype="int8")
    bs = eng.config.kv_block_size
    blocks = jnp.arange(4, dtype=jnp.int32)

    def roundtrip(pool, blocks):
        buf = export_pool_blocks(pool, blocks, bs)
        return import_pool_blocks(pool, buf, blocks, jnp.int32(4), bs)

    jaxpr = jax.make_jaxpr(roundtrip)(eng.pool, blocks)
    avals = _all_avals(jaxpr.jaxpr, [])
    offenders = [a for a in avals
                 if hasattr(a, "shape") and a.shape
                 and a.shape[-1] == cfg.dims_per_head
                 and jnp.issubdtype(a.dtype, jnp.floating)]
    assert not offenders, [f"{a.dtype} {a.shape}" for a in offenders[:5]]
    # ...and int8 pages really flow through the programs
    assert any(hasattr(a, "shape") and a.dtype == jnp.int8 and a.shape
               and a.shape[-1] == cfg.dims_per_head for a in avals)


def test_refcounted_prefix_blocks_export_without_double_free():
    """A request whose blocks the prefix cache also holds: export is
    read-only, and the source's post-migration flush releases only the
    sequence's reference — the cache entries (and their bytes) survive."""
    cfg, _, params = make_model()
    src = _engine(cfg, params, kv_cache_dtype="int8", prefix_cache=True)
    dst = _engine(cfg, params, kv_cache_dtype="int8")
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, cfg.vocab_size, (13,))
    seq = _prefill(src, prompt)
    src._insert_prefix(0, prompt)  # cache takes its own reference
    cached_blocks = [e.block for e in src.prefix_cache._entries.values()]
    assert cached_blocks  # the prompt's full blocks are indexed
    for b in cached_blocks:
        assert src.state.allocator.refcount(b) == 2  # seq + cache

    export = src.export_request(0)
    assert dst.import_request(1, export)
    src.flush(0)  # the migration hand-off's source release
    # cache references intact, no double-free, bytes still addressable
    for b in cached_blocks:
        assert src.state.allocator.refcount(b) == 1
    hit = src.prefix_cache.match(np.concatenate([prompt, prompt[:1]]))
    assert hit.blocks == cached_blocks[: len(hit.blocks)] and hit.blocks
    assert (src.state.free_blocks
            == src.num_kv_blocks - len(cached_blocks))


def test_import_refusal_leaves_destination_unchanged():
    cfg, _, params = make_model()
    src = _engine(cfg, params)
    dst = _engine(cfg, params, num_kv_blocks=2)  # cannot host the request
    rng = np.random.RandomState(3)
    _prefill(src, rng.randint(0, cfg.vocab_size, (14,)))
    export = src.export_request(0)
    free0 = dst.state.free_blocks
    assert dst.import_request(9, export) is False
    assert dst.state.free_blocks == free0
    assert dst.state.get(9) is None
    # max_seqs refusal too
    dst2 = _engine(cfg, params, max_seqs=1)
    _prefill(dst2, rng.randint(0, cfg.vocab_size, (5,)), uid=42)
    assert dst2.import_request(9, export) is False


def test_import_layout_mismatch_raises():
    cfg, _, params = make_model()
    src = _engine(cfg, params, kv_cache_dtype="int8")
    dst = _engine(cfg, params)  # fp pool
    rng = np.random.RandomState(4)
    _prefill(src, rng.randint(0, cfg.vocab_size, (6,)))
    export = src.export_request(0)
    with pytest.raises(ValueError, match="layout mismatch"):
        dst.import_request(1, export)


# ------------------------------------------------------------ remote transport
def test_transposition_perm_is_full_permutation():
    perm = transposition_perm(4, 1, 3)
    srcs = sorted(s for s, _ in perm)
    dsts = sorted(d for _, d in perm)
    assert srcs == dsts == [0, 1, 2, 3]
    assert (1, 3) in perm and (3, 1) in perm and (0, 0) in perm
    assert transposition_perm(3, 2, 2) == [(0, 0), (1, 1), (2, 2)]
    with pytest.raises(ValueError):
        transposition_perm(2, 0, 5)


def test_remote_copy_pages_moves_bytes_rank_to_rank():
    """The PR-8 hop-kernel transport shape on the CPU mesh: rank dst's
    shard ends up holding rank src's pages bit-identically — values and
    fp32 scale pages in ONE permutation (interpret falls back to ppermute
    where the interpreter cannot discharge remote DMA; compiled TPU runs
    the make_async_remote_copy kernel — same permutation, same bytes)."""
    from jax.sharding import Mesh

    n = min(4, jax.device_count())
    if n < 2:
        pytest.skip("needs >= 2 devices")
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("mig",))
    rng = np.random.RandomState(5)
    values = jnp.asarray(
        rng.randint(-128, 127, (n, 2, 8, 2, 4)), jnp.int8)
    scales = jnp.asarray(rng.randn(n, 2, 8, 2, 1), jnp.float32)
    src, dst = 0, n - 1
    out_v, out_s = remote_copy_pages([values, scales], mesh, "mig", src, dst)
    np.testing.assert_array_equal(np.asarray(out_v)[dst],
                                  np.asarray(values)[src])
    np.testing.assert_array_equal(np.asarray(out_s)[dst],
                                  np.asarray(scales)[src])
    # the reverse edge of the transposition moved too
    np.testing.assert_array_equal(np.asarray(out_v)[src],
                                  np.asarray(values)[dst])


# --------------------------------------------------------------- router level
@pytest.mark.parametrize("kvd", [None, "int8"])
def test_disagg_router_greedy_parity_and_migrations(kvd):
    """1 prefill + 1 decode replica: migrated requests' greedy output is
    token-identical to a single never-migrating engine, and every request
    actually migrated (the acceptance-criteria parity pin)."""
    cfg, _, params = make_model()
    over = {} if kvd is None else {"kv_cache_dtype": kvd}
    rng = np.random.RandomState(6)
    prompts = [rng.randint(0, cfg.vocab_size, (p,)) for p in (7, 3, 5, 6)]
    ref = InferenceEngineV2(cfg, params, dict(BASE, **over)).generate(
        prompts, max_new_tokens=8)
    router = ServingRouter.build(cfg, params, dict(BASE, **over),
                                 replicas=2, roles=["prefill", "decode"])
    outs = router.serve(prompts, max_new_tokens=8)
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)
    assert router.migrations == len(prompts)
    assert router.migration_failures == 0
    assert router.migrated_blocks > 0
    # the decode pool ran the chains; the prefill pool only prefilled
    assert router.stats()["dispatches"][0] >= 1


def test_disagg_prefix_cache_survives_migration():
    """Content-hash identity across the move: blocks inserted into the
    DESTINATION's prefix cache after import carry the same blake2b digests
    the source computed — a later prompt sharing the prefix hits on the
    decode replica without re-prefill."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(7)
    shared = rng.randint(0, cfg.vocab_size, (8,))
    p0 = np.concatenate([shared, rng.randint(0, cfg.vocab_size, (3,))])
    p1 = np.concatenate([shared, rng.randint(0, cfg.vocab_size, (4,))])
    router = ServingRouter.build(
        cfg, params, dict(BASE, kv_cache_dtype="int8", prefix_cache=True),
        replicas=2, roles=["prefill", "decode"])
    router.serve([p0], max_new_tokens=4)
    pre, dec = router.replicas[0].engine, router.replicas[1].engine
    # the imported blocks were indexed at the destination with digests
    # matching the live pool bytes (sharing/migration never touched them)
    assert len(dec.prefix_cache) >= 2
    for e in dec.prefix_cache._entries.values():
        if e.content_hash is not None:
            assert dec._block_content_hash(e.block) == e.content_hash
    # second wave hits the decode replica's migrated prefix via its own
    # re-admission path (preempt-free: served through the prefill pool,
    # whose cache ALSO holds the prefix until its flush released it)
    router.serve([p1], max_new_tokens=4)
    cached = pre.prefill_tokens_cached + dec.prefill_tokens_cached
    assert cached >= len(shared)


def test_disagg_migration_failure_degrades_to_mixed():
    """A decode pool that cannot admit the request (max_seqs already held):
    the import refuses, the request stays live on its SOURCE — which
    decodes it to completion, mixed-mode fallback — and nothing admitted
    is dropped. Serial dispatch pins the round ordering: both migrations
    are attempted before the first migrated request could retire."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(8)
    prompts = [rng.randint(0, cfg.vocab_size, (p,)) for p in (7, 5)]
    ref = InferenceEngineV2(cfg, params, dict(BASE)).generate(
        prompts, max_new_tokens=8)
    engines = [
        InferenceEngineV2(cfg, params, dict(BASE, role="prefill")),
        # one decode seat: the second concurrent import must refuse
        InferenceEngineV2(cfg, params, dict(BASE, role="decode",
                                            max_seqs=1)),
    ]
    router = ServingRouter(engines, dispatch="serial")
    outs = router.serve(prompts, max_new_tokens=8)
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)
    assert router.migrations == 1
    assert router.migration_failures == 1
    assert router.shed_count == 0
    # the prefill replica served the refused request's decodes (fallback)
    assert router.stats()["dispatches"][0] >= 2


def test_disagg_refused_import_retries_when_source_cannot_decode():
    """A capacity-refused import whose SOURCE pool cannot host the full
    decode window (prefill pools are guarded for the prompt alone) must
    RETRY the migration instead of falling back to mixed — mixed fallback
    would wedge the source's chain phase on a request its pool can never
    grow. The destination's seat frees as its chains finish, the retried
    ticket lands, and every admitted request completes token-identically."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(21)
    prompts = [rng.randint(0, cfg.vocab_size, (p,)) for p in (7, 5, 6)]
    ref = InferenceEngineV2(cfg, params, dict(BASE)).generate(
        prompts, max_new_tokens=24)
    engines = [
        # 4 blocks x 4 slots = 16 tokens: fits every prompt, can NEVER fit
        # prompt + 24 new tokens — the pre-fix fallback crashed serve()
        InferenceEngineV2(cfg, params, dict(BASE, num_kv_blocks=4,
                                            role="prefill")),
        # one decode seat: concurrent imports must refuse and retry
        InferenceEngineV2(cfg, params, dict(BASE, role="decode",
                                            max_seqs=1)),
    ]
    router = ServingRouter(engines, dispatch="serial")
    outs = router.serve(prompts, max_new_tokens=24)
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)
    # every request eventually migrated (the source cannot decode any of
    # them) after at least one refused-then-retried attempt
    assert router.migrations == len(prompts)
    assert router.migration_failures >= 1
    assert router.shed_count == 0


def test_disagg_errored_import_retries_when_source_cannot_decode(monkeypatch):
    """An import that ERRORS (not a capacity refusal) on a request whose
    decode window exceeds the source prefill pool must retry like a
    refusal — mixed fallback would wedge the source's chain phase — and a
    failed import attempt must not leak destination blocks (allocator
    rollback)."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, (7,))]
    ref = InferenceEngineV2(cfg, params, dict(BASE)).generate(
        prompts, max_new_tokens=24)
    engines = [
        InferenceEngineV2(cfg, params, dict(BASE, num_kv_blocks=4,
                                            role="prefill")),
        InferenceEngineV2(cfg, params, dict(BASE, role="decode")),
    ]
    free_before = engines[1].state.free_blocks
    orig = engines[1].import_request
    calls = {"n": 0}

    def flaky(uid, export):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected transient import failure")
        return orig(uid, export)

    monkeypatch.setattr(engines[1], "import_request", flaky)
    router = ServingRouter(engines, dispatch="serial")
    outs = router.serve(prompts, max_new_tokens=24)
    np.testing.assert_array_equal(outs[0], ref[0])
    assert calls["n"] == 2  # errored once, retried, landed
    assert router.migrations == 1
    assert router.migration_failures == 1
    # the request finished on the decode replica and was flushed: every
    # destination block is back (no leak from the errored attempt)
    assert engines[1].state.free_blocks == free_before


def test_disagg_limbo_pressure_skips_chain_round_instead_of_raising():
    """In-limbo rows (exported, awaiting a refused-retried import) hold
    their source blocks; when that pressure preempts the source's LAST
    decodable row, the chain phase must skip the round — the preempted
    request re-admits once the limbo drains — not raise the
    pool-too-small RuntimeError that aborts the whole serve()."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(11)
    # req 1 (prompt 7) fits the source's 8x4-slot pool with its full
    # window (7+16=23 <= 32) -> mixed fallback when its import refuses;
    # the others (prompt 20, window 36 > 32) must migrate and sit in limbo
    # holding 6-block prompts while req 1's fallback decodes grow
    lens = (20, 7, 20, 20)
    prompts = [rng.randint(0, cfg.vocab_size, (p,)) for p in lens]
    ref = InferenceEngineV2(cfg, params, dict(BASE)).generate(
        prompts, max_new_tokens=16)
    engines = [
        InferenceEngineV2(cfg, params, dict(BASE, num_kv_blocks=8,
                                            role="prefill")),
        InferenceEngineV2(cfg, params, dict(BASE, role="decode",
                                            max_seqs=1)),
    ]
    router = ServingRouter(engines, dispatch="serial")
    outs = router.serve(prompts, max_new_tokens=16)
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)
    assert router.shed_count == 0
    assert router.migrations >= 2
    # the fallback row was preempted under limbo pressure and re-admitted
    assert router.preemptions >= 1


def test_disagg_empty_pool_falls_back_to_mixed_placement():
    cfg, _, params = make_model()
    engines = [InferenceEngineV2(cfg, params, dict(BASE, role="prefill"))
               for _ in range(2)]  # no decode-capable pool anywhere
    router = ServingRouter(engines)
    assert not router.disagg
    assert all(r.role == "mixed" for r in router.replicas)
    rng = np.random.RandomState(9)
    outs = router.serve([rng.randint(0, cfg.vocab_size, (5,))],
                        max_new_tokens=4)
    assert len(outs[0]) == 4 and router.migrations == 0


def test_disagg_layout_mismatch_rejected_at_build():
    cfg, _, params = make_model()
    engines = [
        InferenceEngineV2(cfg, params, dict(BASE, role="prefill")),
        InferenceEngineV2(cfg, params, dict(BASE, role="decode",
                                            kv_cache_dtype="int8")),
    ]
    with pytest.raises(ValueError, match="KV-pool layout"):
        ServingRouter(engines)


def test_disagg_migration_metrics_and_flow(BASE=BASE):
    """Telemetry contract: serving/migration_ms|migrated_blocks land on the
    DESTINATION replica's labels, TTFT stays pinned to the prefill-side
    arrival, and the trace carries a serve:migrate slice with the
    request's flow step inside it (the prefill->decode migration arrow)."""
    cfg, _, params = make_model()
    tr = get_tracer()
    tr.configure(enabled=True)
    rng = np.random.RandomState(10)
    prompts = [rng.randint(0, cfg.vocab_size, (6,)) for _ in range(2)]
    router = ServingRouter.build(cfg, params, BASE, replicas=2,
                                 roles=["prefill", "decode"])
    outs = router.serve(prompts, max_new_tokens=6)
    assert all(len(o) == 6 for o in outs)
    assert router.migrations == 2

    reg = tr.registry
    k = router.replicas[0].engine.config.decode_chain
    h_mig = reg.histogram("serving/migration_ms", k=k, replica=1)
    assert h_mig.count == 2
    assert reg.counter("serving/migrated_blocks", k=k, replica=1).value > 0
    assert reg.counters().get(
        f'serving/migration_failures{{k="{k}",replica="1"}}', 0) == 0
    assert reg.counters()["router/migrations"] == 2
    # lifecycle: records finished on the decode tracker, TTFT from arrival
    dec_tracker = router.replicas[1].tracker
    recs = dec_tracker.records()
    assert set(recs) == {0, 1}
    for rec in recs.values():
        assert rec.migrations == 1 and rec.phase == "finished"
        assert rec.ttft_s is not None
    # trace: serve:migrate slice on the decode side with the request's
    # flow step INSIDE it (Chrome binds the arrow into the slice)
    doc = chrome_trace_events(tr)
    evs = doc["traceEvents"]
    migs = [e for e in evs if e.get("name") == "serve:migrate"
            and e.get("ph") == "X"]
    assert len(migs) == 2
    steps = [e for e in evs if e.get("ph") == "t"]
    for m in migs:
        assert any(m["ts"] <= s["ts"] <= m["ts"] + m["dur"] + 1
                   for s in steps if s.get("tid") == m.get("tid")), \
            "no flow step inside the serve:migrate slice"


def test_trace_merge_migration_links():
    """tools/trace_merge.migration_links: a flow that steps inside a
    serve:migrate slice joins the pids of ALL its bindable events — the
    prefill-process -> decode-process migration arrow; flows without a
    migrate step don't count."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "trace_merge", os.path.join(os.path.dirname(__file__),
                                    "..", "..", "..", "tools",
                                    "trace_merge.py"))
    tm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tm)
    trace = {"traceEvents": [
        # request flow: starts in the prefill process (pid 0)...
        {"ph": "s", "id": 42, "name": "req-42", "cat": "flow",
         "ts": 0.0, "pid": 0, "tid": 1},
        # ...steps inside the decode process's serve:migrate slice (pid 1)
        {"ph": "X", "name": "serve:migrate", "cat": "serve",
         "ts": 10.0, "dur": 5.0, "pid": 1, "tid": 7},
        {"ph": "t", "id": 42, "name": "req-42", "cat": "flow",
         "ts": 12.0, "pid": 1, "tid": 7},
        # an unrelated flow stepping OUTSIDE any migrate slice
        {"ph": "t", "id": 99, "name": "req-99", "cat": "flow",
         "ts": 12.0, "pid": 1, "tid": 8},
    ]}
    links = tm.migration_links(trace)
    assert links == {42: [0, 1]}


def test_thread_per_replica_dispatch_overlaps():
    """The ROADMAP #1 concurrency pin: with dispatch='threads', replica 1
    completes a decode chain WHILE replica 0's chain dispatch is still in
    flight — a long dispatch on one replica no longer blocks the other's
    chain boundaries. (Serial dispatch would deadlock this pairing; the
    events give it a hard 30 s bound instead.)"""
    cfg, _, params = make_model()
    r0_in_chain = threading.Event()
    r1_chained = threading.Event()

    class Blocking(InferenceEngineV2):
        def decode_chain(self, *a, **kw):
            r0_in_chain.set()
            assert r1_chained.wait(timeout=30), \
                "replica 1 never chained while replica 0's dispatch was in flight"
            return super().decode_chain(*a, **kw)

    class Signalling(InferenceEngineV2):
        def decode_chain(self, *a, **kw):
            assert r0_in_chain.wait(timeout=30)
            out = super().decode_chain(*a, **kw)
            r1_chained.set()
            return out

    engines = [Blocking(cfg, params, dict(BASE)),
               Signalling(cfg, params, dict(BASE))]
    router = ServingRouter(engines, dispatch="threads")
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, cfg.vocab_size, (5,)) for _ in range(2)]
    outs = router.serve(prompts, max_new_tokens=4)
    assert all(o is not None and len(o) == 4 for o in outs)
    assert r0_in_chain.is_set() and r1_chained.is_set()


def test_disagg_pool_bytes_split():
    from deepspeed_tpu.utils.hbm import disagg_pool_bytes

    pre, dec = disagg_pool_bytes(1000, ["prefill", "decode"],
                                 prefill_share=0.25)
    assert pre == 250 and dec == 750
    assert disagg_pool_bytes(1000, ["mixed", "mixed"]) == [500, 500]
    a, b, c = disagg_pool_bytes(900, ["prefill", "decode", "decode"],
                                prefill_share=1 / 3)
    assert a == 300 and b == c == 300
    with pytest.raises(ValueError):
        disagg_pool_bytes(100, [])
    with pytest.raises(ValueError):
        disagg_pool_bytes(100, ["prefill"], prefill_share=1.5)
