"""Serving router over N engine replicas (ISSUE 12 tentpole leg a).

Contract under test:
  - routed output is token-identical to a single engine serving the same
    prompts (greedy; each replica runs the unchanged fast path)
  - SLO admission gate: shed/defer/admit decisions pinned against a fake
    clock; a loop-level run with an unmeetable TTFT budget sheds everything
    BEFORE dispatching (admitted requests are never dropped)
  - preemption re-queues replica-affine: the request re-enters through the
    SAME replica (where its prefix-cache blocks live) and still finishes
    with the correct tokens
  - telemetry: router/* counters + per-replica gauges, per-replica
    serving/* SLO metrics (labelled replica=i), one Perfetto track per
    replica with a slice per dispatched program
"""

import numpy as np
import pytest

from deepspeed_tpu.inference import InferenceEngineV2, ServingRouter
from deepspeed_tpu.inference.config import ServingSLOConfig
from deepspeed_tpu.inference.router import REPLICA_TRACK_BASE
from deepspeed_tpu.telemetry import chrome_trace_events, get_tracer

from .test_inference_v2 import make_model


@pytest.fixture(autouse=True)
def _clean_global_tracer():
    tr = get_tracer()
    tr.configure(enabled=False)
    tr.reset()
    yield
    tr.configure(enabled=False)
    tr.reset()


BASE = {"dtype": "fp32", "kv_block_size": 4, "num_kv_blocks": 64,
        "chunk_bucket": 8, "decode_chain": 4, "hbm_check": "off"}


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ------------------------------------------------------------------- parity
def test_router_greedy_parity_with_single_engine():
    cfg, _, params = make_model()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (7, 3, 5, 6, 4, 8)]
    ref = InferenceEngineV2(cfg, params, dict(BASE)).generate(
        prompts, max_new_tokens=8)
    router = ServingRouter.build(cfg, params, BASE, replicas=2)
    outs = router.serve(prompts, max_new_tokens=8)
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)
    # the load balancer actually spread the work
    assert all(d > 0 for d in router.stats()["dispatches"])


def test_router_parity_with_prefix_cache_and_spec():
    """The whole serving tier composed: 2 replicas, content-hash prefix
    cache, speculative chains — still token-identical to the plain single
    engine."""
    cfg, _, params = make_model(seed=1)
    rng = np.random.RandomState(1)
    shared = rng.randint(0, cfg.vocab_size, (8,))
    prompts = [np.concatenate([shared, rng.randint(0, cfg.vocab_size, (n,))])
               for n in (3, 5, 2, 4)]
    ref = InferenceEngineV2(cfg, params, dict(BASE)).generate(
        prompts, max_new_tokens=8)
    router = ServingRouter.build(
        cfg, params, dict(BASE, prefix_cache=True, spec_decode=3), replicas=2)
    # two waves: the first populates each replica's prefix cache, the
    # second's admissions hit it (requests admitted in one batched prefill
    # can't reuse blocks that very prefill is writing)
    outs = router.serve(prompts[:2], max_new_tokens=8)
    outs += router.serve(prompts[2:], max_new_tokens=8)
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)
    cached = sum(r.engine.prefill_tokens_cached for r in router.replicas)
    assert cached >= 8  # wave-2 prompts reused the shared prefix


# -------------------------------------------------------- admission decisions
def _router_with_emas(slo, prefill_ema=0.0, chain_ema=0.0, replicas=2):
    cfg, _, params = make_model()
    r = ServingRouter.build(cfg, params, BASE, replicas=replicas,
                            slo=slo, clock=FakeClock())
    for rep in r.replicas:
        rep.prefill_ema = prefill_ema
        rep.chain_ema = chain_ema
    return r


def test_admission_decision_shed_fake_clock():
    """Projected TTFT = waited + replica prefill estimate, judged against
    ttft_ms * factor — exact decisions, no wall clock involved."""
    slo = ServingSLOConfig(ttft_ms=100.0, admission="shed",
                           admission_ttft_factor=1.0)
    r = _router_with_emas(slo, prefill_ema=0.040)
    rep = r.replicas[0]
    assert r._admission_decision(0.050, rep) == "admit"   # 90 <= 100 ms
    assert r._admission_decision(0.070, rep) == "shed"    # 110 > 100 ms
    # the factor loosens the gate
    r.slo = ServingSLOConfig(ttft_ms=100.0, admission="shed",
                             admission_ttft_factor=1.5)
    assert r._admission_decision(0.070, rep) == "admit"   # 110 <= 150 ms
    # a FULL replica (no admission capacity) adds one chain boundary to
    # the projection — its earliest admission slot
    r.slo = slo
    rep.chain_ema = 0.050
    for i in range(rep.engine.config.max_seqs):
        rep.active[i] = i
    assert r._admission_decision(0.020, rep) == "shed"    # 20+40+50 > 100
    rep.active.clear()
    assert r._admission_decision(0.020, rep) == "admit"   # 20+40 <= 100


def test_admission_decision_defer_vs_shed():
    """defer holds a request while ANY replica could make the budget; it
    sheds only when the wait alone has blown the budget everywhere."""
    slo = ServingSLOConfig(ttft_ms=100.0, admission="defer")
    r = _router_with_emas(slo, prefill_ema=0.200)  # every replica slow
    rep = r.replicas[0]
    r.replicas[1].prefill_ema = 0.010  # ...except replica 1
    assert r._admission_decision(0.050, rep) == "defer"  # rep 1 could admit
    r.replicas[1].prefill_ema = 0.200
    assert r._admission_decision(0.050, rep) == "defer"  # wait itself OK
    assert r._admission_decision(0.150, rep) == "shed"   # wait alone > budget


def test_admission_none_admits_everything():
    slo = ServingSLOConfig(ttft_ms=0.001, admission="none")
    r = _router_with_emas(slo, prefill_ema=10.0)
    assert r._admission_decision(99.0, r.replicas[0]) == "admit"


# ------------------------------------------------------------ loop-level SLO
def test_router_sheds_unmeetable_budget_before_dispatch():
    """ttft budget no real machine can meet: every request sheds (output
    None), nothing is dispatched, and — the nightly gate's invariant —
    nothing was dropped AFTER admission."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, cfg.vocab_size, (5,)) for _ in range(4)]
    slo = ServingSLOConfig(ttft_ms=1e-4, admission="shed")
    router = ServingRouter.build(cfg, params, BASE, replicas=2, slo=slo)
    outs = router.serve(prompts, max_new_tokens=4)
    assert all(o is None for o in outs)
    assert router.shed_count == 4
    assert router.stats()["dispatches"] == [0, 0]


def test_router_generous_budget_sheds_nothing():
    cfg, _, params = make_model()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, (5,)) for _ in range(4)]
    slo = ServingSLOConfig(ttft_ms=60_000.0, tpot_ms=60_000.0, admission="shed")
    router = ServingRouter.build(cfg, params, BASE, replicas=2, slo=slo)
    outs = router.serve(prompts, max_new_tokens=4)
    assert router.shed_count == 0
    assert all(o is not None and len(o) == 4 for o in outs)
    met, missed = router.goodput()
    assert (met, missed) == (0, 0)  # tracker off without telemetry


# -------------------------------------------------- preemption + affinity
def test_preemption_readmits_replica_affine():
    """Pools sized to force preemption mid-generation: the victim re-enters
    through its original replica (prefix-cache blocks live there), the
    affinity counter sees it, and outputs still match the dense path."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, cfg.vocab_size, (8,)) for _ in range(4)]
    ref = InferenceEngineV2(cfg, params, dict(BASE)).generate(
        prompts, max_new_tokens=8)
    router = ServingRouter.build(
        cfg, params, dict(BASE, num_kv_blocks=6, max_seqs=4,
                          prefix_cache=True),
        replicas=2)
    outs = router.serve(prompts, max_new_tokens=8)
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)
    assert router.preemptions >= 1
    assert router.affine_readmits >= 1
    # (no cache-hit assertion here: under exactly the pressure that causes
    # preemption, _can_schedule_evicting drains the cache FIRST by design —
    # live traffic always outranks cached prefixes)
    # everything released (modulo live cache references)
    for rep in router.replicas:
        held = len(rep.engine.prefix_cache)
        assert rep.engine.state.free_blocks == rep.engine.num_kv_blocks - held


# ------------------------------------------------------------------ telemetry
def test_router_metrics_and_replica_tracks():
    cfg, _, params = make_model()
    tr = get_tracer()
    tr.configure(enabled=True)
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (7, 3, 5, 6)]
    slo = ServingSLOConfig(ttft_ms=60_000.0, tpot_ms=60_000.0, admission="shed")
    router = ServingRouter.build(cfg, params, BASE, replicas=2, slo=slo)
    outs = router.serve(prompts, max_new_tokens=6)
    assert all(len(o) == 6 for o in outs)

    reg = tr.registry
    counters = reg.counters()
    assert counters["router/requests"] == 4
    assert counters.get("router/shed_requests", 0) == 0
    disp = [v for k, v in counters.items() if k.startswith("router/dispatches")]
    assert len(disp) == 2 and sum(disp) >= 4  # >= 1 prefill + 1 chain each
    gauges = reg.gauges()
    for i in (0, 1):
        assert f'router/replica_queue_depth{{replica="{i}"}}' in gauges
        assert f'router/replica_active{{replica="{i}"}}' in gauges
    # per-replica serving SLO metrics: every request finished under the
    # generous targets, counted on its replica's labelled family
    met = sum(v for k, v in counters.items() if k.startswith("serving/slo_met"))
    assert met == 4
    met2, missed2 = router.goodput()
    assert (met2, missed2) == (4, 0)

    # per-replica Perfetto tracks with one slice per dispatched program
    doc = chrome_trace_events(tr)
    evs = doc["traceEvents"]
    track_names = {e["tid"]: e["args"]["name"] for e in evs
                   if e.get("ph") == "M" and e["name"] == "thread_name"}
    for rep in router.replicas:
        tid = REPLICA_TRACK_BASE + rep.index
        assert track_names.get(tid) == f"replica {rep.index}"
        slices = [e for e in evs if e.get("cat") == "router"
                  and e.get("tid") == tid]
        assert len(slices) == rep.dispatches
        assert {e["name"] for e in slices} <= {"prefill", "chain"}


def test_defer_migrates_to_budget_capable_replica():
    """admission='defer' must MOVE the request to the replica that can still
    make the budget (a not-yet-prefilled request has no KV to lose), not
    hold it on an over-budget replica until the clock sheds it."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(6)
    slo = ServingSLOConfig(ttft_ms=500.0, admission="defer")
    router = ServingRouter.build(cfg, params, BASE, replicas=2, slo=slo)
    router.replicas[0].prefill_ema = 10.0  # replica 0 projects way over
    router.replicas[1].prefill_ema = 0.001
    outs = router.serve([rng.randint(0, cfg.vocab_size, (5,))], max_new_tokens=4)
    assert outs[0] is not None and len(outs[0]) == 4
    assert router.deferred_count >= 1
    assert router.shed_count == 0
    d = router.stats()["dispatches"]
    assert d[0] == 0 and d[1] >= 2  # served entirely by the viable replica


def test_router_validates_infeasible_prompts_upfront():
    """A prompt no replica can ever serve raises immediately (the engine's
    generate() guards, applied at serve()) instead of stalling the loop."""
    cfg, _, params = make_model()
    router = ServingRouter.build(
        cfg, params, dict(BASE, num_kv_blocks=2), replicas=2)
    with pytest.raises(ValueError, match="KV pool"):
        router.serve([np.arange(12) % cfg.vocab_size], max_new_tokens=8)
    router2 = ServingRouter.build(cfg, params, dict(BASE, max_seq_len=16),
                                  replicas=2)
    with pytest.raises(ValueError, match="max_seq_len"):
        router2.serve([np.arange(12) % cfg.vocab_size], max_new_tokens=8)


def test_router_rejects_spec_with_sampling():
    cfg, _, params = make_model()
    router = ServingRouter.build(cfg, params, dict(BASE, spec_decode=2),
                                 replicas=2)
    with pytest.raises(ValueError, match="greedy-only"):
        router.serve([np.arange(5) % cfg.vocab_size], max_new_tokens=4,
                     do_sample=True)


def test_preempted_request_bypasses_admission_gate():
    """The SLO gate applies to FIRST admissions only: once a request has
    dispatched a prefill (and may hold generated tokens), a later
    re-admission after preemption must NOT shed it — even if the gate would
    now reject it. Pinned by a gate stub that sheds everything after the
    first wave: the preempted requests still finish, tokens intact."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, cfg.vocab_size, (8,)) for _ in range(4)]
    ref = InferenceEngineV2(cfg, params, dict(BASE)).generate(
        prompts, max_new_tokens=8)
    slo = ServingSLOConfig(ttft_ms=60_000.0, admission="shed")
    router = ServingRouter.build(
        cfg, params, dict(BASE, num_kv_blocks=6, max_seqs=4), replicas=2,
        slo=slo)
    calls = {"n": 0}

    def hostile_gate(waited, rep):
        calls["n"] += 1
        return "admit" if calls["n"] <= 4 else "shed"

    router._admission_decision = hostile_gate
    outs = router.serve(prompts, max_new_tokens=8)
    assert router.preemptions >= 1  # pressure really preempted
    assert router.shed_count == 0  # ...and nothing admitted was dropped
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)


def test_router_requires_engines():
    with pytest.raises(ValueError):
        ServingRouter([])
