"""Serving SLO observability: per-request lifecycle tracking (ISSUE 5).

Contract under test:
  - TTFT / TPOT / queue-wait / e2e pinned against a fake clock (exact values
    via the histograms' ``last``; quantiles within log-bucket error)
  - goodput counted against the ``serving_slo`` targets, preemption breaks
    the TPOT chain
  - engine integration: generate() with telemetry on populates the labelled
    serving metrics, the scheduler/pool gauges, and emits one Perfetto track
    per request with flow events linking admission -> prefill -> every chain
  - telemetry disabled: no request records allocated, outputs identical
  - flight-recorder serving mode: dump names the requests with phase stamps
  - open-loop ``arrival_times``: queue-wait measured from nominal arrival
"""

import json

import numpy as np
import pytest

from deepspeed_tpu.inference import InferenceEngineV2
from deepspeed_tpu.inference.config import ServingSLOConfig
from deepspeed_tpu.inference.lifecycle import TRACK_BASE, LifecycleTracker
from deepspeed_tpu.telemetry import chrome_trace_events, get_tracer
from deepspeed_tpu.telemetry.tracer import Tracer

from .test_inference_v2 import make_model


@pytest.fixture(autouse=True)
def _clean_global_tracer():
    tr = get_tracer()
    tr.configure(enabled=False)
    tr.reset()
    yield
    tr.configure(enabled=False)
    tr.reset()


# ------------------------------------------------------------- fake clock
class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_lifecycle_pins_ttft_tpot_queue_wait_against_fake_clock():
    clk = FakeClock()
    tr = Tracer(enabled=True)
    slo = ServingSLOConfig(ttft_ms=60.0, tpot_ms=15.0)
    t = LifecycleTracker(tr, slo=slo, labels={"k": 4}, clock=clk)

    t.arrive(0, now=0.0)
    t.admit(0, uid=7, now=0.010)            # queue wait = 10 ms
    t.mark_dispatch([0], "prefill", now=0.011)
    t.emitted(0, 1, now=0.050)              # TTFT = 50 ms (first token)
    t.mark_dispatch([0], "chain", now=0.051)
    t.emitted(0, 4, now=0.090)              # 4 tokens in 40 ms -> TPOT 10 ms
    t.finish(0, now=0.090)

    reg = tr.registry
    assert reg.histogram("serving/queue_wait_ms", k=4).last == pytest.approx(10.0)
    assert reg.histogram("serving/ttft_ms", k=4).last == pytest.approx(50.0)
    assert reg.histogram("serving/tpot_ms", k=4).last == pytest.approx(10.0)
    assert reg.histogram("serving/e2e_ms", k=4).last == pytest.approx(90.0)
    # quantile answers carry at most the log-bucket error (~4.4%)
    assert reg.histogram("serving/ttft_ms", k=4).quantile(0.5) == pytest.approx(50.0, rel=0.05)
    # 50 <= 60 and 10 <= 15 -> SLO met
    assert reg.counter("serving/slo_met", k=4).value == 1
    assert reg.counter("serving/slo_missed", k=4).value == 0
    t.sample_gauges(now=0.1)
    assert reg.gauge("serving/goodput", k=4).value == 1.0

    rec = t.get(0)
    assert rec.tokens == 5 and rec.chains == 1 and rec.phase == "finished"
    assert rec.ttft_s == pytest.approx(0.050)
    assert rec.queue_wait_s == pytest.approx(0.010)
    assert rec.mean_tpot_s == pytest.approx(0.010)


def test_lifecycle_slo_miss_and_preemption_breaks_tpot_chain():
    clk = FakeClock()
    tr = Tracer(enabled=True)
    t = LifecycleTracker(tr, slo=ServingSLOConfig(ttft_ms=10.0), clock=clk)

    t.arrive(0, now=0.0)
    t.admit(0, uid=1, now=0.005)
    t.emitted(0, 1, now=0.050)   # TTFT 50 ms > 10 ms target -> miss
    t.preempt(0, now=0.060)
    # re-admission: the 940 ms queue gap must NOT become a TPOT sample
    t.admit(0, uid=2, now=1.000)
    t.emitted(0, 1, now=1.000)   # re-prefill token: no TPOT (chain broken)
    t.emitted(0, 4, now=1.040)   # clean chain: 10 ms/token
    t.finish(0, now=1.040)

    reg = tr.registry
    h = reg.histogram("serving/tpot_ms")
    assert h.count == 1 and h.last == pytest.approx(10.0)
    assert reg.counter("serving/slo_missed").value == 1
    assert reg.counter("serving/preemptions", ).value == 0  # engine-side counter
    assert reg.counter("serving/readmissions").value == 1
    rec = t.get(0)
    assert rec.preemptions == 1 and rec.readmissions == 1
    # queue wait pinned to FIRST admission
    assert reg.histogram("serving/queue_wait_ms").last == pytest.approx(5.0)


def test_readmission_keeps_ttft_from_original_arrival():
    """ISSUE 12 satellite: preempt-before-first-token must NOT restart the
    TTFT clock — first-token latency stays measured from the ORIGINAL
    arrival, and the re-admission wait lands in its own
    serving/readmit_wait_ms histogram (anchored at the preemption stamp)."""
    clk = FakeClock()
    tr = Tracer(enabled=True)
    t = LifecycleTracker(tr, slo=ServingSLOConfig(ttft_ms=500.0), clock=clk)

    t.arrive(0, now=0.0)
    t.admit(0, uid=1, now=0.010)
    t.preempt(0, now=0.030)           # preempted BEFORE any token emitted
    t.admit(0, uid=2, now=0.200)      # re-admitted 170 ms later
    t.emitted(0, 1, now=0.260)        # first token
    t.finish(0, now=0.260)

    reg = tr.registry
    # TTFT from the original arrival (260 ms), NOT from the re-admission
    assert reg.histogram("serving/ttft_ms").last == pytest.approx(260.0)
    assert t.get(0).ttft_s == pytest.approx(0.260)
    # queue wait pinned to the FIRST admission; the 170 ms re-admission
    # wait is its own histogram
    assert reg.histogram("serving/queue_wait_ms").last == pytest.approx(10.0)
    assert reg.histogram("serving/queue_wait_ms").count == 1
    assert reg.histogram("serving/readmit_wait_ms").last == pytest.approx(170.0)
    assert reg.histogram("serving/readmit_wait_ms").count == 1
    assert reg.counter("serving/readmissions").value == 1
    # 260 <= 500 -> the readmitted request still meets its TTFT SLO
    assert reg.counter("serving/slo_met").value == 1


def test_migration_wait_disjoint_from_defer_window():
    """ISSUE 14 small fix: a deferred-then-migrated request re-admitted on
    a DIFFERENT replica must not re-count its pre-admission defer window in
    ``serving/readmit_wait_ms`` — the readmission anchors at the LATEST
    hand-off stamp (here the migration start), so queue/defer wait and
    migration wait are disjoint intervals: queue_wait covers
    [arrival, first admit], readmit/migration wait covers
    [migrate start, re-admit]."""
    clk = FakeClock()
    tr = Tracer(enabled=True)
    slo = ServingSLOConfig(ttft_ms=500.0)
    t_pre = LifecycleTracker(tr, slo=slo, labels={"replica": 0}, clock=clk)
    t_dec = LifecycleTracker(tr, slo=slo, labels={"replica": 1}, clock=clk)

    t_pre.arrive(0, now=0.0)
    # deferred by the admission gate for 100 ms, then first-admitted
    t_pre.admit(0, uid=1, now=0.100)      # queue wait = 100 ms (defer incl.)
    t_pre.emitted(0, 1, now=0.150)        # first token on the prefill pool
    t_pre.migrate_start(0, now=0.200)     # export dispatched
    rec = t_pre.transfer(0, t_dec)
    assert rec is not None and t_pre.get(0) is None
    t_dec.admit(0, uid=9, now=0.260)      # re-admitted on the decode pool
    t_dec.migrated(0, n_blocks=3, now=0.260)
    t_dec.emitted(0, 2, now=0.300)
    t_dec.emitted(0, 2, now=0.320)        # clean chain: 10 ms/token
    t_dec.finish(0, now=0.320)

    reg = tr.registry
    # the readmit wait is the 60 ms migration window, NOT 260 ms from
    # arrival (which would double-count the 100 ms defer window already in
    # queue_wait) and NOT anchored anywhere before the hand-off
    assert reg.histogram("serving/readmit_wait_ms",
                         replica=1).last == pytest.approx(60.0)
    assert reg.histogram("serving/migration_ms",
                         replica=1).last == pytest.approx(60.0)
    assert reg.counter("serving/migrated_blocks", replica=1).value == 3
    assert reg.histogram("serving/queue_wait_ms",
                         replica=0).last == pytest.approx(100.0)
    # TTFT from the ORIGINAL arrival, stamped on the prefill replica
    assert reg.histogram("serving/ttft_ms",
                         replica=0).last == pytest.approx(150.0)
    # the TPOT chain restarted cleanly on the decode replica: the 140 ms
    # arrival->decode-pool gap never becomes a TPOT sample
    h = reg.histogram("serving/tpot_ms", replica=1)
    assert h.count == 1 and h.last == pytest.approx(10.0)
    assert reg.histogram("serving/tpot_ms", replica=0).count == 0
    assert rec.migrations == 1 and rec.readmissions == 1
    # finish-side accounting landed on the destination's labels
    assert reg.counter("serving/requests_finished", replica=1).value == 1
    assert reg.counter("serving/requests", replica=0).value == 1


def test_migrate_failed_resumes_on_source():
    clk = FakeClock()
    tr = Tracer(enabled=True)
    t = LifecycleTracker(tr, slo=ServingSLOConfig(), clock=clk)
    t.arrive(0, now=0.0)
    t.admit(0, uid=1, now=0.01)
    t.emitted(0, 1, now=0.02)
    t.migrate_start(0, now=0.03)
    t.migrate_failed(0)
    rec = t.get(0)
    assert rec.phase == "decoding" and rec.migrations == 0
    assert tr.registry.counter("serving/migration_failures").value == 1
    assert tr.registry.histogram("serving/migration_ms").count == 0


def test_goodput_undefined_without_targets():
    tr = Tracer(enabled=True)
    t = LifecycleTracker(tr, slo=ServingSLOConfig(), clock=FakeClock())
    t.arrive(0, now=0.0)
    t.admit(0, uid=1, now=0.1)
    t.emitted(0, 1, now=0.2)
    t.finish(0, now=0.3)
    assert tr.registry.counters().get("serving/slo_met", 0) == 0
    assert tr.registry.counters().get("serving/slo_missed", 0) == 0


# --------------------------------------------------------- engine integration
def _engine(cfg, params, k, **over):
    base = {"dtype": "fp32", "kv_block_size": 4, "num_kv_blocks": 64,
            "chunk_bucket": 8, "decode_chain": k}
    base.update(over)
    return InferenceEngineV2(cfg, params, base)


def test_generate_populates_serving_metrics_and_request_tracks():
    cfg, _, params = make_model()
    tr = get_tracer()
    tr.configure(enabled=True)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (7, 3, 5)]
    eng = _engine(cfg, params, 4,
                  serving_slo={"ttft_ms": 60_000.0, "tpot_ms": 60_000.0})
    n_new = 6
    outs = eng.generate(prompts, max_new_tokens=n_new)
    assert all(len(o) == n_new for o in outs)

    reg = tr.registry
    lb = {"k": 4}
    assert reg.histogram("serving/ttft_ms", **lb).count == 3
    assert reg.histogram("serving/queue_wait_ms", **lb).count == 3
    assert reg.histogram("serving/e2e_ms", **lb).count == 3
    assert reg.histogram("serving/tpot_ms", **lb).count > 0
    assert reg.counter("serving/requests", **lb).value == 3
    assert reg.counter("serving/requests_finished", **lb).value == 3
    assert reg.counter("serving/slo_met", **lb).value == 3  # generous targets
    # per-request token accounting is exact
    assert sum(r.tokens for r in eng.lifecycle.records().values()) == 3 * n_new
    # satellite gauges (chain-boundary scheduler/pool state); utilization
    # carries the KV-storage-dtype label (quantized-serving observability)
    gauges = reg.gauges()
    for name in ("serving/queue_depth", "serving/batch_occupancy",
                 "serving/kv_pool_free_blocks",
                 'serving/kv_pool_utilization{dtype="fp32"}',
                 'serving/kv_pool_dtype{dtype="fp32"}',
                 "serving/kv_bytes_per_token"):
        assert name in gauges, name
    assert gauges["serving/kv_pool_free_blocks"] == eng.state.free_blocks
    assert reg.counters()["serving/preemptions"] == 0

    # ---- Perfetto: one track per request, flow linking admission ->
    # prefill -> every chain dispatch of that request
    doc = chrome_trace_events(tr)
    evs = doc["traceEvents"]
    track_names = {e["tid"]: e["args"]["name"] for e in evs
                   if e.get("ph") == "M" and e["name"] == "thread_name"}
    for rid in range(3):
        tid = TRACK_BASE + rid
        assert track_names.get(tid) == f"req {rid}"
        req_spans = {e["name"] for e in evs
                     if e.get("cat") == "serve_req" and e["tid"] == tid}
        assert {"queue", "prefill", "decode"} <= req_spans
        flows = [e for e in evs if e.get("ph") in ("s", "t", "f")
                 and e.get("id") == rid]
        by_ph = {p: [e for e in flows if e["ph"] == p] for p in "stf"}
        assert len(by_ph["s"]) == 1 and len(by_ph["f"]) == 1
        # one step per dispatch that carried the request: 1 prefill + chains
        rec = eng.lifecycle.get(rid)
        assert len(by_ph["t"]) == rec.chains + 1 + rec.readmissions
        # flow steps land on the engine thread, inside dispatch wall-time
        disp = [e for e in evs if e["name"] == "serve:dispatch"]
        for step_ev in by_ph["t"]:
            assert any(d["ts"] <= step_ev["ts"] <= d["ts"] + d["dur"] + 1
                       for d in disp)
        assert by_ph["f"][0]["bp"] == "e"


def test_generate_disabled_allocates_no_request_records():
    cfg, _, params = make_model()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, cfg.vocab_size, (5,)) for _ in range(2)]
    tr = get_tracer()
    tr.configure(enabled=True)
    outs_on = _engine(cfg, params, 4).generate(prompts, max_new_tokens=5)
    tr.configure(enabled=False)
    tr.reset()
    eng = _engine(cfg, params, 4)
    outs_off = eng.generate(prompts, max_new_tokens=5)
    assert eng.lifecycle is None  # nothing allocated
    assert tr.registry.counters() == {}
    assert tr.events() == []
    for a, b in zip(outs_on, outs_off):  # path unchanged, greedy-identical
        np.testing.assert_array_equal(a, b)


def test_generate_with_arrival_times_measures_queue_wait_from_arrival():
    cfg, _, params = make_model()
    tr = get_tracer()
    tr.configure(enabled=True)
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, cfg.vocab_size, (4,)) for _ in range(2)]
    eng = _engine(cfg, params, 2)
    outs = eng.generate(prompts, max_new_tokens=4,
                        arrival_times=[0.0, 0.05])
    assert all(len(o) == 4 for o in outs)
    recs = eng.lifecycle.records()
    assert recs[1].arrival - recs[0].arrival == pytest.approx(0.05, abs=1e-6)
    # the late request was admitted only after its nominal arrival
    assert recs[1].first_admit >= recs[1].arrival
    assert tr.registry.histogram("serving/queue_wait_ms", k=2).count == 2
    with pytest.raises(ValueError):
        eng.generate(prompts, max_new_tokens=2, arrival_times=[0.0])


def test_preemption_counted_and_lifecycle_stays_consistent():
    cfg, _, params = make_model()
    tr = get_tracer()
    tr.configure(enabled=True)
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, (8,)) for _ in range(2)]
    # pool sized to force preemption mid-generation (test_serving_fastpath
    # pins output parity for this shape; here we pin the observability)
    eng = _engine(cfg, params, 4, num_kv_blocks=6, max_seqs=4)
    eng.generate(prompts, max_new_tokens=8)
    assert tr.registry.counters()["serving/preemptions"] >= 1
    recs = eng.lifecycle.records()
    assert sum(r.preemptions for r in recs.values()) >= 1
    assert all(r.phase == "finished" for r in recs.values())
    assert tr.registry.counter("serving/requests_finished", k=4).value == 2


# ------------------------------------------------------- flight recorder
def test_flight_recorder_serving_mode_names_requests(tmp_path):
    cfg, _, params = make_model()
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, cfg.vocab_size, (5,)) for _ in range(2)]
    # tracer DISABLED: the recorder alone keeps per-request records
    eng = _engine(cfg, params, 4, flight_recorder=True)
    eng.generate(prompts, max_new_tokens=4)
    assert eng.lifecycle is not None
    assert get_tracer().registry.counters() == {}  # no metrics minted

    path = eng._recorder.dump(reason="test", path=str(tmp_path / "fr.jsonl"))
    lines = [json.loads(l) for l in open(path)]
    header = lines[0]
    assert header["kind"] == "header" and header["context"]["kind"] == "serving"
    assert header["n_requests"] == 2
    reqs = {l["rid"]: l for l in lines if l["kind"] == "request_record"}
    assert set(reqs) == {0, 1}
    for rid, rec in reqs.items():
        assert rec["phase"] == "finished"
        assert rec["tokens"] == 4 and rec["chains"] >= 1
        assert rec["arrival"] <= rec["admit"] <= rec["first_token"] <= rec["finish"]


def test_flight_recorder_request_ring_is_bounded():
    from deepspeed_tpu.diagnostics.flight_recorder import FlightRecorder

    fr = FlightRecorder(request_capacity=3)
    for i in range(10):
        fr.record_request(i, phase="queued", tokens=i)
    fr.record_request(7, phase="decoding")  # update moves it to MRU
    with fr._lock:
        keys = list(fr._requests)
    assert len(keys) == 3
    assert keys[-1] == 7 and fr._requests[7]["tokens"] == 7  # merged update
    # serving mode off -> no-op
    fr2 = FlightRecorder()
    fr2.record_request(1, phase="queued")
    assert len(fr2._requests) == 0
