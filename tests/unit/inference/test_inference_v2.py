"""FastGen-analog engine correctness.

Baselines mirror the reference v2 test suite (tests/unit/inference/v2/):
allocator/state-manager unit behavior, and end-to-end parity of the paged
ragged path against the dense v1 KV-cache path (itself proven against the
training forward in test_inference_v1).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference import InferenceEngineV2, init_inference
from deepspeed_tpu.inference.ragged import BlockedAllocator, StateManager, build_ragged_batch
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig


def make_model(seed=0, **overrides):
    base = dict(
        vocab_size=97, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=128,
    )
    base.update(overrides)
    cfg = TransformerConfig(**base)
    module = CausalLM(cfg)
    rng = jax.random.PRNGKey(seed)
    params = module.init({"params": rng, "dropout": rng},
                         {"input_ids": jnp.zeros((1, 8), jnp.int32)}, train=False)["params"]
    return cfg, module, params


# ----------------------------------------------------------- host-side units
def test_blocked_allocator():
    a = BlockedAllocator(4)
    got = a.allocate(3)
    assert len(set(got)) == 3 and a.free_blocks == 1
    with pytest.raises(RuntimeError):
        a.allocate(2)
    a.free(got[:2])
    assert a.free_blocks == 3
    with pytest.raises(ValueError):
        a.free([got[0]])  # double free


def test_state_manager_admission_and_flush():
    m = StateManager(num_blocks=4, block_size=8, max_seqs=2)
    assert m.can_schedule([1], [30])  # 30 tokens -> 4 blocks
    assert not m.can_schedule([1], [33])  # 5 blocks > 4
    m.extend(1, 30)
    assert m.free_blocks == 0
    assert not m.can_schedule([2], [1])
    m.get(1).seen_tokens = 30
    m.flush(1)
    assert m.free_blocks == 4 and m.get(1) is None
    # max_seqs cap
    m.extend(2, 1)
    m.extend(3, 1)
    assert not m.can_schedule([4], [1])


def test_build_ragged_batch_shapes():
    m = StateManager(num_blocks=16, block_size=4, max_seqs=8)
    b = build_ragged_batch(m, [7, 9], [np.arange(5), np.arange(1)],
                           max_pages=8, row_bucket=4, chunk_bucket=8)
    assert b.tokens.shape == (4, 8) and b.new_lens.tolist() == [5, 1, 0, 0]
    assert (b.positions[0, :5] == np.arange(5)).all()
    # second put for uid 7 continues positions from seen_tokens
    m.get(7).seen_tokens = 5
    b2 = build_ragged_batch(m, [7], [np.arange(1)], max_pages=8)
    assert b2.positions[0, 0] == 5


# ----------------------------------------------------------- device parity
@pytest.mark.parametrize("overrides", [
    {},
    {"norm": "layernorm", "activation": "gelu_exact", "num_kv_heads": 1,
     "qkv_bias": False, "dense_bias": False, "parallel_block": True,
     "tie_embeddings": True},  # falcon-style: parallel block through ragged
    {"norm": "layernorm", "activation": "gelu", "position": "alibi",
     "embed_norm": True, "tie_embeddings": True},  # bloom-style: alibi + embed norm
    {"norm": "layernorm", "activation": "gelu_exact", "parallel_block": True,
     "parallel_mlp_norm": True, "rotary_dim": 4},  # gpt-neox-style parallel ln2
    {"norm": "layernorm", "activation": "gelu", "parallel_block": True,
     "rotary_dim": 4, "rope_interleaved": True, "qkv_bias": False,
     "dense_bias": False, "mlp_bias": True},  # gpt-j-style interleaved rotary
])
def test_paged_matches_dense_v1(overrides):
    """Staggered prefill+decance through v2 == per-prompt v1 greedy decode."""
    cfg, module, params = make_model(**overrides)
    eng = InferenceEngineV2(cfg, params, {"dtype": "fp32", "kv_block_size": 4,
                                          "num_kv_blocks": 64, "chunk_bucket": 8})
    v1 = init_inference(model=cfg, params=params, config={"dtype": "fp32", "seq_bucket": 8})

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (7, 3, 5)]
    outs = eng.generate(prompts, max_new_tokens=6)

    for prompt, out in zip(prompts, outs):
        ref = v1.generate(prompt[None, :], max_new_tokens=6)[0, len(prompt):]
        np.testing.assert_array_equal(out, ref)


def test_put_query_flush_api():
    cfg, _, params = make_model()
    eng = InferenceEngineV2(cfg, params, {"dtype": "fp32", "kv_block_size": 4,
                                          "num_kv_blocks": 16, "max_seqs": 4})
    assert eng.can_schedule([0], [10])
    logits = eng.put([0], [np.arange(10) % cfg.vocab_size])
    assert logits.shape == (1, cfg.vocab_size)
    seen, free = eng.query(0)
    assert seen == 10
    logits2 = eng.put([0], [[3]])
    assert eng.query(0)[0] == 11
    eng.flush(0)
    assert eng.query(0)[0] == 0 and eng.query(0)[1] == 16 * 4


def test_kv_exhaustion_raises():
    cfg, _, params = make_model()
    eng = InferenceEngineV2(cfg, params, {"dtype": "fp32", "kv_block_size": 4,
                                          "num_kv_blocks": 2, "max_seqs": 4})
    with pytest.raises(RuntimeError):
        eng.put([0], [np.zeros(9, np.int32)])  # needs 3 blocks, only 2 exist


def test_continuous_batching_interleaves():
    """Sequences of very different lengths share the pool; late arrivals are
    admitted as blocks free up (tiny pool forces queueing)."""
    cfg, module, params = make_model()
    eng = InferenceEngineV2(cfg, params, {"dtype": "fp32", "kv_block_size": 4,
                                          "num_kv_blocks": 12, "max_seqs": 2})
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (6, 6, 6)]
    outs = eng.generate(prompts, max_new_tokens=4)
    v1 = init_inference(model=cfg, params=params, config={"dtype": "fp32", "seq_bucket": 8})
    for prompt, out in zip(prompts, outs):
        ref = v1.generate(prompt[None, :], max_new_tokens=4)[0, len(prompt):]
        np.testing.assert_array_equal(out, ref)


def test_preemption_under_kv_pressure():
    """Pool sized so concurrent decode overflows mid-generation: the youngest
    sequence must be preempted and re-prefilled, and final outputs still match
    the dense v1 baseline."""
    cfg, module, params = make_model()
    # 6 blocks x 4 slots = 24 KV slots; two 8-token prompts + 8 new tokens
    # each = 32 slots needed at peak -> forced preemption
    eng = InferenceEngineV2(cfg, params, {"dtype": "fp32", "kv_block_size": 4,
                                          "num_kv_blocks": 6, "max_seqs": 4})
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, (8,)) for _ in range(2)]
    outs = eng.generate(prompts, max_new_tokens=8)
    v1 = init_inference(model=cfg, params=params, config={"dtype": "fp32", "seq_bucket": 8})
    for prompt, out in zip(prompts, outs):
        ref = v1.generate(prompt[None, :], max_new_tokens=8)[0, len(prompt):]
        np.testing.assert_array_equal(out, ref)
    # everything released at the end
    assert eng.state.free_blocks == 6


def test_v2_moe_generate_matches_v1():
    """The ragged v2 engine serves MoE models (FastGen serves Mixtral): the
    paged forward routes each layer through the expert mixer, and greedy
    output matches the dense v1 engine on the same params (nightly)."""
    cfg, _, params = make_model(num_experts=4, moe_top_k=2)
    eng = InferenceEngineV2(cfg, params, {"dtype": "fp32", "kv_block_size": 4,
                                          "num_kv_blocks": 64})
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (6, 9)]
    outs = eng.generate(prompts, max_new_tokens=5)
    v1 = init_inference(model=cfg, params=params, config={"dtype": "fp32", "seq_bucket": 16})
    for prompt, out in zip(prompts, outs):
        ref = v1.generate(prompt[None, :], max_new_tokens=5)[0, len(prompt):]
        np.testing.assert_array_equal(out, ref)


def test_generate_rejects_overlong():
    cfg, _, params = make_model()
    eng = InferenceEngineV2(cfg, params, {"dtype": "fp32", "kv_block_size": 4,
                                          "num_kv_blocks": 64, "max_seq_len": 16})
    with pytest.raises(ValueError):
        eng.generate([np.zeros(12, np.int32)], max_new_tokens=8)


# ------------------------------------------------- expert-parallel serving
def test_v2_expert_parallel_decode_identical():
    """Acceptance (ISSUE 15): an ep>1 v2 engine serves greedy decode
    TOKEN-IDENTICAL to the ep=1 engine on the same checkpoint (bf16), with
    expert weights actually sharded over ep and the MoE dispatch/combine
    routed through the collective all_to_all path."""
    import deepspeed_tpu.parallel.moe as pmoe

    cfg, _, params = make_model(num_experts=4, moe_top_k=2)
    base = {"dtype": "bf16", "kv_block_size": 4, "num_kv_blocks": 64}
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (6, 9, 4)]
    ref = InferenceEngineV2(cfg, params, dict(base)).generate(
        prompts, max_new_tokens=8)
    calls = []
    orig = pmoe.collective_moe_apply
    try:
        pmoe.collective_moe_apply = lambda *a, **k: (calls.append(1),
                                                     orig(*a, **k))[1]
        ep_eng = InferenceEngineV2(cfg, params, dict(base, ep_size=2))
        outs = ep_eng.generate(prompts, max_new_tokens=8)
    finally:
        pmoe.collective_moe_apply = orig
    assert calls, "ep>1 engine did not trace the collective dispatch"
    assert ep_eng.mesh.shape["ep"] == 2
    w = ep_eng.params["layers"]["moe"]["experts"]["w_up"]
    assert "ep" in str(w.sharding.spec), w.sharding.spec
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)


def test_v2_expert_parallel_through_unchanged_router():
    """The serving tier is oblivious to expert parallelism: ep-sharded
    replicas serve through the STOCK ServingRouter with greedy output
    matching a single ep=1 engine."""
    from deepspeed_tpu.inference import ServingRouter

    cfg, _, params = make_model(num_experts=4, moe_top_k=2)
    base = {"dtype": "bf16", "kv_block_size": 4, "num_kv_blocks": 64}
    rng = np.random.RandomState(12)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (7, 3, 5, 6)]
    ref = InferenceEngineV2(cfg, params, dict(base)).generate(
        prompts, max_new_tokens=6)
    router = ServingRouter.build(cfg, params, dict(base, ep_size=2),
                                 replicas=2)
    outs = router.serve(prompts, max_new_tokens=6)
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)
    assert all(d > 0 for d in router.stats()["dispatches"])


def test_v2_ep_size_validation():
    cfg, _, params = make_model(num_experts=4, moe_top_k=2)
    dense_cfg, _, dense_params = make_model()
    with pytest.raises(ValueError, match="not divisible"):
        InferenceEngineV2(cfg, params, {"ep_size": 3, "kv_block_size": 4,
                                        "num_kv_blocks": 16})
    with pytest.raises(ValueError, match="dense model"):
        InferenceEngineV2(dense_cfg, dense_params,
                          {"ep_size": 2, "kv_block_size": 4,
                           "num_kv_blocks": 16})
