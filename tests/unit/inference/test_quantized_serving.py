"""Quantized-serving equivalence harness (ISSUE 10).

The tentpole's correctness contract, pinned three ways:

  1. accuracy — int8/fp8 KV-cache storage and WOQ weights vs the fp path:
     bounded logit error at the ``put`` API, greedy-token agreement over a
     K-step decode chain on the CPU mesh (int8 KV is token-identical here)
  2. kernel parity — the fused-dequant Pallas block loads (interpret mode)
     match the XLA per-gathered-block fallback bit-tightly
  3. structure — a jaxpr census of the decode-chain program proves the
     full-precision ``[S_flat, kvH, hd]`` pool NEVER materializes: every
     pool-sized tensor in the program is int8/fp8 (the PR-8 program-census
     pattern applied to storage instead of wires)

Plus the capacity plumbing: byte-budget pool sizing admits ~1.9x the
requests at identical bytes, and the new serving gauges land labelled.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference import InferenceEngineV2
from deepspeed_tpu.inference.paged import (
    _kv_block_quant,
    init_pool,
    paged_attention,
    ragged_decode_chain,
)

from .test_inference_v2 import make_model


def _engine(cfg, params, **over):
    base = {"dtype": "fp32", "kv_block_size": 4, "num_kv_blocks": 64,
            "chunk_bucket": 8, "hbm_check": "off"}
    base.update(over)
    return InferenceEngineV2(cfg, params, base)


# ------------------------------------------------------------------ accuracy
def test_kv_int8_greedy_token_identical():
    """int8 KV (per-head-vector blocks) is accurate enough that greedy decode
    through the chained fast path matches the fp32 pool token-for-token."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (7, 3, 5)]
    outs_fp = _engine(cfg, params).generate(prompts, max_new_tokens=12)
    outs_q = _engine(cfg, params, kv_cache_dtype="int8").generate(
        prompts, max_new_tokens=12)
    for a, b in zip(outs_q, outs_fp):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("kvd,bound", [("int8", 0.03), ("fp8", 0.15)])
def test_kv_quant_logit_error_bounded(kvd, bound):
    """Bounded logit drift at the ``put`` API, prefill AND decode reads
    (measured ~1% int8 / ~6% fp8 on this tiny random-init model — real
    checkpoints with structured activations sit well below)."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, cfg.vocab_size, (9,))
    base = _engine(cfg, params)
    l_fp = base.put([0], [prompt])
    l_fp_d = base.put([0], [[3]])
    q = _engine(cfg, params, kv_cache_dtype=kvd)
    l_q = q.put([0], [prompt])
    l_q_d = q.put([0], [[3]])
    denom = np.abs(l_fp).max()
    assert np.abs(l_q - l_fp).max() / denom < bound
    assert np.abs(l_q_d - l_fp_d).max() / denom < bound


def test_kv_quant_chain_equals_per_token_loop():
    """The fast-path invariant survives quantized storage: decode_chain=K
    and decode_chain=1 are the same program semantics (greedy, int8 pool)."""
    cfg, _, params = make_model(seed=2)
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (6, 4)]
    o1 = _engine(cfg, params, kv_cache_dtype="int8", decode_chain=1).generate(
        prompts, max_new_tokens=10)
    ok = _engine(cfg, params, kv_cache_dtype="int8", decode_chain=4).generate(
        prompts, max_new_tokens=10)
    for a, b in zip(o1, ok):
        np.testing.assert_array_equal(a, b)


def test_woq_v2_bounded_and_generates():
    """v2 WOQ (int8 weights + scales through the shared block math, dequant
    at the matmul boundary): bounded logit error vs dense and a working
    greedy chain decode."""
    from deepspeed_tpu.inference.woq import WOQTensor

    cfg, _, params = make_model()
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, cfg.vocab_size, (9,))
    base = _engine(cfg, params)
    woq = _engine(cfg, params,
                  quant={"enabled": True, "bits": 8, "min_leaf_size": 0})
    assert isinstance(woq.params["layers"]["attn"]["wq"]["kernel"], WOQTensor)
    l_fp = base.put([0], [prompt])
    l_q = woq.put([0], [prompt])
    assert np.abs(l_q - l_fp).max() / np.abs(l_fp).max() < 0.08
    outs = woq.generate([prompt], max_new_tokens=8)
    assert len(outs[0]) == 8


def test_woq_tensor_classes_select():
    """Per-tensor-class WOQ: only the selected families quantize."""
    from deepspeed_tpu.inference.woq import WOQTensor, quantize_params

    cfg, _, params = make_model()
    q = quantize_params(params, "int8", min_size=0, classes=["attn"])
    assert isinstance(q["layers"]["attn"]["wq"]["kernel"], WOQTensor)
    assert not isinstance(q["layers"]["mlp"]["w_up"]["kernel"], WOQTensor)
    q2 = quantize_params(params, "int8", min_size=0, classes=["mlp"])
    assert isinstance(q2["layers"]["mlp"]["w_up"]["kernel"], WOQTensor)
    assert not isinstance(q2["layers"]["attn"]["wq"]["kernel"], WOQTensor)
    with pytest.raises(ValueError, match="unknown WOQ tensor class"):
        quantize_params(params, "int8", min_size=0, classes=["bogus"])
    # the v2 engine plumbs the selection through
    eng = _engine(cfg, params, quant={"enabled": True, "bits": 8,
                                      "min_leaf_size": 0,
                                      "tensor_classes": ["attn"]})
    assert isinstance(eng.params["layers"]["attn"]["wq"]["kernel"], WOQTensor)
    assert not isinstance(eng.params["layers"]["mlp"]["w_up"]["kernel"], WOQTensor)


def test_woq_composes_with_quantized_kv():
    """The full quantized-serving stack: int8 weights AND int8 KV pool."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, cfg.vocab_size, (5,))]
    outs = _engine(cfg, params, kv_cache_dtype="int8",
                   quant={"enabled": True, "bits": 8, "min_leaf_size": 0}
                   ).generate(prompts, max_new_tokens=6)
    assert len(outs[0]) == 6


# -------------------------------------------------------------- kernel parity
@pytest.mark.parametrize("quant", ["int8", "fp8"])
def test_fused_pallas_loads_match_xla_fallback(quant):
    """Interpret-mode parity of the fused-dequant Pallas block loads vs the
    XLA gather-then-dequant fallback on an identically quantized pool."""
    cfg, _, _ = make_model()
    pool = init_pool(cfg, 8, 4, jnp.float32, kv_quant=quant)
    S = pool.k.shape[1]
    kvH, hd = cfg.kv_heads, cfg.dims_per_head
    rng = np.random.RandomState(3)
    kv = rng.randn(S - 1, kvH, hd).astype(np.float32)
    kq, ks = _kv_block_quant(jnp.asarray(kv), quant)
    vv = rng.randn(S - 1, kvH, hd).astype(np.float32)
    vq, vs = _kv_block_quant(jnp.asarray(vv), quant)
    pk = pool.k[0].at[: S - 1].set(kq.astype(pool.k.dtype))
    psk = pool.k_scale[0].at[: S - 1].set(ks)
    pv = pool.v[0].at[: S - 1].set(vq.astype(pool.v.dtype))
    psv = pool.v_scale[0].at[: S - 1].set(vs)
    N, C, H = 2, 1, cfg.num_heads
    q = jnp.asarray(rng.randn(N, C, H, hd), jnp.float32)
    bt = jnp.asarray(rng.randint(0, 8, (N, 4)), jnp.int32)
    qpos = jnp.asarray([[5], [9]], jnp.int32)
    o_x = paged_attention(q, pk, pv, bt, qpos, 4, impl="xla",
                          k_scale=psk, v_scale=psv)
    o_p = paged_attention(q, pk, pv, bt, qpos, 4, impl="pallas",
                          k_scale=psk, v_scale=psv)
    np.testing.assert_allclose(np.asarray(o_p), np.asarray(o_x),
                               atol=2e-6, rtol=2e-6)


# ------------------------------------------------------------ program census
def _all_avals(jaxpr, acc):
    for v in list(jaxpr.invars) + list(jaxpr.constvars) + list(jaxpr.outvars):
        if hasattr(v, "aval"):
            acc.append(v.aval)
    for eqn in jaxpr.eqns:
        for v in list(eqn.invars) + list(eqn.outvars):
            if hasattr(v, "aval"):
                acc.append(v.aval)
        for val in eqn.params.values():
            vals = val if isinstance(val, (list, tuple)) else (val,)
            for x in vals:
                if hasattr(x, "jaxpr") and hasattr(x.jaxpr, "eqns"):
                    _all_avals(x.jaxpr, acc)
                elif hasattr(x, "eqns"):
                    _all_avals(x, acc)
    return acc


def test_decode_program_never_materializes_fp_pool():
    """Jaxpr census of the quantized decode-chain program (the PR-8 pattern):
    no floating-dtype tensor anywhere in the program carries the pool's
    S_flat slot dimension — dequant happens per gathered block (XLA path) or
    inside the kernel's VMEM loads (Pallas path), never on the pool."""
    cfg, _, params = make_model()
    eng = _engine(cfg, params, kv_cache_dtype="int8")
    bs = eng.config.kv_block_size
    rows, k = 4, 4

    def chain(params, pool, tokens, start_pos, tables, active, budgets, rng):
        return ragged_decode_chain(params, cfg, pool, tokens, start_pos,
                                   tables, bs, active, budgets, rng, k, None)

    jaxpr = jax.make_jaxpr(chain)(
        eng.params, eng.pool,
        jnp.zeros((rows,), jnp.int32), jnp.zeros((rows,), jnp.int32),
        jnp.zeros((rows, eng.max_pages), jnp.int32),
        jnp.ones((rows,), bool), jnp.full((rows,), k, jnp.int32),
        jax.random.PRNGKey(0))
    s_flat = eng.pool.k.shape[1]
    # the batch's gathered view must be smaller than the pool, or the census
    # couldn't tell "gathered block" from "whole pool"
    assert eng.max_pages * bs != s_flat
    avals = _all_avals(jaxpr.jaxpr, [])
    # offender = a floating [.., S_flat, .., head_dim] tensor: the dense pool
    # (the fp32 [.., S_flat, kvH, 1] SCALES are pool-sized by design — they
    # are 1/head_dim the bytes and exactly what quantized storage stores)
    offenders = [a for a in avals
                 if hasattr(a, "shape") and s_flat in tuple(a.shape)
                 and a.shape and a.shape[-1] == cfg.dims_per_head
                 and jnp.issubdtype(a.dtype, jnp.floating)]
    assert not offenders, [f"{a.dtype} {a.shape}" for a in offenders[:5]]
    # and the quantized pool IS in the program (the census has teeth)
    assert any(hasattr(a, "shape") and s_flat in tuple(a.shape)
               and a.dtype == jnp.int8 for a in avals)


# ----------------------------------------------------------- capacity & gauges
def test_byte_budget_sizing_admits_more():
    """Fixed pool bytes, head_dim=64: the int8 pool's block count (and the
    admission capacity that follows it) is >=1.8x the bf16 pool's."""
    cfg, _, params = make_model(hidden_size=128, num_heads=2, num_kv_heads=2,
                                intermediate_size=128)
    from deepspeed_tpu.utils.hbm import kv_slot_bytes

    budget = 96 * 16 * kv_slot_bytes(cfg.num_layers, cfg.kv_heads,
                                     cfg.dims_per_head, 2, None)
    bf = _engine(cfg, params, kv_block_size=16, kv_pool_bytes=budget,
                 kv_cache_dtype="bf16", max_seqs=256)
    i8 = _engine(cfg, params, kv_block_size=16, kv_pool_bytes=budget,
                 kv_cache_dtype="int8", max_seqs=256)
    assert i8.num_kv_blocks / bf.num_kv_blocks >= 1.8
    # admission control actually admits more: the real can_schedule check
    def admitted(eng):
        n = 0
        while eng.can_schedule(list(range(n + 1)), [48] * (n + 1)):
            n += 1
        return n

    assert admitted(i8) / admitted(bf) >= 1.8


def test_kv_pool_gauges_and_labels():
    """serving/kv_pool_dtype + serving/kv_bytes_per_token gauges land, and
    serving/kv_pool_utilization carries the storage-dtype label."""
    from deepspeed_tpu.telemetry import get_tracer

    cfg, _, params = make_model()
    tr = get_tracer()
    was = tr.enabled
    tr.configure(enabled=True)
    tr.reset()
    try:
        eng = _engine(cfg, params, kv_cache_dtype="int8")
        eng.generate([np.arange(5) % cfg.vocab_size], max_new_tokens=4)
        gauges = tr.registry.gauges()
        assert gauges['serving/kv_pool_dtype{dtype="int8"}'] == 1.0
        assert gauges["serving/kv_bytes_per_token"] == eng.kv_bytes_per_token
        assert 'serving/kv_pool_utilization{dtype="int8"}' in gauges
    finally:
        tr.configure(enabled=was)
        if not was:
            tr.reset()


def test_kv_cache_dtype_rejects_unknown():
    cfg, _, params = make_model()
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        _engine(cfg, params, kv_cache_dtype="int3")
