"""Serving fast path: K-step chained decode, on-device sampling, O(1) host
bookkeeping (ISSUE 4 tentpole).

Contract under test:
  - chained decode (``decode_chain=k``) is token-identical to the per-token
    loop (``k=1``) and to the dense v1 engine, greedy
  - one compiled program and one host sync per K decoded tokens (jit-cache +
    dispatch/sync counter assertions)
  - EOS mid-chain, ``max_new_tokens`` mid-chain, and preemption at chain
    boundaries all preserve outputs
  - the allocator free list never double-allocates; staged assembly buffers
    are reused, not reallocated
"""

import numpy as np
import pytest

import jax

from deepspeed_tpu.inference import InferenceEngineV2, init_inference
from deepspeed_tpu.inference.ragged import BatchStaging, BlockedAllocator, StateManager, build_ragged_batch

from .test_inference_v2 import make_model


def _v1_greedy(cfg, params, prompt, n_new, eos=None):
    v1 = init_inference(model=cfg, params=params,
                        config={"dtype": "fp32", "seq_bucket": 8})
    out = v1.generate(prompt[None, :], max_new_tokens=n_new,
                      eos_token_id=eos)[0, len(prompt):]
    if eos is not None:
        hits = np.nonzero(out == eos)[0]
        if hits.size:
            out = out[: hits[0] + 1]
    return out


def _engine(cfg, params, k, **over):
    base = {"dtype": "fp32", "kv_block_size": 4, "num_kv_blocks": 64,
            "chunk_bucket": 8, "decode_chain": k}
    base.update(over)
    return InferenceEngineV2(cfg, params, base)


# -------------------------------------------------------------- chain parity
def test_chained_decode_greedy_parity():
    """k=4 chained decode is token-identical to k=1 and to the v1 engine."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (7, 3, 5)]

    outs_k4 = _engine(cfg, params, 4).generate(prompts, max_new_tokens=6)
    outs_k1 = _engine(cfg, params, 1).generate(prompts, max_new_tokens=6)
    for p, o4, o1 in zip(prompts, outs_k4, outs_k1):
        np.testing.assert_array_equal(o4, o1)
        np.testing.assert_array_equal(o4, _v1_greedy(cfg, params, p, 6))


def test_chain_max_new_tokens_boundary():
    """max_new_tokens not a multiple of k: the chain shrinks to the budget
    and rows stop exactly at the cap."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, cfg.vocab_size, (4,)) for _ in range(2)]
    for n_new in (1, 3, 5):
        outs = _engine(cfg, params, 4).generate(prompts, max_new_tokens=n_new)
        for p, o in zip(prompts, outs):
            assert len(o) == n_new
            np.testing.assert_array_equal(o, _v1_greedy(cfg, params, p, n_new))


def test_chain_eos_mid_chain():
    """A row hitting EOS inside the chain stops there (device-side masking);
    parity with k=1 and v1."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, cfg.vocab_size, (6,))
    # pick the 3rd greedily generated token as the EOS so it lands mid-chain
    free_run = _engine(cfg, params, 4).generate([prompt], max_new_tokens=8)[0]
    eos = int(free_run[2])
    out_k4 = _engine(cfg, params, 4).generate(
        [prompt], max_new_tokens=8, eos_token_id=eos)[0]
    out_k1 = _engine(cfg, params, 1).generate(
        [prompt], max_new_tokens=8, eos_token_id=eos)[0]
    np.testing.assert_array_equal(out_k4, out_k1)
    np.testing.assert_array_equal(out_k4, _v1_greedy(cfg, params, prompt, 8, eos=eos))
    assert out_k4[-1] == eos and len(out_k4) <= 3


def test_chain_preemption_under_kv_pressure():
    """Pool sized to overflow mid-generation: preemption now happens at chain
    boundaries and outputs still match the dense v1 baseline."""
    cfg, _, params = make_model()
    eng = _engine(cfg, params, 4, num_kv_blocks=6, max_seqs=4)
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, (8,)) for _ in range(2)]
    outs = eng.generate(prompts, max_new_tokens=8)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o, _v1_greedy(cfg, params, p, 8))
    assert eng.state.free_blocks == 6  # everything released


# ------------------------------------------------- dispatch/sync accounting
def test_one_program_one_sync_per_k_tokens():
    """The acceptance contract: a K-token window is exactly 1 compiled
    program dispatched and ≤1 host sync, asserted via the jit cache and the
    engine's dispatch/host-fetch counters."""
    cfg, _, params = make_model()
    k = 4
    eng = _engine(cfg, params, k)
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, cfg.vocab_size, (5,)) for _ in range(2)]

    n_new = 9  # 1 from prefill + 8 decoded in chains of 4
    outs = eng.generate(prompts, max_new_tokens=n_new)
    assert all(len(o) == n_new for o in outs)

    # one chain program total: every K-token window reuses the same compile
    assert eng.jit_cache_size("chain") == 1
    assert eng.jit_cache_size("sample") == 1  # the fused prefill program
    assert eng.jit_cache_size("logits") == 0  # no logits ever shipped

    n_chains = eng.dispatch_count - 1  # minus the single prefill dispatch
    assert n_chains == 2  # 8 decode tokens / k=4
    assert eng.host_sync_count == eng.dispatch_count  # exactly 1 fetch per program
    assert eng.tokens_decoded == 2 * (n_new - 1)
    # ≤1 sync per K decoded tokens (per-row window; both rows share a chain)
    assert n_chains <= -(-eng.tokens_decoded // (2 * k)) + 1


def test_k1_matches_decode_chain_disabled():
    """decode_chain=1 reproduces the per-token loop exactly — one dispatch
    and one sync per decoded token, same outputs."""
    cfg, _, params = make_model()
    eng = _engine(cfg, params, 1)
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, cfg.vocab_size, (5,))]
    outs = eng.generate(prompts, max_new_tokens=4)
    assert len(outs[0]) == 4
    assert eng.jit_cache_size("chain") == 1  # k=1 chain program
    n_chains = eng.dispatch_count - 1
    assert n_chains == 3  # 3 decoded tokens after the prefill-sampled one


def test_sampled_generation_runs_on_device():
    """do_sample generation through the chained path: correct shapes, no
    logits program compiled, deterministic for a fixed seed."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(6)
    prompts = [rng.randint(0, cfg.vocab_size, (5,)) for _ in range(2)]
    eng = _engine(cfg, params, 4)
    a = eng.generate(prompts, max_new_tokens=6, do_sample=True,
                     temperature=0.8, top_k=10, seed=7)
    b = _engine(cfg, params, 4).generate(
        prompts, max_new_tokens=6, do_sample=True, temperature=0.8,
        top_k=10, seed=7)
    assert eng.jit_cache_size("logits") == 0
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
        assert len(x) == 6 and ((0 <= x) & (x < cfg.vocab_size)).all()


# ----------------------------------------------------- host-side properties
def test_allocator_never_double_allocates():
    """Free-list property fuzz: across random alloc/free interleavings the
    allocator never hands out a live block and conserves the block count."""
    rng = np.random.RandomState(0)
    a = BlockedAllocator(64)
    live = []  # allocated, not yet freed
    for _ in range(2000):
        if live and (rng.rand() < 0.45 or a.free_blocks == 0):
            i = rng.randint(len(live))
            a.free(live.pop(i))
        else:
            n = rng.randint(1, min(8, a.free_blocks) + 1)
            got = a.allocate(n)
            flat = [b for blk in live for b in blk]
            assert len(set(got.tolist())) == n
            assert not set(got.tolist()) & set(flat), "double allocation"
            live.append(got)
        assert a.free_blocks + sum(len(b) for b in live) == 64
    for blk in live:
        a.free(blk)
    assert a.free_blocks == 64
    with pytest.raises(ValueError):
        a.free([0, 0])  # duplicate ids within one call


def test_allocator_share_release_fuzz():
    """Refcount property fuzz (ISSUE 12): random allocate/free/share/release
    interleavings conserve blocks, never hand out a held block, and keep the
    bitmap consistent with the refcounts — double-release and
    free-while-shared raise without corrupting state."""
    rng = np.random.RandomState(12)
    a = BlockedAllocator(64)
    refs = {}  # block -> holder count we believe it has

    def check():
        assert a.free_blocks + len(refs) == 64
        for b, n in refs.items():
            assert a.refcount(b) == n, f"block {b}: {a.refcount(b)} != {n}"

    for _ in range(3000):
        op = rng.rand()
        held = list(refs)
        if op < 0.35 and a.free_blocks:
            got = a.allocate(rng.randint(1, min(6, a.free_blocks) + 1))
            assert not set(got.tolist()) & set(held), "allocated a held block"
            for b in got.tolist():
                refs[b] = 1
        elif op < 0.55 and held:
            b = held[rng.randint(len(held))]
            a.share([b])
            refs[b] += 1
        elif op < 0.85 and held:
            b = held[rng.randint(len(held))]
            a.release([b])
            refs[b] -= 1
            if refs[b] == 0:
                del refs[b]
        elif held:
            b = held[rng.randint(len(held))]
            if refs[b] == 1:
                a.free([b])
                del refs[b]
            else:  # free-while-shared must refuse and change nothing
                with pytest.raises(ValueError):
                    a.free([b])
        check()
    # double release of anything already free must refuse with rollback
    if a.free_blocks == 0:  # fuzz may end fully held: release one fully
        b0 = next(iter(refs))
        a.release([b0] * refs.pop(b0))
    free_block = next(b for b in range(64) if b not in refs)
    with pytest.raises(ValueError):
        a.release([free_block])
    with pytest.raises(ValueError):  # ...also mid-batch, rolling back the rest
        held = list(refs)[:2]
        a.release(held + [free_block])
    check()
    for b in list(refs):
        a.release([b] * refs.pop(b))
    assert a.free_blocks == 64


def test_staging_buffers_reused_not_reallocated():
    """Steady-state assembly reuses the per-bucket staging arrays."""
    m = StateManager(num_blocks=64, block_size=4, max_seqs=8)
    st = BatchStaging(max_pages=8)
    b1 = build_ragged_batch(m, [1], [np.arange(5)], 8, staging=st)
    tok_id = id(b1.tokens)
    for i in range(10):
        b = build_ragged_batch(m, [1], [np.asarray([i])], 8, staging=st)
        assert id(b.tokens) == tok_id, "buffer reallocated"
    assert st.allocations == 1  # prefill and decode share the (8, 8) bucket
    assert st.reuses >= 9
    # pad rows/columns stay zeroed across reuse
    assert (b.tokens[1:] == 0).all() and (b.new_lens[1:] == 0).all()


def test_zero_length_row_in_decode_batch():
    """A zero-length token list among 1-token decodes assembles as a pad row
    (the decode fast path must not index t[0] on it)."""
    m = StateManager(num_blocks=64, block_size=4, max_seqs=8)
    st = BatchStaging(max_pages=8)
    b = build_ragged_batch(m, [1, 2], [np.asarray([5], np.int32),
                                       np.asarray([], np.int32)], 8, staging=st)
    assert b.new_lens.tolist()[:2] == [1, 0]
    assert b.tokens[0, 0] == 5 and b.tokens[1, 0] == 0


def test_staging_zeroes_previous_step():
    """A wide batch followed by a narrow one in the same bucket must not leak
    the wide step's tokens/tables into the narrow step's pad area."""
    m = StateManager(num_blocks=64, block_size=4, max_seqs=8)
    st = BatchStaging(max_pages=8)
    wide = build_ragged_batch(m, [1, 2, 3], [np.arange(1, 6)] * 3, 8,
                              row_bucket=4, chunk_bucket=8, staging=st)
    assert wide.new_lens.tolist() == [5, 5, 5, 0]
    narrow = build_ragged_batch(m, [1], [np.asarray([9])], 8,
                                row_bucket=4, chunk_bucket=8, staging=st)
    assert narrow.new_lens.tolist() == [1, 0, 0, 0]
    assert (narrow.tokens[1:] == 0).all() and (narrow.tokens[0, 1:] == 0).all()
    assert (narrow.block_tables[1:] == 0).all()


def test_engine_staging_steady_state():
    """A full generate run allocates at most one staging set per (rows,chunk)
    bucket and reuses them for every subsequent step."""
    cfg, _, params = make_model()
    eng = _engine(cfg, params, 2)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, cfg.vocab_size, (6,)) for _ in range(3)]
    eng.generate(prompts, max_new_tokens=8)
    st = eng._staging
    assert st.allocations <= 2  # prefill bucket(s) only; chains use chain bufs
    total_steps = st.allocations + st.reuses
    assert st.reuses >= 0 and total_steps >= 1
    # chain staging: one buffer set per rows bucket
    assert len(eng._chain_buf) == 1
