"""Speculative decode chains (ISSUE 12): n-gram propose + greedy
verify-and-accept inside the jitted K-step chain.

Contract under test:
  - spec output is token-identical to the plain chain for ANY accept
    pattern (the verify forward compares against exactly the argmax tokens
    the plain chain would emit) — random and repetitive prompts, EOS
    mid-chain, budget tails, int8 quantized pool
  - accept-all shape: on self-repeating greedy output the proposer locks
    on and >1 token per model forward is emitted (the acceptance metric)
  - reject-all shape: acceptance can only add tokens — a spec chain never
    dispatches more programs than the plain chain at the same K (the K=1
    cost floor: one forward per token, same as the plain per-token loop)
  - one compiled program per (rows, K) — the jit-cache pin survives
  - greedy-only: do_sample + spec_decode raises
"""

import numpy as np
import pytest

from deepspeed_tpu.inference import InferenceEngineV2

from .test_inference_v2 import make_model


def _engine(cfg, params, **over):
    base = {"dtype": "fp32", "kv_block_size": 4, "num_kv_blocks": 128,
            "chunk_bucket": 8, "decode_chain": 4, "hbm_check": "off"}
    base.update(over)
    return InferenceEngineV2(cfg, params, base)


# ------------------------------------------------------------------- parity
def test_spec_matches_plain_chain_random_prompts():
    cfg, _, params = make_model()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (7, 3, 5)]
    plain = _engine(cfg, params).generate(prompts, max_new_tokens=12)
    spec = _engine(cfg, params, spec_decode=3).generate(prompts, max_new_tokens=12)
    for a, b in zip(spec, plain):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("n_new", [1, 5, 13])
def test_spec_budget_tail_parity(n_new):
    """max_new_tokens not aligned with the chain window: rows stop exactly
    at the cap, token-identical to the plain chain."""
    cfg, _, params = make_model(seed=1)
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, cfg.vocab_size, (4,)) for _ in range(2)]
    plain = _engine(cfg, params).generate(prompts, max_new_tokens=n_new)
    spec = _engine(cfg, params, spec_decode=2).generate(prompts, max_new_tokens=n_new)
    for a, b in zip(spec, plain):
        assert len(a) == n_new
        np.testing.assert_array_equal(a, b)


def test_spec_eos_mid_window_parity():
    """EOS landing inside a verify window truncates the acceptances there
    (tokens after the EOS are discarded even if accepted)."""
    cfg, _, params = make_model(seed=2)
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, cfg.vocab_size, (6,))
    free = _engine(cfg, params).generate([prompt], max_new_tokens=10)[0]
    eos = int(free[3])
    plain = _engine(cfg, params).generate(
        [prompt], max_new_tokens=10, eos_token_id=eos)[0]
    spec = _engine(cfg, params, spec_decode=3).generate(
        [prompt], max_new_tokens=10, eos_token_id=eos)[0]
    np.testing.assert_array_equal(spec, plain)
    assert spec[-1] == eos


def test_spec_with_int8_pool_parity():
    """Speculation composes with quantized KV storage: the verify forward
    reads/writes the int8 pool like any chain step."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, (5,)) for _ in range(2)]
    plain = _engine(cfg, params, kv_cache_dtype="int8").generate(
        prompts, max_new_tokens=10)
    spec = _engine(cfg, params, kv_cache_dtype="int8", spec_decode=3).generate(
        prompts, max_new_tokens=10)
    for a, b in zip(spec, plain):
        np.testing.assert_array_equal(a, b)


# -------------------------------------------------------------- cost shape
def test_spec_accepts_on_repetitive_text():
    """The acceptance benchmark shape: greedy output that self-repeats lets
    the n-gram proposer lock on — >= 1.3 accepted tokens per model forward
    (the bench corpus's acceptance bar) and fewer dispatches than plain."""
    cfg, _, params = make_model(seed=1)
    rng = np.random.RandomState(1)
    pat = rng.randint(0, cfg.vocab_size, (4,))
    prompts = [np.tile(pat, 6)[:20] for _ in range(2)]
    plain = _engine(cfg, params)
    o_plain = plain.generate(prompts, max_new_tokens=16)
    spec = _engine(cfg, params, spec_decode=3)
    o_spec = spec.generate(prompts, max_new_tokens=16)
    for a, b in zip(o_spec, o_plain):
        np.testing.assert_array_equal(a, b)
    assert spec.spec_model_steps > 0
    tokens_per_forward = spec.spec_tokens_emitted / spec.spec_model_steps
    assert tokens_per_forward >= 1.3
    assert spec.dispatch_count < plain.dispatch_count


def test_spec_never_more_dispatches_than_plain():
    """Reject-all floor: every verify forward emits >= 1 token, so a spec
    chain at K covers at least the plain chain's K tokens — the dispatch
    count can only shrink."""
    cfg, _, params = make_model(seed=4)
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, cfg.vocab_size, (6,)) for _ in range(3)]
    plain = _engine(cfg, params)
    o_plain = plain.generate(prompts, max_new_tokens=12)
    spec = _engine(cfg, params, spec_decode=3)
    o_spec = spec.generate(prompts, max_new_tokens=12)
    for a, b in zip(o_spec, o_plain):
        np.testing.assert_array_equal(a, b)
    assert spec.dispatch_count <= plain.dispatch_count
    assert spec.host_sync_count <= plain.host_sync_count
    # >= 1 token per forward even if nothing was ever accepted
    assert spec.spec_tokens_emitted >= spec.spec_model_steps


def test_spec_one_program_per_rows_k():
    """The jit-cache pin: a full generate compiles ONE spec-chain program
    (plus the fused prefill), regardless of accept pattern."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, cfg.vocab_size, (5,)) for _ in range(3)]
    eng = _engine(cfg, params, spec_decode=3)
    eng.generate(prompts, max_new_tokens=14)
    assert eng.jit_cache_size("spec") == 1
    assert eng.jit_cache_size("chain") == 0  # plain chain never compiled
    assert eng.jit_cache_size("logits") == 0
    assert eng.host_sync_count == eng.dispatch_count  # 1 fetch per program


def test_spec_metrics_and_gauges():
    from deepspeed_tpu.telemetry import get_tracer

    cfg, _, params = make_model(seed=1)
    tr = get_tracer()
    was = tr.enabled
    tr.configure(enabled=True)
    tr.reset()
    try:
        rng = np.random.RandomState(1)
        pat = rng.randint(0, cfg.vocab_size, (4,))
        eng = _engine(cfg, params, spec_decode=3)
        eng.generate([np.tile(pat, 5)], max_new_tokens=12)
        gauges = tr.registry.gauges()
        assert gauges["serving/spec_tokens_per_forward"] >= 1.0
        assert 0.0 <= gauges["serving/spec_accept_rate"] <= 1.0
        # the two describe the same accounting
        assert gauges["serving/spec_tokens_per_forward"] == pytest.approx(
            1.0 + 3 * gauges["serving/spec_accept_rate"])
    finally:
        tr.configure(enabled=was)
        if not was:
            tr.reset()


def test_ngram_proposer_masks_past_history_tail():
    """A match whose continuation runs past the valid history must fall back
    to the current token for the out-of-range slots — NOT propose the
    buffer's zero fill (which would silently kill acceptance on exactly the
    repetitive tails the proposer exists for)."""
    import jax.numpy as jnp

    from deepspeed_tpu.inference.paged import _ngram_propose

    hist = jnp.asarray([[9, 4, 9, 4, 0, 0]], jnp.int32)  # zeros = buffer fill
    drafts = np.asarray(_ngram_propose(hist, jnp.asarray([4]), 3, 2))[0]
    # pattern [9,4] matched at t=0; continuation = hist[2]=9, hist[3]=4,
    # then position 4 >= hist_len -> current token (4), not the buffer 0
    assert drafts.tolist() == [9, 4, 4]
    # no previous occurrence at all -> pure current-token fallback
    hist2 = jnp.asarray([[7, 1, 2, 3, 0, 0]], jnp.int32)
    drafts2 = np.asarray(_ngram_propose(hist2, jnp.asarray([4]), 3, 2))[0]
    assert drafts2.tolist() == [3, 3, 3]


def test_spec_rejects_sampling():
    cfg, _, params = make_model()
    eng = _engine(cfg, params, spec_decode=2)
    with pytest.raises(ValueError, match="greedy-only"):
        eng.generate([np.arange(5) % cfg.vocab_size], max_new_tokens=4,
                     do_sample=True)


def test_spec_composes_with_prefix_cache():
    """The full serving tier in one engine: warm prefix + spec chains."""
    cfg, _, params = make_model(seed=1)
    rng = np.random.RandomState(6)
    shared = rng.randint(0, cfg.vocab_size, (8,))
    p1 = np.concatenate([shared, rng.randint(0, cfg.vocab_size, (3,))])
    p2 = np.concatenate([shared, rng.randint(0, cfg.vocab_size, (4,))])
    cold = _engine(cfg, params).generate([p2], max_new_tokens=10)[0]
    eng = _engine(cfg, params, spec_decode=3, prefix_cache=True)
    eng.generate([p1], max_new_tokens=10)
    out = eng.generate([p2], max_new_tokens=10)[0]
    np.testing.assert_array_equal(out, cold)
    assert eng.prefill_tokens_cached >= 8
