"""ZeRO-Inference NVMe weight streaming (round-3 verdict item 5).

Reference: ZeRO-Inference stage-3 + AIO path
(``runtime/swap_tensor/partitioned_param_swapper.py:37``,
``inference/config.py``) — serve models larger than host RAM by streaming
layer weights from disk through the decode loop.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import CausalLM, TransformerConfig

CFG = TransformerConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_layers=4, num_heads=4, max_seq_len=128, dtype=jnp.float32)


def _params():
    module = CausalLM(CFG)
    batch = {"input_ids": jnp.zeros((1, 8), jnp.int32)}
    return module.init({"params": jax.random.PRNGKey(0)}, batch, train=False)["params"]


def _engine(**cfg_over):
    cfg = {"dtype": "float32", "seq_bucket": 16, "max_out_tokens": 64, **cfg_over}
    return deepspeed_tpu.init_inference(CFG, params=_params(), config=cfg)


def _nvme_engine(tmp_path, **extra):
    return _engine(zero_inference={"enabled": True, "offload": "nvme",
                                   "nvme_path": str(tmp_path)}, **extra)


def test_nvme_generate_matches_resident(tmp_path, devices):
    """Greedy generation through disk-streamed layers == fully resident."""
    dense = _engine()
    nvme = _nvme_engine(tmp_path)
    prompt = np.arange(1, 13, dtype=np.int32)[None, :]
    want = dense.generate(prompt, max_new_tokens=8, do_sample=False)
    got = nvme.generate(prompt, max_new_tokens=8, do_sample=False)
    np.testing.assert_array_equal(got, want)


def test_nvme_generate_matches_resident_sampled_eos(tmp_path, devices):
    """Same rng path: sampled tokens + eos early-stop behave identically."""
    dense = _engine()
    nvme = _nvme_engine(tmp_path)
    prompt = np.arange(3, 11, dtype=np.int32)[None, :].repeat(2, 0)
    kw = dict(max_new_tokens=6, do_sample=True, temperature=0.8, top_k=20,
              eos_token_id=5, pad_token_id=0, seed=7)
    np.testing.assert_array_equal(nvme.generate(prompt, **kw),
                                  dense.generate(prompt, **kw))


def test_nvme_ram_budget_is_num_buffers_layers(tmp_path, devices):
    """At most num_buffers layer trees are materialized at once — the whole
    point of the mode (weights bigger than host RAM)."""
    nvme = _nvme_engine(tmp_path)
    streamed = nvme._streamed.p
    assert streamed.num_layers == CFG.num_layers
    prompt = np.arange(1, 9, dtype=np.int32)[None, :]
    nvme.generate(prompt, max_new_tokens=4, do_sample=False)
    assert len(streamed._ready) <= streamed.num_buffers
    assert not streamed._inflight or len(streamed._inflight) <= 1


def test_nvme_composes_with_woq(tmp_path, devices):
    """int8-quantized layer weights stream from disk (4x less disk traffic);
    output matches the quant-only resident engine."""
    woq = _engine(quant={"enabled": True, "bits": 8, "min_leaf_size": 0})
    nvme = _nvme_engine(tmp_path, quant={"enabled": True, "bits": 8, "min_leaf_size": 0})
    # the streamed layer files hold the QUANTIZED bytes
    from deepspeed_tpu.inference.woq import WOQTensor

    tok = nvme._streamed.p.swapper.swap_in("layer_0", device_put=False)
    assert any(isinstance(x, WOQTensor)
               for x in jax.tree_util.tree_leaves(
                   tok, is_leaf=lambda x: isinstance(x, WOQTensor)))
    prompt = np.arange(1, 9, dtype=np.int32)[None, :]
    want = woq.generate(prompt, max_new_tokens=6, do_sample=False)
    got = nvme.generate(prompt, max_new_tokens=6, do_sample=False)
    np.testing.assert_array_equal(got, want)


def test_nvme_requires_path(devices):
    with pytest.raises(ValueError, match="nvme_path"):
        _engine(zero_inference={"enabled": True, "offload": "nvme"})


def test_nvme_forward_raises_clearly(tmp_path, devices):
    nvme = _nvme_engine(tmp_path)
    with pytest.raises(NotImplementedError, match="generate"):
        nvme.forward(np.ones((1, 8), np.int32))
