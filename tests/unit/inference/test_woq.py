"""Weight-only-quant inference + ZeRO-Inference (round-2 verdict items 4/7).

Reference: deepspeed/inference/quantization (int8/int4 WOQ),
csrc/fp_quantizer (fp8), ZeRO-Inference weight offload.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import CausalLM, TransformerConfig

CFG = TransformerConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_layers=2, num_heads=4, max_seq_len=128, dtype=jnp.float32)


def _params():
    module = CausalLM(CFG)
    batch = {"input_ids": jnp.zeros((1, 8), jnp.int32)}
    return module.init({"params": jax.random.PRNGKey(0)}, batch, train=False)["params"]


def _engine(**cfg_over):
    cfg = {"dtype": "float32", "seq_bucket": 16, "max_out_tokens": 64, **cfg_over}
    return deepspeed_tpu.init_inference(CFG, params=_params(), config=cfg)


# --------------------------------------------------------------- fp quant

def test_fp8_roundtrip():
    from deepspeed_tpu.ops.fp_quant import dequantize_fp8, quantize_fp8

    x = jax.random.normal(jax.random.PRNGKey(0), (64, 128)) * 3.0
    q, s = quantize_fp8(x, block_size=256)
    assert q.dtype == jnp.float8_e4m3fn
    back = dequantize_fp8(q, s, dtype=jnp.float32, block_size=256)
    err = np.abs(np.asarray(back - x)) / (np.abs(np.asarray(x)) + 1e-3)
    assert np.median(err) < 0.05


def test_int4_pack_roundtrip():
    from deepspeed_tpu.ops.fp_quant import dequantize_int4, quantize_int4

    x = jax.random.normal(jax.random.PRNGKey(1), (32, 64))
    q, s = quantize_int4(x, block_size=128)
    assert q.dtype == jnp.uint8 and q.shape == (32, 32)  # 2 values / byte
    back = dequantize_int4(q, s, dtype=jnp.float32, block_size=128)
    # 4-bit symmetric: worst-case half-step error = absmax/14
    assert np.abs(np.asarray(back - x)).max() < np.abs(np.asarray(x)).max() / 7


def test_int4_odd_dim_rejected():
    from deepspeed_tpu.ops.fp_quant import quantize_int4

    with pytest.raises(ValueError, match="even"):
        quantize_int4(jnp.zeros((4, 7)))


# ------------------------------------------------------------------- WOQ

@pytest.mark.parametrize("quant", [{"bits": 8}, {"bits": 4}, {"qtype": "fp"}])
def test_woq_generate_close_to_dense(quant, devices):
    dense = _engine()
    woq = _engine(quant={"enabled": True, "min_leaf_size": 0, **quant})
    prompt = np.asarray([[7, 8, 9, 10]])
    ld = np.asarray(dense.forward(prompt), np.float32)
    lq = np.asarray(woq.forward(prompt), np.float32)
    # logits drift bounded by quantization noise. min_leaf_size=0 quantizes
    # EVERY kernel of this tiny random-init model (2048-elem blocks over
    # 64-wide layers), so the bound is loose; exact-token parity of the
    # quantized path is pinned in test_zero_inference_nvme.py.
    denom = np.abs(ld).max()
    tol = 0.5 if quant.get("bits") == 4 else 0.2
    assert np.abs(lq - ld).max() / denom < tol
    out = woq.generate(prompt, max_new_tokens=4, do_sample=False)
    assert out.shape == (1, 8)


def test_woq_memory_shrinks(devices):
    from deepspeed_tpu.inference.woq import quantize_params, woq_bytes

    params = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), _params())
    dense_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(params))
    q4 = quantize_params(params, "int4", min_size=0)
    assert woq_bytes(q4) < 0.45 * dense_bytes  # ~4x on the kernels, embed dense


def test_woq_stacked_layers_survive_scan(devices):
    """Real models quantize their stacked [L, ...] layer kernels: blocks must
    not cross layer boundaries or lax.scan slicing breaks (engine generate
    runs prefill/decode scans directly over the quantized tree)."""
    from deepspeed_tpu.inference.woq import WOQTensor

    woq = _engine(quant={"enabled": True, "bits": 8, "min_leaf_size": 0})
    wq = woq.params["layers"]["attn"]["wq"]["kernel"]
    assert isinstance(wq, WOQTensor) and wq.stacked
    assert wq.q.shape[0] == CFG.num_layers  # scan-sliceable leading dim
    assert wq.scale.ndim == 2
    out = woq.generate(np.asarray([[7, 8, 9, 10]]), max_new_tokens=4, do_sample=False)
    assert out.shape == (1, 8)


def test_woq_tensor_is_pytree(devices):
    from deepspeed_tpu.inference.woq import WOQTensor, quantize_params

    q = quantize_params({"a": {"kernel": jnp.ones((64, 64))}}, "int8", min_size=0)
    leaves = jax.tree_util.tree_leaves(q)
    assert len(leaves) == 2  # values + scales
    restored = jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(q), leaves)
    assert isinstance(restored["a"]["kernel"], WOQTensor)
    np.testing.assert_allclose(
        np.asarray(restored["a"]["kernel"].astype(jnp.float32)), 1.0, rtol=1e-2)


# --------------------------------------------------------- ZeRO-Inference

def test_zero_inference_offload_generate(devices):
    from deepspeed_tpu.inference.woq import OffloadedTensor

    eng = _engine(zero_inference={"enabled": True, "min_leaf_size": 0})
    wq = eng.params["layers"]["attn"]["wq"]["kernel"]
    assert isinstance(wq, OffloadedTensor)
    # the host placement resolves through the compat fallback: pinned_host
    # where the backend has it, the device-set default kind on CPU (which
    # addresses only unpinned_host — placement degrades to the identity).
    # Expectation derived from the DEVICE's capabilities, not from the
    # object under test, so a regression in offload_params stays visible
    # on backends that do have pinned_host.
    dev = jax.devices()[0]
    kinds = {m.kind for m in dev.addressable_memories()}
    expected_kind = ("pinned_host" if "pinned_host" in kinds
                     else dev.default_memory().kind)
    assert wq.x.sharding.memory_kind == expected_kind
    # the embedding stays device-resident (gather cannot read host operands)
    emb = eng.params["embed"]["embedding"]
    assert not isinstance(emb, OffloadedTensor)
    out = eng.generate(np.asarray([[3, 4, 5]]), max_new_tokens=3, do_sample=False)
    assert out.shape == (1, 6)


def test_zero_inference_composes_with_woq(devices):
    eng = _engine(quant={"enabled": True, "bits": 8, "min_leaf_size": 0},
                  zero_inference={"enabled": True, "min_leaf_size": 0})
    out = eng.generate(np.asarray([[3, 4, 5]]), max_new_tokens=3, do_sample=False)
    assert out.shape == (1, 6)
