"""Content-hash prefix cache over the paged (quantized) KV pool (ISSUE 12).

Contract under test:
  - cache-hit admission is token-identical to cold prefill (greedy), on the
    plain bf16-storage pool AND the int8 quantized pool — the cached
    artifact is the quantized block bytes, never re-quantized
  - the insert-time blake2b over the pool bytes (values + scale pages) still
    matches at hit time: sharing, COW, and eviction never corrupt a cached
    block
  - COW divergence: a prompt sharing only part of a cached block clones it
    at the first divergent token; the source block's bytes are untouched
  - eviction under admission pressure: LRU entries release their blocks,
    live traffic proceeds, and a re-run of the evicted prompt re-prefills
    to the same output
  - allocator refcount bookkeeping: blocks return to the free stack only
    when BOTH the cache and every sequence have released them
"""

import numpy as np
import pytest

from deepspeed_tpu.inference import InferenceEngineV2
from deepspeed_tpu.inference.ragged import BlockedAllocator, PrefixCache

from .test_inference_v2 import make_model


def _engine(cfg, params, **over):
    base = {"dtype": "fp32", "kv_block_size": 4, "num_kv_blocks": 64,
            "chunk_bucket": 8, "decode_chain": 4, "hbm_check": "off",
            "prefix_cache": True}
    base.update(over)
    return InferenceEngineV2(cfg, params, base)


# ------------------------------------------------------------ unit: the cache
def test_prefix_cache_match_insert_roundtrip():
    a = BlockedAllocator(16)
    pc = PrefixCache(a, block_size=4)
    toks = np.arange(11, dtype=np.int32)  # 2 full blocks + 3 tail tokens
    blocks = a.allocate(3)
    pc.insert(toks, blocks)
    assert len(pc) == 2  # only FULL blocks are indexed
    assert a.refcount(int(blocks[0])) == 2  # owner + cache

    hit = pc.match(toks)
    assert hit.blocks == [int(blocks[0]), int(blocks[1])]
    # a diverging prompt matches only the shared leading blocks
    other = toks.copy()
    other[5] = 99
    hit2 = pc.match(other)
    assert hit2.blocks == [int(blocks[0])]
    # ...and the partially-matching second block is offered for COW with
    # the divergence point (token 5 = index 1 into the block)
    assert hit2.cow_block == int(blocks[1]) and hit2.cow_len == 1
    # reuse never covers the full prompt: >= 1 token must remain to prefill
    exact = np.arange(8, dtype=np.int32)
    hit3 = pc.match(exact)
    assert hit3.n_blocks == 1  # block 2 would cover tokens [4, 8) == len-0


def test_prefix_cache_lru_eviction_and_refcounts():
    a = BlockedAllocator(16)
    pc = PrefixCache(a, block_size=4, capacity_blocks=2)
    t1 = np.arange(8, dtype=np.int32)
    b1 = a.allocate(2)
    pc.insert(t1, b1)
    assert len(pc) == 2
    t2 = np.arange(100, 108, dtype=np.int32)
    b2 = a.allocate(2)
    pc.insert(t2, b2)  # capacity 2 -> the two t1 entries evict (LRU)
    assert len(pc) == 2 and pc.evictions == 2
    assert a.refcount(int(b1[0])) == 1  # cache reference gone, owner remains
    assert pc.match(t1).n_blocks == 0 and pc.match(t2).n_blocks >= 1
    # releasing the owners returns everything cache-free to the stack
    a.release(b1)
    pc.clear()
    a.release(b2)
    assert a.free_blocks == 16


# ----------------------------------------------------- allocator share/release
def test_allocator_share_release_validation_and_rollback():
    a = BlockedAllocator(8)
    got = a.allocate(3)
    a.share(got)  # refcount 2 everywhere
    with pytest.raises(ValueError, match="shared"):
        a.free(got)  # free-while-shared refuses
    assert a.free_blocks == 5  # rollback left the stack untouched
    a.release(got)
    a.free(got)  # back to single-owner: strict free works
    assert a.free_blocks == 8
    with pytest.raises(ValueError, match="double release"):
        a.release([int(got[0])])
    with pytest.raises(ValueError, match="unallocated"):
        a.share([int(got[0])])
    # batch rollback: one bad id in a share/release leaves ALL counts intact
    live = a.allocate(2)
    with pytest.raises(ValueError):
        a.share([int(live[0]), 999])
    assert a.refcount(int(live[0])) == 1
    a.share(live)
    with pytest.raises(ValueError):
        a.release([int(live[0]), int(live[1]), int(live[0]), int(live[0]), 7])
    assert a.refcount(int(live[0])) == 2 and a.refcount(int(live[1])) == 2


# ------------------------------------------------------- engine: hit parity
@pytest.mark.parametrize("kvd", ["bf16", "int8"])
def test_cache_hit_greedy_identical_to_cold_prefill(kvd):
    """The acceptance contract: a warm-cache admission produces exactly the
    cold-prefill greedy tokens, for the plain and the quantized pool."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(0)
    shared = rng.randint(0, cfg.vocab_size, (12,))  # 3 full blocks at bs=4
    prompts = [np.concatenate([shared, rng.randint(0, cfg.vocab_size, (n,))])
               for n in (3, 5, 2)]

    cold = _engine(cfg, params, prefix_cache=False, kv_cache_dtype=kvd
                   ).generate(prompts, max_new_tokens=8)
    eng = _engine(cfg, params, kv_cache_dtype=kvd)
    warm0 = eng.generate([prompts[0]], max_new_tokens=8)  # populates the cache
    np.testing.assert_array_equal(warm0[0], cold[0])
    assert eng.prefill_tokens_cached == 0 and len(eng.prefix_cache) >= 3
    hits = eng.generate(prompts[1:], max_new_tokens=8)  # shared prefix cached
    for got, ref in zip(hits, cold[1:]):
        np.testing.assert_array_equal(got, ref)
    assert eng.prefill_tokens_cached >= 2 * len(shared)
    assert eng.prefix_cache.hit_rate > 0


def test_content_hash_stable_at_hit_time():
    """The quantized-bytes digest taken at insert still matches the pool at
    hit time — sharing never mutated the cached block."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(1)
    shared = rng.randint(0, cfg.vocab_size, (8,))
    p1 = np.concatenate([shared, rng.randint(0, cfg.vocab_size, (4,))])
    p2 = np.concatenate([shared, rng.randint(0, cfg.vocab_size, (6,))])
    eng = _engine(cfg, params, kv_cache_dtype="int8")
    eng.generate([p1], max_new_tokens=6)
    entries = list(eng.prefix_cache._entries.values())
    assert entries and all(e.content_hash for e in entries)
    before = {e.block: e.content_hash for e in entries}
    eng.generate([p2], max_new_tokens=6)  # hits the shared blocks
    assert eng.prefill_tokens_cached >= len(shared) // 2
    for blk, h in before.items():
        assert eng._block_content_hash(blk) == h, "cached block bytes changed"


def test_cow_divergence_mid_block():
    """Prompts sharing a strict prefix INSIDE a block: the second admission
    clones the partially-shared block (copy-on-write at the first divergent
    token), output matches cold prefill, and the source block's bytes are
    untouched."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(2)
    p1 = rng.randint(0, cfg.vocab_size, (8,))  # 2 full blocks at bs=4
    p2 = p1.copy()
    p2[6] = (p2[6] + 1) % cfg.vocab_size  # diverge inside block 1 (slot 2)
    cold = _engine(cfg, params, prefix_cache=False).generate(
        [p2], max_new_tokens=8)[0]
    eng = _engine(cfg, params)
    eng.generate([p1], max_new_tokens=8)
    assert len(eng.prefix_cache) >= 2
    src_entry = [e for e in eng.prefix_cache._entries.values()][1]
    src_hash = eng._block_content_hash(src_entry.block)
    out = eng.generate([p2], max_new_tokens=8)[0]
    np.testing.assert_array_equal(out, cold)
    assert eng.cow_copies == 1
    # block 0 fully reused + 2 tokens of block 1 via COW
    assert eng.prefix_cache.hit_tokens >= 4 + 2
    assert eng._block_content_hash(src_entry.block) == src_hash


def test_eviction_under_pressure_reprefills():
    """Pool small enough that cached prefixes must be reclaimed for live
    traffic: admission evicts LRU entries instead of failing, and a re-run
    of the evicted prompt (now a cold prefill again) still matches."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, (8,)) for _ in range(3)]
    refs = _engine(cfg, params, prefix_cache=False, num_kv_blocks=5,
                   max_seqs=1).generate(prompts, max_new_tokens=8)
    # 5 blocks x 4 slots: one request peaks at 4 blocks, each finished
    # prompt leaves 2 cached — the next request's decode window must evict
    eng = _engine(cfg, params, num_kv_blocks=5, max_seqs=1,
                  prefix_cache_fraction=1.0)
    for p, ref in zip(prompts, refs):
        np.testing.assert_array_equal(
            eng.generate([p], max_new_tokens=8)[0], ref)
    assert eng.prefix_cache.evictions > 0
    # the first prompt's prefix was evicted -> cold again, same output
    np.testing.assert_array_equal(
        eng.generate([prompts[0]], max_new_tokens=8)[0], refs[0])


def test_hit_pinned_across_admission_eviction():
    """Admission pressure deep enough that LRU eviction reaches the very
    entries the incoming request just matched: the pin taken between
    ``prefix_probe`` and ``_attach_prefix`` keeps those blocks allocated
    (the cache entries may go, the bytes stay), so the admission completes
    with the correct output instead of raising mid-serving."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(7)
    a = rng.randint(0, cfg.vocab_size, (8,))
    a2 = rng.randint(0, cfg.vocab_size, (8,))
    b = np.concatenate([a, rng.randint(0, cfg.vocab_size, (20,))])  # shares a
    ref = _engine(cfg, params, prefix_cache=False, num_kv_blocks=8,
                  max_seqs=1).generate([b], max_new_tokens=4)[0]
    # pool 8 blocks: after serving a and a2, the cache holds 4 entries and
    # only 4 blocks are free; admitting b (28 prompt tokens, 2 blocks
    # matched from a's prefix) needs 5 fresh blocks -> eviction reaches a's
    # entries — exactly the matched hit
    eng = _engine(cfg, params, num_kv_blocks=8, max_seqs=1,
                  prefix_cache_fraction=1.0)
    eng.generate([a], max_new_tokens=4)
    eng.generate([a2], max_new_tokens=4)
    assert len(eng.prefix_cache) == 4 and eng.state.free_blocks == 4
    out = eng.generate([b], max_new_tokens=4)[0]
    np.testing.assert_array_equal(out, ref)
    assert eng.prefix_cache.evictions > 0  # pressure really evicted
    assert eng.prefill_tokens_cached >= 8  # ...and the hit still served
    # hit-rate stats count ADMISSIONS, not probe retries: three requests
    # were admitted, whatever pressure-induced re-probing happened
    assert eng.prefix_cache.lookups == 3
    # nothing leaked: free == pool - cache-held
    assert eng.state.free_blocks == eng.num_kv_blocks - len(eng.prefix_cache)


def test_pool_accounting_consistent_after_serving():
    """After all sequences flush, allocated blocks == cache-held blocks and
    every refcount is exactly 1 (the cache's own reference)."""
    cfg, _, params = make_model()
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (9, 6, 11)]
    eng = _engine(cfg, params)
    eng.generate(prompts, max_new_tokens=6)
    alloc = eng.state.allocator
    held = len(eng.prefix_cache)
    assert alloc.free_blocks == eng.num_kv_blocks - held
    for e in eng.prefix_cache._entries.values():
        assert alloc.refcount(e.block) == 1
    eng.prefix_cache.clear()
    assert alloc.free_blocks == eng.num_kv_blocks


def test_prefix_gauges_land():
    from deepspeed_tpu.telemetry import get_tracer

    cfg, _, params = make_model()
    tr = get_tracer()
    was = tr.enabled
    tr.configure(enabled=True)
    tr.reset()
    try:
        rng = np.random.RandomState(5)
        shared = rng.randint(0, cfg.vocab_size, (8,))
        eng = _engine(cfg, params)
        eng.generate([np.concatenate([shared, [3]])], max_new_tokens=6)
        eng.generate([np.concatenate([shared, [5]])], max_new_tokens=6)
        gauges = tr.registry.gauges()
        assert gauges["serving/prefix_hit_rate"] > 0
        assert gauges["serving/prefix_cached_blocks"] >= 2
    finally:
        tr.configure(enabled=was)
        if not was:
            tr.reset()
