"""Inference v1 correctness: KV-cache decode == full-forward decode.

Mirrors the reference's inference test strategy (tests/unit/inference/
test_inference.py compares injected-kernel outputs against the HF baseline):
here the baseline is the training-model forward (CausalLM.apply) and the
candidate is the cached prefill/decode path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference import InferenceConfig, init_inference
from deepspeed_tpu.inference.model import decode_step, init_cache, prefill
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig


def make_model(seed=0, **overrides):
    base = dict(
        vocab_size=97, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=128,
    )
    base.update(overrides)
    cfg = TransformerConfig(**base)
    module = CausalLM(cfg)
    rng = jax.random.PRNGKey(seed)
    example = {"input_ids": jnp.zeros((1, 8), jnp.int32)}
    params = module.init({"params": rng, "dropout": rng}, example, train=False)["params"]
    return cfg, module, params


def full_forward_greedy(module, params, ids, steps):
    """Baseline: iterative full forward + argmax (no cache)."""
    out = ids
    for _ in range(steps):
        _, logits = module.apply({"params": params}, {"input_ids": out}, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(out.dtype)
        out = jnp.concatenate([out, nxt[:, None]], axis=1)
    return out


@pytest.mark.parametrize("overrides", [
    {},  # llama-style: rmsnorm + rope + GQA + swiglu
    {"norm": "layernorm", "activation": "gelu", "position": "learned",
     "num_kv_heads": None, "tie_embeddings": True},  # gpt2-style
    {"qkv_bias": True},  # qwen2-style: rmsnorm + rope + qkv biases
    {"norm": "layernorm", "activation": "relu", "position": "learned",
     "num_kv_heads": None, "tie_embeddings": True},  # opt-style
    {"norm": "layernorm", "activation": "gelu_exact", "num_kv_heads": 1,
     "qkv_bias": False, "dense_bias": False, "parallel_block": True,
     "tie_embeddings": True},  # falcon-style: parallel block + MQA
])
def test_cached_decode_matches_full_forward(overrides):
    cfg, module, params = make_model(**overrides)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0, cfg.vocab_size)
    steps = 3  # prefill + 2 cached decodes: enough to catch any cache drift
    ref = full_forward_greedy(module, params, ids, steps)

    cache = init_cache(cfg, 2, 64, jnp.float32)
    logits, cache = prefill(params, cfg, cache, ids)
    toks = [jnp.argmax(logits, axis=-1)]
    for _ in range(steps - 1):
        logits, cache = decode_step(params, cfg, cache, toks[-1])
        toks.append(jnp.argmax(logits, axis=-1))
    got = jnp.concatenate([ids] + [t[:, None] for t in toks], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_ragged_prompts_right_padded():
    """Rows with different prompt lengths in one batch decode correctly."""
    cfg, module, params = make_model()
    full = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0, cfg.vocab_size)
    # row 1 has a 4-token prompt (2 pad slots on the right)
    mask = np.ones((2, 6), bool)
    mask[1, 4:] = False

    cache = init_cache(cfg, 2, 64, jnp.float32)
    logits, cache = prefill(params, cfg, cache, full, jnp.asarray(mask))

    # baseline per row: forward on the unpadded prompt
    for row, L in ((0, 6), (1, 4)):
        _, ref_logits = module.apply(
            {"params": params}, {"input_ids": full[row:row + 1, :L]}, train=False
        )
        np.testing.assert_allclose(
            np.asarray(logits[row]), np.asarray(ref_logits[0, -1]), rtol=2e-4, atol=2e-4
        )


def test_moe_inference_forward():
    """MoE inference: prefill takes the ragged grouped-GEMM dispatch
    (T=10 >= 2E=8), decode the dense-combine path — both finite."""
    cfg, module, params = make_model(num_experts=4, moe_top_k=2)
    ids = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, cfg.vocab_size)
    cache = init_cache(cfg, 2, 32, jnp.float32)
    logits, cache = prefill(params, cfg, cache, ids)
    logits2, _ = decode_step(params, cfg, cache, jnp.argmax(logits, -1))
    assert np.isfinite(np.asarray(logits)).all() and np.isfinite(np.asarray(logits2)).all()


def _moe_layer_params(cfg, seed=0):
    rng = np.random.default_rng(seed)
    r = lambda *s: jnp.asarray(rng.standard_normal(s) * 0.1, jnp.float32)  # noqa: E731
    M, H, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    return {"gate": {"wg": {"kernel": r(M, E)}},
            "experts": {"w_up": r(E, M, H), "w_gate": r(E, M, H),
                        "w_down": r(E, H, M)}}


def test_moe_ragged_prefill_matches_dense_combine():
    """The two dispatch regimes are the same math: running each token alone
    (T=1 < 2E => dense-combine) must equal the batched ragged dispatch
    (reference moe_gather/moe_scatter + grouped GEMM semantics)."""
    from deepspeed_tpu.inference.model import _moe

    cfg = TransformerConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                            num_layers=1, num_heads=2, max_seq_len=64,
                            num_experts=4, moe_top_k=2)
    lp = _moe_layer_params(cfg)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 16, 16)) * 0.3,
                    jnp.float32)
    ragged = _moe(lp, cfg, x)  # T=32 >= 2E=8 -> ragged
    per_token = jnp.stack([
        jnp.stack([_moe(lp, cfg, x[b:b + 1, s:s + 1])[0, 0]  # T=1 -> dense
                   for s in range(x.shape[1])])
        for b in range(x.shape[0])])
    np.testing.assert_allclose(np.asarray(ragged), np.asarray(per_token),
                               rtol=2e-5, atol=2e-6)


def test_moe_ragged_prefill_work_scales_with_top_k():
    """Prefill FFN work must scale with top_k, not E (VERDICT r4 missing #3;
    reference FastGen grouped GEMM). Structural witness that holds on every
    backend: the dense-combine program materializes per-expert [T, E, H]
    activations, the ragged dispatch's widest activation is [T*k, H] — the
    grouped matmuls (megablox on TPU) only touch the routed rows. (XLA-CPU's
    ragged_dot fallback lowers densely, so FLOP counts are asserted
    structurally, not via cost_analysis.)"""
    import re

    from deepspeed_tpu.inference.model import _moe_ragged

    E, k, M, H, T = 8, 2, 64, 128, 256
    cfg = TransformerConfig(vocab_size=64, hidden_size=M, intermediate_size=H,
                            num_layers=1, num_heads=2, max_seq_len=64,
                            num_experts=E, moe_top_k=k)
    lp = _moe_layer_params(cfg)
    ep = lp["experts"]
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.standard_normal((T, M)), jnp.float32)
    top_p = jnp.asarray(rng.uniform(size=(T, k)), jnp.float32)
    top_i = jnp.asarray(rng.integers(0, E, (T, k)), jnp.int32)

    def dense_all_experts(tokens, top_p, top_i):
        gate = jnp.zeros((T, E), jnp.float32).at[
            jnp.arange(T)[:, None], top_i].set(top_p)
        h1 = jax.nn.silu(jnp.einsum("tm,emh->teh", tokens, ep["w_gate"])) * \
            jnp.einsum("tm,emh->teh", tokens, ep["w_up"])
        out_e = jnp.einsum("teh,ehm->tem", h1, ep["w_down"])
        return jnp.einsum("te,tem->tm", gate, out_e)

    def buffer_shapes(fn, *args):
        txt = jax.jit(fn).lower(*args).compile().as_text()
        return {tuple(map(int, m.group(1).split(",")))
                for m in re.finditer(r"f32\[([\d,]+)\]", txt)}

    per_expert = (T, E, H)  # the E-wide activation the ragged path avoids
    dense_shapes = buffer_shapes(dense_all_experts, tokens, top_p, top_i)
    ragged_shapes = buffer_shapes(
        lambda t, p, i: _moe_ragged(cfg, ep, t, p, i), tokens, top_p, top_i)
    assert per_expert in dense_shapes, "positive control broken"
    assert per_expert not in ragged_shapes
    assert (T * k, H) in ragged_shapes  # the routed-rows activation


def test_init_inference_generate_tp():
    """init_inference over a tp=2 mesh: generate matches the no-cache greedy
    baseline (TP sharding must not change results)."""
    cfg, module, params = make_model()
    engine = init_inference(
        model=cfg, params=params,
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 2}, "seq_bucket": 8},
    )
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(4), (2, 7), 0, cfg.vocab_size))
    out = engine.generate(ids, max_new_tokens=4)
    assert out.shape == (2, 11)
    ref = np.asarray(full_forward_greedy(module, params, jnp.asarray(ids), 4))
    np.testing.assert_array_equal(out, ref)


def test_generate_eos_stops():
    cfg, module, params = make_model()
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(5), (1, 4), 0, cfg.vocab_size))
    engine = init_inference(model=cfg, params=params, config={"dtype": "fp32", "seq_bucket": 8})
    # pick whatever greedy emits first as the "eos" so it must stop right away
    first = engine.generate(ids, max_new_tokens=1)[0, -1]
    out = engine.generate(ids, max_new_tokens=5, eos_token_id=int(first), pad_token_id=0)
    assert (out[0, 5:] == 0).all()


def test_sampling_shapes_and_determinism():
    cfg, module, params = make_model()
    ids = np.zeros((2, 4), np.int32)
    engine = init_inference(model=cfg, params=params, config={"dtype": "fp32", "seq_bucket": 8})
    a = engine.generate(ids, max_new_tokens=3, do_sample=True, temperature=0.8, top_k=10, seed=7)
    b = engine.generate(ids, max_new_tokens=3, do_sample=True, temperature=0.8, top_k=10, seed=7)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 7)


def test_gmm_padded_handles_nonmultiple_rows():
    """megablox gmm requires rows % tile == 0; the wrapper pads rows into the
    last group and slices them off (review r5: non-128-multiple prefills
    crashed at trace time on TPU). Interpret mode exercises the real kernel
    path on CPU."""
    from deepspeed_tpu.inference.model import _gmm_padded

    rng = np.random.default_rng(4)
    m, K, N, G = 20, 128, 128, 3
    lhs = jnp.asarray(rng.standard_normal((m, K)), jnp.float32)
    rhs = jnp.asarray(rng.standard_normal((G, K, N)) * 0.1, jnp.float32)
    gs = jnp.asarray([7, 9, 4], jnp.int32)
    got = _gmm_padded(lhs, rhs, gs, interpret=True)
    want = jax.lax.ragged_dot(lhs, rhs, gs)
    assert got.shape == (m, N)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-4)
