"""Inference v1 correctness: KV-cache decode == full-forward decode.

Mirrors the reference's inference test strategy (tests/unit/inference/
test_inference.py compares injected-kernel outputs against the HF baseline):
here the baseline is the training-model forward (CausalLM.apply) and the
candidate is the cached prefill/decode path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference import InferenceConfig, init_inference
from deepspeed_tpu.inference.model import decode_step, init_cache, prefill
from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig


def make_model(seed=0, **overrides):
    base = dict(
        vocab_size=97, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=128,
    )
    base.update(overrides)
    cfg = TransformerConfig(**base)
    module = CausalLM(cfg)
    rng = jax.random.PRNGKey(seed)
    example = {"input_ids": jnp.zeros((1, 8), jnp.int32)}
    params = module.init({"params": rng, "dropout": rng}, example, train=False)["params"]
    return cfg, module, params


def full_forward_greedy(module, params, ids, steps):
    """Baseline: iterative full forward + argmax (no cache)."""
    out = ids
    for _ in range(steps):
        _, logits = module.apply({"params": params}, {"input_ids": out}, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(out.dtype)
        out = jnp.concatenate([out, nxt[:, None]], axis=1)
    return out


@pytest.mark.parametrize("overrides", [
    {},  # llama-style: rmsnorm + rope + GQA + swiglu
    {"norm": "layernorm", "activation": "gelu", "position": "learned",
     "num_kv_heads": None, "tie_embeddings": True},  # gpt2-style
    {"qkv_bias": True},  # qwen2-style: rmsnorm + rope + qkv biases
    {"norm": "layernorm", "activation": "relu", "position": "learned",
     "num_kv_heads": None, "tie_embeddings": True},  # opt-style
    {"norm": "layernorm", "activation": "gelu_exact", "num_kv_heads": 1,
     "qkv_bias": False, "dense_bias": False, "parallel_block": True,
     "tie_embeddings": True},  # falcon-style: parallel block + MQA
])
def test_cached_decode_matches_full_forward(overrides):
    cfg, module, params = make_model(**overrides)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0, cfg.vocab_size)
    steps = 5
    ref = full_forward_greedy(module, params, ids, steps)

    cache = init_cache(cfg, 2, 64, jnp.float32)
    logits, cache = prefill(params, cfg, cache, ids)
    toks = [jnp.argmax(logits, axis=-1)]
    for _ in range(steps - 1):
        logits, cache = decode_step(params, cfg, cache, toks[-1])
        toks.append(jnp.argmax(logits, axis=-1))
    got = jnp.concatenate([ids] + [t[:, None] for t in toks], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_ragged_prompts_right_padded():
    """Rows with different prompt lengths in one batch decode correctly."""
    cfg, module, params = make_model()
    full = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0, cfg.vocab_size)
    # row 1 has a 4-token prompt (2 pad slots on the right)
    mask = np.ones((2, 6), bool)
    mask[1, 4:] = False

    cache = init_cache(cfg, 2, 64, jnp.float32)
    logits, cache = prefill(params, cfg, cache, full, jnp.asarray(mask))

    # baseline per row: forward on the unpadded prompt
    for row, L in ((0, 6), (1, 4)):
        _, ref_logits = module.apply(
            {"params": params}, {"input_ids": full[row:row + 1, :L]}, train=False
        )
        np.testing.assert_allclose(
            np.asarray(logits[row]), np.asarray(ref_logits[0, -1]), rtol=2e-4, atol=2e-4
        )


def test_moe_inference_forward():
    """MoE decode path (exact top-k, no drops) runs and is finite."""
    cfg, module, params = make_model(num_experts=4, moe_top_k=2)
    ids = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, cfg.vocab_size)
    cache = init_cache(cfg, 2, 32, jnp.float32)
    logits, cache = prefill(params, cfg, cache, ids)
    logits2, _ = decode_step(params, cfg, cache, jnp.argmax(logits, -1))
    assert np.isfinite(np.asarray(logits)).all() and np.isfinite(np.asarray(logits2)).all()


def test_init_inference_generate_tp():
    """init_inference over a tp=2 mesh: generate matches the no-cache greedy
    baseline (TP sharding must not change results)."""
    cfg, module, params = make_model()
    engine = init_inference(
        model=cfg, params=params,
        config={"dtype": "fp32", "tensor_parallel": {"tp_size": 2}, "seq_bucket": 8},
    )
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(4), (2, 7), 0, cfg.vocab_size))
    out = engine.generate(ids, max_new_tokens=4)
    assert out.shape == (2, 11)
    ref = np.asarray(full_forward_greedy(module, params, jnp.asarray(ids), 4))
    np.testing.assert_array_equal(out, ref)


def test_generate_eos_stops():
    cfg, module, params = make_model()
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(5), (1, 4), 0, cfg.vocab_size))
    engine = init_inference(model=cfg, params=params, config={"dtype": "fp32", "seq_bucket": 8})
    # pick whatever greedy emits first as the "eos" so it must stop right away
    first = engine.generate(ids, max_new_tokens=1)[0, -1]
    out = engine.generate(ids, max_new_tokens=5, eos_token_id=int(first), pad_token_id=0)
    assert (out[0, 5:] == 0).all()


def test_sampling_shapes_and_determinism():
    cfg, module, params = make_model()
    ids = np.zeros((2, 4), np.int32)
    engine = init_inference(model=cfg, params=params, config={"dtype": "fp32", "seq_bucket": 8})
    a = engine.generate(ids, max_new_tokens=3, do_sample=True, temperature=0.8, top_k=10, seed=7)
    b = engine.generate(ids, max_new_tokens=3, do_sample=True, temperature=0.8, top_k=10, seed=7)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 7)
