"""Telemetry subsystem tests (tracer spans, comm accounting, exporters,
MonitorMaster integration, disabled no-op contract).

Runs in the default tier (tier-1's ``-m 'not slow'`` sweep collects it): the
telemetry substrate is what every future perf PR measures with, so its
contract stays under the cheap sweep.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.utils.compat import shard_map

import deepspeed_tpu
import deepspeed_tpu.comm as dist
from deepspeed_tpu import telemetry
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
from deepspeed_tpu.telemetry import NOOP_SPAN, get_tracer
from deepspeed_tpu.telemetry.tracer import Tracer


@pytest.fixture(autouse=True)
def _clean_global_tracer():
    """The tracer is process-global (like comms_logger): leave it disabled
    and empty for the rest of the suite."""
    tr = get_tracer()
    tr.configure(enabled=False)
    tr.trace_path = None
    tr.jsonl_path = None
    tr.prometheus_path = None
    tr.reset()
    yield
    tr.configure(enabled=False)
    tr.trace_path = None
    tr.jsonl_path = None
    tr.prometheus_path = None
    tr.reset()


def _tiny_engine(config_extra=None):
    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=2, max_seq_len=32,
    )
    eng, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=16),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 10_000,
            **(config_extra or {}),
        },
    )
    return eng


def _batch(eng, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 64, (eng.train_batch_size, 16), dtype=np.int32)}


# --------------------------------------------------------------- tracer core
def test_span_nesting_and_timing():
    tr = Tracer(enabled=True)
    with tr.span("outer", step=3):
        time.sleep(0.01)
        with tr.span("inner"):
            time.sleep(0.005)
    evs = tr.events()
    assert [e["name"] for e in evs] == ["inner", "outer"]  # inner closes first
    inner, outer = evs
    assert inner["kind"] == outer["kind"] == "span"
    assert outer["dur"] >= 0.01 and inner["dur"] >= 0.005
    # same-thread nesting is timestamp containment (how Perfetto nests them)
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert inner["tid"] == outer["tid"] == threading.get_ident()
    assert outer["args"] == {"step": 3}
    # every span also feeds the span/<name> histogram (registry = same truth)
    assert tr.phase_summary()["outer"]["count"] == 1


def test_disabled_tracer_is_noop():
    tr = Tracer(enabled=False)
    s = tr.span("anything", big_arg="ignored")
    assert s is NOOP_SPAN  # shared singleton: no allocation on the hot path
    with s:
        pass
    tr.count("comm/bytes", 1024)
    tr.instant("marker")
    assert tr.events() == []
    assert tr.registry.counters() == {}
    assert tr.step_scalars() == {}


def test_bounded_event_buffer():
    tr = Tracer(enabled=True, max_events=5)
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    assert len(tr.events()) == 5
    assert tr.dropped_events == 5


# ----------------------------------------------------------- comm accounting
def test_comm_bytes_accounting_known_payload():
    """Facade collectives record exact (bytes, world, dtype) at trace time:
    a [2, 64] fp32 local shard over a 4-way axis is 512 bytes, world 4."""
    tr = get_tracer()
    tr.configure(enabled=True)
    dist.comms_logger.configure(enabled=True)
    dist.comms_logger.reset()

    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("dp",))
    x = jnp.ones((8, 64), jnp.float32)  # local shard per rank: [2, 64]

    f = shard_map(lambda v: dist.all_reduce(v, "dp"),
                  mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    np.asarray(jax.jit(f)(x))

    counters = tr.registry.counters()
    assert counters["comm/bytes"] == 2 * 64 * 4  # one trace-time record
    assert counters["comm/bytes/all_reduce_sum"] == 512
    assert counters["comm/count"] == 1
    ev = next(e for e in tr.events() if e.get("cat") == "comm")
    assert ev["name"] == "comm:all_reduce_sum"
    assert ev["args"]["bytes"] == 512
    assert ev["args"]["world"] == 4
    assert ev["args"]["dtype"] == "float32"
    assert ev["args"]["axis"] == "dp"
    # the pre-existing comms logger keeps seeing the same traffic
    rows = dist.comms_logger.summary()
    assert any(r["op"] == "all_reduce_sum" and r["total_bytes"] == 512 for r in rows)
    dist.comms_logger.configure(enabled=False)


# ----------------------------------------------------------------- exporters
def test_chrome_trace_schema_valid(tmp_path):
    tr = get_tracer()
    tr.configure(enabled=True)
    with tr.span("phase_a", cat="span", step=1):
        with tr.span("comm:all_reduce_sum", cat="comm", bytes=2048, world=4,
                     dtype="float32", op="all_reduce_sum"):
            pass
    tr.instant("overflow", reason="test")
    tr.sample_counter("mem/device_bytes_in_use", 12345.0)

    path = telemetry.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and len(evs) >= 4
    for e in evs:
        assert "ph" in e and "name" in e
        if e["ph"] == "X":
            assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
            assert e["dur"] >= 0 and "pid" in e and "tid" in e
    comm = next(e for e in evs if e.get("cat") == "comm")
    assert comm["ph"] == "X" and comm["args"]["bytes"] == 2048
    counter = next(e for e in evs if e["ph"] == "C")
    assert counter["args"]["value"] == 12345.0
    assert doc["otherData"]["dropped_events"] == 0


def test_jsonl_export_one_event_per_line(tmp_path):
    tr = get_tracer()
    tr.configure(enabled=True)
    with tr.span("a"):
        pass
    tr.instant("b", k=1)
    path = telemetry.export_jsonl(str(tmp_path / "events.jsonl"))
    lines = [json.loads(l) for l in open(path) if l.strip()]
    # stream opens with the fleet meta line (identity + origin anchor)
    assert lines[0]["kind"] == "process_meta"
    assert "run_id" in lines[0]["identity"] and "origin_unix" in lines[0]
    evs = [l for l in lines
           if l.get("kind") in ("span", "instant", "flow", "counter")]
    assert {l["name"] for l in evs} == {"a", "b"}
    assert all("pid" in l and "ts" in l for l in evs)


# ------------------------------------------------------- engine + monitoring
def test_engine_spans_and_monitor_csv(tmp_path):
    """telemetry config block -> engine spans -> per-step scalars flow into
    the existing MonitorMaster CSV backend for free."""
    eng = _tiny_engine({
        "telemetry": {"enabled": True},
        "csv_monitor": {"enabled": True, "output_path": str(tmp_path), "job_name": "t"},
    })
    tr = get_tracer()
    assert tr.enabled  # the config block configured the global tracer
    for i in range(2):
        eng.train_batch(_batch(eng, seed=i))
    eng.flush_monitor()

    names = {e["name"] for e in tr.events()}
    assert {"train_batch", "data", "step"} <= names

    csv_dir = os.path.join(str(tmp_path), "t")
    files = os.listdir(csv_dir)
    assert any(f.startswith("Train_loss") for f in files)
    telem_files = [f for f in files if f.startswith("Telemetry_")]
    assert telem_files, files  # registry scalars reached the CSV backend
    # memory watermark gauge is part of the per-step summary
    assert any("mem" in f for f in telem_files), telem_files
    # spans keep flowing through the fwd/bwd/step parity API too
    eng.forward(_batch(eng))
    eng.backward()
    eng.step()
    names = {e["name"] for e in tr.events()}
    assert {"fwd", "bwd"} <= names


def test_engine_disabled_telemetry_records_nothing():
    eng = _tiny_engine()  # no telemetry block, tracer disabled by fixture
    eng.train_batch(_batch(eng))
    assert get_tracer().events() == []
    assert get_tracer().registry.counters() == {}


def test_checkpoint_and_dataloader_spans(tmp_path):
    eng = _tiny_engine({"telemetry": {"enabled": True}})
    eng.train_batch(_batch(eng))
    eng.save_checkpoint(str(tmp_path / "ckpt"))
    eng.load_checkpoint(str(tmp_path / "ckpt"))
    loader = eng.deepspeed_io({"input_ids": np.zeros((32, 16), np.int32)})
    next(iter(loader))
    names = {e["name"] for e in get_tracer().events()}
    assert "checkpoint:save" in names
    assert "checkpoint:load" in names
    assert "data:materialize" in names


def test_bench_telemetry_section(tmp_path, monkeypatch):
    """bench.py's phase breakdown comes from the telemetry registry and its
    trace satisfies the Perfetto contract: fwd/bwd/step spans + at least one
    comm collective span with payload-bytes metadata."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    import bench

    monkeypatch.setenv("DSTPU_TELEMETRY_DIR", str(tmp_path))
    eng = _tiny_engine({"telemetry": {"enabled": True}})
    out = bench._telemetry_section(eng, _batch(eng), steps=2)
    assert {"fwd", "bwd", "step"} <= set(out["phases"])
    assert out["phases"]["step"]["count"] >= 2
    assert out["comm"]["comm/bytes"] > 0
    with open(out["trace"]) as f:
        doc = json.load(f)
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"fwd", "bwd", "step"} <= names
    comm = [e for e in doc["traceEvents"] if e.get("cat") == "comm"]
    assert comm and comm[0]["args"]["bytes"] > 0
