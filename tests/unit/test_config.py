"""Config system tests (batch triad parity with reference runtime/config.py:938-1045)."""

import pytest

from deepspeed_tpu.config import DeepSpeedTPUConfig


def test_defaults():
    cfg = DeepSpeedTPUConfig({})
    assert cfg.train_batch_size == 1
    assert cfg.train_micro_batch_size_per_gpu == 1
    assert cfg.gradient_accumulation_steps == 1
    assert cfg.zero_config.stage == 0
    assert not cfg.fp16_enabled and not cfg.bf16_enabled


def test_batch_triad_all_given():
    cfg = DeepSpeedTPUConfig(
        {"train_batch_size": 16, "train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 2},
        dp_world_size=4,
    )
    assert cfg.train_batch_size == 16


def test_batch_triad_inconsistent_raises():
    with pytest.raises(ValueError):
        DeepSpeedTPUConfig(
            {"train_batch_size": 17, "train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 2},
            dp_world_size=4,
        )


def test_batch_triad_solve_gas():
    cfg = DeepSpeedTPUConfig(
        {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 2}, dp_world_size=4
    )
    assert cfg.gradient_accumulation_steps == 4


def test_batch_triad_solve_micro():
    cfg = DeepSpeedTPUConfig(
        {"train_batch_size": 32, "gradient_accumulation_steps": 4}, dp_world_size=4
    )
    assert cfg.train_micro_batch_size_per_gpu == 2


def test_batch_triad_from_micro_only():
    cfg = DeepSpeedTPUConfig({"train_micro_batch_size_per_gpu": 3}, dp_world_size=2)
    assert cfg.train_batch_size == 6
    assert cfg.gradient_accumulation_steps == 1


def test_zero_section():
    cfg = DeepSpeedTPUConfig(
        {
            "zero_optimization": {
                "stage": 3,
                "offload_optimizer": {"device": "cpu", "pin_memory": True},
                "param_persistence_threshold": 1000,
            }
        }
    )
    z = cfg.zero_config
    assert z.stage == 3
    assert z.offload_optimizer_device == "cpu"
    assert z.param_persistence_threshold == 1000
    assert cfg.zero_enabled


def test_fp16_dynamic_loss_scale():
    cfg = DeepSpeedTPUConfig({"fp16": {"enabled": True, "initial_scale_power": 12}})
    assert cfg.fp16_enabled
    assert cfg.model.fp16.dynamic
    assert cfg.model.fp16.initial_scale_power == 12
    import jax.numpy as jnp

    assert cfg.compute_dtype == jnp.float16


def test_bf16():
    cfg = DeepSpeedTPUConfig({"bf16": {"enabled": True}})
    import jax.numpy as jnp

    assert cfg.compute_dtype == jnp.bfloat16


def test_unknown_keys_tolerated():
    cfg = DeepSpeedTPUConfig({"some_future_section": {"x": 1}, "train_micro_batch_size_per_gpu": 2})
    assert cfg.train_micro_batch_size_per_gpu == 2


def test_auto_values_dropped():
    cfg = DeepSpeedTPUConfig({"gradient_clipping": "auto"})
    assert cfg.gradient_clipping == 0.0


def test_optimizer_scheduler_sections():
    cfg = DeepSpeedTPUConfig(
        {
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3, "betas": [0.9, 0.95]}},
            "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 10}},
        }
    )
    assert cfg.model.optimizer.type == "AdamW"
    assert cfg.model.optimizer.params["lr"] == 1e-3
    assert cfg.model.scheduler.type == "WarmupLR"


def test_mesh_section():
    cfg = DeepSpeedTPUConfig({"mesh": {"fsdp": 4, "tp": 2, "dp": -1}})
    assert cfg.mesh_config.fsdp == 4
    assert cfg.mesh_config.tp == 2


def test_batch_triad_gas_only():
    # regression: a lone gradient_accumulation_steps must be honored, not reset
    cfg = DeepSpeedTPUConfig({"gradient_accumulation_steps": 4}, dp_world_size=2)
    assert cfg.gradient_accumulation_steps == 4
    assert cfg.train_batch_size == 8


def test_stage_auto_dropped():
    cfg = DeepSpeedTPUConfig({"zero_optimization": {"stage": "auto"}})
    assert cfg.zero_config.stage == 0


def test_strict_key_not_swallowed():
    # a config key literally named "strict" must pass through as an extra field
    cfg = DeepSpeedTPUConfig({"strict": True, "train_micro_batch_size_per_gpu": 2})
    assert cfg.train_micro_batch_size_per_gpu == 2


def test_collectives_section():
    cfg = DeepSpeedTPUConfig({
        "collectives": {
            "enabled": True, "algorithm": "ring2d", "codec": "int8",
            "codecs": ["none", "int8"], "mode": "measured",
            "overlap_chunks": 4, "block_size": 512,
        }
    })
    c = cfg.model.collectives
    assert c.enabled and c.algorithm == "ring2d" and c.codec == "int8"
    assert c.codecs == ["none", "int8"] and c.mode == "measured"
    assert c.overlap_chunks == 4 and c.block_size == 512
    # defaults: disabled, invisible
    d = DeepSpeedTPUConfig({}).model.collectives
    assert not d.enabled and d.algorithm == "auto" and d.overlap_chunks == 1
