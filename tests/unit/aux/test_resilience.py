"""Auto-recovery supervisor suite (``elasticity.run_resilient``).

The fault-injection proofs ISSUE 6 demands: NaN at step K → training
completes via rewind with the step counter showing it; persistent NaN →
bounded-retry give-up naming the flight record; corrupt latest snapshot →
rewind lands on the previous good tag; writer crash mid-run → training
continues (a save failure never rewinds healthy state).
"""

import os

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.checkpoint import snapshot as snap
from deepspeed_tpu.diagnostics import FaultInjector, TrainingHealthError
from deepspeed_tpu.elasticity import run_resilient
from tests.unit.simple_model import random_batch, simple_model_spec


def _engine(tmp_path, seed=3, every=2, recovery=None):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 1000,
        "diagnostics": {
            "enabled": True,
            "health": {"nonfinite_policy": "abort"},
            "flight_recorder": {"dump_dir": str(tmp_path / "fr"),
                                "install_signal_handlers": False,
                                "dump_on_exception": False},
        },
        "snapshot": {"enabled": True, "dir": str(tmp_path),
                     "every_n_steps": every, "fsync": False, "blocking": True},
        "recovery": {"backoff_base_s": 0.0, **(recovery or {})},
    }
    e, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg, seed=seed)
    return e


def _batch_fn(engine):
    return lambda step: random_batch(engine.train_batch_size, seed=step)


def test_rewind_completes_training_and_matches_clean_run(devices, tmp_path):
    """Transient NaN at step 3: the run aborts, rewinds to the last-good
    snapshot (step counter visibly rewound), replays, and finishes at the
    target step with the SAME final state as a never-faulted run."""
    e = _engine(tmp_path)
    fi = FaultInjector()
    rewound_steps = []
    report = run_resilient(
        e, fi.nan_batch_fn(_batch_fn(e), at_steps=[3]), num_steps=6,
        on_rewind=lambda entry: rewound_steps.append(entry["step"]))
    assert report.steps_completed == 6 and e.global_steps == 6
    assert report.rewinds == 1 and fi.nan_steps_fired == [3]
    # the rewind landed BEFORE the faulted step: the counter went backwards
    assert rewound_steps == [2]
    assert report.rewind_log[0]["tag"] == "step000002"
    assert report.flight_record and os.path.exists(report.flight_record)
    # cadence stays keyed on OPTIMIZER steps across the rewind (the restore
    # rewinds the host batch counter with the state): the final committed
    # snapshot is the step-6 boundary, not an offset batch count
    assert snap.latest_tag(str(tmp_path)) == "step000006"

    # clean reference run: same seeds, no fault, no supervisor interference
    ref = _engine(tmp_path / "ref", seed=3)
    for s in range(6):
        ref.train_batch(random_batch(ref.train_batch_size, seed=s))
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(ref.state.params)),
                    jax.tree_util.tree_leaves(jax.device_get(e.state.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bounded_retry_gives_up_with_flight_record(devices, tmp_path):
    """Deterministic fault (NaN on every replay of step 3): after
    max_rewinds_per_snapshot rewinds land on the same snapshot, the ORIGINAL
    TrainingHealthError is re-raised carrying the recovery report + flight
    record path."""
    e = _engine(tmp_path, recovery={"max_rewinds_per_snapshot": 2})
    fi = FaultInjector()
    with pytest.raises(TrainingHealthError) as ei:
        run_resilient(e, fi.nan_batch_fn(_batch_fn(e), at_steps=[3], repeat=True),
                      num_steps=6)
    rep = ei.value.recovery_report
    assert rep.gave_up
    assert rep.rewinds == 3  # 2 allowed on the tag + the one that tripped
    assert rep.flight_record and os.path.exists(rep.flight_record)
    assert len(fi.nan_steps_fired) == 3


def test_rewind_skips_corrupted_snapshot(devices, tmp_path):
    """The abort fires AND the latest snapshot is corrupt on disk: the rewind
    validates checksums first and lands on the previous good tag."""
    e = _engine(tmp_path, every=100)
    bf = _batch_fn(e)
    for s in range(2):
        e.train_batch(bf(s))
    e.snapshot_manager.snapshot(blocking=True)  # good anchor at step 2
    for s in range(2, 4):
        e.train_batch(bf(s))
    e.snapshot_manager.snapshot(blocking=True)  # will be corrupted (step 4)
    FaultInjector.truncate_shard(str(tmp_path), shard_index=0)

    fi = FaultInjector()
    report = run_resilient(e, fi.nan_batch_fn(bf, at_steps=[5]), num_steps=7)
    assert report.steps_completed == 7
    assert report.rewinds == 1
    assert report.rewind_log[0]["tag"] == "step000002"  # fell back past step 4


def test_save_failure_does_not_rewind(devices, tmp_path):
    """A writer crash during a cadenced save is swallowed and counted by the
    manager (never raised out of train_batch): training keeps going forward
    (no rewind), the report carries the failure count, and `latest` still
    names the pre-crash snapshot."""
    e = _engine(tmp_path, every=2)
    fi = FaultInjector()
    mgr = e.snapshot_manager
    report = run_resilient(e, _batch_fn(e), num_steps=2)  # anchor at step 2
    fi.kill_writer(mgr, after_shards=1)
    report = run_resilient(e, _batch_fn(e), num_steps=6)
    assert report.steps_completed == 6 and e.global_steps == 6
    assert report.rewinds == 0
    assert report.save_failures >= 1
    assert fi.writer_kills_fired == 1
    assert snap.latest_tag(str(tmp_path)) is not None


def test_run_resilient_requires_snapshots(devices, tmp_path):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "steps_per_print": 1000,
    }
    e, *_ = deepspeed_tpu.initialize(model=simple_model_spec(), config=cfg, seed=0)
    with pytest.raises(ValueError, match="snapshot"):
        run_resilient(e, _batch_fn(e), num_steps=1)
    # snapshot_dir= installs a manager on the fly
    report = run_resilient(e, _batch_fn(e), num_steps=2,
                           snapshot_dir=str(tmp_path))
    assert report.steps_completed == 2
    assert e.snapshot_manager is not None


def test_health_monitor_rearmed_after_rewind(devices, tmp_path):
    """The rewound run re-warms its EMA baselines: state.health is reset to
    the init state right after the rewind (count == 0)."""
    e = _engine(tmp_path)
    fi = FaultInjector()
    seen = []

    def on_rewind(entry):
        seen.append(int(jax.device_get(e.state.health.count)))

    run_resilient(e, fi.nan_batch_fn(_batch_fn(e), at_steps=[3]), num_steps=5,
                  on_rewind=on_rewind)
    assert seen == [0]  # fresh EMAs at the rewind point
    assert int(jax.device_get(e.state.health.count)) > 0  # re-warmed since
