"""Elastic agent restart semantics (reference elasticity/elastic_agent.py:32).

Workers are real subprocesses; a scripted failure on one host must kill the
generation, drop the host, re-resolve the batch triad for the smaller world,
and relaunch.
"""

import subprocess
import sys

import pytest

from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
from deepspeed_tpu.elasticity.elasticity import ElasticityError

ECFG = {
    "enabled": True,
    "max_train_batch_size": 48,
    "micro_batch_sizes": [1, 2, 4],
    "min_gpus": 1,
    "max_gpus": 64,
}


def _proc(code: int) -> subprocess.Popen:
    return subprocess.Popen([sys.executable, "-c", f"import sys; sys.exit({code})"])


def test_agent_restarts_without_failed_host():
    launches = []

    def launch(hosts, gen, cfg):
        launches.append((gen, sorted(hosts), dict(cfg)))
        # generation 0: worker on host 'b' fails; generation 1: all succeed
        return {h: _proc(1 if (gen == 0 and h == "b") else 0) for h in hosts}

    agent = DSElasticAgent({"a": 4, "b": 4}, ECFG, launch, max_restarts=2,
                           poll_interval_s=0.05)
    result = agent.run()
    assert result.ok and result.generation == 1
    assert launches[0][1] == ["a", "b"] and launches[1][1] == ["a"]
    # batch triad re-resolved for the smaller world
    w0 = launches[0][2]
    w1 = launches[1][2]
    assert w0["train_batch_size"] % 8 == 0
    assert w1["train_batch_size"] % 4 == 0
    assert len(agent.history) == 2 and not agent.history[0].ok


def test_agent_gives_up_after_budget():
    def launch(hosts, gen, cfg):
        return {h: _proc(1) for h in hosts}  # everything always fails

    agent = DSElasticAgent({"a": 2, "b": 2, "c": 2, "d": 2}, ECFG, launch,
                           max_restarts=2, poll_interval_s=0.05)
    result = agent.run()
    assert not result.ok
    assert len(agent.history) <= 3


def test_agent_rejects_incompatible_world():
    # micro batches {4}: a 3-chip world can never divide the batch
    cfg = {**ECFG, "micro_batch_sizes": [4], "max_train_batch_size": 8}
    agent = DSElasticAgent({"a": 3}, cfg, lambda *a: {}, poll_interval_s=0.05)
    with pytest.raises(ElasticityError):
        agent.run()


def test_agent_keeps_terminated_survivors():
    """Long-lived survivors killed BY the agent are not 'failed': they must
    be relaunched in the next generation (regression: one crash used to
    disqualify every host)."""
    launches = []

    def launch(hosts, gen, cfg):
        launches.append(sorted(hosts))
        procs = {}
        for h in hosts:
            if gen == 0 and h == "b":
                procs[h] = _proc(1)  # crashes immediately
            elif gen == 0:
                # healthy long-lived worker: only exits when terminated
                procs[h] = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(60)"])
            else:
                procs[h] = _proc(0)
        return procs

    agent = DSElasticAgent({"a": 4, "b": 4, "c": 4}, ECFG, launch,
                           max_restarts=2, poll_interval_s=0.05)
    result = agent.run()
    assert result.ok and result.generation == 1
    assert launches[1] == ["a", "c"], launches  # only the crasher was dropped
