"""Aux subsystems: elasticity, eigenvalue, PLD, data pipeline, compression,
autotuner (coverage model: reference tests/unit/{elasticity,autotuning,
compression,runtime/data_efficiency}/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.unit.simple_model import random_batch, simple_model_spec


# ----------------------------------------------------------------- elasticity
class TestElasticity:
    def test_compute_elastic_config(self):
        from deepspeed_tpu.elasticity import compute_elastic_config

        batch, worlds, table, micro = compute_elastic_config(
            {"max_train_batch_size": 2000, "micro_batch_sizes": [2, 4, 6],
             "min_gpus": 1, "max_gpus": 100}
        )
        assert batch <= 2000 and len(worlds) > 20
        # every advertised world size must decompose exactly
        for w, mb in table.items():
            assert batch % (w * mb) == 0

    def test_world_size_resolution_and_mp(self):
        from deepspeed_tpu.elasticity import compute_elastic_config, ElasticityError

        batch, worlds, table, micro = compute_elastic_config(
            {"max_train_batch_size": 512, "micro_batch_sizes": [2, 4],
             "min_gpus": 1, "max_gpus": 64, "model_parallel_size": 2},
            world_size=16,  # 8 replicas
        )
        assert micro in (2, 4) and 8 in worlds
        # an incompatible world size must raise
        bad = max(worlds) * 2 + 1
        with pytest.raises(ElasticityError):
            compute_elastic_config(
                {"max_train_batch_size": 512, "micro_batch_sizes": [2, 4],
                 "min_gpus": 1, "max_gpus": 64, "model_parallel_size": 2},
                world_size=bad * 2,
            )

    def test_bad_config_raises(self):
        from deepspeed_tpu.elasticity import compute_elastic_config, ElasticityError

        with pytest.raises(ElasticityError):
            compute_elastic_config({"max_train_batch_size": 1, "micro_batch_sizes": [4]})


# ----------------------------------------------------------------- eigenvalue
def test_dominant_eigenvalue_quadratic():
    """H of 0.5*x^T diag(d) x is diag(d): power iteration must find max d."""
    from deepspeed_tpu.runtime.eigenvalue import dominant_eigenvalue

    d = jnp.array([1.0, 5.0, 3.0])
    loss = lambda p: 0.5 * jnp.sum(d * p["x"] ** 2)
    eig, vec = dominant_eigenvalue(loss, {"x": jnp.ones(3)}, iters=50, tol=1e-7)
    assert abs(eig - 5.0) < 1e-3
    v = np.asarray(vec["x"])
    assert abs(abs(v[1]) - 1.0) < 1e-2  # eigenvector concentrated on dim 1


def test_eigenvalue_per_block():
    from deepspeed_tpu.runtime.eigenvalue import Eigenvalue

    loss = lambda p: 0.5 * (2.0 * jnp.sum(p["a"] ** 2) + 4.0 * jnp.sum(p["b"] ** 2))
    out = Eigenvalue(max_iter=50).compute_eigenvalue(loss, {"a": jnp.ones(2), "b": jnp.ones(2)})
    assert abs(out["a"] - 2.0) < 1e-2 and abs(out["b"] - 4.0) < 1e-2


# ----------------------------------------------------------------- PLD
def test_progressive_layer_drop_schedule():
    from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop

    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert pld.get_theta() == 1.0
    early = pld.update_state(0)
    late = pld.update_state(10000)
    assert early == pytest.approx(1.0) and late == pytest.approx(0.5, abs=1e-3)
    probs = np.asarray(pld.layer_keep_probs(4))
    assert (np.diff(probs) < 0).all()  # deeper layers drop more
    mask = np.asarray(pld.sample_keep_mask(jax.random.PRNGKey(0), 4))
    assert ((mask == 0) | (mask >= 1.0)).all()


# ----------------------------------------------------------------- curriculum
def test_curriculum_schedules():
    from deepspeed_tpu.runtime.data_pipeline import CurriculumScheduler

    lin = CurriculumScheduler({"curriculum_type": "fixed_linear", "min_difficulty": 8,
                               "max_difficulty": 64,
                               "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 8}})
    assert lin.get_difficulty(0) == 8
    assert lin.get_difficulty(100) == 64
    assert lin.get_difficulty(50) == 32
    root = CurriculumScheduler({"curriculum_type": "fixed_root", "min_difficulty": 8,
                                "max_difficulty": 64,
                                "schedule_config": {"total_curriculum_step": 100, "root_degree": 2}})
    assert root.get_difficulty(25) > lin.get_difficulty(25)  # root ramps faster early
    disc = CurriculumScheduler({"curriculum_type": "fixed_discrete", "min_difficulty": 1,
                                "max_difficulty": 3,
                                "schedule_config": {"difficulty": [1, 2, 3], "max_step": [10, 20]}})
    assert disc.get_difficulty(5) == 1 and disc.get_difficulty(15) == 2 and disc.get_difficulty(99) == 3


def test_data_sampler_curriculum_filters():
    from deepspeed_tpu.runtime.data_pipeline import CurriculumScheduler, DeepSpeedDataSampler

    n = 64
    difficulties = np.arange(n) % 32  # 0..31
    cur = CurriculumScheduler({"curriculum_type": "fixed_linear", "min_difficulty": 4,
                               "max_difficulty": 32,
                               "schedule_config": {"total_curriculum_step": 8, "difficulty_step": 1}})
    s = DeepSpeedDataSampler(n, batch_size=8, difficulties=difficulties, curriculum=cur, seed=0)
    first = next(iter(s))
    assert (difficulties[first] <= 4).all()  # early batches only easy samples
    # reproducibility
    s2 = DeepSpeedDataSampler(n, batch_size=8, difficulties=difficulties,
                              curriculum=CurriculumScheduler({"curriculum_type": "fixed_linear",
                                                              "min_difficulty": 4, "max_difficulty": 32,
                                                              "schedule_config": {"total_curriculum_step": 8}}),
                              seed=0)
    np.testing.assert_array_equal(first, next(iter(s2)))


# ----------------------------------------------------------------- random-LTD
def test_random_ltd_schedule_and_layer():
    from deepspeed_tpu.runtime.data_pipeline import RandomLTDScheduler
    from deepspeed_tpu.runtime.data_pipeline.random_ltd import apply_random_ltd

    sch = RandomLTDScheduler(initial_seq_len=32, total_seq_len=128,
                             schedule_steps=100, step_granularity=16)
    assert sch.get_seq_len(0) == 32 and sch.get_seq_len(100) == 128
    assert sch.get_seq_len(50) % 16 == 0

    x = jnp.arange(2 * 16 * 4, dtype=jnp.float32).reshape(2, 16, 4)
    out = apply_random_ltd(lambda t: t + 100.0, x, jax.random.PRNGKey(0), keep=8)
    changed = np.asarray((out != x).any(-1).sum(axis=1))
    np.testing.assert_array_equal(changed, [8, 8])  # exactly `keep` tokens touched
    # keep >= S: whole batch goes through
    out_full = apply_random_ltd(lambda t: t + 100.0, x, jax.random.PRNGKey(0), keep=16)
    np.testing.assert_allclose(np.asarray(out_full), np.asarray(x) + 100.0)


def test_variable_batch_packing():
    from deepspeed_tpu.runtime.data_pipeline import batch_by_tokens, scale_lr_by_batch

    lens = [10, 100, 12, 90, 500, 8]
    batches = batch_by_tokens(lens, max_tokens_per_batch=1024, len_bucket=64)
    assert sorted(i for b in batches for i in b) == list(range(6))
    for b in batches:
        padded = -(-max(lens[i] for i in b) // 64) * 64
        assert len(b) * padded <= 1024 or len(b) == 1
    assert scale_lr_by_batch(1e-3, 32, 16, "linear") == pytest.approx(2e-3)
    assert scale_lr_by_batch(1e-3, 64, 16, "sqrt") == pytest.approx(2e-3)


# ----------------------------------------------------------------- compression
class TestCompression:
    def test_fake_quantize_ste(self):
        from deepspeed_tpu.compression import fake_quantize

        w = jnp.linspace(-1, 1, 64).reshape(8, 8)
        q8 = fake_quantize(w, bits=8)
        q2 = fake_quantize(w, bits=2)
        assert float(jnp.abs(q8 - w).max()) < float(jnp.abs(q2 - w).max())
        # STE: gradient passes through unchanged
        g = jax.grad(lambda w: jnp.sum(fake_quantize(w, bits=4) * 2.0))(w)
        np.testing.assert_allclose(np.asarray(g), 2.0)

    def test_prune_masks(self):
        from deepspeed_tpu.compression import head_prune_mask, magnitude_prune_mask, row_prune_mask

        w = jnp.arange(1.0, 17.0).reshape(4, 4)
        m = magnitude_prune_mask(w, sparsity=0.5)
        assert float(m.sum()) == 8
        rm = row_prune_mask(w, sparsity=0.5, axis=0)
        assert float(rm.sum()) == 8 and set(np.asarray(rm.sum(axis=1)).tolist()) == {0.0, 4.0}
        hw = jnp.arange(1.0, 25.0).reshape(2, 3, 4)  # [emb, heads, hd]
        hm = head_prune_mask(hw, sparsity=1 / 3, num_heads=3, head_axis=1)
        assert set(np.asarray(hm.sum(axis=(0, 2))).tolist()) == {0.0, 8.0}

    def test_apply_compression_schedule_and_layer_reduction(self):
        from deepspeed_tpu.compression import apply_compression

        params = {"layers": {"w": jnp.ones((4, 8, 8))}, "head": {"kernel": jnp.ones((8, 8))}}
        cfg = {
            "weight_quantization": {"shared_parameters": {"schedule_offset": 100,
                                                          "target_bits": 4}},
            "layer_reduction": {"enabled": True, "keep_number_layer": 2},
        }
        early = apply_compression(params, cfg, step=0)
        assert early["layers"]["w"].shape[0] == 2  # reduction is schedule-free
        late = apply_compression(params, cfg, step=200)
        assert late["head"]["kernel"].shape == (8, 8)

    def test_init_compression_wraps_loss(self):
        from deepspeed_tpu.compression import init_compression

        sched, compress = init_compression(
            {"weight_quantization": {"shared_parameters": {"schedule_offset": 5, "target_bits": 8}}}
        )
        assert not sched.is_active("weight_quantization", 0)
        assert sched.is_active("weight_quantization", 5)
        p = {"k": jnp.ones((4, 4)) * 0.3}
        before = compress(p, step=0)["k"]
        np.testing.assert_allclose(np.asarray(before), 0.3)


# ----------------------------------------------------------------- autotuner
def test_autotuner_picks_viable_config(devices):
    from deepspeed_tpu.autotuning import Autotuner, estimate_state_memory

    # memory model sanity: sharding reduces footprint monotonically
    est = [estimate_state_memory(int(1e6), s, dp_world=8) for s in range(4)]
    assert est[0] > est[1] > est[2] > est[3]

    base = {"optimizer": {"type": "Adam", "params": {"lr": 1e-2}}, "steps_per_print": 1000}
    tuner = Autotuner(simple_model_spec(), base,
                      micro_batch_candidates=(2,), stage_candidates=(0, 1))
    best, results = tuner.tune(steps=2, batch_fn=lambda s: random_batch(16, seed=s))
    assert best["zero_optimization"]["stage"] in (0, 1)
    # space = stage x micro x remat (the docstring's promised third dimension)
    assert all(r.ok for r in results) and len(results) == 4
    assert any(r.config.get("activation_checkpointing", {}).get("enabled") for r in results)


def test_autotuner_model_factory_overrides(devices):
    """The autotuner can search MODEL-level knobs (scan_layers/fused_ce)
    through model_factory — the dimension PERF.md round 3 showed dominates."""
    import deepspeed_tpu
    from deepspeed_tpu.autotuning import Autotuner
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    tiny = dict(vocab_size=128, hidden_size=32, intermediate_size=64,
                num_layers=2, num_heads=4, max_seq_len=32)

    def factory(**overrides):
        return causal_lm_spec(TransformerConfig(**tiny, **overrides), example_seq_len=16)

    def batch_fn(seed):
        import numpy as np
        rng = np.random.default_rng(seed)
        # micro=2 x dp_world=8 devices -> global batch 16
        return {"input_ids": rng.integers(0, 128, (16, 16), dtype=np.int32)}

    base = {"optimizer": {"type": "Adam", "params": {"lr": 1e-3}}, "steps_per_print": 1000}
    tuner = Autotuner(
        factory(), base,
        micro_batch_candidates=(2,), stage_candidates=(1,), remat_candidates=(False,),
        model_factory=factory,
        model_override_candidates=({}, {"scan_layers": False}),
    )
    best, results = tuner.tune(steps=2, batch_fn=batch_fn)
    assert len(results) == 2 and all(r.ok for r in results)
    # both variants actually ran
    assert any(r.config.get("_model_overrides") == {"scan_layers": False} for r in results)
    # the returned config is initialize-consumable (no private keys), and the
    # winning model lives in best_model_spec / best_overrides
    assert "_model_overrides" not in best
    assert tuner.best_model_spec is not None
    assert tuner.best_overrides in (None, {"scan_layers": False})
    engine, *_ = deepspeed_tpu.initialize(model=tuner.best_model_spec, config=best)
    assert engine.train_batch_size == 16


def test_data_sampler_epoch_is_one_pass():
    """Regression: epoch N must serve exactly one pass, not N+1 passes."""
    from deepspeed_tpu.runtime.data_pipeline import DeepSpeedDataSampler

    s = DeepSpeedDataSampler(32, batch_size=8, seed=0)
    s.set_epoch(3)
    batches = list(s)
    assert len(batches) == 4  # 32 samples / 8 per batch, one pass
    served = sorted(int(i) for b in batches for i in b)
    assert served == list(range(32))


def test_see_memory_usage_and_breakdown_knob(monkeypatch):
    """see_memory_usage (reference runtime/utils.py:771): force-gated, returns
    a stats dict with live-buffer census; the engine's `memory_breakdown`
    config knob logs it at init (previously a dead knob)."""
    import jax.numpy as jnp

    from deepspeed_tpu.utils.memory import memory_status, see_memory_usage

    assert see_memory_usage("quiet") is None  # force gate
    keep = jnp.ones((256, 256), jnp.float32)
    stats = see_memory_usage("loud", force=True)
    assert stats is not None and stats["live_array_count"] >= 1
    assert stats["live_array_gb"] >= 0.0002  # the 256x256 f32 above
    assert "host_used_gb" in memory_status()
    del keep

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                            num_layers=1, num_heads=2, max_seq_len=16)
    import deepspeed_tpu.utils.memory as mem

    calls = []
    monkeypatch.setattr(
        mem, "see_memory_usage",
        lambda msg, force=False, ranks=None: calls.append((msg, force)))
    deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=16),
        config={"train_micro_batch_size_per_gpu": 1, "memory_breakdown": True,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "steps_per_print": 1000})
    assert ("engine state initialized", True) in calls


def test_top_level_api_conveniences():
    """Reference deepspeed.__init__ surface: add_config_arguments,
    default_inference_config, init_distributed re-export (round 5)."""
    import argparse

    import deepspeed_tpu

    p = deepspeed_tpu.add_config_arguments(argparse.ArgumentParser())
    a = p.parse_args(["--deepspeed", "--deepspeed_config", "/tmp/x.json"])
    assert a.deepspeed and a.deepspeed_config == "/tmp/x.json"
    d = deepspeed_tpu.default_inference_config()
    assert isinstance(d, dict) and "dtype" in d
    assert callable(deepspeed_tpu.init_distributed)


def test_ops_adam_class_imports(devices):
    """Reference `deepspeed.ops.adam.FusedAdam`-style imports build optax
    transforms the engine accepts via optimizer= (migration-surface parity)."""
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
    from deepspeed_tpu.ops.adam import DeepSpeedCPUAdam, FusedAdam, FusedLamb

    for factory in (FusedAdam, DeepSpeedCPUAdam, FusedLamb):
        assert hasattr(factory(lr=1e-3), "update")  # optax transformation
    cfg = TransformerConfig(vocab_size=64, hidden_size=16, intermediate_size=32,
                            num_layers=1, num_heads=2, max_seq_len=16)
    eng, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=16),
        optimizer=FusedAdam(lr=1e-3, weight_decay=0.01),
        config={"train_micro_batch_size_per_gpu": 2, "steps_per_print": 1000})
    m = eng.train_batch({"input_ids": np.zeros((eng.train_batch_size, 16), np.int32)})
    assert np.isfinite(float(m["loss"]))
