"""Packaging smoke: ``pip install -e .`` + console entry points.

Reference parity: ``setup.py:152-198`` installs ``deepspeed``/``ds_*`` console
scripts; round-3 verdict item 7 requires the CLIs to be runnable OUTSIDE the
checkout. Strategy: build a venv with --system-site-packages (jax/setuptools
come from the host; the sandbox has no network), editable-install the repo
with --no-deps --no-build-isolation, and drive two entry points from a cwd
outside the repo.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", ".."))


@pytest.fixture(scope="module")
def venv_bin(tmp_path_factory):
    venv = tmp_path_factory.mktemp("pkg") / "venv"
    try:
        subprocess.run([sys.executable, "-m", "venv", str(venv)],
                       check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
        pytest.skip(f"venv creation unavailable: {e}")
    # The test interpreter may itself be a venv (sandbox: /opt/venv), in which
    # case --system-site-packages would expose the BASE python's site-packages
    # and miss jax/setuptools. Link the parent's site-packages explicitly.
    import site

    sp_dirs = [p for p in site.getsitepackages() if os.path.isdir(p)]
    venv_sp = venv / "lib" / f"python{sys.version_info.major}.{sys.version_info.minor}" / "site-packages"
    (venv_sp / "_parent_env.pth").write_text("\n".join(sp_dirs) + "\n")
    pip = venv / "bin" / "pip"
    r = subprocess.run(
        [str(pip), "install", "--no-deps", "--no-build-isolation", "-e", REPO],
        capture_output=True, text=True, timeout=300)
    if r.returncode != 0:
        pytest.fail(f"pip install -e . failed:\n{r.stdout[-2000:]}\n{r.stderr[-2000:]}")
    return venv / "bin"


def _run(venv_bin, exe, *args, cwd="/tmp"):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PYTHONPATH", None)  # prove the INSTALL resolves, not the checkout
    return subprocess.run([str(venv_bin / exe), *args], capture_output=True,
                          text=True, timeout=180, cwd=cwd, env=env)


def test_editable_install_exposes_all_cli_entry_points(venv_bin):
    expected = ["dstpu", "ds_report", "ds_bench", "ds_elastic", "ds_io",
                "ds_nvme_tune", "ds_ssh", "zero_to_fp32"]
    missing = [e for e in expected if not (venv_bin / e).exists()]
    assert not missing, f"entry points not installed: {missing}"


def test_ds_elastic_runs_outside_checkout(venv_bin, tmp_path):
    cfg = tmp_path / "ds_config.json"
    cfg.write_text(json.dumps({
        "elasticity": {"enabled": True, "max_train_batch_size": 64,
                       "micro_batch_sizes": [2, 4], "min_gpus": 1,
                       "max_gpus": 8, "version": 0.1}}))
    r = _run(venv_bin, "ds_elastic", "-c", str(cfg))
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["train_batch_size"] > 0 and out["valid_world_sizes"]


def test_dstpu_help_runs_outside_checkout(venv_bin):
    r = _run(venv_bin, "dstpu", "--help")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "usage" in r.stdout.lower()


def test_entry_point_targets_importable():
    """Default-tier packaging check (the real `pip install -e .` + venv run
    is nightly — it costs ~20 s of the cold budget): every [project.scripts]
    target in pyproject.toml must resolve to a callable."""
    import importlib

    try:
        import tomllib  # stdlib from 3.11
    except ImportError:  # pragma: no cover - declared floor is 3.10
        tomllib = pytest.importorskip("tomli")

    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        scripts = tomllib.load(f)["project"]["scripts"]
    expected = {"dstpu", "ds_report", "ds_bench", "ds_elastic", "ds_io",
                "ds_nvme_tune", "ds_ssh", "zero_to_fp32"}
    assert expected <= set(scripts), f"missing console scripts: {expected - set(scripts)}"
    for name, target in scripts.items():
        mod, _, attr = target.partition(":")
        fn = getattr(importlib.import_module(mod), attr)
        assert callable(fn), f"{name} -> {target} is not callable"
