"""Noise-aware perf gate: fires on real regressions, stays quiet on the
repo's own (genuinely noisy) historical ledger, never mixes backends.

The two load-bearing properties, per ISSUE 16's acceptance bar:

  - the gate FAILS (regression verdicts + counter + armed profiler) on a
    synthetic 30% degradation of the current numbers, and
  - the gate PASSES on the committed history as-is — the historical
    round-to-round noise (serving telemetry overhead wandered 12→28%)
    must not produce false alarms.
"""

import os

import pytest

from deepspeed_tpu.profiling.capture import ProfilerCapture
from deepspeed_tpu.telemetry import perfmigrate
from deepspeed_tpu.telemetry.perfgate import (
    GateConfig,
    gate_fresh,
    gate_row,
    inject_regression,
    is_headline,
    publish,
    self_check,
)
from deepspeed_tpu.telemetry.perfledger import PerfLedger, make_row
from deepspeed_tpu.telemetry.registry import MetricsRegistry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _row(value, *, metric="tokens_per_sec_probe", suite="bench",
         backend="cpu", round=0, direction="higher"):
    return make_row(suite, metric, value, "tokens/s", direction=direction,
                    backend=backend, round=round, run_id="test",
                    git_sha="", time_unix=0.0)


@pytest.fixture()
def seeded(tmp_path):
    """Ledger with 5 rounds of quorum history for one headline key."""
    led = PerfLedger(str(tmp_path))
    for rnd, v in enumerate((100.0, 102.0, 98.0, 101.0, 99.0), start=1):
        led.append([_row(v, round=rnd)])
    return led


# ------------------------------------------------------------------- modes
def test_mad_gate_fires_on_injected_30pct(seeded):
    fresh = [_row(100.0, round=6)]
    assert gate_fresh(fresh, seeded).ok

    degraded = inject_regression(fresh, 30.0)
    assert degraded[0]["value"] == pytest.approx(70.0)
    report = gate_fresh(degraded, seeded)
    assert not report.ok
    (v,) = report.regressions
    assert v.mode == "mad"
    assert "REGRESSION" in report.summary()


def test_mad_gate_lower_is_better(tmp_path):
    led = PerfLedger(str(tmp_path))
    for rnd, v in enumerate((9.9, 10.0, 10.1), start=1):
        led.append([_row(v, metric="host_path/chained/host_us_per_decode_token",
                         suite="serving", round=rnd, direction="lower")])
    fresh = [_row(10.0, metric="host_path/chained/host_us_per_decode_token",
                  suite="serving", round=4, direction="lower")]
    assert gate_fresh(fresh, led).ok
    report = gate_fresh(inject_regression(fresh, 30.0), led)
    assert not report.ok
    assert report.regressions[0].row["value"] == pytest.approx(13.0)


def test_rel_fallback_below_quorum(tmp_path):
    led = PerfLedger(str(tmp_path))
    led.append([_row(100.0, round=1), _row(100.0, round=2)])
    ok = gate_fresh([_row(80.0, round=3)], led)  # -20% < 30% bound
    assert ok.ok and ok.verdicts[0].mode == "rel"
    bad = gate_fresh([_row(65.0, round=3)], led)  # -35% > 30% bound
    assert not bad.ok and bad.regressions[0].mode == "rel"


def test_absolute_overhead_bound_needs_no_history(tmp_path):
    led = PerfLedger(str(tmp_path))
    ok = gate_fresh([_row(1.9, metric="telemetry_overhead_pct", suite="perf",
                          direction="lower")], led)
    assert ok.ok and ok.verdicts[0].mode == "absolute"
    bad = gate_fresh([_row(2.5, metric="telemetry_overhead_pct", suite="perf",
                           direction="lower")], led)
    assert not bad.ok and bad.regressions[0].mode == "absolute"


def test_non_headline_rows_are_trajectory_only(seeded):
    # a 10x crash in a sub-metric never fails the build under the default
    # policy — but policy="all" gates it
    sub = [_row(1.0, metric="probes/some_sub_metric", round=6)]
    report = gate_fresh(sub, seeded)
    assert report.ok and report.verdicts[0].mode == "info"
    assert not is_headline(sub[0], GateConfig())


def test_vs_baseline_rows_excluded_from_headline():
    row = _row(0.5, metric="tokens_per_sec_probe/vs_baseline")
    assert not is_headline(row, GateConfig())


def test_backend_isolation(seeded):
    """5 rounds of cpu history must NOT gate (or vouch for) a tpu row."""
    tpu = [_row(1.0, backend="tpu-v5e", round=6)]  # 99% below cpu median
    report = gate_fresh(tpu, seeded)
    assert report.ok
    assert report.verdicts[0].status == "no_history"
    # and gate_row enforces it defensively even if handed foreign history
    v = gate_row(tpu[0], seeded.rows(), GateConfig())
    assert v.status == "no_history"


def test_round0_rows_compare_against_everything(seeded):
    report = gate_fresh(inject_regression([_row(100.0, round=0)], 30.0), seeded)
    assert not report.ok


def test_versioned_round_ignores_same_round_history(seeded):
    """A round-6 row must not be averaged with other round-6 rows (a bad
    round would vouch for itself)."""
    seeded.append([_row(70.0, round=6)])
    report = gate_fresh([_row(70.0, round=6)], seeded)
    assert not report.ok  # still judged against rounds 1-5 only


# ------------------------------------------------------- publish side-effects
def test_publish_counter_gauge_and_profiler_arm(seeded, tmp_path):
    reg = MetricsRegistry()
    cap = ProfilerCapture(steps=1, out_dir=str(tmp_path / "prof"))
    report = gate_fresh(inject_regression([_row(100.0, round=6)], 30.0), seeded)
    out = publish(report, registry=reg, arm=True)
    assert out["regressions"] == 1
    assert out["captures_armed"] >= 1
    assert cap._armed_reason.startswith("perf_gate:")
    assert reg.counter("perf/regression_events", suite="bench",
                       metric="tokens_per_sec_probe", backend="cpu").value == 1
    assert reg.gauge("perf/trajectory", suite="bench",
                     metric="tokens_per_sec_probe",
                     backend="cpu").value == pytest.approx(70.0)


def test_publish_ok_report_arms_nothing(seeded, tmp_path):
    reg = MetricsRegistry()
    cap = ProfilerCapture(steps=1, out_dir=str(tmp_path / "prof"))
    out = publish(gate_fresh([_row(100.0, round=6)], seeded), registry=reg)
    assert out == {"regressions": 0, "captures_armed": 0}
    assert cap._armed_reason is None
    assert reg.counters() == {}


# ------------------------------------------------------- the real ledger
def test_quiet_on_real_historical_noise(tmp_path):
    """self_check over the migrated legacy ledger: the committed history —
    noise and all — produces ZERO regressions at HEAD."""
    led = PerfLedger(str(tmp_path))
    perfmigrate.migrate(REPO_ROOT, led)
    report = self_check(led)
    assert report.regressions == []
    assert len(report.verdicts) > 200  # the whole ledger was walked
    gated = [v for v in report.verdicts if v.mode != "info"]
    assert gated  # and the headline/overhead rows really were gated
