"""Test harness: run every test on a virtual 8-device CPU mesh.

TPU-native analog of the reference's distributed-in-one-box harness
(``tests/unit/common.py`` — DistributedTest spawning N processes): JAX SPMD
needs no process-per-rank, so we instead force the host CPU platform to expose
8 virtual devices and run real multi-device sharding/collectives in-process.

Note: the sandbox's sitecustomize registers an experimental TPU PJRT plugin
("axon") at interpreter startup and pins JAX_PLATFORMS to it; initializing it
alongside the forced-CPU config deadlocks. jax may already be imported by the
time this conftest runs, so we force the platform via jax.config and drop the
plugin's backend factory instead of relying on env vars alone.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_backend_optimization_level" not in flags:
    # The suite is COMPILE-dominated on the single-core driver lane and the
    # tests assert math, not codegen quality: level 1 compiles the
    # compile-heavy tests ~2-3x faster (round-5 measurement: heaviest test
    # 88 s -> ~31 s cold) WITHOUT level 0's interpreter-slow codegen, which
    # regressed runtime-heavy tests (LoCo EF test 69 s -> 98 s at O0). Keeps
    # the default tier near the 550 s cold budget. Perf numbers never come
    # from tests (bench.py runs without this conftest).
    flags = flags + " --xla_backend_optimization_level=1"
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402

from deepspeed_tpu.utils.cpu_backend import force_cpu_backend  # noqa: E402

force_cpu_backend()
# Persistent compilation cache: the suite is dominated by XLA compiles of
# near-identical tiny programs (round-2 verdict: 186 tests no longer fit one
# 550 s run). Cache survives across pytest invocations in the repo tree.
#
# Keyed per HEAD sha (ISSUE 18): jax's entry keys hash the traced program,
# not the python that built it, so a source change that alters runtime
# behavior without changing the HLO (donation tweaks, compile options read
# from the environment, jax version-adjacent serialization drift) can serve
# a stale executable across commits. One subdir per HEAD commit makes the
# cache's validity domain explicit; stale sibling dirs (and pre-keying flat
# entries) are pruned so the tree holds at most one commit's cache.
_CACHE_ROOT = os.path.join(os.path.dirname(__file__), ".jax_cache")


def _head_sha():
    """Short HEAD sha of the repo this conftest sits in, or None when git
    is unavailable / not a checkout (then the cache keys to 'nogit')."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10.0)
    except (OSError, subprocess.TimeoutExpired):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def jax_cache_dir(root=None, sha=None):
    """The compilation-cache dir for one commit: ``<root>/<short-sha>``."""
    return os.path.join(root or _CACHE_ROOT, sha or _head_sha() or "nogit")


def _prune_stale_cache(keep, root=None):
    """Remove sibling cache dirs from other commits and legacy flat cache
    files from the pre-keyed layout. Returns the entry names removed."""
    import shutil

    root = root or _CACHE_ROOT
    if not os.path.isdir(root):
        return []
    removed = []
    for entry in os.listdir(root):
        path = os.path.join(root, entry)
        if os.path.abspath(path) == os.path.abspath(keep):
            continue
        try:
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.remove(path)
            removed.append(entry)
        except OSError:
            pass  # racing a parallel pytest: its key is the same sha anyway
    return removed


_CACHE_DIR = jax_cache_dir()
_prune_stale_cache(keep=_CACHE_DIR)
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402

# ---------------------------------------------------------------- tiering
# Reference parity (tests/pytest.ini:1-14): the default run excludes the slow
# tier (`nightly`) so one cold single-core run stays under the 550 s budget;
# `pytest -m nightly tests/` runs the deep tier. Central registry (matched as
# nodeid substrings) so the tiering is auditable in one place. POLICY: every
# subsystem keeps at least one canonical parity test in the default tier —
# nightly holds the deep/duplicate/trajectory coverage, never the only
# coverage of a feature.
NIGHTLY_NODE_SUBSTRINGS = [
    # deep checkpoint/trajectory coverage (canonical: test_universal basic
    # roundtrips, test_offload_nvme_roundtrip, zpp[2-knobs0])
    "test_universal_checkpoint_moe_expert_params",
    "test_universal_checkpoint_streams_atoms",
    "test_offload_optimizer_cpu_trajectory_matches_fused",
    "test_offload_zero3_with_param_offload",
    "test_offload_checkpoint_roundtrip",
    "test_hpz_trajectory_matches_stage3",
    "test_hpz_gathers_ride_small_axis",
    "test_zpp_trajectory_close_to_exact[3-knobs1]",
    "test_zpp_trajectory_close_to_exact[3-knobs2]",
    "test_zpp_parity_path_uses_quantized_comm",
    "test_mics_trajectory_matches_full_fsdp",
    "test_onebit_close_to_uncompressed",
    "test_onebit_universal_checkpoint_excludes_residuals",
    "test_onebit_trains_and_ships_uint8",
    "test_activation_checkpointing_changes_program_not_math",
    # parallelism deep tier (canonical: sp_matches_dp_baseline, moe_trains,
    # ring_attention_matches_dense, pipelined_causal_lm_matches_plain)
    "test_expert_parallel_matches_dense_ep",
    "test_pyramid_moe_per_layer_experts",
    "test_pr_moe_residual_trains",
    "test_sp_with_zero3",
    "test_causal_lm_with_ring_sp",
    "test_ring_attention_contiguous_fallback",
    "test_pipelined_engine_end_to_end",
    "test_interleaved_causal_lm_trains",
    "test_zero3_tp_composition",
    "test_hf_flax_gpt2_autotp_exactness",
    # models deep tier (canonical: test_tp_matches_pure_dp)
    "test_remat_and_no_scan_match",
    "test_tiny_llama_trains",
    "test_gpt2_style_trains",
    # ops deep tier (canonical: flash/sparse parity + bwd tests)
    "test_causal_lm_fused_ce_matches_unfused",
    "test_layout_cache_eviction_safe_under_grad",
    # inference deep tier (canonical: cached_decode[overrides0],
    # nvme_generate_matches_resident, paged_matches_dense_v1[overrides0])
    "test_cached_decode_matches_full_forward[overrides1]",
    "test_cached_decode_matches_full_forward[overrides2]",
    "test_cached_decode_matches_full_forward[overrides3]",
    "test_cached_decode_matches_full_forward[overrides4]",
    "test_ragged_prompts_right_padded",
    "test_moe_inference_forward",
    "test_woq_generate_close_to_dense",
    "test_nvme_composes_with_woq",
    # aux deep tier (canonical kept in default: autotuner_picks_viable_config,
    # agent_restarts_without_failed_host)
    "test_autotuner_model_factory_overrides",
    "test_agent_keeps_terminated_survivors",
    "test_agent_gives_up_after_budget",
    # ---- tranche 2 (single-core budget: default must fit one cold <550 s
    # run; canonical parity anchors that STAY default are listed in each
    # subsystem comment above plus: sp_matches_dp_baseline,
    # cached_decode[overrides0], tp_matches_pure_dp, moe_trains,
    # llama_ingestion, offload_nvme_roundtrip, nvme_generate_matches_resident,
    # paged_matches_dense_v1[overrides0], packaging, padding_mask,
    # sparse-attention gradient parity, flash grads[False]) ----
    "test_ring_attention_matches_dense",       # deep ring; zigzag/unit ring tests stay
    "test_pipelined_causal_lm_matches_plain",  # interleaved_pipeline_gradients stays
    "test_zpp_trajectory_close_to_exact[2-knobs0]",
    "test_onebit_error_feedback_state",
    "test_offload_state_not_on_mesh",
    "test_param_only_offload_is_not_a_silent_noop",
    "test_hybrid_engine_train_generate_flip",
    "test_sharded_init_matches_eager_init",
    "test_woq_memory_shrinks",
    "test_nvme_generate_matches_resident_sampled_eos",
    "test_ragged_forward_uses_kernel_consistently",
    "test_initialize_training_from_hf",
    "test_num_params_matches_init[4-1-True]",
    "test_paged_matches_dense_v1[overrides1]",
    "test_paged_matches_dense_v1[overrides2]",
    "test_paged_matches_dense_v1[overrides3]",
    "test_grads_match_xla[True]",
    "test_masked_grads_match_xla[8-8]",
    "test_unequal_blocks_dense_grid",
    # flash+alibi deep grid/GQA gradient variants (canonical [False-8-8] stays)
    "TestFlashAlibi::test_grads_match_xla[False-16-8]",
    "TestFlashAlibi::test_grads_match_xla[True-8-8]",
    # HF greedy-generate comparisons (deep tier; each family's logits-parity
    # test plus the kernel/v2 parity suites stay default)
    "test_gptj_generate_matches_hf",
    "test_bloom_generate_matches_hf",
    "test_paged_matches_dense_v1[overrides4]",
    # round-4 deep engine-level compositions (ops-level parity for the same
    # features stays default: sparse kernel tests, ring-alibi parity,
    # gpt_neox parallel / gptj / bloom logits parity, megatron split/merge +
    # TP-semantics tests)
    "test_sparse_attention_model_trains",
    "test_alibi_model_under_sp_matches_dp",
    "test_codegen_ingestion_logits_parity",
    "test_gpt_neox_sequential_residual_parity",
    "test_megatron_load_convert_logits_consistent",
    "test_pipelined_alibi_embed_norm_matches_plain",
    # sibling-covered variants (the kept sibling is named): opt keeps [relu],
    # qwen2's qkv-bias is covered by gpt2+llama, phi's partial rotary by
    # gptj, the contiguous ring-alibi by the zigzag [64] case
    "test_opt_ingestion_logits_parity[gelu",
    "test_qwen2_ingestion_logits_parity",
    "test_phi_ingestion_logits_parity",
    "test_ring_attention_alibi_matches_dense[52]",
    # ---- tranche 3 (trim to the 550 s budget; measured 570 s cold) ----
    "test_zpp_comm_bytes_reduced",            # zpp config/validation tests stay
    "test_schedule_executor_matches_sequential[2-4]",  # other params stay
    "test_ring_attention_jits_in_train_context",  # zigzag unit tests stay
    "test_paged_pallas_gqa_grouping",         # paged parity params stay
    # ---- tranche 4 (round 5): engine-level trajectory/composition variants;
    # default keeps each feature's canonical proof — FPDT: attention fwd+grad
    # parity + model parity (+ the nightly memory contract); sparse grads:
    # grad-equals-take + manual-scale regression + the HLO comm-pattern
    # assertion; LoCo: the EF property test; zpp x ulysses is also covered by
    # multichip dryrun D every round ----
    "test_k_splits_matches_unsplit[4-16-16]",  # splits=2 squashed-grid case stays (see tranche 6)
    "test_fpdt_engine_sp2_trajectory",
    "test_engine_sparse_gradients_trajectory",
    "test_sparse_gradients_compose_with_zeropp",
    "test_loco_trajectory_close_to_exact",
    "test_zpp_composes_with_ulysses_sp",
    # ---- tranche 5 (round 5: the default tier hit 735 s cold after the
    # round-5 features landed; the moves below are sibling-covered kernel
    # param variants + duplicate compositions, never a feature's only proof.
    # Kept defaults named per line) ----
    "test_fpdt_model_host_offload_parity",     # fpdt_model_parity stays
    # k_splits: [2-16-16] (squashed triangle grid — the PRODUCTION branch,
    # block_q == block_k) stays default; the dense-grid [2-16-8] moves
    # (dense grid + mask + bwd already default via masked_grads[16-8])
    "test_k_splits_matches_unsplit[2-16-8]",
    "test_pallas_sparse_matches_dense_masked[fixed-kw1]",    # local/variable/bslongformer stay
    "test_pallas_sparse_matches_dense_masked[bigbird-kw2]",
    "TestFlashAttention::test_forward_matches_xla[False-16]",  # ragged -100 pair stays
    "TestFlashAttention::test_forward_matches_xla[True-16]",
    "TestFlashAttention::test_padding_mask",   # masked_grads[16-8] (fwd+bwd) stays
    "test_paged_pallas_matches_xla[2]",        # [1] (MQA) and [8] stay... [8] moved too: gqa covered by alibi[2-8]
    "test_paged_pallas_matches_xla[8]",
    "test_paged_pallas_alibi_matches_xla[8-8]",  # [2-8] stays
    "test_paged_pallas_alibi_matches_xla[2-2]",
    "TestFlashAlibi::test_forward_matches_xla[16-8]",  # [8-8] stays
    "test_pipeline_module_matches_pp1[4]",     # [2] stays
    "test_zero_inference_offload_generate",    # composes_with_woq + nvme tests stay
    "test_sampling_shapes_and_determinism",    # eos + cached_decode[overrides0] stay
    "test_attention_pair_bias_and_alibi",      # evoformer_attention test stays
    "test_fpdt_attention_noncausal_parity",    # causal+alibi combos stay
    # the venv pip-install trio (20 s module fixture); the metadata
    # entry-point check stays default
    "test_editable_install_exposes_all_cli_entry_points",
    "test_ds_elastic_runs_outside_checkout",
    "test_dstpu_help_runs_outside_checkout",
    # ---- tranche 6 (round 5, second pass to the <550 s budget; kept
    # default sibling named per move) ----
    "test_sparse_composes_with_alibi_and_padding",  # model-level sparse x alibi x padding stays
    "test_safe_optimizer_state_roundtrip",     # fragment get_full_grad + get_set_fp32 stay
    "test_nvme_ram_budget_is_num_buffers_layers",  # nvme_generate_matches_resident stays
    "test_sparse_lookup_grad_scale_inside_manual_shard_map",  # comm_pattern + grad_equals_take stay
    "test_fpdt_chunk_major_zero_copy_layout",  # fpdt_longer_than_typical_hbm_tile stays
    "test_chunked_attention_non_causal_and_offset",  # chunked_attention_alibi + ring tests stay
    "test_zero_inference_composes_with_woq",   # woq_stacked + nvme_generate stay
    "TestMoE::test_top1_gating",               # gating_capacity_and_aux + moe_trains stay
    "test_pipeline_module_interleaved_matches_pp1",  # interleaved_pipeline_gradients stays
    "test_interleaved_pipeline_matches_sequential",  # ditto (gradients subsumes forward)
    "test_spmd_pipeline_matches_sequential",   # spmd_pipeline_gradients stays
    "test_deepspeed_io_curriculum_filters_batches",  # curriculum scheduler unit tests stay
    "TestUlysses::test_distributed_attention_class",  # sp_matches_dp_baseline stays
    "TestFlashAlibi::test_masked_forward_matches_xla",  # alibi fwd[8-8] + grads[False-8-8] + masked_grads stay
    "test_fused_ce_pad_mask_and_uneven_chunks",  # fused_ce_matches_naive stays
    "test_gpt_bigcode_ingestion_logits_parity[False]",  # MQA [True] variant stays
    "test_woq_stacked_layers_survive_scan",    # r4-bug regression; woq pytree + zero-inference woq composition stay
    "test_safe_get_set_fp32_param_across_shards",  # fragment get_full_grad + tiled_linear stay
    # build_hf_engine is 4-line glue over load_hf_checkpoint (13 family
    # parity tests) + InferenceEngineV2 (continuous-batching parity suite);
    # its engine-compile cost stays out of the default tier
    "test_build_hf_engine_v2_from_checkpoint",
    # Twin-Flow: structure + nvme-reject + fragment-visibility stay default;
    # the two-engine trajectory comparisons are the nightly depth
    "test_twin_flow_trajectory_matches_fused",
    "test_twin_flow_fp16_dynamic_scale_matches_fused",
    "test_v2_moe_generate_matches_v1",  # v1 moe_inference_forward + ragged-prefill parity stay the cheaper anchors
    "test_offload_bf16_grad_transfer_close_to_fp32",  # default keeps bf16_grad_accum_dtype_knob (fused path)
]


def pytest_collection_modifyitems(config, items):
    for item in items:
        if any(s in item.nodeid for s in NIGHTLY_NODE_SUBSTRINGS):
            item.add_marker(pytest.mark.nightly)
    # Default-tier deselection. Done here instead of addopts so that
    # (a) an explicit -m expression takes full control, and (b) running a
    # specific node-id (`pytest tests/...::test_x`) executes it even if it
    # is nightly — addopts would silently report "no tests collected".
    if config.option.markexpr:
        return
    if any("::" in str(a) for a in config.args):
        return
    kept = [i for i in items if i.get_closest_marker("nightly") is None]
    deselected = [i for i in items if i.get_closest_marker("nightly") is not None]
    if deselected:
        config.hook.pytest_deselected(items=deselected)
        items[:] = kept


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _clear_mesh_state():
    yield
    from deepspeed_tpu.topology import mesh as mesh_mod

    mesh_mod._ACTIVE_MESH = None
