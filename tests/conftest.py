"""Test harness: run every test on a virtual 8-device CPU mesh.

TPU-native analog of the reference's distributed-in-one-box harness
(``tests/unit/common.py`` — DistributedTest spawning N processes): JAX SPMD
needs no process-per-rank, so we instead force the host CPU platform to expose
8 virtual devices and run real multi-device sharding/collectives in-process.

Note: the sandbox's sitecustomize registers an experimental TPU PJRT plugin
("axon") at interpreter startup and pins JAX_PLATFORMS to it; initializing it
alongside the forced-CPU config deadlocks. jax may already be imported by the
time this conftest runs, so we force the platform via jax.config and drop the
plugin's backend factory instead of relying on env vars alone.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: the suite is dominated by XLA compiles of
# near-identical tiny programs (round-2 verdict: 186 tests no longer fit one
# 550 s run). Cache survives across pytest invocations in the repo tree.
_CACHE_DIR = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
try:
    from jax._src import xla_bridge

    xla_bridge._backend_factories.pop("axon", None)
except Exception:
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _clear_mesh_state():
    yield
    from deepspeed_tpu.topology import mesh as mesh_mod

    mesh_mod._ACTIVE_MESH = None
