"""Version info for deepspeed_tpu."""

__version__ = "0.1.0"
