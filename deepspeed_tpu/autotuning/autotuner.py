"""Autotuner: search ZeRO stage × micro-batch for best throughput.

Reference: ``autotuning/autotuner.py:42 Autotuner`` (``tune()`` :404) with its
model-based pruning (``tuner/model_based_tuner.py``: estimate per-stage
memory, skip configs that cannot fit) and experiment runner
(``scheduler.py``). TPU differences: experiments run in-process (no
multi-node job launches — one SPMD program per candidate), the memory model
uses the real param count + XLA's compiled peak-memory when available, and
the search space is (zero stage, micro batch, remat), optionally crossed with
model-level overrides via ``model_factory`` (e.g. ``scan_layers``/``fused_ce``
on a ``TransformerConfig`` — the knobs PERF.md round 3 measured to dominate).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


def estimate_state_memory(n_params: int, zero_stage: int, dp_world: int,
                          dtype_bytes: int = 4, opt_factor: int = 2, *,
                          compute_dtype_bytes: int = 0,
                          accum_dtype_bytes: Optional[int] = None,
                          micro_batch: int = 0,
                          seq_len: int = 0,
                          hidden_size: int = 0,
                          num_layers: int = 0,
                          vocab_size: int = 0,
                          num_heads: int = 0,
                          remat: bool = True,
                          fused_ce: bool = False,
                          flash_attention: bool = False) -> int:
    """Bytes/device for params+grads+optimizer state under a ZeRO stage
    (reference ``tuner/model_based_tuner.py`` memory model; Adam opt_factor=2
    fp32 moments), plus — when the model/batch geometry is given — the
    transient terms the original model ignored and the round-5 relay wedge
    proved load-bearing (VERDICT item 2):

    - a compute-dtype parameter copy (``compute_dtype_bytes`` > 0): the
      engine casts fp32 masters to bf16 per step; under ZeRO-3 the gather
      materializes the full copy transiently
    - the gradient ACCUMULATOR in its own dtype (``accum_dtype_bytes``,
      default ``dtype_bytes``) — bf16 accumulation halves this term
    - activations: with remat, ~2 residuals of [micro, seq, hidden] per
      layer boundary; without, ~12 per layer (qkv/attn/mlp intermediates)
    - logits + CE softmax grad: [micro, seq, vocab] in fp32 ×2 — the single
      biggest transient for big-vocab models; fused (chunked) CE reduces it
      to ~1/8
    - XLA temp/fusion workspace (the blind spot PR-7's calibration surfaced:
      ``hbm/estimate_ratio`` ~5x on the bf16 stage-1 CPU bench config —
      ``temp_bytes`` dominated the peak while every term above tracked the
      persistent state). Three structural contributors, coefficients fitted
      against ``memory_analysis().temp_size_in_bytes`` over layer/seq/batch
      sweeps of the bench model (each within ~15%):
        * non-flash attention backward materializes the score matrix class
          ~5x in fp32 per layer ([micro, heads, seq, seq]: scores, probs,
          both grads + a cast copy) — one live layer under remat (scores
          are recomputed per layer), zero when ``flash_attention`` (the
          Pallas kernel never materializes scores, that being the point)
        * CE backward holds ~2 more fp32 logit-class arrays beyond the
          counted pair (log-softmax + dlogits), same 1/8 fused-CE discount
        * dense/MLP fusion gradients: ~8 fp32 [micro, seq, hidden] per
          layer un-remat (~4 with remat: one layer recomputes at a time,
          but boundary residual grads persist)

    The positional-args form is unchanged (grads term == accumulator at
    ``dtype_bytes``), so existing callers see identical estimates — the
    temp terms engage only when the model/batch geometry is given.
    """
    P = n_params
    params_b = P * dtype_bytes
    grads_b = P * (accum_dtype_bytes if accum_dtype_bytes is not None else dtype_bytes)
    opt_b = P * dtype_bytes * opt_factor
    if zero_stage >= 1:
        opt_b //= dp_world
    if zero_stage >= 2:
        grads_b //= dp_world
    if zero_stage >= 3:
        params_b //= dp_world
    total = params_b + grads_b + opt_b
    if compute_dtype_bytes:
        total += P * compute_dtype_bytes
    tokens = micro_batch * seq_len
    if tokens and hidden_size and num_layers:
        act_bytes = compute_dtype_bytes or 2
        per_layer = 2 if remat else 12
        total += tokens * hidden_size * act_bytes * num_layers * per_layer
        # XLA fusion-gradient workspace (fp32)
        total += tokens * hidden_size * 4 * num_layers * (4 if remat else 8)
    if tokens and vocab_size:
        logit_b = tokens * vocab_size * 4 * 2  # fp32 logits + softmax grad
        logit_b += tokens * vocab_size * 4 * 2  # CE bwd transients (temp)
        total += logit_b // 8 if fused_ce else logit_b
    if tokens and num_heads and seq_len and num_layers and not flash_attention:
        # materialized-attention backward workspace (fp32 score-matrix
        # class); under remat one layer's scores are recomputed/live at a
        # time, so the term must not scale with depth there — a 48-layer
        # remat'd model would otherwise be rejected by hundreds of GiB
        live_layers = 1 if remat else num_layers
        total += micro_batch * num_heads * seq_len * seq_len * 4 * live_layers * 5
    return total


@dataclass
class ExperimentResult:
    config: Dict
    throughput: float = 0.0  # samples/sec
    latency_s: float = 0.0
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.error is None


class Autotuner:
    """In-process config search (reference ``Autotuner`` autotuner.py:42)."""

    def __init__(
        self,
        model_spec,
        base_config: Dict,
        micro_batch_candidates: Sequence[int] = (1, 2, 4, 8),
        stage_candidates: Sequence[int] = (0, 1, 2, 3),
        remat_candidates: Sequence[bool] = (False, True),
        memory_budget_bytes: Optional[int] = None,
        metric: str = "throughput",
        model_factory=None,
        model_override_candidates: Sequence[Dict] = ({},),
    ):
        """``model_factory(**overrides) -> model_spec`` extends the search to
        MODEL-level knobs the engine config cannot reach (e.g. a
        ``TransformerConfig``'s ``scan_layers``/``fused_ce`` — PERF.md round 3
        measured a ~25% wall-clock swing on scan_layers alone). Each dict in
        ``model_override_candidates`` multiplies the config space; with no
        factory, ``model_spec`` is used as-is."""
        self.model_spec = model_spec
        self.base_config = dict(base_config)
        self.micro_batch_candidates = list(micro_batch_candidates)
        self.stage_candidates = list(stage_candidates)
        self.remat_candidates = list(remat_candidates)
        self.memory_budget = memory_budget_bytes
        self.metric = metric
        self.model_factory = model_factory
        self.model_override_candidates = list(model_override_candidates)
        if not self.model_override_candidates:
            raise ValueError("model_override_candidates must not be empty (use ({},))")
        if model_factory is None and self.model_override_candidates != [{}]:
            raise ValueError("model_override_candidates needs model_factory")
        if not (self.micro_batch_candidates and self.stage_candidates and self.remat_candidates):
            raise ValueError("candidate lists must not be empty")
        self.results: List[ExperimentResult] = []
        self.best_overrides: Optional[Dict] = None
        self.best_model_spec = None

    # ------------------------------------------------------------ space
    def _candidates(self) -> List[Dict]:
        out = []
        for stage in self.stage_candidates:
            for mb in self.micro_batch_candidates:
                for remat in self.remat_candidates:
                    for overrides in self.model_override_candidates:
                        cfg = dict(self.base_config)
                        cfg.pop("train_batch_size", None)  # re-derived from micro
                        cfg["train_micro_batch_size_per_gpu"] = mb
                        zo = dict(cfg.get("zero_optimization", {}))
                        zo["stage"] = stage
                        cfg["zero_optimization"] = zo
                        ac = dict(cfg.get("activation_checkpointing", {}))
                        ac["enabled"] = remat  # remat=False must really disable it
                        if remat:
                            ac.setdefault("policy", "dots")  # keep a user's policy
                        cfg["activation_checkpointing"] = ac
                        if overrides:
                            # engine-config-invisible; popped before initialize
                            cfg["_model_overrides"] = dict(overrides)
                        out.append(cfg)
        return out

    def _fits_memory(self, cfg: Dict, n_params: int, dp_world: int) -> bool:
        need = estimate_state_memory(n_params, cfg["zero_optimization"]["stage"], dp_world)
        if need <= self.memory_budget:
            return True
        logger.info(
            f"autotuner: prune stage={cfg['zero_optimization']['stage']} "
            f"micro={cfg['train_micro_batch_size_per_gpu']} "
            f"(est {need/1e9:.2f} GB > budget {self.memory_budget/1e9:.2f} GB)"
        )
        return False

    # ------------------------------------------------------------ experiments
    def run_experiment(self, config: Dict, steps: int = 5, warmup: int = 2,
                       batch_fn=None, seed: int = 0) -> ExperimentResult:
        import deepspeed_tpu

        try:
            overrides = config.get("_model_overrides")
            model = self.model_factory(**overrides) if overrides else self.model_spec
            engine_cfg = {k: v for k, v in config.items() if k != "_model_overrides"}
            engine, *_ = deepspeed_tpu.initialize(model=model, config=engine_cfg, seed=seed)
            bs = engine.train_batch_size
            user_make = batch_fn or (lambda s: self._default_batch(bs, s))

            def make(s):
                # batch_fn cannot know each CANDIDATE's global batch (micro
                # varies across the sweep): hand it a pool and slice the
                # candidate's rows — a short pool is a real config error.
                b = user_make(s)
                lead = jax.tree_util.tree_leaves(b)[0].shape[0]
                if lead < bs:
                    raise ValueError(
                        f"batch_fn returned {lead} rows < candidate train_batch_size "
                        f"{bs}; return at least max(micro)*dp_world rows")
                return jax.tree_util.tree_map(lambda x: x[:bs], b) if lead > bs else b

            for i in range(warmup):
                engine.train_batch(make(seed + i))
            t0 = time.perf_counter()
            for i in range(steps):
                m = engine.train_batch(make(seed + warmup + i))
            np.asarray(m["loss"])  # sync
            dt = (time.perf_counter() - t0) / steps
            return ExperimentResult(config=config, throughput=bs / dt, latency_s=dt)
        except Exception as e:  # noqa: BLE001 - an infeasible config is a result
            return ExperimentResult(config=config, error=f"{type(e).__name__}: {e}")

    def _default_batch(self, batch_size: int, seed: int):
        raise ValueError("pass batch_fn= to tune()/run_experiment() — the autotuner "
                         "does not know your model's input schema")

    def _n_params_for(self, overrides: Optional[Dict]) -> int:
        """Parameter count for a candidate's model, shape-only (no compute)."""
        from deepspeed_tpu.runtime.model import as_model_spec

        spec = as_model_spec(self.model_factory(**overrides) if overrides else self.model_spec)
        shapes = jax.eval_shape(spec.init_fn, jax.random.PRNGKey(0))
        return int(sum(np.prod(x.shape) for x in jax.tree_util.tree_leaves(shapes)))

    def tune(self, steps: int = 5, batch_fn=None, seed: int = 0) -> Tuple[Dict, List[ExperimentResult]]:
        """Run the sweep, return (best_config, all_results) (reference
        ``tune()`` autotuner.py:404 + ``get_best_space_config``).

        The returned config is directly consumable by ``initialize``. When the
        winner used model overrides, ``self.best_overrides`` records them and
        ``self.best_model_spec`` is the rebuilt spec — pass THAT as ``model=``
        (the engine config cannot carry model-level knobs)."""
        import deepspeed_tpu

        if self.memory_budget is None:
            cfgs = self._candidates()
        else:
            from deepspeed_tpu.topology.mesh import get_data_parallel_world_size

            # probe: dp world from a throwaway engine on the base config
            probe_cfg = dict(self.base_config)
            probe_cfg.setdefault("train_micro_batch_size_per_gpu", self.micro_batch_candidates[0])
            engine, *_ = deepspeed_tpu.initialize(model=self.model_spec, config=probe_cfg, seed=seed)
            dp_world = get_data_parallel_world_size(engine.mesh)
            del engine
            # per-override param counts (overrides may resize the model);
            # repr-canonicalized keys tolerate unhashable override values
            n_params = {"": self._n_params_for(None)}
            for ov in self.model_override_candidates:
                if ov:
                    n_params[repr(sorted(ov.items()))] = self._n_params_for(ov)

            def params_of(cfg):
                ov = cfg.get("_model_overrides")
                return n_params[repr(sorted(ov.items())) if ov else ""]

            cfgs = [c for c in self._candidates()
                    if self._fits_memory(c, params_of(c), dp_world)]
        if not cfgs:
            raise RuntimeError("autotuner: every candidate exceeds the memory budget")
        self.results = [self.run_experiment(c, steps=steps, batch_fn=batch_fn, seed=seed) for c in cfgs]
        ok = [r for r in self.results if r.ok]
        if not ok:
            raise RuntimeError(
                "autotuner: all experiments failed; first error: " + self.results[0].error
            )
        best = max(ok, key=lambda r: r.throughput)
        self.best_overrides = best.config.get("_model_overrides")
        self.best_model_spec = (
            self.model_factory(**self.best_overrides) if self.best_overrides else self.model_spec
        )
        best_config = {k: v for k, v in best.config.items() if k != "_model_overrides"}
        if self.best_overrides:
            # The winning configuration includes MODEL-level overrides that the
            # returned config cannot carry: a caller who re-initializes with
            # their original model spec silently runs a non-winning model.
            logger.warning(
                "autotuner: best config includes model overrides %s — pass "
                "tuner.best_model_spec (NOT your original model spec) to "
                "initialize(), or the tuned model-level knobs are lost",
                self.best_overrides,
            )
        log_dist(
            f"autotuner: best stage={best.config['zero_optimization']['stage']} "
            f"micro={best.config['train_micro_batch_size_per_gpu']} "
            + (f"model_overrides={self.best_overrides} " if self.best_overrides else "")
            + f"({best.throughput:.1f} samples/s over {len(ok)}/{len(self.results)} viable)",
            ranks=[0],
        )
        return best_config, self.results
