"""deepspeed_tpu.autotuning (reference ``deepspeed/autotuning/``)."""

from deepspeed_tpu.autotuning.autotuner import Autotuner, estimate_state_memory
