"""Environment report (``ds_report`` CLI).

Reference: ``deepspeed/env_report.py:183 main`` — versions, device info, and
the native-op compatibility matrix.
"""

from __future__ import annotations

import importlib
import platform
import sys
from typing import Dict, List, Tuple


def collect_versions() -> Dict[str, str]:
    out = {"python": platform.python_version()}
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy", "transformers"):
        try:
            m = importlib.import_module(mod)
            out[mod] = getattr(m, "__version__", "?")
        except Exception:  # noqa: BLE001
            out[mod] = "not installed"
    import deepspeed_tpu

    out["deepspeed_tpu"] = deepspeed_tpu.__version__
    return out


def collect_devices() -> List[str]:
    try:
        import jax

        return [f"{d.platform}:{d.device_kind} (id {d.id})" for d in jax.devices()]
    except Exception as e:  # noqa: BLE001
        return [f"<device query failed: {e}>"]


def op_compatibility() -> List[Tuple[str, bool, str]]:
    """Native/kernels matrix (reference op-compatibility table)."""
    rows: List[Tuple[str, bool, str]] = []
    try:
        from deepspeed_tpu.ops.op_builder import AsyncIOBuilder

        b = AsyncIOBuilder()
        rows.append(("async_io (C++)", b.is_compatible(), "g++ JIT build"))
    except Exception as e:  # noqa: BLE001
        rows.append(("async_io (C++)", False, str(e)))
    try:
        from deepspeed_tpu.ops.registry import op_report

        for name, impls in sorted(op_report().items()):
            rows.append((f"op:{name}", bool(impls), ",".join(impls)))
    except Exception as e:  # noqa: BLE001
        rows.append(("ops registry", False, str(e)))
    return rows


def report() -> str:
    lines = ["-" * 60, "deepspeed_tpu environment report (ds_report)", "-" * 60]
    lines.append("versions:")
    for k, v in collect_versions().items():
        lines.append(f"  {k:<18} {v}")
    lines.append("devices:")
    for d in collect_devices():
        lines.append(f"  {d}")
    lines.append("op compatibility:")
    for name, ok, note in op_compatibility():
        lines.append(f"  {'[OKAY]' if ok else '[FAIL]'} {name:<24} {note}")
    return "\n".join(lines)


def main() -> int:  # pragma: no cover - CLI shim
    print(report())
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
