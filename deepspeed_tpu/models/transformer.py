"""Decoder-only transformer family (the framework's flagship model).

The reference ships transformer implementations for inference injection
(``deepspeed/module_inject/containers/*``, ``model_implementations/``) and a
legacy fused training layer (``ops/transformer/transformer.py:296``). Here the
model is a first-class Flax module designed for TPU:

  - one config covers Llama-style (RMSNorm + RoPE + SwiGLU + GQA) and
    GPT-2-style (LayerNorm + learned positions + GELU) decoders
  - ``nn.scan`` over layers: one compiled block, stacked params (fast compile,
    XLA-friendly), optional ``nn.remat`` for activation checkpointing
    (the analog of ``runtime/activation_checkpointing``)
  - attention dispatches through the ops registry so the Pallas flash kernel
    replaces the XLA einsum path on TPU (``deepspeed_tpu/ops``)
  - ``partition_rules`` provide tensor-parallel placements (the AutoTP analog,
    reference ``module_inject/auto_tp.py:193``) that the engine composes with
    ZeRO sharding
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.runtime.model import ModelSpec


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    hidden_size: int = 512
    intermediate_size: int = 1408
    num_layers: int = 4
    num_heads: int = 8
    num_kv_heads: Optional[int] = None  # None => MHA
    head_dim: Optional[int] = None  # None => hidden // heads
    max_seq_len: int = 2048
    norm: str = "rmsnorm"  # rmsnorm | layernorm
    activation: str = "silu_glu"  # silu_glu | gelu (tanh approx) | gelu_exact | relu
    # QKV-projection bias override (qwen2-style: rmsnorm model WITH qkv bias).
    # None keeps the norm-derived default (layernorm models carry biases).
    qkv_bias: Optional[bool] = None
    # Output/MLP projection bias override (falcon-style: layernorm model with
    # bias-free dense layers). None keeps the norm-derived default.
    dense_bias: Optional[bool] = None
    # Falcon-7B-style parallel residual: attn and MLP both read ONE shared
    # input layernorm and add into the residual in parallel.
    parallel_block: bool = False
    # GPT-NeoX-style parallel residual: like parallel_block but the MLP reads
    # its OWN norm of the block input (x + attn(ln1(x)) + mlp(ln2(x))).
    parallel_mlp_norm: bool = False
    position: str = "rope"  # rope | learned | alibi (bloom-style score biases)
    rope_theta: float = 500000.0
    # Bloom-style LayerNorm applied to the token embeddings before layer 0.
    embed_norm: bool = False
    # Partial rotary (phi-style): rope only the first rotary_dim of head_dim.
    rotary_dim: Optional[int] = None
    # GPT-J/CodeGen rotary convention: adjacent pairs rotate together
    # (rotate_every_two) instead of the half-split llama/neox rotation.
    rope_interleaved: bool = False
    # MLP bias override (gpt-j: bias-free attention but biased MLP). None
    # falls back to dense_bias / the norm-derived default.
    mlp_bias: Optional[bool] = None
    # lm_head bias (phi-style untied head); disables the fused-CE path.
    lm_head_bias: bool = False
    norm_eps: float = 1e-5
    dropout: float = 0.0
    tie_embeddings: bool = False
    remat: bool = False
    scan_layers: bool = True
    attn_impl: str = "auto"  # auto | xla | flash | sparse | fpdt
    # Block-sparse attention config (reference ``sparse_attention`` config
    # section + ``ops/sparse_attention/sparsity_config.py``): a dict like
    # {"mode": "bigbird", "block": 16, "num_random_blocks": 1, ...} consumed
    # when attn_impl == "sparse". Training runs the tile-skipping Pallas
    # kernels fwd AND bwd. Must be a hashable tuple-of-pairs internally, so
    # pass a dict and it is frozen at construction.
    sparse_attention: Optional[Any] = None
    # FPDT long-context training (reference sequence/fpdt_layer.py:510,971):
    # attn_impl == "fpdt" runs the custom-VJP chunked attention — O(Cq·Ck)
    # score tiles, never O(S²) — composing with Ulysses sp. fpdt_offload
    # additionally parks the q/k/v/out residuals in (pinned) host memory
    # between forward and backward. NOTE: the memory-space transfers are
    # rejected by the current XLA SPMD partitioner ("Side-effect HLO must
    # have sharding" on the placement annotations) — offload therefore works
    # on single-device jit only; the engine raises on multi-device meshes.
    # Multi-chip long-context = fpdt (no offload) and/or ring attention.
    fpdt_q_chunk: int = 1024
    fpdt_kv_chunk: int = 1024
    fpdt_offload: bool = False
    # Engine-wired sparse embedding gradients (reference sparse_gradients +
    # runtime/sparse_tensor.py): the embedding backward all-gathers compact
    # (ids, rows) pairs instead of psum-ing the dense [V, H] grad. Set by the
    # engine when the DS config has ``sparse_gradients: true`` and the
    # heuristic wins; incompatible with tie_embeddings (the tied LM head's
    # dense [V, H] grad would dominate anyway).
    sparse_embedding_grads: bool = False
    # Pallas attention scheduling knobs forwarded to the flash kernel when it
    # is the resolved impl (dropped on the XLA path — identical math either
    # way): {"block_q": ..., "block_k": ..., "k_splits": ...}. The autotuner /
    # profile_bench --stage attn-sweep pick these on hardware. Frozen to a tuple-of-pairs at
    # construction (configs are jit static args).
    attn_kwargs: Optional[Any] = None
    sp_impl: str = "ulysses"  # ulysses (all-to-all) | ring (ppermute) over sp
    dtype: Any = jnp.float32  # activation dtype inside the module
    # Fused chunked-vocab LM-head + cross-entropy on the training path (the
    # [tokens, vocab] logits never materialize). Auto-disabled for small
    # vocabularies where chunking buys nothing.
    fused_ce: bool = True
    fused_ce_min_vocab: int = 4096
    # MoE (0 experts => dense MLP). Mirrors reference moe/layer.py knobs.
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_min_capacity: int = 4
    moe_aux_loss_weight: float = 0.01
    moe_drop_tokens: bool = True
    # PR-MoE (reference moe/layer.py use_residual; DeepSpeed-MoE pyramid):
    # a per-layer expert-count tuple makes the stack a pyramid (0 => dense
    # layer); requires scan_layers=False (heterogeneous layers cannot scan).
    moe_use_residual: bool = False
    moe_layer_experts: Optional[Tuple[int, ...]] = None
    # Emit device-computed MoE dispatch gauges (moe/capacity_factor,
    # moe/token_drop_rate, moe/expert_load_balance — parallel/moe.py
    # MOE_STAT_KEYS) from the training forward: the loss_fn returns
    # (loss, logits, stats_dict) instead of (loss, logits). The engine flips
    # this on via rebuild when telemetry is enabled (no-op for dense models).
    moe_metrics: bool = False
    # Expert-parallel token dispatch (ISSUE 15; parallel/moe.py): how the
    # [E, C, M] dispatch/combine reshards onto ep — "auto" runs the explicit
    # shard_map + facade all_to_all path (cross-tp token gather/drop) on
    # ep x tp meshes and GSPMD constraints elsewhere; "collective"/"gspmd"
    # force one. The algorithm/codec knobs route the dispatch wire
    # (int8/fp8 = quantized token routing; None = facade defaults).
    moe_dispatch: str = "auto"
    moe_dispatch_algorithm: Optional[str] = None
    moe_wire_codec: Optional[str] = None
    # Capacity-factor autotuning ceiling (runtime moe_autotune block): when
    # set, capacity arrays are sized by THIS factor and the enforced cutoff
    # follows a traced scalar (batch key "moe_capacity_factor", threaded by
    # the engine's controller) — capacity moves between steps with the jit
    # cache staying at one program.
    moe_capacity_factor_max: Optional[float] = None

    def __post_init__(self):
        if self.moe_layer_experts is not None and len(self.moe_layer_experts) != self.num_layers:
            raise ValueError(
                f"moe_layer_experts has {len(self.moe_layer_experts)} entries "
                f"for num_layers={self.num_layers} — one expert count per layer"
            )
        if isinstance(self.sparse_attention, dict):
            # frozen dataclass must stay hashable (configs are jit static args)
            object.__setattr__(self, "sparse_attention",
                               tuple(sorted(self.sparse_attention.items())))
        if isinstance(self.attn_kwargs, dict):
            object.__setattr__(self, "attn_kwargs",
                               tuple(sorted(self.attn_kwargs.items())))
        if self.attn_impl == "sparse" and not self.sparse_attention:
            raise ValueError(
                "attn_impl='sparse' needs a sparse_attention config dict, e.g. "
                "{'mode': 'bigbird', 'block': 16, 'num_random_blocks': 1}")
        if self.fpdt_offload and self.attn_impl != "fpdt":
            raise ValueError("fpdt_offload=True needs attn_impl='fpdt'")
        if self.sparse_embedding_grads and self.tie_embeddings:
            raise ValueError(
                "sparse_embedding_grads with tie_embeddings is counter-"
                "productive: the tied LM head contributes a dense [V, H] "
                "gradient either way")

    @property
    def sparse_attention_dict(self) -> Optional[dict]:
        return dict(self.sparse_attention) if self.sparse_attention else None

    def experts_for_layer(self, i: int) -> int:
        if self.moe_layer_experts is not None:
            return self.moe_layer_experts[i]
        return self.num_experts

    @property
    def has_moe(self) -> bool:
        return self.num_experts > 0 or bool(
            self.moe_layer_experts and any(e > 0 for e in self.moe_layer_experts)
        )

    @property
    def num_moe_layers(self) -> int:
        return sum(1 for i in range(self.num_layers) if self.experts_for_layer(i) > 0)

    @property
    def moe_dynamic_capacity(self) -> bool:
        """Whether the gate enforces a traced (autotunable) capacity cutoff
        — requires a ceiling AND drops (capacity is meaningless without)."""
        return (self.moe_capacity_factor_max is not None and self.moe_drop_tokens
                and self.has_moe)

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def dims_per_head(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    def flops_per_token(self, seq_len: int) -> float:
        """Approximate training FLOPs/token (fwd+bwd, 6ND + attention).

        For MoE configs N is the ACTIVE parameter count (top-k experts)."""
        n = self.num_active_params()
        attn = 12 * self.num_layers * self.hidden_size * seq_len  # score+value matmuls
        return 6 * n + attn

    def _mlp_params(self) -> int:
        """One MLP's (one expert's) parameter count."""
        proj = 3 if self.activation == "silu_glu" else 2
        return proj * self.hidden_size * self.intermediate_size

    def num_params(self) -> int:
        h, v, l = self.hidden_size, self.vocab_size, self.num_layers
        hd = self.dims_per_head
        qkv = h * hd * (self.num_heads + 2 * self.kv_heads) + hd * self.num_heads * h
        mlp = self._mlp_params()
        total = v * h * (1 if self.tie_embeddings else 2)  # embedding (+ head)
        total += h  # final norm
        for i in range(l):
            n_exp = self.experts_for_layer(i)
            if n_exp > 0:
                layer_mlp = n_exp * mlp + h * n_exp  # experts + router
                if self.moe_use_residual:
                    layer_mlp += mlp + 2 * h + 2  # residual MLP + coefficient gate
            else:
                layer_mlp = mlp
            total += qkv + layer_mlp + (h if self.parallel_block else 2 * h)
        return total

    def num_active_params(self) -> int:
        """Params a single token touches (top-k experts instead of all)."""
        if not self.has_moe:
            return self.num_params()
        mlp = self._mlp_params()
        dead = 0
        for i in range(self.num_layers):
            n_exp = self.experts_for_layer(i)
            if n_exp > 0:
                dead += (n_exp - min(self.moe_top_k, n_exp)) * mlp
        return self.num_params() - dead


# ---------------------------------------------------------------- presets
PRESETS = {
    "tiny": TransformerConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                              num_layers=2, num_heads=4, max_seq_len=128),
    "gpt2-125m": TransformerConfig(vocab_size=50257, hidden_size=768, intermediate_size=3072,
                                   num_layers=12, num_heads=12, max_seq_len=1024,
                                   norm="layernorm", activation="gelu", position="learned",
                                   tie_embeddings=True),
    "llama3-8b": TransformerConfig(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                                   num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=8192,
                                   rope_theta=500000.0),
    "llama3-1b": TransformerConfig(vocab_size=128256, hidden_size=2048, intermediate_size=8192,
                                   num_layers=16, num_heads=32, num_kv_heads=8, max_seq_len=8192),
}


def act_fn(name: str):
    """Non-GLU activation by config name (shared by every MLP/expert site)."""
    if name == "relu":
        return jax.nn.relu
    if name == "gelu_exact":  # HF 'gelu' is the erf form
        return lambda x: jax.nn.gelu(x, approximate=False)
    if name == "gelu":
        return jax.nn.gelu
    raise ValueError(f"unknown activation {name!r} (silu_glu | gelu | gelu_exact | relu)")


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        from deepspeed_tpu.ops import rms_norm

        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        return rms_norm(x, scale, eps=self.eps)


def _norm(config: TransformerConfig, name: str):
    if config.norm == "rmsnorm":
        return RMSNorm(eps=config.norm_eps, name=name)
    return nn.LayerNorm(epsilon=config.norm_eps, name=name)


def rope_tables(seq_len: int, dim: int, theta: float) -> Tuple[jax.Array, jax.Array]:
    freqs = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    angles = jnp.outer(t, freqs)  # [S, dim/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_qk_rope(cfg: "TransformerConfig", q, k, positions):
    """Apply (possibly partial) rotary embeddings per the config.

    Phi-style partial rotary ropes only the first ``rotary_dim`` of head_dim;
    the tail dims pass through. ``rope_interleaved`` selects the GPT-J
    pairwise rotation. Shared by the training attention and both inference
    decode paths so the three sites cannot drift."""
    hd = q.shape[-1]
    rd = cfg.rotary_dim or hd
    cos, sin = rope_tables(cfg.max_seq_len, rd, cfg.rope_theta)
    ap = lambda x: apply_rope(x, cos, sin, positions, interleaved=cfg.rope_interleaved)  # noqa: E731
    if rd < hd:
        q = jnp.concatenate([ap(q[..., :rd]), q[..., rd:]], -1)
        k = jnp.concatenate([ap(k[..., :rd]), k[..., rd:]], -1)
        return q, k
    return ap(q), ap(k)


def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """Per-head ALiBi slopes (reference: the inference softmax kernels'
    alibi path, ``csrc/transformer/inference/csrc/softmax.cu``; formula
    matches HF ``build_alibi_tensor`` so bloom checkpoints reproduce)."""
    import math

    closest = 2 ** math.floor(math.log2(num_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(closest) - 3)))
    slopes = [base ** p for p in range(1, closest + 1)]
    if closest != num_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * closest) - 3)))
        slopes += [extra_base ** p for p in range(1, 2 * (num_heads - closest), 2)]
    return jnp.asarray(slopes, jnp.float32)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array,
               interleaved: bool = False) -> jax.Array:
    """x: [B, S, H, D]; cos/sin: [maxS, D/2]; positions: [B, S]."""
    from deepspeed_tpu.ops import rope as rope_op

    return rope_op(x, cos, sin, positions, interleaved=interleaved)


class _SparseGradEmbed(nn.Embed):
    """``nn.Embed`` whose backward ships sparse rows through the DP sync.

    Engine-wired ``sparse_gradients: true`` (reference runtime/sparse_tensor.py:69):
    identical params/forward to ``nn.Embed``; only the gradient's cross-replica
    sync changes (see ``runtime/sparse_grad.sparse_lookup``)."""

    def __call__(self, inputs):
        from deepspeed_tpu.runtime.sparse_grad import sparse_lookup

        table = self.embedding
        if self.dtype is not None:
            table = table.astype(self.dtype)
        return sparse_lookup(table, inputs)


class Attention(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, mask, positions, train: bool):
        cfg = self.config
        hd = cfg.dims_per_head
        qkv_bias = cfg.qkv_bias if cfg.qkv_bias is not None else cfg.norm == "layernorm"
        q = nn.DenseGeneral((cfg.num_heads, hd), use_bias=qkv_bias,
                            dtype=cfg.dtype, name="wq")(x)
        k = nn.DenseGeneral((cfg.kv_heads, hd), use_bias=qkv_bias,
                            dtype=cfg.dtype, name="wk")(x)
        v = nn.DenseGeneral((cfg.kv_heads, hd), use_bias=qkv_bias,
                            dtype=cfg.dtype, name="wv")(x)

        if cfg.position == "rope":
            q, k = apply_qk_rope(cfg, q, k, positions)
        slopes = alibi_slopes(cfg.num_heads) if cfg.position == "alibi" else None

        from deepspeed_tpu.ops import causal_attention
        from deepspeed_tpu.parallel.ulysses import sp_active, ulysses_shard, ulysses_unshard

        if cfg.attn_impl == "sparse":
            # Block-sparse attention (reference sparse_attention config +
            # sparsity_config.py): static layout from the config, the
            # tile-skipping Pallas kernels run fwd AND bwd.
            from deepspeed_tpu.ops.sparse_attention import (
                block_sparse_attention,
                get_sparsity_config,
            )

            if sp_active():
                raise NotImplementedError("attn_impl='sparse' under sequence parallelism")
            sa = dict(cfg.sparse_attention_dict)
            mode = sa.pop("mode", "bigbird")
            block = sa.pop("block", 16)
            S = q.shape[1]
            scfg = get_sparsity_config(mode, num_heads=cfg.num_heads,
                                       block=block, **sa)
            layout = scfg.make_layout(S)
            if cfg.kv_heads != cfg.num_heads:
                G = cfg.num_heads // cfg.kv_heads
                k = jnp.repeat(k, G, axis=2)
                v = jnp.repeat(v, G, axis=2)
            # ALiBi and key padding compose through the masked softmax
            # (round 5; those combos ride the XLA path — see
            # ops/sparse_attention.block_sparse_attention)
            out = block_sparse_attention(q, k, v, layout, block=block,
                                         alibi_slopes=slopes, pad_mask=mask)
        elif cfg.attn_impl == "fpdt":
            # FPDT long-context training (reference fpdt_layer.py:971
            # FPDT_Attention): custom-VJP chunked attention, O(Cq·Ck) score
            # tiles. Composes with Ulysses sp exactly like the dense path —
            # the all-to-all head shard happens via the same sharding
            # constraints. fpdt_offload parks the q/k/v/out residuals in
            # (pinned) host memory between forward and backward (the
            # reference's host-offloaded chunks), SPMD-safe.
            from deepspeed_tpu.sequence.fpdt import fpdt_attention

            if mask is not None:
                raise NotImplementedError(
                    "attn_impl='fpdt' with a padding mask is not wired; "
                    "right-pad and rely on causal masking or drop the mask")
            q, k, v = ulysses_shard(q), ulysses_shard(k), ulysses_shard(v)
            out = fpdt_attention(q, k, v, q_chunk=cfg.fpdt_q_chunk,
                                 kv_chunk=cfg.fpdt_kv_chunk, causal=True,
                                 alibi_slopes=slopes,
                                 offload=cfg.fpdt_offload)
            out = ulysses_unshard(out)
        elif cfg.sp_impl == "ring" and sp_active() and mask is None:
            # ring attention: K/V rotate over the sp ring (ppermute), queries
            # stay seq-sharded — O(S/P) memory, neighbor-link comm. ALiBi
            # rides the hops (each block's global k offset feeds the bias).
            from deepspeed_tpu.parallel.ring_attention import ring_attention
            from deepspeed_tpu.topology.mesh import get_mesh

            out = ring_attention(q, k, v, mesh=get_mesh(), axis="sp",
                                 alibi_slopes=slopes)
        else:
            # Ulysses SP: seq-shard -> head-shard all-to-all around exact
            # attention. Alibi composes for free: ulysses_shard is a sharding
            # CONSTRAINT (the program stays global SPMD), so the partitioner
            # splits the per-head slope bias along with the head axis.
            q, k, v = ulysses_shard(q), ulysses_shard(k), ulysses_shard(v)
            out = causal_attention(q, k, v, mask=mask, impl=cfg.attn_impl,
                                   alibi_slopes=slopes,
                                   **dict(cfg.attn_kwargs or ()))  # [B,S,H,hd]
            out = ulysses_unshard(out)
        dense_bias = cfg.dense_bias if cfg.dense_bias is not None else cfg.norm == "layernorm"
        out = nn.DenseGeneral(cfg.hidden_size, axis=(-2, -1), use_bias=dense_bias,
                              dtype=cfg.dtype, name="wo")(out)
        if cfg.dropout > 0:
            out = nn.Dropout(cfg.dropout, deterministic=not train)(out)
        return out


class MLP(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.config
        bias = cfg.mlp_bias if cfg.mlp_bias is not None else (
            cfg.dense_bias if cfg.dense_bias is not None else cfg.norm == "layernorm")
        if cfg.activation == "silu_glu":
            gate = nn.Dense(cfg.intermediate_size, use_bias=bias, dtype=cfg.dtype, name="w_gate")(x)
            up = nn.Dense(cfg.intermediate_size, use_bias=bias, dtype=cfg.dtype, name="w_up")(x)
            h = nn.silu(gate) * up
        else:
            h = nn.Dense(cfg.intermediate_size, use_bias=bias, dtype=cfg.dtype, name="w_up")(x)
            h = act_fn(cfg.activation)(h)
        out = nn.Dense(cfg.hidden_size, use_bias=bias, dtype=cfg.dtype, name="w_down")(h)
        if cfg.dropout > 0:
            out = nn.Dropout(cfg.dropout, deterministic=not train)(out)
        return out


class Block(nn.Module):
    # ``train`` is a module attribute (not a call kwarg) because nn.scan does
    # not forward kwargs through the scanned call.
    config: TransformerConfig
    train: bool = False
    layer_idx: int = 0  # selects the pyramid expert count (PR-MoE)

    @nn.compact
    def __call__(self, carry, _=None):
        cfg = self.config
        cap_scale = None
        if cfg.moe_dynamic_capacity:
            # dynamic capacity rides the carry as a traced fp32 scalar (the
            # engine's autotuning controller feeds it through the batch) —
            # dense layers pass it through untouched
            x, mask, positions, aux, cap_scale = carry
        else:
            x, mask, positions, aux = carry
        if cfg.parallel_block:
            # x = x + attn(ln1(x)) + mlp(h); h = ln1(x) shared (falcon) or a
            # separate ln2(x) (gpt-neox parallel_mlp_norm)
            x_in = x
            h = _norm(cfg, "attn_norm")(x_in)
            x = x + Attention(cfg, name="attn")(h, mask, positions, self.train)
            if cfg.parallel_mlp_norm:
                h = _norm(cfg, "mlp_norm")(x_in)
        else:
            x = x + Attention(cfg, name="attn")(
                _norm(cfg, "attn_norm")(x), mask, positions, self.train
            )
            h = _norm(cfg, "mlp_norm")(x)
        n_exp = cfg.experts_for_layer(self.layer_idx)
        # moe_metrics rides the aux carry as (scalar, stats-dict) — the
        # structure is decided once by CausalLM (dense layers pass it through
        # untouched, so the scan carry stays consistent across the stack)
        collect = cfg.moe_metrics and self.train and cfg.has_moe
        if n_exp > 0:
            from deepspeed_tpu.parallel.moe import MoEConfig, MoELayer

            moe_cfg = MoEConfig(
                num_experts=n_exp,
                top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                min_capacity=cfg.moe_min_capacity,
                drop_tokens=cfg.moe_drop_tokens,
                aux_loss_weight=cfg.moe_aux_loss_weight,
                collect_metrics=collect,
                dispatch=cfg.moe_dispatch,
                dispatch_algorithm=cfg.moe_dispatch_algorithm,
                dispatch_codec=cfg.moe_wire_codec,
                max_capacity_factor=(cfg.moe_capacity_factor_max
                                     if cfg.moe_dynamic_capacity else None),
            )
            moe_out = MoELayer(
                moe_cfg, cfg.hidden_size, cfg.intermediate_size,
                activation=cfg.activation, dtype=cfg.dtype, train=self.train,
                use_residual=cfg.moe_use_residual,
                name="moe",
            )(h, cap_scale)
            if collect:
                l_aux, out, stats = moe_out
                aux_sum, stats_acc = aux
                aux = (aux_sum + l_aux,
                       {k: stats_acc[k] + stats[k] for k in stats_acc})
            else:
                l_aux, out = moe_out
                aux = aux + l_aux
            x = x + out
        else:
            x = x + MLP(cfg, name="mlp")(h, self.train)
        if cfg.moe_dynamic_capacity:
            return (x, mask, positions, aux, cap_scale), None
        return (x, mask, positions, aux), None


class _HeadKernel(nn.Module):
    """Declares the untied LM-head kernel param without running the matmul —
    the fused-CE path reads the weight directly. Param path/shape/init match
    ``nn.Dense(name="lm_head")`` exactly so both paths share one parameter."""

    hidden: int
    vocab: int

    @nn.compact
    def __call__(self):
        return self.param(
            "kernel", nn.initializers.lecun_normal(), (self.hidden, self.vocab)
        )


class CausalLM(nn.Module):
    """Decoder-only LM. batch: {'input_ids': [B,S], optional 'labels',
    'attention_mask', 'position_ids'} -> (loss, logits). On the training path
    with ``fused_ce`` active, logits is None (the fused chunked-vocab CE never
    materializes it)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, batch, train: bool = False):
        cfg = self.config
        ids = batch["input_ids"]
        B, S = ids.shape
        positions = batch.get("position_ids")
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        pad_mask = batch.get("attention_mask")  # [B, S] 1=keep

        embed_cls = _SparseGradEmbed if cfg.sparse_embedding_grads else nn.Embed
        x = embed_cls(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="embed")(ids)
        if cfg.embed_norm:
            x = _norm(cfg, "embed_norm")(x)
        if cfg.position == "learned":
            pos_emb = self.param(
                "pos_embed", nn.initializers.normal(0.02), (cfg.max_seq_len, cfg.hidden_size)
            )
            x = x + pos_emb[None, :S, :].astype(cfg.dtype)

        aux = jnp.zeros((), jnp.float32)
        collect_moe = cfg.moe_metrics and train and cfg.has_moe
        if collect_moe:
            from deepspeed_tpu.parallel.moe import (MOE_DYNAMIC_STAT_KEYS,
                                                    MOE_STAT_KEYS)

            # (aux-loss sum, per-layer stat sums) — averaged over MoE layers
            # below; Block keeps this structure through the whole stack.
            # Dynamic-capacity gates additionally report the enforced factor.
            keys = (MOE_DYNAMIC_STAT_KEYS if (cfg.moe_dynamic_capacity and train)
                    else MOE_STAT_KEYS)
            aux = (aux, {k: jnp.zeros((), jnp.float32) for k in keys})
        block_cls = Block
        if cfg.remat:
            block_cls = nn.remat(Block, prevent_cse=False)
        if cfg.scan_layers and cfg.moe_layer_experts is not None:
            raise ValueError(
                "pyramid MoE (moe_layer_experts) needs scan_layers=False: "
                "heterogeneous expert counts cannot stack into one scan"
            )
        carry = (x, pad_mask, positions, aux)
        if cfg.moe_dynamic_capacity:
            # the autotuning controller's knob: a traced fp32 scalar the
            # engine injects per step (falls back to the configured static
            # factor — same program either way, only the value moves)
            cap = batch.get("moe_capacity_factor")
            cap = (jnp.float32(cfg.moe_capacity_factor) if cap is None
                   else jnp.asarray(cap, jnp.float32).reshape(()))
            carry = carry + (cap,)
        if cfg.scan_layers:
            stack = nn.scan(
                block_cls,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, train, name="layers")
            carry, _ = stack(carry, None)
        else:
            for i in range(cfg.num_layers):
                carry, _ = block_cls(cfg, train, layer_idx=i, name=f"layer_{i}")(
                    carry, None)
        x, aux = carry[0], carry[3]

        moe_stats = None
        if collect_moe:
            aux, stat_sums = aux
            n_moe = max(cfg.num_moe_layers, 1)
            moe_stats = {k: v / n_moe for k, v in stat_sums.items()}

        x = _norm(cfg, "final_norm")(x)
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate([ids[:, 1:], jnp.full((B, 1), -100, dtype=ids.dtype)], axis=1)

        use_fused = (train and cfg.fused_ce and cfg.vocab_size >= cfg.fused_ce_min_vocab
                     and not cfg.lm_head_bias)
        if use_fused:
            # fused chunked-vocab LM head + CE: no [B,S,V] logits in HBM
            # (see ops/cross_entropy.py). Training returns logits=None.
            from deepspeed_tpu.ops.cross_entropy import lm_head_cross_entropy

            if cfg.tie_embeddings:
                head = self.variables["params"]["embed"]["embedding"]  # [V, h]
            else:
                head = _HeadKernel(cfg.hidden_size, cfg.vocab_size, name="lm_head")().T
            loss = lm_head_cross_entropy(x, head.astype(cfg.dtype), labels, pad_mask)
            logits = None
        else:
            if cfg.tie_embeddings:
                embed = self.variables["params"]["embed"]["embedding"]
                logits = x @ embed.T.astype(cfg.dtype)
            else:
                logits = nn.Dense(cfg.vocab_size, use_bias=cfg.lm_head_bias,
                                  dtype=cfg.dtype, name="lm_head")(x)
            loss = cross_entropy_loss(logits, labels, pad_mask)
        if cfg.has_moe:
            # aux is pre-weighted by MoELayer; average over layers
            loss = loss + aux / cfg.num_layers
        if moe_stats is not None:
            # engine contract (_loss_and_aux): a trailing dict of scalars is
            # the device-computed stats side channel (moe/* gauges)
            return loss, logits, moe_stats
        return loss, logits


# --------------------------------------------------- pipelined execution
def _embed_tokens(params, cfg: TransformerConfig, ids):
    """Functional twin of the embedding front-end of ``CausalLM.__call__``."""
    x = jnp.take(params["embed"]["embedding"], ids, axis=0).astype(cfg.dtype)
    if cfg.embed_norm:
        x = _apply_norm(params["embed_norm"], cfg, x)
    if cfg.position == "learned":
        x = x + params["pos_embed"][None, : ids.shape[1], :].astype(cfg.dtype)
    return x


def _apply_norm(norm_params, cfg: TransformerConfig, x):
    """Functional twin of ``_norm`` (RMSNorm / flax LayerNorm)."""
    if cfg.norm == "rmsnorm":
        from deepspeed_tpu.ops import rms_norm

        return rms_norm(x, norm_params["scale"], eps=cfg.norm_eps)
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + cfg.norm_eps)
    y = y * norm_params["scale"].astype(jnp.float32) + norm_params["bias"].astype(jnp.float32)
    return y.astype(cfg.dtype)


def _lm_head_and_loss(params, cfg: TransformerConfig, x, batch, aux):
    x = _apply_norm(params["final_norm"], cfg, x)
    if cfg.tie_embeddings:
        logits = x @ params["embed"]["embedding"].T.astype(cfg.dtype)
    else:
        logits = x @ params["lm_head"]["kernel"].astype(cfg.dtype)
        if "bias" in params["lm_head"]:
            logits = logits + params["lm_head"]["bias"].astype(cfg.dtype)
    ids = batch["input_ids"]
    labels = batch.get("labels")
    if labels is None:
        B = ids.shape[0]
        labels = jnp.concatenate([ids[:, 1:], jnp.full((B, 1), -100, dtype=ids.dtype)], axis=1)
    loss = cross_entropy_loss(logits, labels, batch.get("attention_mask"))
    if cfg.has_moe:
        loss = loss + aux / cfg.num_layers
    return loss, logits


def pipelined_causal_lm_loss(params, batch, rng, *, config: TransformerConfig,
                             num_microbatches: int, mesh, train: bool = True,
                             virtual_stages: int = 1):
    """CausalLM forward+loss with the layer stack executed as an SPMD pipeline
    over the ``pp`` mesh axis (see ``parallel/pipeline_spmd.spmd_pipeline``).

    Embedding and the LM head run outside the pipeline (replicated over pp,
    sharded over dp/tp as usual); the batch splits into ``num_microbatches``
    along dim 0. For dense models this is numerically identical to the
    unpipelined model (same param tree; dropout patterns differ). For MoE
    models, gate capacity and the load-balancing aux loss are computed
    per-microbatch rather than over the full batch — the same per-microbatch
    routing semantics the reference has under gradient accumulation.
    """
    from deepspeed_tpu.parallel.pipeline_spmd import spmd_pipeline_interleaved

    cfg = config
    if not cfg.scan_layers:
        raise ValueError("pipelined execution requires scan_layers=True (stacked layer params)")
    if cfg.moe_metrics and train and cfg.has_moe:
        raise ValueError(
            "moe_metrics is not wired through the pipelined loss path (the "
            "stats dict cannot ride the pp activation ring) — the engine "
            "skips the rebuild on pp>1 meshes; construct with "
            "moe_metrics=False for pipelined MoE")
    if cfg.moe_dynamic_capacity:
        raise ValueError(
            "moe_capacity_factor_max (capacity autotuning) is not wired "
            "through the pipelined loss path (the capacity scalar cannot "
            "ride the pp activation ring) — the engine skips it on pp>1 "
            "meshes; construct without moe_capacity_factor_max")
    M = num_microbatches
    ids = batch["input_ids"]
    B, S = ids.shape
    if B % M:
        raise ValueError(f"batch {B} not divisible by pipeline microbatches {M}")
    positions = batch.get("position_ids")
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    pad_mask = batch.get("attention_mask")

    x = _embed_tokens(params, cfg, ids)
    split = lambda v: v.reshape((M, B // M) + v.shape[1:])
    # Activations + aux ride the ring; mask/positions are stage-invariant and
    # go through side_stream (indexed locally, no inter-stage comm).
    stream = (split(x), jnp.zeros((M,), jnp.float32))
    side = (None if pad_mask is None else split(pad_mask), split(positions))

    block = Block(cfg, train)

    def stage_fn(stage_layers, carry, side, srng):
        x, aux = carry
        mask, pos = side
        n_local = jax.tree_util.tree_leaves(stage_layers)[0].shape[0]
        rngs = jax.random.split(srng, n_local)

        def body(c, xs):
            lp, r = xs
            c2, _ = block.apply({"params": lp}, c, rngs={"dropout": r})
            return c2, None

        if cfg.remat:
            body = jax.checkpoint(body, prevent_cse=False)
        (x, _, _, aux), _ = jax.lax.scan(body, (x, mask, pos, aux), (stage_layers, rngs))
        return (x, aux)

    # virtual <= 1 delegates to the plain fill-and-drain pipeline
    x_out, aux = spmd_pipeline_interleaved(
        stage_fn, params["layers"], stream, mesh=mesh, rng=rng,
        side_stream=side, virtual=virtual_stages,
    )
    x_full = x_out.reshape((B,) + x_out.shape[2:])
    # Equal-size microbatches: mean of per-microbatch means == full-batch mean.
    return _lm_head_and_loss(params, cfg, x_full, batch, aux.mean())


def cross_entropy_loss(logits, labels, pad_mask=None, ignore_index: int = -100):
    """Mean token cross entropy in fp32 with ignore mask."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    if pad_mask is not None:
        valid = valid & (pad_mask > 0)
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)


# ------------------------------------------------------- tensor parallelism
def causal_lm_partition_rules(path: str, shape: tuple) -> Optional[P]:
    """AutoTP-style placement rules for CausalLM parameters.

    Column-parallel: q/k/v, gate/up projections, lm_head (output dim over tp).
    Row-parallel: o and down projections (input dim over tp).
    Embedding: vocab dim over tp. Right-aligned so the scan's leading layer
    dimension stays unsharded. (Reference analog: ``module_inject/auto_tp.py``
    tp_parser + LinearLayer/LinearAllreduce.)

    ``path`` is a ``jax.tree_util.keystr`` string, i.e. bracket form like
    ``"['layers']['attn']['wq']['kernel']"`` — match whole quoted names.
    """

    def has(token: str) -> bool:
        return f"'{token}'" in path

    def right(*entries):
        pad = len(shape) - len(entries)
        if pad < 0:
            return None
        return P(*([None] * pad + list(entries)))

    if has("experts") or has("gate"):
        from deepspeed_tpu.parallel.moe import moe_partition_rules

        return moe_partition_rules(path, shape)
    if has("pos_embed"):
        return None
    if has("embed") and has("embedding"):
        return right("tp", None)
    kernel = has("kernel")
    if kernel and (has("wq") or has("wk") or has("wv")):
        # DenseGeneral kernel [emb, heads, head_dim]: shard heads over tp
        return right(None, "tp", None) if len(shape) >= 3 else right(None, "tp")
    if kernel and has("wo"):
        # DenseGeneral kernel [heads, head_dim, emb]: shard heads over tp
        return right("tp", None, None) if len(shape) >= 3 else right("tp", None)
    if kernel and (has("w_gate") or has("w_up")):
        return right(None, "tp")
    if kernel and has("w_down"):
        return right("tp", None)
    if kernel and has("lm_head"):
        return right(None, "tp")
    if has("bias"):
        # biases of column-parallel layers follow the output (head) dim
        if has("wq") or has("wk") or has("wv"):
            return right("tp", None) if len(shape) >= 2 else None
        if has("w_gate") or has("w_up"):
            return right("tp")
    return None


def pipeline_partition_rules(path: str, shape: tuple) -> Optional[P]:
    """Partition rules with the stacked layer dim sharded over ``pp``.

    Composes with the tp rules (which are right-aligned, leaving dim 0 free on
    scanned-layer leaves). With a pp=1 mesh the ``pp`` entry is a no-op, so
    these rules are safe unconditionally for pipelined specs.
    """
    base = causal_lm_partition_rules(path, shape)
    if "'layers'" in path:
        entries = list(base) if base is not None else []
        entries += [None] * (len(shape) - len(entries))
        if entries and entries[0] is None:
            entries[0] = "pp"
        return P(*entries)
    return base


def causal_lm_spec(
    config: TransformerConfig,
    example_seq_len: int = 8,
    pipeline_microbatches: int = 0,
    pipeline_virtual_stages: int = 1,
) -> ModelSpec:
    """Build the engine-facing ModelSpec for a CausalLM.

    ``pipeline_microbatches > 1`` enables pipelined execution of the layer
    stack over the mesh's ``pp`` axis (reference ``PipelineModule`` +
    ``PipelineEngine`` path); with pp == 1 the plain forward is used.
    """
    module = CausalLM(config)
    example = {"input_ids": jnp.zeros((2, example_seq_len), jnp.int32)}

    def init_fn(rng):
        p_rng, d_rng = jax.random.split(rng)
        return module.init({"params": p_rng, "dropout": d_rng}, example, train=False)["params"]

    def loss_fn(params, batch, rng):
        if pipeline_microbatches > 1:
            from deepspeed_tpu.topology.mesh import get_mesh, has_mesh

            if has_mesh() and get_mesh().shape["pp"] > 1:
                return pipelined_causal_lm_loss(
                    params, batch, rng, config=config,
                    num_microbatches=pipeline_microbatches,
                    mesh=get_mesh(), train=True,
                    virtual_stages=pipeline_virtual_stages,
                )
        return module.apply({"params": params}, batch, train=True, rngs={"dropout": rng})

    def apply_fn(params, batch):
        return module.apply({"params": params}, batch, train=False)

    return ModelSpec(
        init_fn=init_fn,
        loss_fn=loss_fn,
        apply_fn=apply_fn,
        name=f"CausalLM({config.hidden_size}x{config.num_layers})",
        partition_rules=pipeline_partition_rules if pipeline_microbatches > 1 else causal_lm_partition_rules,
        model_config=config,
        # lets the engine re-derive the spec with config tweaks it owns
        # (e.g. sparse_embedding_grads from DS `sparse_gradients: true`)
        rebuild=lambda new_cfg: causal_lm_spec(
            new_cfg, example_seq_len=example_seq_len,
            pipeline_microbatches=pipeline_microbatches,
            pipeline_virtual_stages=pipeline_virtual_stages),
    )
