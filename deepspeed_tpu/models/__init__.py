from deepspeed_tpu.models.transformer import (
    PRESETS,
    CausalLM,
    TransformerConfig,
    causal_lm_partition_rules,
    causal_lm_spec,
    cross_entropy_loss,
)
