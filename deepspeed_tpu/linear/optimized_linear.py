"""OptimizedLinear / LoRAOptimizedLinear.

Reference: ``deepspeed/linear/optimized_linear.py`` — ``OptimizedLinear``
(:18) dispatches to ``LoRAOptimizedLinear`` (:76) when a LoRA config is
given: frozen (optionally quantized, optionally sharded) base weight + small
trainable adapters. TPU design: base-weight "sharding" is the mesh placement
(AutoTP rules), quantization is the int8 fake-quant op, and freezing is an
optax mask (``lora_trainable_mask``) — no special optimizer needed.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

LORA_A = "lora_a"
LORA_B = "lora_b"


class LoRAOptimizedLinear(nn.Module):
    """y = x @ W_frozen + scaling * (x @ A) @ B (reference :76).

    ``base`` params are created here but meant to be loaded from the
    pretrained checkpoint and frozen via ``lora_trainable_mask``.
    """

    features: int
    lora_r: int = 64
    lora_alpha: float = 16.0
    use_bias: bool = False
    quantize_base: bool = False
    q_bits: int = 8
    q_group_size: int = 0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_dim = x.shape[-1]
        w = self.param("kernel", nn.initializers.lecun_normal(), (in_dim, self.features))
        if self.quantize_base:
            from deepspeed_tpu.compression.ops import fake_quantize

            # memory-frugal base (reference QuantizedParameter): quantized
            # forward, no grad flows to it anyway (frozen)
            w = fake_quantize(w, bits=self.q_bits, group_size=self.q_group_size)
        a = self.param(LORA_A, nn.initializers.normal(1e-2), (in_dim, self.lora_r))
        b = self.param(LORA_B, nn.initializers.zeros, (self.lora_r, self.features))
        y = x @ w.astype(self.dtype)
        y = y + (self.lora_alpha / self.lora_r) * ((x @ a.astype(self.dtype)) @ b.astype(self.dtype))
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros, (self.features,)).astype(self.dtype)
        return y


class OptimizedLinear(nn.Module):
    """Config-dispatching facade (reference ``OptimizedLinear`` :18)."""

    features: int
    lora_config: Optional[Any] = None  # LoRAConfig
    quantization_config: Optional[Any] = None  # QuantizationConfig
    use_bias: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        if self.lora_config is not None:
            return LoRAOptimizedLinear(
                features=self.features,
                lora_r=self.lora_config.lora_r,
                lora_alpha=self.lora_config.lora_alpha,
                use_bias=self.use_bias,
                quantize_base=self.quantization_config is not None,
                q_bits=self.quantization_config.q_bits if self.quantization_config else 8,
                q_group_size=self.quantization_config.group_size if self.quantization_config else 0,
                dtype=self.dtype,
                name="lora",
            )(x)
        w = self.param("kernel", nn.initializers.lecun_normal(), (x.shape[-1], self.features))
        if self.quantization_config is not None:
            from deepspeed_tpu.compression.ops import fake_quantize

            w = fake_quantize(w, bits=self.quantization_config.q_bits,
                              group_size=self.quantization_config.group_size)
        y = x @ w.astype(self.dtype)
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros, (self.features,)).astype(self.dtype)
        return y


# ----------------------------------------------------------------- utilities
def _is_lora_path(path_keys) -> bool:
    ks = jax.tree_util.keystr(path_keys)
    return f"'{LORA_A}'" in ks or f"'{LORA_B}'" in ks


def lora_param_labels(params: Any) -> Any:
    """'lora' / 'frozen' label per leaf — feed to ``optax.multi_transform``."""
    return jax.tree_util.tree_map_with_path(
        lambda p, _: "lora" if _is_lora_path(p) else "frozen", params
    )


def lora_trainable_mask(params: Any) -> Any:
    """True only on adapter leaves."""
    return jax.tree_util.tree_map_with_path(lambda p, _: _is_lora_path(p), params)


def lora_optimizer(inner) -> Any:
    """Optimizer updating ONLY adapters; base weights frozen hard
    (``optax.multi_transform`` with set_to_zero — note ``optax.masked`` would
    pass base gradients through unchanged, silently unfreezing them)."""
    import optax

    return optax.multi_transform(
        {"lora": inner, "frozen": optax.set_to_zero()}, lora_param_labels
    )


def lora_merge(params: Any, scaling: float) -> Any:
    """Fold adapters into base kernels (reference HybridEngine
    ``fuse_lora_weight`` runtime/hybrid_engine.py:135): W' = W + s·A@B.
    Works on any subtree holding {kernel, lora_a, lora_b}."""

    def merge(node):
        if isinstance(node, dict) and LORA_A in node and LORA_B in node and "kernel" in node:
            node = dict(node)
            node["kernel"] = node["kernel"] + scaling * (node[LORA_A] @ node[LORA_B])
            node[LORA_A] = jnp.zeros_like(node[LORA_A])
            return node
        if isinstance(node, dict):
            return {k: merge(v) for k, v in node.items()}
        return node

    return merge(params)
