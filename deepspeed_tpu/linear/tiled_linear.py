"""TiledLinear: a large linear evaluated tile-by-tile.

Reference analog: ``deepspeed/runtime/zero/tiling.py`` (``TiledLinear``) —
splitting a huge linear into in/out tiles so ZeRO-3 only materializes one
tile's weights at a time. On TPU the same working-set bound comes from
per-tile rematerialization: each (in_tile, out_tile) product is wrapped in
``jax.checkpoint``, so at most one tile's activations persist, and with
ZeRO-3 placement each tile is an independently-sharded leaf XLA gathers one
at a time.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


class TiledLinear(nn.Module):
    """y = x @ W + b computed over an ``in_splits x out_splits`` tile grid.

    Matches ``nn.Dense(features)`` numerically; params live per-tile
    (``tile_i_j/kernel``), mirroring the reference's grid of sub-linears so
    each tile shards/gathers independently.
    """

    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    remat_each_tile: bool = True
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        if in_features % self.in_splits or self.features % self.out_splits:
            raise ValueError(
                f"tiling {self.in_splits}x{self.out_splits} must divide "
                f"({in_features}, {self.features})"
            )
        d_in = in_features // self.in_splits
        d_out = self.features // self.out_splits
        dtype = self.dtype or x.dtype

        outs = []
        for j in range(self.out_splits):
            acc = None
            for i in range(self.in_splits):
                w = self.param(
                    f"tile_{i}_{j}",
                    nn.initializers.lecun_normal(),
                    (d_in, d_out),
                )

                def tile(xs, ws):
                    return xs @ ws.astype(dtype)

                if self.remat_each_tile:
                    tile = jax.checkpoint(tile, prevent_cse=False)
                part = tile(x[..., i * d_in:(i + 1) * d_in], w)
                acc = part if acc is None else acc + part
            outs.append(acc)
        y = jnp.concatenate(outs, axis=-1)
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros, (self.features,))
            y = y + b.astype(dtype)
        return y
