"""deepspeed_tpu.linear: OptimizedLinear + LoRA (reference ``deepspeed/linear/``)."""

from deepspeed_tpu.linear.config import LoRAConfig, QuantizationConfig
from deepspeed_tpu.linear.optimized_linear import (
    LoRAOptimizedLinear,
    OptimizedLinear,
    lora_merge,
    lora_optimizer,
    lora_param_labels,
    lora_trainable_mask,
)
from deepspeed_tpu.linear.tiled_linear import TiledLinear
