"""LoRA / quantization configs (reference ``deepspeed/linear/config.py:13``)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class LoRAConfig:
    """Reference ``LoRAConfig`` linear/config.py:13."""

    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1  # kept for API parity; sharding is a mesh
    # property here (base weights follow the model's partition rules)

    @property
    def scaling(self) -> float:
        return self.lora_alpha / self.lora_r


@dataclass
class QuantizationConfig:
    """Reference ``QuantizationConfig`` linear/config.py — base-weight
    quantization for memory-frugal LoRA fine-tuning (QLoRA-style)."""

    q_bits: int = 8
    group_size: int = 512
    mantissa_bits: int = 3  # accepted for parity (fp quant variant)
