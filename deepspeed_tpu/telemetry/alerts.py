"""Declarative alert rules over the metrics registry and the event stream.

The incident plane's routing half (ISSUE 20): detectors *emit* typed events
(``telemetry/events.py``); this engine decides which conditions page
someone. Three rule kinds, all evaluated against process-local state on a
cadence (an injectable clock makes the state machine unit-testable):

  - **threshold** — a registry metric (every labelled child matching the
    base name, or one exact ``name{k="v"}`` child) compared against a bound
    (``> < >= <= ==``). Counters/gauges compare their value; histograms
    compare their observation count.
  - **absence** — liveness inverted: fires when the metric is MISSING from
    the registry or its value has not *changed* within ``window_s`` (a
    stalled step counter is the canonical page).
  - **event_rate** — at least ``value`` events matching
    (subsystem, kind, min severity) inside the trailing ``window_s``.

State machine per (rule, labelled child): inactive -> pending (condition
true, waiting out ``for_s``) -> firing -> resolved (condition clear for
``resolve_s`` — the flap damper; a clear shorter than that never resolves).
Re-fires inside ``refire_suppress_s`` of the previous notification keep the
state transition but suppress the notification (counted, never silent).

Firing/resolution notify the configured sinks and ALSO emit ``alerts/*``
events, so alerts federate to the collector and correlate into incidents
like any other detector output. The webhook sink does its HTTP on a daemon
worker thread with a bounded queue and never raises into the evaluation
path — the PR-13 ``push_async`` discipline.

``alerts/firing{rule=}`` gauges expose the live state to every scrape.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.telemetry.events import (
    Event,
    get_event_stream,
    severity_rank,
)
from deepspeed_tpu.telemetry.registry import decode_key, encode_labels
from deepspeed_tpu.utils.logging import logger

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    "<": lambda a, b: a < b,
    ">=": lambda a, b: a >= b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
}


@dataclass
class AlertRule:
    """One declarative rule. ``labels`` narrows threshold/absence matching
    to one labelled child (exact match); empty matches every child of the
    base name. Dedup identity is (rule name, matched child labels)."""

    name: str
    kind: str = "threshold"          # threshold | absence | event_rate
    severity: str = "warn"
    metric: Optional[str] = None     # threshold/absence: registry base name
    labels: Dict[str, str] = field(default_factory=dict)
    op: str = ">"
    value: float = 0.0               # threshold bound / event-rate count
    window_s: float = 60.0           # absence staleness / event-rate window
    for_s: float = 0.0               # condition must hold before firing
    resolve_s: float = 0.0           # condition must clear before resolving
    refire_suppress_s: float = 0.0   # notification dedup after a resolve
    subsystem: Optional[str] = None  # event_rate: event subsystem filter
    event_kind: Optional[str] = None  # event_rate: event kind filter
    min_severity: str = "warn"       # event_rate: severity floor
    summary: str = ""                # human template; {value} interpolates

    def __post_init__(self):
        if self.kind not in ("threshold", "absence", "event_rate"):
            raise ValueError(f"rule {self.name}: kind {self.kind!r}")
        if self.op not in _OPS:
            raise ValueError(f"rule {self.name}: op {self.op!r}")
        if self.kind in ("threshold", "absence") and not self.metric:
            raise ValueError(f"rule {self.name}: {self.kind} needs a metric")
        if self.kind == "event_rate" and not (self.subsystem or self.event_kind):
            raise ValueError(
                f"rule {self.name}: event_rate needs subsystem and/or kind")

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AlertRule":
        return cls(**{k: v for k, v in d.items()})


@dataclass
class _InstanceState:
    state: str = "inactive"          # inactive | pending | firing
    pending_since: float = 0.0
    firing_since: float = 0.0
    clear_since: Optional[float] = None
    last_value: float = 0.0
    last_notified: float = -1e18     # wall time of the last notification


# --------------------------------------------------------------------- sinks
class LogSink:
    """Notifications as log lines (warning on fire, info on resolve)."""

    name = "log"

    def notify(self, n: Dict[str, Any]) -> None:
        line = (f"[alerts] {n['state'].upper()} {n['rule']}"
                f"{n.get('labels_key', '')} value={n.get('value')}"
                f" severity={n['severity']}: {n.get('summary', '')}")
        (logger.warning if n["state"] == "firing" else logger.info)(line)


class JsonlSink:
    """Notifications appended to a JSONL file (post-mortem joins read it)."""

    name = "jsonl"

    def __init__(self, path: str):
        self.path = path

    def notify(self, n: Dict[str, Any]) -> None:
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(n) + "\n")


class WebhookSink:
    """POST each notification as JSON to a URL — on a daemon worker thread
    with a bounded queue, so a dead receiver can never block or raise into
    the evaluation path (the ``FleetClient.push_async`` discipline).
    Delivery failures are counted and warned once, never raised."""

    name = "webhook"

    def __init__(self, url: str, timeout: float = 2.0, queue_max: int = 64):
        self.url = url
        self.timeout = float(timeout)
        self.failures = 0
        self.delivered = 0
        self._queue: List[Dict[str, Any]] = []
        self._queue_max = int(queue_max)
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._worker: Optional[threading.Thread] = None
        self._stop = False
        self._warned = False

    def notify(self, n: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._queue) >= self._queue_max:
                self._queue.pop(0)  # oldest-out: latest state wins
            self._queue.append(n)
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._drain, name="alerts-webhook", daemon=True)
                self._worker.start()
            self._wake.notify()

    def _drain(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._stop:
                    self._wake.wait(timeout=1.0)
                if self._stop and not self._queue:
                    return
                n = self._queue.pop(0)
            try:
                self._post(n)
                with self._lock:
                    self.delivered += 1
            except Exception as e:  # noqa: BLE001 - sink never raises
                with self._lock:
                    self.failures += 1
                    warned, self._warned = self._warned, True
                if not warned:
                    logger.warning(
                        f"alerts: webhook {self.url} delivery failed ({e}); "
                        "further failures counted silently")

    def _post(self, n: Dict[str, Any]) -> None:
        import urllib.request

        body = json.dumps(n).encode("utf-8")
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            resp.read()

    def flush(self, timeout: float = 5.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                if not self._queue:
                    return
            time.sleep(0.01)

    def stop(self) -> None:
        with self._lock:
            self._stop = True
            self._wake.notify_all()


# -------------------------------------------------------------------- engine
class AlertEngine:
    """Evaluates rules on demand (:meth:`evaluate`) or on a daemon cadence
    (:meth:`start`). ``clock`` is injectable so tests drive the pending ->
    firing -> resolved machine with a fake clock."""

    def __init__(self, rules: Optional[List[AlertRule]] = None,
                 registry=None, stream=None,
                 sinks: Optional[List[Any]] = None,
                 clock: Callable[[], float] = time.time):
        self.rules: List[AlertRule] = list(rules or [])
        self._registry = registry
        self.stream = stream or get_event_stream()
        self.sinks: List[Any] = list(sinks) if sinks is not None else [LogSink()]
        self.clock = clock
        self._lock = threading.Lock()
        # (rule.name, labels_key) -> _InstanceState
        self._instances: Dict[tuple, _InstanceState] = {}
        # metric child key -> (last value, last change wall time) for absence
        self._last_changed: Dict[str, tuple] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self.evaluations = 0

    @property
    def registry(self):
        if self._registry is None:
            from deepspeed_tpu.telemetry.tracer import get_tracer

            self._registry = get_tracer().registry
        return self._registry

    def add_rule(self, rule: AlertRule) -> None:
        with self._lock:
            self.rules.append(rule)

    # ----------------------------------------------------------- conditions
    def _metric_children(self, rule: AlertRule) -> Dict[str, float]:
        """Current value of every registry child matching the rule's metric
        (counters + gauges by value, histograms by count), keyed by the
        encoded child key with the base name stripped."""
        want = rule.metric
        sel = encode_labels(rule.labels) if rule.labels else None
        out: Dict[str, float] = {}
        for kind, base, m in self.registry.iter_metrics():
            if base != want:
                continue
            child = encode_labels(m.labels)
            if sel is not None and child != sel:
                continue
            if kind == "histogram":
                out[child] = float(m.state()["count"])
            else:
                out[child] = float(m.value)
        return out

    def _condition_instances(self, rule: AlertRule, now: float,
                             ) -> Dict[str, tuple]:
        """labels_key -> (active, value) for every instance the rule
        currently addresses."""
        if rule.kind == "threshold":
            children = self._metric_children(rule)
            return {k: (_OPS[rule.op](v, rule.value), v)
                    for k, v in children.items()}
        if rule.kind == "absence":
            children = self._metric_children(rule)
            if not children:
                # missing entirely: one instance under the rule's own labels
                key = encode_labels(rule.labels)
                return {key: (True, float("nan"))}
            out = {}
            for k, v in children.items():
                full = (rule.metric or "") + k
                prev = self._last_changed.get(full)
                if prev is None or prev[0] != v:
                    self._last_changed[full] = (v, now)
                    out[k] = (False, v)
                else:
                    out[k] = (now - prev[1] >= rule.window_s, v)
            return out
        # event_rate
        floor = severity_rank(rule.min_severity)
        n = 0
        for ev in self.stream.events(since_ts=now - rule.window_s):
            if severity_rank(ev.severity) < floor:
                continue
            if rule.subsystem is not None and ev.subsystem != rule.subsystem:
                continue
            if rule.event_kind is not None and ev.kind != rule.event_kind:
                continue
            n += ev.count
        key = encode_labels(rule.labels)
        return {key: (_OPS[rule.op](float(n), rule.value), float(n))}

    # ----------------------------------------------------------- evaluation
    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation pass; returns the notifications produced (also
        delivered to every sink)."""
        now = self.clock() if now is None else float(now)
        notifications: List[Dict[str, Any]] = []
        with self._lock:
            rules = list(self.rules)
        for rule in rules:
            try:
                instances = self._condition_instances(rule, now)
            except Exception as e:  # noqa: BLE001 - a bad rule must not
                # take down the evaluation of every other rule
                self.registry.counter("alerts/rule_errors",
                                      rule=rule.name).add(1)
                logger.debug(f"alerts: rule {rule.name} errored: {e}")
                continue
            with self._lock:
                for labels_key, (active, value) in instances.items():
                    n = self._step_instance(rule, labels_key, active,
                                            value, now)
                    if n is not None:
                        notifications.append(n)
                firing = sum(
                    1 for (rn, _lk), st in self._instances.items()
                    if rn == rule.name and st.state == "firing")
            self.registry.gauge("alerts/firing", rule=rule.name).set(
                float(firing))
        self.evaluations += 1
        self.registry.counter("alerts/evaluations").add(1)
        for n in notifications:
            self._deliver(n)
        return notifications

    def _step_instance(self, rule: AlertRule, labels_key: str, active: bool,
                       value: float, now: float) -> Optional[Dict[str, Any]]:
        key = (rule.name, labels_key)
        st = self._instances.get(key)
        if st is None:
            st = self._instances[key] = _InstanceState()
        st.last_value = value
        if active:
            st.clear_since = None
            if st.state == "inactive":
                st.state = "pending"
                st.pending_since = now
            if st.state == "pending" and now - st.pending_since >= rule.for_s:
                st.state = "firing"
                st.firing_since = now
                return self._notification(rule, labels_key, st, "firing",
                                          value, now)
            return None
        # condition clear
        if st.state == "pending":
            st.state = "inactive"
            return None
        if st.state == "firing":
            if st.clear_since is None:
                st.clear_since = now
            if now - st.clear_since >= rule.resolve_s:
                st.state = "inactive"
                st.clear_since = None
                return self._notification(rule, labels_key, st, "resolved",
                                          value, now)
        return None

    def _notification(self, rule: AlertRule, labels_key: str,
                      st: _InstanceState, state: str, value: float,
                      now: float) -> Optional[Dict[str, Any]]:
        suppressed = (state == "firing"
                      and now - st.last_notified < rule.refire_suppress_s)
        if state == "firing":
            st.last_notified = now
        if suppressed:
            self.registry.counter("alerts/suppressed", rule=rule.name).add(1)
            return None
        self.registry.counter(
            "alerts/fired" if state == "firing" else "alerts/resolved",
            rule=rule.name).add(1)
        summary = rule.summary or f"{rule.kind} rule {rule.name}"
        try:
            summary = summary.format(value=value)
        except Exception:  # noqa: BLE001 - a bad template stays literal
            pass
        from deepspeed_tpu.telemetry.fleet import get_identity

        n = {
            "ts": now, "rule": rule.name, "state": state,
            "severity": rule.severity, "value": value,
            "labels_key": labels_key, "summary": summary,
            "identity": get_identity().to_dict(),
        }
        return n

    def _deliver(self, n: Dict[str, Any]) -> None:
        # alerts are events too: they federate + correlate like any detector
        labels = decode_key("x" + n["labels_key"])[1] if n["labels_key"] else {}
        labels["rule"] = n["rule"]
        self.stream.emit(
            "alerts", n["state"], n["summary"],
            severity=n["severity"] if n["state"] == "firing" else "info",
            labels=labels, ts=n["ts"])
        for sink in self.sinks:
            try:
                sink.notify(n)
            except Exception as e:  # noqa: BLE001 - PR-13 discipline: a sink
                # failure must never reach the caller (which may be a step)
                self.registry.counter(
                    "alerts/sink_failures",
                    sink=getattr(sink, "name", type(sink).__name__)).add(1)
                logger.debug(f"alerts: sink {sink!r} failed: {e}")

    # -------------------------------------------------------------- helpers
    def firing(self) -> List[Dict[str, Any]]:
        """Currently-firing instances (rule, labels, since, last value)."""
        with self._lock:
            return [
                {"rule": rn, "labels_key": lk, "since": st.firing_since,
                 "value": st.last_value}
                for (rn, lk), st in sorted(self._instances.items())
                if st.state == "firing"]

    def start(self, interval_s: float = 5.0) -> "AlertEngine":
        """Evaluate on a daemon cadence until :meth:`stop`."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop_evt.clear()

        def loop():
            while not self._stop_evt.wait(interval_s):
                try:
                    self.evaluate()
                except Exception as e:  # noqa: BLE001 - cadence survives
                    logger.debug(f"alerts: evaluation failed: {e}")

        self._thread = threading.Thread(
            target=loop, name="alerts-engine", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        for sink in self.sinks:
            stop = getattr(sink, "stop", None)
            if stop is not None:
                stop()


def default_rules() -> List[AlertRule]:
    """The stock rule pack covering the repo's detectors: quiet on a clean
    run (every threshold is on a *defect* counter that stays zero), loud on
    the faults the nightly injects."""
    return [
        AlertRule(name="numerics_divergence", metric="numerics/divergence_events",
                  op=">", value=0, severity="critical",
                  summary="cross-replica divergence events: {value}"),
        AlertRule(name="collective_drift", metric="coll/drift_events",
                  op=">", value=0, severity="warn",
                  summary="collective observed-vs-predicted drift events: {value}"),
        AlertRule(name="perf_regression", metric="perf/regression_events",
                  op=">", value=0, severity="warn",
                  summary="perf-gate regressions: {value}"),
        AlertRule(name="replica_dead", kind="event_rate", subsystem="fabric",
                  event_kind="replica_dead", window_s=300.0, op=">", value=0,
                  severity="critical",
                  summary="dead serving replicas detected: {value}"),
        AlertRule(name="replica_unreachable", kind="event_rate",
                  subsystem="fabric", event_kind="replica_unreachable",
                  window_s=300.0, op=">", value=0, severity="critical",
                  summary="unreachable serving replicas: {value}"),
        AlertRule(name="rpc_failures", kind="event_rate", subsystem="fabric",
                  event_kind="rpc_failure", window_s=300.0, op=">", value=2,
                  severity="warn",
                  summary="fabric RPC failures in window: {value}"),
        AlertRule(name="health_abort", kind="event_rate", subsystem="health",
                  event_kind="abort", window_s=600.0, op=">", value=0,
                  severity="critical",
                  summary="training health abort: {value}"),
        AlertRule(name="recompile_storm", kind="event_rate",
                  subsystem="recompile", event_kind="storm", window_s=600.0,
                  op=">", value=0, severity="warn",
                  summary="recompile storms: {value}"),
    ]


# ----------------------------------------------------------- process-global
_engine: Optional[AlertEngine] = None
_engine_lock = threading.Lock()


def get_alert_engine() -> AlertEngine:
    """The process-global engine (created empty — rules come from config or
    :func:`default_rules`)."""
    global _engine
    if _engine is None:
        with _engine_lock:
            if _engine is None:
                _engine = AlertEngine(rules=[])
    return _engine


def configure_alerts(rules: Optional[List[Any]] = None,
                     use_defaults: bool = True,
                     jsonl_path: Optional[str] = None,
                     webhook_url: Optional[str] = None,
                     interval_s: Optional[float] = None) -> AlertEngine:
    """(Re)configure the process-global engine: replace the rule set
    (dicts are parsed via :meth:`AlertRule.from_dict`), rebuild sinks, and
    (when ``interval_s`` is set) start the cadence thread."""
    eng = get_alert_engine()
    new_rules: List[AlertRule] = list(default_rules()) if use_defaults else []
    for r in rules or []:
        new_rules.append(r if isinstance(r, AlertRule)
                         else AlertRule.from_dict(r))
    sinks: List[Any] = [LogSink()]
    if jsonl_path:
        sinks.append(JsonlSink(jsonl_path))
    if webhook_url:
        sinks.append(WebhookSink(webhook_url))
    with eng._lock:
        eng.rules = new_rules
        eng._instances.clear()
    eng.sinks = sinks
    if interval_s is not None and interval_s > 0:
        eng.start(interval_s)
    return eng
