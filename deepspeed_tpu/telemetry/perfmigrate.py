"""Legacy perf-artifact migration into the unified ledger (schema v1).

Every pre-ledger round left a root-level JSON artifact with its own shape:

  - ``BENCH_rNN.json``    — headline wrapper ``{n, cmd, rc, tail, parsed}``
  - ``MULTICHIP_rNN.json``— mesh dryrun ``{n_devices, rc, ok, skipped, tail}``
  - ``SERVING_rNN.json``  — nested numeric tree (allocator/assembly/host_path/
                            end_to_end, later slo/kv_capacity/disagg)
  - ``COLL_r11.json`` / ``FLEET_r13.json`` — worst-of-three paired-step extras
  - ``COMPILE_r09.json`` / ``ELASTIC_r08.json`` — 3x paired-step run lists
  - ``MOE_r15.json``      — smoke verdict + loss curve

This module turns each family into schema-v1 rows **losslessly for every
numeric leaf** (strings/bools/nulls are verdicts or provenance, not
measurements; ``rc`` is an exit code): the metric name is the
slash-joined path to the leaf, so a value in the ledger can always be
found again in the original artifact. Originals stay in place — the
ledger is derived state, the artifact is the evidence.

Migration is idempotent (append only rows whose identity is not in the
ledger yet) and ``check()`` verifies the committed ledger still contains
every row a fresh migration would produce — the nightly's migrate-check
stage fails if an artifact and the ledger drift apart.

The generic tree flattener + direction/unit heuristics here are also the
live-emission path for ``tools/bench_serving.py`` (same tree in, same
rows out — a serving number migrated from r12 and one emitted at r16 are
directly comparable).

All legacy rounds ran on the CPU container, so every migrated row is
stamped ``backend=cpu``; ``time_unix`` is fixed at 0.0 (file mtimes are
checkout-volatile and would break idempotence), ``run_id`` is ``legacy``.
"""

from __future__ import annotations

import fnmatch
import json
import os
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.telemetry.perfledger import (
    PerfLedger, SCHEMA_VERSION, row_identity, validate_row,
)

LEGACY_RUN_ID = "legacy"
LEGACY_BACKEND = "cpu"

# numeric leaves under these keys are exit codes / dup round counters,
# not measurements
_SKIP_KEYS = frozenset({"rc", "n"})

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


# --------------------------------------------------------------- heuristics
# ordered: the first higher-marker match wins before any lower-marker is
# consulted (e.g. "tpot_p99_improvement" is higher-better despite "p99")
_HIGHER_MARKERS = (
    "per_sec", "speedup", "goodput", "gbps", "tokens_per_sec", "mfu",
    "capacity_gain", "improvement", "slo_met", "hit_rate", "learns",
)
_LOWER_MARKERS = (
    "_ms", "_us", "ms_per", "us_per", "latency", "wait", "overhead",
    "failures", "shed", "preempt", "missed", "_err", "syncs_per",
    "programs_per", "queue", "loss", "_pct", "bytes_per_token", "stall",
)


def direction_for(metric: str) -> str:
    m = metric.lower()
    if any(h in m for h in _HIGHER_MARKERS):
        return "higher"
    if any(lo in m for lo in _LOWER_MARKERS) or m.endswith(("_ms", "_us", "_s")):
        return "lower"
    return "higher"


def unit_for(metric: str) -> str:
    m = metric.lower()
    if "tokens_per_sec" in m:
        return "tokens/s"
    if "per_sec" in m:
        return "1/s"
    if "gbps" in m:
        return "GB/s"
    if "_pct" in m or m.endswith("pct"):
        return "%"
    if any(x in m for x in ("speedup", "ratio", "gain", "vs_baseline",
                            "improvement", "rel_err")):
        return "ratio"
    if "_ms" in m or m.endswith("_ms"):
        return "ms"
    if "_us" in m or m.endswith("_us"):
        return "us"
    if "bytes" in m:
        return "bytes"
    if "flops" in m:
        return "flops"
    if "goodput" in m or "hit_rate" in m:
        return "fraction"
    if m.endswith(("_s", "wall_s")):
        return "s"
    if "loss" in m:
        return "nats"
    return "count"


def method_for_metric(metric: str, default: str = "single") -> str:
    """Percentile rows carry their percentile as the method stamp."""
    tail = metric.rsplit("/", 1)[-1]
    if tail in ("p50", "p95", "p99"):
        return tail
    return default


def flatten_numeric(obj: Any, prefix: str = "") -> List[Tuple[str, float]]:
    """Every numeric leaf of a JSON tree as (slash-path, value). Bools,
    strings and nulls are skipped (verdicts/provenance); list elements are
    indexed path segments so e.g. a loss curve stays ordered."""
    out: List[Tuple[str, float]] = []
    if isinstance(obj, dict):
        for k, v in obj.items():
            if k in _SKIP_KEYS and not prefix:
                continue
            out.extend(flatten_numeric(v, f"{prefix}/{k}" if prefix else str(k)))
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            out.extend(flatten_numeric(v, f"{prefix}/{i}" if prefix else str(i)))
    elif isinstance(obj, bool) or obj is None:
        pass
    elif isinstance(obj, (int, float)):
        out.append((prefix, float(obj)))
    return out


def rows_from_tree(suite: str, payload: Dict[str, Any], *, round: int,
                   backend: str = LEGACY_BACKEND, run_id: str = LEGACY_RUN_ID,
                   git_sha: str = "", method: str = "single",
                   samples: int = 1, time_unix: float = 0.0,
                   ) -> List[Dict[str, Any]]:
    """Generic tree -> rows: the shared path for migration AND live serving
    emission. Percentile leaves override ``method``; everything else takes
    the family default."""
    rows = []
    for metric, value in flatten_numeric(payload):
        rows.append(validate_row({
            "schema": SCHEMA_VERSION, "run_id": run_id, "git_sha": git_sha,
            "round": int(round), "backend": backend, "suite": suite,
            "metric": metric, "value": value, "unit": unit_for(metric),
            "direction": direction_for(metric),
            "method": method_for_metric(metric, method),
            "samples": int(samples), "time_unix": float(time_unix),
        }))
    return rows


# ----------------------------------------------------------------- families
def _rows_bench(payload: Dict[str, Any], round: int) -> List[Dict[str, Any]]:
    """BENCH wrapper: only ``parsed`` holds measurements — the headline
    metric under its own name plus its vs_baseline ratio."""
    parsed = payload.get("parsed") or {}
    if "metric" not in parsed:
        return []
    rows = [{
        "schema": SCHEMA_VERSION, "run_id": LEGACY_RUN_ID, "git_sha": "",
        "round": round, "backend": LEGACY_BACKEND, "suite": "bench",
        "metric": str(parsed["metric"]), "value": float(parsed["value"]),
        "unit": str(parsed.get("unit", "tokens/s")), "direction": "higher",
        "method": "single", "samples": 1, "time_unix": 0.0,
    }]
    if "vs_baseline" in parsed:
        rows.append({
            "schema": SCHEMA_VERSION, "run_id": LEGACY_RUN_ID, "git_sha": "",
            "round": round, "backend": LEGACY_BACKEND, "suite": "bench",
            "metric": f"{parsed['metric']}/vs_baseline",
            "value": float(parsed["vs_baseline"]), "unit": "ratio",
            "direction": "higher", "method": "single", "samples": 1,
            "time_unix": 0.0,
        })
    return [validate_row(r) for r in rows]


def _family_samples(payload: Dict[str, Any]) -> int:
    runs = payload.get("runs")
    return len(runs) if isinstance(runs, list) and runs else 1


def _make_tree_loader(suite: str, method: str) -> Callable:
    def load(payload: Dict[str, Any], round: int) -> List[Dict[str, Any]]:
        samples = _family_samples(payload)
        rows = rows_from_tree(suite, payload, round=round, method=method,
                              samples=samples)
        # per-run sub-rows are individual observations, not aggregates
        for r in rows:
            if r["metric"].startswith("runs/"):
                r["samples"] = 1
        return rows
    return load


def _policy_method(payload: Dict[str, Any], default: str) -> str:
    policy = str(payload.get("policy", ""))
    return policy.replace("_", "-") if policy else default


def _rows_policy_family(suite: str):
    """COLL/FLEET extras carry their discipline in a ``policy`` field
    (``worst_of_three``) — that, not a family constant, is the method."""
    def load(payload: Dict[str, Any], round: int) -> List[Dict[str, Any]]:
        method = _policy_method(payload, "paired")
        return _make_tree_loader(suite, method)(payload, round)
    return load


#: (glob, suite, loader(payload, round) -> rows) — the closed list of
#: legacy families; later native-ledger artifacts (PERF_r16+) never
#: migrate, they emit rows directly.
FAMILIES: List[Tuple[str, str, Callable]] = [
    ("BENCH_r*.json", "bench", _rows_bench),
    ("MULTICHIP_r*.json", "multichip", _make_tree_loader("multichip", "single")),
    ("SERVING_r*.json", "serving", _make_tree_loader("serving", "single")),
    ("COLL_r*.json", "coll", _rows_policy_family("coll")),
    ("FLEET_r*.json", "fleet", _rows_policy_family("fleet")),
    ("COMPILE_r*.json", "compile", _make_tree_loader("compile", "paired")),
    ("ELASTIC_r*.json", "elastic", _make_tree_loader("elastic", "paired")),
    ("MOE_r*.json", "moe", _make_tree_loader("moe", "single")),
]


def round_from_filename(name: str) -> Optional[int]:
    m = _ROUND_RE.search(name)
    return int(m.group(1)) if m else None


def legacy_rows(repo_root: str) -> List[Dict[str, Any]]:
    """All schema-v1 rows a fresh migration of ``repo_root``'s legacy
    artifacts produces, deterministically ordered."""
    rows: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(repo_root))
    except OSError:
        return rows
    for glob, _suite, loader in FAMILIES:
        for name in names:
            if not fnmatch.fnmatch(name, glob):
                continue
            rnd = round_from_filename(name)
            if rnd is None:
                continue
            with open(os.path.join(repo_root, name), encoding="utf-8") as f:
                payload = json.load(f)
            rows.extend(loader(payload, rnd))
    return rows


def migrate(repo_root: str, ledger: PerfLedger) -> Dict[str, int]:
    """Idempotent: append only rows not already in the ledger (by
    measurement identity). Returns ``{"found": N, "appended": M}``."""
    fresh = legacy_rows(repo_root)
    have = ledger.identities()
    new = [r for r in fresh if row_identity(r) not in have]
    ledger.append(new)
    return {"found": len(fresh), "appended": len(new)}


def check(repo_root: str, ledger: PerfLedger) -> List[Dict[str, Any]]:
    """Rows a fresh migration would produce that the ledger is missing
    (subset check — live rows appended since migration are fine). Empty
    list == the committed ledger still covers every legacy artifact."""
    have = ledger.identities()
    return [r for r in legacy_rows(repo_root) if row_identity(r) not in have]
