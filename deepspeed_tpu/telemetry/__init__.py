"""deepspeed_tpu.telemetry: unified observability substrate.

One process-global ``Tracer`` (nestable wall-clock spans, bounded buffer)
plus a shared ``MetricsRegistry`` (labelled counters/gauges + log-bucketed
quantile histograms), two trace exporters (Chrome trace-event JSON for
Perfetto, JSONL for tooling), and a metrics exposition layer
(``exposition.py``: Prometheus text format, JSON snapshot, opt-in stdlib
``/metrics`` HTTP endpoint).

Wired into:
  - ``runtime/engine.py``   — train_batch/data/step + fwd/bwd/step parity
    phases, per-step monitor scalars, device-memory watermarks
  - ``comm/comm.py``        — every facade collective as a trace-time span
    tagged with op/axis/dtype/payload bytes/participant count, plus
    ``comm/bytes`` + ``comm/count`` counters
  - ``checkpoint/``         — save/load spans
  - ``runtime/dataloader.py`` — batch materialization spans

Enable via the ``telemetry`` config block (see ``config/config.py``) or the
``DSTPU_TELEMETRY=1`` env var; export dir defaults to ``DSTPU_TELEMETRY_DIR``
(else ``./telemetry_out``). Disabled (the default) every hook is a single
attribute check — zero measurable overhead. See ``docs/telemetry.md``.

What WATCHES these streams lives in ``deepspeed_tpu/diagnostics`` (health
probes, recompile detection, step-time anomaly flags, crash flight recorder)
— it shares this registry, so its ``health/``, ``recompile/``, ``anomaly/``,
and ``flops/`` metrics ride the same monitor/export paths. See
``docs/diagnostics.md``.

The FLEET plane (``fleet.py`` + ``collector.py``) lifts all of this across
process boundaries: a ``ProcessIdentity`` stamped on every artifact,
bit-exact metric federation into a ``FleetCollector`` (counters sum,
log-bucket histograms merge bucket-wise, gauges keep last-per-process
under ``{proc=}``), cross-process trace contexts whose flow arrows join
in ``tools/trace_merge.py``, and a cluster health ledger of per-process
heartbeats. See docs/telemetry.md "Fleet telemetry".

The INCIDENT plane (``events.py`` + ``alerts.py`` + the collector's
``/events``, ``/incidents``, ``/console`` routes) types the warnings:
every detector also emits a structured :class:`Event` onto a bounded
process-local stream, a declarative :class:`AlertEngine` evaluates
threshold/absence/event-rate rules over the registry + stream with a
pending→firing→resolved state machine, and the collector correlates
shipped events into cross-process incidents. See docs/telemetry.md
"Events, alerts, incidents".
"""

from deepspeed_tpu.telemetry.alerts import (
    AlertEngine,
    AlertRule,
    configure_alerts,
    default_rules,
    get_alert_engine,
)
from deepspeed_tpu.telemetry.events import (
    Event,
    EventStream,
    WarnOnceSet,
    configure_events,
    emit_event,
    get_event_stream,
    warn_once,
)
from deepspeed_tpu.telemetry.exporters import (
    chrome_trace_events,
    default_output_dir,
    export_chrome_trace,
    export_jsonl,
)
from deepspeed_tpu.telemetry.exposition import (
    MetricsServer,
    export_json_snapshot,
    export_prometheus,
    render_json_snapshot,
    render_prometheus,
    serve_metrics,
)
from deepspeed_tpu.telemetry.fleet import (
    ProcessIdentity,
    TraceContext,
    configure_identity,
    get_identity,
)
from deepspeed_tpu.telemetry.perfledger import (
    PerfLedger,
    make_row,
)
from deepspeed_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from deepspeed_tpu.telemetry.tracer import (
    NOOP_SPAN,
    Tracer,
    configure,
    enabled,
    env_enabled,
    get_tracer,
    span,
)

__all__ = [
    "AlertEngine",
    "AlertRule",
    "Counter",
    "Event",
    "EventStream",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsServer",
    "NOOP_SPAN",
    "PerfLedger",
    "ProcessIdentity",
    "TraceContext",
    "Tracer",
    "WarnOnceSet",
    "chrome_trace_events",
    "configure",
    "configure_alerts",
    "configure_events",
    "configure_identity",
    "default_output_dir",
    "default_rules",
    "emit_event",
    "enabled",
    "env_enabled",
    "export_chrome_trace",
    "export_json_snapshot",
    "export_jsonl",
    "export_prometheus",
    "get_alert_engine",
    "get_event_stream",
    "get_identity",
    "get_tracer",
    "make_row",
    "render_json_snapshot",
    "render_prometheus",
    "serve_metrics",
    "span",
    "warn_once",
]
