"""Fleet telemetry primitives: process identity, mergeable registry dumps,
and cross-process trace context.

Everything the telemetry stack built so far is process-local — one registry,
one tracer with a private ``perf_counter`` origin, one ``/metrics`` port.
This module is the layer that lets N such processes read as ONE system:

  - :class:`ProcessIdentity` — (run_id, process_index, host, role) stamped
    onto every registry exposition, tracer stream, flight-recorder dump and
    observatory table row, so artifacts from different processes can be
    joined after the fact. Process-global like the tracer
    (:func:`get_identity` / :func:`configure_identity`); defaults come from
    ``DSTPU_RUN_ID`` / ``DSTPU_PROCESS_INDEX`` / ``DSTPU_ROLE`` (the
    launcher's contract), then ``jax.process_index()``, then 0.
  - :func:`registry_dump` / :func:`merge_dump_into` — the wire format and
    merge rules for metric federation (``telemetry/collector.py``). The
    merge is exact by construction: counters SUM, the log-bucket histograms
    merge bucket-wise (``Histogram.merge_state`` — a sample lands in the
    same bucket no matter which process observed it, so merging K sharded
    registries equals observing the concatenated stream), and gauges —
    which have no meaningful cross-process fold — keep last-per-process
    under a ``{proc=}`` label.
  - :class:`TraceContext` — the request-scoped context a router propagates
    to a replica across a process boundary. Both sides derive the SAME
    Chrome flow id from (run_id, request_id), so the admission flow arrow
    emitted in the router process and the ``serve:dispatch`` flow step
    emitted in the replica process bind into one arrow once
    ``tools/trace_merge.py`` joins the per-process streams.
  - :func:`note_step` / :func:`last_step_info` — the per-process liveness
    breadcrumb ``/healthz`` and fleet heartbeats report (last step + age)
    without parsing the full exposition.

See docs/telemetry.md "Fleet telemetry".
"""

from __future__ import annotations

import dataclasses
import os
import socket
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.telemetry.registry import (
    MetricsRegistry,
    decode_key,
    encode_labels,
)

# roles a process can declare; free-form strings are accepted (the ledger
# just displays them) but these are the ones the runtime stamps itself.
# "prefill"/"decode" are the disaggregated serving pools (ISSUE 14): a
# phase-specialized replica process exports its role so the collector's
# per-role rollups and the merged traces read the topology directly.
ROLES = ("train", "router", "replica", "prefill", "decode", "collector",
         "worker")


@dataclasses.dataclass
class ProcessIdentity:
    """Who a telemetry stream came from — the join key for every
    cross-process artifact (dumps, tables, traces, ledger rows)."""

    run_id: str
    process_index: int = 0
    host: str = ""
    role: str = "train"
    pid: int = 0

    @property
    def proc(self) -> str:
        """The short ``{proc=}`` label value: ``p<index>``."""
        return f"p{self.process_index}"

    def key(self) -> str:
        """Ledger/collector identity key — unique per fleet member."""
        return f"{self.run_id}/{self.proc}"

    def labels(self) -> Dict[str, str]:
        return {"run_id": self.run_id, "proc": self.proc,
                "host": self.host, "role": self.role}

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ProcessIdentity":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


_lock = threading.Lock()
_identity: Optional[ProcessIdentity] = None
# (step, wall-clock stamp) of the most recent note_step — /healthz liveness
_last_step: Optional[Tuple[int, float]] = None


def _default_run_id() -> str:
    """A run id every process of one launch shares: the launcher exports
    ``DSTPU_RUN_ID``; a standalone process mints one from its start time +
    pid (unique enough to join its own artifacts, and visibly NOT shared
    with anything else)."""
    env = os.environ.get("DSTPU_RUN_ID")
    if env:
        return env
    return f"r{int(time.time()):x}-{os.getpid():x}"


def _default_process_index() -> int:
    env = os.environ.get("DSTPU_PROCESS_INDEX")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    try:  # multi-host jax runtimes know their index; CPU tests get 0
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 - backendless/early import
        return 0


def get_identity() -> ProcessIdentity:
    """The process-global identity (lazily built from the environment)."""
    global _identity
    with _lock:
        if _identity is None:
            _identity = ProcessIdentity(
                run_id=_default_run_id(),
                process_index=_default_process_index(),
                host=socket.gethostname(),
                role=os.environ.get("DSTPU_ROLE", "train"),
                pid=os.getpid(),
            )
        return _identity


def configure_identity(run_id: Optional[str] = None,
                       process_index: Optional[int] = None,
                       host: Optional[str] = None,
                       role: Optional[str] = None) -> ProcessIdentity:
    """Override identity fields (process-global, like ``telemetry.configure``).
    Unset fields keep their current/default resolution."""
    global _identity
    ident = get_identity()
    with _lock:
        if run_id is not None:
            ident.run_id = str(run_id)
        if process_index is not None:
            ident.process_index = int(process_index)
        if host is not None:
            ident.host = str(host)
        if role is not None:
            ident.role = str(role)
        return ident


def reset_identity() -> None:
    """Drop the cached identity (tests; env changes re-resolve lazily)."""
    global _identity, _last_step
    with _lock:
        _identity = None
        _last_step = None


def note_step(step: int) -> None:
    """Record that optimizer/serving step ``step`` just completed — two
    writes, no lock (a torn read across the tuple swap is harmless), cheap
    enough for the unconditional per-step call in the engines."""
    global _last_step
    _last_step = (int(step), time.time())


def last_step_info(now: Optional[float] = None) -> Dict[str, Any]:
    """``{"step", "age_s"}`` of the most recent :func:`note_step`, or
    ``{"step": None, "age_s": None}`` before any step ran — what /healthz
    and fleet heartbeats report as the liveness signal."""
    snap = _last_step
    if snap is None:
        return {"step": None, "age_s": None}
    step, t = snap
    return {"step": step, "age_s": round((now or time.time()) - t, 3)}


# --------------------------------------------------------------- federation
def registry_dump(registry=None, identity: Optional[ProcessIdentity] = None
                  ) -> Dict[str, Any]:
    """The mergeable wire snapshot of one process's registry: counters and
    gauges by flat key, histograms with their RAW sparse buckets
    (``Histogram.state`` — ``summary()`` drops exactly the piece a
    bit-exact merge needs). Served at ``GET /metrics.fleet`` and pushed to
    the collector; :func:`merge_dump_into` is the consuming half."""
    if registry is None:
        from deepspeed_tpu.telemetry.tracer import get_tracer

        registry = get_tracer().registry
    ident = identity or get_identity()
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    for kind, _base, metric in registry.iter_metrics():
        key = metric.name + encode_labels(metric.labels)
        if kind == "counter":
            counters[key] = metric.value
        elif kind == "gauge":
            gauges[key] = metric.value
        else:
            hists[key] = metric.state()
    return {
        "identity": ident.to_dict(),
        "time_unix": time.time(),
        "counters": counters,
        "gauges": gauges,
        "histograms": hists,
    }


def merge_dump_into(registry: MetricsRegistry, dump: Dict[str, Any],
                    proc_label: Optional[str] = None) -> None:
    """Fold one process's :func:`registry_dump` into a federated registry.

    Merge rules (pinned by the property test in tests/unit/test_fleet.py):
      - counters SUM: ``c.add(value)`` per dump, so folding the per-process
        cumulative values yields exactly their arithmetic sum;
      - histograms merge BUCKET-WISE (``merge_state``) — bit-identical to
        observing the concatenated sample stream;
      - gauges have no cross-process fold: each lands under its own
        ``{proc=}`` label (last-write-wins per process), so the federated
        view keeps every process's latest sample side by side.

    ``proc_label`` overrides the gauge label (default: the identity's
    short ``p<index>``) — the collector passes the run_id-qualified key
    when two fleet members share a process index, so their gauges never
    clobber each other."""
    ident = ProcessIdentity.from_dict(dump.get("identity") or {"run_id": "?"})
    proc = proc_label if proc_label is not None else ident.proc
    for key, value in (dump.get("counters") or {}).items():
        name, labels = decode_key(key)
        registry.counter(name, **labels).add(float(value))
    for key, value in (dump.get("gauges") or {}).items():
        name, labels = decode_key(key)
        labels["proc"] = proc
        registry.gauge(name, **labels).set(float(value))
    for key, state in (dump.get("histograms") or {}).items():
        name, labels = decode_key(key)
        registry.histogram(name, **labels).merge_state(state)


# ------------------------------------------------------------ trace context
def flow_id_for(run_id: str, request_id: int) -> int:
    """Stable 63-bit Chrome flow id both sides of a process boundary can
    derive independently from (run_id, request_id) — crc32 over each half,
    concatenated. Collision across requests of one trace is what matters;
    2^63 over a few thousand in-flight requests is comfortably unique."""
    hi = zlib.crc32(run_id.encode()) & 0x7FFF_FFFF
    lo = zlib.crc32(str(int(request_id)).encode()) & 0xFFFF_FFFF
    return (hi << 32) | lo


@dataclasses.dataclass
class TraceContext:
    """What a dispatch carries across a process boundary: enough for the
    receiver to emit spans/flow steps that join the sender's trace. The
    wire form is a plain dict (header-shaped — an HTTP/RPC transport can
    carry it verbatim)."""

    run_id: str
    request_id: int
    flow_id: int

    @property
    def flow_name(self) -> str:
        """The ONE spelling of the flow-event name for this context.
        Chrome binds flow events on (cat, name, id) — both sides of the
        process boundary must emit this exact name or the merged trace
        draws no arrow."""
        return f"req-{self.request_id}"

    @classmethod
    def mint(cls, request_id: int, run_id: Optional[str] = None
             ) -> "TraceContext":
        rid = run_id if run_id is not None else get_identity().run_id
        return cls(run_id=rid, request_id=int(request_id),
                   flow_id=flow_id_for(rid, int(request_id)))

    def to_wire(self) -> Dict[str, Any]:
        return {"run_id": self.run_id, "request_id": self.request_id,
                "flow_id": self.flow_id}

    @classmethod
    def from_wire(cls, d: Dict[str, Any]) -> "TraceContext":
        rid = str(d["run_id"])
        req = int(d["request_id"])
        return cls(run_id=rid, request_id=req,
                   flow_id=int(d.get("flow_id", flow_id_for(rid, req))))


class _DispatchSpan:
    """Span + in-span flow step for a received cross-process dispatch."""

    def __init__(self, tracer, ctx: TraceContext, name: str, args: Dict):
        self._tracer = tracer
        self._ctx = ctx
        self._name = name
        self._args = args
        self._span = None

    def __enter__(self):
        self._span = self._tracer.span(self._name, cat="serve", **self._args)
        self._span.__enter__()
        # the flow STEP lands inside the open span, so the merged trace's
        # arrow terminates on this slice (Chrome binds a flow event to its
        # enclosing slice)
        self._tracer.flow(self._ctx.flow_name, self._ctx.flow_id, "step")
        return self._span

    def __exit__(self, *exc):
        return self._span.__exit__(*exc)


def dispatch_span(ctx: TraceContext, name: str = "serve:dispatch",
                  tracer=None, **args: Any):
    """Context manager a replica wraps around serving a remotely-dispatched
    request: opens a ``serve:dispatch`` span and emits a flow step with the
    context's flow id INSIDE it, so the router process's admission arrow
    lands on this process's dispatch slice in the merged trace."""
    if tracer is None:
        from deepspeed_tpu.telemetry.tracer import get_tracer

        tracer = get_tracer()
    if not tracer.enabled:
        from deepspeed_tpu.telemetry.tracer import NOOP_SPAN

        return NOOP_SPAN
    return _DispatchSpan(tracer, ctx, name,
                         dict(args, request_id=ctx.request_id))


def clock_sync_doc() -> Dict[str, float]:
    """The clock-handshake payload a process sends at collector
    registration: its wall clock now and its tracer's origin as wall time.
    The collector computes ``clock_offset_s = recv_wall - time_unix``
    (one-way, so it includes network latency — honest to within the
    localhost/LAN RTT this targets); ``origin_unix`` is what the trace
    merger uses to place this process's events on the shared timeline."""
    from deepspeed_tpu.telemetry.tracer import get_tracer

    return {"time_unix": time.time(),
            "origin_unix": get_tracer().origin_unix()}


def fleet_rollups(registry: MetricsRegistry,
                  heartbeats: Optional[Dict[str, Dict[str, Any]]] = None,
                  straggler_mads: float = 6.0,
                  roles: Optional[Dict[str, str]] = None) -> None:
    """Compute the ``fleet/*`` rollup series into a federated registry:

      fleet/goodput        summed slo_met / (slo_met + slo_missed) counters
      fleet/tokens_per_s   sum of every process's serving/tokens_per_s gauge
      fleet/step_rate_min  slowest process's heartbeat step rate
      fleet/straggler{proc=} cross-process median+MAD verdict per process
                             (the PR-2 in-process detector's math, lifted)

    ``heartbeats`` maps proc label -> latest heartbeat dict (collector
    state); step-rate rollups are skipped without it. ``roles`` maps proc
    label -> declared role: when given, the disagg topology (ISSUE 14)
    gets per-role rollups — ``fleet/tokens_per_s{role=}`` (summed over the
    role's processes) and ``fleet/step_rate_min{role=}`` — so a dashboard
    reads the prefill pool and the decode pool as two series without
    re-deriving membership. ``fleet/processes`` is NOT set here: its one
    definition (all registered members, heartbeat or not) belongs to the
    collector, which knows the membership."""
    met = missed = 0.0
    tps = 0.0
    saw_tps = False
    role_tps: Dict[str, float] = {}
    roles = roles or {}
    for kind, name, metric in registry.iter_metrics():
        if kind == "counter" and name == "serving/slo_met":
            met += metric.value
        elif kind == "counter" and name == "serving/slo_missed":
            missed += metric.value
        elif kind == "gauge" and name == "serving/tokens_per_s":
            tps += metric.value
            saw_tps = True
            role = roles.get(metric.labels.get("proc", ""))
            if role is not None:
                role_tps[role] = role_tps.get(role, 0.0) + metric.value
    if met + missed > 0:
        registry.gauge("fleet/goodput").set(met / (met + missed))
    if saw_tps:
        # a summed rate of 0 during a fleet-wide stall is exactly when the
        # series matters — report 0, never drop it (an == 0 alert must fire)
        registry.gauge("fleet/tokens_per_s").set(tps)
    for role, v in role_tps.items():
        registry.gauge("fleet/tokens_per_s", role=role).set(v)
    if not heartbeats:
        return
    rates = {p: float(hb["step_rate"]) for p, hb in heartbeats.items()
             if hb.get("step_rate") is not None}
    if rates:
        registry.gauge("fleet/step_rate_min").set(min(rates.values()))
        role_rates: Dict[str, list] = {}
        for p, v in rates.items():
            role = roles.get(p)
            if role is not None:
                role_rates.setdefault(role, []).append(v)
        for role, vals in role_rates.items():
            registry.gauge("fleet/step_rate_min", role=role).set(min(vals))
    # same threshold the caller's ledger uses — the Prometheus gauge and
    # GET /fleet must never disagree on who is straggling
    for proc, flagged in straggler_flags(rates, mads=straggler_mads).items():
        registry.gauge("fleet/straggler", proc=proc).set(float(flagged))


def straggler_flags(rates: Dict[str, float], mads: float = 6.0
                    ) -> Dict[str, bool]:
    """Cross-process straggler verdicts over per-process step RATES: the
    diagnostics median+MAD discipline (``diagnostics/anomaly.py``) applied
    across the fleet instead of across a window — a process whose rate
    falls below ``median - mads * MAD`` is flagged. Same MAD floor as the
    in-process detector so identical healthy rates never flag on jitter."""
    if len(rates) < 3:  # median+MAD needs a quorum to mean anything
        return {p: False for p in rates}
    import statistics

    vals = list(rates.values())
    med = statistics.median(vals)
    mad = statistics.median(abs(v - med) for v in vals)
    mad = max(mad, 0.01 * abs(med), 1e-6)
    return {p: v < med - mads * mad for p, v in rates.items()}
