"""Numerics observatory: live quantization-fidelity and replica-integrity.

The system runs lossy numerics on nearly every wire — int8/fp8 wire codecs,
LoCo error feedback in the ZeRO++ gathers, quantized KV / weight-only-quant
serving, the MoE int8 dispatch wire, n-gram speculative decode — yet until
this module the only evidence any of it stayed accurate was a fixed bound in
a one-off test. The performance observatory (``collectives/observatory.py``
+ the perf ledger/gate) closed the *performance* feedback loop; this module
closes the *correctness* one. Three planes, all riding the same sampled,
jaxpr-identical-when-off discipline:

1. **Wire-fidelity probes** — routed lossy collectives register their
   ``(op, codec, algorithm, backend)`` signature at trace time (one call
   from ``comm._observe_route``); on sampled steps the observatory re-runs
   each codec's encode→decode against a deterministic payload of the routed
   shape and publishes ``numerics/wire_rel_err{op,codec,algorithm,backend}``
   histograms. Error beyond ``drift_ratio ×`` the codec's pinned bound
   (:data:`WIRE_REL_ERR_BOUNDS`, the same numbers the codec tests pin)
   warns once, bumps ``numerics/wire_drift_events``, and arms the PR-7
   profiler capture so the offending step window leaves a trace.

2. **Cross-replica divergence sentinel** (:class:`DivergenceSentinel`) —
   a cheap per-leaf-group digest (sum-of-squares + bit-level xor checksum)
   computed *inside* the jitted train step on sampled steps, carried in
   ``TrainState.numerics`` exactly like the PR-2 ``health`` field. Each
   leaf's digest is compared across the mesh axes the leaf is *replicated*
   over via ``pmin``/``pmax``: physically divergent dp/fsdp replicas make
   min != max and latch a ``numerics/divergence_events`` counter in the
   carried state (host sampling can therefore never miss a detection).
   The xor checksum folds across sharded axes with ``all_gather``+xor —
   order-independent and exact, so the whole-tree checksum is bit-stable
   across mesh shapes and rides the PR-13 fleet heartbeats as the
   cross-process comparator. Policy ``log`` | ``abort`` (the abort raises
   ``diagnostics.manager.TrainingHealthError`` from the host hook).

3. **Serving fidelity** — sampled KV dequant-error and WOQ matmul-error
   probes for the v2 inference engine plus a spec-decode acceptance-rate
   :class:`TrendAlarm` (PR-2 median+MAD discipline, low side).

Disabled (the default) every hook is an attribute check and the sentinel is
absent from the train step — the program is jaxpr-identical to a build
without this module (pinned by ``tests/unit/test_numerics.py``).

Accuracy trajectories land in the perf ledger under the ``numerics`` suite
(``tools/numerics_smoke.py``, ``bench_serving.py --kv-dtype``) so the PR-16
gate's MAD machinery gates them exactly like latency. See docs/telemetry.md
"Numerics observatory".
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, replace as dc_replace
from statistics import median
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils.compat import shard_map
from deepspeed_tpu.utils.logging import logger

#: codecs whose wire drops information on fp32 payloads (bf16 passthrough
#: downcasts, so it is lossy here even though it ships "uncompressed")
LOSSY_CODECS = frozenset({"bf16", "int8", "fp8"})

#: pinned per-codec relative-error bounds on unit-gaussian payloads — the
#: SAME numbers the codec equivalence tests pin (int8 absmax/127 blockwise
#: ~1-2%, fp8 E4M3 3 mantissa bits ~5-6%, bf16 8 mantissa bits ~4e-3);
#: exact codecs get a float32-roundoff allowance
WIRE_REL_ERR_BOUNDS: Dict[str, float] = {
    "none": 1e-6,
    "fp32": 1e-6,
    "bf16": 8e-3,
    "int8": 2e-2,
    "fp8": 6e-2,
}

#: wire signatures past this are registered-but-not-probed (same capacity
#: discipline as the collectives observatory)
_MAX_ROUTES = 64


# --------------------------------------------------------------------- config
@dataclass
class NumericsConfig:
    """Tunables (the engine's ``numerics`` config block mirrors these)."""

    enabled: bool = False
    sample_every: int = 16           # 1-in-N steps runs wire/serving probes
    sentinel: bool = True            # in-jit divergence sentinel (when enabled)
    sentinel_sample_every: int = 16  # 1-in-N train steps digests the params
    divergence_policy: str = "log"   # "log" | "abort"
    max_probe_elems: int = 65536     # wire-probe payload cap (elements)
    drift_ratio: float = 2.0         # rel_err > ratio*pinned bound => drift
    spec_accept_window: int = 64     # acceptance-rate trend window
    spec_accept_mads: float = 6.0    # PR-2 discipline width
    spec_accept_min_n: int = 8       # min history before the alarm can fire


@dataclass
class WireRoute:
    """One routed lossy-collective signature, registered at trace time."""

    op: str
    codec: str
    algorithm: str
    backend: str
    nbytes: int
    itemsize: int
    world: int
    dtype: str
    block_size: Optional[int] = None
    routes: int = 0           # how many traces registered this signature
    probes: int = 0           # how many fidelity probes ran for it
    last_rel_err: float = float("nan")


# ----------------------------------------------------------- digest primitives
def leaf_checksum(x: jax.Array) -> jax.Array:
    """Order-independent bit-level checksum of a float leaf (uint32 scalar).

    xor over the float32 bit patterns: exact, commutative, associative —
    the xor of per-shard checksums equals the whole-tensor checksum, so the
    folded value is bit-stable across mesh shapes (pinned by test).
    """
    bits = lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    if bits.ndim == 0:
        return bits
    return lax.reduce(bits, np.uint32(0), lax.bitwise_xor,
                      tuple(range(bits.ndim)))


def leaf_sumsq(x: jax.Array) -> jax.Array:
    """Sum of squares in fp32 (magnitude digest; NOT bit-stable across mesh
    shapes — used only for the replica min/max gap, never cross-process)."""
    xf = x.astype(jnp.float32)
    return jnp.sum(xf * xf)


def _spec_axes(spec) -> frozenset:
    """Mesh axis names a PartitionSpec shards over."""
    if spec is None:
        return frozenset()
    names: set = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            names.update(str(e) for e in entry)
        else:
            names.add(str(entry))
    return frozenset(names)


def _group_key(path) -> str:
    """Top-level tree key for a leaf path (mirrors diagnostics/health.py)."""
    if not path:
        return "params"
    entry = path[0]
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry).strip("[].'\"")


class NumericsState(NamedTuple):
    """Sentinel state carried in ``TrainState.numerics`` (distinct arrays —
    shared zeros would alias buffers under step donation)."""

    checked: jax.Array    # i32: digest probes run
    events: jax.Array     # i32: cumulative divergence events (latched)
    checksum: jax.Array   # u32: latest whole-tree xor digest
    gap: jax.Array        # f32: latest max replica sum-of-squares gap


class DivergenceSentinel:
    """In-jit cross-replica digest comparator (see module doc, plane 2).

    Construction captures the mesh and the params' PartitionSpec tree so
    each leaf knows which axes it is replicated over: divergence is defined
    per-leaf as ``pmin != pmax`` of the local digest across exactly those
    axes (a sharded axis holds *different* data by construction and is
    folded into the global checksum instead, via all_gather + xor).
    """

    def __init__(self, mesh, param_specs, sample_every: int = 16):
        self.mesh = mesh
        self.param_specs = param_specs
        self.sample_every = int(sample_every)

    @staticmethod
    def init_state() -> NumericsState:
        return NumericsState(
            checked=jnp.zeros((), jnp.int32),
            events=jnp.zeros((), jnp.int32),
            checksum=jnp.zeros((), jnp.uint32),
            gap=jnp.zeros((), jnp.float32),
        )

    # ------------------------------------------------------------ internals
    def _flat(self, params):
        """Float leaves with (path, spec, group) alignment."""
        leaves = jax.tree_util.tree_leaves_with_path(params)
        spec_leaves = jax.tree_util.tree_leaves(self.param_specs)
        if len(spec_leaves) != len(leaves):
            # spec tree shape drifted from params (custom containers):
            # fall back to fully-replicated specs — digesting a sharded
            # leaf as replicated can false-positive, so be loud about it
            logger.warning(
                "numerics sentinel: param spec tree does not match params "
                f"({len(spec_leaves)} specs vs {len(leaves)} leaves); "
                "assuming replicated leaves")
            spec_leaves = [P()] * len(leaves)
        out = []
        for (path, leaf), spec in zip(leaves, spec_leaves):
            if hasattr(spec, "spec"):  # NamedSharding passed instead of spec
                spec = spec.spec
            if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
                continue
            out.append((leaf, spec, _group_key(path)))
        return out

    def _digest(self, flat):
        """shard_map program producing (per-group diverged i32[G], max gap
        f32, whole-tree xor checksum u32), all replicated."""
        mesh = self.mesh
        axis_names = tuple(mesh.axis_names)
        groups: List[str] = []
        for _leaf, _spec, g in flat:
            if g not in groups:
                groups.append(g)
        gidx = {g: i for i, g in enumerate(groups)}
        specs = [spec for _leaf, spec, _g in flat]
        gis = [gidx[g] for _leaf, _spec, g in flat]
        n_groups = len(groups)

        def fn(*locals_):
            div_acc = [jnp.zeros((), jnp.int32) for _ in range(n_groups)]
            gap_all = jnp.zeros((), jnp.float32)
            ck_all = jnp.zeros((), jnp.uint32)
            for x, spec, gi in zip(locals_, specs, gis):
                sharded = _spec_axes(spec) & set(axis_names)
                rep = tuple(a for a in axis_names if a not in sharded)
                ss = leaf_sumsq(x)
                ck = leaf_checksum(x)
                if rep:
                    ck_min, ck_max = lax.pmin(ck, rep), lax.pmax(ck, rep)
                    ss_min, ss_max = lax.pmin(ss, rep), lax.pmax(ss, rep)
                    d = ((ck_min != ck_max) | (ss_min != ss_max)
                         ).astype(jnp.int32)
                    g = ss_max - ss_min
                else:
                    d = jnp.zeros((), jnp.int32)
                    g = jnp.zeros((), jnp.float32)
                # whole-tensor checksum: xor-fold the per-shard checksums
                # across each sharded axis (exact, order-independent)
                for ax in axis_names:
                    if ax not in sharded:
                        continue
                    gathered = lax.all_gather(ck, ax)
                    ck = lax.reduce(gathered, np.uint32(0), lax.bitwise_xor,
                                    (0,))
                    # a sharded axis also means the per-position divergence
                    # verdicts differ: fold to "any position diverged"
                    d = lax.pmax(d, ax)
                    g = lax.pmax(g, ax)
                if rep:
                    # deterministic output when replicas DISAGREE (the
                    # checksum itself is then ill-defined; take the min)
                    ck = lax.pmin(ck, rep)
                div_acc[gi] = jnp.maximum(div_acc[gi], d)
                gap_all = jnp.maximum(gap_all, g)
                ck_all = lax.bitwise_xor(ck_all, ck)
            div = (jnp.stack(div_acc) if div_acc
                   else jnp.zeros((0,), jnp.int32))
            return div, gap_all, ck_all

        in_specs = tuple(spec if spec is not None else P() for spec in specs)
        # fresh closure per trace (shard_map caches on function identity)
        mapped = shard_map(fn, mesh=mesh, in_specs=in_specs,
                           out_specs=(P(), P(), P()), check_vma=False)
        return mapped(*[leaf for leaf, _spec, _g in flat]), groups

    # ---------------------------------------------------------------- probe
    def probe(self, nstate: Optional[NumericsState], params, step,
              ) -> Tuple[Optional[NumericsState], Dict[str, Any]]:
        """Traced into the train step. On sampled steps digests ``params``
        and latches divergence into the carried state; other steps run the
        zero branch of a ``lax.cond`` (no digest work dispatched)."""
        if nstate is None:
            return nstate, {}
        flat = self._flat(params)
        if not flat:
            return nstate, {}
        every = self.sample_every

        def run(leaves):
            flat_now = [(leaf, spec, g)
                        for leaf, (_old, spec, g) in zip(leaves, flat)]
            (div, gap, ck), _groups = self._digest(flat_now)
            return div, gap, ck

        def skip(leaves):
            n_groups = len({g for _l, _s, g in flat})
            return (jnp.zeros((n_groups,), jnp.int32),
                    jnp.zeros((), jnp.float32), jnp.zeros((), jnp.uint32))

        do = ((step % every) == 0) if every > 0 else jnp.asarray(False)
        do = jnp.asarray(do)
        leaves = [leaf for leaf, _s, _g in flat]
        div, gap, ck = lax.cond(do, run, skip, leaves)
        diverged = (jnp.max(div) if div.shape[0] else
                    jnp.zeros((), jnp.int32))
        new_state = NumericsState(
            checked=nstate.checked + do.astype(jnp.int32),
            events=nstate.events + diverged,
            checksum=jnp.where(do, ck, nstate.checksum),
            gap=jnp.where(do, gap, nstate.gap),
        )
        groups = []
        for _l, _s, g in flat:
            if g not in groups:
                groups.append(g)
        metrics: Dict[str, Any] = {
            "numerics/checked": new_state.checked,
            "numerics/diverged": diverged,
            "numerics/divergence_events": new_state.events,
            "numerics/digest_gap": new_state.gap,
            "numerics/digest_checksum": lax.bitcast_convert_type(
                new_state.checksum, jnp.int32),
        }
        for i, g in enumerate(groups):
            metrics[f"numerics/diverged/{g}"] = div[i]
        return new_state, metrics


# ------------------------------------------------------------------ trend alarm
class TrendAlarm:
    """Low-side median+MAD trend alarm (PR-2 straggler discipline) over a
    bounded observation window — fires when a fresh value falls below
    ``median - mads·MAD`` of the PRIOR window (the fresh value never vouches
    for itself)."""

    def __init__(self, window: int = 64, mads: float = 6.0, min_n: int = 8,
                 mad_floor_rel: float = 0.01):
        self.window = int(window)
        self.mads = float(mads)
        self.min_n = int(min_n)
        self.mad_floor_rel = float(mad_floor_rel)
        self._vals: deque = deque(maxlen=self.window)
        self.alarms = 0

    def observe(self, value: float) -> bool:
        hist = list(self._vals)
        self._vals.append(float(value))
        if len(hist) < self.min_n:
            return False
        med = median(hist)
        mad = median(abs(v - med) for v in hist)
        mad = max(mad, self.mad_floor_rel * abs(med), 1e-9)
        fired = value < med - self.mads * mad
        if fired:
            self.alarms += 1
        return fired


# ------------------------------------------------------------------ observatory
def _registry():
    from deepspeed_tpu.telemetry import get_tracer

    return get_tracer().registry


class NumericsObservatory:
    """Process-global fidelity observer (same lifecycle discipline as
    ``collectives.observatory``: ``configure()`` resets, ``install()``
    attaches the live engine's profiler arm)."""

    def __init__(self):
        self.config = NumericsConfig()
        self._lock = threading.Lock()
        from deepspeed_tpu.telemetry.events import WarnOnceSet

        self._warn_once_set = WarnOnceSet(subsystem="numerics",
                                          default_kind="fidelity_warning")
        self._routes: Dict[Tuple, WireRoute] = {}
        self._probe_cache: Dict[Tuple, Callable] = {}
        self.profiler_arm: Optional[Callable[..., None]] = None
        self.wire_drift_events = 0
        self.divergence_events_seen = 0  # host-side last-seen cumulative
        self.spec_accept_alarm = TrendAlarm()

    # ----------------------------------------------------------- configure
    @property
    def enabled(self) -> bool:
        return self.config.enabled

    def configure(self, config: Optional[NumericsConfig] = None,
                  **kwargs) -> "NumericsObservatory":
        with self._lock:
            cfg = (dc_replace(config, **kwargs) if config is not None
                   else NumericsConfig(**kwargs))
            self.config = cfg
            self._routes.clear()
            self._probe_cache.clear()
            self._warn_once_set.reset()
            self.wire_drift_events = 0
            self.divergence_events_seen = 0
            self.spec_accept_alarm = TrendAlarm(
                window=cfg.spec_accept_window, mads=cfg.spec_accept_mads,
                min_n=cfg.spec_accept_min_n)
            # install() targets belong to the engine that configured us
            self.profiler_arm = None
        return self

    def install(self, profiler_arm: Optional[Callable] = None) -> None:
        if profiler_arm is not None:
            self.profiler_arm = profiler_arm

    def warn_once(self, key: str, msg: str) -> bool:
        """Log ``msg`` once per ``key`` per configure() epoch (shared
        warn-once helper: the first occurrence also lands on the typed
        event stream). Active even when the observatory is disabled (the
        forced-lossy-codec warning must fire regardless of whether anyone
        is measuring)."""
        return self._warn_once_set(key, msg, log=logger)

    # ------------------------------------------------- trace-time registry
    def note_route(self, op: str, algorithm: str, codec: str, nbytes: int,
                   itemsize: int, world: int, axis, dtype: str,
                   block_size: Optional[int] = None) -> None:
        """Register one routed facade collective (called at trace time from
        ``comm._observe_route`` next to the perf observatory's hook). Only
        lossy codecs get fidelity probes; exact wires have nothing to
        measure."""
        if not self.config.enabled:
            return
        if codec is None:
            codec = "none"
        codec = str(codec)
        if codec not in LOSSY_CODECS:
            return
        key = (op, codec, algorithm, str(dtype), block_size)
        with self._lock:
            info = self._routes.get(key)
            if info is None:
                if len(self._routes) >= _MAX_ROUTES:
                    return
                from deepspeed_tpu.collectives.observatory import _backend_of

                try:
                    backend = _backend_of(algorithm)
                except Exception:
                    backend = "xla"
                info = self._routes[key] = WireRoute(
                    op=op, codec=codec, algorithm=algorithm, backend=backend,
                    nbytes=int(nbytes), itemsize=int(itemsize),
                    world=int(world), dtype=str(dtype),
                    block_size=block_size)
            info.routes += 1
            info.nbytes = max(info.nbytes, int(nbytes))

    def routes(self) -> List[WireRoute]:
        with self._lock:
            return list(self._routes.values())

    # ---------------------------------------------------------- wire probes
    def _roundtrip_fn(self, codec: str, block: Optional[int], elems: int):
        key = (codec, block, elems)
        fn = self._probe_cache.get(key)
        if fn is None:
            from deepspeed_tpu.collectives.codecs import get_codec

            c = get_codec(codec, block)

            def roundtrip(x):
                wire = c.encode_rows(x)
                y = c.decode_rows(wire, x.shape[1], jnp.float32)
                num = jnp.sqrt(jnp.sum((x - y) ** 2))
                den = jnp.sqrt(jnp.sum(x * x))
                return num / jnp.maximum(den, 1e-12)

            fn = self._probe_cache[key] = jax.jit(roundtrip)
            if len(self._probe_cache) > 4 * _MAX_ROUTES:
                self._probe_cache.clear()
                self._probe_cache[key] = fn
        return fn

    def _probe_route(self, route: WireRoute) -> float:
        """One standalone encode→decode fidelity measurement against a
        deterministic payload of the routed shape (byte-capped)."""
        elems = max(16, route.nbytes // max(route.itemsize, 1))
        elems = min(elems, int(self.config.max_probe_elems))
        seed = abs(hash((route.op, route.codec, route.algorithm))) % (2**31)
        x = np.asarray(
            np.random.RandomState(seed).standard_normal((1, elems)),
            np.float32)
        rel = float(jax.device_get(
            self._roundtrip_fn(route.codec, route.block_size, elems)(x)))
        route.probes += 1
        route.last_rel_err = rel
        return rel

    def sample_now(self) -> Dict[str, float]:
        """Force a full wire-fidelity probe round over every registered
        route; returns ``{op/codec: rel_err}``. The sampled-step path
        (:meth:`on_step`) calls this 1-in-``sample_every`` steps."""
        if not self.config.enabled:
            return {}
        out: Dict[str, float] = {}
        reg = _registry()
        for route in self.routes():
            try:
                rel = self._probe_route(route)
            except Exception as e:  # a probe must never kill the step loop
                self.warn_once(
                    f"probe_fail:{route.op}/{route.codec}",
                    f"numerics wire probe failed for {route.op}/"
                    f"{route.codec}: {type(e).__name__}: {e}")
                continue
            out[f"{route.op}/{route.codec}"] = rel
            reg.histogram("numerics/wire_rel_err", op=route.op,
                          codec=route.codec, algorithm=route.algorithm,
                          backend=route.backend).observe(rel)
            bound = WIRE_REL_ERR_BOUNDS.get(route.codec)
            if bound is not None and rel > bound * self.config.drift_ratio:
                self.wire_drift_events += 1
                reg.counter("numerics/wire_drift_events", op=route.op,
                            codec=route.codec).add(1)
                self._warn_once_set(
                    f"drift:{route.op}/{route.codec}",
                    f"numerics drift: {route.op}/{route.codec} wire rel err "
                    f"{rel:.3e} exceeds {self.config.drift_ratio:g}x the "
                    f"pinned bound {bound:.3e} "
                    f"(algorithm={route.algorithm})",
                    kind="wire_drift",
                    labels={"op": route.op, "codec": route.codec,
                            "algorithm": route.algorithm},
                    log=logger)
                if self.profiler_arm is not None:
                    try:
                        self.profiler_arm(
                            reason=f"numerics_drift:{route.op}/{route.codec}")
                    except Exception:
                        pass
        return out

    def on_step(self, step: int) -> Dict[str, float]:
        """Host-side sampled hook (engine step loop). Cheap when off or on
        a non-sampled step: one attribute check + one modulo."""
        cfg = self.config
        if not cfg.enabled or cfg.sample_every <= 0:
            return {}
        if step % cfg.sample_every != 0:
            return {}
        return self.sample_now()

    # ----------------------------------------------------- EF residual gauges
    def note_ef_residuals(self, err_tree) -> Dict[str, float]:
        """Per-top-level-group L2 norms of the LoCo/1-bit error-feedback
        residuals (called on sampled steps with ``TrainState.comm_error``).
        A residual norm trending up means the wire is dropping more than
        the feedback loop is re-capturing."""
        if err_tree is None or not self.config.enabled:
            return {}
        sums: Dict[str, Any] = {}
        for path, leaf in jax.tree_util.tree_leaves_with_path(err_tree):
            if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
                continue
            g = _group_key(path)
            ss = leaf_sumsq(leaf)
            sums[g] = sums[g] + ss if g in sums else ss
        if not sums:
            return {}
        vals = jax.device_get({g: jnp.sqrt(s) for g, s in sums.items()})
        reg = _registry()
        out = {}
        for g, v in vals.items():
            out[g] = float(v)
            reg.gauge("numerics/ef_residual_norm", group=g).set(float(v))
        return out

    # ------------------------------------------------ divergence host plane
    def note_divergence_events(self, step: int, events_cum: int,
                               checksum: Optional[int] = None) -> int:
        """Fold the sentinel's carried cumulative event count into the host
        plane: publishes new events (counter + warning + profiler arm) and
        the fleet-visible digest checksum gauge. Returns the number of NEW
        events since the last call (0 = quiet)."""
        events_cum = int(events_cum)
        new = max(0, events_cum - self.divergence_events_seen)
        self.divergence_events_seen = max(self.divergence_events_seen,
                                          events_cum)
        reg = _registry()
        if checksum is not None:
            # exact in f64 for any uint32, so the heartbeat comparator is
            # bit-faithful cross-process
            reg.gauge("numerics/digest_checksum").set(
                float(int(checksum) & 0xFFFFFFFF))
        if new > 0:
            reg.counter("numerics/divergence_events").add(new)
            msg = (f"NUMERICS DIVERGENCE: cross-replica digest mismatch at "
                   f"step {step} ({new} new event(s), {events_cum} total) — "
                   f"dp/fsdp replicas no longer hold identical parameters")
            logger.warning(msg)
            from deepspeed_tpu.telemetry.events import emit_event

            emit_event("numerics", "divergence", msg, severity="critical",
                       labels={"new_events": new, "total": events_cum},
                       step=step,
                       dedup_key="numerics:divergence")
            if self.profiler_arm is not None:
                try:
                    self.profiler_arm(reason=f"numerics_divergence:{step}")
                except Exception:
                    pass
        return new

    # ------------------------------------------------------- serving probes
    def kv_dequant_probe(self, kv_quant: str, head_dim: int = 128,
                         vectors: int = 64, seed: int = 0) -> float:
        """Round-trip relative error of the paged-KV block quantizer on a
        gaussian payload shaped like ``vectors`` per-head KV rows."""
        from deepspeed_tpu.ops.quant import (
            fp8_block_dequant, fp8_block_math, int8_block_math)

        x = jnp.asarray(
            np.random.RandomState(seed).standard_normal((vectors, head_dim)),
            jnp.float32)
        if kv_quant == "int8":
            q, s = int8_block_math(x)
            y = q.astype(jnp.float32) * s
        elif kv_quant == "fp8":
            q, s = fp8_block_math(x)
            y = fp8_block_dequant(q, s)
        else:
            return 0.0
        rel = float(jax.device_get(
            jnp.sqrt(jnp.sum((x - y) ** 2)) /
            jnp.maximum(jnp.sqrt(jnp.sum(x * x)), 1e-12)))
        _registry().gauge("numerics/kv_dequant_rel_err",
                          dtype=kv_quant).set(rel)
        return rel

    def woq_matmul_probe(self, fmt: str, m: int = 8, k: int = 256,
                         n: int = 256, seed: int = 0) -> float:
        """Relative matmul error of a weight-only-quantized gaussian weight
        vs the fp32 reference (the number WOQ serving accuracy rides on)."""
        from deepspeed_tpu.inference import woq as woq_mod

        rs = np.random.RandomState(seed)
        w = jnp.asarray(rs.standard_normal((k, n)), jnp.float32)
        x = jnp.asarray(rs.standard_normal((m, k)), jnp.float32)
        qt = woq_mod._quantize_leaf(w, fmt)
        wq = qt.astype(jnp.float32) if hasattr(qt, "astype") else qt
        ref = x @ w
        got = x @ wq
        rel = float(jax.device_get(
            jnp.sqrt(jnp.sum((ref - got) ** 2)) /
            jnp.maximum(jnp.sqrt(jnp.sum(ref * ref)), 1e-12)))
        _registry().gauge("numerics/woq_matmul_rel_err", fmt=fmt).set(rel)
        return rel

    def note_spec_accept(self, rate: float) -> bool:
        """Feed one spec-decode acceptance-rate observation to the trend
        alarm; fires (returns True, counts, warns once per epoch) when the
        rate collapses below the PR-2 median−MADs band."""
        if not self.config.enabled:
            return False
        fired = self.spec_accept_alarm.observe(float(rate))
        if fired:
            _registry().counter("numerics/spec_accept_alarm").add(1)
            self.warn_once(
                "spec_accept",
                f"numerics: spec-decode acceptance rate {rate:.3f} fell "
                f"below the trailing median-MAD band "
                f"({self.spec_accept_alarm.alarms} alarm(s))")
        return fired


# ------------------------------------------------------------------- singleton
_observatory = NumericsObservatory()


def get_observatory() -> NumericsObservatory:
    return _observatory


def configure(config: Optional[NumericsConfig] = None,
              **kwargs) -> NumericsObservatory:
    return _observatory.configure(config, **kwargs)


def enabled() -> bool:
    return _observatory.enabled


def note_route(*args, **kwargs) -> None:
    _observatory.note_route(*args, **kwargs)


def warn_once(key: str, msg: str) -> bool:
    return _observatory.warn_once(key, msg)
