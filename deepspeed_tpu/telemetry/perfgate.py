"""Noise-aware perf regression gate over the unified ledger.

The gate compares fresh rows against the per-``(backend, suite, metric)``
ledger history — NEVER across backends: a cpu number can't vouch for (or
indict) a tpu number. Three gating modes, chosen per row:

  1. **Absolute overhead bound** — metrics ending in ``overhead_pct`` /
     ``overhead_pct_max`` carry their own contract (the repo-wide <2%
     paired-step bound every telemetry feature ships under); they fail on
     ``value > overhead_bound_pct`` with no history needed.
  2. **Headline history gate** — the curated per-suite headline metrics
     (:data:`HEADLINE_PATTERNS`) gate on the PR-2 median+MAD discipline
     when history has quorum (>=3 rows): regression = beyond
     ``median ± mads·MAD`` in the row's bad direction, with the MAD floored
     at ``mad_floor_rel·|median|`` so a too-quiet history can't make the
     gate hair-triggered. Below quorum, a relative-bound fallback
     (default 30% worsening vs the historical median) applies.
  3. **Trajectory-only** — everything else (config echoes, percentile
     tails, sub-metrics) publishes a ``perf/trajectory`` gauge and never
     fails the build. The legacy history is genuinely noisy (serving
     telemetry-overhead wandered 12→28% across rounds); gating every row
     would train people to ignore the gate.

A regression does three things beyond the nonzero exit: increments the
``perf/regression_events`` counter, publishes the offending value as a
``perf/trajectory`` gauge (both in the PR-1 registry), and arms every
live PR-7 profiler capture via :func:`profiling.capture.arm_all` — a
nightly regression leaves a step trace, not just a red line in a log.
"""

from __future__ import annotations

import fnmatch
import statistics
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.telemetry.perfledger import PerfLedger, row_key

#: per-suite curated headline metrics (fnmatch patterns on the metric
#: path) — the numbers a round is *about*; sub-metrics stay trajectory-only
HEADLINE_PATTERNS: Dict[str, Tuple[str, ...]] = {
    "bench": ("tokens_per_sec*",),
    "serving": (
        "end_to_end/chained/tokens_per_sec",
        "host_path/chained/host_us_per_decode_token",
        "slo/goodput",
    ),
    "perf": ("*tokens_per_sec*",),
    # accuracy trajectories (telemetry/numerics.py): wire codec fidelity,
    # divergence detection latency, and the fp8-vs-fp32 KV token-divergence
    # step gate on the SAME median+MAD machinery as latency
    "numerics": ("wire_rel_err/*", "*divergence_detect_steps",
                 "*token_divergence_step"),
    # cross-process serving fabric (ISSUE 18): the three wire costs a
    # remote replica adds over a local one (bench_serving --remote)
    "fabric": ("remote/dispatch_rtt_ms/p50", "remote/wire_migration_ms",
               "remote/drain_handoff_ms"),
    # collective schedule compiler + fused GEMM collectives (ISSUE 19):
    # the compiled-vs-best-hand predicted-latency ratio must not drift up
    # (the search regressing against its own cost model), and the fused
    # ZeRO-3 step must not get slower relative to its unfused twin
    "schedule": ("compiled_vs_hand/pred_ratio",
                 "fused_gemm/step_time_ratio"),
}

#: matched AFTER the headline patterns: derived ratios ride along with a
#: headline name but are baseline-relative, not round-comparable
HEADLINE_EXCLUDE: Tuple[str, ...] = ("*/vs_baseline",)

_OVERHEAD_SUFFIXES = ("overhead_pct", "overhead_pct_max")


@dataclass
class GateConfig:
    mads: float = 6.0            # PR-2 straggler discipline width
    quorum: int = 3              # min history rows for the MAD gate
    rel_bound: float = 0.30      # sub-quorum fallback: max fractional worsening
    mad_floor_rel: float = 0.01  # MAD floor as a fraction of |median|
    overhead_bound_pct: float = 2.0  # absolute bound for *overhead_pct rows
    policy: str = "headline"     # "headline" | "all" (gate every row)
    headline: Dict[str, Tuple[str, ...]] = field(
        default_factory=lambda: dict(HEADLINE_PATTERNS))


@dataclass
class Verdict:
    row: Dict[str, Any]
    status: str        # "ok" | "regression" | "no_history" | "info"
    mode: str          # "absolute" | "mad" | "rel" | "info"
    detail: str = ""
    threshold: Optional[float] = None
    history_n: int = 0

    @property
    def key(self) -> Tuple[str, str, str]:
        return row_key(self.row)


@dataclass
class GateReport:
    verdicts: List[Verdict]

    @property
    def regressions(self) -> List[Verdict]:
        return [v for v in self.verdicts if v.status == "regression"]

    @property
    def ok(self) -> bool:
        return not self.regressions

    def summary(self) -> str:
        n = len(self.verdicts)
        gated = sum(1 for v in self.verdicts if v.mode != "info")
        lines = [f"perf_gate: {n} rows checked, {gated} gated, "
                 f"{len(self.regressions)} regression(s)"]
        for v in self.regressions:
            b, s, m = v.key
            lines.append(f"  REGRESSION [{b}] {s}/{m}: value="
                         f"{v.row['value']:.6g} {v.detail}")
        return "\n".join(lines)


def is_overhead_metric(metric: str) -> bool:
    return metric.endswith(_OVERHEAD_SUFFIXES)


def is_headline(row: Dict[str, Any], cfg: GateConfig) -> bool:
    metric = str(row["metric"])
    if any(fnmatch.fnmatch(metric, pat) for pat in HEADLINE_EXCLUDE):
        return False
    pats = cfg.headline.get(str(row["suite"]), ())
    return any(fnmatch.fnmatch(metric, pat) for pat in pats)


def _worsening(value: float, base: float, direction: str) -> float:
    """Fractional change in the row's BAD direction (positive = worse)."""
    if base == 0:
        return 0.0
    delta = (base - value) if direction == "higher" else (value - base)
    return delta / abs(base)


def gate_row(row: Dict[str, Any], history: Sequence[Dict[str, Any]],
             cfg: GateConfig) -> Verdict:
    """Pure per-row decision. ``history`` must already be the row's own
    (backend, suite, metric) key — callers own backend isolation; this
    function enforces it defensively."""
    history = [h for h in history if row_key(h) == row_key(row)]
    value = float(row["value"])
    direction = str(row["direction"])
    metric = str(row["metric"])

    if is_overhead_metric(metric):
        bound = cfg.overhead_bound_pct
        if value > bound:
            return Verdict(row, "regression", "absolute",
                           f"> absolute bound {bound:g}%", bound, len(history))
        return Verdict(row, "ok", "absolute", f"<= bound {bound:g}%",
                       bound, len(history))

    if cfg.policy != "all" and not is_headline(row, cfg):
        return Verdict(row, "info", "info", "trajectory-only",
                       None, len(history))

    vals = [float(h["value"]) for h in history]
    if not vals:
        return Verdict(row, "no_history", "info", "no history for key",
                       None, 0)

    med = statistics.median(vals)
    if len(vals) >= cfg.quorum:
        mad = statistics.median(abs(v - med) for v in vals)
        mad = max(mad, cfg.mad_floor_rel * abs(med), 1e-9)
        if direction == "higher":
            threshold = med - cfg.mads * mad
            bad = value < threshold
        else:
            threshold = med + cfg.mads * mad
            bad = value > threshold
        status = "regression" if bad else "ok"
        return Verdict(row, status, "mad",
                       f"median={med:.6g} mad={mad:.6g} n={len(vals)} "
                       f"threshold={threshold:.6g}", threshold, len(vals))

    worsening = _worsening(value, med, direction)
    bad = worsening > cfg.rel_bound
    return Verdict(row, "regression" if bad else "ok", "rel",
                   f"median={med:.6g} n={len(vals)} worsening="
                   f"{worsening:.1%} (bound {cfg.rel_bound:.0%})",
                   cfg.rel_bound, len(vals))


# ------------------------------------------------------------ orchestration
def gate_fresh(rows: Sequence[Dict[str, Any]], ledger: PerfLedger,
               cfg: Optional[GateConfig] = None) -> GateReport:
    """Gate a fresh run's rows against the full ledger history. Rows of a
    versioned round (round > 0) compare only against strictly older
    rounds; unversioned rows (round 0) compare against everything."""
    cfg = cfg or GateConfig()
    verdicts = []
    for row in rows:
        backend, suite, metric = row_key(row)
        before = int(row["round"]) if int(row["round"]) > 0 else None
        history = ledger.history(backend, suite, metric, before_round=before)
        verdicts.append(gate_row(row, history, cfg))
    return GateReport(verdicts)


def self_check(ledger: PerfLedger, cfg: Optional[GateConfig] = None,
               ) -> GateReport:
    """Gate the latest round of every key against its own older history —
    the nightly's HEAD-must-pass check over the committed ledger."""
    cfg = cfg or GateConfig()
    by_key: Dict[Tuple[str, str, str], List[Dict[str, Any]]] = {}
    for row in ledger.rows():
        by_key.setdefault(row_key(row), []).append(row)
    verdicts = []
    for key, rows in sorted(by_key.items()):
        latest = max(int(r["round"]) for r in rows)
        fresh = [r for r in rows if int(r["round"]) == latest]
        history = sorted((r for r in rows if int(r["round"]) < latest),
                         key=lambda r: int(r["round"]))
        for row in fresh:
            verdicts.append(gate_row(row, history, cfg))
    return GateReport(verdicts)


def inject_regression(rows: Sequence[Dict[str, Any]], pct: float,
                      ) -> List[Dict[str, Any]]:
    """Synthetically degrade rows by ``pct``% in each row's bad direction —
    the nightly proves the gate FIRES on these (inverted exit check), so a
    green gate is evidence of a working sentinel, not a silent one."""
    factor = pct / 100.0
    out = []
    for row in rows:
        row = dict(row)
        if row["direction"] == "higher":
            row["value"] = float(row["value"]) * (1.0 - factor)
        else:
            row["value"] = float(row["value"]) * (1.0 + factor)
        out.append(row)
    return out


def publish(report: GateReport, registry=None, arm: bool = True,
            ) -> Dict[str, Any]:
    """Land the gate outcome in the telemetry plane: a ``perf/trajectory``
    gauge per gated row, a ``perf/regression_events`` counter increment per
    regression, and (``arm=True``) arm every live profiler capture so the
    next step window leaves a trace."""
    if registry is None:
        from deepspeed_tpu.telemetry import get_tracer

        registry = get_tracer().registry
    armed = 0
    for v in report.verdicts:
        backend, suite, metric = v.key
        if v.mode != "info" or v.status == "no_history":
            registry.gauge("perf/trajectory", suite=suite, metric=metric,
                           backend=backend).set(float(v.row["value"]))
    from deepspeed_tpu.telemetry.events import emit_event

    for v in report.regressions:
        backend, suite, metric = v.key
        registry.counter("perf/regression_events", suite=suite,
                         metric=metric, backend=backend).add(1)
        emit_event(
            "perf", "regression",
            f"perf gate regression {'/'.join(v.key)}: "
            f"{float(v.row['value']):.6g} ({v.detail})",
            severity="warn",
            labels={"suite": suite, "metric": metric, "backend": backend,
                    "mode": v.mode,
                    "incident_key": "perf_gate:" + "/".join(v.key)},
            dedup_key="perf:regression:" + "/".join(v.key))
    if report.regressions and arm:
        from deepspeed_tpu.profiling.capture import arm_all

        worst = report.regressions[0]
        armed = arm_all(reason="perf_gate:" + "/".join(worst.key))
    return {"regressions": len(report.regressions), "captures_armed": armed}
