"""Process-local structured event stream — the incident plane's front door.

Every detector the repo has grown (diagnostics health/anomaly/recompile, the
collective observatory's drift alarm, the numerics wire-drift/divergence
sentinel, the perf gate, the router's liveness/migration paths, the rewind
supervisor) used to terminate in a warn-once log line on whichever process
happened to notice. This module gives those warnings a second, *typed*
destination: an :class:`Event` with severity / subsystem / kind / labels /
dedup key / process identity, appended to a bounded ring, exportable as
JSONL next to the trace stream, and shippable to the fleet collector where
cross-process events correlate into incidents (``telemetry/collector.py``).

Log lines are unchanged — ``emit_event`` rides *alongside* every existing
``logger.warning``, never replaces it. Emission is host-side only (a lock,
a deque append, two counter bumps): nothing here is ever traced into a
jitted program, so the hot train/decode programs are jaxpr-identical with
the event plane on, off, or absent.

Dedup: an event carrying a ``dedup_key`` that was already seen inside
``dedup_window_s`` is not appended again — the FIRST occurrence's ``count``
is bumped and ``events/deduped`` counts the suppression. That is the
warn-once discipline, applied to the typed stream.

The shared warn-once helper (:class:`WarnOnceSet` / :func:`warn_once`)
unifies the two historic ``_warn_once`` implementations
(``utils/logging.py`` message-keyed, ``collectives/observatory.py``
key-keyed) so warn-once coverage and event coverage cannot drift apart:
one call logs once AND emits the typed event.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

SEVERITIES = ("info", "warn", "critical")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}


def severity_rank(severity: str) -> int:
    """info=0 < warn=1 < critical=2 (unknown severities read as info)."""
    return _SEV_RANK.get(severity, 0)


@dataclass
class Event:
    """One structured occurrence. ``ts`` is wall-clock unix seconds (events
    cross process boundaries — a shared epoch, not a per-process origin);
    ``seq`` is the per-process monotonic sequence number; ``count`` grows
    when later emissions dedup onto this event."""

    ts: float
    severity: str
    subsystem: str
    kind: str
    message: str
    labels: Dict[str, str] = field(default_factory=dict)
    dedup_key: Optional[str] = None
    seq: int = 0
    count: int = 1
    identity: Optional[Dict[str, Any]] = None
    request_id: Optional[int] = None
    flow_id: Optional[int] = None
    step: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "ts": self.ts, "severity": self.severity,
            "subsystem": self.subsystem, "kind": self.kind,
            "message": self.message, "seq": self.seq, "count": self.count,
        }
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.dedup_key is not None:
            d["dedup_key"] = self.dedup_key
        if self.identity is not None:
            d["identity"] = self.identity
        if self.request_id is not None:
            d["request_id"] = self.request_id
        if self.flow_id is not None:
            d["flow_id"] = self.flow_id
        if self.step is not None:
            d["step"] = self.step
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Event":
        return cls(
            ts=float(d.get("ts", 0.0)),
            severity=str(d.get("severity", "info")),
            subsystem=str(d.get("subsystem", "")),
            kind=str(d.get("kind", "")),
            message=str(d.get("message", "")),
            labels=dict(d.get("labels") or {}),
            dedup_key=d.get("dedup_key"),
            seq=int(d.get("seq", 0)),
            count=int(d.get("count", 1)),
            identity=d.get("identity"),
            request_id=d.get("request_id"),
            flow_id=d.get("flow_id"),
            step=d.get("step"),
        )


class EventStream:
    """Bounded ring of :class:`Event` with dedup and subscriber fan-out.

    Thread-safe; emission under load is O(1). Subscribers (the alert
    engine's event-rate rules, tests) are called OUTSIDE the stream lock
    with the appended event; a subscriber that raises is dropped from the
    hot path into a counted failure — a watcher must never break the
    detector that fed it (the PR-13 never-raise discipline).
    """

    def __init__(self, capacity: int = 2048, dedup_window_s: float = 300.0,
                 registry=None, clock: Callable[[], float] = time.time):
        self.capacity = int(capacity)
        self.dedup_window_s = float(dedup_window_s)
        self.enabled = True
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._total = 0
        # dedup_key -> (first Event still holding the count, ts last seen)
        self._dedup: Dict[str, List[Any]] = {}
        self._subscribers: List[Callable[[Event], None]] = []
        self._registry = registry
        self.jsonl_path: Optional[str] = None

    # ------------------------------------------------------------- plumbing
    @property
    def registry(self):
        if self._registry is None:
            from deepspeed_tpu.telemetry.tracer import get_tracer

            self._registry = get_tracer().registry
        return self._registry

    def subscribe(self, fn: Callable[[Event], None]) -> None:
        with self._lock:
            if fn not in self._subscribers:
                self._subscribers.append(fn)

    def unsubscribe(self, fn: Callable[[Event], None]) -> None:
        with self._lock:
            if fn in self._subscribers:
                self._subscribers.remove(fn)

    # ------------------------------------------------------------- emission
    def emit(self, subsystem: str, kind: str, message: str, *,
             severity: str = "warn", labels: Optional[Dict[str, Any]] = None,
             dedup_key: Optional[str] = None, ctx=None,
             request_id: Optional[int] = None, step: Optional[int] = None,
             ts: Optional[float] = None) -> Optional[Event]:
        """Append one event; returns it, or ``None`` when disabled or
        deduped onto an earlier occurrence. ``ctx`` may be a
        :class:`~deepspeed_tpu.telemetry.fleet.TraceContext` — its
        request/flow ids become incident-correlation join keys."""
        if not self.enabled:
            return None
        if severity not in _SEV_RANK:
            raise ValueError(f"severity {severity!r}: one of {SEVERITIES}")
        now = self._clock() if ts is None else float(ts)
        flow_id = None
        if ctx is not None:
            request_id = ctx.request_id if request_id is None else request_id
            flow_id = ctx.flow_id
        with self._lock:
            if dedup_key is not None:
                hit = self._dedup.get(dedup_key)
                if hit is not None and now - hit[1] <= self.dedup_window_s:
                    hit[0].count += 1
                    hit[1] = now
                    deduped = True
                else:
                    deduped = False
            else:
                deduped = False
            if deduped:
                ev = None
            else:
                from deepspeed_tpu.telemetry.fleet import get_identity

                self._seq += 1
                self._total += 1
                ev = Event(
                    ts=now, severity=severity, subsystem=subsystem,
                    kind=kind, message=message,
                    labels={k: str(v) for k, v in (labels or {}).items()},
                    dedup_key=dedup_key, seq=self._seq,
                    identity=get_identity().to_dict(),
                    request_id=request_id, flow_id=flow_id, step=step)
                self._ring.append(ev)
                if dedup_key is not None:
                    self._dedup[dedup_key] = [ev, now]
                    if len(self._dedup) > 4 * self.capacity:
                        # bound the dedup index like the ring it shadows
                        for k in list(self._dedup)[: self.capacity]:
                            self._dedup.pop(k, None)
            subscribers = list(self._subscribers)
        reg = self.registry
        if ev is None:
            reg.counter("events/deduped").add(1)
            return None
        reg.counter("events/emitted", severity=severity).add(1)
        reg.gauge("events/buffered").set(float(len(self._ring)))
        for fn in subscribers:
            try:
                fn(ev)
            except Exception as e:  # noqa: BLE001 - never break the emitter
                reg.counter("events/subscriber_failures").add(1)
                from deepspeed_tpu.utils.logging import logger

                logger.debug(f"events: subscriber {fn!r} raised: {e}")
        return ev

    # -------------------------------------------------------------- reading
    def events(self, min_severity: Optional[str] = None,
               subsystem: Optional[str] = None,
               since_ts: Optional[float] = None,
               since_seq: Optional[int] = None) -> List[Event]:
        with self._lock:
            out = list(self._ring)
        if min_severity is not None:
            floor = severity_rank(min_severity)
            out = [e for e in out if severity_rank(e.severity) >= floor]
        if subsystem is not None:
            out = [e for e in out if e.subsystem == subsystem]
        if since_ts is not None:
            out = [e for e in out if e.ts >= since_ts]
        if since_seq is not None:
            out = [e for e in out if e.seq > since_seq]
        return out

    def drain_since(self, seq: int) -> List[Dict[str, Any]]:
        """Wire dicts of every buffered event with ``seq`` greater than the
        given watermark — the fleet client's incremental push cursor."""
        return [e.to_dict() for e in self.events(since_seq=seq)]

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    @property
    def total_emitted(self) -> int:
        with self._lock:
            return self._total

    @property
    def dropped(self) -> int:
        """Events pushed out of the bounded ring (emitted minus retained)."""
        with self._lock:
            return self._total - len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dedup.clear()

    # ------------------------------------------------------------ exporting
    def export_jsonl(self, path: Optional[str] = None) -> str:
        """Write the buffered events as JSONL next to the trace stream: one
        ``process_meta`` header line (identity + schema marker), then one
        event per line. Returns the path written."""
        from deepspeed_tpu.telemetry.exporters import default_output_dir
        from deepspeed_tpu.telemetry.fleet import get_identity

        path = path or self.jsonl_path or os.path.join(
            default_output_dir(), "event_log.jsonl")
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(json.dumps({
                "kind": "process_meta", "schema": "dstpu_events_v1",
                "identity": get_identity().to_dict(), "pid": os.getpid(),
            }) + "\n")
            for ev in self.events():
                f.write(json.dumps(ev.to_dict()) + "\n")
        return path

    def maybe_export(self) -> Optional[str]:
        """Export iff a path is configured (the tracer's flush hook)."""
        if self.jsonl_path:
            return self.export_jsonl(self.jsonl_path)
        return None


def load_events_jsonl(path: str) -> List[Event]:
    """Parse an ``export_jsonl`` file back into events (header skipped) —
    the incident-report side of the round trip."""
    out: List[Event] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            if d.get("kind") == "process_meta" or "severity" not in d:
                continue
            out.append(Event.from_dict(d))
    return out


# ----------------------------------------------------------- process-global
_stream: Optional[EventStream] = None
_stream_lock = threading.Lock()


def get_event_stream() -> EventStream:
    global _stream
    if _stream is None:
        with _stream_lock:
            if _stream is None:
                _stream = EventStream()
    return _stream


def configure_events(capacity: Optional[int] = None,
                     dedup_window_s: Optional[float] = None,
                     jsonl_path: Optional[str] = None,
                     enabled: Optional[bool] = None) -> EventStream:
    """(Re)configure the process-global stream in place — handles held by
    detectors and the fleet client stay valid (the tracer convention)."""
    s = get_event_stream()
    if capacity is not None and int(capacity) != s.capacity:
        with s._lock:
            s.capacity = int(capacity)
            s._ring = deque(s._ring, maxlen=s.capacity)
    if dedup_window_s is not None:
        s.dedup_window_s = float(dedup_window_s)
    if jsonl_path is not None:
        s.jsonl_path = jsonl_path or None
    if enabled is not None:
        s.enabled = bool(enabled)
    return s


def emit_event(subsystem: str, kind: str, message: str, *,
               severity: str = "warn",
               labels: Optional[Dict[str, Any]] = None,
               dedup_key: Optional[str] = None, ctx=None,
               request_id: Optional[int] = None,
               step: Optional[int] = None,
               ts: Optional[float] = None) -> Optional[Event]:
    """Emit onto the process-global stream (see :meth:`EventStream.emit`).

    This is THE detector-side API: call it right next to the existing
    ``logger.warning`` — never instead of it."""
    return get_event_stream().emit(
        subsystem, kind, message, severity=severity, labels=labels,
        dedup_key=dedup_key, ctx=ctx, request_id=request_id, step=step,
        ts=ts)


# ------------------------------------------------------- shared warn-once
class WarnOnceSet:
    """THE warn-once implementation (satellite of ISSUE 20): one keyed set
    behind its own lock (callers may hold other non-reentrant locks — the
    observatory's ``note_route`` does), logging once per key AND emitting a
    typed event on that first occurrence.

    Returns True when this call was the first for ``key`` (and therefore
    logged + emitted), False on every repeat — the observatory/numerics
    call sites branch on that.
    """

    def __init__(self, subsystem: str = "telemetry",
                 default_kind: str = "warn_once"):
        self.subsystem = subsystem
        self.default_kind = default_kind
        self._lock = threading.Lock()
        self._seen: set = set()

    def __call__(self, key: str, message: str, *, kind: Optional[str] = None,
                 severity: str = "warn",
                 labels: Optional[Dict[str, Any]] = None,
                 subsystem: Optional[str] = None, log=None) -> bool:
        with self._lock:
            if key in self._seen:
                return False
            self._seen.add(key)
        if log is None:
            from deepspeed_tpu.utils.logging import logger as log
        log.warning(message)
        try:
            emit_event(subsystem or self.subsystem,
                       kind or self.default_kind, message,
                       severity=severity, labels=labels, dedup_key=key)
        except Exception:  # noqa: BLE001 - a warn must never raise
            pass
        return True

    def seen(self, key: str) -> bool:
        with self._lock:
            return key in self._seen

    def reset(self) -> None:
        with self._lock:
            self._seen.clear()


_global_warn_once = WarnOnceSet(subsystem="logging", default_kind="warning_once")


def warn_once(message: str, *, key: Optional[str] = None,
              subsystem: str = "logging", kind: str = "warning_once",
              severity: str = "warn") -> bool:
    """Process-global warn-once keyed by ``key`` (default: the message
    itself — the historic ``utils/logging.warning_once`` contract)."""
    k = message if key is None else key
    return _global_warn_once(k, message, kind=kind, severity=severity,
                             subsystem=subsystem)


def reset_warn_once() -> None:
    """Test hook: forget every process-global warn-once key."""
    _global_warn_once.reset()
