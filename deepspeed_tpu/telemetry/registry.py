"""Metrics registry: counters, gauges, histograms.

The numeric half of the telemetry subsystem (the ``Tracer`` in ``tracer.py``
is the temporal half). Closest reference analogs are the scattered aggregates
in ``utils/comms_logging.py`` (bytes/counts per op) and the monitor scalars —
here they share ONE registry so the ``MonitorMaster`` backends, ``bench.py``'s
phase breakdown, and the exporters all read the same numbers.

Thread-safe end to end: creation AND mutation run under the registry's lock
(spans may close on any thread — the tracer records per-thread ids), so
concurrent increments never drop. Contention is negligible: updates happen
per span/collective, not per tensor element.

Creation is get-or-create so call sites never coordinate.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class Counter:
    """Monotonic accumulator (e.g. ``comm/bytes``)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self.value = 0.0
        self._lock = lock

    def add(self, v: float) -> None:
        with self._lock:
            self.value += v


class Gauge:
    """Last-write-wins sample (e.g. ``mem/device_bytes_in_use``)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self.value = 0.0
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self.value = v


class Histogram:
    """Streaming summary (count/total/min/max/last) — enough for phase
    breakdowns without bucket bookkeeping."""

    __slots__ = ("name", "count", "total", "min", "max", "last", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.last = 0.0
        self._lock = lock

    def observe(self, v: float) -> None:
        with self._lock:
            self.count += 1
            self.total += v
            self.last = v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def summary(self) -> Dict[str, float]:
        with self._lock:
            if self.count == 0:
                return {"count": 0, "total": 0.0, "mean": 0.0, "min": 0.0, "max": 0.0}
            return {
                "count": self.count,
                "total": self.total,
                "mean": self.total / self.count,
                "min": self.min,
                "max": self.max,
            }


class MetricsRegistry:
    """Get-or-create registry of named metrics (one shared lock — see module
    docstring)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name, self._lock)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, self._lock)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, self._lock)
            return h

    def peek_histogram(self, name: str) -> Optional[Histogram]:
        """Read-only lookup — never creates (keeps snapshots free of
        zero-count entries from probes)."""
        with self._lock:
            return self._histograms.get(name)

    def snapshot(self) -> Dict[str, object]:
        """Flat dict of every metric's current value(s)."""
        with self._lock:
            out: Dict[str, object] = {}
            for n, c in self._counters.items():
                out[n] = c.value
            for n, g in self._gauges.items():
                out[n] = g.value
            for n, h in self._histograms.items():
                out[n] = h.summary()
            return out

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return {n: c.value for n, c in self._counters.items()}

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return {n: g.value for n, g in self._gauges.items()}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
