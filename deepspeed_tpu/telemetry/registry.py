"""Metrics registry: counters, gauges, quantile-capable histograms — with labels.

The numeric half of the telemetry subsystem (the ``Tracer`` in ``tracer.py``
is the temporal half). Closest reference analogs are the scattered aggregates
in ``utils/comms_logging.py`` (bytes/counts per op) and the monitor scalars —
here they share ONE registry so the ``MonitorMaster`` backends, ``bench.py``'s
phase breakdown, and the exporters all read the same numbers.

Labels (serving SLO observability): every factory accepts keyword labels —
``registry.histogram("serving/ttft_ms", k=8)`` — producing one child metric
per label set, keyed ``name{k="8"}`` in the flat snapshot and exposed as a
proper labelled family by ``exposition.render_prometheus``. The unlabelled
call is unchanged (same object identity, same snapshot keys), so every
pre-existing call site keeps its exact behavior.

Histograms are **log-bucketed**: each observation lands in a sparse
geometric bucket (growth ``2**(1/8)`` per bucket, so any quantile estimate
carries at most ~4.4% relative error — ``sqrt(growth)-1``). That answers
p50/p95/p99 queries in O(populated buckets) with O(1) per observe (one
``log2`` + one dict bump), which is what lets per-request serving latencies
(TTFT/TPOT/queue-wait) stay cheap enough for the decode hot path while still
producing honest tail percentiles and a Prometheus histogram exposition.

Thread-safe end to end: creation AND mutation run under the registry's lock
(spans may close on any thread — the tracer records per-thread ids), so
concurrent increments never drop. Contention is negligible: updates happen
per span/collective/chain-boundary, not per tensor element.

Creation is get-or-create so call sites never coordinate.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterator, List, Optional, Tuple

# Log-bucket growth factor: 2**(1/8) per bucket. A value v>0 lands in bucket
# floor(log2(v) * 8); the bucket's representative (geometric midpoint) is at
# most sqrt(growth) ~ 1.044x away from any value in it -> bounded ~4.4%
# relative error on every quantile estimate.
_BUCKETS_PER_OCTAVE = 8
_GROWTH = 2.0 ** (1.0 / _BUCKETS_PER_OCTAVE)


def bucket_upper_bound(idx: Optional[int]) -> float:
    """Inclusive upper bound of a log bucket (``le`` in Prometheus terms).
    ``idx=None`` is the underflow bucket for values <= 0 (le == 0)."""
    if idx is None:
        return 0.0
    return 2.0 ** ((idx + 1) / _BUCKETS_PER_OCTAVE)


def encode_labels(labels: Dict[str, object]) -> str:
    """Canonical label suffix: ``{a="1",b="x"}`` sorted by key; "" when
    empty. This is the ONE spelling — snapshot keys, registry child keys and
    the Prometheus exposition all use it."""
    if not labels:
        return ""
    return "{" + ",".join(
        f'{k}="{labels[k]}"' for k in sorted(labels)) + "}"


_KEY_LABEL_RE = None


def decode_key(key: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of ``name + encode_labels(labels)``: split a flat registry
    key back into (base name, label dict). The fleet collector re-labels
    per-process gauges (``{proc=}``) from dump keys, so the parse must
    round-trip exactly what :func:`encode_labels` writes — plain
    ``k="v"`` pairs, no escaping (registry label values never contain
    quotes; the Prometheus exposition escapes separately)."""
    global _KEY_LABEL_RE
    brace = key.find("{")
    if brace < 0 or not key.endswith("}"):
        return key, {}
    if _KEY_LABEL_RE is None:
        import re

        _KEY_LABEL_RE = re.compile(r'([a-zA-Z0-9_]+)="([^"]*)"')
    labels = {m.group(1): m.group(2)
              for m in _KEY_LABEL_RE.finditer(key[brace + 1:-1])}
    return key[:brace], labels


class Counter:
    """Monotonic accumulator (e.g. ``comm/bytes``)."""

    __slots__ = ("name", "labels", "value", "_lock")

    def __init__(self, name: str, lock: threading.Lock,
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = labels or {}
        self.value = 0.0
        self._lock = lock

    def add(self, v: float) -> None:
        with self._lock:
            self.value += v


class Gauge:
    """Last-write-wins sample (e.g. ``mem/device_bytes_in_use``)."""

    __slots__ = ("name", "labels", "value", "_lock")

    def __init__(self, name: str, lock: threading.Lock,
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = labels or {}
        self.value = 0.0
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self.value = v


class Histogram:
    """Streaming summary (count/total/min/max/last) plus sparse log buckets
    for cheap bounded-error quantiles (p50/p95/p99)."""

    __slots__ = ("name", "labels", "count", "total", "min", "max", "last",
                 "_buckets", "_lock")

    def __init__(self, name: str, lock: threading.Lock,
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = labels or {}
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.last = 0.0
        # sparse log buckets: {idx: count}; idx None = underflow (v <= 0)
        self._buckets: Dict[Optional[int], int] = {}
        self._lock = lock

    @staticmethod
    def _bucket_idx(v: float):
        """Sparse bucket key for ``v``: None (underflow, le=0) for v <= 0 or
        NaN; a finite int for finite v > 0; ``...`` (Ellipsis sentinel) for
        +inf — counted only by the implicit +Inf bucket (= count) in the
        exposition, and pushing high quantiles to ``max`` rather than
        raising (floor(log2(inf)) would OverflowError)."""
        if not (v > 0):  # catches <= 0 and NaN
            return None
        lg = math.log2(v) * _BUCKETS_PER_OCTAVE
        if lg == float("inf"):
            return ...
        return math.floor(lg)

    def observe(self, v: float) -> None:
        with self._lock:
            self.count += 1
            self.total += v
            self.last = v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            idx = self._bucket_idx(v)
            if idx is not ...:
                self._buckets[idx] = self._buckets.get(idx, 0) + 1

    def observe_n(self, v: float, n: int) -> None:
        """``n`` observations of the same value in one lock/bucket hit — the
        serving loop groups a chain's identical per-row TPOT samples."""
        if n <= 0:
            return
        with self._lock:
            self.count += n
            self.total += v * n
            self.last = v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            idx = self._bucket_idx(v)
            if idx is not ...:
                self._buckets[idx] = self._buckets.get(idx, 0) + n

    def buckets(self) -> List[Tuple[Optional[int], int]]:
        """Populated log buckets sorted ascending (underflow first)."""
        with self._lock:
            return sorted(self._buckets.items(),
                          key=lambda kv: -math.inf if kv[0] is None else kv[0])

    def state(self) -> Dict[str, object]:
        """Wire-portable full state (JSON-safe): summary scalars plus the
        RAW sparse buckets — the piece a cross-process merge needs that
        ``summary()`` drops. Bucket keys stringify (JSON objects can't key
        on ints/None): ``"u"`` is the underflow bucket, ints are
        ``str(idx)``."""
        with self._lock:
            return {
                "count": self.count,
                "total": self.total,
                "min": self.min if self.count else 0.0,
                "max": self.max if self.count else 0.0,
                "last": self.last,
                "buckets": {("u" if k is None else str(k)): v
                            for k, v in self._buckets.items()},
            }

    def merge_state(self, state: Dict[str, object]) -> None:
        """Fold another histogram's :meth:`state` into this one — EXACTLY
        equivalent to having observed the other histogram's sample stream
        here (bucket counts add, count/total add, min/max widen; ``last``
        is taken from the incoming state, the per-process notion of
        "latest" — label merged streams per process if that matters).
        The log buckets make this exact by construction: a sample lands in
        the same bucket no matter which process observed it."""
        n = int(state.get("count", 0))
        if n <= 0:
            return
        with self._lock:
            self.count += n
            self.total += float(state.get("total", 0.0))
            self.last = float(state.get("last", 0.0))
            s_min = float(state.get("min", 0.0))
            s_max = float(state.get("max", 0.0))
            if s_min < self.min:
                self.min = s_min
            if s_max > self.max:
                self.max = s_max
            for k, v in (state.get("buckets") or {}).items():
                idx = None if k == "u" else int(k)
                self._buckets[idx] = self._buckets.get(idx, 0) + int(v)

    def quantile(self, q: float) -> float:
        """Bounded-relative-error quantile estimate from the log buckets.

        Walks the sparse buckets to the target rank and returns the bucket's
        geometric midpoint, clamped to the exact observed [min, max] — so
        p0/p100 are exact and everything between carries at most
        ``sqrt(growth) - 1`` (~4.4%) relative error.
        """
        with self._lock:
            if self.count == 0:
                return 0.0
            target = max(1, math.ceil(q * self.count))
            cum = 0
            items = sorted(self._buckets.items(),
                           key=lambda kv: -math.inf if kv[0] is None else kv[0])
            for idx, c in items:
                cum += c
                if cum >= target:
                    if idx is None:
                        return self.min  # underflow bucket: v <= 0
                    mid = 2.0 ** ((idx + 0.5) / _BUCKETS_PER_OCTAVE)
                    return min(max(mid, self.min), self.max)
            return self.max  # unreachable; defensive

    def summary(self) -> Dict[str, float]:
        with self._lock:
            if self.count == 0:
                return {"count": 0, "total": 0.0, "mean": 0.0, "min": 0.0, "max": 0.0}
            out = {
                "count": self.count,
                "total": self.total,
                "mean": self.total / self.count,
                "min": self.min,
                "max": self.max,
            }
        # quantiles re-take the (reentrant) registry lock per call
        out["p50"] = self.quantile(0.50)
        out["p95"] = self.quantile(0.95)
        out["p99"] = self.quantile(0.99)
        return out


class MetricsRegistry:
    """Get-or-create registry of named metrics (one shared lock — see module
    docstring). Labels produce one child per label set, keyed
    ``name{k="v",...}`` in the flat dicts."""

    def __init__(self):
        self._lock = threading.RLock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str, **labels) -> Counter:
        key = name + encode_labels(labels)
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = Counter(
                    name, self._lock, {k: str(v) for k, v in labels.items()})
            return c

    def gauge(self, name: str, **labels) -> Gauge:
        key = name + encode_labels(labels)
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self._gauges[key] = Gauge(
                    name, self._lock, {k: str(v) for k, v in labels.items()})
            return g

    def histogram(self, name: str, **labels) -> Histogram:
        key = name + encode_labels(labels)
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = Histogram(
                    name, self._lock, {k: str(v) for k, v in labels.items()})
            return h

    def peek_histogram(self, name: str, **labels) -> Optional[Histogram]:
        """Read-only lookup — never creates (keeps snapshots free of
        zero-count entries from probes)."""
        with self._lock:
            return self._histograms.get(name + encode_labels(labels))

    def iter_metrics(self) -> Iterator[Tuple[str, str, object]]:
        """``(kind, base_name, metric)`` for every registered metric —
        label-aware iteration for the exposition layer (labels live on the
        metric objects)."""
        with self._lock:
            items = (
                [("counter", c.name, c) for c in self._counters.values()]
                + [("gauge", g.name, g) for g in self._gauges.values()]
                + [("histogram", h.name, h) for h in self._histograms.values()]
            )
        return iter(items)

    def snapshot(self) -> Dict[str, object]:
        """Flat dict of every metric's current value(s); labelled children
        appear under their ``name{k="v"}`` key."""
        with self._lock:
            out: Dict[str, object] = {}
            for n, c in self._counters.items():
                out[n] = c.value
            for n, g in self._gauges.items():
                out[n] = g.value
            for n, h in self._histograms.items():
                out[n] = h.summary()
            return out

    def size(self) -> int:
        """Number of registered metric children (labelled children count
        individually) — the ``/healthz`` registry-size signal."""
        with self._lock:
            return (len(self._counters) + len(self._gauges)
                    + len(self._histograms))

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return {n: c.value for n, c in self._counters.items()}

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return {n: g.value for n, g in self._gauges.items()}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
