"""Trace exporters: Chrome trace-event JSON (Perfetto) and JSONL.

Chrome trace-event format reference:
https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU
— spans are "X" (complete) events with microsecond ``ts``/``dur``; memory
watermarks are "C" (counter) events which Perfetto renders as plotted tracks.
Open the output at https://ui.perfetto.dev (or chrome://tracing).

The JSONL exporter writes one structured event per line (the raw tracer event
schema plus ``pid``), for downstream tooling that wants greppable records
rather than a viewer format.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional


def default_output_dir() -> str:
    """The ONE resolution of the telemetry artifact directory (trace/JSONL
    exports, flight-recorder dumps, bench telemetry): $DSTPU_TELEMETRY_DIR,
    else ./telemetry_out. Counterpart of ``tracer.env_enabled`` — don't
    re-implement the default at call sites."""
    return os.environ.get("DSTPU_TELEMETRY_DIR", "telemetry_out")


def _resolve(tracer) -> Any:
    if tracer is None:
        from deepspeed_tpu.telemetry.tracer import get_tracer

        tracer = get_tracer()
    return tracer


def chrome_trace_events(tracer=None) -> Dict[str, Any]:
    """Tracer buffer -> a Chrome trace-event JSON object (in memory)."""
    from deepspeed_tpu.telemetry.fleet import get_identity

    tracer = _resolve(tracer)
    ident = get_identity()
    pid = os.getpid()
    out: List[Dict[str, Any]] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            # identity in the Perfetto process label: two replicas' traces
            # stop being indistinguishable "deepspeed_tpu" rows
            "args": {"name": f"deepspeed_tpu {ident.proc} "
                             f"{ident.role}@{ident.host}"},
        }
    ]
    # virtual-track labels (per-request serving tracks): thread_name metadata
    for tid, tname in sorted(tracer.track_names().items()):
        out.append({
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"name": tname},
        })
    for ev in tracer.events():
        ts_us = ev["ts"] * 1e6
        if ev["kind"] == "span":
            rec: Dict[str, Any] = {
                "name": ev["name"],
                "cat": ev.get("cat", "span"),
                "ph": "X",
                "ts": ts_us,
                "dur": ev["dur"] * 1e6,
                "pid": pid,
                "tid": ev["tid"],
            }
            if "args" in ev:
                rec["args"] = ev["args"]
        elif ev["kind"] == "instant":
            rec = {
                "name": ev["name"],
                "cat": ev.get("cat", "event"),
                "ph": "i",
                "s": "t",
                "ts": ts_us,
                "pid": pid,
                "tid": ev["tid"],
            }
            if "args" in ev:
                rec["args"] = ev["args"]
        elif ev["kind"] == "flow":
            # flow arrows: "s" (start) -> "t" (step)* -> "f" (end); matching
            # (cat, name, id) bind them; "bp": "e" attaches the end to its
            # enclosing slice instead of the next one
            rec = {
                "name": ev["name"],
                "cat": ev.get("cat", "flow"),
                "ph": ev["ph"],
                "id": ev["id"],
                "ts": ts_us,
                "pid": pid,
                "tid": ev["tid"],
            }
            if ev["ph"] == "f":
                rec["bp"] = "e"
        else:  # counter
            rec = {
                "name": ev["name"],
                "ph": "C",
                "ts": ts_us,
                "pid": pid,
                "args": {"value": ev["value"]},
            }
        out.append(rec)
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "dropped_events": tracer.dropped_events,
            "metrics": tracer.registry.snapshot(),
            "identity": ident.to_dict(),
            "origin_unix": tracer.origin_unix(),
        },
    }


def export_chrome_trace(path: Optional[str] = None, tracer=None) -> str:
    """Write the Chrome trace JSON; returns the path written."""
    tracer = _resolve(tracer)
    path = path or tracer.trace_path or os.path.join(
        default_output_dir(), "trace.json")
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(chrome_trace_events(tracer), f)
    return path


def export_jsonl(path: Optional[str] = None, tracer=None) -> str:
    """Write one JSON object per event; returns the path written.

    The stream opens with meta lines (``kind: process_meta`` — identity +
    the wall-clock origin the event ``ts`` values are relative to — and one
    ``kind: track_name`` per labelled virtual track), which is exactly what
    ``tools/trace_merge.py`` needs to place this process's events on a
    fleet-wide timeline with a distinct pid. Event lines are unchanged
    (raw tracer schema plus ``pid``)."""
    from deepspeed_tpu.telemetry.fleet import get_identity

    tracer = _resolve(tracer)
    path = path or tracer.jsonl_path or os.path.join(
        default_output_dir(), "events.jsonl")
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    pid = os.getpid()
    with open(path, "w") as f:
        f.write(json.dumps({
            "kind": "process_meta",
            "identity": get_identity().to_dict(),
            "origin_unix": tracer.origin_unix(),
            "pid": pid,
        }) + "\n")
        for tid, tname in sorted(tracer.track_names().items()):
            f.write(json.dumps({"kind": "track_name", "tid": tid,
                                "track": tname, "pid": pid}) + "\n")
        for ev in tracer.events():
            f.write(json.dumps({"pid": pid, **ev}) + "\n")
    return path
