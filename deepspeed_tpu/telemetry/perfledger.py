"""Unified performance ledger: one versioned row schema for every number.

Fifteen PRs produced ~20 ad-hoc root-level perf artifacts (``BENCH_rNN``,
``SERVING_rNN``, ``COLL_r11``, ``FLEET_r13``, ...) with incompatible schemas
and a hand-written PERF.md — the bench trajectory was not machine-readable,
so nothing would have caught a silent 2x serving regression between rounds.
This module is the landing pad that fixes it:

  - **Row schema (v1).** Every measurement is one flat JSON object::

        {schema, run_id, git_sha, round, backend, suite, metric, value,
         unit, direction, method, samples[, proc, time_unix]}

    ``backend`` is the accelerator the number was measured on (``cpu`` /
    ``tpu-v5e`` / ``interpret``) — the gate NEVER compares across backends.
    ``direction`` says which way is better (``higher`` / ``lower``);
    ``method`` names the measurement discipline (``worst-of-three``,
    ``paired``, ``p99``, ``single``); ``round`` is the PR round the row
    belongs to (0 = unversioned HEAD run).

  - **Append-only JSONL** under ``perf/ledger/<suite>.jsonl``. Rows are
    never rewritten; migration (``perfmigrate.py``) and live emitters
    (bench.py extras, ``tools/bench_serving.py``, ``comm/benchmark.py
    --sweep``) both append here, so the trajectory back to PR 4 and the
    next TPU relay session land in ONE queryable place.

  - **Identity stamps.** :func:`make_row` stamps :class:`ProcessIdentity`
    (run_id + proc, PR 13) and the tree's git sha onto every fresh row, so
    a number can always be joined back to the process and tree that
    produced it.

Consumers: ``telemetry/perfgate.py`` (noise-aware regression gate),
``tools/perf_report.py`` (PERF.md round tables + trajectory curves),
``profiling/attribution.py`` (step-time decomposition context). See
docs/telemetry.md "Performance ledger & attribution".
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

SCHEMA_VERSION = 1

# canonical backends; free-form strings are stored verbatim (a future
# tpu-v6 stamp must not require a code change) but these are the ones the
# runtime resolves itself
BACKENDS = ("cpu", "tpu-v5e", "interpret")

DIRECTIONS = ("higher", "lower")

# canonical measurement disciplines (method is free-form; these are the
# spellings the repo's own emitters use)
METHODS = ("single", "paired", "worst-of-three", "p50", "p95", "p99")

REQUIRED_FIELDS = (
    "schema", "run_id", "git_sha", "round", "backend", "suite", "metric",
    "value", "unit", "direction", "method", "samples",
)

_SUITE_OK = frozenset("abcdefghijklmnopqrstuvwxyz0123456789_-")


def default_ledger_root() -> str:
    """The ONE resolution of the ledger directory: ``$DSTPU_PERF_LEDGER_DIR``,
    else ``<repo>/perf/ledger`` (the repo root is the parent of the
    ``deepspeed_tpu`` package — this checkout's layout; installed trees set
    the env var)."""
    env = os.environ.get("DSTPU_PERF_LEDGER_DIR")
    if env:
        return env
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo, "perf", "ledger")


_git_sha_cache: Optional[str] = None


def resolve_git_sha() -> str:
    """Tree identity stamp: ``$DSTPU_GIT_SHA``, else ``git rev-parse --short
    HEAD`` of the repo this package lives in (cached; "" when unavailable —
    a missing stamp must never block a measurement)."""
    global _git_sha_cache
    env = os.environ.get("DSTPU_GIT_SHA")
    if env is not None:
        return env
    if _git_sha_cache is None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        try:
            _git_sha_cache = subprocess.run(
                ["git", "-C", repo, "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10,
            ).stdout.strip()
        except (OSError, subprocess.SubprocessError):
            _git_sha_cache = ""
    return _git_sha_cache


def default_backend() -> str:
    """The accelerator stamp for rows measured in THIS process:
    ``$DSTPU_PERF_BACKEND`` (the relay session exports ``tpu-v5e``; interpret
    parity runs export ``interpret``), else mapped from
    ``jax.default_backend()``. Emitters that KNOW they ran under the Pallas
    interpreter pass ``backend="interpret"`` explicitly — the env/jax
    resolution cannot see inside a kernel."""
    env = os.environ.get("DSTPU_PERF_BACKEND")
    if env:
        return env
    try:
        import jax

        b = jax.default_backend()
    except Exception:  # noqa: BLE001 - backendless imports stamp cpu
        return "cpu"
    return "tpu-v5e" if b == "tpu" else "cpu"


def default_round() -> int:
    """The PR round fresh rows belong to: ``$DSTPU_PERF_ROUND`` (the nightly
    exports ``rNN``'s NN), else 0 — "unversioned HEAD run"."""
    env = os.environ.get("DSTPU_PERF_ROUND", "")
    digits = "".join(c for c in env if c.isdigit())
    try:
        return int(digits) if digits else 0
    except ValueError:
        return 0


def make_row(suite: str, metric: str, value: float, unit: str,
             direction: str = "higher", method: str = "single",
             samples: int = 1, backend: Optional[str] = None,
             round: Optional[int] = None, run_id: Optional[str] = None,
             git_sha: Optional[str] = None,
             time_unix: Optional[float] = None) -> Dict[str, Any]:
    """One schema-v1 row, identity-stamped from the process defaults.
    Everything the caller omits resolves here (ProcessIdentity run_id/proc,
    git sha, backend, round) so emitters stay one-liners."""
    if run_id is None or time_unix is None:
        from deepspeed_tpu.telemetry.fleet import get_identity

        ident = get_identity()
        run_id = run_id if run_id is not None else ident.run_id
        proc = ident.proc
    else:
        proc = None
    row: Dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "run_id": run_id,
        "git_sha": git_sha if git_sha is not None else resolve_git_sha(),
        "round": int(round) if round is not None else default_round(),
        "backend": backend if backend is not None else default_backend(),
        "suite": suite,
        "metric": metric,
        "value": float(value),
        "unit": unit,
        "direction": direction,
        "method": method,
        "samples": int(samples),
        "time_unix": (round_time(time_unix) if time_unix is not None
                      else round_time(time.time())),
    }
    if proc:
        row["proc"] = proc
    validate_row(row)
    return row


def round_time(t: float) -> float:
    return round(float(t), 3)


def validate_row(row: Dict[str, Any]) -> Dict[str, Any]:
    """Schema check — raises ``ValueError`` with the offending field.
    Direction is a closed enum (the gate's comparisons depend on it);
    backend/method are open sets with canonical spellings."""
    for f in REQUIRED_FIELDS:
        if f not in row:
            raise ValueError(f"ledger row missing field {f!r}: {row!r}")
    if int(row["schema"]) != SCHEMA_VERSION:
        raise ValueError(
            f"ledger row schema {row['schema']!r} != {SCHEMA_VERSION} "
            f"(metric {row.get('metric')!r})")
    if row["direction"] not in DIRECTIONS:
        raise ValueError(
            f"ledger row direction {row['direction']!r} not in {DIRECTIONS}")
    if not isinstance(row["value"], (int, float)) or isinstance(row["value"], bool):
        raise ValueError(f"ledger row value not numeric: {row!r}")
    if not row["suite"] or set(str(row["suite"])) - _SUITE_OK:
        raise ValueError(f"ledger row suite {row['suite']!r} not a file-safe slug")
    return row


def row_key(row: Dict[str, Any]) -> Tuple[str, str, str]:
    """The history key the gate compares within: (backend, suite, metric).
    Backends never mix — a cpu row must never gate a tpu row."""
    return (str(row["backend"]), str(row["suite"]), str(row["metric"]))


def row_identity(row: Dict[str, Any]) -> Tuple:
    """Dedupe identity for idempotent migration: everything measurement-
    defining, nothing stamp-volatile (time_unix/proc/git_sha excluded —
    re-migrating the same artifact from a different checkout must produce
    the same identity)."""
    return (row["suite"], int(row["round"]), row["backend"], row["metric"],
            float(row["value"]), row["method"], int(row["samples"]),
            row["run_id"])


class PerfLedger:
    """Append-only JSONL ledger under one directory, one file per suite.

    Append never rewrites: a row, once written, is history. Thread-safe
    appends (one lock; emitters may append from bench worker threads).
    Loading tolerates an empty/missing directory (fresh checkout before
    migration) but NOT malformed rows — a corrupt ledger must fail loudly,
    not silently shrink the gate's history.
    """

    def __init__(self, root: Optional[str] = None):
        self.root = root or default_ledger_root()
        self._lock = threading.Lock()

    def path_for(self, suite: str) -> str:
        return os.path.join(self.root, f"{suite}.jsonl")

    def suites(self) -> List[str]:
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        return [n[:-len(".jsonl")] for n in names if n.endswith(".jsonl")]

    # ------------------------------------------------------------- writing
    def append(self, rows: Iterable[Dict[str, Any]]) -> int:
        """Validate + append rows, grouped into their suite files. Returns
        the number written. Partial-failure honest: validation runs on ALL
        rows before the first byte is written."""
        by_suite: Dict[str, List[str]] = {}
        n = 0
        for row in rows:
            validate_row(row)
            by_suite.setdefault(str(row["suite"]), []).append(
                json.dumps(row, sort_keys=True))
            n += 1
        if not n:
            return 0
        with self._lock:
            os.makedirs(self.root, exist_ok=True)
            for suite, lines in by_suite.items():
                with open(self.path_for(suite), "a", encoding="utf-8") as f:
                    f.write("\n".join(lines) + "\n")
        return n

    # ------------------------------------------------------------- reading
    def rows(self, suite: Optional[str] = None) -> List[Dict[str, Any]]:
        suites = [suite] if suite is not None else self.suites()
        out: List[Dict[str, Any]] = []
        for s in suites:
            path = self.path_for(s)
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                continue
            for i, line in enumerate(text.splitlines()):
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError as e:
                    raise ValueError(f"{path}:{i + 1}: unparseable ledger row: {e}")
                validate_row(row)
                out.append(row)
        return out

    def identities(self) -> set:
        return {row_identity(r) for r in self.rows()}

    def history(self, backend: str, suite: str, metric: str,
                before_round: Optional[int] = None) -> List[Dict[str, Any]]:
        """Rows for one (backend, suite, metric) key, oldest round first.
        ``before_round`` drops rows of that round and later — the gate
        compares a round's rows only against STRICTLY older history."""
        key = (backend, suite, metric)
        rows = [r for r in self.rows(suite) if row_key(r) == key]
        if before_round is not None:
            rows = [r for r in rows if int(r["round"]) < before_round]
        return sorted(rows, key=lambda r: (int(r["round"]),
                                           float(r.get("time_unix", 0.0))))
